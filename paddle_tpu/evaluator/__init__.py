"""Evaluators — successor of ``paddle/gserver/evaluators/Evaluator.cpp:172-1357``
(classification_error, sum, column_sum, rankauc, precision_recall, pnpair,
ctc_edit_distance, chunk F1, detection mAP + printers).

Two tiers:
- in-jit metrics (classification error) computed inside the train step;
- host-side accumulators here, fed from output values batch by batch, for the
  metrics that don't belong in compiled code (AUC buckets, edit distance,
  chunk F1).  ``Evaluator`` mirrors start/eval/finish of the C++ registry."""

from __future__ import annotations

import numpy as np


class Evaluator:
    name = "base"

    def start(self):
        raise NotImplementedError

    def eval_batch(self, **kw):
        raise NotImplementedError

    def finish(self) -> dict:
        raise NotImplementedError


class ClassificationError(Evaluator):
    """≅ classification_error_evaluator: argmax error, or threshold error on
    a single-column predictor, or top-k error; optionally sample-weighted
    (ClassificationErrorEvaluator, Evaluator.cpp:78)."""

    name = "classification_error"

    def __init__(self, threshold: float | None = None,
                 top_k: int | None = None):
        self.threshold = threshold
        self.top_k = top_k
        self.start()

    def start(self):
        self.wrong = 0.0
        self.total = 0.0

    def eval_batch(self, pred=None, label=None, weight=None, **kw):
        p = np.asarray(pred)
        p = p.reshape(-1, p.shape[-1]) if p.ndim > 1 else p.reshape(-1, 1)
        lbl = np.asarray(label).reshape(-1)
        if p.shape[-1] == 1:
            thr = 0.5 if self.threshold is None else self.threshold
            err = (p[:, 0] > thr).astype(np.int64) != lbl
        elif self.top_k and self.top_k > 1:
            topk = np.argsort(-p, axis=-1)[:, : self.top_k]
            err = ~(topk == lbl[:, None]).any(axis=-1)
        else:
            err = np.argmax(p, axis=-1) != lbl
        w = (np.asarray(weight).reshape(-1) if weight is not None
             else np.ones_like(lbl, np.float64))
        self.wrong += float((err * w).sum())
        self.total += float(w.sum())

    def finish(self):
        return {self.name: self.wrong / max(self.total, 1e-9)}


class SumEvaluator(Evaluator):
    """≅ sum_evaluator."""

    name = "sum"

    def __init__(self):
        self.start()

    def start(self):
        self.total = 0.0
        self.count = 0

    def eval_batch(self, value=None, weight=None, **kw):
        v = np.asarray(value)
        if weight is not None:
            v = v * np.asarray(weight).reshape((-1,) + (1,) * (v.ndim - 1))
        self.total += float(v.sum())
        self.count += v.size

    def finish(self):
        return {self.name: self.total}


class ColumnSumEvaluator(Evaluator):
    """≅ column_sum_evaluator (ref Evaluator.cpp:276 ColumnSumEvaluator).

    Reports ``sum[col_idx] / numSamples`` like the reference's
    ``printStats`` (Evaluator.cpp:351-363); ``numSamples`` is the weight
    sum when a weight input exists, else the sample count
    (Evaluator.cpp:288-294).
    """

    name = "column_sum"

    def __init__(self, col_idx: int = -1):
        self.col_idx = col_idx
        self.start()

    def start(self):
        self.total = None
        self.count = 0.0

    def eval_batch(self, value=None, weight=None, **kw):
        v = np.asarray(value)
        v = v.reshape(-1, v.shape[-1]) if v.ndim > 1 else v.reshape(-1, 1)
        if weight is not None:
            w = np.asarray(weight).reshape(-1, 1)
            self.count += float(w.sum())
            v = v * w
        else:
            self.count += v.shape[0]
        v = v.sum(axis=0)
        self.total = v if self.total is None else self.total + v

    def finish(self):
        if self.total is None:
            return {self.name: 0.0}
        return {self.name: float(self.total[self.col_idx] / self.count)
                if self.count else 0.0}


class AUC(Evaluator):
    """≅ auc_evaluator (bucketed trapezoid AUC, Fluid auc_op style)."""

    name = "auc"

    def __init__(self, num_thresholds: int = 200):
        self.k = num_thresholds
        self.start()

    def start(self):
        self.tp = np.zeros(self.k + 1)
        self.fp = np.zeros(self.k + 1)

    def eval_batch(self, prob=None, label=None, weight=None, **kw):
        p = np.asarray(prob)
        if p.ndim > 1 and p.shape[-1] > 1:
            p = p[..., -1]  # "last-column-auc": last column for any width
        p = p.reshape(-1)
        y = np.asarray(label).reshape(-1)
        w = (np.asarray(weight).reshape(-1) if weight is not None
             else np.ones_like(p))
        for t in range(self.k + 1):
            thr = t / self.k
            pred_pos = p >= thr
            self.tp[t] += float((w * (pred_pos & (y == 1))).sum())
            self.fp[t] += float((w * (pred_pos & (y == 0))).sum())

    def finish(self):
        pos = max(self.tp[0], 1e-9)
        neg = max(self.fp[0], 1e-9)
        tpr = self.tp / pos
        fpr = self.fp / neg
        auc = float(-np.trapezoid(tpr, fpr))
        return {self.name: auc}


class PrecisionRecall(Evaluator):
    """≅ precision_recall_evaluator (macro over classes + F1)."""

    name = "precision_recall"

    def __init__(self, num_classes: int | None = 2,
                 positive_label: int | None = None):
        self.num_classes = num_classes
        self.positive_label = (None if positive_label in (None, -1)
                               else positive_label)
        self.start()

    def start(self):
        n = self.num_classes or 0
        self.tp = np.zeros(n)
        self.fp = np.zeros(n)
        self.fn = np.zeros(n)

    def _grow(self, n):
        if n > self.tp.size:
            pad = n - self.tp.size
            self.tp = np.concatenate([self.tp, np.zeros(pad)])
            self.fp = np.concatenate([self.fp, np.zeros(pad)])
            self.fn = np.concatenate([self.fn, np.zeros(pad)])
            self.num_classes = n

    def eval_batch(self, pred=None, label=None, weight=None, **kw):
        p = np.asarray(pred)
        self._grow(p.shape[-1] if p.ndim > 1 else 2)
        ids = np.argmax(p, axis=-1).reshape(-1)
        lbl = np.asarray(label).reshape(-1)
        w = (np.asarray(weight).reshape(-1) if weight is not None
             else np.ones_like(ids, np.float64))
        for c in range(self.num_classes):
            self.tp[c] += float((w * ((ids == c) & (lbl == c))).sum())
            self.fp[c] += float((w * ((ids == c) & (lbl != c))).sum())
            self.fn[c] += float((w * ((ids != c) & (lbl == c))).sum())

    def finish(self):
        prec = self.tp / np.maximum(self.tp + self.fp, 1)
        rec = self.tp / np.maximum(self.tp + self.fn, 1)
        f1 = 2 * prec * rec / np.maximum(prec + rec, 1e-9)
        if self.positive_label is not None:
            c = self.positive_label
            if not 0 <= c < prec.size:
                raise ValueError(
                    f"positive_label={c} out of range for "
                    f"{prec.size}-class precision_recall evaluator")
            return {"precision": float(prec[c]), "recall": float(rec[c]),
                    "F1-score": float(f1[c])}
        return {
            "precision": float(prec.mean()),
            "recall": float(rec.mean()),
            "F1-score": float(f1.mean()),
        }


class PnpairEvaluator(Evaluator):
    """≅ pnpair_evaluator: positive-negative pair ordering accuracy."""

    name = "pnpair"

    def __init__(self):
        self.start()

    def start(self):
        self.records: list[tuple[float, int, int]] = []

    def eval_batch(self, score=None, label=None, query=None, weight=None,
                   **kw):
        s = np.asarray(score)
        if s.ndim > 1 and s.shape[-1] > 1:
            s = s[..., -1]  # ref Evaluator.cpp:925: score is the last column
        s = s.reshape(-1)
        y = np.asarray(label).reshape(-1)
        q = (np.asarray(query).reshape(-1) if query is not None
             else np.zeros_like(y))
        w = (np.asarray(weight).reshape(-1) if weight is not None
             else np.ones_like(s))
        self.records.extend(zip(s.tolist(), y.tolist(), q.tolist(),
                                w.tolist()))

    def finish(self):
        pos, neg, tie = 0.0, 0.0, 0.0
        from collections import defaultdict

        by_q = defaultdict(list)
        for s, y, q, w in self.records:
            by_q[q].append((s, y, w))
        for items in by_q.values():
            for i in range(len(items)):
                for j in range(i + 1, len(items)):
                    (si, yi, wi), (sj, yj, wj) = items[i], items[j]
                    if yi == yj:
                        continue
                    pw = (wi + wj) * 0.5
                    hi, lo = (si, sj) if yi > yj else (sj, si)
                    if hi > lo:
                        pos += pw
                    elif hi < lo:
                        neg += pw
                    else:
                        tie += pw
        total = max(pos + neg + tie, 1e-9)
        return {self.name: (pos + 0.5 * tie) / total}


#: per-scheme tag ids: (num_tag_types, begin, inside, end, single)
#: ≅ ChunkEvaluator.cpp:82-108 (init)
_CHUNK_SCHEMES = {
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


class ChunkEvaluator(Evaluator):
    """≅ ChunkEvaluator.cpp:53: chunk-level F1 for sequence tagging.

    Labels encode (chunk_type, tag_type) as in the reference:
    ``tag = label % num_tag_types; type = label / num_tag_types``, with
    ``type == num_chunk_types`` meaning "other/O".  Supports the four
    reference schemes (plain/IOB/IOE/IOBES) and ``excluded_chunk_types``
    (excluded segments never count, ChunkEvaluator.cpp:160-184).
    """

    name = "chunk"

    def __init__(self, chunk_scheme: str = "IOB", num_chunk_types: int = 1,
                 excluded_chunk_types=None):
        if chunk_scheme not in _CHUNK_SCHEMES:
            raise ValueError(f"Unknown chunk scheme: {chunk_scheme}")
        self.scheme = chunk_scheme
        (self.num_tag_types, self.tag_begin, self.tag_inside,
         self.tag_end, self.tag_single) = _CHUNK_SCHEMES[chunk_scheme]
        self.num_chunk_types = num_chunk_types
        self.other_type = num_chunk_types
        self.excluded = frozenset(excluded_chunk_types or ())
        self.start()

    def start(self):
        self.correct = 0
        self.infer_total = 0
        self.label_total = 0

    def _is_chunk_end(self, prev_tag, prev_type, tag, type_):
        """≅ ChunkEvaluator.cpp:224 isChunkEnd."""
        if prev_type == self.other_type:
            return False
        if type_ == self.other_type or type_ != prev_type:
            return True
        if prev_tag in (self.tag_begin, self.tag_inside):
            return tag in (self.tag_begin, self.tag_single)
        return prev_tag in (self.tag_end, self.tag_single)

    def _is_chunk_begin(self, prev_tag, prev_type, tag, type_):
        """≅ ChunkEvaluator.cpp:236 isChunkBegin."""
        if prev_type == self.other_type:
            return type_ != self.other_type
        if type_ == self.other_type:
            return False
        if type_ != prev_type or tag in (self.tag_begin, self.tag_single):
            return True
        if tag in (self.tag_inside, self.tag_end):
            return prev_tag in (self.tag_end, self.tag_single)
        return False

    def _extract(self, labels: list[int]):
        """≅ ChunkEvaluator.cpp:186 getSegments: (begin, end, type) list."""
        segments = []
        chunk_start, in_chunk = 0, False
        tag, type_ = -1, self.other_type
        hi = self.num_chunk_types * self.num_tag_types
        for i, lab in enumerate(labels):
            prev_tag, prev_type = tag, type_
            if 0 <= lab < hi:
                tag = lab % self.num_tag_types
                type_ = lab // self.num_tag_types
            else:
                # out-of-range / negative (padding) labels count as O —
                # the reference CHECKs the range (ChunkEvaluator.cpp:196);
                # we degrade gracefully for padded batches
                tag, type_ = -1, self.other_type
            if in_chunk and self._is_chunk_end(prev_tag, prev_type,
                                               tag, type_):
                segments.append((chunk_start, i - 1, prev_type))
                in_chunk = False
            if self._is_chunk_begin(prev_tag, prev_type, tag, type_):
                chunk_start, in_chunk = i, True
        if in_chunk:
            segments.append((chunk_start, len(labels) - 1, type_))
        return segments

    def eval_batch(self, pred=None, label=None, lengths=None, **kw):
        p = np.asarray(pred)
        y = np.asarray(label)
        if p.ndim == 1:
            p, y = p[None], y[None]
        lens = (np.asarray(lengths) if lengths is not None
                else np.full(p.shape[0], p.shape[1]))
        for i in range(p.shape[0]):
            pi = self._extract(p[i, : lens[i]].tolist())
            yi = self._extract(y[i, : lens[i]].tolist())
            keep = lambda seg: seg[2] not in self.excluded  # noqa: E731
            self.correct += len(set(filter(keep, pi)) &
                                set(filter(keep, yi)))
            self.infer_total += sum(1 for s in pi if keep(s))
            self.label_total += sum(1 for s in yi if keep(s))

    def finish(self):
        prec = self.correct / max(self.infer_total, 1)
        rec = self.correct / max(self.label_total, 1)
        f1 = 2 * prec * rec / max(prec + rec, 1e-9)
        return {"precision": prec, "recall": rec, "F1-score": f1}


def edit_distance(a: list, b: list) -> int:
    """Levenshtein distance (core of ctc_error_evaluator)."""
    m, n = len(a), len(b)
    dp = list(range(n + 1))
    for i in range(1, m + 1):
        prev = dp[0]
        dp[0] = i
        for j in range(1, n + 1):
            cur = dp[j]
            dp[j] = min(dp[j] + 1, dp[j - 1] + 1, prev + (a[i - 1] != b[j - 1]))
            prev = cur
    return dp[n]


class CTCError(Evaluator):
    """≅ CTCErrorEvaluator.cpp: edit distance between greedy CTC decode and
    the label sequence, normalized by label length."""

    name = "ctc_error"

    def __init__(self, blank: int = 0):
        self.blank = blank
        self.start()

    def start(self):
        self.total_dist = 0.0
        self.total_len = 0

    @staticmethod
    def greedy_decode(logits: np.ndarray, blank: int) -> list[int]:
        ids = np.argmax(logits, axis=-1).tolist()
        out, prev = [], None
        for t in ids:
            if t != prev and t != blank:
                out.append(t)
            prev = t
        return out

    def eval_batch(self, logits=None, label=None, **kw):
        for lg, lb in zip(logits, label):
            dec = self.greedy_decode(np.asarray(lg), self.blank)
            ref = [int(x) for x in lb]
            self.total_dist += edit_distance(dec, ref)
            self.total_len += len(ref)

    def finish(self):
        return {self.name: self.total_dist / max(self.total_len, 1)}


class DetectionMAP(Evaluator):
    """≅ detection_map evaluator (DetectionMAPEvaluator.cpp): mean average
    precision over classes at an IoU threshold, 11-point interpolated or
    integral.  ``eval_batch(detections=[[label,score,x1,y1,x2,y2],...] per
    image, gts=[[label,x1,y1,x2,y2],...] per image)``."""

    name = "detection_map"

    def __init__(self, overlap_threshold: float = 0.5,
                 ap_version: str = "11point",
                 evaluate_difficult: bool = False,
                 background_id: int = 0):
        self.thr = overlap_threshold
        self.ap_version = ap_version
        self.evaluate_difficult = evaluate_difficult
        # kept for config parity: the reference reads background_id into
        # the evaluator but never consults it in evalImp
        # (DetectionMAPEvaluator.cpp:44,293) — post-NMS detection output
        # carries no background rows
        self.background_id = background_id
        self.start()

    def start(self):
        self.dets: list = []   # (class, score, image_id, box)
        self.gts: dict = {}    # (image_id, class) -> [(box, difficult)]
        self.n_img = 0

    @staticmethod
    def _iou(a, b):
        lt = np.maximum(a[:2], b[:2])
        rb = np.minimum(a[2:], b[2:])
        wh = np.maximum(rb - lt, 0.0)
        inter = wh[0] * wh[1]
        ua = max((a[2]-a[0]) * (a[3]-a[1]), 0) + max(
            (b[2]-b[0]) * (b[3]-b[1]), 0) - inter
        return inter / max(ua, 1e-10)

    def eval_batch(self, detections=None, gts=None, **kw):
        for det_rows, gt_rows in zip(detections, gts):
            img = self.n_img
            self.n_img += 1
            for row in det_rows:
                if row[0] < 0:
                    continue
                self.dets.append((int(row[0]), float(row[1]), img,
                                  np.asarray(row[2:6], np.float64)))
            for row in gt_rows:
                if row[0] < 0:
                    continue
                # 6th column, when present, is the VOC difficult flag
                # (getBBoxFromLabelData reads 6 fields per row)
                difficult = bool(row[5]) if len(row) > 5 else False
                self.gts.setdefault((img, int(row[0])), []).append(
                    (np.asarray(row[1:5], np.float64), difficult))

    def _ap(self, recalls, precisions):
        if self.ap_version == "11point":
            return float(np.mean([
                max([p for r, p in zip(recalls, precisions) if r >= t],
                    default=0.0)
                for t in np.linspace(0, 1, 11)
            ]))
        # integral AP
        ap, prev_r = 0.0, 0.0
        for r, p in zip(recalls, precisions):
            ap += p * (r - prev_r)
            prev_r = r
        return float(ap)

    def finish(self):
        classes = sorted({c for c, _, _, _ in self.dets} |
                         {c for _, c in self.gts})
        aps = []
        for c in classes:
            # positives exclude difficult gts unless evaluate_difficult
            # (DetectionMAPEvaluator.cpp:106-116)
            n_gt = sum(
                sum(1 for _, diff in v
                    if self.evaluate_difficult or not diff)
                for (img, cc), v in self.gts.items() if cc == c)
            dets = sorted([d for d in self.dets if d[0] == c],
                          key=lambda d: -d[1])
            if n_gt == 0:
                continue
            used: dict = {}
            tp = np.zeros(len(dets))
            fp = np.zeros(len(dets))
            keep = np.ones(len(dets), bool)
            for i, (_, score, img, box) in enumerate(dets):
                cand = self.gts.get((img, c), [])
                # VOC rule: only the single max-overlap gt counts; if it is
                # already claimed by a higher-scoring detection, this is FP
                best, best_iou = -1, 0.0
                for j, (g, _diff) in enumerate(cand):
                    iou = self._iou(box, g)
                    if iou > best_iou:
                        best, best_iou = j, iou
                if best >= 0 and best_iou > self.thr:
                    if not self.evaluate_difficult and cand[best][1]:
                        # matched a difficult gt: neither TP nor FP
                        # (DetectionMAPEvaluator.cpp:184-185)
                        keep[i] = False
                    elif (img, c, best) not in used:
                        tp[i] = 1
                        used[(img, c, best)] = True
                    else:
                        fp[i] = 1
                else:
                    fp[i] = 1
            ctp, cfp = np.cumsum(tp[keep]), np.cumsum(fp[keep])
            recalls = ctp / n_gt
            precisions = ctp / np.maximum(ctp + cfp, 1e-10)
            aps.append(self._ap(recalls, precisions))
        return {self.name: float(np.mean(aps)) if aps else 0.0}


class RankAUC(Evaluator):
    """≅ rankauc (RankAucEvaluator): exact AUC from raw ranking scores and
    binary relevance labels (optionally weighted), computed by sorting —
    unlike :class:`AUC`, no threshold grid."""

    name = "rankauc"

    def __init__(self):
        self.start()

    def start(self):
        self.scores: list = []
        self.labels: list = []
        self.weights: list = []

    def eval_batch(self, score=None, label=None, weight=None, **kw):
        score = np.asarray(score, np.float64).reshape(-1)
        label = np.asarray(label, np.float64).reshape(-1)
        weight = (np.ones_like(score) if weight is None
                  else np.asarray(weight, np.float64).reshape(-1))
        self.scores.append(score)
        self.labels.append(label)
        self.weights.append(weight)

    def finish(self):
        s = np.concatenate(self.scores)
        y = np.concatenate(self.labels)
        w = np.concatenate(self.weights)
        # weighted AUC = [sum over pos p, neg n of w_p w_n (1[s_p>s_n]
        # + 0.5*1[s_p=s_n])] / (W_pos W_neg), aggregated per unique score
        uniq, inv = np.unique(s, return_inverse=True)
        pos_g = np.zeros(len(uniq))
        neg_g = np.zeros(len(uniq))
        np.add.at(pos_g, inv, w * (y > 0))
        np.add.at(neg_g, inv, w * (y <= 0))
        n_pos, n_neg = pos_g.sum(), neg_g.sum()
        if n_pos == 0 or n_neg == 0:
            return {self.name: 0.0}
        neg_below = np.concatenate([[0.0], np.cumsum(neg_g)[:-1]])
        auc = np.sum(pos_g * (neg_below + 0.5 * neg_g)) / (n_pos * n_neg)
        return {self.name: float(auc)}


class ValuePrinter(Evaluator):
    """≅ value_printer_evaluator (printer evaluators family): logs the
    values handed to it each batch; passes nothing back."""

    name = "value_printer"

    def __init__(self, prefix: str = "value", max_elems: int = 16):
        self.prefix = prefix
        self.max_elems = max_elems

    def start(self):
        pass

    def eval_batch(self, **kw):
        from paddle_tpu.core import logger as log

        for name, v in kw.items():
            arr = np.asarray(v)
            flat = arr.reshape(-1)[: self.max_elems]
            log.info("%s[%s] shape=%s %s%s", self.prefix, name, arr.shape,
                     np.array2string(flat, precision=4),
                     "..." if arr.size > self.max_elems else "")

    def finish(self):
        return {}


REGISTRY = {
    c.name: c
    for c in (ClassificationError, SumEvaluator, ColumnSumEvaluator, AUC,
              PrecisionRecall, PnpairEvaluator, ChunkEvaluator, CTCError,
              DetectionMAP, RankAUC, ValuePrinter)
}


def create(name: str, **kw) -> Evaluator:
    return REGISTRY[name](**kw)
