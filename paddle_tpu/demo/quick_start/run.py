"""quick_start text classification — the reference config
(``v1_api_demo/quick_start/trainer_config.lr.py`` + ``dataprovider_bow``)
executed UNMODIFIED by the paddle_tpu trainer CLI.

The original demo downloads Amazon review data; here a synthetic
sentiment corpus with the same file formats (``dict.txt``, tab-separated
``label\\ttext`` lines, ``train.list``/``test.list``) stands in.

Run: python -m paddle_tpu.demo.quick_start.run [--passes N] [--workdir D]
"""

from __future__ import annotations

import argparse
import os
import random

from paddle_tpu.demo import REFERENCE_ROOT

POS = "good great fine excellent loved wonderful best happy".split()
NEG = "bad awful terrible hate worst boring poor sad".split()
FILLER = "the a movie film it was i this and of to very really".split()


def make_data(workdir: str, n_train: int = 1280, n_test: int = 256) -> None:
    data = os.path.join(workdir, "data")
    os.makedirs(data, exist_ok=True)
    rnd = random.Random(0)
    with open(os.path.join(data, "dict.txt"), "w") as f:
        for w in sorted(set(POS + NEG + FILLER)):
            f.write(w + "\t0\n")

    def gen(path, n):
        with open(path, "w") as f:
            for _ in range(n):
                y = rnd.randint(0, 1)
                words = rnd.choices(POS if y else NEG, k=6) + \
                    rnd.choices(FILLER, k=6)
                rnd.shuffle(words)
                f.write(f"{y}\t{' '.join(words)}\n")

    gen(os.path.join(data, "train.txt"), n_train)
    gen(os.path.join(data, "test.txt"), n_test)
    with open(os.path.join(data, "train.list"), "w") as f:
        f.write("data/train.txt\n")
    with open(os.path.join(data, "test.list"), "w") as f:
        f.write("data/test.txt\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=8)
    ap.add_argument("--workdir", default="./quick_start_work")
    ap.add_argument("--config", default=os.path.join(
        REFERENCE_ROOT, "v1_api_demo/quick_start/trainer_config.lr.py"))
    args = ap.parse_args(argv)

    os.makedirs(args.workdir, exist_ok=True)
    make_data(args.workdir)
    cwd = os.getcwd()
    os.chdir(args.workdir)  # the config refs ./data/* relative paths
    try:
        from paddle_tpu.trainer import cli

        rc = cli.main(["--config", args.config, "--job", "train",
                       "--num_passes", str(args.passes),
                       "--config_args", "dict_file=data/dict.txt",
                       "--save_dir", "out"])
        if rc:
            return rc
        last = sorted(os.listdir("out"))[-1]
        return cli.main(["--config", args.config, "--job", "test",
                         "--init_model_path", os.path.join("out", last),
                         "--config_args", "dict_file=data/dict.txt"])
    finally:
        os.chdir(cwd)


if __name__ == "__main__":
    raise SystemExit(main())
