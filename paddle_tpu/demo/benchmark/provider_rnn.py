"""py3 port of ``benchmark/paddle/rnn/provider.py`` (the reference's is
python-2-only: ``six.moves.cPickle``, generator ``map``): IMDB pickle ->
(optionally fixed-length-padded) id sequences + binary labels."""

import pickle

import numpy as np

from paddle.trainer.PyDataProvider2 import (
    CacheType,
    integer_value,
    integer_value_sequence,
    provider,
)


def remove_unk(x, n_words):
    return [[1 if w >= n_words else w for w in sen] for sen in x]


def pad_sequences(sequences,
                  maxlen=None,
                  dtype='int32',
                  padding='post',
                  truncating='post',
                  value=0.):
    lengths = [len(s) for s in sequences]
    nb_samples = len(sequences)
    if maxlen is None:
        maxlen = np.max(lengths)
    x = (np.ones((nb_samples, maxlen)) * value).astype(dtype)
    for idx, s in enumerate(sequences):
        if len(s) == 0:
            continue
        if truncating == 'pre':
            trunc = s[-maxlen:]
        elif truncating == 'post':
            trunc = s[:maxlen]
        else:
            raise ValueError("Truncating type '%s' not understood" % padding)
        if padding == 'post':
            x[idx, :len(trunc)] = trunc
        elif padding == 'pre':
            x[idx, -len(trunc):] = trunc
        else:
            raise ValueError("Padding type '%s' not understood" % padding)
    return x


def initHook(settings, vocab_size, pad_seq, maxlen, **kwargs):
    settings.vocab_size = vocab_size
    settings.pad_seq = pad_seq
    settings.maxlen = maxlen
    settings.input_types = [
        integer_value_sequence(vocab_size), integer_value(2)
    ]


@provider(
    init_hook=initHook, min_pool_size=-1, cache=CacheType.CACHE_PASS_IN_MEM)
def process(settings, file):
    with open(file, 'rb') as f:
        train_set = pickle.load(f)
    x, y = train_set
    x = remove_unk(x, settings.vocab_size)
    if settings.pad_seq:
        x = pad_sequences(x, maxlen=settings.maxlen, value=0.)
    for i in range(len(y)):
        yield [int(v) for v in x[i]], int(y[i])
