"""Drive the reference's benchmark configs verbatim.

``image/run.sh`` is ``paddle train --job=time --config=<net>.py
--config_args=batch_size=N`` over alexnet/googlenet/smallnet (and
resnet/vgg via ``run_mkldnn.sh``); ``rnn/run.sh`` sweeps rnn.py over
batch/hidden_size/lstm_num.  This runner reproduces that invocation
through the paddle_tpu CLI: the config files are copied byte-identical
from the reference tree; only the data shims are py3 ports (see the
package docstring).

Examples:
    python -m paddle_tpu.demo.benchmark.run --net smallnet --batch_size 64
    python -m paddle_tpu.demo.benchmark.run --net rnn \
        --config_args hidden_size=128,lstm_num=2
    python -m paddle_tpu.demo.benchmark.run --net all   # run.sh 1-device grid
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys

from paddle_tpu.demo import REFERENCE_ROOT

HERE = os.path.dirname(os.path.abspath(__file__))

# net -> (reference config path, family, run.sh default batch)
NETS = {
    "alexnet": ("benchmark/paddle/image/alexnet.py", "image", 128),
    "googlenet": ("benchmark/paddle/image/googlenet.py", "image", 128),
    "resnet": ("benchmark/paddle/image/resnet.py", "image", 64),
    "vgg": ("benchmark/paddle/image/vgg.py", "image", 64),
    "smallnet": ("benchmark/paddle/image/smallnet_mnist_cifar.py",
                 "image", 64),
    "rnn": ("benchmark/paddle/rnn/rnn.py", "rnn", 128),
}

# the reference's single-device sweep (image/run.sh lines 28-42; rnn
# analog at the README's bs 64-256 table)
RUN_SH_GRID = [
    ("alexnet", 64), ("alexnet", 128), ("alexnet", 256), ("alexnet", 512),
    ("googlenet", 64), ("googlenet", 128), ("googlenet", 256),
    ("smallnet", 64), ("smallnet", 128), ("smallnet", 256),
    ("smallnet", 512),
    ("rnn", 64), ("rnn", 128), ("rnn", 256),
]


def setup_workdir(net: str, workdir: str) -> str:
    """Copy the reference config (byte-identical) + py3 data shims."""
    cfg_rel, family, _ = NETS[net]
    d = os.path.join(workdir, family)
    os.makedirs(d, exist_ok=True)
    cfg = os.path.basename(cfg_rel)
    shutil.copyfile(os.path.join(REFERENCE_ROOT, cfg_rel),
                    os.path.join(d, cfg))  # byte-identical
    if family == "image":
        shutil.copyfile(os.path.join(HERE, "provider_image.py"),
                        os.path.join(d, "provider.py"))
        with open(os.path.join(d, "train.list"), "w") as f:
            f.write("train\n")  # provider ignores the entry (run.sh: echo)
    else:
        shutil.copyfile(os.path.join(HERE, "provider_rnn.py"),
                        os.path.join(d, "provider.py"))
        shutil.copyfile(os.path.join(HERE, "imdb_synth.py"),
                        os.path.join(d, "imdb.py"))
    return d


def run_one(net: str, batch_size: int | None, job: str, workdir: str,
            config_args: str = "", num_passes: int = 1,
            seq_dim: int = 100, extra_argv: list[str] | None = None) -> int:
    cfg_rel, family, default_bs = NETS[net]
    d = setup_workdir(net, workdir)
    bs = batch_size or default_bs
    cargs = f"batch_size={bs}"
    if config_args:
        cargs += "," + config_args
    argv = ["--config", os.path.basename(cfg_rel), "--job", job,
            "--config_args", cargs, "--num_passes", str(num_passes),
            "--log_period", "10"] + list(extra_argv or [])
    if family == "rnn":
        argv += ["--seq_dim", str(seq_dim)]  # run.sh pads to fixedlen=100
    # each family ships its own provider.py/imdb.py: drop stale imports
    for mod in ("provider", "imdb"):
        sys.modules.pop(mod, None)
    cwd = os.getcwd()
    os.chdir(d)
    sys.path.insert(0, os.getcwd())  # rnn.py does `import imdb` at parse
    try:
        from paddle_tpu.trainer import cli

        return cli.main(argv)
    finally:
        sys.path.pop(0)
        os.chdir(cwd)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--net", default="smallnet",
                    choices=sorted(NETS) + ["all"])
    ap.add_argument("--batch_size", type=int, default=None,
                    help="default: the net's run.sh batch")
    ap.add_argument("--job", default="time", choices=["time", "train"])
    ap.add_argument("--config_args", default="",
                    help="extra k=v,... appended (hidden_size, lstm_num, "
                         "layer_num, pad_seq)")
    ap.add_argument("--num_passes", type=int, default=1)
    ap.add_argument("--seq_dim", type=int, default=100,
                    help="--job=time synthetic timesteps for rnn "
                         "(reference fixedlen)")
    ap.add_argument("--workdir", default="./benchmark_work")
    args, extra = ap.parse_known_args(argv)  # e.g. --bf16 -> trainer gflags

    os.makedirs(args.workdir, exist_ok=True)
    if args.net == "all":
        rc = 0
        for net, bs in RUN_SH_GRID:
            print(f"=== {net} batch_size={bs} ===", flush=True)
            rc |= run_one(net, bs, args.job, args.workdir,
                          args.config_args, args.num_passes, args.seq_dim,
                          extra_argv=extra)
        return rc
    return run_one(args.net, args.batch_size, args.job, args.workdir,
                   args.config_args, args.num_passes, args.seq_dim,
                   extra_argv=extra)


if __name__ == "__main__":
    raise SystemExit(main())
