"""py3 port of ``benchmark/paddle/image/provider.py`` (the reference's
is python-2-only: ``xrange``): random images + labels, shapes
parameterized by the config's init_hook args."""

import random

import numpy as np

from paddle.trainer.PyDataProvider2 import (
    CacheType,
    dense_vector,
    integer_value,
    provider,
)


def initHook(settings, height, width, color, num_class, **kwargs):
    settings.height = height
    settings.width = width
    settings.color = color
    settings.num_class = num_class
    if settings.color:
        settings.data_size = settings.height * settings.width * 3
    else:
        settings.data_size = settings.height * settings.width
    settings.slots = [dense_vector(settings.data_size), integer_value(1)]


@provider(
    init_hook=initHook, min_pool_size=-1, cache=CacheType.CACHE_PASS_IN_MEM)
def process(settings, file_list):
    for i in range(1024):
        img = np.random.rand(1, settings.data_size).reshape(-1, 1).flatten()
        lab = random.randint(0, settings.num_class - 1)
        yield img.astype('float32'), int(lab)
