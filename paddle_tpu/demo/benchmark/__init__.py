"""The reference's own benchmark harness, run verbatim.

``/root/reference/benchmark/paddle/`` is the suite behind every number
in ``benchmark/README.md`` (the BASELINE.md anchors): image nets
(alexnet / googlenet / resnet / vgg / smallnet_mnist_cifar) driven by
``run.sh`` as ``paddle train --job=time --config=<net>.py
--config_args=batch_size=N``, and the IMDB LSTM sweep
(``rnn/rnn.py``) with batch/hidden/lstm_num config args.

This package executes those config files BYTE-IDENTICAL (copied from
``$PADDLE_REFERENCE_ROOT/benchmark/paddle``) through the paddle_tpu
trainer CLI's ``--job=time`` (≅ TrainerBenchmark.cpp) on synthetic
data.  Only the data-prep shims are py3 ports, same policy as the
other demo families:

- ``provider_image``  — py3 port of ``image/provider.py`` (xrange).
- ``provider_rnn``    — py3 port of ``rnn/provider.py`` (map()/file()).
- ``imdb_synth``      — hermetic stand-in for ``rnn/imdb.py``, whose
  original downloads imdb.pkl from the network; generates synthetic
  variable-length id sequences in the same two-pickle layout.

Run: ``python -m paddle_tpu.demo.benchmark.run --net smallnet
--batch_size 64``; ``--net all`` sweeps the reference's single-device
grid from ``image/run.sh`` / ``rnn/run.sh``.
"""
