"""Hermetic stand-in for ``benchmark/paddle/rnn/imdb.py``.

The reference module downloads ``imdb.pkl`` and splits it into
``imdb.train.pkl`` / ``imdb.test.pkl`` (each a ``(x, y)`` pair: list of
word-id sequences, list of 0/1 labels), then writes ``train.list``.
This stand-in synthesizes the same two-pickle layout with
variable-length random sequences (zero egress), keeping ``rnn.py``'s
``import imdb; imdb.create_data('imdb.pkl')`` call verbatim.
"""

import os
import pickle

import numpy as np

N_SAMPLES = int(os.environ.get("PADDLE_TPU_IMDB_SYNTH_N", "2048"))


def _synth(n, seed):
    rng = np.random.default_rng(seed)
    lengths = rng.integers(20, 120, size=n)
    x = [rng.integers(2, 35000, size=int(L)).tolist() for L in lengths]
    y = rng.integers(0, 2, size=n).tolist()
    return x, y


def create_data(path="imdb.pkl"):
    if not os.path.isfile('imdb.train.pkl'):
        with open('imdb.train.pkl', 'wb') as f:
            pickle.dump(_synth(N_SAMPLES, seed=0), f)
        with open('imdb.test.pkl', 'wb') as f:
            pickle.dump(_synth(N_SAMPLES // 4, seed=1), f)
    if not os.path.isfile('train.list'):
        with open('train.list', 'w') as f:
            f.write('imdb.train.pkl\n')


def main():
    create_data('imdb.pkl')


if __name__ == "__main__":
    main()
