"""Runnable ports of the reference ``v1_api_demo`` applications.

Each sub-package drives a REFERENCE config (byte-identical; taken from
``$PADDLE_REFERENCE_ROOT``, default ``/root/reference``) through the
paddle_tpu trainer with synthetic stand-in data, since the original demo
datasets require downloads:

- ``quick_start``          — text classification, ``trainer_config.lr.py``
                             run unmodified (dict via --config_args).
- ``traffic_prediction``   — multi-task traffic forecasting; the config is
                             used verbatim, the data provider is a py3 port
                             (the reference's is python-2-only: ``f.next``,
                             list-``map``, ``sys.maxint``).
- ``model_zoo``            — pretrained-model feature extraction: save /
                             load parameters in the reference
                             ``Parameter::save`` binary-dir layout and pull
                             hidden-layer features via ``paddle.infer``
                             (≅ ``model_zoo/resnet/classify.py``).
- ``sequence_tagging``     — CRF tagger; ``rnn_crf.py``/``linear_crf.py``
                             byte-identical (py3 provider port).
- ``mnist``                — ``light_mnist.py``/``vgg_16_mnist.py`` AND
                             ``mnist_provider.py`` run unmodified; only
                             ``mnist_util`` is a py3 port.
"""

import os

REFERENCE_ROOT = os.environ.get("PADDLE_REFERENCE_ROOT", "/root/reference")
