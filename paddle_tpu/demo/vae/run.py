"""VAE demo runner — executes the reference config
``v1_api_demo/vae/vae_conf.py`` verbatim and reproduces
``v1_api_demo/vae/vae_train.py:110-172``'s loop through the v2 API:

- two machines parsed from ONE config via ``is_generating`` config-args
  (``vae_train.py:111-112``): the full encoder(q_func) ->
  reparameterization -> generator ELBO network, and the decoder-only
  generator network;
- the MNIST loader's [-1, 1] mapping (``dataloader.py:33``) replaced by
  the same synthetic idx digits the mnist demo writes;
- ``copy_shared_parameters`` (``vae_train.py:55-75``) syncs the decoder
  weights (hidden.w/prob.w named via ParamAttr) into the generator
  machine before sampling.

Run: python -m paddle_tpu.demo.vae.run [--num_batches 120]
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from paddle_tpu.demo import REFERENCE_ROOT
from paddle_tpu.demo.gan.run import copy_shared_parameters, _quiet


def load_batches(workdir: str, n: int = 4096,
                 batch_size: int = 32) -> list[np.ndarray]:
    """Synthetic idx digits in [-1, 1] (shared gan-demo loader, same
    mapping as ``dataloader.MNISTloader._extract_images``,
    vae/dataloader.py:28-38), pre-split into batches like the loader."""
    from paddle_tpu.demo.gan.run import load_mnist_like

    data = load_mnist_like(workdir, n=n)
    return [data[i:i + batch_size]
            for i in range(0, n - batch_size + 1, batch_size)]


def run(num_batches: int = 120, workdir: str = "./vae_work",
        log_period: int = 20):
    """Returns (losses, samples): per-batch VAE loss and a final block of
    generated samples."""
    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.trainer.config_parser import parse_config
    from paddle_tpu.trainer.inference import Inference
    from paddle_tpu.trainer_config_helpers.optimizers import (
        get_settings_optimizer,
    )

    conf = os.path.join(REFERENCE_ROOT, "v1_api_demo/vae/vae_conf.py")
    trainer_conf = parse_config(conf, "is_generating=False")
    parameters = paddle.parameters.create(Topology(
        trainer_conf.output_layers()))
    trainer = paddle.trainer.SGD(
        cost=trainer_conf.output_layers(), parameters=parameters,
        update_equation=get_settings_optimizer())

    gener_conf = parse_config(conf, "is_generating=True")
    generator_machine = Inference(
        gener_conf.output_layers(),
        paddle.parameters.create(Topology(gener_conf.output_layers())))
    batch_size = trainer_conf.opt_config.batch_size or 32
    noise_dim = next(n.attrs["dim"] for n in gener_conf.layers
                     if n.name == "noise")

    batches = load_batches(workdir, batch_size=batch_size)
    losses = []
    for it in range(num_batches):
        X = batches[it % len(batches)]
        batch = [(row,) for row in X]
        if it % log_period == 0:
            loss = trainer.test(reader=lambda: iter([batch])).cost
            losses.append(loss)
            print(f"iter {it:03d}: VAE loss {loss:.4f}")
        trainer.train(reader=lambda: iter([batch]), num_passes=1,
                      event_handler=_quiet)
    final_loss = trainer.test(
        reader=lambda: iter([[(row,) for row in batches[0]]])).cost
    losses.append(final_loss)
    print(f"final VAE loss {final_loss:.4f}")

    # sample from the decoder (vae_train.py:153-158)
    copy_shared_parameters(trainer, generator_machine)
    z = np.random.randn(batch_size, noise_dim).astype("float32")
    samples = np.asarray(generator_machine.infer([(row,) for row in z]))
    print("sample stats: mean", float(samples.mean()),
          "min", float(samples.min()), "max", float(samples.max()))
    return losses, samples


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--num_batches", type=int, default=120)
    ap.add_argument("--workdir", default="./vae_work")
    args = ap.parse_args(argv)
    losses, samples = run(num_batches=args.num_batches,
                          workdir=args.workdir)
    ok = np.isfinite(losses[-1]) and losses[-1] < losses[0]
    print(f"ELBO decreased: {losses[0]:.2f} -> {losses[-1]:.2f} ({ok})")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
