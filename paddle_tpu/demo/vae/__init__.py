"""VAE demo — runs the reference's ``v1_api_demo/vae/vae_conf.py``
VERBATIM (read from the reference tree at runtime) and reproduces
``vae_train.py:1-175``'s loop through the v2 API: a training machine
(``is_generating=False``) and a generator machine
(``is_generating=True``) sharing parameters by name via
``copy_shared_parameters``."""
