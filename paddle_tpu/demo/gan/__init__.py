"""GAN demo — runs the reference's ``v1_api_demo/gan/gan_conf.py`` /
``gan_conf_image.py`` VERBATIM (read from the reference tree at runtime)
and reproduces ``gan_trainer.py:1-349``'s alternating two-GradientMachine
loop through the v2 API: three machines parsed from one config with
``mode=`` config-args, cross-machine parameter copying, and the
strike-based choose-who-trains schedule."""
