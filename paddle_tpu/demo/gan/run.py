"""GAN demo runner — executes the reference config
``v1_api_demo/gan/gan_conf.py`` (or ``gan_conf_image.py``) verbatim and
reproduces the alternating two-machine training loop of
``v1_api_demo/gan/gan_trainer.py:1-349``:

- three machines parsed from ONE config via ``--config_args mode=...``
  (generator_training / discriminator_training / generator), exactly as
  ``gan_trainer.py:241-247`` calls ``parse_config`` three times;
- cross-machine gradient flow through ``ParamAttr(is_static=...)``: the
  generator trains THROUGH the frozen discriminator and vice versa;
- ``copy_shared_parameters`` (``gan_trainer.py:50-71``) moves same-named
  parameters (and BN moving stats) between machines after each update;
- the strike schedule (``gan_trainer.py:299-331``): whoever has the
  larger loss trains, but never more than MAX_strike=5 times in a row.

Data sources: "uniform" (the reference's synthetic 2-D uniform,
``load_uniform_data``, gan_trainer.py:113-116) needs no files; "mnist"
writes synthetic idx images like the mnist demo.

Run: python -m paddle_tpu.demo.gan.run [--data_source uniform]
"""

from __future__ import annotations

import argparse
import os

import numpy as np

from paddle_tpu.demo import REFERENCE_ROOT


def _quiet(_event):
    pass


class Machine:
    """One 'GradientMachine + Trainer' pair built from a parsed config
    (the v2 replacement for ``api.GradientMachine.createFromConfigProto``
    + ``api.Trainer.create``)."""

    def __init__(self, parsed):
        import paddle_tpu as paddle
        from paddle_tpu.config.topology import Topology
        from paddle_tpu.layers import data_type as dt
        from paddle_tpu.trainer_config_helpers.optimizers import (
            get_settings_optimizer,
        )

        self.topology = Topology(parsed.output_layers())
        # the reference feeds the label slot as ids
        # (prepare_discriminator_data_batch_*: setSlotIds); the config's
        # data_layer(name="label", size=1) carries no type, so bind it here
        # the way a provider would
        label = self.topology.data_layers().get("label")
        if label is not None:
            it = dt.integer_value(2)
            label.attrs.update(data_type=it.kind, seq_type=it.seq_type,
                               dim=it.dim)
        self.parameters = paddle.parameters.create(self.topology)
        self.trainer = paddle.trainer.SGD(
            cost=parsed.output_layers(), parameters=self.parameters,
            update_equation=get_settings_optimizer())
        self._feeding = None

    def train_batch(self, batch) -> None:
        self.trainer.train(reader=lambda: iter([batch]), num_passes=1,
                           event_handler=_quiet)

    def loss(self, batch) -> float:
        """forward-only mean cost (``get_training_loss``,
        gan_trainer.py:163-167)."""
        return self.trainer.test(reader=lambda: iter([batch]),
                                 feeding=self._feeding).cost


def copy_shared_parameters(src, dst) -> None:
    """``gan_trainer.py:50-71``: same-named parameters copy src -> dst;
    BN moving stats (states here, PARAMETER-typed in the reference) ride
    along.  dst may be a Machine or an Inference."""
    src_params = src.parameters if hasattr(src, "parameters") else src
    dst_params = dst.parameters
    for name in dst_params.names():
        if name in src_params:
            dst_params[name] = np.asarray(src_params[name])
    src_states = getattr(getattr(src, "trainer", src), "states", None) or {}
    dst_owner = getattr(dst, "trainer", dst)
    dst_states = getattr(dst_owner, "states", None)
    if dst_states is not None:
        import jax.numpy as jnp

        for name in list(dst_states):
            if name in src_states:
                dst_states[name] = jnp.asarray(src_states[name])


def get_noise(batch_size: int, noise_dim: int) -> np.ndarray:
    return np.random.normal(size=(batch_size, noise_dim)).astype("float32")


def load_uniform_data(n: int = 100000) -> np.ndarray:
    """``load_uniform_data`` (gan_trainer.py:113-116) at demo scale."""
    return np.random.rand(n, 2).astype("float32")


def load_mnist_like(workdir: str, n: int = 4096) -> np.ndarray:
    """Synthetic idx images in [-1, 1] (``load_mnist_data``,
    gan_trainer.py:84-98), written/read through the same idx format the
    mnist demo uses."""
    from paddle_tpu.demo.mnist.run import make_data

    make_data(workdir, n_train=n, n_test=64)
    import struct

    path = os.path.join(workdir, "data", "raw_data", "train-images-idx3-ubyte")
    with open(path, "rb") as f:
        f.read(16)
        data = np.frombuffer(f.read(n * 28 * 28), np.uint8)
    return (data.reshape(n, 28 * 28) / 255.0 * 2.0 - 1.0).astype("float32")


def get_real_samples(batch_size: int, data_np: np.ndarray) -> np.ndarray:
    return data_np[np.random.choice(data_np.shape[0], batch_size,
                                    replace=False), :]


def run(data_source: str = "uniform", num_iter: int = 120,
        num_passes: int = 1, workdir: str = "./gan_work",
        conf_override: str | None = None, log_period: int = 20):
    """Returns (dis_losses, gen_losses, trained_sides) across iterations."""
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.trainer.config_parser import parse_config
    from paddle_tpu.trainer.inference import Inference

    assert data_source in ("uniform", "mnist", "cifar")
    conf = conf_override or os.path.join(
        REFERENCE_ROOT, "v1_api_demo/gan",
        "gan_conf.py" if data_source == "uniform" else "gan_conf_image.py")
    cargs = f"mode=%s" + (f",data={data_source}"
                          if data_source != "uniform" else "")

    gen_conf = parse_config(conf, cargs % "generator_training")
    gen_training = Machine(gen_conf)
    dis_conf = parse_config(conf, cargs % "discriminator_training")
    dis_training = Machine(dis_conf)
    generator_conf = parse_config(conf, cargs % "generator")
    batch_size = dis_conf.opt_config.batch_size or 128
    noise_dim = next(n.attrs["dim"] for n in generator_conf.layers
                     if n.name == "noise")
    import paddle_tpu as paddle

    generator_machine = Inference(
        generator_conf.output_layers(),
        paddle.parameters.create(Topology(generator_conf.output_layers())))

    if data_source == "uniform":
        data_np = load_uniform_data()
    else:
        data_np = load_mnist_like(workdir)

    # Sync parameters between networks at the beginning (gan_trainer:268)
    copy_shared_parameters(gen_training, dis_training)
    copy_shared_parameters(gen_training, generator_machine)

    def fake_samples(noise):
        # flatten any spatial output to the reference's flat-row convention
        # (copyToNumpyMat returns [B, sample_dim])
        out = np.asarray(generator_machine.infer([(row,) for row in noise]))
        return out.reshape(len(noise), -1)

    curr_train, curr_strike, MAX_strike = "dis", 0, 5
    dis_losses, gen_losses, sides = [], [], []
    for train_pass in range(num_passes):
        for i in range(num_iter):
            noise = get_noise(batch_size, noise_dim)
            real = get_real_samples(batch_size, data_np)
            ones = np.ones(batch_size, dtype="int32")
            zeros = np.zeros(batch_size, dtype="int32")
            batch_dis_pos = [(real[j], int(ones[j]))
                             for j in range(batch_size)]
            fake = fake_samples(noise)
            batch_dis_neg = [(fake[j], int(zeros[j]))
                             for j in range(batch_size)]
            batch_gen = [(noise[j], int(ones[j]))
                         for j in range(batch_size)]

            dis_loss_pos = dis_training.loss(batch_dis_pos)
            dis_loss_neg = dis_training.loss(batch_dis_neg)
            dis_loss = (dis_loss_pos + dis_loss_neg) / 2.0
            gen_loss = gen_training.loss(batch_gen)
            dis_losses.append(dis_loss)
            gen_losses.append(gen_loss)

            if i % log_period == 0:
                print(f"pass {train_pass} iter {i}: d_loss {dis_loss:.4f} "
                      f"(pos {dis_loss_pos:.4f} neg {dis_loss_neg:.4f}) "
                      f"g_loss {gen_loss:.4f} training={curr_train}")

            # strike schedule (gan_trainer.py:299-331)
            if (not (curr_train == "dis" and curr_strike == MAX_strike)) and \
               ((curr_train == "gen" and curr_strike == MAX_strike)
                    or dis_loss > gen_loss):
                if curr_train == "dis":
                    curr_strike += 1
                else:
                    curr_train, curr_strike = "dis", 1
                dis_training.train_batch(batch_dis_neg)
                dis_training.train_batch(batch_dis_pos)
                copy_shared_parameters(dis_training, gen_training)
            else:
                if curr_train == "gen":
                    curr_strike += 1
                else:
                    curr_train, curr_strike = "gen", 1
                gen_training.train_batch(batch_gen)
                copy_shared_parameters(gen_training, dis_training)
                copy_shared_parameters(gen_training, generator_machine)
            sides.append(curr_train)

    final = fake_samples(get_noise(batch_size, noise_dim))
    print("generated sample mean:", np.mean(final, 0),
          "std:", np.std(final, 0))
    return dis_losses, gen_losses, sides, final


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("-d", "--data_source", default="uniform",
                    choices=["uniform", "mnist", "cifar"])
    ap.add_argument("--num_iter", type=int, default=120)
    ap.add_argument("--num_passes", type=int, default=1)
    ap.add_argument("--workdir", default="./gan_work")
    args = ap.parse_args(argv)
    dis_losses, gen_losses, sides, _ = run(
        data_source=args.data_source, num_iter=args.num_iter,
        num_passes=args.num_passes, workdir=args.workdir)
    trained_both = len(set(sides)) == 2
    print(f"trained sides: {sorted(set(sides))}; "
          f"final d_loss {dis_losses[-1]:.4f} g_loss {gen_losses[-1]:.4f}")
    return 0 if trained_both and np.isfinite(dis_losses[-1]) else 1


if __name__ == "__main__":
    raise SystemExit(main())
