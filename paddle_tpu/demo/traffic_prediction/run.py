"""traffic_prediction — the reference multi-task config
(``v1_api_demo/traffic_prediction/trainer_config.py``) executed verbatim
(copied byte-identical into the workdir so the py3 dataprovider port in
this package shadows the python-2-only original), on synthetic traffic
CSVs.

Run: python -m paddle_tpu.demo.traffic_prediction.run [--passes N]
"""

from __future__ import annotations

import argparse
import os
import random
import shutil

from paddle_tpu.demo import REFERENCE_ROOT

TERM_NUM, FORECASTING_NUM = 24, 24


def make_data(workdir: str, links: int = 40, t: int = 120) -> None:
    data = os.path.join(workdir, "data")
    os.makedirs(data, exist_ok=True)
    rnd = random.Random(0)

    def gen(path, n_links):
        with open(path, "w") as f:
            f.write("link," + ",".join(f"t{i}" for i in range(t)) + "\n")
            for li in range(n_links):
                # speeds 1..4 with slow daily drift (class 0 = missing)
                base = rnd.randint(1, 4)
                speeds = []
                for i in range(t):
                    base = min(4, max(1, base + rnd.choice((-1, 0, 0, 1))))
                    speeds.append(str(base))
                f.write(f"link_{li}," + ",".join(speeds) + "\n")

    gen(os.path.join(data, "train.csv"), links)
    gen(os.path.join(data, "test.csv"), max(links // 4, 2))
    with open(os.path.join(data, "train.list"), "w") as f:
        f.write("data/train.csv\n")
    with open(os.path.join(data, "test.list"), "w") as f:
        f.write("data/test.csv\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--workdir", default="./traffic_work")
    args = ap.parse_args(argv)

    os.makedirs(args.workdir, exist_ok=True)
    make_data(args.workdir)
    ref_cfg = os.path.join(
        REFERENCE_ROOT, "v1_api_demo/traffic_prediction/trainer_config.py")
    cfg = os.path.join(args.workdir, "trainer_config.py")
    shutil.copyfile(ref_cfg, cfg)  # byte-identical
    shutil.copyfile(
        os.path.join(os.path.dirname(__file__), "dataprovider.py"),
        os.path.join(args.workdir, "dataprovider.py"))
    cwd = os.getcwd()
    os.chdir(args.workdir)
    try:
        from paddle_tpu.trainer import cli

        return cli.main(["--config", "trainer_config.py", "--job", "train",
                         "--num_passes", str(args.passes)])
    finally:
        os.chdir(cwd)


if __name__ == "__main__":
    raise SystemExit(main())
