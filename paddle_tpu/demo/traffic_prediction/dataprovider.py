"""Python-3 port of ``v1_api_demo/traffic_prediction/dataprovider.py``.

The reference provider is python-2-only (``f.next()``, list-returning
``map``, ``sys.maxint``); the semantics here are identical: each CSV row
is ``link_id,spd,spd,...``; a sliding window of TERM_NUM speeds is the
dense input and the following FORECASTING_NUM speeds (minus 1, classes
0..3; windows containing missing readings are dropped) are the
multi-task labels.
"""

from __future__ import annotations

import sys

from paddle.trainer.PyDataProvider2 import (
    CacheType,
    dense_vector,
    integer_value,
    provider,
)

TERM_NUM = 24
FORECASTING_NUM = 24
LABEL_VALUE_NUM = 4


def initHook(settings, file_list, **kwargs):
    del kwargs
    settings.pool_size = sys.maxsize
    settings.input_types = [dense_vector(TERM_NUM)] + [
        integer_value(LABEL_VALUE_NUM) for _ in range(FORECASTING_NUM)
    ]


@provider(
    init_hook=initHook, cache=CacheType.CACHE_PASS_IN_MEM,
    should_shuffle=True)
def process(settings, file_name):
    with open(file_name) as f:
        next(f)  # header row
        for line in f:
            speeds = [int(t) for t in line.rstrip("\r\n").split(",")[1:]]
            end_time = len(speeds)
            for i in range(TERM_NUM, end_time - FORECASTING_NUM):
                pre_spd = [float(s) for s in speeds[i - TERM_NUM:i]]
                fol_spd = [j - 1 for j in speeds[i:i + FORECASTING_NUM]]
                if -1 in fol_spd:
                    continue
                yield [pre_spd] + fol_spd


def predict_initHook(settings, file_list, **kwargs):
    settings.pool_size = sys.maxsize
    settings.input_types = [dense_vector(TERM_NUM)]


@provider(init_hook=predict_initHook, should_shuffle=False)
def process_predict(settings, file_name):
    with open(file_name) as f:
        next(f)
        for line in f:
            speeds = [int(t) for t in line.rstrip("\r\n").split(",")]
            end_time = len(speeds)
            yield [float(s) for s in speeds[end_time - TERM_NUM:end_time]]
