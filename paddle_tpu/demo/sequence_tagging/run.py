"""sequence_tagging — the reference CRF tagger configs
(``v1_api_demo/sequence_tagging/rnn_crf.py`` and ``linear_crf.py``)
executed verbatim (byte-identical copies; the py3 dataprovider port in
this package shadows the python-2-only original) on synthetic
CoNLL-2000-shaped data.  Exercises mixed/table projections, forward and
reverse recurrent_layer, crf_layer + crf_decoding_layer, the chunk
evaluator (IOB, 11 types) and sum evaluator, ModelAverage and LR decay.

Run: python -m paddle_tpu.demo.sequence_tagging.run [--config rnn_crf.py]
"""

from __future__ import annotations

import argparse
import os
import random
import shutil

from paddle_tpu.demo import REFERENCE_ROOT

# dims hardcoded by the reference configs (rnn_crf.py:47-52); the
# dataprovider module declares the same (it imports the `paddle` alias,
# so it is only importable once a config parse installed it)
FEATURE_DIM, WORD_DIM, POS_DIM, CHUNK_DIM = 76328, 6778, 44, 23


def make_data(workdir: str, n_train: int = 64, n_test: int = 16) -> None:
    data = os.path.join(workdir, "data")
    os.makedirs(data, exist_ok=True)
    rnd = random.Random(0)

    def gen(path, n):
        with open(path, "w") as f:
            for _ in range(n):
                length = rnd.randint(3, 8)
                for _t in range(length):
                    word = rnd.randrange(WORD_DIM)
                    pos = rnd.randrange(POS_DIM)
                    # IOB chunk ids: B=2*type, I=2*type+1, O=22
                    chunk = (22 if rnd.random() < 0.4
                             else 2 * rnd.randrange(11) + rnd.randint(0, 1))
                    feats = sorted(rnd.sample(range(FEATURE_DIM), 6))
                    f.write(" ".join(map(str, [word, pos, chunk] + feats))
                            + "\n")
                f.write("\n")

    gen(os.path.join(data, "train.txt"), n_train)
    gen(os.path.join(data, "test.txt"), n_test)
    with open(os.path.join(data, "train.list"), "w") as f:
        f.write("data/train.txt\n")
    with open(os.path.join(data, "test.list"), "w") as f:
        f.write("data/test.txt\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="rnn_crf.py",
                    choices=["rnn_crf.py", "linear_crf.py"])
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--workdir", default="./sequence_tagging_work")
    args = ap.parse_args(argv)

    os.makedirs(args.workdir, exist_ok=True)
    make_data(args.workdir)
    ref = os.path.join(REFERENCE_ROOT, "v1_api_demo/sequence_tagging",
                       args.config)
    shutil.copyfile(ref, os.path.join(args.workdir, args.config))
    shutil.copyfile(
        os.path.join(os.path.dirname(__file__), "dataprovider.py"),
        os.path.join(args.workdir, "dataprovider.py"))
    cwd = os.getcwd()
    os.chdir(args.workdir)
    try:
        from paddle_tpu.trainer import cli

        return cli.main(["--config", args.config, "--job", "train",
                         "--num_passes", str(args.passes)])
    finally:
        os.chdir(cwd)


if __name__ == "__main__":
    raise SystemExit(main())
