"""Python-3 data provider for the sequence_tagging demo configs
(``v1_api_demo/sequence_tagging/{rnn_crf,linear_crf}.py`` run verbatim).

The reference provider (python-2-only) builds CoNLL-2000 feature
dictionaries with frequency cutoffs; the configs hardcode the resulting
dims (features 76328, word 6778, pos 44, chunk 23).  This port keeps the
exact slot contract — [sparse features, word id, pos id, chunk label]
per token, one sequence per sentence — over a simple file format:
``word_id pos_id chunk_id feat_id feat_id ...`` lines, blank line
between sentences.
"""

from __future__ import annotations

from paddle.trainer.PyDataProvider2 import (
    integer_value_sequence,
    provider,
    sparse_binary_vector_sequence,
)

FEATURE_DIM = 76328
WORD_DIM = 6778
POS_DIM = 44
CHUNK_DIM = 23


@provider(input_types={
    "features": sparse_binary_vector_sequence(FEATURE_DIM),
    "word": integer_value_sequence(WORD_DIM),
    "pos": integer_value_sequence(POS_DIM),
    "chunk": integer_value_sequence(CHUNK_DIM),
})
def process(settings, file_name):
    with open(file_name) as f:
        feats, words, poss, chunks = [], [], [], []
        for line in f:
            line = line.strip()
            if not line:
                if words:
                    yield {"features": feats, "word": words, "pos": poss,
                           "chunk": chunks}
                    feats, words, poss, chunks = [], [], [], []
                continue
            parts = [int(t) for t in line.split()]
            words.append(parts[0])
            poss.append(parts[1])
            chunks.append(parts[2])
            feats.append(parts[3:])
        if words:
            yield {"features": feats, "word": words, "pos": poss,
                   "chunk": chunks}
