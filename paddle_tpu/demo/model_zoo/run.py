"""model_zoo-style pretrained-model feature extraction
(≅ ``v1_api_demo/model_zoo/resnet/classify.py``: load a pretrained
parameter DIRECTORY — one reference-binary file per parameter — and pull
an intermediate layer's activations as features).

The original demo downloads a pretrained ResNet; its mechanism is what
matters for parity and is exercised here end to end with a small CNN:

1. train briefly, 2. dump the parameters in the reference
``Parameter::save`` binary-dir layout (``Parameters.to_reference_dir``),
3. load them into a FRESH model from that directory
(``init_from_reference_dir`` — the same loader consumes the reference's
own model_zoo dumps, as the rnn-generation goldens prove with
``rnn_gen_test_model_dir``), 4. extract penultimate-layer features via
``paddle.infer(output_layer=...)`` like classify.py's
``--job=extract_fea_py``.

Run: python -m paddle_tpu.demo.model_zoo.run
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def build_model(img_hw: int = 16, classes: int = 4):
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type

    base.reset_name_counters()
    img = layer.data(name="image",
                     type=data_type.dense_vector(img_hw * img_hw))
    conv = layer.img_conv_layer(input=img, filter_size=3, num_filters=8,
                                num_channels=1, padding=1,
                                act=act.ReluActivation())
    pool = layer.img_pool_layer(input=conv, pool_size=2, stride=2)
    feat = layer.fc_layer(input=pool, size=32, act=act.TanhActivation(),
                          name="feature")
    pred = layer.fc_layer(input=feat, size=classes,
                          act=act.SoftmaxActivation())
    lbl = layer.data(name="label", type=data_type.integer_value(classes))
    cost = layer.classification_cost(input=pred, label=lbl)
    return cost, feat, img_hw, classes


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", default="./model_zoo_work")
    ap.add_argument("--batches", type=int, default=30)
    args = ap.parse_args(argv)
    os.makedirs(args.workdir, exist_ok=True)

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology

    cost, feat, hw, classes = build_model()
    params = paddle.parameters.create(Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-3))

    rng = np.random.default_rng(0)

    def reader():
        for _ in range(args.batches * 16):
            y = int(rng.integers(0, classes))
            x = rng.normal(size=(hw * hw,)).astype(np.float32) * 0.1
            x[y * 32:(y + 1) * 32] += 1.0
            yield x, y

    trainer.train(reader=paddle.reader.batch(reader, batch_size=16),
                  num_passes=1)

    # 2. dump in the reference pretrained-model-dir layout
    model_dir = os.path.join(args.workdir, "pretrained_model")
    params.to_reference_dir(model_dir)
    print(f"saved {len(params.names())} parameters to {model_dir} "
          "(reference Parameter::save binary format)")

    # 3. fresh model + warm start from the binary dir
    cost2, feat2, _, _ = build_model()
    params2 = paddle.parameters.create(Topology(cost2))
    params2.init_from_reference_dir(model_dir)

    # 4. feature extraction (classify.py --job=extract_fea_py analog)
    batch = [(rng.normal(size=(hw * hw,)).astype(np.float32),)
             for _ in range(8)]
    feats = paddle.infer(output_layer=feat2, parameters=params2,
                         input=batch, feeding={"image": 0})
    feats = np.asarray(feats)
    print(f"extracted features: shape {feats.shape}")
    # the loaded model must reproduce the trained one bit-for-bit
    feats_ref = np.asarray(paddle.infer(
        output_layer=feat, parameters=params, input=batch,
        feeding={"image": 0}))
    assert np.allclose(feats, feats_ref, atol=1e-6), "feature mismatch"
    print("features from the reloaded binary-dir model match the "
          "trained model")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
