"""Python-3 port of ``v1_api_demo/mnist/mnist_util.py`` (the original is
python-2-only: ``xrange``) — same idx-ubyte reading and [-1, 1] pixel
scaling, but the sample count comes from the idx HEADER instead of the
original's hardcoded 60000/10000, so synthetic stand-in datasets of any
size work.  ``mnist_provider.py`` and the configs run byte-identical."""

from __future__ import annotations

import struct

import numpy

__all__ = ["read_from_mnist"]


def read_from_mnist(filename):
    imgf = filename + "-images-idx3-ubyte"
    labelf = filename + "-labels-idx1-ubyte"
    with open(imgf, "rb") as f, open(labelf, "rb") as l:  # noqa: E741
        _, n, rows, cols = struct.unpack(">iiii", f.read(16))
        l.read(8)
        images = numpy.fromfile(
            f, "ubyte", count=n * rows * cols).reshape(
            (n, rows * cols)).astype("float32")
        images = images / 255.0 * 2.0 - 1.0
        labels = numpy.fromfile(l, "ubyte", count=n).astype("int")

    for i in range(n):
        yield {"pixel": images[i, :], "label": labels[i]}
