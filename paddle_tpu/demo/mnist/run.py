"""mnist — the reference configs (``v1_api_demo/mnist/light_mnist.py`` or
``vgg_16_mnist.py``) and provider (``mnist_provider.py``) executed
byte-identical on synthetic idx-format digit data; only ``mnist_util``
is a py3 port (this package).

Run: python -m paddle_tpu.demo.mnist.run [--config light_mnist.py]
"""

from __future__ import annotations

import argparse
import os
import shutil
import struct

import numpy as np

from paddle_tpu.demo import REFERENCE_ROOT


def _write_idx(prefix: str, n: int, seed: int) -> None:
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, size=(n,)).astype(np.uint8)
    images = rng.integers(0, 60, size=(n, 28, 28)).astype(np.uint8)
    # class signal: a bright 6x6 patch whose position encodes the digit
    for i, y in enumerate(labels):
        r, c = divmod(int(y), 5)
        images[i, 4 + r * 12: 10 + r * 12, 2 + c * 5: 8 + c * 5] = 250
    with open(prefix + "-images-idx3-ubyte", "wb") as f:
        f.write(struct.pack(">iiii", 0x803, n, 28, 28))
        f.write(images.tobytes())
    with open(prefix + "-labels-idx1-ubyte", "wb") as f:
        f.write(struct.pack(">ii", 0x801, n))
        f.write(labels.tobytes())


def make_data(workdir: str, n_train: int = 1024, n_test: int = 256) -> None:
    raw = os.path.join(workdir, "data", "raw_data")
    os.makedirs(raw, exist_ok=True)
    _write_idx(os.path.join(raw, "train"), n_train, seed=0)
    _write_idx(os.path.join(raw, "t10k"), n_test, seed=1)
    data = os.path.join(workdir, "data")
    with open(os.path.join(data, "train.list"), "w") as f:
        f.write("data/raw_data/train\n")
    with open(os.path.join(data, "test.list"), "w") as f:
        f.write("data/raw_data/t10k\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="light_mnist.py",
                    choices=["light_mnist.py", "vgg_16_mnist.py"])
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--workdir", default="./mnist_work")
    ap.add_argument("--n-train", type=int, default=1024)
    ap.add_argument("--n-test", type=int, default=256)
    args = ap.parse_args(argv)

    os.makedirs(args.workdir, exist_ok=True)
    make_data(args.workdir, n_train=args.n_train, n_test=args.n_test)
    src = os.path.join(REFERENCE_ROOT, "v1_api_demo/mnist")
    for fn in (args.config, "mnist_provider.py"):
        shutil.copyfile(os.path.join(src, fn),
                        os.path.join(args.workdir, fn))  # byte-identical
    shutil.copyfile(
        os.path.join(os.path.dirname(__file__), "mnist_util.py"),
        os.path.join(args.workdir, "mnist_util.py"))
    cwd = os.getcwd()
    os.chdir(args.workdir)
    try:
        from paddle_tpu.trainer import cli

        return cli.main(["--config", args.config, "--job", "train",
                         "--num_passes", str(args.passes)])
    finally:
        os.chdir(cwd)


if __name__ == "__main__":
    raise SystemExit(main())
