"""PR1 end-to-end slice (SURVEY §7.3): MNIST LeNet through the v2 API —
reader → DataFeeder → topology → jitted train step (forward, jax.grad, SGD
update) → events → Parameters tar round trip → inference.  Mirrors the
reference's test_TrainerOnePass / api/test/testTrain.py."""

import io

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.lenet import lenet_cost


def test_mnist_lenet_one_pass_learns():
    cost, predict, img, label = lenet_cost()
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    optimizer = paddle.optimizer.Momentum(
        momentum=0.9, learning_rate=0.05,
        regularization=paddle.optimizer.L2Regularization(rate=1e-4),
    )
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters, update_equation=optimizer
    )

    events = []

    def handler(e):
        events.append(type(e).__name__)
        if isinstance(e, paddle.event.EndIteration):
            assert np.isfinite(e.cost)

    reader = paddle.reader.batch(
        paddle.reader.shuffle(paddle.dataset.mnist.train(), buf_size=512),
        batch_size=64,
    )
    small = paddle.reader.firstn(reader, 30)  # 30 batches is plenty to learn blobs
    trainer.train(reader=small, num_passes=2, event_handler=handler)

    assert "BeginPass" in events and "EndPass" in events
    assert "EndIteration" in events

    result = trainer.test(
        reader=paddle.reader.batch(paddle.dataset.mnist.test(), batch_size=64)
    )
    err = result.metrics["classification_error_evaluator"]
    assert err < 0.25, f"model did not learn: error={err}"


def test_parameters_tar_and_inference_consistency():
    cost, predict, img, label = lenet_cost()
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.SGD(learning_rate=0.01),
    )
    reader = paddle.reader.batch(paddle.dataset.mnist.train(), batch_size=32)
    trainer.train(reader=paddle.reader.firstn(reader, 3), num_passes=1)

    buf = io.BytesIO()
    trainer.save_parameter_to_tar(buf)
    buf.seek(0)
    loaded = paddle.parameters.Parameters.from_tar(buf)

    samples = [s for _, s in zip(range(8), paddle.dataset.mnist.test()())]
    probs1 = paddle.infer(
        output_layer=predict, parameters=trainer.parameters,
        input=[(s[0],) for s in samples],
    )
    probs2 = paddle.infer(
        output_layer=predict, parameters=loaded,
        input=[(s[0],) for s in samples],
    )
    np.testing.assert_allclose(probs1, probs2, rtol=1e-5, atol=1e-6)
    assert probs1.shape == (8, 10)
    np.testing.assert_allclose(probs1.sum(axis=1), 1.0, rtol=1e-4)
