"""TPP fused microkernel layer (ops/pallas/tpp): interpret-mode parity of
every kernel against its in-module jnp reference (forward AND gradients),
flag-routing semantics, the fused conv+BN+ReLU layer node, and the
ZeRO-2 fused shard update's bit-identical trajectory."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core import flags
from paddle_tpu.ops.pallas import tpp


@pytest.fixture
def flag_snapshot():
    snap = flags.snapshot_raw()
    yield
    flags.restore_raw(snap)


# -- brgemm -------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_brgemm_matches_reference(rng_np, dtype):
    a = jnp.asarray(rng_np.normal(size=(3, 17, 9)).astype(np.float32)).astype(dtype)
    b = jnp.asarray(rng_np.normal(size=(3, 9, 21)).astype(np.float32)).astype(dtype)
    ref = tpp.brgemm_reference(a, b)
    ker = tpp.brgemm(a, b, impl="kernel", interpret=True)
    assert ker.dtype == ref.dtype == dtype
    np.testing.assert_allclose(np.asarray(ref, np.float32),
                               np.asarray(ker, np.float32),
                               rtol=2e-5, atol=2e-5)


def test_brgemm_epilogue_and_stats(rng_np):
    a = jnp.asarray(rng_np.normal(size=(2, 30, 12)).astype(np.float32))
    b = jnp.asarray(rng_np.normal(size=(2, 12, 7)).astype(np.float32))
    sc = jnp.asarray(rng_np.normal(size=(7,)).astype(np.float32))
    sh = jnp.asarray(rng_np.normal(size=(7,)).astype(np.float32))
    ref, rs, rss = tpp.brgemm_reference(a, b, scale=sc, shift=sh,
                                        act="relu", stats=True)
    ker, ks, kss = tpp.brgemm(a, b, scale=sc, shift=sh, act="relu",
                              stats=True, impl="kernel", interpret=True)
    assert float(jnp.min(ker)) >= 0.0  # relu applied
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ker),
                               rtol=2e-5, atol=2e-5)
    # stats are of the PRE-epilogue accumulator (row/col padding excluded)
    np.testing.assert_allclose(np.asarray(rs), np.asarray(ks),
                               rtol=2e-5, atol=2e-4)
    np.testing.assert_allclose(np.asarray(rss), np.asarray(kss),
                               rtol=2e-5, atol=2e-3)


# -- channel stats ------------------------------------------------------------


def test_channel_stats_matches_reference_fwd_and_grad(rng_np):
    x = jnp.asarray(rng_np.normal(size=(3, 5, 6, 7)).astype(np.float32))
    rs, rss = tpp.channel_stats_reference(x)
    ks, kss = tpp.channel_stats(x, "kernel", True)
    np.testing.assert_allclose(np.asarray(rs), np.asarray(ks), atol=1e-5)
    np.testing.assert_allclose(np.asarray(rss), np.asarray(kss), atol=1e-5)

    def loss_r(x):
        s, ss = tpp.channel_stats_reference(x)
        return jnp.sum(s * 0.5) + jnp.sum(ss * 0.25)

    def loss_k(x):
        s, ss = tpp.channel_stats(x, "kernel", True)
        return jnp.sum(s * 0.5) + jnp.sum(ss * 0.25)

    np.testing.assert_allclose(np.asarray(jax.grad(loss_r)(x)),
                               np.asarray(jax.grad(loss_k)(x)),
                               rtol=2e-5, atol=2e-5)


# -- direct conv --------------------------------------------------------------


@pytest.mark.parametrize("cfg", [
    (3, 1, 1),   # the ResNet 3x3
    (3, 2, 1),   # strided 3x3
    (1, 1, 0),   # 1x1 -> the brgemm fast path
    (1, 2, 0),   # strided 1x1 (downsample projection)
    (7, 2, 3),   # the stem conv
])
def test_conv2d_direct_matches_reference(rng_np, cfg):
    k, s, p = cfg
    x = jnp.asarray(rng_np.normal(size=(2, 13, 14, 5)).astype(np.float32))
    w = jnp.asarray(rng_np.normal(size=(k, k, 5, 9)).astype(np.float32) * .3)
    ref = tpp.conv2d_direct_reference(x, w, stride=s, padding=p)
    ker = tpp.conv2d_direct(x, w, stride=s, padding=p, impl="kernel",
                            interpret=True)
    assert ker.shape == ref.shape
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ker),
                               rtol=2e-5, atol=2e-5)

    def loss(fn):
        return lambda x, w: jnp.sum(fn(x, w) ** 2)

    gr = jax.grad(loss(lambda x, w: tpp.conv2d_direct_reference(
        x, w, stride=s, padding=p)), argnums=(0, 1))(x, w)
    gk = jax.grad(loss(lambda x, w: tpp.conv2d_direct(
        x, w, stride=s, padding=p, impl="kernel", interpret=True)),
        argnums=(0, 1))(x, w)
    for a, b in zip(gr, gk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


# -- fused conv + BN + act ----------------------------------------------------


@pytest.mark.parametrize("is_train", [True, False])
def test_conv2d_bn_act_matches_reference(rng_np, is_train):
    x = jnp.asarray(rng_np.normal(size=(2, 10, 11, 4)).astype(np.float32))
    w = jnp.asarray(rng_np.normal(size=(3, 3, 4, 8)).astype(np.float32) * .3)
    ga = jnp.asarray(rng_np.normal(size=(8,)).astype(np.float32) * .2 + 1)
    be = jnp.asarray(rng_np.normal(size=(8,)).astype(np.float32) * .2)
    rm = jnp.asarray(rng_np.normal(size=(8,)).astype(np.float32) * .1)
    rv = jnp.asarray(np.abs(rng_np.normal(size=(8,)).astype(np.float32)) + .5)

    def run(impl):
        return tpp.conv2d_bn_act(x, w, ga, be, rm, rv, is_train, stride=2,
                                 padding=1, act="relu", impl=impl,
                                 interpret=True)

    ref, ker = run("reference"), run("kernel")
    for a, b in zip(ref, ker):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)

    def loss(impl):
        def f(x, w, ga, be):
            y, nm, nv = tpp.conv2d_bn_act(
                x, w, ga, be, rm, rv, is_train, stride=2, padding=1,
                act="relu", impl=impl, interpret=True)
            return jnp.sum(y ** 2) + jnp.sum(nm) + 0.5 * jnp.sum(nv)
        return f

    gr = jax.grad(loss("reference"), argnums=(0, 1, 2, 3))(x, w, ga, be)
    gk = jax.grad(loss("kernel"), argnums=(0, 1, 2, 3))(x, w, ga, be)
    for a, b in zip(gr, gk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_conv2d_bn_act_reference_equals_unfused_composition(rng_np):
    """The reference IS the separate conv2d -> batch_norm -> relu chain —
    bit-identical, the bench ablation's CPU contract."""
    from paddle_tpu.ops import nn

    x = jnp.asarray(rng_np.normal(size=(2, 8, 9, 3)).astype(np.float32))
    w = jnp.asarray(rng_np.normal(size=(3, 3, 3, 6)).astype(np.float32))
    ga, be = jnp.ones((6,)), jnp.zeros((6,))
    rm, rv = jnp.zeros((6,)), jnp.ones((6,))
    y1, nm1, nv1 = tpp.conv2d_bn_act_reference(
        x, w, ga, be, rm, rv, True, stride=1, padding=1, act="relu")
    yc = nn.conv2d_xla(x, w, stride=1, padding=1)
    y2, nm2, nv2 = nn.batch_norm(yc, ga, be, rm, rv, is_train=True,
                                 use_fused_stats=False)
    y2 = jax.nn.relu(y2)
    assert bool(jnp.all(y1 == y2))
    assert bool(jnp.all(nm1 == nm2)) and bool(jnp.all(nv1 == nv2))


# -- flag routing -------------------------------------------------------------


def test_fused_enabled_flag_semantics(flag_snapshot):
    flags.set("fused_kernels", "on")
    assert tpp.fused_enabled() is True
    flags.set("fused_kernels", "off")
    assert tpp.fused_enabled() is False
    flags.set("fused_kernels", "auto")
    assert tpp.fused_enabled() is (jax.default_backend() == "tpu")


def test_nn_conv2d_routes_through_tpp_when_forced(rng_np, flag_snapshot,
                                                  monkeypatch):
    """Flag on -> ops/nn.conv2d dispatches eligible shapes to the tpp
    entry; on CPU that entry resolves to the reference, so values are
    bit-equal to the unfused lowering."""
    import paddle_tpu.ops.nn as nn
    import jax as jax_mod

    x = jnp.asarray(rng_np.normal(size=(2, 8, 8, 3)).astype(np.float32))
    w = jnp.asarray(rng_np.normal(size=(3, 3, 3, 4)).astype(np.float32))
    base = nn.conv2d_xla(x, w, stride=1, padding=1)

    calls = {"direct": 0}

    def counting(x, w, stride=1, padding=0, **k):
        calls["direct"] += 1
        # the faked-TPU backend can't run a compiled kernel on CPU; the
        # dispatch decision is what's under test, so answer via the oracle
        return tpp.conv2d_direct_reference(x, w, stride=stride,
                                           padding=padding)

    flags.set("fused_kernels", "on")
    # flag on over CPU: dispatch requires a TPU backend, stays on XLA
    y = nn.conv2d(x, w, stride=1, padding=1)
    assert bool(jnp.all(y == base))
    # pretend TPU: the dispatcher must route to the tpp entry (whose
    # reference path reproduces the XLA values exactly)
    monkeypatch.setattr(jax_mod, "default_backend", lambda: "tpu")
    monkeypatch.setattr(tpp, "conv2d_direct", counting)
    try:
        y2 = nn.conv2d(x, w, stride=1, padding=1)
    finally:
        monkeypatch.undo()
    assert calls["direct"] == 1
    # groups/dilation stay on the XLA lowering regardless
    flags.set("fused_kernels", "on")
    yd = nn.depthwise_conv2d(x, jnp.ones((3, 3, 1, 3)), padding=1)
    assert yd.shape == (2, 8, 8, 3)


# -- fused optimizer update ---------------------------------------------------


@pytest.mark.parametrize("nesterov", [False, True])
def test_fused_momentum_update_matches_reference(rng_np, nesterov):
    p = jnp.asarray(rng_np.normal(size=(37, 53)).astype(np.float32))
    g = jnp.asarray(rng_np.normal(size=(37, 53)).astype(np.float32))
    v = jnp.asarray(rng_np.normal(size=(37, 53)).astype(np.float32))
    ref = tpp.fused_momentum_update_reference(p, g, v, 0.1, 0.9,
                                              nesterov=nesterov,
                                              weight_decay=0.01)
    ker = tpp.fused_momentum_update(p, g, v, jnp.float32(0.1),
                                    jnp.float32(0.9), nesterov=nesterov,
                                    weight_decay=0.01, impl="kernel",
                                    interpret=True)
    for a, b in zip(ref, ker):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6)


def test_fused_sgd_update_matches_reference(rng_np):
    p = jnp.asarray(rng_np.normal(size=(130,)).astype(np.float32))
    g = jnp.asarray(rng_np.normal(size=(130,)).astype(np.float32))
    ref = tpp.fused_sgd_update_reference(p, g, 0.05, weight_decay=0.02)
    ker = tpp.fused_sgd_update(p, g, jnp.float32(0.05), weight_decay=0.02,
                               impl="kernel", interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ker), atol=2e-6)


def test_fused_update_reference_bit_equals_optimizer_apply(rng_np):
    """The reference replicates Optimizer.apply op for op — bit-equal, so
    the fused ZeRO-2 path cannot drift from the unfused trainer."""
    from paddle_tpu.core.parameters import ParamSpec
    from paddle_tpu.optimizer import Momentum

    p = jnp.asarray(rng_np.normal(size=(24, 16)).astype(np.float32))
    g = jnp.asarray(rng_np.normal(size=(24, 16)).astype(np.float32))
    v = jnp.asarray(rng_np.normal(size=(24, 16)).astype(np.float32))
    opt = Momentum(momentum=0.9, learning_rate=0.1)
    specs = {"w": ParamSpec(name="w", shape=p.shape, initializer=None)}
    state = opt.init({"w": p}, specs)
    state["slots"]["w"]["velocity"] = v
    new_p, new_s = opt.apply({"w": g}, {"w": p}, state, specs)
    lr = opt.lr_fn(state["step"]) * specs["w"].learning_rate
    wd = (specs["w"].decay_rate
          if specs["w"].decay_rate is not None else opt.l2_rate) or 0.0
    fp, fv = tpp.fused_momentum_update_reference(p, g, v, lr, 0.9,
                                                 weight_decay=wd)
    assert bool(jnp.all(new_p["w"] == fp))
    assert bool(jnp.all(new_s["slots"]["w"]["velocity"] == fv))


def test_zero2_fused_shard_update_trajectory_bit_identical(flag_snapshot):
    """4 ZeRO-2 steps on the forced-8-device mesh: the fused shard update
    (flag on) must reproduce the unfused optimizer.apply trajectory
    bit for bit, and must actually be taken (fused_shard_apply used)."""
    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core import rng as prng
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.parallel import mesh as mesh_mod
    from paddle_tpu.parallel import zero as Z
    from paddle_tpu.trainer.step import build_train_step

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")

    in_dim, classes = 32, 8
    rngn = np.random.default_rng(3)
    feeds = [{"x": jnp.asarray(rngn.normal(size=(16, in_dim)).astype(np.float32)),
              "y": jnp.asarray(rngn.integers(0, classes, size=(16,)))}
             for _ in range(4)]

    def cost():
        x = layer.data(name="x", type=data_type.dense_vector(in_dim))
        h = layer.fc(input=x, size=64, act=act.ReluActivation())
        pred = layer.fc(input=h, size=classes, act=act.SoftmaxActivation())
        lab = layer.data(name="y", type=data_type.integer_value(classes))
        return layer.classification_cost(input=pred, label=lab)

    def train(fused):
        flags.set("fused_kernels", "on" if fused else "off")
        base.reset_name_counters()
        prng.seed(7)
        topo = Topology(cost())
        mesh = mesh_mod.MeshContext(mesh=mesh_mod.make_mesh({"data": 8}))
        params = {k: jnp.array(v) for k, v in
                  paddle.parameters.create(topo).as_dict().items()}
        opt = Momentum(momentum=0.9, learning_rate=1e-2)
        specs = {s.name: s for s in topo.param_specs()}
        opt_state = opt.init(params, specs)
        states = topo.init_states()
        params = mesh.place_params(params, specs)
        states = mesh.replicate(states)
        opt_state = Z.shard_opt_state(opt_state, params, mesh.mesh)
        step = build_train_step(topo, opt, mesh=mesh, zero=2)
        key = jax.random.key(0)
        for feed in feeds:
            params, opt_state, states, c, _ = step(
                params, opt_state, states, mesh.shard_batch(feed), key)
        return {k: np.asarray(v) for k, v in params.items()}, float(c)

    p_off, c_off = train(False)
    p_on, c_on = train(True)
    assert c_off == c_on
    for k in p_off:
        assert np.array_equal(p_off[k], p_on[k]), k


def test_fused_shard_apply_declines_ineligible_configs():
    """Adam / model-average / clipping configs must fall back (None)."""
    from paddle_tpu.optimizer import Adam, Momentum

    assert tpp.fused_shard_apply(
        Adam(), {}, {}, {"step": 0, "slots": {}}, {}, None, {}) is None
    clip = Momentum(momentum=0.9, gradient_clipping_threshold=1.0)
    assert tpp.fused_shard_apply(
        clip, {}, {}, {"step": 0, "slots": {}}, {}, None, {}) is None


# -- the fused layer node -----------------------------------------------------


def test_img_conv_bn_layer_matches_separate_layers(rng_np):
    """layer.img_conv_bn == img_conv(no bias, linear) -> batch_norm(relu)
    on identical weights: same forward, same running stats, same grads."""
    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type

    h = w = 8
    x = rng_np.normal(size=(4, 3 * h * w)).astype(np.float32)

    def build(fused):
        base.reset_name_counters()
        img = layer.data(name="image",
                         type=data_type.dense_vector(3 * h * w, channels=3),
                         height=h, width=w)
        if fused:
            out = layer.img_conv_bn(name="blk", input=img, filter_size=3,
                                    num_filters=6, num_channels=3, padding=1,
                                    act=act.ReluActivation())
        else:
            tmp = layer.img_conv(name="blk_conv", input=img, filter_size=3,
                                 num_channels=3, num_filters=6, padding=1,
                                 act=act.LinearActivation(), bias_attr=False)
            out = layer.batch_norm(name="blk_bn", input=tmp,
                                   act=act.ReluActivation())
        topo = Topology(out)
        params = paddle.parameters.create(topo).as_dict()
        return topo, params, out.name

    topo_f, params_f, name_f = build(True)
    topo_u, params_u, name_u = build(False)
    # identical parameter census (the checkpoint-compat contract)
    assert sorted(params_f) == sorted(params_u)
    shared = {k: jnp.asarray(rng_np.normal(size=v.shape).astype(np.float32))
              for k, v in params_f.items()}
    states_f, states_u = topo_f.init_states(), topo_u.init_states()
    assert sorted(states_f) == sorted(states_u)

    vf, sf = topo_f.forward(shared, states_f, {"image": x}, True,
                            jax.random.key(0))
    vu, su = topo_u.forward(shared, states_u, {"image": x}, True,
                            jax.random.key(0))
    np.testing.assert_allclose(np.asarray(vf[name_f]),
                               np.asarray(vu[name_u]), atol=1e-6)
    for k in sf:
        np.testing.assert_allclose(np.asarray(sf[k]), np.asarray(su[k]),
                                   atol=1e-6)

    def loss(topo, name, states):
        def f(p):
            v, _ = topo.forward(p, states, {"image": x}, True,
                                jax.random.key(0))
            return jnp.sum(v[name] ** 2)
        return f

    gf = jax.grad(loss(topo_f, name_f, states_f))(shared)
    gu = jax.grad(loss(topo_u, name_u, states_u))(shared)
    for k in gf:
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(gu[k]),
                                   rtol=1e-5, atol=1e-5, err_msg=k)
