"""Transformer LM: single-device correctness, attn-impl equivalence, and the
full 3-axis (data x seq x model) sharded train step on the virtual mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.models import transformer as T
from paddle_tpu.optimizer import Adam


def _cfg(**kw):
    base = dict(
        vocab_size=64, num_layers=2, num_heads=2, embed_dim=16, mlp_dim=32,
        max_seq_len=32, remat=False,
    )
    base.update(kw)
    return T.TransformerConfig(**base)


def test_forward_shapes_and_loss():
    cfg = _cfg()
    params = T.init_params(cfg, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)))
    logits = T.forward(cfg, params, ids)
    assert logits.shape == (2, 16, 64)
    loss = T.loss_fn(cfg, params, ids)
    assert np.isfinite(float(loss))
    assert float(loss) < 2 * np.log(64)


def test_attn_impls_agree():
    cfg = _cfg()
    params = T.init_params(cfg, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)))
    ref = T.forward(cfg, params, ids)
    blk = T.forward(
        dataclasses.replace(cfg, attn_impl="blockwise", attn_block_size=4),
        params, ids,
    )
    np.testing.assert_allclose(np.asarray(blk), np.asarray(ref), atol=1e-4)
    flash = T.forward(dataclasses.replace(cfg, attn_impl="flash"), params, ids)
    np.testing.assert_allclose(np.asarray(flash), np.asarray(ref), atol=1e-4)


def test_train_step_learns():
    cfg = _cfg()
    params = T.init_params(cfg, jax.random.key(0))
    opt = Adam(learning_rate=1e-2)
    state = opt.init_tree(params)
    step = T.build_train_step(cfg, opt)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)))
    losses = []
    for _ in range(10):
        params, state, loss = step(params, state, ids)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8


def test_sharded_train_step_dp_tp_sp():
    """2x2x2 mesh: batch over data, sequence over seq (ring attention),
    weights over model — the full 3D parallel train step."""
    devs = np.asarray(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "seq", "model"))
    cfg = _cfg(attn_impl="ring")
    params = T.init_params(cfg, jax.random.key(0))
    params = T.place_params(params, mesh, cfg)
    opt = Adam(learning_rate=1e-2)
    state = opt.init_tree(params)
    step = T.build_train_step(cfg, opt, mesh=mesh)

    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 17)))
    # tokens: ids[:, :-1] has T=16 -> sharded 2-way over seq
    ids = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    l0 = None
    for _ in range(5):
        params, state, loss = step(params, state, ids)
        if l0 is None:
            l0 = float(loss)
    assert np.isfinite(float(loss)) and float(loss) < l0

    # sharded result == single-device result (first step loss)
    cfg1 = _cfg()
    params1 = T.init_params(cfg1, jax.random.key(0))
    ids1 = jnp.asarray(np.asarray(ids))
    loss1 = float(T.loss_fn(cfg1, params1, ids1))
    np.testing.assert_allclose(l0, loss1, atol=1e-3)


def test_sharded_forward_flash_dp_tp():
    """flash kernel per-device under shard_map on a data x model mesh."""
    devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh = Mesh(devs, ("data", "model"))
    cfg = _cfg(attn_impl="flash")
    params = T.init_params(cfg, jax.random.key(0))
    params = T.place_params(params, mesh, cfg)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 16)))
    ids = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    logits = jax.jit(lambda p, i: T.forward(cfg, p, i, mesh=mesh))(params, ids)
    ref = T.forward(_cfg(), params, jnp.asarray(np.asarray(ids)))
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref), atol=1e-4)
