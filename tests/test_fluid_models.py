"""Fluid model-level e2e parity (VERDICT r4 #6) — ports of the four
reference composition tests that exercise fluid layers + Executor as
whole models, on the hermetic datasets:

- ``test_word2vec.py`` (shared-name embeddings, concat, N-gram LM)
- ``test_understand_sentiment_lstm.py`` (embedding -> reshape ->
  transpose -> StaticRNN lstm -> fc, the layers.lstm path)
- ``test_recommender_system.py`` (9 inputs, shared feature towers,
  sequence_pool + sequence_conv_pool over LoD inputs, cos_sim)
- ``test_image_classification_train.py`` (resnet_cifar10 +
  vgg16_bn_drop via conv2d/batch_norm/img_conv_group)

Success criteria mirror the references: decreasing loss (word2vec's
"cost < 10", recommender's "cost < 6") or batches completing with
finite metrics (image classification's two-minibatch criterion).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import fluid
from paddle_tpu.fluid import framework, layers, nets


def _reset():
    framework.reset_default_programs()


def _startup(exe):
    exe.run(fluid.default_startup_program(), feed={}, fetch_list=[])


def test_word2vec_ngram_lm_trains():
    """≅ test_word2vec.py:1-165 on the hermetic imikolov."""
    import paddle_tpu as paddle

    _reset()
    embed_size, hidden_size, N, batch_size = 32, 256, 5, 32
    word_dict = paddle.dataset.imikolov.build_dict()
    dict_size = len(word_dict)

    words = [layers.data(name=n, shape=[1], dtype="int64")
             for n in ("firstw", "secondw", "thirdw", "forthw", "nextw")]
    embeds = [layers.embedding(
        input=w, size=[dict_size, embed_size], dtype="float32",
        is_sparse=True, param_attr={"name": "shared_w"})
        for w in words[:4]]
    concat_embed = layers.concat(input=embeds, axis=1)
    hidden1 = layers.fc(input=concat_embed, size=hidden_size, act="sigmoid")
    predict_word = layers.fc(input=hidden1, size=dict_size, act="softmax")
    cost = layers.cross_entropy(input=predict_word, label=words[4])
    avg_cost = layers.mean(cost)
    fluid.SGDOptimizer(learning_rate=0.1).minimize(avg_cost)

    # shared_w really is shared: the four embedding calls return the SAME
    # parameter object, every lookup reads it, and the program holds
    # exactly the expected parameter set (shared_w + 2 fc pairs)
    block = fluid.default_main_program().global_block()
    shared = [v for v in block.all_parameters() if v.name == "shared_w"]
    assert len(shared) == 1
    assert len(block.all_parameters()) == 5, sorted(
        p.name for p in block.all_parameters())
    lookup_ins = [op for op in block.ops if op.type == "lookup_table"]
    assert len(lookup_ins) == 4
    assert all(op.inputs["W"] == ["shared_w"] for op in lookup_ins)

    reader = paddle.reader.batch(paddle.dataset.imikolov.train(word_dict, N),
                                 batch_size)
    exe = fluid.Executor()
    _startup(exe)
    costs = []
    for epoch in range(3):
        for data in reader():
            cols = [np.asarray([row[i] for row in data],
                               np.int64)[:, None] for i in range(5)]
            feed = dict(zip(("firstw", "secondw", "thirdw", "forthw",
                             "nextw"), cols))
            (out,) = exe.run(feed=feed, fetch_list=[avg_cost])
            costs.append(float(out))
    assert np.isfinite(costs).all()
    assert costs[-1] < costs[0], (costs[0], costs[-1])
    assert costs[-1] < 10.0  # the reference's success criterion


def test_understand_sentiment_lstm_trains():
    """≅ test_understand_sentiment_lstm.py:12-41 (layers.lstm =
    StaticRNN + lstm_unit) on the hermetic imdb, seq chopped like
    chop_data."""
    import paddle_tpu as paddle

    _reset()
    word_dict = paddle.dataset.imdb.word_dict()
    dict_dim, class_dim, emb_dim = len(word_dict), 2, 32
    seq_len, batch_size = 32, 50

    data = layers.data(name="words", shape=[seq_len * batch_size, 1],
                       append_batch_size=False, dtype="int64")
    label = layers.data(name="label", shape=[batch_size, 1],
                        append_batch_size=False, dtype="int64")
    emb = layers.embedding(input=data, size=[dict_dim, emb_dim])
    emb = layers.reshape(x=emb, shape=[batch_size, seq_len, emb_dim])
    emb = layers.transpose(x=emb, axis=[1, 0, 2])
    c_pre_init = layers.fill_constant(dtype="float32",
                                      shape=[batch_size, emb_dim], value=0.0)
    layer_1_out = layers.lstm(emb, c_pre_init=c_pre_init, hidden_dim=emb_dim)
    layer_1_out = layers.transpose(x=layer_1_out, axis=[1, 0, 2])
    prediction = layers.fc(input=layer_1_out, size=class_dim, act="softmax")
    cost = layers.cross_entropy(input=prediction, label=label)
    avg_cost = layers.mean(cost)
    fluid.AdamOptimizer(learning_rate=0.002).minimize(avg_cost)
    acc = layers.accuracy(input=prediction, label=label)

    # chop_data: keep sequences >= seq_len, truncate, take batch_size
    rows = [(x[0][:seq_len], x[1])
            for x in paddle.dataset.imdb.train(word_dict)()
            if len(x[0]) >= seq_len][:batch_size]
    assert len(rows) == batch_size, "hermetic imdb too short for chop_data"
    words_np = np.concatenate([np.asarray(r[0], np.int64)
                               for r in rows]).reshape(-1, 1)
    label_np = np.asarray([r[1] for r in rows], np.int64).reshape(-1, 1)

    exe = fluid.Executor()
    _startup(exe)
    accs = []
    for it in range(40):
        c, a = exe.run(feed={"words": words_np, "label": label_np},
                       fetch_list=[avg_cost, acc])
        accs.append(float(a))
        if accs[-1] > 0.9:  # the reference's stopping criterion
            break
    assert accs[-1] > 0.9, accs[-5:]


def test_recommender_system_trains():
    """≅ test_recommender_system.py:1-315 on the hermetic movielens:
    7 id towers, LoD category/title inputs through sequence_pool and
    nets.sequence_conv_pool, cos_sim head, square_error_cost."""
    import paddle_tpu as paddle
    from paddle_tpu.core.lod import from_ragged

    _reset()
    ml = paddle.dataset.movielens
    is_sparse = True

    def usr_combined():
        uid = layers.data(name="user_id", shape=[1], dtype="int64")
        usr_emb = layers.embedding(
            input=uid, dtype="float32", size=[ml.max_user_id() + 1, 32],
            param_attr={"name": "user_table"}, is_sparse=is_sparse)
        usr_fc = layers.fc(input=usr_emb, size=32)
        gid = layers.data(name="gender_id", shape=[1], dtype="int64")
        g_emb = layers.embedding(input=gid, size=[2, 16],
                                 param_attr={"name": "gender_table"},
                                 is_sparse=is_sparse)
        g_fc = layers.fc(input=g_emb, size=16)
        aid = layers.data(name="age_id", shape=[1], dtype="int64")
        a_emb = layers.embedding(input=aid, size=[len(ml.age_table), 16],
                                 param_attr={"name": "age_table"},
                                 is_sparse=is_sparse)
        a_fc = layers.fc(input=a_emb, size=16)
        jid = layers.data(name="job_id", shape=[1], dtype="int64")
        j_emb = layers.embedding(input=jid, size=[ml.max_job_id() + 1, 16],
                                 param_attr={"name": "job_table"},
                                 is_sparse=is_sparse)
        j_fc = layers.fc(input=j_emb, size=16)
        cat = layers.concat(input=[usr_fc, g_fc, a_fc, j_fc], axis=1)
        return layers.fc(input=cat, size=200, act="tanh")

    def mov_combined():
        mid = layers.data(name="movie_id", shape=[1], dtype="int64")
        m_emb = layers.embedding(
            input=mid, dtype="float32", size=[ml.max_movie_id() + 1, 32],
            param_attr={"name": "movie_table"}, is_sparse=is_sparse)
        m_fc = layers.fc(input=m_emb, size=32)
        cid = layers.data(name="category_id", shape=[1], dtype="int64",
                          lod_level=1)
        c_emb = layers.embedding(input=cid,
                                 size=[len(ml.movie_categories()), 32],
                                 is_sparse=is_sparse)
        c_hidden = layers.sequence_pool(input=c_emb, pool_type="sum")
        tid = layers.data(name="movie_title", shape=[1], dtype="int64",
                          lod_level=1)
        t_emb = layers.embedding(input=tid,
                                 size=[len(ml.get_movie_title_dict()), 32],
                                 is_sparse=is_sparse)
        t_conv = nets.sequence_conv_pool(input=t_emb, num_filters=32,
                                         filter_size=3, act="tanh",
                                         pool_type="sum")
        cat = layers.concat(input=[m_fc, c_hidden, t_conv], axis=1)
        return layers.fc(input=cat, size=200, act="tanh")

    inference = layers.cos_sim(X=usr_combined(), Y=mov_combined())
    score = layers.data(name="score", shape=[1], dtype="float32")
    cost = layers.square_error_cost(input=inference, label=score)
    avg_cost = layers.mean(cost)
    fluid.SGDOptimizer(learning_rate=0.2).minimize(avg_cost)

    reader = paddle.reader.batch(ml.train(), batch_size=64)
    exe = fluid.Executor()
    _startup(exe)

    def func_feed(data):
        feed = {}
        for key, idx in (("user_id", 0), ("gender_id", 1), ("age_id", 2),
                         ("job_id", 3), ("movie_id", 4), ("score", 7)):
            dt = np.float32 if key == "score" else np.int64
            feed[key] = np.asarray([row[idx] for row in data],
                                   dt).reshape(len(data), 1)
        for key, idx in (("category_id", 5), ("movie_title", 6)):
            feed[key] = from_ragged(
                [np.asarray(row[idx], np.int64)[:, None] for row in data])
        return feed

    costs = []
    for epoch in range(2):
        for data in reader():
            (out,) = exe.run(feed=func_feed(data), fetch_list=[avg_cost])
            costs.append(float(out))
    assert np.isfinite(costs).all()
    assert costs[-1] < costs[0], (costs[0], costs[-1])
    assert costs[-1] < 6.0  # the reference's success criterion


def _resnet_cifar10(input, depth=8):
    """≅ resnet_cifar10 (test_image_classification_train.py:12-127)."""

    def conv_bn_layer(input, ch_out, filter_size, stride, padding,
                      act="relu"):
        tmp = layers.conv2d(input=input, filter_size=filter_size,
                            num_filters=ch_out, stride=stride,
                            padding=padding, act=None, bias_attr=False)
        return layers.batch_norm(input=tmp, act=act)

    def shortcut(input, ch_in, ch_out, stride):
        if ch_in != ch_out:
            return conv_bn_layer(input, ch_out, 1, stride, 0, None)
        return input

    def basicblock(input, ch_in, ch_out, stride):
        tmp = conv_bn_layer(input, ch_out, 3, stride, 1)
        tmp = conv_bn_layer(tmp, ch_out, 3, 1, 1, act=None)
        short = shortcut(input, ch_in, ch_out, stride)
        return layers.elementwise_add(x=tmp, y=short, act="relu")

    def layer_warp(block_func, input, ch_in, ch_out, count, stride):
        tmp = block_func(input, ch_in, ch_out, stride)
        for _ in range(1, count):
            tmp = block_func(tmp, ch_out, ch_out, 1)
        return tmp

    assert (depth - 2) % 6 == 0
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, 16, 3, 1, 1)
    res1 = layer_warp(basicblock, conv1, 16, 16, n, 1)
    res2 = layer_warp(basicblock, res1, 16, 32, n, 2)
    res3 = layer_warp(basicblock, res2, 32, 64, n, 2)
    return layers.pool2d(input=res3, pool_size=8, pool_type="avg",
                         pool_stride=1)


def _vgg16_bn_drop(input):
    """≅ vgg16_bn_drop (test_image_classification_train.py:130-192),
    narrowed channel widths for test runtime (structure identical)."""
    from paddle_tpu.fluid.initializer import XavierInitializer

    def conv_block(input, num_filter, groups, dropouts):
        return nets.img_conv_group(
            input=input, pool_size=2, pool_stride=2,
            conv_num_filter=[num_filter] * groups, conv_filter_size=3,
            conv_act="relu", conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts, pool_type="max")

    conv1 = conv_block(input, 16, 2, [0.3, 0])
    conv2 = conv_block(conv1, 32, 2, [0.4, 0])
    conv3 = conv_block(conv2, 64, 3, [0.4, 0.4, 0])
    drop = layers.dropout(x=conv3, dropout_prob=0.5)
    fc1 = layers.fc(input=drop, size=64, act=None,
                    param_attr={"initializer": XavierInitializer()})
    reshape1 = layers.reshape(x=fc1, shape=[-1, 64, 1, 1])
    bn = layers.batch_norm(input=reshape1, act="relu")
    drop2 = layers.dropout(x=bn, dropout_prob=0.5)
    return layers.fc(input=drop2, size=64, act=None,
                     param_attr={"initializer": XavierInitializer()})


def _train_image_classifier(net_fn, batches=2, batch_size=16):
    rng = np.random.default_rng(0)
    classdim, data_shape = 10, [3, 32, 32]
    images = layers.data(name="pixel", shape=data_shape, dtype="float32")
    label = layers.data(name="label", shape=[1], dtype="int64")
    net = net_fn(images)
    predict = layers.fc(input=net, size=classdim, act="softmax")
    cost = layers.cross_entropy(input=predict, label=label)
    avg_cost = layers.mean(cost)
    accuracy = layers.accuracy(input=predict, label=label)
    fluid.AdamOptimizer(learning_rate=0.001).minimize(avg_cost)

    exe = fluid.Executor()
    _startup(exe)
    outs = []
    for _ in range(batches):
        img = rng.normal(size=(batch_size, 3, 32, 32)).astype(np.float32)
        lbl = rng.integers(0, classdim,
                           size=(batch_size, 1)).astype(np.int64)
        loss, acc = exe.run(feed={"pixel": img, "label": lbl},
                            fetch_list=[avg_cost, accuracy])
        outs.append((float(loss), float(acc)))
    return outs


def test_image_classification_resnet_two_batches():
    """The reference's success criterion: two minibatches train with
    finite loss/acc (test_image_classification_train.py:253-258)."""
    _reset()
    outs = _train_image_classifier(lambda im: _resnet_cifar10(im, depth=8))
    assert all(np.isfinite(l) for l, _ in outs), outs


def test_image_classification_vgg_two_batches():
    _reset()
    outs = _train_image_classifier(_vgg16_bn_drop, batches=2, batch_size=8)
    assert all(np.isfinite(l) for l, _ in outs), outs
