"""The serving engine end to end: ragged paged attention vs the dense
reference, bit-exact incremental decode vs repeated full-context forward,
scheduler determinism + admission control, per-request telemetry with
TTFT/TPOT percentiles, strict inference, servable export, and the
``python -m paddle_tpu.serving`` CLI loop (subprocess, ``serving``
marker)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import transformer as T
from paddle_tpu.ops.pallas import paged_attention as PA
from paddle_tpu.serving import ServingConfig, ServingEngine
from paddle_tpu.telemetry import MemorySink, MetricsRegistry


def small_cfg(**kw):
    base = dict(vocab_size=64, num_layers=2, num_heads=2, embed_dim=32,
                mlp_dim=64, max_seq_len=64, remat=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def make_paged(rng, lens, H=2, D=16, ps=8, maxp=4, pool=16):
    """Random contiguous K/V + their paged twin for ragged ``lens``."""
    B = len(lens)
    pt = np.zeros((B, maxp), np.int32)
    nxt = 1
    for b in range(B):
        for i in range(-(-int(lens[b]) // ps)):
            pt[b, i] = nxt
            nxt += 1
    assert nxt <= pool
    kp = np.zeros((H, pool, ps, D), np.float32)
    vp = np.zeros((H, pool, ps, D), np.float32)
    full_k = rng.normal(size=(B, maxp * ps, H, D)).astype(np.float32)
    full_v = rng.normal(size=(B, maxp * ps, H, D)).astype(np.float32)
    for b in range(B):
        for t in range(int(lens[b])):
            kp[:, pt[b, t // ps], t % ps] = full_k[b, t]
            vp[:, pt[b, t // ps], t % ps] = full_v[b, t]
    return kp, vp, pt, full_k, full_v


class TestRaggedPagedAttention:
    def test_reference_matches_dense_on_ragged_batch(self, rng_np):
        from paddle_tpu.ops.attention import dot_product_attention

        lens = np.array([1, 7, 20, 0], np.int32)
        kp, vp, pt, full_k, full_v = make_paged(rng_np, lens)
        q = rng_np.normal(size=(4, 2, 16)).astype(np.float32)
        out = PA.ragged_paged_attention_reference(q, kp, vp, pt, lens)
        out = np.asarray(out)
        for b, n in enumerate(lens):
            if n == 0:
                assert np.allclose(out[b], 0.0)  # idle row: zeros, no NaNs
                continue
            dense = dot_product_attention(
                q[b][None, None], full_k[b:b + 1, :n], full_v[b:b + 1, :n])
            np.testing.assert_allclose(out[b], np.asarray(dense)[0, 0],
                                       rtol=2e-5, atol=2e-5)

    def test_kernel_matches_reference_on_ragged_batch(self, rng_np):
        lens = np.array([3, 8, 17, 25], np.int32)
        kp, vp, pt, _, _ = make_paged(rng_np, lens)
        q = rng_np.normal(size=(4, 2, 16)).astype(np.float32)
        ref = PA.ragged_paged_attention(q, kp, vp, pt, lens,
                                        impl="reference")
        ker = PA.ragged_paged_attention(q, kp, vp, pt, lens,
                                        impl="kernel", interpret=True)
        np.testing.assert_allclose(np.asarray(ker), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_write_then_read_round_trip(self, rng_np):
        kc, vc = PA.init_kv_pages(1, 2, 8, 4, 16)
        pt = jnp.asarray(np.array([[1, 2], [3, 0]], np.int32))
        k = rng_np.normal(size=(2, 2, 16)).astype(np.float32)
        v = rng_np.normal(size=(2, 2, 16)).astype(np.float32)
        # row 0 writes position 5 (page 2, off 1); row 1 position 2
        kc1, vc1 = PA.write_decode_kv(kc[0], vc[0], jnp.asarray(k),
                                      jnp.asarray(v), pt,
                                      jnp.asarray([5, 2]))
        np.testing.assert_allclose(np.asarray(kc1)[:, 2, 1], k[0])
        np.testing.assert_allclose(np.asarray(vc1)[:, 3, 2], v[1])


class TestBitExactDecode:
    def test_paged_incremental_equals_full_context_argmax(self, rng_np):
        """The acceptance bit-exactness property: engine tokens (paged
        cache + prefill/decode split + continuous batching) equal
        repeated full-context ``forward`` argmax per prompt."""
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        prompts = [list(rng_np.integers(1, 64, size=n)) for n in (3, 7, 12)]
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=32, max_prompt_len=16,
            max_new_tokens=8, prefill_batch=2, seed=0))
        results = eng.generate(prompts, max_new_tokens=5)
        for prompt, res in zip(prompts, results):
            assert res.finish_reason == "length"
            # one full-context pass over prompt+generated: position i's
            # argmax must equal token i+1 at EVERY step — equivalent to
            # re-running forward per step (greedy diverges at the first
            # mismatch, which the positional check would catch), but one
            # compile signature per prompt instead of one per length
            full = prompt + res.tokens
            logits = T.forward(cfg, params, jnp.asarray([full]))
            want = [int(t) for t in
                    jnp.argmax(logits[0, len(prompt) - 1:-1], axis=-1)]
            assert res.tokens == want


class TestSchedulerAndEngine:
    def test_deterministic_given_seed_and_arrival_order(self, rng_np):
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(2))
        prompts = [list(rng_np.integers(1, 64, size=5)) for _ in range(4)]

        def run():
            eng = ServingEngine(cfg, params, ServingConfig(
                max_slots=2, page_size=4, num_pages=32, max_prompt_len=8,
                max_new_tokens=6, prefill_batch=2, seed=123))
            return [r.tokens for r in
                    eng.generate(prompts, max_new_tokens=6,
                                 temperature=0.8)]

        first, second = run(), run()
        assert first == second  # same seed + arrival order -> same trace
        # temperature actually samples (vs collapsing to argmax)
        from paddle_tpu.serving.sampling import request_keys, sample_tokens

        logits = jnp.asarray(rng_np.normal(size=(8, 64)).astype(np.float32))
        keys = request_keys(jax.random.key(123),
                            jnp.arange(8, dtype=jnp.int32),
                            jnp.zeros(8, jnp.int32))
        hot = sample_tokens(logits, keys, jnp.full((8,), 5.0))
        cold = sample_tokens(logits, keys, jnp.zeros((8,)))
        assert (np.asarray(hot) != np.asarray(cold)).any()
        np.testing.assert_array_equal(np.asarray(cold),
                                      np.asarray(jnp.argmax(logits, -1)))

    def test_eos_stops_and_frees_pages(self, rng_np):
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        prompt = list(rng_np.integers(1, 64, size=4))
        ref = ServingEngine(cfg, params, ServingConfig(
            max_slots=1, page_size=4, num_pages=16, max_prompt_len=8,
            max_new_tokens=8, prefill_batch=1))
        tokens = ref.generate([prompt], max_new_tokens=8)[0].tokens
        eos = tokens[2]  # force an eos at the 3rd generated token
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=1, page_size=4, num_pages=16, max_prompt_len=8,
            max_new_tokens=8, prefill_batch=1, eos_id=eos))
        res = eng.generate([prompt], max_new_tokens=8)[0]
        assert res.finish_reason == "eos"
        # generation stops at the FIRST occurrence of eos (inclusive)
        assert res.tokens == tokens[:tokens.index(eos) + 1]
        assert eng.cache.allocator.free_pages == 15  # all pages returned

    def test_admission_blocks_on_pages_then_drains(self, rng_np):
        """More work than the pool can hold at once: requests queue,
        admission rejections are counted, everything still completes."""
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        prompts = [list(rng_np.integers(1, 64, size=6)) for _ in range(6)]
        # pool: 7 usable pages; each request reserves (6+8)/4 -> 4 pages
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=4, page_size=4, num_pages=8, max_prompt_len=8,
            max_new_tokens=8, prefill_batch=4, seed=0))
        results = eng.generate(prompts, max_new_tokens=4)
        assert len(results) == 6
        assert all(len(r.tokens) == 4 for r in results)
        assert eng.scheduler.rejected_admissions > 0
        assert eng.cache.allocator.free_pages == 7

    def test_concurrent_token_budget(self, rng_np):
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        prompts = [list(rng_np.integers(1, 64, size=4)) for _ in range(3)]
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=4, page_size=4, num_pages=64, max_prompt_len=8,
            max_new_tokens=8, prefill_batch=4,
            max_concurrent_tokens=20))  # one (4+8)-token reservation + slack
        results = eng.generate(prompts, max_new_tokens=3)
        assert len(results) == 3
        assert eng.scheduler.rejected_admissions > 0

    def test_threaded_submit_results(self, rng_np):
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=32, max_prompt_len=8,
            max_new_tokens=4, prefill_batch=2))
        eng.start()
        try:
            ids = [eng.submit(list(rng_np.integers(1, 64, size=4)),
                              max_new_tokens=3) for _ in range(3)]
            got = eng.results(n=3, timeout=60.0)
        finally:
            eng.stop()
        assert sorted(r.id for r in got) == sorted(ids)
        assert all(len(r.tokens) == 3 for r in got)

    def test_loop_crash_fails_pending_results(self, rng_np):
        """A dead background loop must FAIL blocked results() callers
        with its exception (and count the crash), not park them forever
        behind an engine that will never complete anything."""
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        reg = MetricsRegistry("serve_crash")
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=32, max_prompt_len=8,
            max_new_tokens=4, prefill_batch=2), registry=reg)
        boom = RuntimeError("injected decode fault")

        def bad_step():
            raise boom

        # submit BEFORE arming the crash: with the dead-engine guard a
        # post-crash submit refuses (asserted below), so the pending
        # request must predate the loop death
        eng.submit([1, 2, 3], max_new_tokens=3)
        eng.step = bad_step
        eng.start()
        try:
            with pytest.raises(RuntimeError,
                               match="serving loop crashed") as ei:
                eng.results(n=1, timeout=30.0)
            assert ei.value.__cause__ is boom
            # the non-blocking drain reports the crash too, rather than
            # returning an innocent-looking empty list
            with pytest.raises(RuntimeError, match="serving loop crashed"):
                eng.results()
            # ... and so does submit(): enqueueing into the dead engine
            # would park the request forever (PR 8 regression family)
            with pytest.raises(RuntimeError, match="submit refused"):
                eng.submit([1, 2, 3], max_new_tokens=3)
        finally:
            eng.stop()
        assert reg.counter("serve_loop_crashes", "").value() == 1.0

    def test_submit_after_stop_raises(self, rng_np):
        """stop() on a background engine marks it dead: a later submit
        must raise immediately, not enqueue into a loop that will never
        run again.  start() forgives (and sync-only engines that never
        ran a loop keep accepting)."""
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=32, max_prompt_len=8,
            max_new_tokens=4, prefill_batch=2))
        eng.start()
        eng.submit([1, 2, 3], max_new_tokens=2)
        eng.results(n=1, timeout=60.0)
        eng.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            eng.submit([1, 2, 3], max_new_tokens=2)
        eng.start()  # a restart re-opens the front door
        try:
            eng.submit([1, 2, 3], max_new_tokens=2)
            assert len(eng.results(n=1, timeout=60.0)) == 1
        finally:
            eng.stop()

    def test_impossible_reservation_rejected_at_enqueue(self):
        """A request whose prompt+max_new reservation exceeds the TOTAL
        page pool (or a table row, or the token budget) can never be
        admitted — FIFO admission would block forever behind it, so
        enqueue must reject it immediately with the reason."""
        from paddle_tpu.serving.kv_cache import PagedKVCache
        from paddle_tpu.serving.scheduler import Request, Scheduler

        def mk(num_pages, max_pages_per_seq, budget=0):
            cache = PagedKVCache(1, 2, 16, num_pages, 4, 2,
                                 max_pages_per_seq)
            s = ServingConfig(max_slots=2, page_size=4,
                              num_pages=num_pages, max_prompt_len=64,
                              max_new_tokens=64,
                              max_concurrent_tokens=budget)
            return Scheduler(s, cache)

        # 8+8 tokens -> 4 pages, pool has 3 usable
        sched = mk(num_pages=4, max_pages_per_seq=8)
        with pytest.raises(Exception, match="whole pool"):
            sched.enqueue(Request(id=0, prompt=[1] * 8, max_new_tokens=8))
        assert not sched.queue  # nothing wedged at the head
        # table row too short even though the pool is big enough
        sched = mk(num_pages=64, max_pages_per_seq=2)
        with pytest.raises(Exception, match="max_pages_per_seq"):
            sched.enqueue(Request(id=1, prompt=[1] * 8, max_new_tokens=8))
        # reservation above the concurrent-token budget
        sched = mk(num_pages=64, max_pages_per_seq=32, budget=10)
        with pytest.raises(Exception, match="max_concurrent_tokens"):
            sched.enqueue(Request(id=2, prompt=[1] * 8, max_new_tokens=8))
        # a request that fits all three still queues, and drains
        sched = mk(num_pages=8, max_pages_per_seq=4, budget=16)
        sched.enqueue(Request(id=3, prompt=[1] * 4, max_new_tokens=4))
        assert len(sched.queue) == 1 and len(sched.admit()) == 1


class TestServeTelemetry:
    def test_per_request_records_and_percentiles(self, rng_np):
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        reg = MetricsRegistry("serve_test")
        sink = MemorySink()
        reg.add_sink(sink)
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=32, max_prompt_len=8,
            max_new_tokens=4, prefill_batch=2), registry=reg)
        prompts = [list(rng_np.integers(1, 64, size=4)) for _ in range(3)]
        eng.generate(prompts, max_new_tokens=4)
        eng.emit_summary()
        serves = [r for r in sink.records if r.get("kind") == "serve"]
        assert len(serves) == 3
        for r in serves:
            assert r["schema"] == "paddle_tpu.metrics/15"
            for f in ("queue_wait_ms", "ttft_ms", "tpot_ms", "total_ms"):
                assert r[f] >= 0.0
            assert r["new_tokens"] == 4
        # TTFT/TPOT histograms expose asserted percentiles
        for name in ("serve_ttft_ms", "serve_tpot_ms"):
            h = reg.get(name)
            assert h.percentile(50) is not None
            assert h.percentile(50) <= h.percentile(99) <= h.summary()["max"]
        summaries = [r for r in sink.records
                     if r.get("kind") == "serve_summary"]
        assert summaries and "serve_ttft_ms" in summaries[-1]["summary"]
        assert reg.counter("serve_tokens").value() == 12.0

    def test_metrics_to_md_renders_serving_table(self, tmp_path, capsys):
        import json
        import sys

        sys.path.insert(0, "tools")
        try:
            import metrics_to_md
        finally:
            sys.path.pop(0)
        path = tmp_path / "m.jsonl"
        recs = [{"kind": "serve", "request": i, "prompt_tokens": 4,
                 "new_tokens": 8, "queue_wait_ms": 1.0 * i,
                 "ttft_ms": 10.0 + i, "tpot_ms": 2.0, "total_ms": 30.0}
                for i in range(5)]
        recs.append({"kind": "serve_summary", "rejected_admissions": 2,
                     "summary": {"serve_ttft_ms": {
                         "count": 5, "p50": 12.0, "p99": 14.9,
                         "max": 14.9}}})
        path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
        metrics_to_md.main([str(path)])
        out = capsys.readouterr().out
        assert "## Serving latency" in out
        assert "TTFT" in out and "TPOT" in out
        assert "admission attempts" in out


class TestPrefixCacheAndChunkedPrefill:
    """The perf tentpole's correctness contract: prefix caching and
    chunked prefill are pure optimizations — greedy tokens identical in
    every flag combination, warm or cold — and the refcounted page
    accounting stays conservative throughout."""

    def _setup(self, rng_np, n_prompts=4, shared_head=8):
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(3))
        head = list(rng_np.integers(1, 64, size=shared_head))
        prompts = [head + list(rng_np.integers(1, 64, size=4))
                   for _ in range(n_prompts)]
        prompts.append(list(rng_np.integers(1, 64, size=3)))  # no prefix
        return cfg, params, prompts

    def _run(self, cfg, params, prompts, registry=None, repeats=1, **kw):
        scfg = ServingConfig(max_slots=4, page_size=4, num_pages=64,
                             max_prompt_len=16, max_new_tokens=6,
                             prefill_batch=4, seed=0, **kw)
        eng = ServingEngine(cfg, params, scfg, registry=registry)
        out = []
        for _ in range(repeats):
            out.append([r.tokens for r in
                        eng.generate(prompts, temperature=0.0)])
        return eng, out

    def test_greedy_tokens_identical_across_all_flag_modes(self, rng_np):
        cfg, params, prompts = self._setup(rng_np)
        _, (base,) = self._run(cfg, params, prompts)
        # the prefix-only arm rides the warm-cache test's cold pass;
        # chunk 3 is the page-misaligned chunk boundary
        for kw in ({"prefill_chunk_tokens": 4},
                   {"prefill_chunk_tokens": 3},
                   {"prefix_cache": True, "prefill_chunk_tokens": 4}):
            _, (got,) = self._run(cfg, params, prompts, **kw)
            assert got == base, f"tokens diverged with {kw}"

    def test_warm_cache_identity_stats_and_page_conservation(self, rng_np):
        cfg, params, prompts = self._setup(rng_np)
        _, (base,) = self._run(cfg, params, prompts)
        reg = MetricsRegistry("serve_prefix")
        sink = MemorySink()
        reg.add_sink(sink)
        eng, (cold, warm) = self._run(cfg, params, prompts, registry=reg,
                                      repeats=2, prefix_cache=True)
        assert cold == base and warm == base
        p = eng.cache.prefix
        # warm round: 4 prompts share an 8-token (2-page) head; the
        # 3-token prompt has no full page to match
        assert p.hits >= 4 and p.hit_tokens >= 4 * 8
        assert reg.counter("serve_prefix_hit_tokens").value() >= 4 * 8
        assert reg.counter("serve_prefill_flops_saved").value() > 0
        # refcounted conservation: free + unique == pool - 1, with
        # cached pages resident and reclaimable after all releases
        rep = eng.cache.resident_report()
        assert rep["free_pages"] + rep["unique_pages"] == 63
        assert rep["cached_pages"] > 0
        assert rep["reclaimable_pages"] == rep["cached_pages"]
        # serve records carry the /14 fields
        serves = [r for r in sink.records if r.get("kind") == "serve"]
        assert sum(r["cached_tokens"] for r in serves) == p.hit_tokens
        eng.emit_summary()
        summ = [r for r in sink.records
                if r.get("kind") == "serve_summary"][-1]
        pre = summ["prefix"]
        assert pre["hit_tokens"] == p.hit_tokens
        assert 0.0 < pre["hit_rate"] <= 1.0
        assert pre["cached_pages"] == p.cached_pages
        assert pre["flops_saved"] > 0

    def test_chunked_prefill_interleaves_with_decode(self, rng_np):
        """A long prompt admitted behind a decoding sequence advances
        chunk-by-chunk while the resident sequence keeps decoding —
        TTFT for the long prompt no longer blocks the decode stream."""
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(3))
        short = list(rng_np.integers(1, 64, size=4))
        long_p = list(rng_np.integers(1, 64, size=16))
        reg = MetricsRegistry("serve_chunk")
        sink = MemorySink()
        reg.add_sink(sink)
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=64, max_prompt_len=16,
            max_new_tokens=6, prefill_batch=2, seed=0,
            prefill_chunk_tokens=4), registry=reg)
        eng.submit(short, max_new_tokens=6, temperature=0.0)
        eng.step()  # short's first chunk == its whole prompt
        eng.submit(long_p, max_new_tokens=6, temperature=0.0)
        interleaved = 0
        for _ in range(30):
            if not eng.step():
                break
            live = {a.request.id: a for a in eng.scheduler.live}
            if (0 in live and live[0].generated
                    and 1 in live and not live[1].generated):
                interleaved += 1
        assert interleaved > 0, "decode never ran beside a mid-prefill row"
        res = {r.id: r.tokens for r in eng.results()}
        # chunk accounting: the long prompt took ceil(16/4) = 4 passes
        serves = [r for r in sink.records if r.get("kind") == "serve"]
        chunks = {r["request"]: r["prefill_chunks"] for r in serves}
        assert chunks[1] == 4 and chunks[0] == 1
        assert reg.counter("serve_prefill_chunks").value() >= 5.0
        # identity vs the whole-prompt engine
        eng2 = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=64, max_prompt_len=16,
            max_new_tokens=6, prefill_batch=2, seed=0))
        eng2.submit(short, max_new_tokens=6, temperature=0.0)
        eng2.submit(long_p, max_new_tokens=6, temperature=0.0)
        eng2.run_until_idle()
        ref = {r.id: r.tokens for r in eng2.results()}
        assert res == ref

    def test_admission_under_pressure_evicts_cached_prefixes(self, rng_np):
        """A warm cache under page pressure: LRU cached prefixes are
        reclaimed instead of blocking admissions, OutOfPages never
        surfaces while reclaimable pages exist, and every request
        completes."""
        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(3))
        heads = [list(rng_np.integers(1, 64, size=8)) for _ in range(3)]
        prompts = [h + list(rng_np.integers(1, 64, size=2))
                   for h in heads for _ in range(2)]
        # pool of 11 usable pages; each request reserves
        # ceil((10 + 4)/4) = 4; three 2-page prefixes want caching, so
        # a full cache (6 pages) + two active rows (8, minus shared
        # heads) overflows the pool and forces LRU reclaim
        reg = MetricsRegistry("serve_evict")
        eng = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=12, max_prompt_len=16,
            max_new_tokens=4, prefill_batch=2, seed=0,
            prefix_cache=True), registry=reg)
        results = eng.generate(prompts, max_new_tokens=4,
                               temperature=0.0)
        assert len(results) == 6
        assert all(len(r.tokens) == 4 for r in results)
        p = eng.cache.prefix
        assert p.evictions > 0, "pressure never reclaimed a cached page"
        rep = eng.cache.resident_report()
        assert rep["free_pages"] + rep["unique_pages"] == 11
        # identical tokens with the cache off
        eng2 = ServingEngine(cfg, params, ServingConfig(
            max_slots=2, page_size=4, num_pages=12, max_prompt_len=16,
            max_new_tokens=4, prefill_batch=2, seed=0))
        ref = eng2.generate(prompts, max_new_tokens=4, temperature=0.0)
        assert [r.tokens for r in results] == [r.tokens for r in ref]

    def test_serving_memory_report_counts_unique_resident_bytes(
            self, rng_np):
        from paddle_tpu.analysis.memory import serving_memory_report

        cfg, params, prompts = self._setup(rng_np, n_prompts=3)
        scfg = ServingConfig(max_slots=4, page_size=4, num_pages=64,
                             max_prompt_len=16, max_new_tokens=6,
                             prefill_batch=4, seed=0, prefix_cache=True)
        eng = ServingEngine(cfg, params, scfg)
        eng.generate(prompts, temperature=0.0)  # populate the cache
        rep = serving_memory_report(cfg, scfg, cache=eng.cache)
        page_bytes = rep["page_bytes"]
        assert page_bytes * scfg.num_pages == rep["kv_pool_bytes"]
        assert rep["unique_resident_bytes"] == (
            rep["unique_pages"] * page_bytes)
        assert rep["cached_pages"] > 0
        # all slots idle: unique resident == cached pages exactly
        assert rep["unique_pages"] == rep["cached_pages"]
        assert rep["free_pages"] + rep["unique_pages"] == 63


class TestStrictInference:
    def test_strict_raises_on_missing_parameters(self):
        import paddle_tpu as paddle
        from paddle_tpu.layers import api as layer
        from paddle_tpu.layers import data_type
        from paddle_tpu.trainer.inference import Inference

        x = layer.data(name="x", type=data_type.dense_vector(4))
        out = layer.fc(input=x, size=2)
        empty = paddle.parameters.Parameters()  # no values loaded at all
        with pytest.raises(ValueError, match="incomplete"):
            Inference(out, empty, strict=True)
        # the default stays permissive (v2 back-compat)
        from paddle_tpu.layers import base as layer_base

        layer_base.reset_name_counters()
        x = layer.data(name="x", type=data_type.dense_vector(4))
        out = layer.fc(input=x, size=2)
        inf = Inference(out, paddle.parameters.Parameters())
        assert inf.infer([ (np.zeros(4, np.float32),) ]).shape == (1, 2)

    def test_strict_passes_on_complete_parameters(self):
        import paddle_tpu as paddle
        from paddle_tpu.layers import api as layer
        from paddle_tpu.layers import data_type
        from paddle_tpu.trainer.inference import Inference

        x = layer.data(name="x", type=data_type.dense_vector(4))
        out = layer.fc(input=x, size=2)
        params = paddle.parameters.create(paddle.topology.Topology(out))
        inf = Inference(out, params, strict=True)
        assert inf.infer([(np.zeros(4, np.float32),)]).shape == (1, 2)


class TestDenseBatcher:
    def test_coalesces_and_matches_direct(self):
        import threading

        from paddle_tpu.serving.dense import DenseBatcher

        calls = []

        def predict(rows):
            calls.append(len(rows))
            return np.asarray([[float(r), float(r) * 2] for r in rows])

        reg = MetricsRegistry("dense_test")
        b = DenseBatcher(predict, max_batch=8, max_wait_ms=20.0,
                         registry=reg)
        pending = []
        barrier = threading.Barrier(5)

        def client(i):
            barrier.wait()
            pending.append((i, b.submit(i)))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, p in pending:
            np.testing.assert_allclose(p.result(10.0), [i, i * 2])
        b.close()
        assert sum(calls) == 5
        assert len(calls) < 5  # at least one coalesced batch
        assert reg.counter("serve_dense_requests").value() == 5.0

    def test_predict_error_fans_out(self):
        from paddle_tpu.serving.dense import DenseBatcher

        def boom(rows):
            raise RuntimeError("model exploded")

        b = DenseBatcher(boom, max_batch=4, max_wait_ms=1.0,
                         registry=MetricsRegistry("dense_err"))
        p = b.submit(1)
        with pytest.raises(RuntimeError, match="exploded"):
            p.result(10.0)
        b.close()


class TestExport:
    def test_round_trip_and_tamper_detection(self, tmp_path, rng_np):
        from paddle_tpu.serving.export import export_servable, load_servable

        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(3))
        out = str(tmp_path / "servable")
        export_servable(out, cfg, params, meta={"note": "test"})
        cfg2, params2 = load_servable(out)
        assert cfg2 == cfg
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b)), params, params2)
        # served tokens from the loaded artifact match the live params
        prompt = list(rng_np.integers(1, 64, size=4))
        scfg = ServingConfig(max_slots=1, page_size=4, num_pages=16,
                             max_prompt_len=8, max_new_tokens=3,
                             prefill_batch=1)
        a = ServingEngine(cfg, params, scfg).generate([prompt])[0].tokens
        b = ServingEngine(cfg2, params2, scfg).generate([prompt])[0].tokens
        assert a == b
        # flip a byte -> load refuses
        payload = tmp_path / "servable" / "params.npz"
        raw = bytearray(payload.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        payload.write_bytes(bytes(raw))
        with pytest.raises(Exception, match="hash mismatch"):
            load_servable(out)

    def test_checkpoint_to_servable(self, tmp_path):
        from paddle_tpu.serving.export import (
            checkpoint_to_servable,
            load_servable,
        )
        from paddle_tpu.trainer.checkpoint import save_checkpoint

        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(4))
        flat = {}

        def flatten(d, prefix=""):
            for k, v in d.items():
                if isinstance(v, dict):
                    flatten(v, f"{prefix}{k}/")
                else:
                    flat[f"{prefix}{k}"] = np.asarray(v)

        flatten(params)
        ckpt = str(tmp_path / "ckpts")
        save_checkpoint(ckpt, 0, flat)
        out = checkpoint_to_servable(ckpt, str(tmp_path / "servable"), cfg)
        cfg2, params2 = load_servable(out)
        np.testing.assert_allclose(np.asarray(params2["embed"]),
                                   np.asarray(params["embed"]))
        np.testing.assert_allclose(
            np.asarray(params2["blocks"]["wq"]),
            np.asarray(params["blocks"]["wq"]))

    def test_partial_manifest_cases_refuse_to_load(self, tmp_path):
        """load_servable must refuse, with the reason, every partial-
        artifact shape: a manifest-listed file missing from disk, a
        payload param set that drifted from the manifest inventory, and
        a per-param dtype mismatch — never serve garbage-shaped
        weights."""
        import json

        from paddle_tpu.serving.export import export_servable, load_servable

        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(5))

        def fresh(name):
            out = str(tmp_path / name)
            export_servable(out, cfg, params)
            return out

        # (a) payload file listed in the manifest but deleted on disk
        out = fresh("missing_file")
        (tmp_path / "missing_file" / "params.npz").unlink()
        with pytest.raises(Exception, match="missing from disk"):
            load_servable(out)

        # (b) manifest inventory lists a param the payload lacks
        out = fresh("missing_param")
        mpath = tmp_path / "missing_param" / "servable.json"
        m = json.loads(mpath.read_text())
        m["params"]["blocks/extra_w"] = "float32"
        mpath.write_text(json.dumps(m))
        with pytest.raises(Exception, match="do not match the"):
            load_servable(out)

        # (c) dtype drift between manifest inventory and payload
        out = fresh("dtype_drift")
        mpath = tmp_path / "dtype_drift" / "servable.json"
        m = json.loads(mpath.read_text())
        key = next(k for k in m["params"])
        m["params"][key] = "float16"
        mpath.write_text(json.dumps(m))
        with pytest.raises(Exception, match="dtype mismatch"):
            load_servable(out)


@pytest.mark.slow
@pytest.mark.serving
class TestBenchServingLong:
    def test_long_trace_speedup_and_identical_tokens(self):
        """The bench acceptance property on the long trace: continuous
        batching needs >= 1.3x fewer fixed-cost decode steps than static
        for the same tokens (the step count is deterministic — the wall
        ratio rides it but flutters with machine load, so it only gets a
        loose sanity bound here)."""
        import json
        import os
        import subprocess
        import sys

        script = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "bench_serving.py")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, script, "--long"], env=env,
                             capture_output=True, text=True, timeout=600)
        assert out.returncode == 0, out.stderr[-800:]
        rows = {r["metric"]: r for r in
                (json.loads(l) for l in out.stdout.splitlines()
                 if l.startswith("{"))}
        speed = rows["serving_continuous_vs_static_speedup"]
        assert speed["decode_step_ratio"] >= 1.3
        assert speed["tokens_identical"] is True
        assert speed["value"] > 1.0  # loose: wall clock under any load
        cont = rows["serving_continuous_tokens_per_sec"]
        stat = rows["serving_static_tokens_per_sec"]
        assert cont["tokens"] == stat["tokens"]


@pytest.mark.serving
class TestCliLoop:
    def test_stdin_loop_subprocess(self):
        import os
        import subprocess
        import sys

        env = dict(os.environ, JAX_PLATFORMS="cpu")
        lines = "5 17 3\n9 9 9 9\n"
        out = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.serving", "--random",
             "--vocab", "64", "--embed", "32", "--max_new_tokens", "4",
             "--seed", "7"],
            input=lines, env=env, capture_output=True, text=True,
            timeout=300)
        assert out.returncode == 0, out.stderr[-800:]
        got = [l for l in out.stdout.splitlines() if l.strip()]
        assert len(got) == 2
        assert got[0].startswith("0:") and got[1].startswith("1:")
        toks = [int(t) for t in got[0].split(":")[1].split()]
        assert len(toks) == 4 and all(0 <= t < 64 for t in toks)
        # deterministic: same seed -> same bytes out
        out2 = subprocess.run(
            [sys.executable, "-m", "paddle_tpu.serving", "--random",
             "--vocab", "64", "--embed", "32", "--max_new_tokens", "4",
             "--seed", "7"],
            input=lines, env=env, capture_output=True, text=True,
            timeout=300)
        assert out2.stdout == out.stdout


class TestKvPoolPreflightGate:
    """GL-P-MEM's serving path: the static KV page-pool accounting that
    fails engine construction instead of OOMing at first admission."""

    def test_serving_memory_report_exact_bytes(self):
        from paddle_tpu.analysis import serving_memory_report

        cfg = small_cfg()  # 2 layers, 2 heads, head_dim 16, f32
        scfg = ServingConfig(page_size=8, num_pages=32)
        rep = serving_memory_report(cfg, scfg)
        # k AND v pools: 2 · L·H·pages·page_size·head_dim·itemsize
        assert rep["kv_pool_bytes"] == 2 * 2 * 2 * 32 * 8 * 16 * 4
        assert rep["dtype"] == "float32"
        assert rep["total_bytes"] == rep["kv_pool_bytes"]
        params = T.init_params(cfg, jax.random.key(0))
        with_p = serving_memory_report(cfg, scfg, params)
        assert with_p["params_bytes"] > 0
        assert with_p["total_bytes"] == (rep["kv_pool_bytes"]
                                         + with_p["params_bytes"])

    def test_budget_pass_names_the_pool_and_clean_under_budget(self):
        from paddle_tpu.analysis import (serving_budget_pass,
                                         serving_memory_report)

        cfg = small_cfg()
        rep = serving_memory_report(cfg, ServingConfig(page_size=8,
                                                       num_pages=32))
        found = serving_budget_pass(rep, hbm_gb=1e-6)
        assert len(found) == 1
        f = found[0]
        assert f.rule == "GL-P-MEM" and f.anchor == "kv-pool-budget"
        assert "pages" in f.message and "first admission" in f.message
        # generous budget or report-only (0): clean
        assert serving_budget_pass(rep, hbm_gb=64.0) == []
        assert serving_budget_pass(rep, hbm_gb=0.0) == []

    def test_engine_construction_fails_preflight_not_oom(self):
        from paddle_tpu.core import flags
        from paddle_tpu.core.enforce import EnforceError

        cfg = small_cfg()
        params = T.init_params(cfg, jax.random.key(1))
        old = flags.get("hbm_gb")
        try:
            flags.set("hbm_gb", 1e-6)
            with pytest.raises(EnforceError, match="kv-pool|KV pool"):
                ServingEngine(cfg, params, ServingConfig(
                    max_slots=2, page_size=4, num_pages=32,
                    max_prompt_len=16, max_new_tokens=8))
            # under budget (or unset): constructs fine
            flags.set("hbm_gb", 0.0)
            ServingEngine(cfg, params, ServingConfig(
                max_slots=2, page_size=4, num_pages=32,
                max_prompt_len=16, max_new_tokens=8))
        finally:
            flags.set("hbm_gb", old)
