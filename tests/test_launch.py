"""The trainer-fleet launcher (paddle_tpu.distributed.launch): rank
env/argv templating, per-rank log tee, first-failure propagation, pod
command emission — the SSH cluster launcher of the reference
(``paddle/scripts/cluster_train/paddle.py``) rebuilt for SPMD."""

from __future__ import annotations

import os
import sys

from paddle_tpu.distributed.launch import (
    emit_pod_commands,
    launch_local,
    main,
    rank_env,
)

_PY = sys.executable


def test_all_ranks_succeed_and_logs_teed(tmp_path):
    rc = launch_local(
        [_PY, "-c",
         "import os, sys; print('rank', os.environ['PADDLE_TPU_TRAINER_ID'],"
         " 'of', os.environ['PADDLE_TPU_NPROC'], 'arg {rank}')"],
        nproc=3, log_dir=str(tmp_path), echo_rank0=False, timeout=60)
    assert rc == 0
    for i in range(3):
        text = (tmp_path / f"rank{i}.log").read_text()
        # env AND {rank} substitution agree
        assert f"rank {i} of 3 arg {i}" in text


def test_first_failure_propagates_and_kills_stragglers(tmp_path):
    import time

    t0 = time.monotonic()
    rc = launch_local(
        [_PY, "-c",
         "import os, sys, time\n"
         "r = int(os.environ['PADDLE_TPU_TRAINER_ID'])\n"
         "sys.exit(7) if r == 1 else time.sleep(120)"],
        nproc=3, log_dir=str(tmp_path), echo_rank0=False, timeout=90)
    # rank 1's code comes back, and the 120 s sleepers were reaped
    assert rc == 7
    assert time.monotonic() - t0 < 60


def test_coordinator_env_is_shared(tmp_path):
    rc = launch_local(
        [_PY, "-c",
         "import os; print('coord', os.environ['PADDLE_TPU_COORDINATOR'],"
         " 'port {port}')"],
        nproc=2, log_dir=str(tmp_path), echo_rank0=False, timeout=60)
    assert rc == 0
    texts = [(tmp_path / f"rank{i}.log").read_text() for i in range(2)]
    coord0 = texts[0].split("coord ")[1].split()[0]
    coord1 = texts[1].split("coord ")[1].split()[0]
    assert coord0 == coord1  # every rank sees the same rendezvous point
    assert coord0.split(":")[1] in texts[0]  # {port} matches the env


def test_timeout_kills_fleet(tmp_path):
    rc = launch_local([_PY, "-c", "import time; time.sleep(60)"],
                      nproc=2, log_dir=str(tmp_path), echo_rank0=False,
                      timeout=1.0, poll_s=0.05)
    assert rc == 124  # the timeout(1) convention


def test_emit_pod_commands():
    lines = emit_pod_commands(["h0", "h1"], ["python", "train.py",
                                             "--trainer_id", "{rank}"])
    assert len(lines) == 2
    assert "PADDLE_TPU_TRAINER_ID=0" in lines[0]
    assert "PADDLE_TPU_COORDINATOR=h0:8476" in lines[1]  # host 0 leads
    assert "--trainer_id 1" in lines[1]


def test_cli_emit_mode(capsys):
    rc = main(["--emit_hosts", "a,b", "--", "python", "w.py"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "# on a:" in out and "# on b:" in out


def test_rank_env_isolated_base():
    env = rank_env(2, 4, 1234, base_env={"KEEP": "1"})
    assert env["PADDLE_TPU_TRAINER_ID"] == "2"
    assert env["PADDLE_TPU_NPROC"] == "4"
    assert env["KEEP"] == "1"
    assert env["PADDLE_TPU_RENDEZVOUS_EPOCH"] == "0"
    assert "PATH" not in env or os.environ.get("PATH") != env  # no leak


# -- operator signals / drain / elastic membership ---------------------------

_TRAP_CHILD = (
    "import os, signal, sys, time\n"
    "def bye(sig, frame):\n"
    "    print('rank', os.environ['PADDLE_TPU_TRAINER_ID'],\n"
    "          'draining', flush=True)\n"
    "    sys.exit(0)\n"
    "signal.signal(signal.SIGTERM, bye)\n"
    "print('ready', flush=True)\n"
    "time.sleep(120)\n"
)


def _spawn_launcher(tmp_path, extra_args, child_src, nproc=2):
    import subprocess

    return subprocess.Popen(
        [_PY, "-m", "paddle_tpu.distributed.launch",
         "--nproc", str(nproc), "--log_dir", str(tmp_path),
         "--grace", "10", *extra_args, "--", _PY, "-c", child_src],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _wait_logs(tmp_path, nproc, marker, timeout=30.0):
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        texts = []
        for i in range(nproc):
            p = tmp_path / f"rank{i}.log"
            texts.append(p.read_text() if p.exists() else "")
        if all(marker in t for t in texts):
            return texts
        time.sleep(0.1)
    raise AssertionError(f"marker {marker!r} never appeared in all "
                         f"rank logs: {texts}")


def test_sigterm_forwarded_to_ranks_and_reaped(tmp_path):
    """An operator SIGTERM to the launcher must reach every rank (their
    graceful-shutdown handlers run) and reap them — not orphan sleepers
    behind a dead launcher."""
    import signal as sig

    p = _spawn_launcher(tmp_path, [], _TRAP_CHILD)
    try:
        _wait_logs(tmp_path, 2, "ready")
        p.send_signal(sig.SIGTERM)
        rc = p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert rc == 128 + sig.SIGTERM  # 143: terminated, after forwarding
    for i in range(2):
        assert f"rank {i} draining" in (tmp_path / f"rank{i}.log"
                                        ).read_text()


def test_drain_signal_delivers_sigterm_and_waits(tmp_path):
    """--drain: SIGUSR1 to the launcher SIGTERMs the ranks (the trainer
    checkpoint-and-exit path) and WAITS for their graceful exit —
    rc 0, nobody killed."""
    import signal as sig

    p = _spawn_launcher(tmp_path, ["--drain"], _TRAP_CHILD)
    try:
        _wait_logs(tmp_path, 2, "ready")
        p.send_signal(sig.SIGUSR1)
        rc = p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert rc == 0
    for i in range(2):
        assert f"rank {i} draining" in (tmp_path / f"rank{i}.log"
                                        ).read_text()


def test_elastic_rank_death_updates_membership_and_notifies(tmp_path):
    """--elastic: a dying rank is a membership event, not fleet death —
    the membership file is rewritten (epoch bump, rank removed) and the
    survivors get SIGUSR1; the launcher returns the SURVIVORS' verdict."""
    import json

    child = (
        "import json, os, signal, sys, time\n"
        "r = int(os.environ['PADDLE_TPU_TRAINER_ID'])\n"
        "path = os.environ['PADDLE_TPU_MEMBERSHIP']\n"
        "assert os.environ['PADDLE_TPU_RENDEZVOUS_EPOCH'] == '0'\n"
        "if r == 1:\n"
        "    sys.exit(5)\n"
        "hit = []\n"
        "signal.signal(signal.SIGUSR1, lambda s, f: hit.append(s))\n"
        "print('ready', flush=True)\n"
        "deadline = time.monotonic() + 60\n"
        "while not hit and time.monotonic() < deadline:\n"
        "    time.sleep(0.05)\n"
        "m = json.load(open(path))\n"
        "print('notified epoch', m['epoch'], 'ranks', m['ranks'],\n"
        "      flush=True)\n"
        "sys.exit(0)\n"
    )
    p = _spawn_launcher(tmp_path, ["--elastic"], child)
    try:
        rc = p.wait(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()
    assert rc == 0  # survivor exited clean; the lost rank is the event
    m = json.loads((tmp_path / "membership.json").read_text())
    assert m["epoch"] == 1 and m["ranks"] == [0]
    log0 = (tmp_path / "rank0.log").read_text()
    assert "notified epoch 1 ranks [0]" in log0


def test_elastic_all_ranks_dead_is_a_failure(tmp_path):
    """--elastic must not launder a fully-failed fleet into rc 0: when
    every rank dies, the first failure's code comes back."""
    rc = launch_local(
        [_PY, "-c", "import sys; sys.exit(9)"], nproc=2,
        log_dir=str(tmp_path), echo_rank0=False, timeout=60,
        elastic=True)
    assert rc == 9


def test_elastic_sigusr1_ignored_until_armed(tmp_path):
    """Elastic children start with SIGUSR1 ignored (exec keeps ignored
    dispositions), so the membership notice fired by a sibling's death
    cannot kill a survivor that has not armed its handler yet."""
    child = (
        "import os, signal, sys, time\n"
        "r = int(os.environ['PADDLE_TPU_TRAINER_ID'])\n"
        "assert signal.getsignal(signal.SIGUSR1) is signal.SIG_IGN\n"
        "if r == 1:\n"
        "    sys.exit(5)\n"  # dies while rank 0 is still 'importing'
        "time.sleep(1.0)\n"  # absorb the SIGUSR1 notice unarmed
        "print('survived unarmed', flush=True)\n"
    )
    rc = launch_local([_PY, "-c", child], nproc=2,
                      log_dir=str(tmp_path), echo_rank0=False,
                      timeout=60, elastic=True)
    assert rc == 0
    assert "survived unarmed" in (tmp_path / "rank0.log").read_text()


def test_serving_env_has_replica_id_and_no_rendezvous():
    from paddle_tpu.distributed.launch import serving_env

    base = {"PATH": "/bin", "PADDLE_TPU_COORDINATOR": "stale:1"}
    env = serving_env(2, 3, base_env=base)
    assert env["PADDLE_TPU_REPLICA_ID"] == "2"
    assert env["PADDLE_TPU_NREPLICAS"] == "3"
    # replicas are independent processes: no trainer rendezvous vars,
    # and a stale inherited coordinator is scrubbed (a replica that
    # kept it would try to join a collective fleet that does not exist)
    assert "PADDLE_TPU_COORDINATOR" not in env
    assert "PADDLE_TPU_NPROC" not in env


def test_serving_replica_death_is_membership_event_not_fleet_death(
        tmp_path):
    """--serving: one replica dying removes it from the membership file
    (the fleet health monitor's failover signal) while the survivors
    keep serving and decide the verdict."""
    child = (
        "import os, sys, time\n"
        "r = int(os.environ['PADDLE_TPU_REPLICA_ID'])\n"
        "assert os.environ['PADDLE_TPU_NREPLICAS'] == '3'\n"
        "assert 'PADDLE_TPU_COORDINATOR' not in os.environ\n"
        "if r == 1:\n"
        "    sys.exit(3)\n"
        "time.sleep(0.5)\n"
        "print('replica', r, 'served', flush=True)\n"
    )
    rc = launch_local([_PY, "-c", child], nproc=3,
                      log_dir=str(tmp_path), echo_rank0=False,
                      timeout=60, serving=True)
    assert rc == 0  # survivors' verdict; the lost replica is the event
    from paddle_tpu.distributed.multihost import Membership

    m = Membership.read(str(tmp_path / "membership.json"))
    assert m.ranks == [0, 2] and m.epoch == 1
    assert m.missing(range(3)) == [1]
    for r in (0, 2):
        assert f"replica {r} served" in \
            (tmp_path / f"rank{r}.log").read_text()
