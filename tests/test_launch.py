"""The trainer-fleet launcher (paddle_tpu.distributed.launch): rank
env/argv templating, per-rank log tee, first-failure propagation, pod
command emission — the SSH cluster launcher of the reference
(``paddle/scripts/cluster_train/paddle.py``) rebuilt for SPMD."""

from __future__ import annotations

import os
import sys

from paddle_tpu.distributed.launch import (
    emit_pod_commands,
    launch_local,
    main,
    rank_env,
)

_PY = sys.executable


def test_all_ranks_succeed_and_logs_teed(tmp_path):
    rc = launch_local(
        [_PY, "-c",
         "import os, sys; print('rank', os.environ['PADDLE_TPU_TRAINER_ID'],"
         " 'of', os.environ['PADDLE_TPU_NPROC'], 'arg {rank}')"],
        nproc=3, log_dir=str(tmp_path), echo_rank0=False, timeout=60)
    assert rc == 0
    for i in range(3):
        text = (tmp_path / f"rank{i}.log").read_text()
        # env AND {rank} substitution agree
        assert f"rank {i} of 3 arg {i}" in text


def test_first_failure_propagates_and_kills_stragglers(tmp_path):
    import time

    t0 = time.monotonic()
    rc = launch_local(
        [_PY, "-c",
         "import os, sys, time\n"
         "r = int(os.environ['PADDLE_TPU_TRAINER_ID'])\n"
         "sys.exit(7) if r == 1 else time.sleep(120)"],
        nproc=3, log_dir=str(tmp_path), echo_rank0=False, timeout=90)
    # rank 1's code comes back, and the 120 s sleepers were reaped
    assert rc == 7
    assert time.monotonic() - t0 < 60


def test_coordinator_env_is_shared(tmp_path):
    rc = launch_local(
        [_PY, "-c",
         "import os; print('coord', os.environ['PADDLE_TPU_COORDINATOR'],"
         " 'port {port}')"],
        nproc=2, log_dir=str(tmp_path), echo_rank0=False, timeout=60)
    assert rc == 0
    texts = [(tmp_path / f"rank{i}.log").read_text() for i in range(2)]
    coord0 = texts[0].split("coord ")[1].split()[0]
    coord1 = texts[1].split("coord ")[1].split()[0]
    assert coord0 == coord1  # every rank sees the same rendezvous point
    assert coord0.split(":")[1] in texts[0]  # {port} matches the env


def test_timeout_kills_fleet(tmp_path):
    rc = launch_local([_PY, "-c", "import time; time.sleep(60)"],
                      nproc=2, log_dir=str(tmp_path), echo_rank0=False,
                      timeout=1.0, poll_s=0.05)
    assert rc == 124  # the timeout(1) convention


def test_emit_pod_commands():
    lines = emit_pod_commands(["h0", "h1"], ["python", "train.py",
                                             "--trainer_id", "{rank}"])
    assert len(lines) == 2
    assert "PADDLE_TPU_TRAINER_ID=0" in lines[0]
    assert "PADDLE_TPU_COORDINATOR=h0:8476" in lines[1]  # host 0 leads
    assert "--trainer_id 1" in lines[1]


def test_cli_emit_mode(capsys):
    rc = main(["--emit_hosts", "a,b", "--", "python", "w.py"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "# on a:" in out and "# on b:" in out


def test_rank_env_isolated_base():
    env = rank_env(2, 4, 1234, base_env={"KEEP": "1"})
    assert env["PADDLE_TPU_TRAINER_ID"] == "2"
    assert env["PADDLE_TPU_NPROC"] == "4"
    assert env["KEEP"] == "1"
    assert "PATH" not in env or os.environ.get("PATH") != env  # no leak
