"""Fault tolerance — every recovery path exercised under deterministic
chaos injection (resilience/chaos.py), mirroring how the reference proved
its Go master/pserver recovery (kill-and-restart in client_test.go /
service_internal_test.go) but without needing a cluster:

- RetryPolicy: bounded attempts, deterministic jitter, class filters;
- chaos-injected reader fault -> supervisor restart -> bit-identical;
- NaN-at-step-k: skip policy and rollback policy (reduced-LR rescue);
- kill (worker fault / SIGTERM) + resume mid-pass == unfaulted run,
  asserted bit-identically on the final parameters;
- corrupt-newest-checkpoint fallback;
- restart-budget exhaustion re-raises the original error;
- heartbeat-staleness watchdog dumps the flight ring.
"""

import json
import os
import signal

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags, rng
from paddle_tpu.layers import api as layer, base, data_type
from paddle_tpu.metrics import MetricsRegistry
from paddle_tpu.resilience import (
    ChaosError,
    ChaosSchedule,
    NumericGuard,
    RetryPolicy,
    Supervisor,
    corrupt_newest_checkpoint,
    flaky,
)
from paddle_tpu.trainer import checkpoint as ckpt


# -- shared tiny trainer ------------------------------------------------------

def _build():
    """Deterministic tiny regression trainer (rebuildable mid-test: the
    supervisor constructs a fresh one per attempt)."""
    base.reset_name_counters()
    rng.seed(7)
    x = layer.data(name="x", type=data_type.dense_vector(4))
    y = layer.data(name="y", type=data_type.dense_vector(1))
    fc = layer.fc(input=x, size=1, act=paddle.activation.LinearActivation(),
                  name="out")
    cost = layer.mse_cost(input=fc, label=y)
    params = paddle.parameters.create(paddle.topology.Topology(cost))
    return paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.05))


def _reader(n_samples=32):
    def r():
        rs = np.random.RandomState(0)
        w = np.array([1.0, -2.0, 0.5, 3.0])
        for _ in range(n_samples):
            x = rs.randn(4).astype(np.float32)
            yield x, np.array([x @ w], np.float32)
    return paddle.reader.batch(r, batch_size=8)  # 4 batches per pass


def _final_w(trainer):
    return np.asarray(trainer.parameters["_out.w0"]).copy()


@pytest.fixture(scope="module")
def baseline_w():
    """Final weights of an unfaulted 2-pass run — the bit-identical
    target every recovery test compares against."""
    tr = _build()
    tr.train(reader=_reader(), num_passes=2)
    return _final_w(tr)


# -- RetryPolicy --------------------------------------------------------------

def test_retry_policy_bounded_attempts_and_filters():
    slept = []
    p = RetryPolicy(max_attempts=3, base_delay_s=0.01, sleep=slept.append,
                    retry_on=(ConnectionError,))
    assert p.call(flaky(lambda: 7, fail_times=2, exc=ConnectionError)) == 7
    assert len(slept) == 2  # two retries, bounded

    # attempts exhausted -> the last error propagates unwrapped
    with pytest.raises(ConnectionError):
        p.call(flaky(lambda: 7, fail_times=5, exc=ConnectionError))

    # per-exception-class filter: unlisted classes never retry
    calls = {"n": 0}

    def wrong_class():
        calls["n"] += 1
        raise KeyError("not transient")

    with pytest.raises(KeyError):
        p.call(wrong_class)
    assert calls["n"] == 1


def test_retry_policy_jitter_is_deterministic():
    a = RetryPolicy(max_attempts=5, seed=3, scope="x", jitter=0.5)
    b = RetryPolicy(max_attempts=5, seed=3, scope="x", jitter=0.5)
    assert a.delays() == b.delays()
    assert a.delays() == a.delays()  # stable per call, not consumed
    c = RetryPolicy(max_attempts=5, seed=4, scope="x", jitter=0.5)
    assert a.delays() != c.delays()  # seed actually reaches the jitter
    # backoff grows and respects the ceiling
    d = RetryPolicy(max_attempts=6, base_delay_s=1.0, max_delay_s=4.0,
                    jitter=0.0).delays()
    assert d == [1.0, 2.0, 4.0, 4.0, 4.0]


# -- dataset download (satellite) ---------------------------------------------

def test_download_md5_verification(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "cache"))
    src = tmp_path / "blob.bin"
    src.write_bytes(b"paddle_tpu dataset payload")
    url = "file://" + str(src)
    good = common.md5file(str(src))

    got = common.download(url, "unit", md5sum=good)
    assert got == common.data_path("unit", "blob.bin")
    assert common.md5file(got) == good

    # cached-and-valid short-circuits (the source may even disappear)
    src.unlink()
    assert common.download(url, "unit", md5sum=good) == got

    # a torn cached file is discarded and re-fetched; with the source
    # gone the re-fetch fails through the (fast) retry policy
    with open(got, "ab") as f:
        f.write(b"garbage")
    fast = RetryPolicy(max_attempts=2, base_delay_s=0.0, sleep=lambda s: 0)
    with pytest.raises(OSError):
        common.download(url, "unit", md5sum=good, retry=fast)


def test_download_retries_transient_fetch_errors(tmp_path, monkeypatch):
    import urllib.request

    from paddle_tpu.dataset import common

    monkeypatch.setattr(common, "DATA_HOME", str(tmp_path / "cache"))
    src = tmp_path / "blob.bin"
    src.write_bytes(b"retry me")
    url = "file://" + str(src)
    real = urllib.request.urlopen
    monkeypatch.setattr(urllib.request, "urlopen",
                        flaky(real, fail_times=2, exc=ConnectionError))
    fast = RetryPolicy(max_attempts=3, base_delay_s=0.0, sleep=lambda s: 0,
                       retry_on=(OSError,))
    got = common.download(url, "unit", md5sum=common.md5file(str(src)),
                          retry=fast)
    assert common.md5file(got) == common.md5file(str(src))


# -- chaos harness ------------------------------------------------------------

def test_chaos_schedule_parses_and_fires_once():
    reg = MetricsRegistry()
    sched = ChaosSchedule("reader_error@1,nan@2", registry=reg)

    def reader():
        for i in range(4):
            yield [(np.ones(2, np.float32), 0)]

    wrapped = sched.wrap_reader(reader)
    out = []
    with pytest.raises(ChaosError):
        for b in wrapped():
            out.append(b)
    assert len(out) == 1  # batch 0 delivered, batch 1 exploded
    # second pull-through: the once-fault stays fired; nan@2 (global
    # index) poisons the next stream's position 2
    batches = list(wrapped())
    assert len(batches) == 4
    assert np.isnan(batches[0][0][0]).all()  # global batch 2 == index 0 here
    assert not any(np.isnan(b[0][0]).any() for b in batches[1:])
    assert reg.counter("faults_injected", "").value(kind="reader_error") == 1
    assert reg.counter("faults_injected", "").value(kind="nan") == 1

    with pytest.raises(ValueError):
        ChaosSchedule("meteor@3")


def test_skip_feed_batches_counts_like_the_trainer():
    from paddle_tpu.reader.prefetch import skip_feed_batches

    def reader():
        yield [1] * 8
        yield [2] * 3   # dropped entirely under remainder="drop", m=8
        yield [3] * 8
        yield [4] * 8

    # error-mode: every batch counts
    got = [b[0] for b in skip_feed_batches(reader, 2)()]
    assert got == [3, 4]
    # drop-mode: the undersized batch never reached the step loop, so it
    # must not count against the cursor
    got = [b[0] for b in skip_feed_batches(reader, 2, replicas=8,
                                           remainder="drop")()]
    assert got == [4]
    assert skip_feed_batches(reader, 0) is reader


# -- numeric guard ------------------------------------------------------------

def test_nan_skip_policy_drops_the_poisoned_update():
    from paddle_tpu.distributed import multihost as mh

    reg = MetricsRegistry()
    sched = ChaosSchedule("nan@2", registry=reg)
    seen = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            seen.append((e.pass_id, e.batch_id))

    tr = _build()
    tr.train(reader=sched.wrap_reader(_reader()), num_passes=1,
             nan_policy="skip", event_handler=handler,
             metrics_registry=reg)
    w = _final_w(tr)
    assert np.isfinite(w).all()
    assert reg.counter("batches_skipped", "").value(run="train") == 1
    # the skipped batch emitted no EndIteration — it never happened
    assert (0, 2) not in seen and (0, 3) in seen
    # flight recorder carries the heartbeat tag for the post-mortem
    assert any(h["tag"] == "nan_skip" for h in mh.flight_recorder().heartbeats)


def test_nan_rollback_restores_checkpoint_with_rescue_window(tmp_path):
    reg = MetricsRegistry()
    sched = ChaosSchedule("nan@5", registry=reg)  # pass 1, batch 1
    d = str(tmp_path / "ck")
    tr = _build()
    tr.train(reader=sched.wrap_reader(_reader()), num_passes=2,
             nan_policy="rollback", checkpoint_dir=d, metrics_registry=reg)
    assert np.isfinite(_final_w(tr)).all()
    assert reg.counter("rollbacks", "").value(run="train") == 1
    assert reg.counter("batches_skipped", "").value(run="train") == 0
    # rollback without any checkpoint degrades to skip (and says so)
    reg2 = MetricsRegistry()
    sched2 = ChaosSchedule("nan@1", registry=reg2)
    tr2 = _build()
    tr2.train(reader=sched2.wrap_reader(_reader()), num_passes=1,
              nan_policy="rollback", metrics_registry=reg2)
    assert reg2.counter("batches_skipped", "").value(run="train") == 1


def test_guard_gives_up_after_max_consecutive():
    prev = flags.snapshot_raw()
    flags.set("guard_max_consecutive", 3)
    try:
        # every batch is poisoned: skipping forever would hide a dead run
        sched = ChaosSchedule(
            ",".join(f"nan@{i}:always" for i in range(8)))
        tr = _build()
        with pytest.raises(FloatingPointError):
            tr.train(reader=sched.wrap_reader(_reader()), num_passes=1,
                     nan_policy="skip", metrics_registry=MetricsRegistry())
    finally:
        flags.restore_raw(prev)


def test_guard_rejects_unknown_policy():
    with pytest.raises(ValueError):
        NumericGuard(policy="pray")


# -- supervisor + kill-and-resume ---------------------------------------------

def test_supervisor_worker_fault_resumes_bit_identical(tmp_path, baseline_w):
    """A worker fault at an arbitrary mid-pass step, restarted by the
    supervisor from a mid-pass cursor checkpoint, must produce the exact
    final trajectory of an unfaulted run (same batches, same RNG keys)."""
    reg = MetricsRegistry()
    d = str(tmp_path / "ck")
    sched = ChaosSchedule("step_error@6", registry=reg)  # pass 1, batch 2

    def attempt(i):
        tr = _build()
        tr.train(reader=sched.wrap_reader(_reader()), num_passes=2,
                 checkpoint_dir=d, checkpoint_batch_period=2,
                 event_handler=sched.wrap_event_handler(None),
                 metrics_registry=reg)
        return tr

    sup = Supervisor(max_restarts=2, registry=reg)
    tr = sup.run(attempt)
    assert sup.restarts == 1
    assert reg.counter("restarts", "").value(run="train") == 1
    assert reg.counter("faults_recovered", "").value(run="train") == 1
    np.testing.assert_array_equal(_final_w(tr), baseline_w)


def test_supervisor_reader_fault_resumes_bit_identical(tmp_path, baseline_w):
    """Chaos-injected reader IOError mid-pass: the pass dies, the
    supervisor restarts, resume replays from the cursor checkpoint."""
    d = str(tmp_path / "ck")
    sched = ChaosSchedule("reader_error@2")

    def attempt():
        tr = _build()
        tr.train(reader=sched.wrap_reader(_reader()), num_passes=2,
                 checkpoint_dir=d, checkpoint_batch_period=1,
                 metrics_registry=MetricsRegistry())
        return tr

    sup = Supervisor(max_restarts=2, retry_on=(ChaosError,))
    tr = sup.run(attempt)
    assert sup.restarts == 1
    np.testing.assert_array_equal(_final_w(tr), baseline_w)


def test_sigterm_preemption_resumes_bit_identical(tmp_path, baseline_w):
    """Simulated pod eviction (chaos sigterm@k): the trainer writes a
    mid-pass cursor checkpoint and exits cleanly; a fresh trainer resumes
    the same pass at the next batch — final weights bit-identical."""
    d = str(tmp_path / "ck")
    sched = ChaosSchedule("sigterm@5")
    tr = _build()
    tr.train(reader=sched.wrap_reader(_reader()), num_passes=2,
             checkpoint_dir=d,
             event_handler=sched.wrap_event_handler(None),
             metrics_registry=MetricsRegistry())
    found = ckpt.latest_checkpoint(d)
    assert found[1]["meta"]["preempted"] is True
    cursor = found[1]["cursor"]
    assert cursor == {"pass_id": 1, "batch_id": 2}  # batches 0,1 applied
    assert found[1]["meta"]["reader_cursor"]["batches_consumed"] == 2

    tr2 = _build()
    tr2.train(reader=_reader(), num_passes=2, checkpoint_dir=d,
              metrics_registry=MetricsRegistry())
    np.testing.assert_array_equal(_final_w(tr2), baseline_w)


def test_corrupt_newest_checkpoint_falls_back(tmp_path, baseline_w):
    """The corrupt-checkpoint writer: resume skips the damaged newest
    snapshot, restores the previous valid one, and replays to the same
    final trajectory."""
    d = str(tmp_path / "ck")
    tr = _build()
    tr.train(reader=_reader(), num_passes=2, checkpoint_dir=d,
             checkpoint_batch_period=2, metrics_registry=MetricsRegistry())
    entries_before = ckpt.checkpoint_entries(d)
    corrupt_newest_checkpoint(d, seed=1)
    path, manifest = ckpt.latest_checkpoint(d)
    assert path != entries_before[-1]  # fell back past the corrupt one

    tr2 = _build()
    tr2.train(reader=_reader(), num_passes=2, checkpoint_dir=d,
              metrics_registry=MetricsRegistry())
    np.testing.assert_array_equal(_final_w(tr2), baseline_w)


def test_supervisor_budget_exhaustion_raises_original_error(tmp_path):
    d = str(tmp_path / "ck")
    sched = ChaosSchedule("step_error@0:always")
    reg = MetricsRegistry()

    def attempt():
        sched.reset_counters()  # the :always fault re-fires per attempt
        tr = _build()
        tr.train(reader=_reader(), num_passes=1, checkpoint_dir=d,
                 event_handler=sched.wrap_event_handler(None),
                 metrics_registry=reg)

    sup = Supervisor(max_restarts=2, registry=reg,
                     backoff=RetryPolicy(max_attempts=3, base_delay_s=0.0,
                                         sleep=lambda s: 0))
    with pytest.raises(ChaosError):
        sup.run(attempt)
    assert sup.restarts == 2  # budget spent, then the fault re-raised
    assert reg.counter("restarts", "").value(run="train") == 2
    assert reg.counter("faults_recovered", "").value(run="train") == 0


def test_supervisor_never_retries_fatal():
    calls = {"n": 0}

    def attempt():
        calls["n"] += 1
        raise KeyboardInterrupt()

    with pytest.raises(KeyboardInterrupt):
        Supervisor(max_restarts=3).run(attempt)
    assert calls["n"] == 1


# -- checkpoint cursor machinery ----------------------------------------------

def test_checkpoint_cursor_ordering_and_gc(tmp_path):
    """Mid-pass cursors order chronologically against end-of-pass
    snapshots (pass-1-batch-2 < pass-1 < pass-2-batch-1), not
    lexicographically."""
    d = str(tmp_path)
    w = {"w": np.zeros(1, np.float32)}
    ckpt.save_checkpoint(d, 0, w, keep_last=10)
    ckpt.save_checkpoint(d, 1, w, batch_id=2, keep_last=10)
    ckpt.save_checkpoint(d, 1, w, keep_last=10)
    ckpt.save_checkpoint(d, 2, w, batch_id=1, keep_last=10)
    names = [os.path.basename(p) for p in ckpt.checkpoint_entries(d)]
    assert names == ["pass-00000", "pass-00001-batch-000002", "pass-00001",
                     "pass-00002-batch-000001"]
    path, manifest = ckpt.latest_checkpoint(d)
    assert manifest["cursor"] == {"pass_id": 2, "batch_id": 1}

    # gc keeps the newest N by cursor order
    ckpt.save_checkpoint(d, 2, w, batch_id=3, keep_last=2)
    names = [os.path.basename(p) for p in ckpt.checkpoint_entries(d)]
    assert names == ["pass-00002-batch-000001", "pass-00002-batch-000003"]


def test_async_checkpointer_failure_counted_and_raised(tmp_path):
    from paddle_tpu.telemetry import get_default_registry

    reg = get_default_registry()
    before = reg.counter("checkpoint_write_failures", "").value()
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("x")
    w = ckpt.AsyncCheckpointer(
        retry=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                          sleep=lambda s: 0, retry_on=(OSError,)))
    w.save(str(blocker / "denied"), 0, {"w": np.zeros(1, np.float32)})
    with pytest.raises(OSError):
        w.wait()
    assert reg.counter("checkpoint_write_failures", "").value() == before + 1


# -- heartbeat watchdog -------------------------------------------------------

def test_heartbeat_watchdog_dumps_and_reports(tmp_path):
    import time

    from paddle_tpu.distributed.multihost import (
        FlightRecorder,
        HeartbeatWatchdog,
    )

    rec = FlightRecorder(capacity=8)
    rec.heartbeat("alive", step=1)
    fired = []
    wd = HeartbeatWatchdog(recorder=rec, stale_after_s=0.15, poll_s=0.03,
                           on_stale=lambda age, path: fired.append(
                               (age, path)),
                           dump_dir=str(tmp_path))
    with wd:
        deadline = time.time() + 3.0
        while not fired and time.time() < deadline:
            time.sleep(0.02)
    assert wd.fired and fired
    age, path = fired[0]
    assert age >= 0.15
    with open(path) as f:
        dump = json.load(f)
    assert "heartbeat stale" in dump["reason"]
    assert dump["heartbeats"][-1]["tag"] == "alive"

    # fresh heartbeats keep it quiet
    rec2 = FlightRecorder(capacity=8)
    quiet = HeartbeatWatchdog(recorder=rec2, stale_after_s=10.0,
                              poll_s=0.02, on_stale=lambda *a: None,
                              dump_dir=str(tmp_path))
    with quiet:
        rec2.heartbeat("alive")
        time.sleep(0.1)
    assert not quiet.fired


# -- master reconnect (satellite) ---------------------------------------------

def test_master_client_survives_master_restart(tmp_path):
    """Socket fault mid-conversation: the client redials with bounded
    backoff; a FAIL sent to the snapshot-recovered master re-queues the
    task (the reference Go master's re-queue-on-timeout semantics)."""
    from paddle_tpu.distributed import MasterClient, MasterServer

    snap = str(tmp_path / "master.snapshot")
    try:
        s = MasterServer(timeout_ms=60000, snapshot_path=snap)
    except Exception as e:  # native binary unavailable in this env
        pytest.skip(f"master binary unavailable: {e}")
    import time

    c = s.client()
    c.set_dataset([f"t{i}" for i in range(3)])
    tid, epoch, _ = c.get_task()
    time.sleep(0.4)  # snapshot flush throttle
    port = s.port
    s.kill()  # crash — the client's socket is now dead

    s2 = MasterServer(timeout_ms=60000, snapshot_path=snap, port=port)
    try:
        # the SAME client object: task_failed redials and the re-queue
        # lands on the recovered queue
        assert c.task_failed(tid, epoch) in (True, False)
        st = c.stat()
        assert st["todo"] + st["pending"] == 3  # nothing lost
        got = c.get_task()
        assert got not in (None,)  # tasks are dispatchable again
    finally:
        c.close()
        s2.shutdown()


# -- tooling ------------------------------------------------------------------

def test_metrics_to_md_renders_fault_and_recovery(tmp_path, capsys):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "metrics_to_md", os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "metrics_to_md.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    stream = tmp_path / "m.jsonl"
    records = [
        {"kind": "step", "run": "train", "step": 0, "loss": 1.0,
         "step_ms": 2.0, "examples_per_sec": 10.0, "mfu_pct": 0.0},
        {"kind": "fault", "fault": "nan_skip", "pass_id": 0, "batch_id": 2,
         "loss": float("nan")},
        {"kind": "recovery", "restart": 1, "error": "ChaosError: boom",
         "recovery_ms": 52.1},
    ]
    stream.write_text("\n".join(json.dumps(r) for r in records) + "\n")
    assert mod.main([str(stream)]) == 0
    out = capsys.readouterr().out
    assert "Faults & recovery" in out
    assert "run restarted 1 time(s)" in out  # restarts > 0 is flagged
    assert "nan_skip" in out and "ChaosError: boom" in out


# -- whole-process kill-and-resume (chaos marker: filtered from tier-1) -------

_PROC_SCRIPT = r"""
import os, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.core import rng
from paddle_tpu.layers import api as layer, base, data_type

mode, ckdir, out = sys.argv[1], sys.argv[2], sys.argv[3]
base.reset_name_counters(); rng.seed(7)
x = layer.data(name="x", type=data_type.dense_vector(4))
y = layer.data(name="y", type=data_type.dense_vector(1))
fc = layer.fc(input=x, size=1, act=paddle.activation.LinearActivation(), name="out")
cost = layer.mse_cost(input=fc, label=y)
params = paddle.parameters.create(paddle.topology.Topology(cost))
tr = paddle.trainer.SGD(cost=cost, parameters=params,
                        update_equation=paddle.optimizer.Momentum(
                            momentum=0.9, learning_rate=0.05))

def r():
    rs = np.random.RandomState(0)
    w = np.array([1.0, -2.0, 0.5, 3.0])
    for _ in range(32):
        xs = rs.randn(4).astype(np.float32)
        yield xs, np.array([xs @ w], np.float32)
reader = paddle.reader.batch(r, batch_size=8)

def killer(e):
    if mode == "kill" and isinstance(e, paddle.event.BeginIteration) \
            and (e.pass_id, e.batch_id) == (1, 3):
        os.kill(os.getpid(), 9)  # SIGKILL: no handlers, no cleanup

tr.train(reader=reader, num_passes=2, event_handler=killer,
         checkpoint_dir=(ckdir or None), checkpoint_batch_period=2)
np.save(out, np.asarray(tr.parameters["_out.w0"]))
"""


@pytest.mark.chaos
@pytest.mark.slow
def test_process_sigkill_and_resume_bit_identical(tmp_path):
    """The real thing: SIGKILL the training process mid-pass (no Python
    cleanup at all), run it again, and the resumed process finishes with
    weights bit-identical to a never-killed run."""
    import subprocess
    import sys

    script = tmp_path / "train_proc.py"
    script.write_text(_PROC_SCRIPT)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + env.get("PYTHONPATH", "").split(os.pathsep))

    def run(mode, ckdir, out):
        return subprocess.run(
            [sys.executable, str(script), mode, ckdir, out],
            env=env, capture_output=True, text=True, timeout=300)

    ref = str(tmp_path / "ref.npy")
    assert run("clean", "", ref).returncode == 0

    ckdir = str(tmp_path / "ck")
    out = str(tmp_path / "resumed.npy")
    first = run("kill", ckdir, out)
    assert first.returncode == -signal.SIGKILL
    second = run("clean", ckdir, out)
    assert second.returncode == 0, second.stderr[-2000:]
    np.testing.assert_array_equal(np.load(out), np.load(ref))
