"""The last gserver registry layers: mdlstmemory (MDLstmLayer.cpp:180),
subseq (SubSequenceLayer.cpp:29), switch_order (SwitchOrderLayer) — runtime
semantics + gradient flow; the registry audit lives in PARITY.md."""

from __future__ import annotations

import numpy as np


def test_mdlstm_wavefront_semantics(rng_np):
    """2-D LSTM: gradient flows, causal influence crosses the grid, and
    direction flips change which corner sees which."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type, more

    base.reset_name_counters()
    B, H, W, D = 2, 4, 5, 3
    img = layer.data(name="x", type=data_type.dense_vector(
        5 * D * H * W, height=H, width=W, channels=5 * D))
    md = more.mdlstmemory(input=img, size=D)
    topo = Topology(md)
    params = paddle.parameters.create(topo).as_dict()
    x = rng_np.normal(size=(B, 5 * D * H * W)).astype(np.float32)
    vals, _ = topo.forward(params, {}, {"x": x}, True, jax.random.key(0))
    out = vals[md.name]
    assert out.shape == (B, H, W, D)

    def loss(p):
        v, _ = topo.forward(p, {}, {"x": x}, True, jax.random.key(0))
        return jnp.sum(v[md.name])

    g = jax.grad(loss)(params)
    for k, gv in g.items():
        assert float(jnp.max(jnp.abs(gv))) > 0, k

    # candidate-gate channel offset for grid cell (i, j): gate layout is
    # [i, o, g, f1, f2] x D over a CHW block
    def g_gate_flat(i, j):
        return (2 * D) * H * W + i * W + j

    # top-left input perturbation reaches the bottom-right cell (fwd scan)
    x2 = x.copy()
    x2[0, g_gate_flat(0, 0)] += 10.0
    v2, _ = topo.forward(params, {}, {"x": x2}, True, jax.random.key(0))
    diff = np.abs(np.asarray(v2[md.name] - out))[0]
    assert diff[-1, -1].max() > 0
    # ...but never flows backward against the scan: perturb the LAST input
    # cell and check the first output cell is untouched
    x3 = x.copy()
    x3[0, g_gate_flat(H - 1, W - 1)] += 10.0
    v3, _ = topo.forward(params, {}, {"x": x3}, True, jax.random.key(0))
    diff3 = np.abs(np.asarray(v3[md.name] - out))[0]
    assert diff3[0, 0].max() == 0

    # reversed directions invert the causality
    base.reset_name_counters()
    img2 = layer.data(name="x", type=data_type.dense_vector(
        5 * D * H * W, height=H, width=W, channels=5 * D))
    md_r = more.mdlstmemory(input=img2, size=D,
                            directions=(False, False))
    topo_r = Topology(md_r)
    params_r = paddle.parameters.create(topo_r).as_dict()
    v0, _ = topo_r.forward(params_r, {}, {"x": x}, True, jax.random.key(0))
    v1, _ = topo_r.forward(params_r, {}, {"x": x3}, True, jax.random.key(0))
    d = np.abs(np.asarray(v1[md_r.name] - v0[md_r.name]))[0]
    assert d[0, 0].max() > 0  # last-cell input now reaches the first cell


def test_sub_seq_layer(rng_np):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type, more

    base.reset_name_counters()
    B, T, D = 3, 6, 2
    seq = layer.data(name="s", type=data_type.dense_vector_sequence(D))
    offs = layer.data(name="off", type=data_type.integer_value(T))
    sizes = layer.data(name="sz", type=data_type.integer_value(T))
    sub = more.sub_seq(input=seq, offsets=offs, sizes=sizes)
    topo = Topology(sub)
    params = paddle.parameters.create(topo).as_dict()
    data = rng_np.normal(size=(B, T, D)).astype(np.float32)
    lengths = np.array([6, 5, 4], np.int32)
    off = np.array([1, 0, 2], np.int32)
    sz = np.array([3, 2, 2], np.int32)
    vals, _ = topo.forward(
        params, {},
        {"s": SequenceBatch(data=data, length=lengths), "off": off, "sz": sz},
        False, jax.random.key(0))
    out = vals[sub.name]
    np.testing.assert_array_equal(np.asarray(out.length), sz)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(out.data)[b, :sz[b]],
            data[b, off[b]:off[b] + sz[b]], rtol=1e-6)


def test_switch_order_layer(rng_np):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type, more

    base.reset_name_counters()
    B, C, H, W = 2, 3, 4, 5
    img = layer.data(name="x", type=data_type.dense_vector(
        C * H * W, height=H, width=W, channels=C))
    sw = more.switch_order(input=img)
    topo = Topology(sw)
    params = paddle.parameters.create(topo).as_dict()
    x = rng_np.normal(size=(B, C * H * W)).astype(np.float32)
    vals, _ = topo.forward(params, {}, {"x": x}, False, jax.random.key(0))
    out = np.asarray(vals[sw.name])
    # NCHW flat rows -> NHWC flat rows
    ref = x.reshape(B, C, H, W).transpose(0, 2, 3, 1).reshape(B, -1)
    np.testing.assert_allclose(out, ref, rtol=1e-6)
