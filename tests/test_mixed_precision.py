"""Mixed precision: bf16 compute path trains correctly with f32 master
parameters (the TPU-idiomatic policy SURVEY's north star assumes)."""

import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.lenet import lenet_cost


def test_bf16_compute_trains_and_keeps_f32_params():
    cost, predict, img, label = lenet_cost()
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.05),
        compute_dtype=jnp.bfloat16,
    )
    costs = []
    trainer.train(
        reader=paddle.reader.firstn(
            paddle.reader.batch(paddle.dataset.mnist.train(), 64), 30),
        num_passes=1,
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert np.isfinite(costs[-1])
    assert costs[-1] < costs[0] * 0.5, (costs[0], costs[-1])
    # master params stay f32 under the bf16 compute policy
    for name in trainer.parameters.names():
        assert trainer.parameters[name].dtype == np.float32, name


def test_bf16_forward_close_to_f32():
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.trainer.step import build_train_step
    from paddle_tpu.optimizer import SGD as SGDOpt

    cost, predict, img, label = lenet_cost()
    topo = Topology(cost)
    opt = SGDOpt(learning_rate=0.0)  # no update: compare pure compute
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    feed = {
        "pixel": np.random.default_rng(0).normal(
            size=(8, 784)).astype(np.float32),
        "label": np.random.default_rng(1).integers(0, 10, size=(8,)),
    }
    import jax

    outs = {}
    for dt, tag in ((None, "f32"), (jnp.bfloat16, "bf16")):
        step = build_train_step(topo, opt, compute_dtype=dt)
        p = {k: jnp.array(v) for k, v in params.items()}  # step donates args
        _, _, _, c, _ = step(p, opt.init(p, specs),
                             topo.init_states(), feed, jax.random.key(0))
        outs[tag] = float(c)
    assert abs(outs["bf16"] - outs["f32"]) < 0.1 * abs(outs["f32"]) + 0.05
