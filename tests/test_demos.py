"""v1_api_demo parity runners (paddle_tpu.demo.*): the REFERENCE configs
execute through our trainer — quick_start's trainer_config.lr.py runs
completely unmodified; traffic_prediction's config is byte-identical
with a py3 data provider; model_zoo's pretrained-binary-dir
load/extract mechanism round-trips."""

from __future__ import annotations

import os

import pytest

REF = os.environ.get("PADDLE_REFERENCE_ROOT", "/root/reference")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(REF, "v1_api_demo")),
    reason="reference checkout absent")


def test_quick_start_reference_config(tmp_path, capsys):
    from paddle_tpu.demo.quick_start import run

    rc = run.main(["--workdir", str(tmp_path), "--passes", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "classification_error_evaluator" in out


def test_traffic_prediction_reference_config(tmp_path, capsys):
    from paddle_tpu.demo.traffic_prediction import run

    rc = run.main(["--workdir", str(tmp_path), "--passes", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Cost" in out
    # the reference config is used byte-identically
    with open(os.path.join(
            REF, "v1_api_demo/traffic_prediction/trainer_config.py")) as f:
        ref = f.read()
    with open(tmp_path / "trainer_config.py") as f:
        assert f.read() == ref


def test_model_zoo_feature_extraction(tmp_path, capsys):
    from paddle_tpu.demo.model_zoo import run

    rc = run.main(["--workdir", str(tmp_path), "--batches", "10"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "features from the reloaded binary-dir model match" in out
