"""v1_api_demo parity runners (paddle_tpu.demo.*): the REFERENCE configs
execute through our trainer — quick_start's trainer_config.lr.py runs
completely unmodified; traffic_prediction's config is byte-identical
with a py3 data provider; model_zoo's pretrained-binary-dir
load/extract mechanism round-trips."""

from __future__ import annotations

import os

import pytest

REF = os.environ.get("PADDLE_REFERENCE_ROOT", "/root/reference")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(REF, "v1_api_demo")),
    reason="reference checkout absent")


def test_quick_start_reference_config(tmp_path, capsys):
    from paddle_tpu.demo.quick_start import run

    rc = run.main(["--workdir", str(tmp_path), "--passes", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "classification_error_evaluator" in out


def test_traffic_prediction_reference_config(tmp_path, capsys):
    from paddle_tpu.demo.traffic_prediction import run

    rc = run.main(["--workdir", str(tmp_path), "--passes", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Cost" in out
    # the reference config is used byte-identically
    with open(os.path.join(
            REF, "v1_api_demo/traffic_prediction/trainer_config.py")) as f:
        ref = f.read()
    with open(tmp_path / "trainer_config.py") as f:
        assert f.read() == ref


def test_model_zoo_feature_extraction(tmp_path, capsys):
    from paddle_tpu.demo.model_zoo import run

    rc = run.main(["--workdir", str(tmp_path), "--batches", "10"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "features from the reloaded binary-dir model match" in out


def test_sequence_tagging_reference_configs(tmp_path, capsys):
    from paddle_tpu.demo.sequence_tagging import run

    for cfg in ("linear_crf.py", "rnn_crf.py"):
        d = tmp_path / cfg.replace(".py", "")
        rc = run.main(["--workdir", str(d), "--passes", "1",
                       "--config", cfg])
        assert rc == 0
        with open(os.path.join(
                REF, "v1_api_demo/sequence_tagging", cfg)) as f:
            assert (d / cfg).read_text() == f.read()
    out = capsys.readouterr().out
    assert "chunk_f1" in out  # IOB chunk evaluator ran


def test_config_defaults_and_crf_coeff():
    """default_initial_std/default_decay_rate/default_initial_strategy are
    consumed (not silently dropped), crf coeff scales the cost, and both
    reset with the naming counters so they can't leak across builds."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.config import parse_state
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type, extras

    base.reset_name_counters()
    parse_state.default_initial_std(0.0)  # zero-init everything
    parse_state.default_decay_rate(0.25)
    x = layer.data(name="x", type=data_type.dense_vector(4))
    fc = layer.fc_layer(input=x, size=3, act=act.LinearActivation())
    topo = Topology(fc)
    spec = topo.param_specs()[0]
    assert spec.decay_rate == 0.25
    params = paddle.parameters.create(topo)
    assert float(np.abs(params[spec.name]).max()) == 0.0  # std 0 applied
    base.reset_name_counters()
    assert parse_state.G_DEFAULTS["initial_std"] is None  # reset with build

    # crf coeff scales the mean NLL
    base.reset_name_counters()
    from paddle_tpu.core.lod import SequenceBatch

    emis = layer.data(name="emis", type=data_type.dense_vector_sequence(3))
    lbl = layer.data(name="lab", type=data_type.integer_value_sequence(3))
    pa = paddle.attr.Param(name="crfw")
    c1 = extras.crf(input=emis, label=lbl, size=3, name="c1", param_attr=pa)
    c2 = extras.crf(input=emis, label=lbl, size=3, name="c2", coeff=0.5,
                    param_attr=pa)
    topo = Topology([c1, c2])
    params = paddle.parameters.create(topo)
    feed = {
        "emis": SequenceBatch(
            data=np.random.default_rng(0).normal(
                size=(2, 4, 3)).astype(np.float32),
            length=np.asarray([4, 2], np.int32)),
        "lab": SequenceBatch(data=np.zeros((2, 4), np.int32),
                             length=np.asarray([4, 2], np.int32)),
    }
    values, _ = topo.forward(params.as_dict(), {}, feed, False,
                             jax.random.key(0))
    assert abs(float(values["c2"]) - 0.5 * float(values["c1"])) < 1e-6


def test_chunk_evaluator_reads_ids_companion_v2_path():
    """v2 SGD (no CLI): a chunk evaluator on crf_decoding(label=...) must
    score the decoded PATH (the '#ids' companion auto-joins the
    topology), not the 0/1 error indicator."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.evaluator import declare
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type, extras
    from paddle_tpu.trainer_config_helpers.evaluators import chunk_evaluator

    base.reset_name_counters()
    declare.reset()
    x = layer.data(name="x", type=data_type.dense_vector_sequence(6))
    emis = layer.fc_layer(input=x, size=5, act=act.LinearActivation())
    lbl = layer.data(name="lab", type=data_type.integer_value_sequence(5))
    pa = paddle.attr.Param(name="crfw")
    dec = extras.crf_decoding(input=emis, size=5, label=lbl, name="dec",
                              param_attr=pa)
    cost = extras.crf(input=emis, label=lbl, size=5, param_attr=pa)
    chunk_evaluator(input=dec, label=lbl, chunk_scheme="IOB",
                    num_chunk_types=2, name="f1")
    params = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-2),
        declared_evaluators=declare.collect())
    assert "dec#ids" in {n.name for n in trainer.topology.nodes}

    rng = np.random.default_rng(0)
    def reader():
        for _ in range(32):
            y = rng.integers(0, 4, size=(6,)).astype(np.int32)
            xv = np.zeros((6, 6), np.float32)
            xv[np.arange(6), y] = 2.0
            yield xv, y
    seen = {}
    def on_event(ev):
        if isinstance(ev, paddle.event.EndPass):
            seen.update(ev.metrics)
    trainer.train(reader=paddle.reader.batch(reader, batch_size=8),
                  num_passes=10, event_handler=on_event)
    f1 = [v for k, v in seen.items() if "F1" in k]
    # the mapping is learnable; a real (path-scored) F1 climbs well above
    # what scoring the [B,1] error indicator could ever produce
    assert f1 and f1[0] > 0.5, seen


def test_mnist_reference_config(tmp_path, capsys):
    """light_mnist.py + mnist_provider.py run byte-identical (only
    mnist_util is a py3 port); the synthetic digits are learned."""
    from paddle_tpu.demo.mnist import run
    from paddle_tpu.parallel import mesh as mesh_mod

    prev = mesh_mod.get_mesh()
    try:
        # the config's batch_size=50 doesn't divide the 8-device test mesh
        mesh_mod.get_mesh({"data": 1})
        rc = run.main(["--workdir", str(tmp_path), "--passes", "2",
                       "--n-train", "512", "--n-test", "128"])
    finally:
        mesh_mod.set_mesh(prev)
    assert rc == 0
    for fn in ("light_mnist.py", "mnist_provider.py"):
        with open(os.path.join(REF, "v1_api_demo/mnist", fn)) as f:
            assert (tmp_path / fn).read_text() == f.read()
    out = capsys.readouterr().out
    last = [l for l in out.splitlines() if "Eval:" in l][-1]
    err = float(last.split("classification_error_evaluator=")[1].split()[0])
    assert err < 0.1, out


def test_gan_reference_config_alternating_machines(tmp_path):
    """gan_conf.py runs VERBATIM; the gan_trainer.py two-machine
    alternating loop trains both sides with finite oscillating losses
    (VERDICT r4 missing #2)."""
    import numpy as np

    from paddle_tpu.demo.gan import run as gan_run

    np.random.seed(0)
    dis_losses, gen_losses, sides, final = gan_run.run(
        data_source="uniform", num_iter=16,
        workdir=str(tmp_path / "gan"), log_period=8)
    assert len(dis_losses) == 16 and len(gen_losses) == 16
    assert np.isfinite(dis_losses).all() and np.isfinite(gen_losses).all()
    # both machines actually take update steps
    assert set(sides) == {"dis", "gen"}
    # the discriminator's loss moves (training is live, not a no-op)
    assert dis_losses[-1] != dis_losses[0]
    assert final.shape[1] == 2  # sample_dim from the verbatim config


def test_gan_image_reference_config_parses_and_steps(tmp_path):
    """gan_conf_image.py (conv+BN generator/discriminator) builds all
    three machines and completes alternating iterations."""
    import numpy as np

    from paddle_tpu.demo.gan import run as gan_run

    np.random.seed(0)
    dis_losses, gen_losses, sides, final = gan_run.run(
        data_source="mnist", num_iter=2,
        workdir=str(tmp_path / "ganimg"), log_period=1)
    assert np.isfinite(dis_losses).all() and np.isfinite(gen_losses).all()
    assert final.shape[1] == 784


def test_vae_reference_config_elbo_decreases(tmp_path):
    """vae_conf.py runs VERBATIM through the vae_train.py loop; the ELBO
    cost decreases and the decoder generates via the second machine."""
    import numpy as np

    from paddle_tpu.demo.vae import run as vae_run

    np.random.seed(0)
    losses, samples = vae_run.run(num_batches=24,
                                  workdir=str(tmp_path / "vae"),
                                  log_period=8)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    assert samples.shape[1] == 784
    assert 0.0 <= samples.min() and samples.max() <= 1.0  # sigmoid decoder
