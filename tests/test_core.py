"""Core substrate tests: places, flags, LoD sequences, parameters tar
round-trip (reference test analogs: test_Matrix/test_Argument semantics +
v2 parameters tests)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core import flags, initializer, lod
from paddle_tpu.core.parameters import Parameters, ParamSpec


def test_places():
    p = paddle_tpu.CPUPlace()
    assert p.device().platform == "cpu"
    assert repr(p) == "CPUPlace(0)"


def test_flags_env_and_parse():
    assert flags.get("trainer_count") == 1
    rest = flags.parse_args(["--trainer_count=4", "positional", "--log_period", "10"])
    assert flags.get("trainer_count") == 4
    assert flags.get("log_period") == 10
    assert rest == ["positional"]
    flags.set("trainer_count", 1)
    flags.set("log_period", 100)


def test_sequence_batch_mask_and_last():
    seqs = [np.ones((3, 4)), 2 * np.ones((5, 4)), 3 * np.ones((1, 4))]
    sb = lod.from_ragged(seqs)
    assert sb.data.shape[0] == 3
    assert sb.max_len == 16  # bucketed
    np.testing.assert_array_equal(np.asarray(sb.length), [3, 5, 1])
    mask = np.asarray(sb.mask())
    assert mask.sum() == 9
    last = np.asarray(sb.last_step())
    np.testing.assert_allclose(last[1], 2 * np.ones(4))
    ragged = lod.to_ragged(sb)
    assert [len(r) for r in ragged] == [3, 5, 1]


def test_nested_sequences():
    nested = [
        [np.ones((2, 3)), np.ones((4, 3))],
        [np.ones((1, 3))],
    ]
    nb = lod.from_nested_ragged(nested)
    np.testing.assert_array_equal(np.asarray(nb.seq_length), [2, 1])
    assert np.asarray(nb.inner_mask()).sum() == 7
    flat = nb.flatten_outer()
    assert flat.batch_size == nb.data.shape[0] * nb.data.shape[1]


def test_parameters_tar_roundtrip():
    specs = [
        ParamSpec("w", (3, 4), initializer.xavier()),
        ParamSpec("b", (4,), initializer.constant(0.5)),
    ]
    p = Parameters.from_specs(specs, key=jax.random.key(0))
    np.testing.assert_allclose(p["b"], 0.5 * np.ones(4))
    buf = io.BytesIO()
    p.to_tar(buf)
    buf.seek(0)
    q = Parameters.from_tar(buf)
    assert set(q.names()) == {"w", "b"}
    np.testing.assert_allclose(q["w"], p["w"])


def test_parameters_shared_and_shape_check():
    specs = [
        ParamSpec("shared", (2, 2), initializer.constant(1.0)),
        ParamSpec("shared", (2, 2), initializer.constant(1.0)),
    ]
    p = Parameters.from_specs(specs)
    assert len(p) == 1
    with pytest.raises(Exception):
        p["shared"] = np.zeros((3, 3))


def test_initializers_shapes():
    k = jax.random.key(1)
    for init in [
        initializer.xavier(),
        initializer.msra(),
        initializer.uniform(-0.1, 0.1),
        initializer.normal(0, 1),
        initializer.paddle_default(),
    ]:
        v = init(k, (8, 16), jnp.float32)
        assert v.shape == (8, 16)
