"""Core substrate tests: places, flags, LoD sequences, parameters tar
round-trip (reference test analogs: test_Matrix/test_Argument semantics +
v2 parameters tests)."""

import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.core import flags, initializer, lod
from paddle_tpu.core.parameters import Parameters, ParamSpec


def test_places():
    p = paddle_tpu.CPUPlace()
    assert p.device().platform == "cpu"
    assert repr(p) == "CPUPlace(0)"


def test_flags_env_and_parse():
    assert flags.get("trainer_count") == 1
    rest = flags.parse_args(["--trainer_count=4", "positional", "--log_period", "10"])
    assert flags.get("trainer_count") == 4
    assert flags.get("log_period") == 10
    assert rest == ["positional"]
    flags.set("trainer_count", 1)
    flags.set("log_period", 100)


def test_sequence_batch_mask_and_last():
    seqs = [np.ones((3, 4)), 2 * np.ones((5, 4)), 3 * np.ones((1, 4))]
    sb = lod.from_ragged(seqs)
    assert sb.data.shape[0] == 3
    assert sb.max_len == 16  # bucketed
    np.testing.assert_array_equal(np.asarray(sb.length), [3, 5, 1])
    mask = np.asarray(sb.mask())
    assert mask.sum() == 9
    last = np.asarray(sb.last_step())
    np.testing.assert_allclose(last[1], 2 * np.ones(4))
    ragged = lod.to_ragged(sb)
    assert [len(r) for r in ragged] == [3, 5, 1]


def test_nested_sequences():
    nested = [
        [np.ones((2, 3)), np.ones((4, 3))],
        [np.ones((1, 3))],
    ]
    nb = lod.from_nested_ragged(nested)
    np.testing.assert_array_equal(np.asarray(nb.seq_length), [2, 1])
    assert np.asarray(nb.inner_mask()).sum() == 7
    flat = nb.flatten_outer()
    assert flat.batch_size == nb.data.shape[0] * nb.data.shape[1]


def test_parameters_tar_roundtrip():
    specs = [
        ParamSpec("w", (3, 4), initializer.xavier()),
        ParamSpec("b", (4,), initializer.constant(0.5)),
    ]
    p = Parameters.from_specs(specs, key=jax.random.key(0))
    np.testing.assert_allclose(p["b"], 0.5 * np.ones(4))
    buf = io.BytesIO()
    p.to_tar(buf)
    buf.seek(0)
    q = Parameters.from_tar(buf)
    assert set(q.names()) == {"w", "b"}
    np.testing.assert_allclose(q["w"], p["w"])


def test_parameters_shared_and_shape_check():
    specs = [
        ParamSpec("shared", (2, 2), initializer.constant(1.0)),
        ParamSpec("shared", (2, 2), initializer.constant(1.0)),
    ]
    p = Parameters.from_specs(specs)
    assert len(p) == 1
    with pytest.raises(Exception):
        p["shared"] = np.zeros((3, 3))


def test_initializers_shapes():
    k = jax.random.key(1)
    for init in [
        initializer.xavier(),
        initializer.msra(),
        initializer.uniform(-0.1, 0.1),
        initializer.normal(0, 1),
        initializer.paddle_default(),
    ]:
        v = init(k, (8, 16), jnp.float32)
        assert v.shape == (8, 16)


def test_rankauc_evaluator():
    from paddle_tpu.evaluator import RankAUC

    ev = RankAUC()
    ev.eval_batch(score=[0.9, 0.8, 0.3, 0.1], label=[1, 1, 0, 0])
    assert ev.finish()["rankauc"] == 1.0  # perfect ranking
    ev.start()
    ev.eval_batch(score=[0.1, 0.2, 0.8, 0.9], label=[1, 1, 0, 0])
    assert ev.finish()["rankauc"] == 0.0  # inverted
    ev.start()
    ev.eval_batch(score=[0.5, 0.5, 0.5, 0.5], label=[1, 0, 1, 0])
    assert abs(ev.finish()["rankauc"] - 0.5) < 1e-9  # ties -> 0.5


def test_pruning_hook_masks_smallest_weights():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.layers import api as layer, base, data_type
    from paddle_tpu.layers.attr import ParamAttr

    base.reset_name_counters()
    x = layer.data(name="px", type=data_type.dense_vector(16))
    h = layer.fc(input=x, size=8,
                 param_attr=ParamAttr(name="pruned_w", sparsity_ratio=0.5))
    label = layer.data(name="plabel", type=data_type.integer_value(8))
    cost = layer.classification_cost(input=h, label=label)
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=paddle.optimizer.SGD(
                                     learning_rate=0.1))
    rng = np.random.default_rng(0)

    def reader():
        for _ in range(32):
            v = rng.normal(size=(16,)).astype(np.float32)
            yield v, int(rng.integers(0, 8))

    trainer.train(reader=paddle.reader.batch(reader, 16), num_passes=1)
    w = np.asarray(trainer.parameters["pruned_w"])
    sparsity = float((w == 0).mean())
    assert 0.45 <= sparsity <= 0.55, sparsity


def test_v1_trainer_config_helpers_surface():
    """A 2017-style v1 config file builds and trains against the shim."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.layers import base
    import paddle_tpu.trainer_config_helpers as tch

    base.reset_name_counters()
    tch.settings(batch_size=16, learning_rate=0.1,
                 learning_method=tch.MomentumOptimizer(momentum=0.9))
    from paddle_tpu.layers import data_type
    dat = tch.data_layer(name="v1x", type=data_type.dense_vector(8))
    hid = tch.fc_layer(input=dat, size=16, act=tch.TanhActivation())
    out = tch.fc_layer(input=hid, size=4, act=tch.SoftmaxActivation())
    lbl = tch.data_layer(name="v1y", type=data_type.integer_value(4))
    cost = tch.classification_cost_layer(input=out, label=lbl) \
        if hasattr(tch, "classification_cost_layer") else \
        tch.classification_cost(input=out, label=lbl)

    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=tch.optimizers.get_settings_optimizer())
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 4)).astype(np.float32)

    def reader():
        for _ in range(64):
            v = rng.normal(size=(8,)).astype(np.float32)
            yield v, int(np.argmax(v @ w))

    costs = []
    trainer.train(reader=paddle.reader.batch(reader, 16), num_passes=4,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0]


def test_rankauc_weighted():
    from paddle_tpu.evaluator import RankAUC

    ev = RankAUC()
    # one positive above the negative: weighted AUC must be 1.0
    ev.eval_batch(score=[2.0, 1.0], label=[1, 0], weight=[2.0, 1.0])
    assert ev.finish()["rankauc"] == 1.0
    ev.start()
    # duplicate an item via weight: same auc as literal duplication
    ev.eval_batch(score=[0.9, 0.8, 0.7], label=[1, 0, 1],
                  weight=[1.0, 2.0, 1.0])
    a_w = ev.finish()["rankauc"]
    ev.start()
    ev.eval_batch(score=[0.9, 0.8, 0.8, 0.7], label=[1, 0, 0, 1])
    assert abs(ev.finish()["rankauc"] - a_w) < 1e-12


def test_v2_alias_and_init_flags():
    import paddle_tpu.v2 as p2
    from paddle_tpu.core import flags

    assert hasattr(p2, "layer") and hasattr(p2, "trainer")
    prev = {k: flags.get(k) for k in ("use_tpu", "trainer_count")}
    try:
        p2.init(use_gpu=False, trainer_count=2, bogus_flag_from_2017=True)
        assert flags.get("use_tpu") is False
        assert flags.get("trainer_count") == 2
    finally:
        for k, v in prev.items():
            flags.set(k, v)


def test_debug_nans_traps_poisoned_batch():
    import numpy as np
    import pytest
    import paddle_tpu as paddle
    from paddle_tpu.core import flags
    from paddle_tpu.layers import api as layer, base, data_type

    base.reset_name_counters()
    x = layer.data(name="nx", type=data_type.dense_vector(4))
    h = layer.fc(input=x, size=4)
    lbl = layer.data(name="ny", type=data_type.integer_value(4))
    cost = layer.classification_cost(input=h, label=lbl)
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                 update_equation=paddle.optimizer.SGD(
                                     learning_rate=0.1))

    def reader():
        for _ in range(8):
            yield np.full((4,), np.nan, np.float32), 0

    flags.set("debug_nans", True)
    try:
        with pytest.raises(FloatingPointError):
            trainer.train(reader=paddle.reader.batch(reader, 8), num_passes=1)
    finally:
        flags.set("debug_nans", False)
