"""ICI scaling harness (tools/bench_multichip.py): the dp/sp/tp/pp grid
runs green on the virtual 8-device mesh with a sane collective census
per configuration (VERDICT r4 #7).  On a pod, the same entry point is
the scaling benchmark."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))


def test_grid_runs_with_collective_census():
    import jax

    import bench_multichip as bm

    n = len(jax.devices())
    assert n >= 8, "conftest forces 8 virtual devices"
    rows = bm.run_grid(steps=2, layers=1, embed=16, seq_len=16,
                       batch_per_replica=1)
    by_name = {r["config"]: r for r in rows}
    assert {"dp8", "dp4_tp2", "dp2_sp2_tp2", "tp8", "pp4",
            "dp8_zero1", "dp8_zero2"} <= set(by_name)
    for r in rows:
        assert np.isfinite(r["loss"]), r
        assert r["wall_ms_per_step"] > 0
    # collective inventories reflect the shardings:
    # dp -> grad all-reduce; tp -> more all-reduces (per-layer activation
    # reductions); sp(ring) and pp -> collective-permutes
    assert by_name["dp8"]["collectives_hlo"].get("all-reduce", 0) >= 1
    assert (by_name["tp8"]["collectives_hlo"]["all-reduce"]
            > by_name["dp8"]["collectives_hlo"]["all-reduce"])
    assert by_name["dp2_sp2_tp2"]["collectives_hlo"].get(
        "collective-permute", 0) >= 1
    assert by_name["pp4"]["collectives_hlo"].get(
        "collective-permute", 0) >= 1
    # the zero2 row's compiled program carries the ZeRO-2 collective
    # swap: literal reduce-scatter + all-gather ops for the grad flow
    z2 = by_name["dp8_zero2"]["collectives_hlo"]
    assert z2.get("reduce-scatter", 0) >= 1, z2
    assert z2.get("all-gather", 0) >= 1, z2


def test_grid_for_scales_down():
    import bench_multichip as bm

    assert [c["name"] for c in bm.grid_for(1)] == ["dp1"]
    names2 = [c["name"] for c in bm.grid_for(2)]
    assert "dp2" in names2 and "pp2" in names2
    names8 = [c["name"] for c in bm.grid_for(8)]
    assert len(names8) == 7
    assert "dp8_zero2" in names8
