"""Elastic fleet: live resharding on host loss and scale events
(resilience/elastic.py + the trainer drain points + launch --elastic
membership protocol).  The acceptance property mirrors the reference's
survivability claim (the Go master re-queued a dead trainer's task and
the fleet went on): a chaos-injected host loss at step k on the forced
8-device mesh continues at the reduced dp degree, and the post-drain
trajectory is BIT-IDENTICAL to a run launched at that degree and resumed
from step k's cursor — asserted for the live-shard path, the
checkpoint-fallback path, and the symmetric scale-up."""

from __future__ import annotations

import os
import shutil

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import rng as prng
from paddle_tpu.distributed.multihost import Membership
from paddle_tpu.layers import api as layer
from paddle_tpu.layers import base, data_type
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel import zero as Z
from paddle_tpu.resilience.chaos import ChaosSchedule
from paddle_tpu.resilience.elastic import (
    ElasticCoordinator,
    ElasticError,
    ElasticEvent,
)
from paddle_tpu.telemetry import MemorySink, MetricsRegistry

pytestmark = pytest.mark.elastic

IN_DIM, HIDDEN, CLASSES = 8, 16, 4


@pytest.fixture(autouse=True)
def _restore_global_mesh():
    """An elastic rebuild publishes the new mesh via ``set_mesh`` so
    global-mesh consumers follow; undo that between tests."""
    prev = mesh_mod._current
    yield
    mesh_mod._current = prev


def _trainer(mesh_ctx, zero=2):
    from paddle_tpu.layers import activation as act

    base.reset_name_counters()
    prng.seed(7)
    x = layer.data(name="x", type=data_type.dense_vector(IN_DIM))
    h = layer.fc(input=x, size=HIDDEN, act=act.ReluActivation())
    predict = layer.fc(input=h, size=CLASSES,
                       act=act.SoftmaxActivation())
    lbl = layer.data(name="y", type=data_type.integer_value(CLASSES))
    cost = layer.classification_cost(input=predict, label=lbl)
    params = paddle.parameters.create(paddle.topology.Topology(cost))
    return paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.05),
        mesh=mesh_ctx, zero=zero)


def _reader(batches=10, bs=8):
    def r():
        rs = np.random.RandomState(0)
        for i in range(batches * bs):
            yield rs.randn(IN_DIM).astype(np.float32), int(i % CLASSES)

    return paddle.reader.batch(r, bs)


def _mesh(dp):
    return mesh_mod.MeshContext(
        mesh=mesh_mod.make_mesh({"data": dp}, devices=jax.devices()[:dp]))


def _params_of(tr):
    return {n: np.asarray(tr.parameters[n]) for n in tr.parameters.names()}


def _isolate(src_dir, entry, tmp_path, name):
    """A checkpoint dir holding ONLY ``entry`` — the reference run's
    resume anchor (the elastic run keeps writing newer checkpoints the
    reference must not see)."""
    d = str(tmp_path / name)
    shutil.copytree(os.path.join(src_dir, entry), os.path.join(d, entry))
    return d


def _drain_entries(ckpt_dir):
    return sorted(e for e in os.listdir(ckpt_dir) if "batch" in e)


# -- the acceptance property: bit-identical post-drain trajectories ----------


def test_host_loss_live_reshard_bit_identical(tmp_path):
    """Chaos host loss at step 4 on the 8-device zero=2 mesh: training
    continues at dp=4, and the final parameters equal — bitwise — a
    fresh dp=4 run resumed from the drain-boundary cursor checkpoint."""
    d = str(tmp_path / "ck")
    tr = _trainer(_mesh(8))
    coord = ElasticCoordinator()
    sched = ChaosSchedule("host_loss@4:dp=4").bind_elastic(coord)
    costs = []

    def on_event(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    tr.train(reader=_reader(), num_passes=1, checkpoint_dir=d,
             event_handler=sched.wrap_event_handler(on_event),
             elastic=coord)
    assert dict(tr.mesh.mesh.shape) == {"data": 4}
    assert len(coord.applied) == 1
    rec = coord.applied[0]
    assert rec["event"] == "host_loss" and rec["shard_source"] == "live"
    assert rec["old_dp"] == 8 and rec["new_dp"] == 4
    assert rec["recovery_ms"] > 0
    # the run is healthy end to end — a NaN trajectory would make the
    # bitwise comparisons below vacuous
    assert len(costs) == 10 and np.isfinite(costs).all()
    p_elastic = _params_of(tr)
    assert all(np.isfinite(v).all() for v in p_elastic.values())

    # the drain checkpoint at the rebuild boundary is the anchor
    drains = _drain_entries(d)
    assert drains == ["pass-00000-batch-000005"]
    d_ref = _isolate(d, drains[0], tmp_path, "ref")
    tr_ref = _trainer(_mesh(4))
    tr_ref.train(reader=_reader(), num_passes=1, checkpoint_dir=d_ref)
    p_ref = _params_of(tr_ref)
    for n in p_elastic:
        np.testing.assert_array_equal(
            p_elastic[n], p_ref[n],
            err_msg=f"post-drain trajectory diverged at {n}")


def test_host_loss_checkpoint_fallback_bit_identical(tmp_path):
    """source=checkpoint declares the live shards unrecoverable: the
    rebuild restores the newest cursor checkpoint (batch 6 here, from
    checkpoint_batch_period=3), REPLAYS from its cursor at dp=4, and
    matches a fresh dp=4 run resumed from that same checkpoint."""
    d = str(tmp_path / "ck")
    tr = _trainer(_mesh(8))
    coord = ElasticCoordinator()
    sched = ChaosSchedule(
        "host_loss@6:dp=4:source=checkpoint").bind_elastic(coord)
    tr.train(reader=_reader(), num_passes=1, checkpoint_dir=d,
             checkpoint_batch_period=3,
             event_handler=sched.wrap_event_handler(None), elastic=coord)
    assert dict(tr.mesh.mesh.shape) == {"data": 4}
    rec = coord.applied[0]
    assert rec["shard_source"] == "checkpoint"
    assert rec["replay_cursor"] == {"pass_id": 0, "batch_id": 6}
    p_elastic = _params_of(tr)

    d_ref = _isolate(d, "pass-00000-batch-000006", tmp_path, "ref")
    tr_ref = _trainer(_mesh(4))
    tr_ref.train(reader=_reader(), num_passes=1, checkpoint_dir=d_ref)
    p_ref = _params_of(tr_ref)
    for n in p_elastic:
        np.testing.assert_array_equal(
            p_elastic[n], p_ref[n],
            err_msg=f"fallback replay diverged at {n}")


def test_scale_up_bit_identical_and_prefetch_rebind(tmp_path):
    """The symmetric event: dp=4 grows to the full 8-device mesh.  Run
    with prefetch=2 so staged device feeds cross the rebuild — the
    prefetcher re-places them on the new mesh instead of dropping them,
    keeping the stream gapless (any skip/replay would break
    bit-identity against the reference run)."""
    d = str(tmp_path / "ck")
    tr = _trainer(_mesh(4))
    coord = ElasticCoordinator()
    sched = ChaosSchedule("scale_up@4:dp=8").bind_elastic(coord)
    tr.train(reader=_reader(), num_passes=1, checkpoint_dir=d,
             event_handler=sched.wrap_event_handler(None), elastic=coord,
             prefetch=2)
    assert dict(tr.mesh.mesh.shape) == {"data": 8}
    rec = coord.applied[0]
    assert rec["event"] == "scale_up" and rec["old_dp"] == 4 \
        and rec["new_dp"] == 8
    p_elastic = _params_of(tr)

    drains = _drain_entries(d)
    d_ref = _isolate(d, drains[0], tmp_path, "ref")
    tr_ref = _trainer(_mesh(8))
    tr_ref.train(reader=_reader(), num_passes=1, checkpoint_dir=d_ref)
    p_ref = _params_of(tr_ref)
    for n in p_elastic:
        np.testing.assert_array_equal(
            p_elastic[n], p_ref[n],
            err_msg=f"scale-up trajectory diverged at {n}")


def test_zero0_replicated_run_also_reshards(tmp_path):
    """Elastic is not ZeRO-only: a replicated (zero=0) run reshards the
    same way — the optimizer state is simply replicated onto the new
    mesh."""
    tr = _trainer(_mesh(8), zero=0)
    coord = ElasticCoordinator()
    fired = {"done": False}

    def handler(e):
        if isinstance(e, paddle.event.BeginIteration) \
                and e.batch_id == 3 and not fired["done"]:
            fired["done"] = True
            coord.post_host_loss(new_data_parallel=2)

    tr.train(reader=_reader(), num_passes=1, event_handler=handler,
             elastic=coord)
    assert dict(tr.mesh.mesh.shape) == {"data": 2}
    assert coord.applied[0]["shard_source"] == "live"


# -- telemetry ----------------------------------------------------------------


def test_elastic_event_record_counter_and_gauge(tmp_path):
    reg = MetricsRegistry("elastic_test")
    sink = MemorySink()
    reg.add_sink(sink)
    tr = _trainer(_mesh(8))
    coord = ElasticCoordinator(registry=reg)
    sched = ChaosSchedule("host_loss@4:dp=4",
                          registry=reg).bind_elastic(coord)
    tr.train(reader=_reader(), num_passes=1,
             checkpoint_dir=str(tmp_path / "ck"),
             event_handler=sched.wrap_event_handler(None), elastic=coord,
             metrics_registry=reg)
    recs = [r for r in sink.records if r.get("kind") == "elastic_event"]
    assert len(recs) == 1
    r = recs[0]
    assert r["event"] == "host_loss" and r["shard_source"] == "live"
    assert r["old_dp"] == 8 and r["new_dp"] == 4
    assert r["recovery_ms"] > 0
    assert r["respec"]["old_degree"] == 8
    assert r["respec"]["new_degree"] == 4
    assert reg.counter("elastic_events", "").value(kind="host_loss") == 1.0
    assert reg.gauge("recovery_ms", "").value(run="elastic") > 0
    # the chaos injection itself is accounted like every other fault
    assert reg.counter("faults_injected", "").value(kind="host_loss") \
        == 1.0


def test_metrics_to_md_renders_elastic_table(tmp_path, capsys):
    import json
    import sys

    sys.path.insert(0, "tools")
    try:
        import metrics_to_md
    finally:
        sys.path.pop(0)
    path = tmp_path / "m.jsonl"
    recs = [
        {"kind": "elastic_event", "event": "host_loss", "old_dp": 8,
         "new_dp": 4, "recovery_ms": 13.4, "shard_source": "live",
         "pass_id": 0, "batch_id": 5},
        {"kind": "elastic_event", "event": "host_loss", "old_dp": 4,
         "new_dp": 2, "recovery_ms": 62.7, "shard_source": "checkpoint",
         "pass_id": 0, "batch_id": 9,
         "replay_cursor": {"pass_id": 0, "batch_id": 6}},
        {"kind": "elastic_event", "event": "scale_up", "old_dp": 2,
         "new_dp": 8, "recovery_ms": 15.0, "shard_source": "live",
         "pass_id": 1, "batch_id": 2},
    ]
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    metrics_to_md.main([str(path)])
    out = capsys.readouterr().out
    assert "## Elastic events" in out
    assert "host_loss" in out and "scale_up" in out
    assert "8 → 4" in out and "2 → 8" in out
    # checkpoint fallbacks are flagged loudly, with the replay cursor
    assert "checkpoint ⚠" in out
    assert "1 checkpoint-fallback recovery" in out
    assert "pass 0 batch 6" in out
    assert "3 elastic rebuild(s)" in out


# -- membership protocol ------------------------------------------------------


def test_membership_remove_add_renumber_epoch():
    m = Membership(ranks=range(4))
    assert m.epoch == 0
    ren = m.remove(1)
    assert m.ranks == [0, 2, 3] and m.epoch == 1
    # stable global ids, dense mesh renumbering, order preserved
    assert ren == {0: 0, 2: 1, 3: 2}
    m.remove(1)  # duplicate notice: idempotent, no epoch bump
    assert m.epoch == 1
    ren = m.add(4)
    assert m.ranks == [0, 2, 3, 4] and m.epoch == 2
    assert ren == {0: 0, 2: 1, 3: 2, 4: 3}
    m.add(4)
    assert m.epoch == 2


def test_membership_heartbeats_and_staleness():
    m = Membership(ranks=range(3))
    m.heartbeat(0, ts=100.0)
    m.heartbeat(1, ts=100.0)
    m.heartbeat(2, ts=109.5)
    assert m.stale_ranks(5.0, now=110.0) == [0, 1]
    assert m.stale_ranks(15.0, now=110.0) == []


def test_membership_file_roundtrip(tmp_path):
    path = str(tmp_path / "membership.json")
    m = Membership(ranks=[0, 2, 5], epoch=3)
    m.write(path)
    m2 = Membership.read(path)
    assert m2.ranks == [0, 2, 5] and m2.epoch == 3


def test_observe_membership_posts_delta_events(tmp_path):
    coord = ElasticCoordinator(devices_per_rank=4)
    # first view is the baseline — no event
    assert coord.observe_membership(Membership(ranks=[0, 1], epoch=0)) \
        is False
    assert not coord.pending()
    # a rank dies: epoch bump + fewer ranks -> host_loss at 1*4 devices
    assert coord.observe_membership(Membership(ranks=[0], epoch=1))
    ev = coord._events[0]
    assert ev.kind == "host_loss" and ev.new_data_parallel == 4
    coord.reset_pending()
    # re-reading the same epoch is idempotent
    assert coord.observe_membership(Membership(ranks=[0], epoch=1)) \
        is False
    # scale back up
    assert coord.observe_membership(Membership(ranks=[0, 3], epoch=2))
    ev = coord._events[0]
    assert ev.kind == "scale_up" and ev.new_data_parallel == 8


def test_seeded_membership_catches_pre_first_read_loss():
    """A rank that dies BEFORE the survivor's first membership read
    must still register: seeding anchors the baseline to the fleet the
    process joined, so the first observed view is a delta, not a
    baseline."""
    coord = ElasticCoordinator(devices_per_rank=4)
    coord.seed_membership(epoch=0, rank_count=2)
    assert coord.observe_membership(Membership(ranks=[0], epoch=1))
    ev = coord._events[0]
    assert ev.kind == "host_loss" and ev.new_data_parallel == 4


def test_on_stale_requires_rank_attribution():
    """Guessing a lost rank would evict a healthy host; without
    attribution on_stale logs and does NOT post."""
    coord = ElasticCoordinator()
    coord.on_stale(12.0, "/tmp/flight.json")
    assert not coord.pending()
    coord.on_stale(12.0, "/tmp/flight.json", lost_ranks=(3,))
    assert coord.pending()
    assert coord._events[0].lost_ranks == (3,)


def test_watch_membership_polls_file(tmp_path):
    import time

    path = str(tmp_path / "membership.json")
    Membership(ranks=[0, 1], epoch=0).write(path)
    coord = ElasticCoordinator(devices_per_rank=2)
    coord.watch_membership(path, poll_s=0.02)
    try:
        deadline = time.monotonic() + 5.0
        while coord._last_membership_epoch is None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert coord._last_membership_epoch == 0
        Membership(ranks=[0], epoch=1).write(path)
        while not coord.pending() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert coord.pending()
        assert coord._events[0].kind == "host_loss"
        assert coord._events[0].new_data_parallel == 2
    finally:
        coord.stop()


# -- zero respec + mesh resize ------------------------------------------------


def test_respec_report_counts_layout_changes():
    import jax.numpy as jnp

    old = mesh_mod.make_mesh({"data": 8})
    new = mesh_mod.make_mesh({"data": 4}, devices=jax.devices()[:4])
    opt_state = {"step": jnp.zeros(()), "slots": {
        "w": {"m": jnp.zeros((16, 8))},   # divides 8 and 4: resharded
        "odd": {"m": jnp.zeros((4, 3))},  # divides 4 only: to_sharded
        "tiny": {"m": jnp.zeros((3,))},   # divides neither: replicated
    }}
    rep = Z.respec_report(opt_state, old, new)
    assert rep["old_degree"] == 8 and rep["new_degree"] == 4
    assert rep["resharded"] == 1
    assert rep["to_sharded"] == 1
    assert rep["replicated"] == 1
    assert rep["to_replicated"] == 0
    # 16*8*4/8 + 4*3*4 (replicated at 8) + 3*4  vs  /4 + /4 + 3*4
    assert rep["old_bytes_per_device"] == 64 + 48 + 12
    assert rep["new_bytes_per_device"] == 128 + 12 + 12


def test_resize_data_axis_validates():
    ctx = mesh_mod.MeshContext(
        mesh=mesh_mod.make_mesh({"data": 4, "model": 2}))
    with pytest.raises(Exception, match="pure data"):
        mesh_mod.resize_data_axis(ctx, 2)
    ctx = _mesh(4)
    out = mesh_mod.resize_data_axis(ctx, 8)
    assert dict(out.mesh.shape) == {"data": 8}
    # shrink keeps the leading survivors
    out2 = mesh_mod.resize_data_axis(ctx, 2)
    assert list(out2.mesh.devices.flat) == jax.devices()[:2]


# -- coordinator edge cases ---------------------------------------------------


def test_event_validation():
    with pytest.raises(ValueError, match="unknown elastic event"):
        ElasticEvent("explode")
    with pytest.raises(ValueError, match="scale_up needs"):
        ElasticEvent("scale_up")
    with pytest.raises(ValueError, match="host_loss needs"):
        ElasticEvent("host_loss")
    with pytest.raises(ValueError, match="shard_source"):
        ElasticEvent("host_loss", new_data_parallel=2,
                     shard_source="telepathy")


def test_chaos_spec_parsing_and_binding():
    s = ChaosSchedule("host_loss@5:dp=4:source=checkpoint,scale_up@9:dp=8")
    assert s.faults[0].kind == "host_loss" and s.faults[0].step == 5
    assert s.faults[0].params == {"dp": 4, "source": "checkpoint"}
    assert s.faults[1].kind == "scale_up"
    with pytest.raises(ValueError, match="needs a :dp"):
        ChaosSchedule("host_loss@5")
    with pytest.raises(ValueError, match="source must be"):
        ChaosSchedule("host_loss@5:dp=4:source=wishful")
    with pytest.raises(ValueError, match="unknown chaos fault option"):
        ChaosSchedule("host_loss@5:dp=4:color=red")
    # the old suffix syntax still parses
    s2 = ChaosSchedule("step_error@4:always")
    assert s2.faults[0].always is True


def test_fallback_without_checkpoint_raises_elastic_error():
    tr = _trainer(_mesh(8))
    coord = ElasticCoordinator()
    fired = {"done": False}

    def handler(e):
        if isinstance(e, paddle.event.BeginIteration) \
                and e.batch_id == 2 and not fired["done"]:
            fired["done"] = True
            coord.post_host_loss(new_data_parallel=4,
                                 shard_source="checkpoint")

    with pytest.raises(ElasticError, match="no checkpoint"):
        tr.train(reader=_reader(), num_passes=1, event_handler=handler,
                 elastic=coord)


def test_cli_elastic_chaos_host_loss(tmp_path, capsys):
    """The operator surface end to end: ``--elastic`` +
    ``--chaos='host_loss@k:dp=N'`` on the trainer CLI reshards mid-run,
    finishes the job rc 0, emits the elastic_event record through the
    ``--metrics_jsonl`` stream, and leaves the drain cursor checkpoint
    on disk."""
    import json

    import test_trainer_cli as cli_fixtures

    from paddle_tpu.trainer import cli

    cfg = cli_fixtures._write_digits_config(tmp_path)
    jsonl = tmp_path / "m.jsonl"
    ckdir = tmp_path / "ck"
    rc = cli.main(["--config", cfg, "--job", "train", "--num_passes", "1",
                   "--checkpoint_dir", str(ckdir),
                   "--elastic", "--chaos", "host_loss@3:dp=4",
                   "--sync_period", "1", "--prefetch", "0",
                   "--log_period", "4",
                   f"--metrics_jsonl={jsonl}"])
    assert rc == 0
    recs = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    evs = [r for r in recs if r.get("kind") == "elastic_event"]
    assert len(evs) == 1
    assert evs[0]["event"] == "host_loss" and evs[0]["new_dp"] == 4
    assert evs[0]["shard_source"] == "live"
    assert any("batch" in e for e in os.listdir(ckdir))
    # steps kept flowing after the rebuild (the run finished its pass)
    steps = [r for r in recs if r.get("kind") == "step"]
    assert steps and steps[-1]["batch_id"] > evs[0]["batch_id"]


def test_supervisor_drops_stale_elastic_events(tmp_path):
    """The restart budget is the fallback of the elastic fallback: an
    ElasticError is a retryable worker fault, and the retry first drops
    the queued events the restored state already reflects."""
    from paddle_tpu.resilience.supervisor import Supervisor

    coord = ElasticCoordinator()
    coord.post_host_loss(new_data_parallel=4)
    attempts = []

    def train_fn():
        attempts.append(coord.pending())
        if len(attempts) == 1:
            raise ElasticError("live shard gather failed: injected")
        return "done"

    sup = Supervisor(max_restarts=1, elastic=coord)
    assert sup.run(train_fn) == "done"
    # first attempt saw the queued event; the retry entered clean
    assert attempts == [True, False]
    assert sup.restarts == 1
