"""ZeRO-1 optimizer-state sharding (parallel/zero.py): the pserver's
sharded-state property in-mesh.  Invariance vs the replicated-state step,
1/n per-device state bytes, and composition with the TP layout."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.models import transformer as T
from paddle_tpu.optimizer import Adam
from paddle_tpu.parallel.zero import (
    shard_opt_state,
    state_bytes_per_device,
    zero1_specs,
)


def _cfg(**kw):
    base = dict(vocab_size=64, num_layers=2, num_heads=2, embed_dim=16,
                mlp_dim=32, max_seq_len=32, remat=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def _ids(bsz, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 64, (bsz, 17)))


def test_zero1_matches_replicated_step():
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("data",))
    cfg = _cfg()
    opt = Adam(learning_rate=1e-3)
    params0 = T.init_params(cfg, jax.random.key(0))
    ids = _ids(8)

    # replicated-state reference
    p_ref = jax.device_put(params0)
    s_ref = opt.init_tree(p_ref)
    step_ref = T.build_train_step(cfg, opt)
    for _ in range(3):
        p_ref, s_ref, loss_ref = step_ref(p_ref, s_ref, ids)

    # zero-1 sharded state
    p_z = T.place_params(T.init_params(cfg, jax.random.key(0)), mesh, cfg)
    s_z = shard_opt_state(opt.init_tree(p_z), p_z, mesh,
                          param_specs=T.param_shardings(cfg))
    step_z = T.build_train_step(cfg, opt, mesh=mesh, zero1=True)
    ids_z = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    for _ in range(3):
        p_z, s_z, loss_z = step_z(p_z, s_z, ids_z)

    np.testing.assert_allclose(float(loss_z), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_z)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_zero1_state_is_sharded_quarter_bytes():
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("data",))
    cfg = _cfg()
    opt = Adam(learning_rate=1e-3)
    params = T.place_params(T.init_params(cfg, jax.random.key(0)), mesh, cfg)
    state = shard_opt_state(opt.init_tree(params), params, mesh,
                            param_specs=T.param_shardings(cfg))
    total = sum(l.size * l.dtype.itemsize
                for l in jax.tree.leaves(state["slots"]))
    per_dev = state_bytes_per_device(state)
    # every slot dim here divides 4 except tiny vectors; allow slack
    assert per_dev < total / 3, (per_dev, total)

    # the step KEEPS the state sharded (with_sharding_constraint holds)
    step = T.build_train_step(cfg, opt, mesh=mesh, zero1=True)
    ids = jax.device_put(_ids(8), NamedSharding(mesh, P("data", None)))
    params, state, _ = step(params, state, ids)
    m = state["slots"][0]["m"]  # embed-table moment
    assert "data" in jax.tree.leaves(
        m.sharding.spec, is_leaf=lambda x: x is not None) or \
        any(a == "data" for a in m.sharding.spec if a)
    assert state_bytes_per_device(state) < total / 3


def test_zero1_composes_with_tp():
    devs = jax.devices()[:8]
    mesh = Mesh(np.asarray(devs).reshape(4, 2), ("data", "model"))
    cfg = _cfg()
    opt = Adam(learning_rate=1e-3)
    params0 = T.init_params(cfg, jax.random.key(0))
    ids = _ids(8)

    p_ref = jax.device_put(params0)
    s_ref = opt.init_tree(p_ref)
    step_ref = T.build_train_step(cfg, opt)
    p_ref, s_ref, _ = step_ref(p_ref, s_ref, ids)

    p_z = T.place_params(T.init_params(cfg, jax.random.key(0)), mesh, cfg)
    specs = T.param_shardings(cfg)
    s_z = shard_opt_state(opt.init_tree(p_z), p_z, mesh, param_specs=specs)
    step_z = T.build_train_step(cfg, opt, mesh=mesh, zero1=True)
    ids_z = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    p_z, s_z, _ = step_z(p_z, s_z, ids_z)

    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_z)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    # a TP-sharded weight's moment carries BOTH axes (e.g. wq: model on
    # dim 2, data laid on a free dim)
    wq_spec = zero1_specs(s_z, p_z, mesh, param_specs=specs)
    flat = jax.tree.leaves(
        wq_spec["slots"], is_leaf=lambda x: isinstance(x, P))
    axes = {a for sp in flat for a in sp if a is not None}
    assert "data" in axes and "model" in axes
