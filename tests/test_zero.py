"""ZeRO-1 optimizer-state sharding (parallel/zero.py): the pserver's
sharded-state property in-mesh.  Invariance vs the replicated-state step,
1/n per-device state bytes, and composition with the TP layout."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.models import transformer as T
from paddle_tpu.optimizer import Adam
from paddle_tpu.parallel.zero import (
    shard_opt_state,
    state_bytes_per_device,
    zero1_specs,
)


def _cfg(**kw):
    base = dict(vocab_size=64, num_layers=2, num_heads=2, embed_dim=16,
                mlp_dim=32, max_seq_len=32, remat=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def _ids(bsz, seed=0):
    return jnp.asarray(
        np.random.default_rng(seed).integers(0, 64, (bsz, 17)))


def _zero1_setup(mesh, cfg=None, seed=0):
    """(params, state, step, sharded ids, specs) — the common ZeRO-1
    harness: placed params, sharded slots, zero1 train step."""
    cfg = cfg or _cfg()
    opt = Adam(learning_rate=1e-3)
    params = T.place_params(T.init_params(cfg, jax.random.key(seed)),
                            mesh, cfg)
    specs = T.param_shardings(cfg)
    state = shard_opt_state(opt.init_tree(params), params, mesh,
                            param_specs=specs)
    step = T.build_train_step(cfg, opt, mesh=mesh, zero1=True)
    ids = jax.device_put(_ids(8), NamedSharding(mesh, P("data", None)))
    return params, state, step, ids, specs


def test_zero1_matches_replicated_step():
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("data",))
    cfg = _cfg()
    opt = Adam(learning_rate=1e-3)
    params0 = T.init_params(cfg, jax.random.key(0))
    ids = _ids(8)

    # replicated-state reference
    p_ref = jax.device_put(params0)
    s_ref = opt.init_tree(p_ref)
    step_ref = T.build_train_step(cfg, opt)
    for _ in range(3):
        p_ref, s_ref, loss_ref = step_ref(p_ref, s_ref, ids)

    # zero-1 sharded state
    p_z, s_z, step_z, ids_z, _ = _zero1_setup(mesh, cfg)
    for _ in range(3):
        p_z, s_z, loss_z = step_z(p_z, s_z, ids_z)

    np.testing.assert_allclose(float(loss_z), float(loss_ref),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_z)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_zero1_state_is_sharded_quarter_bytes():
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("data",))
    params, state, step, ids, _ = _zero1_setup(mesh)
    total = sum(l.size * l.dtype.itemsize
                for l in jax.tree.leaves(state["slots"]))
    per_dev = state_bytes_per_device(state)
    # every slot dim here divides 4 except tiny vectors; allow slack
    assert per_dev < total / 3, (per_dev, total)

    # the step KEEPS the state sharded (with_sharding_constraint holds)
    params, state, _ = step(params, state, ids)
    m = state["slots"][0]["m"]  # embed-table moment
    assert "data" in jax.tree.leaves(
        m.sharding.spec, is_leaf=lambda x: x is not None) or \
        any(a == "data" for a in m.sharding.spec if a)
    assert state_bytes_per_device(state) < total / 3


def test_zero1_composes_with_tp():
    devs = jax.devices()[:8]
    mesh = Mesh(np.asarray(devs).reshape(4, 2), ("data", "model"))
    cfg = _cfg()
    opt = Adam(learning_rate=1e-3)
    params0 = T.init_params(cfg, jax.random.key(0))
    ids = _ids(8)

    p_ref = jax.device_put(params0)
    s_ref = opt.init_tree(p_ref)
    step_ref = T.build_train_step(cfg, opt)
    p_ref, s_ref, _ = step_ref(p_ref, s_ref, ids)

    p_z, s_z, step_z, ids_z, specs = _zero1_setup(mesh, cfg)
    p_z, s_z, _ = step_z(p_z, s_z, ids_z)

    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_z)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)

    # a TP-sharded weight's moment carries BOTH axes (e.g. wq: model on
    # dim 2, data laid on a free dim)
    wq_spec = zero1_specs(s_z, p_z, mesh, param_specs=specs)
    flat = jax.tree.leaves(
        wq_spec["slots"], is_leaf=lambda x: isinstance(x, P))
    axes = {a for sp in flat for a in sp if a is not None}
    assert "data" in axes and "model" in axes


def test_zero1_state_checkpoint_roundtrip(tmp_path):
    """A ZeRO-1-sharded run survives save/load: params + sharded slots
    checkpoint after step 1, a fresh process-style rebuild restores them,
    and step 2 from the restored state equals step 2 of the
    uninterrupted run — the full resume-equivalence check."""
    from paddle_tpu.trainer import checkpoint as ckpt

    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("data",))
    params, state, step, ids, specs = _zero1_setup(mesh)
    params, state, _ = step(params, state, ids)

    d = str(tmp_path / "z")
    flat_params = {f"p{i}": np.asarray(l)
                   for i, l in enumerate(jax.tree.leaves(params))}
    ckpt.save_checkpoint(d, 0, flat_params, opt_state=state)
    # host copy BEFORE the continuation step donates the buffers
    state_host = jax.tree.map(np.asarray, state)
    # the uninterrupted continuation (reference trajectory)
    p_cont, s_cont, _ = step(params, state, ids)
    p_cont_host = jax.tree.map(np.asarray, p_cont)

    # fresh rebuild (as a restarted process would), then restore
    params2, tmpl, step2, ids2, _ = _zero1_setup(mesh)
    loaded_p, restored, _, _ = ckpt.load_checkpoint(
        ckpt.latest_checkpoint(d)[0], opt_state_template=tmpl)
    treedef = jax.tree.structure(params2)
    params2 = jax.tree.unflatten(
        treedef, [jnp.asarray(loaded_p[f"p{i}"])
                  for i in range(treedef.num_leaves)])
    params2 = T.place_params(params2, mesh, _cfg())
    restored = shard_opt_state(restored, params2, mesh, param_specs=specs)

    for a, b in zip(jax.tree.leaves(state_host), jax.tree.leaves(restored)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-6, atol=1e-7)
    # resumed step 2 == uninterrupted step 2
    p_res, s_res, loss = step2(params2, restored, ids2)
    assert np.isfinite(float(loss))
    for a, b in zip(jax.tree.leaves(p_cont_host), jax.tree.leaves(p_res)):
        np.testing.assert_allclose(a, np.asarray(b), rtol=1e-5, atol=1e-6)
