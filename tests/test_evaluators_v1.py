"""v1 declarative evaluator surface end-to-end:

- ``*_evaluator`` calls inside an (unmodified-style) v1 config file are
  emitted into ``ModelConfig.evaluators`` (EvaluatorConfig parity) and
- executed by the trainer CLI: train prints pass "Eval:" metrics, test
  merges them into the result (≅ Tester.cpp printing GradientMachine eval).
- printer family members (value/maxid/gradient printers) run host-side,
  the gradient printer fed by d(cost)/d(layer) taps.
- chunk evaluator works batch-wise on sequence data (unit-level).
"""

from __future__ import annotations

import textwrap

import numpy as np


def _write_binary_config(tmp_path):
    cfg = tmp_path / "bin.conf"
    cfg.write_text(textwrap.dedent("""
        from paddle.trainer_config_helpers import *

        define_py_data_sources2(
            train_list='{d}/train.list', test_list='{d}/test.list',
            module='bin_provider', obj='process')
        settings(batch_size=32, learning_rate=1e-2,
                 learning_method=AdamOptimizer())

        img = data_layer(name='pixel', size=32)
        hidden = fc_layer(input=img, size=16, act=ReluActivation())
        predict = fc_layer(input=hidden, size=2, act=SoftmaxActivation())
        lbl = data_layer(name='label', size=2)

        classification_error_evaluator(input=predict, label=lbl,
                                       name='err_rate')
        auc_evaluator(input=predict, label=lbl, name='train_auc')
        sum_evaluator(input=predict, name='prob_sum')
        value_printer_evaluator(input=predict, name='probs_vp')
        maxid_printer_evaluator(input=predict, name='top1')
        gradient_printer_evaluator(input=predict, name='dpredict')

        outputs(classification_cost(input=predict, label=lbl))
    """).format(d=tmp_path))
    (tmp_path / "bin_provider.py").write_text(textwrap.dedent("""
        import numpy as np
        from paddle.trainer.PyDataProvider2 import (
            provider, dense_vector, integer_value)

        @provider(input_types={'pixel': dense_vector(32),
                               'label': integer_value(2)})
        def process(settings, filename):
            rng = np.random.default_rng(int(filename.split('-')[-1]))
            for _ in range(128):
                y = int(rng.integers(0, 2))
                x = rng.normal(size=(32,)).astype(np.float32) * 0.1
                x[y * 16:(y + 1) * 16] += 1.0
                yield x, y
    """))
    (tmp_path / "train.list").write_text("seed-0\n")
    (tmp_path / "test.list").write_text("seed-7\n")
    return str(cfg)


def test_evaluator_declarations_emit_proto(tmp_path):
    from paddle_tpu.trainer.config_parser import parse_config

    cfg = _write_binary_config(tmp_path)
    parsed = parse_config(cfg, "")
    evs = {e.name: e for e in parsed.model_config.evaluators}
    # auto evaluator from classification_cost + the six declared ones
    assert "classification_error_evaluator" in evs
    assert evs["err_rate"].type == "classification_error"
    assert list(evs["err_rate"].input_layers) == ["__fc_layer_1__", "label"]
    assert evs["train_auc"].type == "last-column-auc"
    assert evs["probs_vp"].type == "value_printer"
    assert evs["top1"].type == "max_id_printer"
    assert evs["dpredict"].type == "gradient_printer"
    # declared specs ride on ParsedConfig for the runtime
    assert {s.name for s in parsed.evaluators} >= {
        "err_rate", "train_auc", "prob_sum", "probs_vp", "top1", "dpredict"}
    # protostr renders the evaluator block (EvaluatorConfig parity)
    assert 'evaluators {' in parsed.protostr()
    assert 'type: "last-column-auc"' in parsed.protostr()


def test_cli_train_and_test_with_declared_evaluators(tmp_path, capsys):
    from paddle_tpu.trainer import cli

    cfg = _write_binary_config(tmp_path)
    rc = cli.main(["--config", cfg, "--job", "train", "--num_passes", "2",
                   "--log_period", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    # pass summary carries the declared evaluator metrics
    assert "Eval:" in out
    assert "err_rate=" in out
    assert "train_auc=" in out
    # the error rate at the final pass should beat chance
    last_eval = [ln for ln in out.splitlines() if "err_rate=" in ln][-1]
    err = float(last_eval.split("err_rate=")[1].split()[0])
    assert err < 0.3, out

    rc = cli.main(["--config", cfg, "--job", "test"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "err_rate=" in out.replace("'err_rate': ", "err_rate=")


def test_chunk_evaluator_runtime_sequence():
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.evaluator import declare, runtime

    declare.reset()
    from paddle_tpu.trainer_config_helpers.evaluators import chunk_evaluator

    chunk_evaluator(input="pred", label="lab", chunk_scheme="IOB",
                    num_chunk_types=1, name="chunks")
    evs = runtime.build(declare.collect())
    evs.start()
    # B-I-O tag ids for IOB with 1 type: B=0, I=1, O=2
    pred = SequenceBatch(data=np.asarray([[0, 1, 2, 0, 1]]),
                         length=np.asarray([5]))
    lab = SequenceBatch(data=np.asarray([[0, 1, 2, 0, 2]]),
                        length=np.asarray([5]))
    evs.eval_batch({"pred": pred, "lab": lab})
    res = evs.finish()
    f1 = [v for k, v in res.items() if "F1" in k or "f1" in k]
    assert res, "chunk evaluator returned no metrics"
    assert f1 and 0 <= f1[0] <= 1


def test_seqtext_printer_plain_sequences(tmp_path):
    """Non-beam path: integer sequences (or prob matrices via argmax) are
    printed one line per sample (Evaluator.cpp:1219 basic format)."""
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.evaluator import declare, runtime

    declare.reset()
    from paddle_tpu.trainer_config_helpers.evaluators import (
        seqtext_printer_evaluator,
    )

    out = tmp_path / "seq.txt"
    seqtext_printer_evaluator(input="ids", result_file=str(out))
    evs = runtime.build(declare.collect())
    evs.start()
    ids = SequenceBatch(data=np.asarray([[3, 1, 2], [2, 2, 0]]),
                        length=np.asarray([3, 2]))
    evs.eval_batch({"ids": ids})
    evs.finish()
    lines = out.read_text().splitlines()
    assert lines == ["0\t 3 1 2", "1\t 2 2"]

def test_pnpair_evaluator_reference_order_and_result():
    """pnpair inputs are declared [score, label, info, weight] like the
    reference (evaluators.py:295 appends label then info;
    Evaluator.cpp:880-887 reads output/label/info/weight in that order),
    ``info`` is the reference parameter name, and the runtime maps the
    indices accordingly."""
    import numpy as np

    from paddle_tpu.evaluator import declare, runtime
    from paddle_tpu.trainer_config_helpers.evaluators import pnpair_evaluator

    declare.reset()
    spec = pnpair_evaluator(input="score", label="lab", info="qid",
                            name="pn")
    assert spec.input_layers == ["score", "lab", "qid"]
    # query_id= kept as an alias for old callers
    declare.reset()
    spec2 = pnpair_evaluator(input="score", label="lab", query_id="qid")
    assert spec2.input_layers == ["score", "lab", "qid"]

    declare.reset()
    pnpair_evaluator(input="score", label="lab", info="qid", name="pn")
    evs = runtime.build(declare.collect())
    evs.start()
    # one query, one (pos, neg) pair ranked correctly -> pnpair accuracy 1
    evs.eval_batch({
        "score": np.asarray([[0.9], [0.1]], np.float32),
        "lab": np.asarray([1, 0]),
        "qid": np.asarray([7, 7]),
    })
    res = evs.finish()
    vals = [v for v in res.values() if isinstance(v, (int, float))]
    assert vals and any(abs(v - 1.0) < 1e-6 for v in vals), res


def test_chunk_evaluator_excluded_types_and_iobes():
    from paddle_tpu.evaluator import ChunkEvaluator

    # IOB, 2 chunk types; exclude type 1 -> only type-0 chunks count
    ev = ChunkEvaluator(chunk_scheme="IOB", num_chunk_types=2,
                        excluded_chunk_types=[1])
    # labels: tag = lab % 2, type = lab // 2, O = 4
    # pred: [B0 I0 B1 I1] -> (0,1,0) and (2,3,1); label identical
    ev.eval_batch(pred=[[0, 1, 2, 3]], label=[[0, 1, 2, 3]])
    res = ev.finish()
    assert res["F1-score"] == 1.0
    assert ev.correct == 1 and ev.infer_total == 1 and ev.label_total == 1

    # IOBES single-token chunk via S tag (tag ids B=0 I=1 E=2 S=3)
    ev = ChunkEvaluator(chunk_scheme="IOBES", num_chunk_types=1)
    # S0, O, B0 I0 E0  (O = 1 * 4 = 4)
    ev.eval_batch(pred=[[3, 4, 0, 1, 2]], label=[[3, 4, 0, 1, 2]])
    res = ev.finish()
    assert res["F1-score"] == 1.0 and ev.correct == 2


def test_column_sum_evaluator_last_column_mean():
    """ColumnSumEvaluator reports sum[-1]/numSamples like the reference's
    printStats (Evaluator.cpp:351-363)."""
    import numpy as np

    from paddle_tpu.evaluator import ColumnSumEvaluator

    ev = ColumnSumEvaluator()
    ev.eval_batch(value=np.asarray([[1.0, 2.0], [3.0, 4.0]]))
    ev.eval_batch(value=np.asarray([[5.0, 6.0]]))
    res = ev.finish()
    (val,) = res.values()
    assert abs(val - (2.0 + 4.0 + 6.0) / 3) < 1e-9

    # weighted: numSamples is the weight sum (Evaluator.cpp:288-294)
    ev = ColumnSumEvaluator()
    ev.eval_batch(value=np.asarray([[2.0], [4.0]]),
                  weight=np.asarray([1.0, 3.0]))
    (val,) = ev.finish().values()
    assert abs(val - (2.0 * 1 + 4.0 * 3) / 4.0) < 1e-9


def test_precision_recall_positive_label_out_of_range():
    import numpy as np
    import pytest

    from paddle_tpu.evaluator import PrecisionRecall

    ev = PrecisionRecall(num_classes=None, positive_label=3)
    ev.eval_batch(pred=np.asarray([[0.4, 0.6]]), label=np.asarray([1]))
    with pytest.raises(ValueError, match="positive_label"):
        ev.finish()


def test_test_job_reader_keeps_tail_batches(tmp_path):
    """The test job must evaluate every sample: _reader_from_data_config
    flushes tail batches when shuffle=False (train still drops them to
    keep batch shapes pinned)."""
    import sys
    import textwrap

    from paddle_tpu.trainer.cli import _reader_from_data_config

    (tmp_path / "tail_provider.py").write_text(textwrap.dedent("""
        import numpy as np
        from paddle.trainer.PyDataProvider2 import (
            provider, dense_vector, integer_value)

        @provider(input_types={'x': dense_vector(4),
                               'y': integer_value(2)})
        def process(settings, filename):
            for i in range(10):
                yield np.full((4,), float(i), np.float32), i % 2
    """))
    (tmp_path / "files.list").write_text("f0\n")
    sys.path.insert(0, str(tmp_path))
    from paddle_tpu.parallel import mesh as mesh_mod

    prev = mesh_mod.get_mesh()
    try:
        rec = {"module": "tail_provider", "obj": "process",
               "files": str(tmp_path / "files.list")}
        # single-replica mesh: the test job covers every sample
        mesh_mod.get_mesh({"data": 1})
        test_batches = list(_reader_from_data_config(
            rec, batch_size=4, shuffle=False)())
        assert sum(len(b) for b in test_batches) == 10
        train_batches = list(_reader_from_data_config(
            rec, batch_size=4, shuffle=True)())
        assert all(len(b) == 4 for b in train_batches)
        # multi-replica mesh: tails are trimmed to the replica multiple so
        # shard_batch's divisibility enforce can't fire (full batches of a
        # user-chosen size pass through untouched)
        mesh_mod.get_mesh({"data": 4})
        test_batches = list(_reader_from_data_config(
            rec, batch_size=8, shuffle=False)())
        assert [len(b) for b in test_batches] == [8]
    finally:
        mesh_mod.set_mesh(prev)
        sys.path.remove(str(tmp_path))


def test_chunk_evaluator_padding_labels_are_O():
    from paddle_tpu.evaluator import ChunkEvaluator

    ev = ChunkEvaluator(chunk_scheme="IOB", num_chunk_types=1)
    ev.eval_batch(pred=[[0, 1, 2, 2]], label=[[0, 1, -1, -1]])
    res = ev.finish()
    assert res["recall"] == 1.0 and ev.label_total == 1


def test_pnpair_rejects_multi_input():
    import pytest

    from paddle_tpu.evaluator import declare
    from paddle_tpu.trainer_config_helpers.evaluators import pnpair_evaluator

    declare.reset()
    with pytest.raises(ValueError, match="single score input"):
        pnpair_evaluator(input=["a", "b"], label="l", info="q")


def test_detection_map_instantiates_from_spec():
    from paddle_tpu.evaluator import declare, runtime

    declare.reset()
    from paddle_tpu.trainer_config_helpers.evaluators import (
        detection_map_evaluator,
    )

    detection_map_evaluator(input="det", label="gt", name="mAP")
    evs = runtime.build(declare.collect())
    assert evs.bound, "detection_map evaluator failed to instantiate"


def test_detection_map_difficult_gts():
    """evaluate_difficult=False: difficult gts neither count as positives
    nor turn their matched detections into FPs
    (DetectionMAPEvaluator.cpp:106-116,184-185)."""
    from paddle_tpu.evaluator import DetectionMAP

    dets = [[[0, 0.9, 0, 0, 10, 10], [0, 0.8, 20, 20, 30, 30]]]
    gts = [[[0, 0, 0, 10, 10, 1],      # difficult, matched by det 1
            [0, 20, 20, 30, 30, 0]]]   # normal, matched by det 2
    ev = DetectionMAP(evaluate_difficult=False)
    ev.eval_batch(detections=dets, gts=gts)
    assert ev.finish()["detection_map"] == 1.0  # 1 positive, 1 TP

    ev = DetectionMAP(evaluate_difficult=True)
    ev.eval_batch(detections=dets, gts=gts)
    assert ev.finish()["detection_map"] == 1.0  # 2 positives, 2 TPs

    # unmatched difficult gt must not hurt recall
    ev = DetectionMAP(evaluate_difficult=False)
    ev.eval_batch(
        detections=[[[0, 0.9, 0, 0, 10, 10]]],
        gts=[[[0, 0, 0, 10, 10, 0], [0, 50, 50, 60, 60, 1]]])
    assert ev.finish()["detection_map"] == 1.0
