"""Sparse/embedding-parallel path — mirrors the reference's sparse tests
(``test_CompareSparse.cpp``: sparse-vs-dense training equality;
selected_rows_functor tests) on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.ops import selected_rows as sr_ops
from paddle_tpu.parallel import embedding as emb_par
from paddle_tpu.parallel.mesh import make_mesh


class TestSelectedRows:
    def test_to_dense_and_merge(self):
        sr = sr_ops.SelectedRows(
            rows=jnp.asarray([2, 0, 2], jnp.int32),
            values=jnp.asarray([[1., 1.], [2., 2.], [3., 3.]]),
            height=4)
        dense = np.asarray(sr.to_dense())
        np.testing.assert_allclose(dense[2], [4., 4.])
        np.testing.assert_allclose(dense[0], [2., 2.])
        np.testing.assert_allclose(dense[1], 0.0)
        merged = sr_ops.merge_rows(sr)
        d2 = np.asarray(merged.to_dense())
        np.testing.assert_allclose(d2, dense)

    def test_sgd_update_equals_dense(self):
        rs = np.random.RandomState(0)
        table = jnp.asarray(rs.randn(6, 3).astype(np.float32))
        ids = jnp.asarray([1, 4, 1], jnp.int32)
        ct = jnp.asarray(rs.randn(3, 3).astype(np.float32))
        grad = sr_ops.embedding_grad(ids, ct, 6)
        sparse = sr_ops.sgd_update(table, grad, lr=0.1)
        dense = table - 0.1 * grad.to_dense()
        np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                                   rtol=1e-6)

    def test_adagrad_touched_rows_only(self):
        table = jnp.zeros((5, 2))
        accum = jnp.zeros((5, 2))
        grad = sr_ops.SelectedRows(
            rows=jnp.asarray([3, 3], jnp.int32),
            values=jnp.asarray([[1., 0.], [1., 0.]]), height=5)
        new_t, new_a = sr_ops.adagrad_update(table, accum, grad, lr=0.5)
        assert float(new_a[3, 0]) == 4.0  # merged grad 2 -> squared
        assert float(new_t[3, 0]) < 0
        np.testing.assert_allclose(np.asarray(new_t)[[0, 1, 2, 4]], 0.0)
        np.testing.assert_allclose(np.asarray(new_a)[[0, 1, 2, 4]], 0.0)

    def test_momentum_and_decay_on_touch(self):
        table = jnp.ones((4, 2))
        vel = jnp.zeros((4, 2))
        grad = sr_ops.SelectedRows(
            rows=jnp.asarray([1], jnp.int32),
            values=jnp.asarray([[1., 1.]]), height=4)
        t2, v2 = sr_ops.momentum_update(table, vel, grad, lr=0.1, mu=0.9)
        np.testing.assert_allclose(np.asarray(v2)[1], 1.0)
        np.testing.assert_allclose(np.asarray(t2)[1], 0.9)
        np.testing.assert_allclose(np.asarray(t2)[0], 1.0)
        t3 = sr_ops.decay_on_touch(table, grad, l2_rate=0.5, lr=0.1)
        np.testing.assert_allclose(np.asarray(t3)[1], 1.0 - 0.05)
        np.testing.assert_allclose(np.asarray(t3)[2], 1.0)


class TestShardedEmbedding:
    def test_sharded_lookup_matches_dense(self):
        mesh = make_mesh({"model": 4})
        rs = np.random.RandomState(1)
        table = jnp.asarray(rs.randn(16, 5).astype(np.float32))
        ids = jnp.asarray(rs.randint(0, 16, (3, 7)), jnp.int32)
        sharded = emb_par.shard_table(table, mesh)
        got = emb_par.sharded_lookup(sharded, ids, mesh)
        want = jnp.take(table, ids, axis=0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)

    def test_sharded_lookup_grad_matches_dense(self):
        mesh = make_mesh({"model": 4})
        rs = np.random.RandomState(2)
        table = jnp.asarray(rs.randn(8, 3).astype(np.float32))
        ids = jnp.asarray([0, 5, 5, 7], jnp.int32)

        def loss_sharded(t):
            return jnp.sum(emb_par.sharded_lookup(t, ids, mesh) ** 2)

        def loss_dense(t):
            return jnp.sum(jnp.take(t, ids, axis=0) ** 2)

        g1 = jax.grad(loss_sharded)(emb_par.shard_table(table, mesh))
        g2 = jax.grad(loss_dense)(table)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5)


def test_wide_and_deep_learns():
    from paddle_tpu.models.ctr import wide_and_deep_ctr

    cost, predict, _ = wide_and_deep_ctr(
        wide_dim=32, categorical_vocab_sizes=[10, 8], embedding_size=4,
        hidden_sizes=(16,))
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))

    rs = np.random.RandomState(0)

    def corpus():
        def r():
            for _ in range(256):
                wide_ids = rs.randint(0, 32, 3).tolist()
                c0 = int(rs.randint(0, 10))
                c1 = int(rs.randint(0, 8))
                label = int((c0 % 2) ^ (c1 % 2))  # learnable rule
                yield wide_ids, c0, c1, label
        return r

    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    feeding = {"wide_input": 0, "cat_0": 1, "cat_1": 2, "label": 3}
    trainer.train(reader=paddle.reader.batch(corpus(), batch_size=32),
                  num_passes=6, event_handler=handler, feeding=feeding)
    assert costs[-1] < costs[0] * 0.6, (costs[0], costs[-1])
    # embedding tables exist and carry the EP sharding annotation
    spec = parameters.spec("emb_0")
    assert spec.sharding == ("model", None)


class TestShardedEmbeddingClass:
    """The production ShardedEmbedding wrapper: vocab padding, both
    lowering paths, clamp-and-zero, exact duplicate-id gradients."""

    def _emb(self, path, vocab=10, dim=4):
        mesh = make_mesh({"model": 4})
        return emb_par.ShardedEmbedding(vocab=vocab, dim=dim, mesh=mesh,
                                        path=path)

    def test_layout_math(self):
        emb = self._emb("gspmd")
        assert emb.padded_vocab == 12 and emb.rows_per_shard == 3
        assert emb.total_bytes() == 12 * 4 * 4
        assert emb.per_device_bytes() == 3 * 4 * 4
        assert emb.total_bytes() == 4 * emb.per_device_bytes()

    def test_paths_agree_and_match_dense_oracle(self):
        rs = np.random.RandomState(3)
        dense = jnp.asarray(rs.randn(10, 4).astype(np.float32))
        ids = jnp.asarray([0, 7, 7, 9, 3], jnp.int32)
        want = jnp.take(dense, ids, axis=0)
        outs = {}
        for path in ("gspmd", "shard_map"):
            emb = self._emb(path)
            table = emb.place(dense)
            assert table.shape == (12, 4)
            outs[path] = np.asarray(emb.lookup(table, ids))
            np.testing.assert_allclose(outs[path], np.asarray(want),
                                       rtol=1e-6)
        # GL-P-COLL-style path agreement: same numbers both lowerings
        np.testing.assert_array_equal(outs["gspmd"], outs["shard_map"])

    def test_out_of_vocab_ids_clamp_and_zero(self):
        """Ids outside the LOGICAL vocab (including ids that would land in
        the pad rows) read as zero rows and receive zero gradient."""
        rs = np.random.RandomState(4)
        dense = jnp.asarray(rs.randn(10, 4).astype(np.float32))
        # 10, 11 fall in the pad rows; -1 and 99 are plain out-of-range
        ids = jnp.asarray([2, 10, 11, -1, 99], jnp.int32)
        for path in ("gspmd", "shard_map"):
            emb = self._emb(path)
            table = emb.place(dense)
            got = np.asarray(emb.lookup(table, ids))
            np.testing.assert_allclose(got[0], np.asarray(dense)[2],
                                       rtol=1e-6)
            np.testing.assert_array_equal(got[1:], 0.0)

            def loss(t):
                return jnp.sum(emb.lookup(t, ids) ** 2)

            g = np.asarray(jax.grad(loss)(table))
            # only the one valid id gets gradient; pad rows get none
            assert np.any(g[2] != 0)
            mask = np.ones(12, bool)
            mask[2] = False
            np.testing.assert_array_equal(g[mask], 0.0)

    def test_duplicate_ids_exact_scatter_add_grads(self):
        """Duplicate ids accumulate gradients exactly — compared against
        the dense one-device oracle on the same loss, both paths."""
        rs = np.random.RandomState(5)
        dense = jnp.asarray(rs.randn(10, 4).astype(np.float32))
        ids = jnp.asarray([7, 7, 7, 1, 1, 0], jnp.int32)
        ct = jnp.asarray(rs.randn(6, 4).astype(np.float32))

        def oracle(t):
            return jnp.sum(jnp.take(t, ids, axis=0) * ct)

        g_dense = np.asarray(jax.grad(oracle)(dense))
        for path in ("gspmd", "shard_map"):
            emb = self._emb(path)
            table = emb.place(dense)

            def loss(t):
                return jnp.sum(emb.lookup(t, ids) * ct)

            g = np.asarray(jax.grad(loss)(table))
            np.testing.assert_allclose(g[:10], g_dense, rtol=1e-5,
                                       atol=1e-6)
            np.testing.assert_array_equal(g[10:], 0.0)


class TestLazySparseOptimizer:
    """The SparseRowMatrix row-lazy contract on SGD/Momentum: rows a batch
    does not touch keep parameter AND slot bit-for-bit, even with weight
    decay on (decay folds only on touch)."""

    def _spec(self, decay=0.25):
        from paddle_tpu.core.parameters import ParamSpec
        from paddle_tpu.layers.attr import ParamAttr

        return ParamSpec(
            name="emb", shape=(8, 4),
            initializer=lambda k, s, d: jnp.zeros(s, d),
            decay_rate=decay, sparse=True,
            attr=ParamAttr(name="emb", sparse_update=True))

    def _grad(self, rs, rows):
        g = np.zeros((8, 4), np.float32)
        for r in rows:
            g[r] = rs.randn(4)
        return jnp.asarray(g)

    def test_momentum_untouched_rows_bit_identical(self):
        from paddle_tpu.optimizer import Momentum

        rs = np.random.RandomState(6)
        spec = self._spec()
        p = jnp.asarray(rs.randn(8, 4).astype(np.float32))
        opt = Momentum(momentum=0.9, learning_rate=0.1)
        state = opt.init({"emb": p}, {"emb": spec})
        # step 1 touches {1, 3} -> their velocity becomes nonzero
        p1, state = opt.apply({"emb": self._grad(rs, [1, 3])}, {"emb": p},
                              state, {"emb": spec})
        # step 2 touches {3, 5}: row 1 must keep param AND velocity
        p2, state2 = opt.apply({"emb": self._grad(rs, [3, 5])}, p1,
                               state, {"emb": spec})
        v1 = np.asarray(state["slots"]["emb"]["velocity"])
        v2 = np.asarray(state2["slots"]["emb"]["velocity"])
        np.testing.assert_array_equal(np.asarray(p2["emb"])[1],
                                      np.asarray(p1["emb"])[1])
        np.testing.assert_array_equal(v2[1], v1[1])
        assert np.any(v1[1] != 0)  # row 1 carried real momentum to freeze
        # touched rows DID move (decay + momentum on touch)
        assert np.any(np.asarray(p2["emb"])[3] != np.asarray(p1["emb"])[3])
        assert np.any(np.asarray(p2["emb"])[5] != np.asarray(p1["emb"])[5])

    def test_sgd_untouched_rows_bit_identical(self):
        from paddle_tpu.optimizer import SGD

        rs = np.random.RandomState(7)
        spec = self._spec()
        p = jnp.asarray(rs.randn(8, 4).astype(np.float32))
        opt = SGD(learning_rate=0.1)
        state = opt.init({"emb": p}, {"emb": spec})
        p1, _ = opt.apply({"emb": self._grad(rs, [2])}, {"emb": p}, state,
                          {"emb": spec})
        keep = [r for r in range(8) if r != 2]
        np.testing.assert_array_equal(np.asarray(p1["emb"])[keep],
                                      np.asarray(p)[keep])
        assert np.any(np.asarray(p1["emb"])[2] != np.asarray(p)[2])

    def test_dense_param_still_decays_everywhere(self):
        """A plain dense parameter under the same optimizer still gets the
        global decay fold — laziness is opt-in per ParamAttr."""
        from paddle_tpu.core.parameters import ParamSpec
        from paddle_tpu.optimizer import SGD

        spec = ParamSpec(name="w", shape=(4, 4),
                         initializer=lambda k, s, d: jnp.zeros(s, d),
                         decay_rate=0.5)
        p = jnp.ones((4, 4), jnp.float32)
        opt = SGD(learning_rate=0.1)
        state = opt.init({"w": p}, {"w": spec})
        p1, _ = opt.apply({"w": jnp.zeros((4, 4))}, {"w": p}, state,
                          {"w": spec})
        # zero grad but decay still applies to every entry
        np.testing.assert_allclose(np.asarray(p1["w"]), 0.95, rtol=1e-6)


def test_ctr_vocab_exceeds_one_device_budget():
    """The tentpole end-to-end: a wide&deep CTR whose embedding tables do
    NOT fit one device's HBM budget trains on a {data:2, model:4} mesh
    because row-sharding splits each table 4 ways.  Asserted BOTH ways:
    runtime census over addressable shards and the static GL-P-MEM byte
    model."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.analysis import memory as mem
    from paddle_tpu.layers import base
    from paddle_tpu.models.ctr import wide_and_deep_ctr
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    vocab, emb_dim, wide_dim, bs = 6000, 32, 16, 16
    cost, _, _ = wide_and_deep_ctr(
        wide_dim=wide_dim, categorical_vocab_sizes=[vocab, vocab],
        embedding_size=emb_dim, hidden_sizes=(16,), pad_vocab_to=4)
    topo = paddle.topology.Topology(cost)
    params0 = paddle.parameters.create(topo).as_dict()
    specs = {s.name: s for s in topo.param_specs()}

    from paddle_tpu.parallel import mesh as mesh_mod
    ctx = mesh_mod.MeshContext(
        mesh=mesh_mod.make_mesh({"data": 2, "model": 4}))
    params = ctx.place_params(
        {k: jnp.array(v) for k, v in params0.items()}, specs)

    emb_names = sorted(n for n in params if n.startswith("emb_"))
    assert len(emb_names) == 2
    # vocab 6000 pads to 6000 (already % 4) — tables [6000, 32] f32
    table_total = sum(int(params[n].size) * params[n].dtype.itemsize
                     for n in emb_names)
    assert table_total == 2 * 6000 * emb_dim * 4

    # the budget one device gets: LESS than the tables want replicated,
    # MORE than the sharded layout needs
    budget = table_total * 2 // 3

    # (1) runtime census: bytes device 0 actually holds
    dev0 = ctx.mesh.devices.flat[0]
    census = 0
    for n, v in params.items():
        for sh in v.addressable_shards:
            if sh.device == dev0:
                census += int(np.prod(sh.data.shape)) * v.dtype.itemsize
    assert census < budget < table_total, (census, budget, table_total)
    # each table's shard on dev0 is exactly rows/4
    for n in emb_names:
        shard0 = [s for s in params[n].addressable_shards
                  if s.device == dev0]
        assert len(shard0) == 1 and shard0[0].data.shape == (1500, emb_dim)

    # (2) static GL-P-MEM byte model agrees without touching devices
    base_specs = {
        n: (P(*specs[n].sharding) if specs[n].sharding else P())
        for n in params
    }
    static_bytes = mem.params_bytes_per_device(params, ctx.mesh, base_specs)
    assert static_bytes < budget < mem.tree_bytes(params)
    assert static_bytes == census

    # and it trains: two steps, finite cost, tables stay sharded
    opt = Momentum(momentum=0.9, learning_rate=0.05)
    opt_state = ctx.replicate(opt.init(params, specs))
    states = ctx.replicate(topo.init_states())
    step = build_train_step(topo, opt, mesh=ctx)
    rs = np.random.default_rng(9)
    for _ in range(2):
        wide = np.zeros((bs, wide_dim), np.float32)
        for r in range(bs):
            wide[r, rs.integers(0, wide_dim, size=3)] = 1.0
        feed = ctx.shard_batch({
            "wide_input": jnp.asarray(wide),
            "cat_0": jnp.asarray(rs.integers(0, vocab, size=(bs,))),
            "cat_1": jnp.asarray(rs.integers(0, vocab, size=(bs,))),
            "label": jnp.asarray(rs.integers(0, 2, size=(bs,))),
        })
        params, opt_state, states, cost_v, _ = step(
            params, opt_state, states, feed, jax.random.key(0))
    assert np.isfinite(float(cost_v))
    post = 0
    for n in emb_names:
        for sh in params[n].addressable_shards:
            if sh.device == dev0:
                post += int(np.prod(sh.data.shape)) * params[n].dtype.itemsize
    assert post == table_total // 4  # still sharded after the step


def test_ctr_serving_routes_through_dense_batcher():
    """CTR inference behind DenseBatcher.from_inference — the serving leg
    of the train->serve loop for the sharded-embedding model."""
    from paddle_tpu.layers import base
    from paddle_tpu.models.ctr import wide_and_deep_ctr
    from paddle_tpu.serving.dense import DenseBatcher

    base.reset_name_counters()
    cost, predict, _ = wide_and_deep_ctr(
        wide_dim=16, categorical_vocab_sizes=[12, 10], embedding_size=4,
        hidden_sizes=(8,), pad_vocab_to=4)
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    feeding = {"wide_input": 0, "cat_0": 1, "cat_1": 2}
    batcher = DenseBatcher.from_inference(
        predict, parameters, feeding=feeding, max_batch=8, max_wait_ms=20.0)
    try:
        rows = [([i % 16, (2 * i) % 16], i % 12, i % 10) for i in range(5)]
        pendings = [batcher.submit(r) for r in rows]
        outs = np.stack([p.result(30.0) for p in pendings])
        assert outs.shape[0] == 5
        assert np.all((outs >= 0.0) & (outs <= 1.0))
        # batching must be transparent: same numbers as direct inference
        from paddle_tpu.trainer.inference import Inference
        direct = np.asarray(Inference(predict, parameters).infer(
            rows, feeding=feeding))
        np.testing.assert_allclose(outs.reshape(direct.shape), direct,
                                   rtol=1e-6, atol=1e-6)
    finally:
        batcher.close()
