"""Sparse/embedding-parallel path — mirrors the reference's sparse tests
(``test_CompareSparse.cpp``: sparse-vs-dense training equality;
selected_rows_functor tests) on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.ops import selected_rows as sr_ops
from paddle_tpu.parallel import embedding as emb_par
from paddle_tpu.parallel.mesh import make_mesh


class TestSelectedRows:
    def test_to_dense_and_merge(self):
        sr = sr_ops.SelectedRows(
            rows=jnp.asarray([2, 0, 2], jnp.int32),
            values=jnp.asarray([[1., 1.], [2., 2.], [3., 3.]]),
            height=4)
        dense = np.asarray(sr.to_dense())
        np.testing.assert_allclose(dense[2], [4., 4.])
        np.testing.assert_allclose(dense[0], [2., 2.])
        np.testing.assert_allclose(dense[1], 0.0)
        merged = sr_ops.merge_rows(sr)
        d2 = np.asarray(merged.to_dense())
        np.testing.assert_allclose(d2, dense)

    def test_sgd_update_equals_dense(self):
        rs = np.random.RandomState(0)
        table = jnp.asarray(rs.randn(6, 3).astype(np.float32))
        ids = jnp.asarray([1, 4, 1], jnp.int32)
        ct = jnp.asarray(rs.randn(3, 3).astype(np.float32))
        grad = sr_ops.embedding_grad(ids, ct, 6)
        sparse = sr_ops.sgd_update(table, grad, lr=0.1)
        dense = table - 0.1 * grad.to_dense()
        np.testing.assert_allclose(np.asarray(sparse), np.asarray(dense),
                                   rtol=1e-6)

    def test_adagrad_touched_rows_only(self):
        table = jnp.zeros((5, 2))
        accum = jnp.zeros((5, 2))
        grad = sr_ops.SelectedRows(
            rows=jnp.asarray([3, 3], jnp.int32),
            values=jnp.asarray([[1., 0.], [1., 0.]]), height=5)
        new_t, new_a = sr_ops.adagrad_update(table, accum, grad, lr=0.5)
        assert float(new_a[3, 0]) == 4.0  # merged grad 2 -> squared
        assert float(new_t[3, 0]) < 0
        np.testing.assert_allclose(np.asarray(new_t)[[0, 1, 2, 4]], 0.0)
        np.testing.assert_allclose(np.asarray(new_a)[[0, 1, 2, 4]], 0.0)

    def test_momentum_and_decay_on_touch(self):
        table = jnp.ones((4, 2))
        vel = jnp.zeros((4, 2))
        grad = sr_ops.SelectedRows(
            rows=jnp.asarray([1], jnp.int32),
            values=jnp.asarray([[1., 1.]]), height=4)
        t2, v2 = sr_ops.momentum_update(table, vel, grad, lr=0.1, mu=0.9)
        np.testing.assert_allclose(np.asarray(v2)[1], 1.0)
        np.testing.assert_allclose(np.asarray(t2)[1], 0.9)
        np.testing.assert_allclose(np.asarray(t2)[0], 1.0)
        t3 = sr_ops.decay_on_touch(table, grad, l2_rate=0.5, lr=0.1)
        np.testing.assert_allclose(np.asarray(t3)[1], 1.0 - 0.05)
        np.testing.assert_allclose(np.asarray(t3)[2], 1.0)


class TestShardedEmbedding:
    def test_sharded_lookup_matches_dense(self):
        mesh = make_mesh({"model": 4})
        rs = np.random.RandomState(1)
        table = jnp.asarray(rs.randn(16, 5).astype(np.float32))
        ids = jnp.asarray(rs.randint(0, 16, (3, 7)), jnp.int32)
        sharded = emb_par.shard_table(table, mesh)
        got = emb_par.sharded_lookup(sharded, ids, mesh)
        want = jnp.take(table, ids, axis=0)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6)

    def test_sharded_lookup_grad_matches_dense(self):
        mesh = make_mesh({"model": 4})
        rs = np.random.RandomState(2)
        table = jnp.asarray(rs.randn(8, 3).astype(np.float32))
        ids = jnp.asarray([0, 5, 5, 7], jnp.int32)

        def loss_sharded(t):
            return jnp.sum(emb_par.sharded_lookup(t, ids, mesh) ** 2)

        def loss_dense(t):
            return jnp.sum(jnp.take(t, ids, axis=0) ** 2)

        g1 = jax.grad(loss_sharded)(emb_par.shard_table(table, mesh))
        g2 = jax.grad(loss_dense)(table)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5)


def test_wide_and_deep_learns():
    from paddle_tpu.models.ctr import wide_and_deep_ctr

    cost, predict, _ = wide_and_deep_ctr(
        wide_dim=32, categorical_vocab_sizes=[10, 8], embedding_size=4,
        hidden_sizes=(16,))
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=0.02))

    rs = np.random.RandomState(0)

    def corpus():
        def r():
            for _ in range(256):
                wide_ids = rs.randint(0, 32, 3).tolist()
                c0 = int(rs.randint(0, 10))
                c1 = int(rs.randint(0, 8))
                label = int((c0 % 2) ^ (c1 % 2))  # learnable rule
                yield wide_ids, c0, c1, label
        return r

    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    feeding = {"wide_input": 0, "cat_0": 1, "cat_1": 2, "label": 3}
    trainer.train(reader=paddle.reader.batch(corpus(), batch_size=32),
                  num_passes=6, event_handler=handler, feeding=feeding)
    assert costs[-1] < costs[0] * 0.6, (costs[0], costs[-1])
    # embedding tables exist and carry the EP sharding annotation
    spec = parameters.spec("emb_0")
    assert spec.sharding == ("model", None)
