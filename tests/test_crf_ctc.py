"""CRF + CTC numerics — mirrors the reference's compare-two-implementations
test strategy (``test_LinearChainCRF.cpp``, ``test_CTCLayerGrad.cpp``,
``test_WarpCTCLayer.cpp``): brute-force enumeration for CRF, torch's
``ctc_loss`` as the independent oracle for CTC."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.ops import crf as crf_ops
from paddle_tpu.ops import ctc as ctc_ops


def _brute_force_crf(x, w, length):
    """Enumerate all label paths for one sequence. x: [T, C], w: [C+2, C]."""
    a, b, trans = w[0], w[1], w[2:]
    t, c = length, x.shape[1]
    scores = {}
    for path in itertools.product(range(c), repeat=t):
        s = a[path[0]] + b[path[-1]] + sum(x[i, path[i]] for i in range(t))
        s += sum(trans[path[i], path[i + 1]] for i in range(t - 1))
        scores[path] = s
    logz = np.logaddexp.reduce(np.array(list(scores.values())))
    best = max(scores, key=scores.get)
    return scores, logz, best


class TestCRF:
    def setup_method(self, _):
        rs = np.random.RandomState(0)
        self.c = 3
        self.t = 4
        self.x = rs.randn(2, self.t, self.c).astype(np.float32)
        self.w = rs.randn(self.c + 2, self.c).astype(np.float32) * 0.5
        self.lengths = np.array([4, 3], np.int32)
        self.emis = SequenceBatch(jnp.asarray(self.x),
                                  jnp.asarray(self.lengths))

    def test_log_partition_matches_brute_force(self):
        got = np.asarray(crf_ops.crf_log_partition(self.emis,
                                                   jnp.asarray(self.w)))
        for i in range(2):
            _, logz, _ = _brute_force_crf(self.x[i], self.w, self.lengths[i])
            np.testing.assert_allclose(got[i], logz, rtol=1e-5)

    def test_path_score_and_nll(self):
        rs = np.random.RandomState(1)
        y = rs.randint(0, self.c, (2, self.t)).astype(np.int32)
        labels = SequenceBatch(jnp.asarray(y), jnp.asarray(self.lengths))
        score = np.asarray(crf_ops.crf_path_score(self.emis, labels,
                                                  jnp.asarray(self.w)))
        nll = np.asarray(crf_ops.crf_nll(self.emis, labels,
                                         jnp.asarray(self.w)))
        for i in range(2):
            scores, logz, _ = _brute_force_crf(self.x[i], self.w,
                                               self.lengths[i])
            want = scores[tuple(y[i, :self.lengths[i]])]
            np.testing.assert_allclose(score[i], want, rtol=1e-5)
            np.testing.assert_allclose(nll[i], logz - want, rtol=1e-4)
            assert nll[i] > 0  # -log p, p < 1

    def test_viterbi_matches_brute_force(self):
        path = crf_ops.crf_decode(self.emis, jnp.asarray(self.w))
        got = np.asarray(path.data)
        for i in range(2):
            _, _, best = _brute_force_crf(self.x[i], self.w, self.lengths[i])
            np.testing.assert_array_equal(got[i, :self.lengths[i]],
                                          np.array(best))

    def test_crf_grad_finite(self):
        rs = np.random.RandomState(1)
        y = rs.randint(0, self.c, (2, self.t)).astype(np.int32)
        labels = SequenceBatch(jnp.asarray(y), jnp.asarray(self.lengths))

        def loss(w, x):
            return jnp.mean(crf_ops.crf_nll(
                SequenceBatch(x, self.emis.length), labels, w))

        gw, gx = jax.grad(loss, argnums=(0, 1))(jnp.asarray(self.w),
                                                jnp.asarray(self.x))
        assert np.all(np.isfinite(np.asarray(gw)))
        assert np.all(np.isfinite(np.asarray(gx)))
        # padded timestep of row 1 must not receive gradient
        np.testing.assert_allclose(np.asarray(gx)[1, 3], 0.0, atol=1e-7)


class TestCTC:
    def _torch_ctc(self, log_probs, in_lens, labels, lbl_lens, blank):
        import torch
        import torch.nn.functional as F

        lp = torch.tensor(np.asarray(log_probs)).permute(1, 0, 2)  # [T,B,V]
        return F.ctc_loss(
            lp, torch.tensor(np.asarray(labels)),
            torch.tensor(np.asarray(in_lens)),
            torch.tensor(np.asarray(lbl_lens)),
            blank=blank, reduction="none", zero_infinity=False).numpy()

    @pytest.mark.parametrize("blank", [0, 4])
    def test_matches_torch(self, blank):
        rs = np.random.RandomState(2)
        b, t, v, l = 3, 7, 5, 3
        logits = rs.randn(b, t, v).astype(np.float32)
        log_probs = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
        in_lens = np.array([7, 5, 6], np.int32)
        lbl_lens = np.array([3, 2, 1], np.int32)
        labels = np.zeros((b, l), np.int32)
        for i in range(b):
            choices = [k for k in range(v) if k != blank]
            labels[i, :lbl_lens[i]] = rs.choice(choices, lbl_lens[i])
        got = np.asarray(ctc_ops.ctc_loss(
            log_probs, jnp.asarray(in_lens), jnp.asarray(labels),
            jnp.asarray(lbl_lens), blank=blank))
        want = self._torch_ctc(log_probs, in_lens, labels, lbl_lens, blank)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_grad_matches_torch(self):
        import torch
        import torch.nn.functional as F

        rs = np.random.RandomState(5)
        b, t, v, l = 2, 6, 4, 2
        logits = rs.randn(b, t, v).astype(np.float32)
        in_lens = np.array([6, 4], np.int32)
        lbl_lens = np.array([2, 1], np.int32)
        labels = np.array([[1, 2], [3, 0]], np.int32)

        def loss_jax(lg):
            lp = jax.nn.log_softmax(lg, axis=-1)
            return jnp.sum(ctc_ops.ctc_loss(
                lp, jnp.asarray(in_lens), jnp.asarray(labels),
                jnp.asarray(lbl_lens), blank=0))

        g_jax = np.asarray(jax.grad(loss_jax)(jnp.asarray(logits)))

        lg_t = torch.tensor(logits, requires_grad=True)
        lp_t = F.log_softmax(lg_t, dim=-1).permute(1, 0, 2)
        loss_t = F.ctc_loss(lp_t, torch.tensor(labels),
                            torch.tensor(in_lens), torch.tensor(lbl_lens),
                            blank=0, reduction="sum")
        loss_t.backward()
        np.testing.assert_allclose(g_jax, lg_t.grad.numpy(), rtol=1e-3,
                                   atol=1e-4)

    def test_greedy_decode(self):
        # [blank a a blank b] -> "a b"
        v = 3  # blank=0, a=1, b=2
        frames = np.array([[0, 1, 1, 0, 2]], np.int32)
        lp = np.full((1, 5, v), -10.0, np.float32)
        for t, k in enumerate(frames[0]):
            lp[0, t, k] = 0.0
        ids, lens = ctc_ops.ctc_greedy_decode(jnp.asarray(lp),
                                              jnp.asarray([5]))
        assert int(lens[0]) == 2
        np.testing.assert_array_equal(np.asarray(ids)[0, :2], [1, 2])


def test_crf_layers_end_to_end():
    """crf + crf_decoding layer surface, shared transitions by param name."""
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core.parameters import Parameters
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import data_type
    from paddle_tpu.layers.attr import ParamAttr
    from paddle_tpu.layers.extras import crf, crf_decoding

    c = 4
    feat = layer.data(name="feat", type=data_type.dense_vector_sequence(8))
    emis = layer.fc(input=feat, size=c, act=None, bias_attr=False,
                    name="emission")
    lbl = layer.data(name="lbl", type=data_type.integer_value_sequence(c))
    cost = crf(input=emis, label=lbl, size=c,
               param_attr=ParamAttr(name="crf_w"))
    decode = crf_decoding(input=emis, size=c,
                          param_attr=ParamAttr(name="crf_w"))
    topo = Topology([cost, decode])
    params = Parameters.from_specs(topo.param_specs(),
                                   key=jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    feed = {
        "feat": SequenceBatch(jnp.asarray(rs.randn(2, 5, 8), jnp.float32),
                              jnp.asarray([5, 3])),
        "lbl": SequenceBatch(jnp.asarray(rs.randint(0, c, (2, 5))),
                             jnp.asarray([5, 3])),
    }
    vals, _ = topo.forward(params.as_dict(), {}, feed, is_train=True)
    assert np.isfinite(float(vals[cost.name]))
    path = vals[decode.name]
    assert np.asarray(path.data).shape == (2, 5)
    # one shared transition parameter
    assert sum(1 for s in topo.param_specs() if s.name == "crf_w") == 1
