"""C inference ABI: merge a trained model to one artifact, serve it from a
real C program linked against libpaddle_capi.so, and check the C outputs
equal python-side inference (the reference tests capi via
examples/model_inference + gradient_machine tests)."""

import os
import subprocess

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.build import native_binary
from paddle_tpu.models.lenet import lenet_cost
from paddle_tpu.utils.merge_model import MergedModel, merge_v2_model

_NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def _train_tiny():
    cost, predict, img, label = lenet_cost()
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.SGD(learning_rate=0.01),
    )
    reader = paddle.reader.batch(paddle.dataset.mnist.train(), batch_size=32)
    trainer.train(reader=paddle.reader.firstn(reader, 3), num_passes=1)
    return predict, trainer.parameters


def test_merge_model_python_roundtrip(tmp_path):
    predict, parameters = _train_tiny()
    path = str(tmp_path / "model.tar")
    merge_v2_model(predict, parameters, path)

    samples = [s for _, s in zip(range(6), paddle.dataset.mnist.test()())]
    x = np.stack([s[0] for s in samples]).astype(np.float32)
    ref = paddle.infer(output_layer=predict, parameters=parameters,
                       input=[(s[0],) for s in samples])

    m = MergedModel.from_path(path)
    (probs,) = m.forward(x)
    np.testing.assert_allclose(probs, ref, rtol=1e-5, atol=1e-6)
    # a different batch size through the same artifact (symbolic batch dim)
    (probs2,) = m.forward(x[:2])
    np.testing.assert_allclose(probs2, ref[:2], rtol=1e-5, atol=1e-6)


def test_c_program_serves_model(tmp_path):
    predict, parameters = _train_tiny()
    model = str(tmp_path / "model.tar")
    merge_v2_model(predict, parameters, model)

    samples = [s for _, s in zip(range(4), paddle.dataset.mnist.test()())]
    x = np.stack([s[0] for s in samples]).astype("<f4")
    ref = paddle.infer(output_layer=predict, parameters=parameters,
                       input=[(s[0],) for s in samples])

    exe = native_binary("capi_infer")

    pypath = os.path.dirname(_NATIVE) + os.pathsep + \
        os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=pypath)
    out = subprocess.run(
        [exe, model, str(x.shape[1]), str(x.shape[0]), "--use_cpu"],
        input=x.tobytes(), stdout=subprocess.PIPE, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stdout[-2000:]
    got = np.array([[float(v) for v in line.split()]
                    for line in out.stdout.decode().strip().splitlines()])
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_shared_param_machines(tmp_path):
    """create_shared_param: shared machines alias ONE loaded artifact (no
    per-machine weight copy) and produce identical outputs; the C-level
    multi-thread serving bench (serve_bench.c) runs green."""
    from paddle_tpu import capi_bridge

    predict, parameters = _train_tiny()
    model = str(tmp_path / "model.tar")
    merge_v2_model(predict, parameters, model)

    with open(model, "rb") as f:
        origin = capi_bridge.create_machine(f.read())
    shared = capi_bridge.create_shared_machine(origin)
    # exact aliasing: one MergedModel object behind both handles
    assert capi_bridge._machines[origin] is capi_bridge._machines[shared]

    x = np.random.default_rng(0).normal(size=(4, 784)).astype("<f4")
    a = capi_bridge.forward(origin, [x.tobytes()], 4)
    b = capi_bridge.forward(shared, [x.tobytes()], 4)
    assert a[0][0] == b[0][0]  # byte-identical outputs
    capi_bridge.destroy_machine(shared)
    # origin still serves after destroying the shared handle
    assert capi_bridge.forward(origin, [x.tobytes()], 4)[0][0] == a[0][0]
    capi_bridge.destroy_machine(origin)

    exe = native_binary("serve_bench")
    pypath = os.path.dirname(_NATIVE) + os.pathsep + \
        os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=pypath)
    out = subprocess.run([exe, model, "8", "2", "3", "--use_cpu"],
                         stdout=subprocess.PIPE, env=env, timeout=300)
    assert out.returncode == 0, out.stdout[-2000:]
    assert b"threads=2" in out.stdout
