"""C inference ABI: merge a trained model to one artifact, serve it from a
real C program linked against libpaddle_capi.so, and check the C outputs
equal python-side inference (the reference tests capi via
examples/model_inference + gradient_machine tests)."""

import os
import subprocess

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.distributed.build import native_binary
from paddle_tpu.models.lenet import lenet_cost
from paddle_tpu.utils.merge_model import MergedModel, merge_v2_model

_NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")


def _train_tiny():
    cost, predict, img, label = lenet_cost()
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.SGD(learning_rate=0.01),
    )
    reader = paddle.reader.batch(paddle.dataset.mnist.train(), batch_size=32)
    trainer.train(reader=paddle.reader.firstn(reader, 3), num_passes=1)
    return predict, trainer.parameters


def test_merge_model_python_roundtrip(tmp_path):
    predict, parameters = _train_tiny()
    path = str(tmp_path / "model.tar")
    merge_v2_model(predict, parameters, path)

    samples = [s for _, s in zip(range(6), paddle.dataset.mnist.test()())]
    x = np.stack([s[0] for s in samples]).astype(np.float32)
    ref = paddle.infer(output_layer=predict, parameters=parameters,
                       input=[(s[0],) for s in samples])

    m = MergedModel.from_path(path)
    (probs,) = m.forward(x)
    np.testing.assert_allclose(probs, ref, rtol=1e-5, atol=1e-6)
    # a different batch size through the same artifact (symbolic batch dim)
    (probs2,) = m.forward(x[:2])
    np.testing.assert_allclose(probs2, ref[:2], rtol=1e-5, atol=1e-6)


def test_c_program_serves_model(tmp_path):
    predict, parameters = _train_tiny()
    model = str(tmp_path / "model.tar")
    merge_v2_model(predict, parameters, model)

    samples = [s for _, s in zip(range(4), paddle.dataset.mnist.test()())]
    x = np.stack([s[0] for s in samples]).astype("<f4")
    ref = paddle.infer(output_layer=predict, parameters=parameters,
                       input=[(s[0],) for s in samples])

    exe = native_binary("capi_infer")

    pypath = os.path.dirname(_NATIVE) + os.pathsep + \
        os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=pypath)
    out = subprocess.run(
        [exe, model, str(x.shape[1]), str(x.shape[0]), "--use_cpu"],
        input=x.tobytes(), stdout=subprocess.PIPE, env=env, timeout=300,
    )
    assert out.returncode == 0, out.stdout[-2000:]
    got = np.array([[float(v) for v in line.split()]
                    for line in out.stdout.decode().strip().splitlines()])
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_shared_param_machines(tmp_path):
    """create_shared_param: shared machines alias ONE loaded artifact (no
    per-machine weight copy) and produce identical outputs; the C-level
    multi-thread serving bench (serve_bench.c) runs green."""
    from paddle_tpu import capi_bridge

    predict, parameters = _train_tiny()
    model = str(tmp_path / "model.tar")
    merge_v2_model(predict, parameters, model)

    with open(model, "rb") as f:
        origin = capi_bridge.create_machine(f.read())
    shared = capi_bridge.create_shared_machine(origin)
    # exact aliasing: one MergedModel object behind both handles
    assert capi_bridge._machines[origin] is capi_bridge._machines[shared]

    x = np.random.default_rng(0).normal(size=(4, 784)).astype("<f4")
    a = capi_bridge.forward(origin, [x.tobytes()], 4)
    b = capi_bridge.forward(shared, [x.tobytes()], 4)
    assert a[0][0] == b[0][0]  # byte-identical outputs
    capi_bridge.destroy_machine(shared)
    # origin still serves after destroying the shared handle
    assert capi_bridge.forward(origin, [x.tobytes()], 4)[0][0] == a[0][0]
    capi_bridge.destroy_machine(origin)

    exe = native_binary("serve_bench")
    pypath = os.path.dirname(_NATIVE) + os.pathsep + \
        os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=pypath)
    out = subprocess.run([exe, model, "8", "2", "3", "--use_cpu"],
                         stdout=subprocess.PIPE, env=env, timeout=300)
    assert out.returncode == 0, out.stdout[-2000:]
    assert b"threads=2" in out.stdout


def test_forward_releases_gil_for_overlap(tmp_path):
    """Decides the serving thread-overlap question BY CONSTRUCTION
    (VERDICT r4 #9): during ``MergedModel.forward`` — the exact call the
    C ABI's ``paddle_gradient_machine_forward`` lands in — the GIL is
    released by jaxlib's PJRT execute, so a concurrent thread makes
    Python progress while the device computes.  A 1 kHz ticker thread
    heartbeats through a multi-forward window; the assertion is on the
    LONGEST inter-heartbeat gap (see the comment below for why a tick
    count cannot discriminate), which is valid on a single-core host
    too."""
    import threading
    import time

    from paddle_tpu.layers import api as layer, base, data_type

    base.reset_name_counters()
    x = layer.data(name="gx", type=data_type.dense_vector(2048))
    h = x
    for _ in range(12):
        h = layer.fc(input=h, size=2048)
    parameters = paddle.parameters.create(paddle.topology.Topology(h))
    path = str(tmp_path / "big.tar")
    merge_v2_model(h, parameters, path)
    m = MergedModel.from_path(path)

    batch = np.random.default_rng(0).normal(
        size=(512, 2048)).astype(np.float32)
    m.forward(batch)  # compile outside the measured window

    stamps: list[float] = []
    stop = threading.Event()

    def ticker():
        while not stop.is_set():
            stamps.append(time.monotonic())
            time.sleep(0.001)

    # one forward's duration, marshalling included — the discriminating
    # statistic below is relative to it
    t0 = time.monotonic()
    m.forward(batch)
    per_fwd = time.monotonic() - t0

    t = threading.Thread(target=ticker, daemon=True)
    t.start()
    time.sleep(0.05)
    t0 = time.monotonic()
    for _ in range(4):
        m.forward(batch)
    t1 = time.monotonic()
    stop.set()
    t.join(timeout=2)

    # Discriminator: the LONGEST gap between ticker heartbeats inside
    # the forward window.  If PJRT held the GIL during device execution,
    # the ticker would starve for one whole execute stretch (most of
    # per_fwd) — interpreter switch intervals cannot preempt a C
    # extension that holds the GIL.  With the release in place, gaps
    # stay at scheduler scale even on one core.  (A mere tick COUNT
    # cannot distinguish these: ticks also accrue in the Python
    # marshalling slices between executes.)
    inside = [s for s in stamps if t0 - 0.002 <= s <= t1]
    if per_fwd < 0.05:
        import pytest

        pytest.skip(f"forward too fast ({per_fwd*1e3:.0f} ms) to "
                    "discriminate GIL starvation on this host")
    assert len(inside) >= 3, (len(stamps), per_fwd)
    gaps = [b - a for a, b in zip(inside, inside[1:])]
    max_gap = max(gaps + [t1 - inside[-1], inside[0] - t0])
    assert max_gap < 0.6 * per_fwd, (max_gap, per_fwd)
