"""Fused Pallas LSTM/GRU sequence kernels vs the lax.scan cells.

The kernels (ops/pallas/{lstm,gru}.py) are the hand-kernel-class analog of
the reference's ``hl_lstm_parallel_forward`` (hl_cuda_lstm.cu:334) and
``KeGruForwardUnit`` (hl_gpu_gru.cuh:28).  On CPU they run in interpret
mode; these tests pin forward and gradient equality against the scan
implementations for ragged batches, peepholes, and both directions.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.ops import rnn


@pytest.fixture
def ragged(rng_np):
    B, T, D = 4, 7, 8
    lens = np.asarray([7, 5, 3, 1], np.int32)
    return B, T, D, jnp.asarray(lens)


def test_lstm_fused_matches_scan_with_peephole(rng_np, ragged):
    B, T, D, lens = ragged
    xw = jnp.asarray(rng_np.normal(size=(B, T, 4 * D)).astype(np.float32) * .4)
    wh = jnp.asarray(rng_np.normal(size=(D, 4 * D)).astype(np.float32) * .3)
    peep = jnp.asarray(rng_np.normal(size=(3 * D,)).astype(np.float32) * .2)
    sb = SequenceBatch(data=xw, length=lens)
    init = rnn.LSTMState(h=jnp.zeros((B, D)), c=jnp.zeros((B, D)))

    def scan_loss(wh, peep, reverse):
        def step(state, xt):
            return rnn.lstm_cell(xt, state, wh, peephole=peep)
        last, ys = rnn._masked_scan(step, sb, init, reverse=reverse)
        return (jnp.sum(ys.h * sb.mask()[:, :, None]) + jnp.sum(last.h)
                + 0.5 * jnp.sum(last.c))

    def fused_loss(wh, peep, reverse):
        ys, last = rnn.lstm_fused(sb, wh, init, peephole=peep,
                                  reverse=reverse)
        return (jnp.sum(ys.data * sb.mask()[:, :, None]) + jnp.sum(last.h)
                + 0.5 * jnp.sum(last.c))

    for reverse in (False, True):
        r = scan_loss(wh, peep, reverse)
        k = fused_loss(wh, peep, reverse)
        assert abs(float(r - k)) < 1e-5, (reverse, float(r), float(k))
        gr = jax.grad(scan_loss, argnums=(0, 1))(wh, peep, reverse)
        gk = jax.grad(fused_loss, argnums=(0, 1))(wh, peep, reverse)
        for a, b in zip(gr, gk):
            np.testing.assert_allclose(np.asarray(a),
                                       np.asarray(b).reshape(a.shape),
                                       rtol=2e-5, atol=2e-5)


def test_lstm_fused_dxw_and_state_grads(rng_np, ragged):
    B, T, D, lens = ragged
    xw = jnp.asarray(rng_np.normal(size=(B, T, 4 * D)).astype(np.float32) * .4)
    wh = jnp.asarray(rng_np.normal(size=(D, 4 * D)).astype(np.float32) * .3)
    init = rnn.LSTMState(h=jnp.asarray(
        rng_np.normal(size=(B, D)).astype(np.float32) * .2),
        c=jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2))
    mask = (np.arange(T)[None] < np.asarray(lens)[:, None])

    def scan_loss(xw_, h0, c0):
        sb = SequenceBatch(data=xw_, length=lens)

        def step(state, xt):
            return rnn.lstm_cell(xt, state, wh)
        last, ys = rnn._masked_scan(
            step, sb, rnn.LSTMState(h=h0, c=c0))
        return jnp.sum(ys.h * jnp.asarray(mask)[:, :, None]) + jnp.sum(last.c)

    def fused_loss(xw_, h0, c0):
        sb = SequenceBatch(data=xw_, length=lens)
        ys, last = rnn.lstm_fused(sb, wh, rnn.LSTMState(h=h0, c=c0))
        return jnp.sum(ys.data * jnp.asarray(mask)[:, :, None]) + jnp.sum(last.c)

    gr = jax.grad(scan_loss, argnums=(0, 1, 2))(xw, init.h, init.c)
    gk = jax.grad(fused_loss, argnums=(0, 1, 2))(xw, init.h, init.c)
    for a, b in zip(gr, gk):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


def test_gru_fused_matches_scan(rng_np, ragged):
    B, T, D, lens = ragged
    xw = jnp.asarray(rng_np.normal(size=(B, T, 3 * D)).astype(np.float32) * .4)
    wh = jnp.asarray(rng_np.normal(size=(D, 2 * D)).astype(np.float32) * .3)
    whc = jnp.asarray(rng_np.normal(size=(D, D)).astype(np.float32) * .3)
    sb = SequenceBatch(data=xw, length=lens)
    init = jnp.zeros((B, D))

    def scan_loss(wh, whc, xw_, reverse):
        sbx = SequenceBatch(data=xw_, length=lens)

        def step(h, xt):
            return rnn.gru_cell(xt, h, wh, whc)
        last, ys = rnn._masked_scan(step, sbx, init, reverse=reverse)
        return jnp.sum(ys * sbx.mask()[:, :, None]) + jnp.sum(last)

    def fused_loss(wh, whc, xw_, reverse):
        sbx = SequenceBatch(data=xw_, length=lens)
        ys, last = rnn.gru_fused(sbx, wh, whc, init, reverse=reverse)
        return jnp.sum(ys.data * sbx.mask()[:, :, None]) + jnp.sum(last)

    for reverse in (False, True):
        r = scan_loss(wh, whc, xw, reverse)
        k = fused_loss(wh, whc, xw, reverse)
        assert abs(float(r - k)) < 1e-5
        gr = jax.grad(scan_loss, argnums=(0, 1, 2))(wh, whc, xw, reverse)
        gk = jax.grad(fused_loss, argnums=(0, 1, 2))(wh, whc, xw, reverse)
        for a, b in zip(gr, gk):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-5)


def test_cast_for_matmul_mixed_pair_stays_narrow():
    """Under the f32 default, a mixed f32/bf16 operand pair (only possible
    under an explicit mixed-precision policy) must resolve to bf16 —
    promoting to f32+HIGHEST silently doubled the NMT step (measured
    11.8 -> 23.4 ms on a v5e)."""
    from paddle_tpu.core import dtype as dt
    from paddle_tpu.core import flags

    assert flags.get("bf16") is False  # the default under test
    a = jnp.ones((4, 4), jnp.float32)
    b = jnp.ones((4, 4), jnp.bfloat16)
    ca, cb = dt.cast_for_matmul(a, b)
    assert ca.dtype == jnp.bfloat16 and cb.dtype == jnp.bfloat16
    # pure f32 stays f32 (reference numerics)
    ca, cb = dt.cast_for_matmul(a, jnp.ones((4, 4), jnp.float32))
    assert ca.dtype == jnp.float32 and cb.dtype == jnp.float32
    # and f32 pairs request true-f32 MXU passes
    assert dt.dot_precision(ca, cb) == jax.lax.Precision.HIGHEST
    assert dt.dot_precision(a, b) is None


def test_fused_falls_back_over_vmem_budget(monkeypatch):
    """Oversized weights (or f16) must take the lax.scan path instead of
    failing Mosaic compilation — and produce identical results."""
    import paddle_tpu.ops.rnn as rnn_mod

    B, T, D = 2, 5, 8
    g = np.random.default_rng(1)
    xw = jnp.asarray(g.normal(size=(B, T, 4 * D)).astype(np.float32) * .3)
    wh = jnp.asarray(g.normal(size=(D, 4 * D)).astype(np.float32) * .3)
    sb = SequenceBatch(data=xw, length=jnp.asarray([5, 3], np.int32))
    init = rnn_mod.LSTMState(h=jnp.zeros((B, D)), c=jnp.zeros((B, D)))
    want, _ = rnn_mod.lstm_fused(sb, wh, init)

    calls = {"kernel": 0}
    from paddle_tpu.ops.pallas import lstm as klstm
    orig = klstm.lstm_seq
    def counting(*a, **k):
        calls["kernel"] += 1
        return orig(*a, **k)
    monkeypatch.setattr(klstm, "lstm_seq", counting)
    monkeypatch.setattr(rnn_mod, "_fused_fits", lambda *a: False)
    got, _ = rnn_mod.lstm_fused(sb, wh, init)
    assert calls["kernel"] == 0, "fallback still invoked the kernel"
    np.testing.assert_allclose(np.asarray(want.data), np.asarray(got.data),
                               rtol=2e-5, atol=2e-5)
    # f16 weights are rejected by the budget check itself
    assert not rnn_mod._fused_fits(2, 8, 4, wh.astype(jnp.float16))


def test_gru_group_fused_fast_path_matches_cell_scan(rng_np):
    """simple_gru/gru_group lowers to the fused GRU kernel (the group
    node's fn is the fused closure) and matches a hand scan of gru_cell
    over the same parameters."""
    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type, networks

    base.reset_name_counters()
    x = layer.data(name="x", type=data_type.dense_vector_sequence(8))
    g = networks.simple_gru(input=x, size=16, name="sg")
    topo = Topology(g)
    grp = [n for n in topo.nodes
           if n.layer_type == "recurrent_layer_group"][0]
    assert grp.fn.__name__ == "fused_fwd"

    params = paddle.parameters.create(topo)
    feed = {"x": SequenceBatch(
        data=rng_np.normal(size=(3, 6, 8)).astype(np.float32),
        length=np.asarray([6, 4, 1], np.int32))}
    vals, _ = topo.forward(params.as_dict(), {}, feed, False,
                           jax.random.key(0))
    got = vals[g.name]

    # hand scan: xw = the transform mixed layer's output; w from the group
    xw = vals["sg_transform"]
    wname = grp.param_specs[0].name
    w = params[wname]
    bias = [s.name for s in grp.param_specs if "bias" in s.name]

    def step(h, xt):
        xt = xt + (params[bias[0]] if bias else 0.0)
        return rnn.gru_cell(xt, h, jnp.asarray(w[:, :32]),
                            jnp.asarray(w[:, 32:]))

    last, ys = rnn._masked_scan(step, xw, jnp.zeros((3, 16)))
    np.testing.assert_allclose(np.asarray(got.data), np.asarray(ys),
                               rtol=2e-5, atol=2e-5)


# -- fast kernel-vs-in-module-reference parity (the check_kernel_parity
# contract: small shapes, interpret mode, forward + vjp — kernel coverage
# no longer rides the slow CRNN convergence test) ----------------------------


def test_lstm_seq_matches_reference_fwd_and_vjp(rng_np):
    from paddle_tpu.ops.pallas.lstm import lstm_seq, lstm_seq_reference

    B, T, D = 2, 4, 8
    xw = jnp.asarray(rng_np.normal(size=(B, T, 4 * D)).astype(np.float32) * .4)
    wh = jnp.asarray(rng_np.normal(size=(D, 4 * D)).astype(np.float32) * .3)
    peep = jnp.asarray(rng_np.normal(size=(3, D)).astype(np.float32) * .2)
    mask = jnp.asarray((np.arange(T)[None] <
                        np.asarray([4, 2])[:, None]).astype(np.float32))
    h0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)
    c0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)

    for reverse in (False, True):
        def k_loss(xw, wh, peep, h0, c0):
            hs, (hT, cT) = lstm_seq(xw, mask, wh, peep, h0, c0, reverse,
                                    True)
            return (jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)
                    + 0.5 * jnp.sum(cT))

        def r_loss(xw, wh, peep, h0, c0):
            hs, (hT, cT) = lstm_seq_reference(xw, mask, wh, peep, h0, c0,
                                              reverse)
            return (jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)
                    + 0.5 * jnp.sum(cT))

        hs_k, (hT_k, cT_k) = lstm_seq(xw, mask, wh, peep, h0, c0, reverse,
                                      True)
        hs_r, (hT_r, cT_r) = lstm_seq_reference(xw, mask, wh, peep, h0, c0,
                                                reverse)
        np.testing.assert_allclose(np.asarray(hs_k), np.asarray(hs_r),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(hT_k), np.asarray(hT_r),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(cT_k), np.asarray(cT_r),
                                   rtol=2e-5, atol=2e-5)
        gk = jax.grad(k_loss, argnums=(0, 1, 2, 3, 4))(xw, wh, peep, h0, c0)
        gr = jax.grad(r_loss, argnums=(0, 1, 2, 3, 4))(xw, wh, peep, h0, c0)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-5)


def test_lstm_seq_fi_matches_reference_fwd_and_vjp(rng_np):
    """Fused-input kernel (x @ W_x inside the time loop) vs the hoisted-
    projection oracle, both remat modes, both directions."""
    from paddle_tpu.ops.pallas.lstm import lstm_seq_fi, lstm_seq_fi_reference

    B, T, E, D = 2, 5, 6, 8
    x = jnp.asarray(rng_np.normal(size=(B, T, E)).astype(np.float32) * .4)
    wx = jnp.asarray(rng_np.normal(size=(E, 4 * D)).astype(np.float32) * .3)
    b = jnp.asarray(rng_np.normal(size=(4 * D,)).astype(np.float32) * .1)
    wh = jnp.asarray(rng_np.normal(size=(D, 4 * D)).astype(np.float32) * .3)
    peep = jnp.asarray(rng_np.normal(size=(3, D)).astype(np.float32) * .2)
    mask = jnp.asarray((np.arange(T)[None] <
                        np.asarray([5, 3])[:, None]).astype(np.float32))
    h0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)
    c0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)

    for reverse in (False, True):
        for remat in (False, True):
            def k_loss(x, wx, b, wh, peep, h0, c0):
                hs, (hT, cT) = lstm_seq_fi(x, mask, wx, b, wh, peep, h0,
                                           c0, reverse, True, remat)
                return (jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)
                        + 0.5 * jnp.sum(cT))

            def r_loss(x, wx, b, wh, peep, h0, c0):
                hs, (hT, cT) = lstm_seq_fi_reference(x, mask, wx, b, wh,
                                                     peep, h0, c0, reverse)
                return (jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)
                        + 0.5 * jnp.sum(cT))

            args = (x, wx, b, wh, peep, h0, c0)
            assert abs(float(k_loss(*args) - r_loss(*args))) < 1e-4
            gk = jax.grad(k_loss, argnums=tuple(range(7)))(*args)
            gr = jax.grad(r_loss, argnums=tuple(range(7)))(*args)
            for a, bb in zip(gk, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                           rtol=3e-5, atol=3e-5)


def test_lstm_seq_remat_bit_identical_to_stored_gates(rng_np):
    """remat is a pure memory knob: the recomputed-gates backward must
    reproduce the stored-residual gradients BIT-identically (the
    recomputation round-trips through the io dtype)."""
    from paddle_tpu.ops.pallas.lstm import lstm_seq

    B, T, D = 2, 5, 8
    xw = jnp.asarray(rng_np.normal(size=(B, T, 4 * D)).astype(np.float32) * .4)
    wh = jnp.asarray(rng_np.normal(size=(D, 4 * D)).astype(np.float32) * .3)
    peep = jnp.asarray(rng_np.normal(size=(3, D)).astype(np.float32) * .2)
    mask = jnp.asarray((np.arange(T)[None] <
                        np.asarray([5, 3])[:, None]).astype(np.float32))
    h0 = jnp.zeros((B, D))
    c0 = jnp.zeros((B, D))

    for reverse in (False, True):
        def grads(remat):
            def loss(xw, wh, peep):
                hs, (hT, cT) = lstm_seq(xw, mask, wh, peep, h0, c0,
                                        reverse, True, remat)
                return jnp.sum(hs * mask[:, :, None]) + jnp.sum(cT)
            return jax.grad(loss, argnums=(0, 1, 2))(xw, wh, peep)

        for a, bb in zip(grads(False), grads(True)):
            assert np.array_equal(np.asarray(a), np.asarray(bb))


def test_bilstm_seq_matches_reference_fwd_and_vjp(rng_np):
    """One-residency bidirectional kernel vs the composed fused-input
    references (fwd + rev), forward and gradients, both remat modes."""
    from paddle_tpu.ops.pallas.lstm import bilstm_seq, bilstm_seq_reference

    B, T, E, D = 2, 5, 6, 8
    x = jnp.asarray(rng_np.normal(size=(B, T, E)).astype(np.float32) * .4)
    mask = jnp.asarray((np.arange(T)[None] <
                        np.asarray([5, 3])[:, None]).astype(np.float32))

    def w(scale, *shape):
        return jnp.asarray(rng_np.normal(size=shape).astype(np.float32)
                           * scale)

    wxf, wxb = w(.3, E, 4 * D), w(.3, E, 4 * D)
    bf, bb_ = w(.1, 4 * D), w(.1, 4 * D)
    whf, whb = w(.3, D, 4 * D), w(.3, D, 4 * D)
    pf, pb = w(.2, 3, D), jnp.zeros((3, D), jnp.float32)
    h0 = w(.2, B, D)
    c0 = w(.2, B, D)

    for remat in (False, True):
        def k_loss(x, wxf, whf, wxb, whb):
            hf, hb, (hTf, cTf), (hTb, cTb) = bilstm_seq(
                x, mask, wxf, bf, whf, pf, wxb, bb_, whb, pb,
                h0, c0, h0, c0, True, remat)
            return (jnp.sum((hf + 2 * hb) * mask[:, :, None])
                    + jnp.sum(hTf) + jnp.sum(cTb))

        def r_loss(x, wxf, whf, wxb, whb):
            hf, hb, (hTf, cTf), (hTb, cTb) = bilstm_seq_reference(
                x, mask, wxf, bf, whf, pf, wxb, bb_, whb, pb,
                h0, c0, h0, c0)
            return (jnp.sum((hf + 2 * hb) * mask[:, :, None])
                    + jnp.sum(hTf) + jnp.sum(cTb))

        args = (x, wxf, whf, wxb, whb)
        assert abs(float(k_loss(*args) - r_loss(*args))) < 1e-4
        gk = jax.grad(k_loss, argnums=tuple(range(5)))(*args)
        gr = jax.grad(r_loss, argnums=tuple(range(5)))(*args)
        for a, bb in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=3e-5, atol=3e-5)


def test_gru_seq_fi_matches_reference_fwd_and_vjp(rng_np):
    from paddle_tpu.ops.pallas.gru import gru_seq_fi, gru_seq_fi_reference

    B, T, E, D = 2, 5, 6, 8
    x = jnp.asarray(rng_np.normal(size=(B, T, E)).astype(np.float32) * .4)
    wx = jnp.asarray(rng_np.normal(size=(E, 3 * D)).astype(np.float32) * .3)
    b = jnp.asarray(rng_np.normal(size=(3 * D,)).astype(np.float32) * .1)
    wh = jnp.asarray(rng_np.normal(size=(D, 2 * D)).astype(np.float32) * .3)
    whc = jnp.asarray(rng_np.normal(size=(D, D)).astype(np.float32) * .3)
    mask = jnp.asarray((np.arange(T)[None] <
                        np.asarray([3, 5])[:, None]).astype(np.float32))
    h0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)

    for reverse in (False, True):
        for remat in (False, True):
            def k_loss(x, wx, b, wh, whc, h0):
                hs, hT = gru_seq_fi(x, mask, wx, b, wh, whc, h0,
                                    reverse, True, remat)
                return jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)

            def r_loss(x, wx, b, wh, whc, h0):
                hs, hT = gru_seq_fi_reference(x, mask, wx, b, wh, whc,
                                              h0, reverse)
                return jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)

            args = (x, wx, b, wh, whc, h0)
            assert abs(float(k_loss(*args) - r_loss(*args))) < 1e-4
            gk = jax.grad(k_loss, argnums=tuple(range(6)))(*args)
            gr = jax.grad(r_loss, argnums=tuple(range(6)))(*args)
            for a, bb in zip(gk, gr):
                np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                           rtol=3e-5, atol=3e-5)


def test_gru_seq_remat_bit_identical_to_stored_gates(rng_np):
    from paddle_tpu.ops.pallas.gru import gru_seq

    B, T, D = 2, 5, 8
    xw = jnp.asarray(rng_np.normal(size=(B, T, 3 * D)).astype(np.float32) * .4)
    wh = jnp.asarray(rng_np.normal(size=(D, 2 * D)).astype(np.float32) * .3)
    whc = jnp.asarray(rng_np.normal(size=(D, D)).astype(np.float32) * .3)
    mask = jnp.asarray((np.arange(T)[None] <
                        np.asarray([5, 3])[:, None]).astype(np.float32))
    h0 = jnp.zeros((B, D))

    for reverse in (False, True):
        def grads(remat):
            def loss(xw, wh, whc):
                hs, hT = gru_seq(xw, mask, wh, whc, h0, reverse, True,
                                 remat)
                return jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)
            return jax.grad(loss, argnums=(0, 1, 2))(xw, wh, whc)

        for a, bb in zip(grads(False), grads(True)):
            assert np.array_equal(np.asarray(a), np.asarray(bb))


def test_bilstm_layer_node_matches_composed_pair(rng_np):
    """layer.bilstm (ops/rnn.bilstm_fused unfused composition on CPU)
    must equal the explicit fc+lstmemory+concat build over the SAME
    parameter values — the checkpoint/ablation contract of the node."""
    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import activation as act_mod
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type

    B, T, E, D = 3, 6, 8, 4
    base.reset_name_counters()
    x = layer.data(name="x", type=data_type.dense_vector_sequence(E))
    node = layer.bilstm(input=x, size=D, name="bi")
    topo = Topology(node)
    params = paddle.parameters.create(topo)
    feed = {"x": SequenceBatch(
        data=rng_np.normal(size=(B, T, E)).astype(np.float32),
        length=np.asarray([6, 4, 1], np.int32))}
    vals, _ = topo.forward(params.as_dict(), {}, feed, False,
                           jax.random.key(0))
    got = vals[node.name]
    assert got.data.shape == (B, T, 2 * D)

    # composed build with the node's weights copied in by name
    base.reset_name_counters()
    x2 = layer.data(name="x", type=data_type.dense_vector_sequence(E))
    fw = layer.lstmemory(input=layer.fc(
        input=x2, size=4 * D, act=act_mod.LinearActivation(),
        name="bi_fw_transform"), name="bi_fw")
    bw = layer.lstmemory(input=layer.fc(
        input=x2, size=4 * D, act=act_mod.LinearActivation(),
        name="bi_bw_transform"), name="bi_bw", reverse=True)
    cat = layer.concat(input=[fw, bw])
    topo2 = Topology(cat)
    params2 = paddle.parameters.create(topo2)
    for n in params2.names():
        params2[n] = np.asarray(params[n])
    vals2, _ = topo2.forward(params2.as_dict(), {}, feed, False,
                             jax.random.key(0))
    np.testing.assert_allclose(np.asarray(got.data),
                               np.asarray(vals2[cat.name].data),
                               rtol=2e-5, atol=2e-5)


def test_gru_seq_matches_reference_fwd_and_vjp(rng_np):
    from paddle_tpu.ops.pallas.gru import gru_seq, gru_seq_reference

    B, T, D = 2, 4, 8
    xw = jnp.asarray(rng_np.normal(size=(B, T, 3 * D)).astype(np.float32) * .4)
    wh = jnp.asarray(rng_np.normal(size=(D, 2 * D)).astype(np.float32) * .3)
    whc = jnp.asarray(rng_np.normal(size=(D, D)).astype(np.float32) * .3)
    mask = jnp.asarray((np.arange(T)[None] <
                        np.asarray([3, 4])[:, None]).astype(np.float32))
    h0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)

    for reverse in (False, True):
        hs_k, hT_k = gru_seq(xw, mask, wh, whc, h0, reverse, True)
        hs_r, hT_r = gru_seq_reference(xw, mask, wh, whc, h0, reverse)
        np.testing.assert_allclose(np.asarray(hs_k), np.asarray(hs_r),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(hT_k), np.asarray(hT_r),
                                   rtol=2e-5, atol=2e-5)

        def k_loss(xw, wh, whc, h0):
            hs, hT = gru_seq(xw, mask, wh, whc, h0, reverse, True)
            return jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)

        def r_loss(xw, wh, whc, h0):
            hs, hT = gru_seq_reference(xw, mask, wh, whc, h0, reverse)
            return jnp.sum(hs * mask[:, :, None]) + jnp.sum(hT)

        gk = jax.grad(k_loss, argnums=(0, 1, 2, 3))(xw, wh, whc, h0)
        gr = jax.grad(r_loss, argnums=(0, 1, 2, 3))(xw, wh, whc, h0)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-5)


def test_bigru_seq_matches_reference_fwd_and_vjp(rng_np):
    """One-residency bidirectional GRU kernel vs the composed
    fused-input references (fwd + rev), forward and gradients, both
    remat modes."""
    from paddle_tpu.ops.pallas.gru import bigru_seq, bigru_seq_reference

    B, T, E, D = 2, 5, 6, 8
    x = jnp.asarray(rng_np.normal(size=(B, T, E)).astype(np.float32) * .4)
    mask = jnp.asarray((np.arange(T)[None] <
                        np.asarray([5, 3])[:, None]).astype(np.float32))

    def w(scale, *shape):
        return jnp.asarray(rng_np.normal(size=shape).astype(np.float32)
                           * scale)

    wxf, wxb = w(.3, E, 3 * D), w(.3, E, 3 * D)
    bf, bb_ = w(.1, 3 * D), w(.1, 3 * D)
    whf, whb = w(.3, D, 2 * D), w(.3, D, 2 * D)
    whcf, whcb = w(.3, D, D), w(.3, D, D)
    h0f, h0b = w(.2, B, D), w(.2, B, D)

    for remat in (False, True):
        def k_loss(x, wxf, whf, whcf, wxb, whb, whcb):
            hf, hb, hTf, hTb = bigru_seq(
                x, mask, wxf, bf, whf, whcf, wxb, bb_, whb, whcb,
                h0f, h0b, True, remat)
            return (jnp.sum((hf + 2 * hb) * mask[:, :, None])
                    + jnp.sum(hTf) + jnp.sum(hTb))

        def r_loss(x, wxf, whf, whcf, wxb, whb, whcb):
            hf, hb, hTf, hTb = bigru_seq_reference(
                x, mask, wxf, bf, whf, whcf, wxb, bb_, whb, whcb,
                h0f, h0b)
            return (jnp.sum((hf + 2 * hb) * mask[:, :, None])
                    + jnp.sum(hTf) + jnp.sum(hTb))

        args = (x, wxf, whf, whcf, wxb, whb, whcb)
        assert abs(float(k_loss(*args) - r_loss(*args))) < 1e-4
        gk = jax.grad(k_loss, argnums=tuple(range(7)))(*args)
        gr = jax.grad(r_loss, argnums=tuple(range(7)))(*args)
        for a, bb in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                       rtol=3e-5, atol=3e-5)


def test_bigru_layer_node_matches_composed_pair(rng_np):
    """layer.bigru (ops/rnn.bigru_fused unfused composition on CPU)
    must equal the explicit fc+grumemory+concat build over the SAME
    parameter values — the checkpoint/ablation contract of the node."""
    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import activation as act_mod
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type

    B, T, E, D = 3, 6, 8, 4
    base.reset_name_counters()
    x = layer.data(name="x", type=data_type.dense_vector_sequence(E))
    node = layer.bigru(input=x, size=D, name="bi")
    topo = Topology(node)
    params = paddle.parameters.create(topo)
    feed = {"x": SequenceBatch(
        data=rng_np.normal(size=(B, T, E)).astype(np.float32),
        length=np.asarray([6, 4, 1], np.int32))}
    vals, _ = topo.forward(params.as_dict(), {}, feed, False,
                           jax.random.key(0))
    got = vals[node.name]
    assert got.data.shape == (B, T, 2 * D)

    # composed build with the node's weights copied in by name
    base.reset_name_counters()
    x2 = layer.data(name="x", type=data_type.dense_vector_sequence(E))
    fw = layer.grumemory(input=layer.fc(
        input=x2, size=3 * D, act=act_mod.LinearActivation(),
        name="bi_fw_transform"), name="bi_fw")
    bw = layer.grumemory(input=layer.fc(
        input=x2, size=3 * D, act=act_mod.LinearActivation(),
        name="bi_bw_transform"), name="bi_bw", reverse=True)
    cat = layer.concat(input=[fw, bw])
    topo2 = Topology(cat)
    params2 = paddle.parameters.create(topo2)
    for n in params2.names():
        params2[n] = np.asarray(params[n])
    vals2, _ = topo2.forward(params2.as_dict(), {}, feed, False,
                             jax.random.key(0))
    np.testing.assert_allclose(np.asarray(got.data),
                               np.asarray(vals2[cat.name].data),
                               rtol=2e-5, atol=2e-5)


def test_lstm_seq_batch_blocked_matches_reference(rng_np):
    """B past _BATCH_BLOCK splits the grid into batch blocks (padded to a
    block multiple); fwd and vjp must match the scan oracle exactly as in
    the single-block regime — including the cross-block dpeep
    accumulator and the remat variant."""
    from paddle_tpu.ops.pallas import lstm as klstm
    from paddle_tpu.ops.pallas.lstm import lstm_seq, lstm_seq_reference

    B, T, D = klstm._BATCH_BLOCK + 44, 4, 8  # 2 blocks, ragged pad
    xw = jnp.asarray(rng_np.normal(size=(B, T, 4 * D)).astype(np.float32) * .4)
    mask = jnp.asarray(
        (rng_np.uniform(size=(B, T)) < 0.8).astype(np.float32)
    ).at[:, 0].set(1.0)
    wh = jnp.asarray(rng_np.normal(size=(D, 4 * D)).astype(np.float32) * .3)
    peep = jnp.asarray(rng_np.normal(size=(3, D)).astype(np.float32) * .2)
    h0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)
    c0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)

    def loss_k(xw, wh, peep, h0, c0, reverse, remat):
        hs, (hT, cT) = lstm_seq(xw, mask, wh, peep, h0, c0, reverse,
                                True, remat)
        return jnp.sum(hs) + jnp.sum(hT) + 0.5 * jnp.sum(cT)

    def loss_r(xw, wh, peep, h0, c0, reverse):
        hs, (hT, cT) = lstm_seq_reference(xw, mask, wh, peep, h0, c0,
                                          reverse)
        return jnp.sum(hs) + jnp.sum(hT) + 0.5 * jnp.sum(cT)

    # (fwd, stored-gates) and (reverse, remat) cover both grid directions
    # and both backward variants without the full 4-combo sweep
    for reverse, remat in ((False, False), (True, True)):
        hs_k, (hT_k, cT_k) = lstm_seq(xw, mask, wh, peep, h0, c0,
                                      reverse, True, remat)
        assert hs_k.shape == (B, T, D) and hT_k.shape == (B, D)
        hs_r, (hT_r, cT_r) = lstm_seq_reference(
            xw, mask, wh, peep, h0, c0, reverse)
        np.testing.assert_allclose(hs_k, hs_r, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(cT_k, cT_r, rtol=2e-5, atol=2e-5)
        gk = jax.grad(loss_k, argnums=(0, 1, 2, 3, 4))(
            xw, wh, peep, h0, c0, reverse, remat)
        gr = jax.grad(loss_r, argnums=(0, 1, 2, 3, 4))(
            xw, wh, peep, h0, c0, reverse)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


def test_gru_seq_batch_blocked_matches_reference(rng_np):
    """GRU sibling of the blocked-batch LSTM test (no cross-block
    accumulator, but the same pad-rows-are-inert contract)."""
    from paddle_tpu.ops.pallas import lstm as klstm
    from paddle_tpu.ops.pallas.gru import gru_seq, gru_seq_reference

    B, T, D = klstm._BATCH_BLOCK + 44, 4, 8
    xw = jnp.asarray(rng_np.normal(size=(B, T, 3 * D)).astype(np.float32) * .4)
    mask = jnp.asarray(
        (rng_np.uniform(size=(B, T)) < 0.8).astype(np.float32)
    ).at[:, 0].set(1.0)
    wh = jnp.asarray(rng_np.normal(size=(D, 2 * D)).astype(np.float32) * .3)
    whc = jnp.asarray(rng_np.normal(size=(D, D)).astype(np.float32) * .3)
    h0 = jnp.asarray(rng_np.normal(size=(B, D)).astype(np.float32) * .2)

    def loss_k(xw, wh, whc, h0, reverse, remat):
        hs, hT = gru_seq(xw, mask, wh, whc, h0, reverse, True, remat)
        return jnp.sum(hs) + jnp.sum(hT)

    def loss_r(xw, wh, whc, h0, reverse):
        hs, hT = gru_seq_reference(xw, mask, wh, whc, h0, reverse)
        return jnp.sum(hs) + jnp.sum(hT)

    for reverse, remat in ((False, False), (True, True)):
        hs_k, hT_k = gru_seq(xw, mask, wh, whc, h0, reverse, True, remat)
        assert hs_k.shape == (B, T, D) and hT_k.shape == (B, D)
        hs_r, hT_r = gru_seq_reference(xw, mask, wh, whc, h0, reverse)
        np.testing.assert_allclose(hs_k, hs_r, rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(hT_k, hT_r, rtol=2e-5, atol=2e-5)
        gk = jax.grad(loss_k, argnums=(0, 1, 2, 3))(
            xw, wh, whc, h0, reverse, remat)
        gr = jax.grad(loss_r, argnums=(0, 1, 2, 3))(
            xw, wh, whc, h0, reverse)
        for a, b in zip(gk, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)
