"""Pallas flash-attention kernel vs the exact einsum path.

The reference's kernel-test pattern is compare-two-implementations
(``paddle/function/FunctionTest.h`` Compare2Function, CPU vs GPU); here the
two implementations are the Pallas kernel (interpret mode on CPU) and the
XLA einsum attention, for both forward values and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import attention as A
from paddle_tpu.ops.pallas import flash_attention


def _qkv(rng_np, b=2, t=100, h=2, d=32):
    mk = lambda: jnp.asarray(rng_np.normal(size=(b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


# block sizes 32 so T=100/70 exercise the multi-block online-softmax
# recurrence (accumulator init/correction/finalize across grid steps)
@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_exact(rng_np, causal):
    q, k, v = _qkv(rng_np)
    mask = A.causal_mask(q.shape[1], k.shape[1]) if causal else None
    ref = A.dot_product_attention(q, k, v, mask=mask)
    out = flash_attention(q, k, v, causal, None, 32, 32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_exact(rng_np, causal):
    q, k, v = _qkv(rng_np, b=1, t=70, h=2, d=16)
    mask = A.causal_mask(q.shape[1], k.shape[1]) if causal else None

    def loss_ref(q, k, v):
        return jnp.sum(A.dot_product_attention(q, k, v, mask=mask) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, 32, 32) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_flash_cross_attention_rectangular(rng_np):
    """nq != nk grids, fwd and bwd (encoder-decoder attention shape)."""
    b, h, d = 2, 2, 16
    q = jnp.asarray(rng_np.normal(size=(b, 37, h, d)).astype(np.float32))
    k = jnp.asarray(rng_np.normal(size=(b, 150, h, d)).astype(np.float32))
    v = jnp.asarray(rng_np.normal(size=(b, 150, h, d)).astype(np.float32))
    ref = A.dot_product_attention(q, k, v)
    out = flash_attention(q, k, v, False, None, 32, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(lambda *a: jnp.sum(A.dot_product_attention(*a) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(
        lambda *a: jnp.sum(flash_attention(*a, False, None, 32, 64) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-4, atol=2e-4)


def test_flash_under_jit_and_vmap(rng_np):
    q, k, v = _qkv(rng_np, b=1, t=64, h=1, d=8)
    jitted = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))
    ref = A.dot_product_attention(q, k, v, mask=A.causal_mask(64, 64))
    np.testing.assert_allclose(np.asarray(jitted(q, k, v)), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # vmap over a leading axis (batches the pallas_call + custom_vjp)
    qs = jnp.stack([q, q * 0.5])
    ks = jnp.stack([k, k])
    vs = jnp.stack([v, v * 2.0])
    outs = jax.vmap(lambda a, b_, c: flash_attention(a, b_, c, True))(qs, ks, vs)
    for i in range(2):
        ref_i = A.dot_product_attention(qs[i], ks[i], vs[i],
                                        mask=A.causal_mask(64, 64))
        np.testing.assert_allclose(np.asarray(outs[i]), np.asarray(ref_i),
                                   rtol=2e-5, atol=2e-5)


def test_softmax_xent_matches_xla():
    """Fused-CE kernel (ops/pallas/softmax_xent.py): forward and backward
    equal the XLA logsumexp formulation (interpret mode on CPU)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.ops.pallas.softmax_xent import softmax_xent

    rng = np.random.default_rng(0)
    n, v = 70, 300
    logits = jnp.asarray(rng.normal(size=(n, v)).astype(np.float32) * 3)
    tgt = jnp.asarray(rng.integers(0, v, size=(n,)))

    nll = softmax_xent(logits, tgt, 32, 128)
    ref = (jax.nn.logsumexp(logits, axis=-1)
           - jnp.take_along_axis(logits, tgt[:, None], axis=-1)[:, 0])
    np.testing.assert_allclose(np.asarray(nll), np.asarray(ref), atol=1e-4)

    g1 = jax.grad(lambda l: jnp.mean(softmax_xent(l, tgt, 32, 128)))(logits)
    g2 = jax.grad(lambda l: jnp.mean(
        jax.nn.logsumexp(l, axis=-1)
        - jnp.take_along_axis(l, tgt[:, None], axis=-1)[:, 0]))(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-5)


def test_flash_matches_in_module_reference(rng_np):
    """flash_attention vs flash_attention_reference (the in-module oracle
    the check_kernel_parity tool audits), fwd + grad, causal and not."""
    from paddle_tpu.ops.pallas.flash_attention import (
        flash_attention_reference,
    )

    q, k, v = _qkv(rng_np, b=1, t=48, h=2, d=16)
    for causal in (False, True):
        ref = flash_attention_reference(q, k, v, causal)
        out = flash_attention(q, k, v, causal, None, 32, 32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        g_r = jax.grad(lambda *a: jnp.sum(
            flash_attention_reference(*a, causal) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        g_k = jax.grad(lambda *a: jnp.sum(
            flash_attention(*a, causal, None, 32, 32) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_k, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)


def test_softmax_xent_matches_in_module_reference():
    from paddle_tpu.ops.pallas.softmax_xent import (
        softmax_xent,
        softmax_xent_reference,
    )

    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(40, 170)).astype(np.float32) * 3)
    tgt = jnp.asarray(rng.integers(0, 170, size=(40,)))
    np.testing.assert_allclose(
        np.asarray(softmax_xent(logits, tgt, 32, 128)),
        np.asarray(softmax_xent_reference(logits, tgt)), atol=1e-4)
    g1 = jax.grad(lambda l: jnp.mean(softmax_xent(l, tgt, 32, 128)))(logits)
    g2 = jax.grad(lambda l: jnp.mean(softmax_xent_reference(l, tgt)))(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-5)
