"""Detection suite (priorbox / multibox_loss / detection_output) + the
registry-parity layer sweep (prelu, multiplex, tensor, selective_fc, ...).
The reference tests detection in test_LayerGrad + DetectionUtil tests;
here: box-math invariants, a planted-box recovery test, and a learning
test for the loss."""

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.layers import api as layer
from paddle_tpu.layers import data_type, detection as det, more
from paddle_tpu.ops import detection as D


def test_iou_encode_decode_roundtrip(rng_np):
    boxes = np.sort(rng_np.random((10, 4)).astype(np.float32), axis=-1)
    priors = np.sort(rng_np.random((7, 4)).astype(np.float32), axis=-1)
    iou = np.asarray(D.iou_matrix(jnp.asarray(boxes), jnp.asarray(boxes)))
    np.testing.assert_allclose(np.diag(iou), 1.0, atol=1e-5)
    assert np.all(iou >= 0) and np.all(iou <= 1 + 1e-6)
    # encode/decode inverse
    m = min(len(boxes), len(priors))
    enc = D.encode_boxes(jnp.asarray(boxes[:m]), jnp.asarray(priors[:m]))
    dec = D.decode_boxes(enc, jnp.asarray(priors[:m]))
    np.testing.assert_allclose(np.asarray(dec), boxes[:m], atol=1e-5)


def test_nms_suppresses_overlaps():
    boxes = jnp.asarray([
        [0.1, 0.1, 0.4, 0.4],
        [0.11, 0.11, 0.41, 0.41],  # heavy overlap with 0
        [0.6, 0.6, 0.9, 0.9],
    ])
    scores = jnp.asarray([0.9, 0.8, 0.7])
    idxs, valid = D.nms(boxes, scores, iou_threshold=0.5, max_out=3)
    kept = [int(i) for i, v in zip(idxs, valid) if bool(v)]
    assert kept == [0, 2]


def test_ssd_pipeline_learns_and_detects():
    """2-class toy SSD on a 4x4 feature map: the loss decreases and
    detection_output recovers a planted box."""
    fm = layer.data(name="feat", type=data_type.dense_vector(4 * 4 * 8),
                    height=4, width=4)
    fm.depth = 8
    priors = det.priorbox(fm, image_size=64, min_size=16,
                          aspect_ratio=(2.0,))
    n_priors = priors.attrs["num_priors"]
    per_cell = n_priors // 16
    from paddle_tpu.layers import activation as act

    loc = layer.fc(input=fm, size=n_priors * 4, act=act.LinearActivation())
    conf = layer.fc(input=fm, size=n_priors * 2, act=act.LinearActivation())
    gt = layer.data(name="gt", type=data_type.dense_vector(2 * 5),
                    height=2, width=5)

    cost = det.multibox_loss(priors, _as_gt(gt, 2), [loc], [conf],
                             num_classes=2)
    out = det.detection_output(priors, [loc], [conf], num_classes=2,
                               keep_top_k=5)

    parameters = paddle.parameters.create(paddle.topology.Topology([cost, out]))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-2),
    )

    rng = np.random.default_rng(0)

    def reader():
        # one object at a fixed location keyed by the feature content
        for _ in range(512):
            which = int(rng.integers(0, 2))
            feat = np.zeros((4, 4, 8), np.float32)
            box = (0.1, 0.1, 0.35, 0.35) if which == 0 else (0.6, 0.55, 0.85, 0.9)
            cell = (1, 1) if which == 0 else (2, 2)
            feat[cell[0], cell[1], :] = 1.0
            feat += rng.normal(0, 0.05, feat.shape)
            g = np.full((2, 5), -1, np.float32)
            g[0] = [1, *box]
            yield feat.reshape(-1), g.reshape(-1)

    feeding = {"feat": 0, "gt": 1}
    costs = []
    trainer.train(reader=paddle.reader.batch(reader, 32), num_passes=10,
                  feeding=feeding,
                  event_handler=lambda e: costs.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    assert costs[-1] < costs[0] * 0.5, (costs[0], costs[-1])

    # inference: detection_output finds the planted box
    feat = np.zeros((4, 4, 8), np.float32)
    feat[1, 1, :] = 1.0
    g = np.full((2, 5), -1, np.float32)
    dets = paddle.infer(output_layer=out, parameters=trainer.parameters,
                        input=[(feat.reshape(-1), g.reshape(-1))],
                        feeding=feeding)
    # reference-shaped rows: [image_id, label, score, xmin, ymin, xmax, ymax]
    dets = np.asarray(dets).reshape(-1, 7)
    best = dets[np.argmax(dets[:, 2])]
    assert best[1] == 1.0  # class 1 detected
    iou = float(D.iou_matrix(
        jnp.asarray(best[None, 3:7]),
        jnp.asarray([[0.1, 0.1, 0.35, 0.35]]))[0, 0])
    assert iou > 0.3, (best, iou)


def _as_gt(gt_layer, g_max):
    """View a dense [B, g*5] feed as [B, g, 5] for multibox_loss."""
    from paddle_tpu.layers.base import LayerOutput, gen_name, raw

    name = gen_name("gt_view")

    def fwd(ctx, params, states, x):
        v = raw(x)
        return v.reshape(v.shape[0], g_max, 5)

    return LayerOutput(name=name, layer_type="reshape", size=gt_layer.size,
                       parents=(gt_layer,), fn=fwd)


def test_more_layers_smoke(rng_np):
    """prelu / multiplex / tensor / selective_fc / conv_shift / scale_shift
    / resize / data_norm forward semantics."""
    from paddle_tpu.config.topology import Topology

    x = layer.data(name="x", type=data_type.dense_vector(6))
    y = layer.data(name="y", type=data_type.dense_vector(6))
    idx = layer.data(name="idx", type=data_type.integer_value(2))
    k = layer.data(name="k", type=data_type.dense_vector(3))

    nodes = {
        "prelu": more.prelu(x),
        "multiplex": more.multiplex([idx, x, y]),
        "tensor": more.tensor_layer(x, y, size=3),
        "selective_fc": more.selective_fc(x, y, size=6),
        "conv_shift": more.conv_shift(x, k),
        "scale_shift": more.scale_shift(x),
        "resize": more.resize(x, 3),
        "data_norm": more.data_norm(x),
    }
    topo = Topology(list(nodes.values()))
    params = paddle.parameters.create(topo).as_dict()
    xv = rng_np.normal(size=(2, 6)).astype(np.float32)
    yv = rng_np.normal(size=(2, 6)).astype(np.float32)
    kv = np.asarray([0, 1])
    kern = rng_np.normal(size=(2, 3)).astype(np.float32)
    values, _ = topo.forward(params, topo.init_states(),
                             {"x": xv, "y": yv, "idx": kv, "k": kern}, False,
                             jax.random.key(0))
    # prelu: slope 0.25 on negatives
    np.testing.assert_allclose(
        np.asarray(values[nodes["prelu"].name]),
        np.where(xv > 0, xv, 0.25 * xv), atol=1e-6)
    # multiplex row 0 from x, row 1 from y
    mv = np.asarray(values[nodes["multiplex"].name])
    np.testing.assert_allclose(mv[0], xv[0], atol=1e-6)
    np.testing.assert_allclose(mv[1], yv[1], atol=1e-6)
    assert np.asarray(values[nodes["tensor"].name]).shape == (2, 3)
    assert np.asarray(values[nodes["resize"].name]).shape == (4, 3)
    # scale_shift starts as identity (w=1, b=0)
    np.testing.assert_allclose(
        np.asarray(values[nodes["scale_shift"].name]), xv, atol=1e-6)


def test_detection_map_evaluator():
    from paddle_tpu.evaluator import DetectionMAP

    ev = DetectionMAP(overlap_threshold=0.5)
    # image 0: one gt of class 1; a perfect detection + a false positive
    ev.eval_batch(
        detections=[[[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                     [1, 0.3, 0.6, 0.6, 0.9, 0.9]]],
        gts=[[[1, 0.1, 0.1, 0.4, 0.4]]],
    )
    m = ev.finish()["detection_map"]
    assert 0.99 <= m <= 1.0  # the tp outranks the fp at every threshold

    ev.start()
    ev.eval_batch(  # detection misses entirely
        detections=[[[1, 0.9, 0.5, 0.5, 0.6, 0.6]]],
        gts=[[[1, 0.1, 0.1, 0.4, 0.4]]],
    )
    assert ev.finish()["detection_map"] == 0.0


def test_conv3d_pool3d_volumes(rng_np):
    from paddle_tpu.config.topology import Topology

    vol = layer.data(name="vol", type=data_type.dense_vector(2 * 4 * 8 * 8))
    c3 = more.img_conv3d(vol, filter_size=3, num_filters=5, num_channels=2,
                         img_size=(4, 8, 8), padding=1)
    p3 = more.img_pool3d(c3, pool_size=2)
    d3 = more.img_conv3d(vol, filter_size=2, num_filters=2, num_channels=2,
                         img_size=(4, 8, 8), stride=2, trans=True)
    topo = Topology([p3, d3])
    params = paddle.parameters.create(topo).as_dict()
    x = rng_np.normal(size=(3, 2 * 4 * 8 * 8)).astype(np.float32)
    values, _ = topo.forward(params, topo.init_states(), {"vol": x}, False,
                             jax.random.key(0))
    assert np.asarray(values[c3.name]).shape == (3, 4, 8, 8, 5)
    assert np.asarray(values[p3.name]).shape == (3, 2, 4, 4, 5)
    # transposed: (4-1)*2+2 = 8 -> (3, 8, 16, 16, 2)
    assert np.asarray(values[d3.name]).shape == (3, 8, 16, 16, 2)
