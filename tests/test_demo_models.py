"""Demo-model parity (v1_api_demo: gan, vae, sequence_tagging; book demos:
recommender) — each trains briefly and must show learning, mirroring the
reference's end-to-end model tests (test_fit_a_line etc.)."""

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.dataset import conll05, mnist, movielens
from paddle_tpu.models.gan import GAN
from paddle_tpu.models.recommender import recommender_cost
from paddle_tpu.models.sequence_tagging import srl_cost
from paddle_tpu.models.vae import VAE


def _mnist_batches(n_batches, batch_size=64):
    src = mnist.train()()
    for _ in range(n_batches):
        batch = [next(src) for _ in range(batch_size)]
        yield np.stack([b[0] for b in batch])


def test_gan_adversarial_losses_move():
    gan = GAN(jax.random.key(0), x_dim=784)
    d0 = g0 = None
    for imgs in _mnist_batches(20):
        d_loss = gan.train_d(imgs)
        g_loss = gan.train_g()
        if d0 is None:
            d0, g0 = d_loss, g_loss
    # discriminator learns to separate (loss well below chance 2*ln2)
    assert d_loss < d0
    assert d_loss < 1.2
    fake = np.asarray(gan.generate(4))
    assert fake.shape == (4, 784) and np.all(np.abs(fake) <= 1.0)
    assert np.isfinite(g_loss)


def test_vae_elbo_decreases():
    vae = VAE(jax.random.key(0))
    losses = []
    for imgs in _mnist_batches(25):
        losses.append(vae.train_batch((imgs + 1.0) / 2.0))  # to [0,1]
    assert losses[-1] < losses[0] * 0.8
    src = mnist.test()()
    x = np.stack([b[0] for b in [next(src) for _ in range(4)]])
    rec = np.asarray(vae.reconstruct((x + 1.0) / 2.0))
    assert rec.shape == (4, 784) and np.all((rec >= 0) & (rec <= 1))
    assert np.asarray(vae.sample(3)).shape == (3, 784)


def test_recommender_learns():
    cost, prediction, feed_order = recommender_cost()
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3),
    )
    feeding = {name: i for i, name in enumerate(feed_order)}
    reader = paddle.reader.batch(
        paddle.reader.shuffle(movielens.train(), buf_size=2048),
        batch_size=128,
    )
    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    trainer.train(reader=paddle.reader.firstn(reader, 40), num_passes=2,
                  event_handler=handler, feeding=feeding)
    first, last = np.mean(costs[:5]), np.mean(costs[-5:])
    assert last < first * 0.75, (first, last)


def test_srl_tagger_learns():
    cost, decode_err, feed_order = srl_cost(emb_dim=16, hidden=32)
    parameters = paddle.parameters.create(
        paddle.topology.Topology([cost, decode_err]))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3),
    )
    feeding = {name: i for i, name in enumerate(feed_order)}
    reader = conll05.bucketed_batches(conll05.train(), batch_size=32)
    costs = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            costs.append(e.cost)

    trainer.train(reader=paddle.reader.firstn(reader, 25), num_passes=1,
                  event_handler=handler, feeding=feeding)
    assert costs[-1] < costs[0] * 0.5, (costs[0], costs[-1])

    # Viterbi decode through inference: per-sequence 0/1 error indicator
    samples = [s for _, s in zip(range(8), conll05.test()())]
    errs = paddle.infer(output_layer=decode_err, parameters=trainer.parameters,
                        input=[s[:-1] + (s[-1],) for s in samples],
                        feeding=feeding)
    errs = np.asarray(errs)
    assert errs.shape[0] == 8 and set(np.unique(errs)) <= {0.0, 1.0}
