"""Dataset modules: reference-schema conformance + determinism (the
reference's dataset tests assert sample counts and id ranges —
python/paddle/v2/dataset/tests/)."""

import numpy as np

from paddle_tpu.dataset import (
    conll05, imikolov, movielens, sentiment, wmt14,
)


def test_imikolov_ngram_and_seq():
    word_idx = imikolov.build_dict(min_word_freq=5)
    assert word_idx["<unk>"] == len(word_idx) - 1
    assert "<s>" in word_idx and "<e>" in word_idx
    grams = list(imikolov.train(word_idx, 5)())
    assert len(grams) > 1000
    assert all(len(g) == 5 for g in grams[:50])
    assert all(0 <= w < len(word_idx) for g in grams[:50] for w in g)
    seqs = list(imikolov.test(word_idx, 0, imikolov.DataType.SEQ)())
    src, trg = seqs[0]
    assert src[0] == word_idx["<s>"] and trg[-1] == word_idx["<e>"]
    assert src[1:] == trg[:-1]
    # deterministic across calls
    assert grams[0] == next(iter(imikolov.train(word_idx, 5)()))


def test_movielens_schema():
    samples = list(movielens.train()())
    assert len(samples) == movielens.N_USERS * movielens._TRAIN_PER_USER
    uid, gender, age, job, mid, cats, title, rating = samples[0]
    assert 1 <= uid <= movielens.max_user_id()
    assert gender in (0, 1)
    assert 0 <= age < len(movielens.age_table)
    assert 0 <= job <= movielens.max_job_id()
    assert 1 <= mid <= movielens.max_movie_id()
    assert all(0 <= c < len(movielens.movie_categories()) for c in cats)
    assert all(0 <= t < len(movielens.get_movie_title_dict()) for t in title)
    assert 1.0 <= rating[0] <= 5.0
    # ratings reflect latent structure: not all identical
    ratings = [s[-1][0] for s in samples[:500]]
    assert len(set(ratings)) > 2


def test_conll05_slots_aligned():
    word_dict, verb_dict, label_dict = conll05.get_dict()
    assert label_dict["O"] == 0 or "O" in label_dict
    sample = next(iter(conll05.test()()))
    assert len(sample) == 9
    n = len(sample[0])
    for slot in sample:
        assert len(slot) == n
    words, c_n2, c_n1, c_0, c_p1, c_p2, verbs, mark, labels = sample
    assert sum(mark) == 1  # exactly one predicate
    assert len(set(verbs)) == 1
    assert conll05.get_embedding().shape == (conll05.WORD_VOCAB, 32)


def test_sentiment_learnable_signal():
    data = list(sentiment.train()())
    assert len(data) == sentiment.NUM_TRAINING_INSTANCES
    # labels decodable from cue-word parity => a classifier can learn
    correct = 0
    for ids, label in data[:200]:
        cues = [w for w in ids if w < sentiment._N_POLAR]
        votes = sum(1 if w % 2 == 0 else -1 for w in cues)
        pred = 1 if votes > 0 else 0  # even cue ids signal positive
        correct += (pred == label)
    assert correct > 150


def test_wmt14_translation_consistent():
    dict_size = 1000
    pairs = list(wmt14.train(dict_size)())
    assert len(pairs) == wmt14.TRAIN_PAIRS
    src, trg, trg_next = pairs[0]
    assert src[0] == wmt14.START_IDX and src[-1] == wmt14.END_IDX
    assert trg[0] == wmt14.START_IDX and trg_next[-1] == wmt14.END_IDX
    assert trg[1:] == trg_next[:-1]
    # the mapping is a fixed bijection of the reversed source
    core_src = src[1:-1]
    perm = wmt14._mapping(dict_size, "bijection")
    expect = [int(perm[w - wmt14._RESERVED]) + wmt14._RESERVED
              for w in core_src[::-1]]
    assert trg[1:] == expect
    src_dict, trg_dict = wmt14.get_dict(dict_size)
    assert src_dict[0] == "<s>" and trg_dict[1] == "<e>"


def test_flowers_schema_and_learnable():
    from paddle_tpu.dataset import flowers

    samples = [s for _, s in zip(range(64), flowers.train()())]
    img, lbl = samples[0]
    assert img.shape == (3 * 32 * 32,) and 0 <= lbl < flowers.NUM_CLASSES
    # same-class images are more similar than cross-class (learnable signal)
    by_cls = {}
    for im, l in samples:
        by_cls.setdefault(l, []).append(im)
    dup = next((v for v in by_cls.values() if len(v) >= 2), None)
    if dup is not None:
        within = np.linalg.norm(dup[0] - dup[1])
        other = next(v[0] for k, v in by_cls.items() if v[0] is not dup[0])
        across = np.linalg.norm(dup[0] - other)
        assert within < across


def test_voc2012_mask_schema():
    from paddle_tpu.dataset import voc2012

    img, mask = next(iter(voc2012.train()()))
    assert img.shape == (3, 32, 32) and mask.shape == (32, 32)
    vals = set(np.unique(mask)) - {255}
    assert vals <= set(range(voc2012.NUM_CLASSES))
    assert 255 in np.unique(mask)  # void borders present
    assert len(vals) >= 2  # background + at least one object


def test_mq2007_formats_consistent():
    from paddle_tpu.dataset import mq2007

    r, f = next(iter(mq2007.train("pointwise")()))
    assert f.shape == (mq2007.FEATURE_DIM,) and r in (0, 1, 2)
    lbl, hi, lo = next(iter(mq2007.train("pairwise")()))
    assert lbl.shape == (1,)
    assert hi.shape == lo.shape == (mq2007.FEATURE_DIM,)
    rels, feats = next(iter(mq2007.train("listwise")()))
    assert feats.shape == (mq2007._DOCS_PER_QUERY, mq2007.FEATURE_DIM)
    assert rels.shape == (mq2007._DOCS_PER_QUERY,)
    # pairwise pairs are genuinely ordered under the latent scorer:
    # a linear probe fit on pointwise data ranks hi above lo mostly
    X, y = [], []
    for i, (r, fv) in enumerate(mq2007.train("pointwise")()):
        X.append(fv); y.append(r)
        if i > 800:
            break
    X, y = np.asarray(X), np.asarray(y)
    w, *_ = np.linalg.lstsq(X, y, rcond=None)
    good = total = 0
    for i, (lbl, hi, lo) in enumerate(mq2007.test("pairwise")()):
        good += float(hi @ w > lo @ w); total += 1
        if i > 300:
            break
    assert good / total > 0.75, good / total


def test_bucketed_batches_quantize_to_tables():
    """The default bucketed entry points pad every batch to a ceiling
    from the module's SEQ_BUCKETS table — no batch mixes lengths above
    its ceiling, so one jit signature per bucket holds downstream."""
    from paddle_tpu.dataset import imdb

    for mod, reader in ((wmt14, wmt14.train(1000)),
                        (conll05, conll05.train()),
                        (imdb, imdb.train())):
        batches = list(mod.bucketed_batches(reader, 16)())
        assert batches, mod.__name__
        seen_ceilings = set()
        for batch in batches:
            longest = max(
                max((len(f) for f in sample if hasattr(f, "__len__")),
                    default=1)
                for sample in batch)
            ceiling = next(b for b in mod.SEQ_BUCKETS if longest <= b)
            seen_ceilings.add(ceiling)
        # the tables fit the length distributions: >1 bucket in use
        assert len(seen_ceilings) > 1, (mod.__name__, seen_ceilings)


def test_bucketed_batches_deterministic_and_lossless():
    n_samples = sum(len(b) for b in
                    conll05.bucketed_batches(conll05.train(), 16)())
    # remainder="drop" only drops sub-batch remainders per bucket
    assert n_samples <= conll05.TRAIN_SENTENCES
    assert n_samples >= conll05.TRAIN_SENTENCES - 16 * len(conll05.SEQ_BUCKETS)
    a = [tuple(map(tuple, s)) for b in
         conll05.bucketed_batches(conll05.train(), 16, seed=7)() for s in b]
    b = [tuple(map(tuple, s)) for b in
         conll05.bucketed_batches(conll05.train(), 16, seed=7)() for s in b]
    assert a == b
