"""Trainer CLI (`python -m paddle_tpu.trainer`) + v1 config-file e2e.

≅ TrainerMain.cpp job modes (train/test/time/checkgrad, :24-61) and the
reference's own trainer tests (test_Trainer.cpp, test_TrainerOnePass.cpp)
driving sample_trainer_config.conf; plus the v1_api_demo compatibility
claim: unmodified reference config files (light_mnist.py,
sample_trainer_config.conf) parse and train through the shim.
"""

from __future__ import annotations

import os
import textwrap

import numpy as np
import pytest

REF_CONF = "/root/reference/paddle/trainer/tests/sample_trainer_config.conf"
LIGHT_MNIST = "/root/reference/v1_api_demo/mnist/light_mnist.py"


def _write_digits_config(tmp_path):
    """A small v1 config + PyDataProvider2 provider over synthetic digits."""
    cfg = tmp_path / "digits.conf"
    cfg.write_text(textwrap.dedent("""
        from paddle.trainer_config_helpers import *

        define_py_data_sources2(
            train_list='{d}/train.list', test_list='{d}/test.list',
            module='digits_provider', obj='process')
        settings(batch_size=32, learning_rate=1e-2,
                 learning_method=AdamOptimizer())

        img = data_layer(name='pixel', size=64)
        hidden = fc_layer(input=img, size=32, act=ReluActivation())
        predict = fc_layer(input=hidden, size=4, act=SoftmaxActivation())
        lbl = data_layer(name='label', size=4)
        outputs(classification_cost(input=predict, label=lbl))
    """).format(d=tmp_path))
    (tmp_path / "digits_provider.py").write_text(textwrap.dedent("""
        import numpy as np
        from paddle.trainer.PyDataProvider2 import (
            provider, dense_vector, integer_value)

        @provider(input_types={'pixel': dense_vector(64),
                               'label': integer_value(4)})
        def process(settings, filename):
            rng = np.random.default_rng(int(filename.split('-')[-1]))
            for _ in range(256):
                y = int(rng.integers(0, 4))
                x = rng.normal(size=(64,)).astype(np.float32) * 0.1
                x[y * 16:(y + 1) * 16] += 1.0
                yield x, y
    """))
    (tmp_path / "train.list").write_text("seed-0\nseed-1\n")
    (tmp_path / "test.list").write_text("seed-7\n")
    return str(cfg)


def test_cli_train_test_and_checkpoint(tmp_path, capsys):
    from paddle_tpu.trainer import cli

    cfg = _write_digits_config(tmp_path)
    save = tmp_path / "out"
    rc = cli.main(["--config", cfg,
                   "--config_args", f"unused=1",
                   "--job", "train", "--num_passes", "2",
                   "--save_dir", str(save), "--log_period", "4"])
    assert rc == 0
    ckpt = save / "pass-00001.tar"
    assert ckpt.exists()
    out = capsys.readouterr().out
    costs = [float(ln.split("Cost ")[1].split(",")[0])
             for ln in out.splitlines() if "Cost " in ln]
    assert costs[-1] < costs[0] * 0.7, costs

    # --job=test with the trained parameters
    rc = cli.main(["--config", cfg, "--job", "test",
                   "--init_model_path", str(ckpt)])
    assert rc == 0
    out = capsys.readouterr().out
    test_cost = float(out.split("Test cost ")[1].split(",")[0])
    assert test_cost < 1.0  # well below ln(4)=1.386 after training


@pytest.mark.skipif(not os.path.exists(REF_CONF),
                    reason="reference checkout not available")
def test_cli_checkgrad_reference_conf():
    """checkgrad over the UNMODIFIED reference sample_trainer_config.conf."""
    from paddle_tpu.trainer import cli

    rc = cli.main(["--config", REF_CONF, "--job", "checkgrad",
                   "--checkgrad_samples", "4"])
    assert rc == 0


def test_cli_checkgrad_catches_broken_gradient(tmp_path):
    """A layer whose custom_vjp lies about its gradient must FAIL the check
    (≅ the reference using checkgrad to validate hand-written backward)."""
    cfg = tmp_path / "broken.conf"
    cfg.write_text(textwrap.dedent("""
        import jax
        import jax.numpy as jnp
        from paddle.trainer_config_helpers import *
        from paddle_tpu.layers.base import LayerOutput, gen_name

        settings(batch_size=8, learning_rate=1e-3)

        @jax.custom_vjp
        def lying_square(x):
            return x * x

        def _fwd(x):
            return x * x, x

        def _bwd(x, g):
            return (g * 3.0 * x,)  # WRONG: claims d(x^2)/dx = 3x

        lying_square.defvjp(_fwd, _bwd)

        din = data_layer(name='data', size=6)
        base = fc_layer(input=din, size=6, act=LinearActivation())

        def fwd(ctx, params, states, x):
            return lying_square(x)

        # piggyback an emitted layer type; only the runtime fn (and its
        # lying vjp) matter to checkgrad
        broken = LayerOutput(name=gen_name('fc_layer'),
                             layer_type='slope_intercept',
                             size=6, parents=(base,), fn=fwd,
                             attrs={'slope': 1.0, 'intercept': 0.0})
        outputs(broken)
    """))
    from paddle_tpu.trainer import cli

    rc = cli.main(["--config", str(cfg), "--job", "checkgrad"])
    assert rc == 1


@pytest.mark.skipif(not os.path.exists(REF_CONF),
                    reason="reference checkout not available")
def test_sample_trainer_config_trains():
    """The unmodified reference .conf file builds and LEARNS (v1 e2e)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.trainer.config_parser import parse_config
    from paddle_tpu.trainer.step import build_train_step
    from paddle_tpu.trainer_config_helpers.optimizers import (
        get_settings_optimizer,
    )

    parsed = parse_config(REF_CONF, "with_cost=1")
    topo = Topology(parsed.output_layers())
    opt = get_settings_optimizer()
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, opt)
    rng = np.random.default_rng(0)
    key = jax.random.key(0)
    first = last = None
    for i in range(40):
        y = rng.integers(0, 3, size=(32,))
        x = (np.eye(3, dtype=np.float32)[y] * 2.0
             + rng.normal(size=(32, 3)).astype(np.float32) * 0.1)
        feed = {"input": x, "label": y}
        params, opt_state, states, c, _ = step(
            params, opt_state, states, feed, key)
        c = float(c)
        first = first if first is not None else c
        last = c
    assert last < first * 0.6, (first, last)


@pytest.mark.skipif(not os.path.exists(LIGHT_MNIST),
                    reason="reference checkout not available")
def test_light_mnist_parses_and_trains():
    """v1_api_demo/mnist/light_mnist.py — the VERDICT's named compatibility
    config — parses unmodified and its 4x[conv-BN-relu-pool] CNN learns."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.trainer.config_parser import parse_config
    from paddle_tpu.trainer.step import build_train_step
    from paddle_tpu.trainer_config_helpers.optimizers import (
        get_settings_optimizer,
    )

    parsed = parse_config(LIGHT_MNIST, "")
    assert parsed.opt_config.learning_method == "adam"
    assert parsed.trainer_config.data_config.load_data_module == (
        "mnist_provider")
    topo = Topology(parsed.output_layers())
    names = {n.layer_type for n in topo.nodes}
    assert "exconv" in names and "batch_norm" in names

    opt = get_settings_optimizer()
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, opt)
    rng = np.random.default_rng(0)
    key = jax.random.key(0)
    batch = 4
    first = last = None
    for i in range(4):
        y = rng.integers(0, 10, size=(batch,))
        x = rng.normal(size=(batch, 28 * 28)).astype(np.float32) * 0.05
        x[np.arange(batch), y * 20] += 3.0  # learnable pixel cue
        feed = {"pixel": x, "label": y}
        params, opt_state, states, c, _ = step(
            params, opt_state, states, feed, key)
        c = float(c)
        first = first if first is not None else c
        last = c
    assert np.isfinite(last)
    assert last < first * 1.5  # trains without diverging in a few steps


def test_cli_gflags_passthrough_and_restore(tmp_path):
    """Unknown argparse args route to the gflags registry (TrainerMain's
    gflags convention), apply for the job, and restore afterwards."""
    from paddle_tpu.core import flags
    from paddle_tpu.trainer import cli

    cfg = tmp_path / "c.py"
    cfg.write_text(
        "from paddle.trainer_config_helpers import *\n"
        "settings(batch_size=4, learning_rate=0.1)\n"
        "x = data_layer('x', 4)\n"
        "y = fc_layer(input=x, size=2, act=LinearActivation())\n"
        "lab = data_layer('l', 2)\n"
        "outputs(mse_cost(input=y, label=lab))\n")
    assert flags.get("with_timer") is False
    rc = cli.main(["--config", str(cfg), "--job", "time",
                   "--with_timer", "--bf16"])
    assert rc == 0
    # restored after the in-process call
    assert flags.get("with_timer") is False
    assert flags.get("bf16") is False

    import pytest
    with pytest.raises(SystemExit):
        cli.main(["--config", str(cfg), "--job", "time", "--not_a_flag"])
