"""ZeRO-2 weight-update sharding through the trainer (parallel/zero.py +
trainer/step.py zero modes): invariance vs the replicated update, the
collective census proving reduce-scatter replaced all-reduce at 1/n
bytes/device, 1/n optimizer-state residency, sharded checkpoints with
cross-mode resharding, and SGD.train plumbing — the pserver's sharded
aggregation (ParameterServer2::addGradient) re-expressed in-mesh."""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.config.topology import Topology
from paddle_tpu.core import rng as prng
from paddle_tpu.layers import activation as act
from paddle_tpu.layers import api as layer
from paddle_tpu.layers import base, data_type
from paddle_tpu.optimizer import Adam, Momentum
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.parallel import zero as Z
from paddle_tpu.telemetry import capture_comm
from paddle_tpu.trainer.step import build_train_step

IN_DIM, HIDDEN, CLASSES = 32, 64, 8  # every dim divides the 8-way mesh


def _mlp_cost(in_dim=IN_DIM, classes=CLASSES):
    img = layer.data(name="x", type=data_type.dense_vector(in_dim))
    h = layer.fc(input=img, size=HIDDEN, act=act.ReluActivation())
    h = layer.fc(input=h, size=HIDDEN // 2, act=act.TanhActivation())
    predict = layer.fc(input=h, size=classes, act=act.SoftmaxActivation())
    lab = layer.data(name="y", type=data_type.integer_value(classes))
    return layer.classification_cost(input=predict, label=lab)


def _feeds(steps=5, bs=16, seed=3):
    rng = np.random.default_rng(seed)
    return [
        {"x": jnp.asarray(rng.normal(size=(bs, IN_DIM)).astype(np.float32)),
         "y": jnp.asarray(rng.integers(0, CLASSES, size=(bs,)))}
        for _ in range(steps)
    ]


def _train(zero, mesh, feeds, optimizer=None):
    """len(feeds) steps of the Topology trainer step; returns
    (host params, last cost, last metrics, lowered-comm capture,
    final opt_state)."""
    base.reset_name_counters()
    prng.seed(7)
    topo = Topology(_mlp_cost())
    params = {k: jnp.array(v)
              for k, v in paddle.parameters.create(topo).as_dict().items()}
    opt = optimizer or Adam(learning_rate=1e-2)
    specs = {s.name: s for s in topo.param_specs()}
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    if mesh is not None:
        params = mesh.place_params(params, specs)
        states = mesh.replicate(states)
        if zero and zero >= 1:
            opt_state = Z.shard_opt_state(opt_state, params, mesh.mesh)
        else:
            opt_state = mesh.replicate(opt_state)
    step = build_train_step(topo, opt, mesh=mesh, zero=zero)
    key = jax.random.key(0)
    comm = {}
    if mesh is not None:
        with capture_comm() as comm:
            step.lower(params, opt_state, states,
                       mesh.shard_batch(feeds[0]), key)
    for feed in feeds:
        if mesh is not None:
            feed = mesh.shard_batch(feed)
        params, opt_state, states, cost, metrics = step(
            params, opt_state, states, feed, key)
    return ({k: np.asarray(v) for k, v in params.items()}, float(cost),
            {k: float(v) for k, v in metrics.items()}, dict(comm),
            opt_state)


def _mesh8():
    return mesh_mod.MeshContext(mesh=mesh_mod.make_mesh({"data": 8}))


# -- invariance: zero trajectories equal the replicated/local one -------------


def test_trainer_zero_modes_match_local_training():
    """5 steps of zero=0/1/2 on the 8-device data mesh end with the same
    parameters, cost and metrics as unsharded local training (the
    test_CompareTwoNets property, extended to the sharded weight
    update).  Divergence budget: cross-device reduction order only."""
    feeds = _feeds(steps=5)
    local, cost_l, metrics_l, _, _ = _train(None, None, feeds)
    ctx = _mesh8()
    for zero in (0, 1, 2):
        shard, cost_s, metrics_s, _, _ = _train(zero, ctx, feeds)
        assert local.keys() == shard.keys()
        for name in local:
            np.testing.assert_allclose(
                local[name], shard[name], rtol=3e-5, atol=3e-5,
                err_msg=f"zero={zero}: parameter {name} diverged from "
                        f"local training")
        np.testing.assert_allclose(cost_s, cost_l, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            metrics_s["classification_error_evaluator"],
            metrics_l["classification_error_evaluator"],
            rtol=1e-6, atol=1e-7)


def test_trainer_zero2_with_momentum_matches_local():
    """The invariance must not lean on Adam's grad-scale invariance:
    heavy-ball momentum (scale-sensitive) catches any 1/n mis-scaling
    of the reduce-scattered gradient flow."""
    feeds = _feeds(steps=4)
    opt = lambda: Momentum(momentum=0.9, learning_rate=0.05)  # noqa: E731
    local, _, _, _, _ = _train(None, None, feeds, optimizer=opt())
    shard, _, _, _, _ = _train(2, _mesh8(), feeds, optimizer=opt())
    for name in local:
        np.testing.assert_allclose(
            local[name], shard[name], rtol=3e-5, atol=3e-5,
            err_msg=f"zero=2 momentum: parameter {name} diverged")


# -- the collective census: reduce-scatter replaced all-reduce at 1/n ---------


def test_zero2_collective_census_proves_the_swap():
    """Under zero=2 the traced gradient flow is reduce_scatter +
    all_gather at exactly 1/n bytes/device of the replicated run's
    all-reduce payload, and the grad all_reduce counter is ZERO (every
    leaf here divides the mesh)."""
    feeds = _feeds(steps=1)
    ctx = _mesh8()
    _, _, _, comm, _ = _train(2, ctx, feeds)
    # the replicated run's gradient all-reduce payload: one full copy of
    # every trainable gradient (statically known from the shapes)
    base.reset_name_counters()
    prng.seed(7)
    topo = Topology(_mlp_cost())
    grad_bytes = sum(
        int(np.prod(s.shape)) * 4
        for s in topo.param_specs() if not s.is_static)
    n = 8
    assert comm.get("reduce_scatter/data") == grad_bytes / n, comm
    assert comm.get("all_gather/data") == grad_bytes / n, comm
    assert "all_reduce/data" not in comm, (
        f"gradient all-reduce survived under zero=2: {comm}")
    assert "psum_tree/data" not in comm, comm


def test_zero1_keeps_allreduce_and_state_sharding():
    """zero=1 is the midpoint: gradients stay all-reduced (no explicit
    reduce-scatter traced) while the optimizer state lives 1/n."""
    feeds = _feeds(steps=1)
    _, _, _, comm, ostate = _train(1, _mesh8(), feeds)
    assert "reduce_scatter/data" not in comm
    total = sum(l.size * l.dtype.itemsize
                for l in jax.tree.leaves(ostate["slots"]))
    assert Z.state_bytes_per_device(ostate) == total // 8


def test_zero2_state_stays_sharded_across_steps():
    feeds = _feeds(steps=3)
    _, _, _, _, ostate = _train(2, _mesh8(), feeds)
    total = sum(l.size * l.dtype.itemsize
                for l in jax.tree.leaves(ostate["slots"]))
    # every slot leaf here divides 8 -> exactly 1/8 residency, held
    # through the jitted steps (the constraint pinned the layout)
    assert Z.state_bytes_per_device(ostate) == total // 8


# -- spec edge cases (zero1_specs / state_specs) ------------------------------


def test_zero_specs_indivisible_leaves_stay_replicated():
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    params = {"odd": jnp.zeros((5, 3)), "even": jnp.zeros((16, 4))}
    opt = Adam(learning_rate=1e-3)
    state = opt.init_tree(params)
    specs = Z.zero1_specs(state, params, mesh)
    # init_tree slot order follows tree.leaves(params): sorted keys ->
    # ["even", "odd"]; even shards, odd (5x3, nothing divides 8) stays
    # fully replicated
    even_specs, odd_specs = specs["slots"][0], specs["slots"][1]
    for sp in jax.tree.leaves(even_specs,
                              is_leaf=lambda x: isinstance(x, P)):
        assert "data" in tuple(sp), sp
    for sp in jax.tree.leaves(odd_specs,
                              is_leaf=lambda x: isinstance(x, P)):
        assert all(a is None for a in tuple(sp)), sp


def test_zero_specs_preserve_tp_base_axes():
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2),
                ("data", "model"))
    params = {"w": jnp.zeros((16, 8))}
    pspecs = {"w": P(None, "model")}
    opt = Adam(learning_rate=1e-3)
    specs = Z.state_specs(opt.init_tree(params), params, mesh,
                          param_specs=pspecs)
    for sp in jax.tree.leaves(specs["slots"],
                              is_leaf=lambda x: isinstance(x, P)):
        assert tuple(sp) == ("data", "model"), sp  # TP axis untouched


def test_zero_specs_scalar_step_never_sharded():
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    params = {"w": jnp.zeros((16, 16))}
    opt = Adam(learning_rate=1e-3)
    state = opt.init_tree(params)
    specs = Z.state_specs(state, params, mesh)
    assert tuple(specs["step"]) == ()
    # trainer layout too (named slots + scalar-bearing SGD slots)
    mom = Momentum(momentum=0.9, learning_rate=0.1)
    tstate = {"step": jnp.zeros((), jnp.int32),
              "slots": {"w": mom.slot_init(params["w"])}}
    tspecs = Z.state_specs(tstate, params, mesh)
    assert tuple(tspecs["step"]) == ()
    assert "data" in tuple(tspecs["slots"]["w"]["velocity"])


def test_zero_specs_bf16_slots_survive_placement():
    """bf16 Adam moments keep their dtype through spec assignment AND
    the sharded device_put (shard_opt_state)."""
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    params = {"w": jnp.zeros((16, 16), jnp.float32)}
    opt = Adam(learning_rate=1e-3, moment_dtype=jnp.bfloat16)
    state = opt.init_tree(params)
    placed = Z.shard_opt_state(state, params, mesh)
    for leaf in jax.tree.leaves(placed["slots"]):
        assert leaf.dtype == jnp.bfloat16
        assert "data" in tuple(leaf.sharding.spec)
    assert Z.state_bytes_per_device(placed) == (16 * 16 * 2 * 2) // 8


def test_zero_specs_scalar_aux_slots_replicated():
    """SparseMomentum-style scalar slots (alpha/beta/tau) ride next to
    full-shape u/v buffers — scalars stay P() while buffers shard."""
    from paddle_tpu.optimizer import SparseMomentum

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    params = {"w": jnp.zeros((16, 16))}
    sm = SparseMomentum(momentum=0.9, learning_rate=0.1)
    state = {"step": jnp.zeros((), jnp.int32),
             "slots": {"w": sm.slot_init(params["w"])}}
    specs = Z.state_specs(state, params, mesh)
    assert tuple(specs["slots"]["w"]["alpha"]) == ()
    assert "data" in tuple(specs["slots"]["w"]["u"])


# -- SGD.train plumbing -------------------------------------------------------


def _build_sgd(zero, lr=0.05):
    base.reset_name_counters()
    prng.seed(7)
    cost = _mlp_cost()
    params = paddle.parameters.create(paddle.topology.Topology(cost))
    # explicit 8-device mesh: the get_mesh() default is a process-global
    # cache other tests may have pinned to a different shape
    return paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=Momentum(momentum=0.9, learning_rate=lr),
        mesh=_mesh8(), zero=zero)


def _reader(nb=6, bs=16, seed=11):
    def r():
        rng = np.random.default_rng(seed)
        for _ in range(nb):
            yield [(rng.normal(size=(IN_DIM,)).astype(np.float32),
                    int(rng.integers(0, CLASSES)))
                   for _ in range(bs)]
    return r


def test_sgd_train_zero2_trajectory_matches_replicated():
    """Full SGD.train: the zero=2 run's per-batch costs and final
    parameters equal the zero=0 run's (same reader, same RNG stream) —
    the trainer-level invariance the step-level tests can't see
    (placement, checkpoint plumbing, state write-back)."""
    results = {}
    for zero in (0, 2):
        tr = _build_sgd(zero)
        costs = []

        def on_event(e):
            if isinstance(e, paddle.event.EndIteration):
                costs.append(e.cost)

        tr.train(reader=_reader(), num_passes=2, event_handler=on_event)
        results[zero] = (costs,
                         {n: np.asarray(tr.parameters[n])
                          for n in tr.parameters.names()})
    np.testing.assert_allclose(results[0][0], results[2][0],
                               rtol=1e-5, atol=1e-6)
    for name in results[0][1]:
        np.testing.assert_allclose(
            results[0][1][name], results[2][1][name],
            rtol=3e-5, atol=3e-5, err_msg=f"SGD zero=2 param {name}")


# -- sharded checkpoints ------------------------------------------------------


def test_sharded_checkpoint_files_and_manifest(tmp_path):
    """A zero=2 run's checkpoint stores the optimizer state as per-shard
    npz files listed (sha256-covered) in the manifest's files map, with
    the shard map under ``opt_shards``."""
    import json

    from paddle_tpu.trainer import checkpoint as ckpt

    tr = _build_sgd(2)
    d = str(tmp_path / "ck")
    tr.train(reader=_reader(nb=4), num_passes=1, checkpoint_dir=d)
    path, manifest = ckpt.latest_checkpoint(d)
    shard_files = [f for f in manifest["files"]
                   if f.startswith("opt_state.shard-")]
    assert len(shard_files) == 8, manifest["files"]
    assert manifest["opt_shards"]["count"] == 8
    assert manifest["opt_shards"]["axis"] == "data"
    assert manifest["opt_shards"]["dims"]  # per-keypath sharded dim
    # the manifest on disk matches (json round-trip, not just in-memory)
    with open(os.path.join(path, "checkpoint.json")) as f:
        assert json.load(f)["opt_shards"]["count"] == 8


def test_corrupt_shard_file_invalidates_checkpoint(tmp_path):
    """sha256 verification covers the per-shard payloads: one flipped
    byte in one shard file makes latest_checkpoint fall back (here: to
    nothing)."""
    from paddle_tpu.trainer import checkpoint as ckpt

    tr = _build_sgd(2)
    d = str(tmp_path / "ck")
    tr.train(reader=_reader(nb=2), num_passes=1, checkpoint_dir=d,
             resume=False)
    path, manifest = ckpt.latest_checkpoint(d)
    victim = os.path.join(
        path, [f for f in manifest["files"]
               if f.startswith("opt_state.shard-")][3])
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(blob))
    assert ckpt.latest_checkpoint(d) is None


@pytest.mark.parametrize("save_zero,load_zero", [(2, 0), (0, 2), (2, 1)])
def test_checkpoint_reshards_across_zero_modes(tmp_path, save_zero,
                                               load_zero):
    """A checkpoint written under one zero mode restores into a trainer
    running another (2->0: sharded state reassembled to replicated;
    0->2: full state re-sharded) and the resumed trajectory equals the
    uninterrupted one — resharding on restore."""
    d = str(tmp_path / "ck")

    # uninterrupted reference: 2 passes in one go
    ref = _build_sgd(save_zero)
    ref.train(reader=_reader(), num_passes=2)
    ref_params = {n: np.asarray(ref.parameters[n])
                  for n in ref.parameters.names()}

    # pass 0 under save_zero, checkpoint, then pass 1 under load_zero
    a = _build_sgd(save_zero)
    a.train(reader=_reader(), num_passes=1, checkpoint_dir=d)
    b = _build_sgd(load_zero)
    b.train(reader=_reader(), num_passes=2, checkpoint_dir=d, resume=True)
    for name in ref_params:
        np.testing.assert_allclose(
            ref_params[name], np.asarray(b.parameters[name]),
            rtol=3e-5, atol=3e-5,
            err_msg=f"zero {save_zero}->{load_zero} resume: param {name}")


# -- transformer routes through the shared implementation ---------------------


def _tcfg():
    from paddle_tpu.models import transformer as T

    return T.TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                               embed_dim=16, mlp_dim=32, max_seq_len=32,
                               remat=False)


def test_transformer_zero2_explicit_matches_replicated():
    """Pure-DP mesh -> the explicit shard_map lowering: bit-comparable
    trajectory AND a census showing the full param payload moving as
    reduce_scatter + all_gather at 1/8 per device."""
    from paddle_tpu.models import transformer as T

    cfg = _tcfg()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (8, 17)))
    params0 = T.init_params(cfg, jax.random.key(0))

    opt = Adam(learning_rate=1e-3)
    p_ref = jax.tree.map(jnp.array, params0)
    s_ref = opt.init_tree(p_ref)
    step_ref = T.build_train_step(cfg, opt)
    for _ in range(3):
        p_ref, s_ref, loss_ref = step_ref(p_ref, s_ref, ids)

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(8), ("data",))
    opt2 = Adam(learning_rate=1e-3)
    p_z = T.place_params(jax.tree.map(jnp.array, params0), mesh, cfg)
    s_z = Z.shard_opt_state(opt2.init_tree(p_z), p_z, mesh,
                            param_specs=T.param_shardings(cfg))
    step_z = T.build_train_step(cfg, opt2, mesh=mesh, zero=2)
    ids_z = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    with capture_comm() as comm:
        step_z.lower(p_z, s_z, ids_z)
    for _ in range(3):
        p_z, s_z, loss_z = step_z(p_z, s_z, ids_z)

    np.testing.assert_allclose(float(loss_z), float(loss_ref),
                               rtol=1e-4, atol=1e-5)
    for i, (a, b) in enumerate(zip(jax.tree.leaves(p_ref),
                                   jax.tree.leaves(p_z))):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=f"transformer zero=2 leaf {i}")
    assert comm.get("reduce_scatter/data", 0) > 0
    assert comm.get("all_gather/data", 0) > 0
    assert "all_reduce/data" not in comm, comm
    # reduce_scatter accounting = per-device OUTPUT shard: divisible
    # param bytes / 8
    total = sum(x.size * x.dtype.itemsize
                for x in jax.tree.leaves(params0))
    assert comm["reduce_scatter/data"] <= total / 8


def test_transformer_zero2_gspmd_composes_with_tp():
    """(data, model) mesh -> the GSPMD constraint lowering (Xu et al.):
    ZeRO-2 composes with the Megatron TP layout — trajectory equals the
    replicated run, slots carry BOTH axes, residency stays sharded."""
    from paddle_tpu.models import transformer as T

    cfg = _tcfg()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (8, 17)))
    params0 = T.init_params(cfg, jax.random.key(0))

    opt = Adam(learning_rate=1e-3)
    p_ref = jax.tree.map(jnp.array, params0)
    s_ref = opt.init_tree(p_ref)
    step_ref = T.build_train_step(cfg, opt)
    for _ in range(3):
        p_ref, s_ref, _ = step_ref(p_ref, s_ref, ids)

    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2),
                ("data", "model"))
    opt2 = Adam(learning_rate=1e-3)
    p_t = T.place_params(jax.tree.map(jnp.array, params0), mesh, cfg)
    sspecs = Z.state_specs(opt2.init_tree(p_t), p_t, mesh,
                           param_specs=T.param_shardings(cfg))
    axes = {a for sp in jax.tree.leaves(
        sspecs["slots"], is_leaf=lambda x: isinstance(x, P))
        for a in tuple(sp) if a is not None}
    assert {"data", "model"} <= axes  # both axes live on the slots
    s_t = Z.shard_opt_state(opt2.init_tree(p_t), p_t, mesh,
                            param_specs=T.param_shardings(cfg))
    step_t = T.build_train_step(cfg, opt2, mesh=mesh, zero=2)
    ids_t = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
    for _ in range(3):
        p_t, s_t, _ = step_t(p_t, s_t, ids_t)

    for i, (a, b) in enumerate(zip(jax.tree.leaves(p_ref),
                                   jax.tree.leaves(p_t))):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
            err_msg=f"transformer zero=2+TP leaf {i}")


def test_transformer_zero1_kwarg_back_compat():
    """The original ``zero1=True`` spelling still builds and matches."""
    from paddle_tpu.models import transformer as T

    cfg = _tcfg()
    mesh = Mesh(np.asarray(jax.devices()[:4]).reshape(4), ("data",))
    opt = Adam(learning_rate=1e-3)
    params = T.place_params(T.init_params(cfg, jax.random.key(0)), mesh,
                            cfg)
    state = Z.shard_opt_state(opt.init_tree(params), params, mesh,
                              param_specs=T.param_shardings(cfg))
    step = T.build_train_step(cfg, opt, mesh=mesh, zero1=True)
    ids = jax.device_put(
        jnp.asarray(np.random.default_rng(0).integers(0, 64, (8, 17))),
        NamedSharding(mesh, P("data", None)))
    params, state, loss = step(params, state, ids)
    assert np.isfinite(float(loss))


# -- census tooling -----------------------------------------------------------


def test_census_by_kind_rollup():
    from paddle_tpu.telemetry import census_by_kind

    comm = {"reduce_scatter/data": 2160.0, "all_gather/data": 2160.0,
            "all_reduce/data": 16.0, "all_reduce/model": 64.0,
            "all_to_all/expert": 512.0}
    census = census_by_kind(comm)
    assert census["reduce_scatter"]["bytes"] == 2160.0
    assert census["all_reduce"]["bytes"] == 80.0
    assert census["all_reduce"]["sites"] == 2
    assert set(census["all_reduce"]["axes"]) == {"data", "model"}
    assert census_by_kind({}) == {}


def test_metrics_to_md_renders_collective_census(tmp_path, capsys):
    """A zero2-shaped step record renders the per-kind census table and
    the collective-swap note (all-reduce ≈ 0, reduce-scatter carrying
    the grad flow)."""
    import importlib.util
    import json

    spec = importlib.util.spec_from_file_location(
        "metrics_to_md", os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "metrics_to_md.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)

    stream = tmp_path / "m.jsonl"
    rec = {"kind": "step", "run": "train", "step": 0, "loss": 1.0,
           "step_ms": 2.0, "examples_per_sec": 10.0, "mfu_pct": 0.0,
           "comm_bytes": {"reduce_scatter/data": 2160.0,
                          "all_gather/data": 2160.0}}
    stream.write_text(json.dumps(rec) + "\n")
    assert mod.main([str(stream)]) == 0
    out = capsys.readouterr().out
    assert "Collective census (per kind)" in out
    assert "reduce_scatter" in out and "all_gather" in out
    assert "ZeRO-sharded" in out


# -- kill-and-resume under zero=2 (chaos marker: filtered from tier-1) --------

_PROC_SCRIPT = r"""
import os, sys
import numpy as np
import paddle_tpu as paddle
from paddle_tpu.core import rng
from paddle_tpu.layers import api as layer, base, data_type
from paddle_tpu.layers import activation as act

mode, ckdir, out = sys.argv[1], sys.argv[2], sys.argv[3]
base.reset_name_counters(); rng.seed(7)
x = layer.data(name="x", type=data_type.dense_vector(32))
h = layer.fc(input=x, size=64, act=act.ReluActivation())
p = layer.fc(input=h, size=8, act=act.SoftmaxActivation())
y = layer.data(name="y", type=data_type.integer_value(8))
cost = layer.classification_cost(input=p, label=y)
params = paddle.parameters.create(paddle.topology.Topology(cost))
tr = paddle.trainer.SGD(cost=cost, parameters=params,
                        update_equation=paddle.optimizer.Momentum(
                            momentum=0.9, learning_rate=0.05),
                        zero=2)

def r():
    rs = np.random.RandomState(0)
    for _ in range(32):
        xs = rs.randn(32).astype(np.float32)
        yield xs, int(rs.randint(0, 8))
reader = paddle.reader.batch(r, batch_size=8)

def killer(e):
    if mode == "kill" and isinstance(e, paddle.event.BeginIteration) \
            and (e.pass_id, e.batch_id) == (1, 3):
        os.kill(os.getpid(), 9)  # SIGKILL: no handlers, no cleanup

tr.train(reader=reader, num_passes=2, event_handler=killer,
         checkpoint_dir=(ckdir or None), checkpoint_batch_period=2)
np.save(out, np.asarray(tr.parameters["___fc_layer_0__.w0"]))
"""


@pytest.mark.chaos
@pytest.mark.slow
def test_zero2_sigkill_and_resume_bit_identical(tmp_path):
    """SIGKILL mid-pass under zero=2 (sharded mid-pass cursor
    checkpoints), run again, and the resumed process ends bit-identical
    to a never-killed zero=2 run — the PR 4 chaos harness over the
    sharded checkpoint format."""
    import signal
    import subprocess
    import sys

    script = tmp_path / "train_zero2.py"
    script.write_text(_PROC_SCRIPT)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(
        kept + ["--xla_force_host_platform_device_count=8"])
    env["PYTHONPATH"] = os.pathsep.join(
        [repo_root] + env.get("PYTHONPATH", "").split(os.pathsep))

    def run(mode, ckdir, out):
        return subprocess.run(
            [sys.executable, str(script), mode, ckdir, out],
            env=env, capture_output=True, text=True, timeout=300)

    ref = str(tmp_path / "ref.npy")
    clean = run("clean", "", ref)
    assert clean.returncode == 0, clean.stderr[-2000:]

    ckdir = str(tmp_path / "ck")
    out = str(tmp_path / "resumed.npy")
    first = run("kill", ckdir, out)
    assert first.returncode == -signal.SIGKILL
    # the mid-pass cursor checkpoint it died after is SHARDED
    from paddle_tpu.trainer import checkpoint as ckpt

    path, manifest = ckpt.latest_checkpoint(ckdir)
    assert any(f.startswith("opt_state.shard-") for f in manifest["files"])
    second = run("clean", ckdir, out)
    assert second.returncode == 0, second.stderr[-2000:]
    np.testing.assert_array_equal(np.load(out), np.load(ref))
