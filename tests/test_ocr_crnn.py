"""OCR CRNN end-to-end: CTC cost decreases and greedy decode recovers the
synthetic bar-code labels (the reference's scene-text CRNN + WarpCTC path,
tested like its test_TrainerOnePass convergence checks)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.ocr_crnn import crnn_ctc_cost, synthetic_ocr_reader


# ~2.5 min on CPU: the GRU runs the fused pallas kernel in interpret
# mode for a full convergence loop
@pytest.mark.slow
def test_crnn_ctc_learns_and_decodes():
    cost, probs, feed_order = crnn_ctc_cost(num_classes=8, rnn_size=32)
    parameters = paddle.parameters.create(
        paddle.topology.Topology([cost, probs]))
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.Adam(learning_rate=3e-3),
    )
    reader = synthetic_ocr_reader(n_samples=512, num_classes=8)
    costs = []
    trainer.train(
        reader=paddle.reader.batch(reader, 32), num_passes=25,
        feeding={n: i for i, n in enumerate(feed_order)},
        event_handler=lambda e: costs.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None,
    )
    assert costs[-1] < costs[0] * 0.05, (costs[0], costs[-1])

    # greedy CTC decode on fresh samples: majority exact-match
    from paddle_tpu.ops.ctc import ctc_greedy_decode
    import jax.numpy as jnp

    samples = list(synthetic_ocr_reader(n_samples=16, num_classes=8,
                                        seed=123)())
    out = paddle.infer(output_layer=probs, parameters=trainer.parameters,
                       input=[(s[0], s[1]) for s in samples],
                       feeding={n: i for i, n in enumerate(feed_order)})
    # out: per-sample list of [T, C+1] prob rows (sequence output)
    exact = 0
    for (img, labels), p in zip(samples, out):
        p = np.asarray(p)
        lp = jnp.log(jnp.asarray(p)[None] + 1e-9)
        dec, dec_len = ctc_greedy_decode(
            lp, jnp.asarray([p.shape[0]]), blank=8)
        got = [int(x) for x in np.asarray(dec[0])[:int(dec_len[0])]]
        exact += (got == labels)
    assert exact >= 13, f"only {exact}/16 decoded exactly"
