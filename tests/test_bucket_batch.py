"""calc_batch_size / bucketed dynamic batching (PyDataProvider2.py:367-374
semantics on static XLA shapes) — VERDICT r2 task 8."""

import textwrap

import numpy as np


def test_bucket_batch_cost_balances_by_length():
    from paddle_tpu.reader.decorator import bucket_batch

    rng = np.random.default_rng(0)
    samples = []
    for _ in range(200):
        n = int(rng.integers(4, 120))
        samples.append(([0] * n, n))  # (sequence, label)

    token_budget = 256

    def calc(sample):
        return len(sample[0])

    batches = list(bucket_batch(lambda: iter(samples), token_budget,
                                calc_batch_size=calc)())
    assert sum(len(b) for b in batches) == len(samples)
    from paddle_tpu.core.lod import bucket_length

    sizes_by_bucket = {}
    for b in batches[:-4]:  # tail flush batches may be under budget
        lens = [len(s[0]) for s in b]
        # one static shape per batch: all members share the bucket
        bkt = {bucket_length(n) for n in lens}
        assert len(bkt) == 1
        # approximately cost-balanced around the token budget (the first
        # flush pins the bucket's batch size; later costs fluctuate with
        # the length mix inside the bucket)
        assert token_budget * 0.5 <= sum(lens) < token_budget + 128
        sizes_by_bucket.setdefault(bkt.pop(), set()).add(len(b))
    # shape discipline: ONE batch size per bucket -> bounded jit signatures
    for bkt, sizes in sizes_by_bucket.items():
        assert len(sizes) == 1, (bkt, sizes)
    # long sequences ride in smaller batches than short ones
    short = [len(b) for b in batches if bucket_length(len(b[0][0])) <= 16]
    long_ = [len(b) for b in batches if bucket_length(len(b[0][0])) >= 128]
    if short and long_:
        assert min(short) > max(long_)


def test_cli_trains_with_calc_batch_size(tmp_path, capsys):
    """An NMT-style variable-length provider declaring calc_batch_size
    trains under the CLI with bucketed cost-balanced batches."""
    from paddle_tpu.trainer import cli

    cfg = tmp_path / "seq.conf"
    cfg.write_text(textwrap.dedent("""
        from paddle.trainer_config_helpers import *

        define_py_data_sources2(
            train_list='{d}/train.list', test_list=None,
            module='seq_provider', obj='process')
        settings(batch_size=128, learning_rate=1e-2,
                 learning_method=AdamOptimizer())

        words = data_layer(name='words', size=32)
        emb = embedding_layer(input=words, size=16)
        pooled = pooling_layer(input=emb)
        predict = fc_layer(input=pooled, size=2, act=SoftmaxActivation())
        lbl = data_layer(name='label', size=2)
        outputs(classification_cost(input=predict, label=lbl))
    """).format(d=tmp_path))
    (tmp_path / "seq_provider.py").write_text(textwrap.dedent("""
        import numpy as np
        from paddle.trainer.PyDataProvider2 import (
            provider, integer_value_sequence, integer_value)

        @provider(input_types={'words': integer_value_sequence(32),
                               'label': integer_value(2)},
                  calc_batch_size=lambda sample: len(sample[0]),
                  pool_size=512)
        def process(settings, filename):
            rng = np.random.default_rng(0)
            for _ in range(160):
                n = int(rng.integers(3, 40))
                y = int(rng.integers(0, 2))
                words = rng.integers(y * 16, y * 16 + 16, size=n)
                yield [int(w) for w in words], y
    """))
    (tmp_path / "train.list").write_text("f-0\n")

    rc = cli.main(["--config", str(cfg), "--job", "train",
                   "--num_passes", "2", "--log_period", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    costs = [float(ln.split("Cost ")[1].split(",")[0])
             for ln in out.splitlines() if "Cost " in ln]
    assert costs and costs[-1] < costs[0]
