"""Parity tests for the sharded-embedding kernel pair
(ops/pallas/tpp/embedding.py): every ``pallas_call`` entry against its
``*_reference`` twin (the GL-KERNEL contract), plus the fused lookup's
custom_vjp against a dense one-device oracle.

Kernels run in interpret mode on the CPU testbed.  Touched rows compare
at float tolerance (separately-jitted programs fuse differently);
UNTOUCHED rows in the sparse row update must stay bit-identical — that
is the lazy-sparse optimizer contract.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.tpp import (
    dedup_ids, dedup_ids_reference,
    embedding_gather, embedding_gather_reference,
    embedding_scatter_add, embedding_scatter_add_reference,
    fused_embedding_lookup,
    sparse_row_update, sparse_row_update_reference,
)


# ---------------------------------------------------------------------------
# dedup_ids
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("ids", [
    [3, 1, 3, 7, 1, 0],          # duplicates
    [5, 2, 9, 0],                # all unique
    [4, 4, 4, 4],                # all duplicate
    [11],                        # single id (ragged/odd n)
])
def test_dedup_ids_matches_reference(ids):
    ids = jnp.asarray(ids, jnp.int32)
    u_k, inv_k = dedup_ids(ids)
    u_r, inv_r = dedup_ids_reference(ids)
    np.testing.assert_array_equal(u_k, u_r)
    np.testing.assert_array_equal(inv_k, inv_r)
    # reconstruction: uids[inv] == ids, -1 fill only past the unique count
    np.testing.assert_array_equal(np.asarray(u_k)[np.asarray(inv_k)],
                                  np.asarray(ids).ravel())
    nuniq = len(set(np.asarray(ids).ravel().tolist()))
    assert (np.asarray(u_k)[:nuniq] >= 0).all()
    assert (np.asarray(u_k)[nuniq:] == -1).all()


def test_dedup_ids_capacity_and_2d():
    ids = jnp.asarray([[3, 1], [3, 7]], jnp.int32)
    u, inv = dedup_ids(ids, capacity=8)
    assert u.shape == (8,) and inv.shape == (4,)
    np.testing.assert_array_equal(np.asarray(u)[np.asarray(inv)],
                                  np.asarray(ids).ravel())


# ---------------------------------------------------------------------------
# embedding_gather
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("d", [8, 130])  # below / past one lane tile
def test_embedding_gather_matches_reference(rng_np, dtype, d):
    v = 37
    table = jnp.asarray(rng_np.normal(size=(v, d)), dtype)
    ids = jnp.asarray(rng_np.integers(0, v, size=(11,)), jnp.int32)
    got = embedding_gather(table, ids, impl="kernel", interpret=True)
    ref = embedding_gather_reference(table, ids)
    assert got.dtype == table.dtype
    np.testing.assert_array_equal(got, ref)


def test_embedding_gather_2d_ids(rng_np):
    table = jnp.asarray(rng_np.normal(size=(16, 8)), jnp.float32)
    ids = jnp.asarray(rng_np.integers(0, 16, size=(3, 5)), jnp.int32)
    got = embedding_gather(table, ids, impl="kernel", interpret=True)
    assert got.shape == (3, 5, 8)
    np.testing.assert_array_equal(got, embedding_gather_reference(table, ids))


# ---------------------------------------------------------------------------
# embedding_scatter_add
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("case", ["dup", "unique", "all_dup", "ragged"])
def test_embedding_scatter_add_matches_reference(rng_np, case):
    v, d = 40, 8
    table = jnp.asarray(rng_np.normal(size=(v, d)), jnp.float32)
    ids = {
        "dup": [3, 1, 3, 7, 1, 3],
        "unique": [5, 2, 9, 0, 11, 38],
        "all_dup": [4, 4, 4, 4, 4],
        "ragged": [13],
    }[case]
    ids = jnp.asarray(ids, jnp.int32)
    rows = jnp.asarray(rng_np.normal(size=(ids.shape[0], d)), jnp.float32)
    got = embedding_scatter_add(table, ids, rows, impl="kernel",
                                interpret=True)
    ref = embedding_scatter_add_reference(table, ids, rows)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    # untouched rows pass through bit-identically
    touched = set(np.asarray(ids).tolist())
    keep = np.asarray([i for i in range(v) if i not in touched])
    np.testing.assert_array_equal(np.asarray(got)[keep],
                                  np.asarray(table)[keep])


def test_embedding_scatter_add_skips_negative_ids(rng_np):
    """-1 ids are the dedup fill convention: contribute nothing."""
    v, d = 16, 8
    table = jnp.asarray(rng_np.normal(size=(v, d)), jnp.float32)
    ids = jnp.asarray([2, -1, 5, -1], jnp.int32)
    rows = jnp.asarray(rng_np.normal(size=(4, d)), jnp.float32)
    got = embedding_scatter_add(table, ids, rows, impl="kernel",
                                interpret=True)
    ref = embedding_scatter_add_reference(table, ids, rows)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
    keep = np.asarray([i for i in range(v) if i not in (2, 5)])
    np.testing.assert_array_equal(np.asarray(got)[keep],
                                  np.asarray(table)[keep])


# ---------------------------------------------------------------------------
# sparse_row_update
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("momentum", [False, True])
@pytest.mark.parametrize("nesterov", [False, True])
def test_sparse_row_update_matches_reference(rng_np, momentum, nesterov):
    if nesterov and not momentum:
        pytest.skip("nesterov needs a velocity slot")
    v, d = 24, 8
    p = jnp.asarray(rng_np.normal(size=(v, d)), jnp.float32)
    g = jnp.asarray(rng_np.normal(size=(v, d)), jnp.float32)
    touched = jnp.asarray(rng_np.uniform(size=(v,)) < 0.3)
    g = jnp.where(touched[:, None], g, 0.0)  # sparse-row gradient
    vel = (jnp.asarray(rng_np.normal(size=(v, d)), jnp.float32)
           if momentum else None)
    kw = dict(lr=0.1, weight_decay=0.02)
    if momentum:
        kw.update(mu=0.9, nesterov=nesterov)
    p_k, v_k = sparse_row_update(p, g, vel, impl="kernel", interpret=True,
                                 **kw)
    p_r, v_r = sparse_row_update_reference(p, g, vel, **kw)
    np.testing.assert_allclose(p_k, p_r, rtol=1e-5, atol=1e-6)
    keep = ~np.asarray(touched)
    # lazy-sparse contract: untouched rows bit-identical (param AND slot)
    np.testing.assert_array_equal(np.asarray(p_k)[keep], np.asarray(p)[keep])
    if momentum:
        np.testing.assert_allclose(v_k, v_r, rtol=1e-5, atol=1e-6)
        np.testing.assert_array_equal(np.asarray(v_k)[keep],
                                      np.asarray(vel)[keep])
    else:
        assert v_k is None and v_r is None


# ---------------------------------------------------------------------------
# fused_embedding_lookup (custom_vjp) vs the dense oracle
# ---------------------------------------------------------------------------


def _dense_oracle(table, ids, padding_idx=None):
    got = jnp.take(table, ids, axis=0)
    if padding_idx is not None:
        got = jnp.where((ids == padding_idx)[..., None], 0.0,
                        got.astype(jnp.float32)).astype(table.dtype)
    return got


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("ids", [
    [3, 1, 3, 7, 1, 0],          # duplicates
    [5, 2, 9, 0],                # all unique
    [4, 4, 4],                   # all duplicate
    [11],                        # ragged
])
def test_fused_embedding_lookup_fwd_and_vjp(rng_np, dtype, ids):
    v, d = 16, 8
    table = jnp.asarray(rng_np.normal(size=(v, d)), dtype)
    ids = jnp.asarray(ids, jnp.int32)
    got = fused_embedding_lookup(table, ids, None, "kernel", True)
    np.testing.assert_array_equal(got, _dense_oracle(table, ids))

    def loss_fused(tbl):
        out = fused_embedding_lookup(tbl, ids, None, "kernel", True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    def loss_dense(tbl):
        return jnp.sum(_dense_oracle(tbl, ids).astype(jnp.float32) ** 2)

    gk = jax.grad(loss_fused)(table)
    gr = jax.grad(loss_dense)(table)
    assert gk.dtype == table.dtype
    tol = dict(rtol=1e-5, atol=1e-6) if dtype == jnp.float32 else \
        dict(rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(gk, np.float32),
                               np.asarray(gr, np.float32), **tol)
    # duplicate ids accumulate exactly: rows never in ids get zero grad
    untouched = np.asarray([i for i in range(v)
                            if i not in set(np.asarray(ids).tolist())])
    np.testing.assert_array_equal(np.asarray(gk)[untouched], 0.0)


def test_fused_embedding_lookup_padding_idx(rng_np):
    v, d = 12, 8
    table = jnp.asarray(rng_np.normal(size=(v, d)), jnp.float32)
    ids = jnp.asarray([0, 3, 0, 5], jnp.int32)
    got = fused_embedding_lookup(table, ids, 0, "kernel", True)
    np.testing.assert_array_equal(got, _dense_oracle(table, ids, 0))

    g = jax.grad(lambda tbl: jnp.sum(
        fused_embedding_lookup(tbl, ids, 0, "kernel", True)))(table)
    # the padding row receives NO gradient
    np.testing.assert_array_equal(np.asarray(g)[0], 0.0)
    np.testing.assert_array_equal(np.asarray(g)[3], 1.0)


def test_fused_embedding_lookup_2d_ids_under_jit(rng_np):
    v, d = 16, 8
    table = jnp.asarray(rng_np.normal(size=(v, d)), jnp.float32)
    ids = jnp.asarray(rng_np.integers(0, v, size=(3, 5)), jnp.int32)

    @jax.jit
    def f(tbl):
        out = fused_embedding_lookup(tbl, ids, None, "kernel", True)
        return jnp.sum(out ** 2)

    got = jax.grad(f)(table)
    ref = jax.grad(lambda tbl: jnp.sum(
        _dense_oracle(tbl, ids) ** 2))(table)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
