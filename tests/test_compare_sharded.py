"""Sharded-vs-local convergence-equality tests — the reference's strongest
correctness pattern, rebuilt for the mesh world:

- ``test_CompareTwoNets.cpp:50,107,170-177``: two setups training the same
  model must produce identical gradients/parameters.  Here: the SAME model
  trained with trainer_count=1 (no mesh) vs an 8-device data-parallel mesh
  at the same global batch must end with equal parameters.
- ``test_CompareSparse.cpp:48-67,140``: multi-trainer sparse-embedding
  training vs local must produce equal parameter tables.  Here: the CTR
  wide&deep sparse-gather path on the 8-device mesh vs local.
- ``test_NetworkCompare.cpp`` + ``concat_dotmul_a.conf``/``_b.conf``: two
  differently-written configs computing the same function must produce
  identical outputs and gradients.  Here: the literal reference config
  files are parsed and executed (skipped if the reference checkout is
  absent).

All runs use f32 compute so the only divergence source is cross-device
reduction order (tolerance 1e-5).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.config.topology import Topology
from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.layers import activation as act
from paddle_tpu.layers import api as layer
from paddle_tpu.layers import base, data_type
from paddle_tpu.optimizer import AdaGrad, Momentum
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.trainer.step import build_train_step

REF = "/root/reference"


def _train(topo, opt, params, feeds, mesh=None):
    """Run len(feeds) steps; returns final params dict (host numpy)."""
    # the jitted step donates params/opt_state/states; copy so the caller's
    # arrays survive for the second run
    params = {k: jnp.array(v) for k, v in params.items()}
    specs = {s.name: s for s in topo.param_specs()}
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    if mesh is not None:
        params = mesh.place_params(params, specs)
        opt_state = mesh.replicate(opt_state)
        states = mesh.replicate(states)
    step = build_train_step(topo, opt, mesh=mesh)
    key = jax.random.key(0)
    for feed in feeds:
        if mesh is not None:
            feed = mesh.shard_batch(feed)
        params, opt_state, states, cost, _ = step(
            params, opt_state, states, feed, key)
    assert np.isfinite(float(cost))
    return {k: np.asarray(v) for k, v in params.items()}


def _mlp_cost(in_dim=24, classes=4):
    img = layer.data(name="x", type=data_type.dense_vector(in_dim))
    h = layer.fc(input=img, size=32, act=act.ReluActivation())
    h = layer.fc(input=h, size=16, act=act.TanhActivation())
    predict = layer.fc(input=h, size=classes, act=act.SoftmaxActivation())
    lab = layer.data(name="y", type=data_type.integer_value(classes))
    return layer.classification_cost(input=predict, label=lab)


def test_dp8_parameters_equal_local():
    """trainer_count=1 vs 8-way DP at the same global batch -> same params
    (test_CompareTwoNets analog on the virtual mesh)."""
    rng = np.random.default_rng(3)
    in_dim, classes, bs, steps = 24, 4, 32, 5
    feeds = [
        {"x": jnp.asarray(rng.normal(size=(bs, in_dim)).astype(np.float32)),
         "y": jnp.asarray(rng.integers(0, classes, size=(bs,)))}
        for _ in range(steps)
    ]

    base.reset_name_counters()
    topo = Topology(_mlp_cost(in_dim, classes))
    params0 = paddle.parameters.create(topo).as_dict()
    opt = Momentum(momentum=0.9, learning_rate=0.05)

    local = _train(topo, opt, dict(params0), feeds)

    ctx = mesh_mod.MeshContext(mesh=mesh_mod.make_mesh({"data": 8}))
    sharded = _train(topo, opt, dict(params0), feeds, mesh=ctx)

    assert local.keys() == sharded.keys()
    for name in local:
        np.testing.assert_allclose(
            local[name], sharded[name], rtol=2e-5, atol=2e-5,
            err_msg=f"parameter {name} diverged between local and 8-way DP")


def test_sparse_ctr_dp_equals_local():
    """Sparse-embedding CTR trained sharded vs local -> equal tables
    (test_CompareSparse.cpp:140 analog)."""
    from paddle_tpu.models.ctr import wide_and_deep_ctr

    rng = np.random.default_rng(5)
    vocabs, wide_dim, bs, steps = [64] * 3, 128, 32, 4

    def make_feed():
        feed = {"label": jnp.asarray(rng.integers(0, 2, size=(bs,)))}
        wide = np.zeros((bs, wide_dim), np.float32)
        for r in range(bs):
            wide[r, rng.integers(0, wide_dim, size=3)] = 1.0
        feed["wide_input"] = jnp.asarray(wide)
        for i, v in enumerate(vocabs):
            feed[f"cat_{i}"] = jnp.asarray(rng.integers(0, v, size=(bs,)))
        return feed

    feeds = [make_feed() for _ in range(steps)]

    base.reset_name_counters()
    cost, _, _ = wide_and_deep_ctr(
        wide_dim=wide_dim, categorical_vocab_sizes=vocabs,
        embedding_size=8, hidden_sizes=(16,))
    topo = Topology(cost)
    params0 = paddle.parameters.create(topo).as_dict()
    opt = AdaGrad(learning_rate=0.05)

    local = _train(topo, opt, dict(params0), feeds)
    ctx = mesh_mod.MeshContext(mesh=mesh_mod.make_mesh({"data": 8}))
    sharded = _train(topo, opt, dict(params0), feeds, mesh=ctx)

    emb_names = [n for n in local if "emb" in n.lower()] or list(local)
    for name in local:
        np.testing.assert_allclose(
            local[name], sharded[name], rtol=3e-5, atol=3e-5,
            err_msg=f"CTR parameter {name} diverged (sparse path)")
    assert emb_names, "expected embedding tables in the CTR model"


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference checkout absent")
@pytest.mark.parametrize("pair", ["concat_dotmul", "concat_fullmatrix"])
def test_network_compare_reference_configs(pair):
    """Two equivalent reference configs -> identical outputs and input
    gradients (test_NetworkCompare.cpp analog, executing the reference's own
    concat_*_a.conf / concat_*_b.conf)."""
    from paddle_tpu.trainer.config_parser import parse_config

    confs = [
        os.path.join(REF, "paddle/gserver/tests", f"{pair}_{s}.conf")
        for s in ("a", "b")
    ]
    if not all(os.path.isfile(c) for c in confs):
        pytest.skip("reference confs missing")

    outs, grads, shapes = [], [], []
    rng = np.random.default_rng(11)
    x = None

    for conf in confs:
        base.reset_name_counters()
        parsed = parse_config(conf, "")
        topo = Topology(parsed.output_layers())
        if x is None:
            in_dim = topo.data_layers()["input"].attrs["dim"]
            x = rng.normal(size=(4, in_dim)).astype(np.float32) * 0.1
        specs = list(topo.param_specs())
        # deterministic identical init by creation order: the a/b configs
        # declare the same parameters in the same data-flow order
        params = {}
        for i, s in enumerate(specs):
            r = np.random.default_rng(100 + i)
            params[s.name] = jnp.asarray(
                r.normal(size=s.shape).astype(np.float32) * 0.05)
        shapes.append([tuple(s.shape) for s in specs])
        states = topo.init_states()
        out_name = topo.outputs[0].name

        def fwd(params, x):
            values, _ = topo.forward(
                params, states, {"input": jnp.asarray(x)}, False,
                jax.random.key(0))
            return values[out_name]

        out = np.asarray(fwd(params, x))
        g = jax.grad(
            lambda p: jnp.sum(jnp.cos(fwd(p, x))))(params)
        outs.append(out)
        grads.append({i: np.asarray(g[s.name])
                      for i, s in enumerate(specs)})

    assert shapes[0] == shapes[1], (
        "a/b configs declare different parameter shapes")
    np.testing.assert_allclose(
        outs[0], outs[1], rtol=1e-6, atol=1e-6,
        err_msg=f"{pair}: outputs differ between equivalent configs")
    for i in grads[0]:
        np.testing.assert_allclose(
            grads[0][i], grads[1][i], rtol=1e-6, atol=1e-6,
            err_msg=f"{pair}: gradient {i} differs between equivalent configs")


def test_transformer_tp_dp_parameters_equal_local():
    """Flagship-model CompareTwoNets: the SAME transformer trained 3 steps
    on a 2x2 {data, model} mesh (Megatron TP + DP) vs unsharded must end
    with equal parameters — the full train-step (fwd+bwd+Adam) sharding
    invariance, not just a first-step loss check."""
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    cfg = T.TransformerConfig(vocab_size=64, num_layers=2, num_heads=2,
                              embed_dim=16, mlp_dim=32, max_seq_len=32,
                              remat=False, attn_impl="exact")
    params0 = T.init_params(cfg, jax.random.key(0))
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 17)))

    def run(mesh):
        params = jax.tree.map(jnp.array, params0)
        if mesh is not None:
            params = T.place_params(params, mesh, cfg)
            ids_d = jax.device_put(ids, NamedSharding(mesh, P("data", None)))
        else:
            ids_d = ids
        opt = Adam(learning_rate=1e-2)
        state = opt.init_tree(params)
        step = T.build_train_step(cfg, opt, mesh=mesh)
        for _ in range(3):
            params, state, loss = step(params, state, ids_d)
        assert np.isfinite(float(loss))
        return jax.tree.map(np.asarray, params)

    local = run(None)
    devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
    sharded = run(Mesh(devs, ("data", "model")))

    flat_l, _ = jax.tree.flatten(local)
    flat_s, _ = jax.tree.flatten(sharded)
    for i, (a, b) in enumerate(zip(flat_l, flat_s)):
        np.testing.assert_allclose(
            a, b, rtol=2e-4, atol=2e-4,
            err_msg=f"transformer param leaf {i} diverged under TP+DP")


def test_nmt_decoder_group_dp_equals_local():
    """The round-5 decoder path (recurrent_group with a SUNK softmax
    tail + fused logits-CE) under 8-way DP equals local training — the
    sink/fused-CE machinery must compose with mesh sharding."""
    from paddle_tpu.core import flags, rng as prng
    from paddle_tpu.models import seqtoseq as S

    prev_bf16 = flags.get("bf16")
    flags.set("bf16", False)
    try:
        vocab, bs, tlen, steps = 40, 16, 5, 3
        rng = np.random.default_rng(5)

        def seq():
            return SequenceBatch(
                data=jnp.asarray(rng.integers(0, vocab, size=(bs, tlen))),
                length=jnp.full((bs,), tlen, jnp.int32))

        feeds = [{"source_language_word": seq(),
                  "target_language_word": seq(),
                  "target_language_next_word": seq()}
                 for _ in range(steps)]

        def build():
            base.reset_name_counters()
            cost = S.seqtoseq_net(vocab, vocab, word_vector_dim=8,
                                  encoder_size=8, decoder_size=8)
            topo = Topology(cost)
            # the fused path must actually be engaged
            assert any(n.name.endswith("#logits") for n in topo.nodes)
            prng.seed(17)
            return topo, paddle.parameters.create(topo).as_dict()

        topo, params0 = build()
        opt = Momentum(momentum=0.9, learning_rate=0.05)
        local = _train(topo, opt, dict(params0), feeds)

        topo2, params2 = build()
        for k in params0:
            np.testing.assert_array_equal(np.asarray(params0[k]),
                                          np.asarray(params2[k]))
        ctx = mesh_mod.MeshContext(mesh=mesh_mod.make_mesh({"data": 8}))
        sharded = _train(topo2, opt, dict(params2), feeds, mesh=ctx)

        assert local.keys() == sharded.keys()
        for name in local:
            np.testing.assert_allclose(
                local[name], sharded[name], rtol=3e-5, atol=3e-5,
                err_msg=f"parameter {name} diverged (sunk decoder, DP8)")
    finally:
        flags.set("bf16", prev_bf16)
