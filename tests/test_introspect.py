"""tracewire — span tracing, the per-rank introspection server, and
windowed device profiling (telemetry/tracing.py + introspect.py +
tools/trace_merge.py).

The acceptance surface: a 4-step CPU train with --status_port serves
parseable /metrics /healthz /snapshot MID-RUN and /trace yields a valid
Chrome trace whose feed/compute/fence spans nest per step; a disabled
tracer is a no-op (bit-identical trajectory); trace_merge over a 2-rank
launch produces one timeline with both rank lanes; the introspection
server survives a concurrent scrape loop under train/serve load with
zero GL-THREAD findings.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import metrics as metrics_mod
from paddle_tpu.core import flags
from paddle_tpu.telemetry import MemorySink, MetricsRegistry, introspect
from paddle_tpu.telemetry.tracing import (
    ProfileWindow,
    Tracer,
    parse_profile_steps,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PY = sys.executable


@pytest.fixture(autouse=True)
def _restore_flags():
    snap = flags.snapshot_raw()
    yield
    flags.restore_raw(snap)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(port: int, path: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode()


def _fake_clock(start=100.0, tick=0.5):
    state = {"t": start}

    def clock():
        state["t"] += tick
        return state["t"]

    return clock


# -- Tracer core ---------------------------------------------------------------


class TestTracer:
    def test_deterministic_ids_and_fake_clock_durations(self):
        t = Tracer(enabled=True, rank=3, clock=_fake_clock(0.0, 1.0))
        with t.span("step", batch_id=0):
            with t.span("feed"):
                pass
        spans = {s.name: s for s in t.spans}
        # ids are rank*2**32 + seq, allocated in begin order
        assert spans["step"].span_id == 3 * (1 << 32)
        assert spans["feed"].span_id == 3 * (1 << 32) + 1
        assert spans["feed"].parent_id == spans["step"].span_id
        # fake clock: step spans ticks 1..4, feed 2..3 — exact durations
        assert spans["feed"].dur_ms == pytest.approx(1000.0)
        assert spans["step"].dur_ms == pytest.approx(3000.0)
        # a second identical run allocates identical ids
        t2 = Tracer(enabled=True, rank=3, clock=_fake_clock(0.0, 1.0))
        with t2.span("step", batch_id=0):
            with t2.span("feed"):
                pass
        assert [s.span_id for s in t2.spans] == \
            [s.span_id for s in t.spans]

    def test_disabled_tracer_is_a_shared_noop(self):
        t = Tracer(enabled=False)
        cm1 = t.span("a")
        cm2 = t.span("b", arg=1)
        assert cm1 is cm2  # one shared object: no allocation per call
        with cm1:
            pass
        assert t.begin("x") is None
        t.end(None)  # tolerated, so call sites skip the flag re-check
        assert t.add_span("y", 0.0, 1.0) is None
        assert t.spans == []

    def test_nesting_is_per_thread(self):
        t = Tracer(enabled=True, rank=0)
        tok = t.begin("main_parent")
        seen = {}

        def worker():
            with t.span("worker_span"):
                pass
            seen["spans"] = [s for s in t.spans
                             if s.name == "worker_span"]

        th = threading.Thread(target=worker)
        th.start()
        th.join()
        t.end(tok)
        # the worker's span must NOT be parented under the main
        # thread's open span — stacks are thread-local
        assert seen["spans"][0].parent_id is None
        assert seen["spans"][0].thread != \
            [s for s in t.spans if s.name == "main_parent"][0].thread

    def test_end_truncates_abandoned_children(self):
        t = Tracer(enabled=True, rank=0)
        outer = t.begin("outer")
        t.begin("leaked")  # an exception path never closed this
        t.end(outer)
        with t.span("next_top"):
            pass
        nxt = [s for s in t.spans if s.name == "next_top"][0]
        assert nxt.parent_id is None  # not re-parented under "leaked"

    def test_retrospective_spans_and_drain(self):
        t = Tracer(enabled=True, rank=1)
        parent = t.add_span("request", 1.0, 5.0, cat="serving", request=7)
        t.add_span("queue", 1.0, 2.0, parent_id=parent, request=7)
        assert [s.name for s in t.spans] == ["request", "queue"]
        drained = t.drain()
        assert len(drained) == 2 and t.spans == []

    def test_chrome_trace_shape(self):
        t = Tracer(enabled=True, rank=2, clock=_fake_clock())
        with t.span("step", cat="trainer", batch_id=4):
            pass
        ct = t.chrome_trace()
        names = {e["name"] for e in ct["traceEvents"]}
        assert "process_name" in names and "step" in names
        x = [e for e in ct["traceEvents"] if e.get("ph") == "X"][0]
        assert x["pid"] == 2 and x["args"]["batch_id"] == 4
        assert x["dur"] > 0 and "ts" in x
        json.dumps(ct)  # serializable as-is

    def test_phase_summary_percentiles(self):
        t = Tracer(enabled=True, rank=0)
        for ms in (1.0, 2.0, 3.0, 4.0):
            t.add_span("feed", 0.0, ms / 1e3)
        s = t.phase_summary()["feed"]
        assert s["count"] == 4
        assert s["total_ms"] == pytest.approx(10.0)
        assert s["p50_ms"] == pytest.approx(2.5)
        assert s["max_ms"] == pytest.approx(4.0)

    def test_ring_capacity_drops_oldest(self):
        t = Tracer(enabled=True, rank=0, capacity=3)
        for i in range(5):
            t.add_span(f"s{i}", 0.0, 1.0)
        assert [s.name for s in t.spans] == ["s2", "s3", "s4"]
        assert t.dropped == 2


def test_parse_profile_steps():
    assert parse_profile_steps("") is None
    assert parse_profile_steps(None) is None
    assert parse_profile_steps("2:5") == (2, 5)
    assert parse_profile_steps("3") == (3, 4)
    with pytest.raises(ValueError):
        parse_profile_steps("5:2")


# -- histogram None-safety (the satellite fix) ---------------------------------


class TestEmptyHistograms:
    def test_summary_of_zero_count_is_json_safe(self):
        from paddle_tpu.telemetry.registry import Histogram, _Hist

        reg = MetricsRegistry("t")
        h = reg.histogram("h", "help")
        # force the pathological series a bug could leave behind
        with reg._lock:
            h._series[()] = _Hist(buckets=[0] * 13)
        s = h.summary()
        assert s["count"] == 0 and s["min"] == 0.0 and s["max"] == 0.0
        assert s["p99"] == 0.0
        json.dumps(s)  # no Infinity leaks into JSON
        assert h.percentile(99) is None
        assert isinstance(h, Histogram)

    def test_engine_summary_skips_empty_histograms(self, tmp_path):
        # emit_summary over a registry whose latency histograms exist
        # but have zero observations must not roll them up
        from paddle_tpu.serving.engine import _LAT_HISTS

        reg = MetricsRegistry("t")
        sink = MemorySink()
        reg.add_sink(sink)
        for name in _LAT_HISTS:
            reg.histogram(name, "empty")
        reg.histogram("serve_ttft_ms", "").observe(10.0)

        class _Eng:  # just the summary path, no engine build
            registry = reg
            scheduler = type("S", (), {"rejected_admissions": 0})()
            cache = type("C", (), {"prefix": None})()
            serving = type("V", (), {"incremental_prefill": False})()

        from paddle_tpu.serving.engine import ServingEngine

        ServingEngine.emit_summary(_Eng)
        rec = [r for r in sink.records
               if r.get("kind") == "serve_summary"][0]
        assert set(rec["summary"]) == {"serve_ttft_ms"}

    def test_exposition_skips_empty_histograms(self):
        reg = MetricsRegistry("t")
        reg.histogram("observed", "x").observe(2.0)
        reg.histogram("empty", "y")
        text = introspect.render_prometheus(reg)
        assert "observed_count 1" in text
        assert "empty" not in text
        assert "NaN" not in text and "inf" not in text


# -- prometheus render / parse -------------------------------------------------


def test_prometheus_roundtrip_with_labels():
    reg = MetricsRegistry("t")
    reg.counter("reqs", "c").inc(3, reason="ok")
    reg.counter("reqs", "c").inc(1, reason='we"ird')
    reg.gauge("depth", "g").set(7.5)
    reg.histogram("lat", "h").observe(12.0)
    text = introspect.render_prometheus(reg)
    parsed = introspect.parse_prometheus(text)
    assert parsed[("reqs", (("reason", "ok"),))] == 3.0
    assert parsed[("depth", ())] == 7.5
    assert parsed[("lat_count", ())] == 1.0
    assert parsed[("lat_sum", ())] == 12.0
    cum = [v for (n, labels), v in parsed.items() if n == "lat_bucket"]
    assert max(cum) == 1.0
    # aggregation sums across replicas
    agg = introspect.aggregate_prometheus([text, text])
    assert agg[("reqs", (("reason", "ok"),))] == 6.0


# -- the 4-step acceptance run -------------------------------------------------


def _tiny_trainer(lr=0.05):
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type

    base.reset_name_counters()
    x = layer.data(name="px", type=data_type.dense_vector(6))
    h = layer.fc(input=x, size=4, act=act.SoftmaxActivation())
    lbl = layer.data(name="py", type=data_type.integer_value(4))
    cost = layer.classification_cost(input=h, label=lbl)
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    return paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.SGD(learning_rate=lr))


def _batches(n_samples=32, batch=8):
    rng = np.random.default_rng(0)
    data = [(rng.normal(size=(6,)).astype(np.float32), int(i % 4))
            for i in range(n_samples)]
    return paddle.reader.batch(lambda: iter(data), batch)


def _run_train(trace_spans: bool, status_port=0, scrape_at=None,
               profile_steps="", n_samples=32, registry=None):
    from paddle_tpu.core import rng
    from paddle_tpu.telemetry.tracing import get_tracer

    rng.seed(7)
    get_tracer().configure(enabled=trace_spans)
    get_tracer().clear()
    flags.set("trace_spans", trace_spans)
    flags.set("status_port", status_port)
    flags.set("profile_steps", profile_steps)
    trainer = _tiny_trainer()
    reg = registry or MetricsRegistry("test_introspect")
    sink = MemorySink()
    reg.add_sink(sink)
    scraped = {}

    def handler(e):
        if (scrape_at is not None
                and isinstance(e, paddle.event.EndIteration)
                and e.batch_id == scrape_at and not scraped):
            for path in ("/metrics", "/healthz", "/snapshot", "/trace"):
                scraped[path] = _get(status_port, path)

    trainer.train(reader=_batches(n_samples), num_passes=1,
                  event_handler=handler, metrics_registry=reg)
    steps = [r for r in sink.records if r.get("kind") == "step"]
    return trainer, steps, scraped, sink


def test_four_step_train_serves_all_endpoints_midrun():
    """The acceptance run: 4 steps on CPU with --status_port; /metrics,
    /healthz, /snapshot parse mid-run and /trace is a valid Chrome
    trace whose feed/compute/fence spans nest per step."""
    port = _free_port()
    trainer, steps, scraped, _ = _run_train(
        True, status_port=port, scrape_at=3)
    assert len(steps) == 4
    assert set(scraped) == {"/metrics", "/healthz", "/snapshot",
                            "/trace"}

    st, text = scraped["/metrics"]
    assert st == 200
    parsed = introspect.parse_prometheus(text)  # the tiny parser
    # by batch 3's EndIteration, 4 steps retired into the step counter
    assert parsed[("steps", (("run", "train"),))] == 4.0
    assert any(n == "step_ms_count" for n, _l in parsed)

    st, health = scraped["/healthz"]
    health = json.loads(health)
    assert st == 200 and health["ok"] is True
    assert health["heartbeat"]["age_s"] >= 0.0

    st, snap = scraped["/snapshot"]
    snap = json.loads(snap)
    # the flight ring is inspectable BEFORE any crash
    assert any(h.get("tag") == "begin_batch"
               for h in snap["flight"]["heartbeats"])
    assert "metrics" in snap and "census" in snap

    st, trace = scraped["/trace"]
    trace = json.loads(trace)
    events = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    by_name: dict = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    # sync_period=1: steps 0..2 fully retired (fence included) by the
    # time batch 3's EndIteration fires inside its own fence
    assert len(by_name["step"]) >= 3
    assert len(by_name["fence"]) >= 3
    step_ids = {e["args"]["id"]: e for e in by_name["step"]}
    for child in ("feed", "compute", "fence"):
        nested = [e for e in by_name[child]
                  if e["args"].get("parent") in step_ids]
        assert len(nested) >= 3, f"{child} spans not nested under steps"
        for e in nested:
            parent = step_ids[e["args"]["parent"]]
            # 5e-3 us slack: ts/dur are rounded to ns in the export
            assert parent["ts"] <= e["ts"] + 5e-3
            assert e["ts"] + e["dur"] <= \
                parent["ts"] + parent["dur"] + 5e-3

    # after train() the server is down: the port no longer accepts
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(port, "/healthz")


def test_disabled_tracing_is_bitwise_noop():
    """The no-op guard: tracing off vs on must not change the
    trajectory AT ALL, and tracing off must record nothing."""
    from paddle_tpu.telemetry.tracing import get_tracer

    tr_off, steps_off, _, _ = _run_train(False)
    assert get_tracer().spans == []  # nothing recorded, nothing leaked
    tr_on, steps_on, _, _ = _run_train(True)
    assert len(get_tracer().spans) > 0
    np.testing.assert_array_equal(
        np.asarray([r["loss"] for r in steps_off]),
        np.asarray([r["loss"] for r in steps_on]),
        err_msg="span tracing changed the training trajectory")
    for name in tr_off.parameters.names():
        np.testing.assert_array_equal(
            np.asarray(tr_off.parameters[name]),
            np.asarray(tr_on.parameters[name]))


def test_profile_steps_window_emits_record(tmp_path):
    flags.set("profile_dir", str(tmp_path / "prof"))
    _, steps, _, sink = _run_train(True, profile_steps="1:3")
    prof = [r for r in sink.records if r.get("kind") == "profile"]
    assert len(prof) == 1
    rec = prof[0]
    assert rec["start_step"] == 1 and rec["end_step"] == 3
    assert rec["schema"] == "paddle_tpu.metrics/15"
    assert rec["trace_dir"] == str(tmp_path / "prof")
    assert os.path.isdir(rec["trace_dir"])  # the device capture landed
    assert rec["spans"]["compute"]["count"] == 2  # the window's steps
    assert rec["wall_ms"] > 0


def test_profile_window_closes_when_run_is_shorter_than_B(tmp_path):
    flags.set("profile_dir", str(tmp_path / "prof2"))
    _, steps, _, sink = _run_train(True, profile_steps="2:100")
    prof = [r for r in sink.records if r.get("kind") == "profile"]
    assert len(prof) == 1  # close() at train() exit emitted it
    assert prof[0]["start_step"] == 2


def test_metrics_to_md_renders_trace_spans_table(tmp_path, capsys):
    flags.set("profile_dir", str(tmp_path / "prof3"))
    _, _, _, sink = _run_train(True, profile_steps="0:4")
    jsonl = tmp_path / "m.jsonl"
    from paddle_tpu.telemetry.sinks import json_default

    with open(jsonl, "w") as f:
        for r in sink.records:
            f.write(json.dumps(r, default=json_default) + "\n")
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import metrics_to_md
    finally:
        sys.path.pop(0)
    metrics_to_md.main([str(jsonl)])
    out = capsys.readouterr().out
    assert "## Trace spans" in out
    assert "| phase |" in out and "| compute |" in out
    # a fence phase >20% of step time gets flagged
    fake = {"kind": "profile", "start_step": 0, "end_step": 2,
            "wall_ms": 10.0, "trace_dir": "/tmp/x",
            "spans": {"step": {"count": 2, "total_ms": 100.0,
                               "p50_ms": 50.0, "p99_ms": 50.0,
                               "max_ms": 50.0},
                      "fence": {"count": 2, "total_ms": 40.0,
                                "p50_ms": 20.0, "p99_ms": 20.0,
                                "max_ms": 20.0}}}
    metrics_to_md.trace_table([fake])
    out = capsys.readouterr().out
    assert "⚠" in out and "fence" in out


# -- concurrent scrape under load (the satellite test) -------------------------


def test_concurrent_scrape_under_train_and_fleet_load():
    """A scrape loop hammers every endpoint while a 2-step train runs,
    then while a local fleet pumps; every /metrics snapshot parses and
    the new modules carry zero GL-THREAD findings."""
    port = _free_port()
    stop = threading.Event()
    results = {"scrapes": 0, "errors": []}

    def scrape_loop():
        while not stop.is_set():
            for path in ("/metrics", "/healthz", "/snapshot",
                         "/trace?keep=1"):
                try:
                    st, body = _get(port, path)
                    if path == "/metrics":
                        introspect.parse_prometheus(body)  # must parse
                    elif path != "/metrics":
                        json.loads(body)
                    results["scrapes"] += 1
                except urllib.error.HTTPError as e:
                    if e.code != 503:  # dead-loop verdicts are legal
                        results["errors"].append(f"{path}: {e}")
                except (urllib.error.URLError, ConnectionError,
                        OSError):
                    pass  # server not up yet / shut down between runs
                except Exception as e:  # noqa: BLE001 - the assertion
                    results["errors"].append(f"{path}: {e!r}")

    th = threading.Thread(target=scrape_loop, daemon=True)
    th.start()
    try:
        # phase 1: scrape during a 2-step train
        _run_train(True, status_port=port, n_samples=16)
        # phase 2: scrape during a fleet pump on the same port
        import jax

        from paddle_tpu.models import transformer as T
        from paddle_tpu.serving import ServingConfig
        from paddle_tpu.serving.fleet import build_local_fleet

        cfg = T.TransformerConfig(
            vocab_size=64, num_layers=1, num_heads=2, embed_dim=32,
            mlp_dim=64, max_seq_len=64, remat=False)
        params = T.init_params(cfg, jax.random.key(1))
        reg = MetricsRegistry("fleet_scrape")
        router = build_local_fleet(
            cfg, params,
            ServingConfig(max_slots=2, page_size=4, num_pages=32,
                          max_prompt_len=8, max_new_tokens=4, seed=0),
            n=2, registry=reg)
        srv = introspect.IntrospectionServer(registry=reg, port=port)
        srv.start()
        srv.add_health("fleet_pump",
                       lambda: router._loop_error_now() is None)
        rng = np.random.default_rng(0)
        for i in range(4):
            router.submit(list(rng.integers(1, 64, size=3)),
                          max_new_tokens=3)
        router.run_until_idle()
        assert router.stats()["requests_lost"] == 0
        srv.stop()
    finally:
        stop.set()
        th.join(timeout=10)
    assert results["errors"] == []
    assert results["scrapes"] > 0  # the loop really scraped mid-run

    # zero GL-THREAD/GL-LOCKORDER findings over the new modules
    from paddle_tpu.analysis.codebase import (
        THREADED_MODULES,
        iter_corpus,
        pass_lock_order,
        pass_thread_safety,
    )
    from paddle_tpu.analysis.core import repo_root

    mods = ("paddle_tpu/telemetry/tracing.py",
            "paddle_tpu/telemetry/introspect.py")
    assert all(m in THREADED_MODULES for m in mods)
    corpus = iter_corpus(repo_root(), files=list(mods))
    assert pass_thread_safety(corpus, repo_root(), modules=mods) == []
    assert pass_lock_order(corpus, repo_root(), modules=mods) == []


# -- serving lifecycle spans + fleet scrape aggregator -------------------------


@pytest.mark.serving
def test_serving_request_lifecycle_spans_and_scrape_rollup():
    import jax

    from paddle_tpu.models import transformer as T
    from paddle_tpu.serving import ServingConfig
    from paddle_tpu.serving.engine import ServingEngine
    from paddle_tpu.serving.fleet import build_local_fleet
    from paddle_tpu.telemetry.tracing import get_tracer

    get_tracer().configure(enabled=True)
    get_tracer().clear()
    cfg = T.TransformerConfig(
        vocab_size=64, num_layers=1, num_heads=2, embed_dim=32,
        mlp_dim=64, max_seq_len=64, remat=False)
    params = T.init_params(cfg, jax.random.key(1))
    reg = MetricsRegistry("lifecycle")
    eng = ServingEngine(
        cfg, params,
        ServingConfig(max_slots=2, page_size=4, num_pages=32,
                      max_prompt_len=8, max_new_tokens=4, seed=0),
        registry=reg)
    res = eng.generate([[5, 17, 3], [9, 2]], max_new_tokens=3)
    assert all(len(r.tokens) >= 1 for r in res)
    spans = get_tracer().spans
    by_name: dict = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    # live batch spans + per-request retrospective lifecycles
    assert by_name["serve_prefill"] and by_name["serve_decode"]
    assert len(by_name["request"]) == 2
    req_ids = {s.span_id for s in by_name["request"]}
    for phase in ("queue", "prefill", "decode"):
        assert len(by_name[phase]) == 2
        assert all(s.parent_id in req_ids for s in by_name[phase])
    # queue -> prefill -> decode tile the request interval in order
    for r in by_name["request"]:
        kids = sorted((s for s in spans
                       if s.parent_id == r.span_id),
                      key=lambda s: s.t_start)
        assert [k.name for k in kids] == ["queue", "prefill", "decode"]
        assert kids[0].t_start >= r.t_start - 1e-9
        assert kids[-1].t_end <= r.t_end + 1e-9
    get_tracer().configure(enabled=False)

    # the FleetRouter-side aggregator: two replica /metrics endpoints
    # folded into one fleet rollup record
    regs = [MetricsRegistry(f"replica{i}") for i in range(2)]
    for i, r in enumerate(regs):
        r.counter("serve_tokens", "t").inc(10 * (i + 1))
        r.gauge("serve_free_pages", "p").set(5)
    servers = [introspect.IntrospectionServer(registry=r, port=0)
               for r in regs]
    urls = [f"http://127.0.0.1:{s.start()}/metrics" for s in servers]
    fleet_reg = MetricsRegistry("fleet")
    sink = MemorySink()
    fleet_reg.add_sink(sink)
    router = build_local_fleet(
        cfg, params,
        ServingConfig(max_slots=2, page_size=4, num_pages=32,
                      max_prompt_len=8, max_new_tokens=4, seed=0),
        n=1, registry=fleet_reg)
    rollup = router.scrape_replicas(urls + ["http://127.0.0.1:9/metrics"])
    for s in servers:
        s.stop()
    assert rollup["replicas_scraped"] == 2
    assert rollup["serve_tokens"] == 30.0
    assert rollup["serve_free_pages"] == 10.0
    assert len(rollup["scrape_errors"]) == 1  # the dead endpoint, named
    recs = [r for r in sink.records
            if r.get("kind") == "fleet" and r.get("event") == "scrape"]
    assert recs and recs[0]["serve_tokens"] == 30.0


# -- 2-rank launch + trace_merge (the fleet timeline) --------------------------


_RANK_TRACE_CHILD = r"""
import os, sys
sys.path.insert(0, os.environ["REPO_ROOT"])
# create the backend FIRST: a local-fleet rank is its own single-process
# jax world where process_index() is 0 on EVERY rank — host_index must
# prefer the launcher's PADDLE_TPU_TRAINER_ID stamp or both ranks dump
# trace-host0.json and clobber each other (regression: the real-CLI
# 2-rank drive caught exactly this)
import jax
jax.config.update("jax_platforms", "cpu")
jax.devices()
from paddle_tpu.telemetry.tracing import Tracer
t = Tracer(enabled=True)  # rank from PADDLE_TPU_TRAINER_ID
assert t.rank == int(os.environ["PADDLE_TPU_TRAINER_ID"])
with t.span("step", cat="trainer", batch_id=0):
    with t.span("feed"):
        pass
    with t.span("compute"):
        pass
t.dump(os.path.join(os.environ["TRACE_OUT"],
                    "trace-host%d.json" % t.rank))
"""


def test_trace_merge_over_two_rank_launch(tmp_path):
    from paddle_tpu.distributed.launch import launch_local

    out = tmp_path / "traces"
    out.mkdir()
    env = dict(os.environ, TRACE_OUT=str(out), REPO_ROOT=REPO)
    rc = launch_local([_PY, "-c", _RANK_TRACE_CHILD], nproc=2, env=env,
                      log_dir=str(tmp_path / "logs"), timeout=120)
    assert rc == 0
    files = sorted(os.listdir(out))
    assert files == ["trace-host0.json", "trace-host1.json"]

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import trace_merge
    finally:
        sys.path.pop(0)
    merged_path = tmp_path / "merged.json"
    rc = trace_merge.main([str(out), "-o", str(merged_path)])
    assert rc == 0
    merged = json.load(open(merged_path))
    counts = trace_merge.census(merged)
    # ONE timeline, BOTH rank lanes populated
    assert set(counts) == {0, 1}
    assert counts[0] == 3 and counts[1] == 3
    names = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert {"rank 0", "rank 1"} <= names
    # span ids never collide across lanes (rank-strided allocation)
    ids = [e["args"]["id"] for e in merged["traceEvents"]
           if e.get("ph") == "X"]
    assert len(ids) == len(set(ids))


def test_launch_stamps_per_rank_status_port(tmp_path):
    from paddle_tpu.distributed.launch import launch_local

    child = ("import os, sys; "
             "assert os.environ['PADDLE_TPU_STATUS_PORT'] == "
             "str(19000 + int(os.environ['PADDLE_TPU_TRAINER_ID'])), "
             "os.environ.get('PADDLE_TPU_STATUS_PORT'); "
             "assert sys.argv[1] == os.environ['PADDLE_TPU_STATUS_PORT']")
    rc = launch_local([_PY, "-c", child, "{status_port}"], nproc=2,
                      log_dir=str(tmp_path), timeout=120,
                      status_port_base=19000)
    assert rc == 0
