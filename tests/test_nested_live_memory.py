"""Live-outer-memory nested generation (VERDICT r3 missing #4): an inner
beam step whose recurrent memory boots from an OUTER ``memory()`` carries
state ACROSS subsequences — each subsequence's generation starts from the
state the previous one ended in (best beam), the reference's outer-frame
memory plumbing (RecurrentGradientMachine.cpp:1291, ScatterAgentLayer).

The model is hand-weighted so the expectation is computable on paper:

    inner step:  h_t = h_{t-1} + 1            ("hstate" fc, W=1, b=1)
                 logits = (0, h_t, 2.2-h_t, -10) over vocab 4, eos=3
    greedy (beam 1), max_length 2, outer memory = live "hstate"

With h booting at 0 for the FIRST subsequence only:
    sub 0: h = 1, 2     -> argmax tokens (2, 1), carry-out h = 2
    sub 1: h = 3, 4     -> tokens (1, 1)         (carry crossed frames!)
Without the live link (independent subsequences) sub 1 would repeat
sub 0's (2, 1) — which is exactly what this test distinguishes.
"""

from __future__ import annotations

import numpy as np
import pytest


def _build():
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type
    from paddle_tpu.layers.attr import ParamAttr
    from paddle_tpu.layers.recurrent_group import (
        GeneratedInput,
        StaticInput,
        SubsequenceInput,
        beam_search,
        memory,
        recurrent_group,
    )

    base.reset_name_counters()
    data = layer.data(name="src",
                      type=data_type.dense_vector_sub_sequence(2))

    def outer_step(x):
        om = memory(name="hstate", size=1)  # boots at zero

        def inner_step(sx, word):
            h = memory(name="hstate", size=1, boot_layer=om)
            hn = layer.fc_layer(
                input=h, size=1, name="hstate", act=act.LinearActivation(),
                param_attr=ParamAttr(name="w_h"),
                bias_attr=ParamAttr(name="b_h"))
            out = layer.fc_layer(
                input=hn, size=4, act=act.SoftmaxActivation(),
                param_attr=ParamAttr(name="w_out"),
                bias_attr=ParamAttr(name="b_out"))
            return out

        return beam_search(
            step=inner_step,
            input=[StaticInput(input=x, is_seq=True),
                   GeneratedInput(size=4, embedding_name="emb",
                                  embedding_size=1)],
            bos_id=0, eos_id=3, beam_size=1, max_length=2)

    gen = recurrent_group(step=outer_step, input=SubsequenceInput(data))
    return gen, data


def test_live_outer_memory_carries_state_across_subsequences():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core.lod import NestedSequenceBatch

    gen, data = _build()
    topo = Topology(gen)
    params = paddle.parameters.create(topo)
    params["w_h"] = np.asarray([[1.0]], np.float32)
    params["b_h"] = np.asarray([1.0], np.float32)
    params["w_out"] = np.asarray([[0.0, 1.0, -1.0, 0.0]], np.float32)
    params["b_out"] = np.asarray([0.0, 0.0, 2.2, -10.0], np.float32)
    params["emb"] = np.zeros((4, 1), np.float32)

    b, n_sub = 2, 2
    feed = {
        "src": NestedSequenceBatch(
            data=np.zeros((b, n_sub, 1, 2), np.float32),
            seq_length=np.asarray([2, 1], np.int32),
            sub_length=np.ones((b, n_sub), np.int32)),
    }
    values, _ = topo.forward(params.as_dict(), topo.init_states(), feed,
                             False, jax.random.key(0))
    res = values[gen.name]
    ids = np.asarray(jax.device_get(res.inner.ids)).reshape(b, n_sub, 1, 2)
    lens = np.asarray(jax.device_get(res.inner.length)).reshape(b, n_sub)

    # row 0 (2 live subsequences): carry crosses the frame boundary
    assert ids[0, 0, 0].tolist() == [2, 1], ids[0]
    assert ids[0, 1, 0].tolist() == [1, 1], ids[0]
    assert lens[0].tolist() == [2, 2]
    # row 1: first subsequence identical to row 0's first (same boot)
    assert ids[1, 0, 0].tolist() == [2, 1]
    # its outer sequence ends after 1 subsequence; the padded frame's
    # output is masked by seq_length for consumers
    assert int(np.asarray(res.seq_length)[1]) == 1


def test_without_live_memory_subsequences_are_independent():
    """Control: the SAME model minus the outer-memory link generates the
    same tokens for every subsequence (the pre-round-4 behavior)."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core.lod import NestedSequenceBatch
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type
    from paddle_tpu.layers.attr import ParamAttr
    from paddle_tpu.layers.recurrent_group import (
        GeneratedInput,
        StaticInput,
        SubsequenceInput,
        beam_search,
        memory,
        recurrent_group,
    )

    base.reset_name_counters()
    data = layer.data(name="src",
                      type=data_type.dense_vector_sub_sequence(2))

    def outer_step(x):
        def inner_step(sx, word):
            h = memory(name="hstate", size=1)  # zero boot every frame
            hn = layer.fc_layer(
                input=h, size=1, name="hstate", act=act.LinearActivation(),
                param_attr=ParamAttr(name="w_h"),
                bias_attr=ParamAttr(name="b_h"))
            return layer.fc_layer(
                input=hn, size=4, act=act.SoftmaxActivation(),
                param_attr=ParamAttr(name="w_out"),
                bias_attr=ParamAttr(name="b_out"))

        return beam_search(
            step=inner_step,
            input=[StaticInput(input=x, is_seq=True),
                   GeneratedInput(size=4, embedding_name="emb",
                                  embedding_size=1)],
            bos_id=0, eos_id=3, beam_size=1, max_length=2)

    gen = recurrent_group(step=outer_step, input=SubsequenceInput(data))
    topo = Topology(gen)
    params = paddle.parameters.create(topo)
    params["w_h"] = np.asarray([[1.0]], np.float32)
    params["b_h"] = np.asarray([1.0], np.float32)
    params["w_out"] = np.asarray([[0.0, 1.0, -1.0, 0.0]], np.float32)
    params["b_out"] = np.asarray([0.0, 0.0, 2.2, -10.0], np.float32)
    params["emb"] = np.zeros((4, 1), np.float32)

    feed = {
        "src": NestedSequenceBatch(
            data=np.zeros((1, 2, 1, 2), np.float32),
            seq_length=np.asarray([2], np.int32),
            sub_length=np.ones((1, 2), np.int32)),
    }
    values, _ = topo.forward(params.as_dict(), topo.init_states(), feed,
                             False, jax.random.key(0))
    ids = np.asarray(jax.device_get(values[gen.name].inner.ids))
    ids = ids.reshape(1, 2, 1, 2)
    assert ids[0, 0, 0].tolist() == [2, 1]
    assert ids[0, 1, 0].tolist() == [2, 1]  # independent: repeats
