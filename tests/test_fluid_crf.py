"""Fluid CRF kernels (≅ linear_chain_crf_op.cc / crf_decoding_op.cc +
their python op tests): log-likelihood against a numpy forward, gradient
check through jax.grad, and decode/mismatch semantics."""

from __future__ import annotations

import numpy as np


def _np_crf_nll(emission, labels, w, lengths):
    """Numpy linear-chain CRF NLL per sequence — independent reference
    mirroring LinearChainCrfForward (test_linear_chain_crf_op.py)."""
    a, b, trans = w[0], w[1], w[2:]
    out = []
    for i in range(emission.shape[0]):
        t_len = int(lengths[i])
        x = emission[i, :t_len]
        y = labels[i, :t_len]
        # path score
        s = a[y[0]] + x[0, y[0]]
        for t in range(1, t_len):
            s += trans[y[t - 1], y[t]] + x[t, y[t]]
        s += b[y[-1]]
        # partition
        alpha = a + x[0]
        for t in range(1, t_len):
            alpha = x[t] + _logsumexp(alpha[:, None] + trans, axis=0)
        logz = _logsumexp(alpha + b, axis=0)
        out.append(logz - s)
    return np.asarray(out)


def _logsumexp(v, axis):
    m = np.max(v, axis=axis, keepdims=True)
    return np.squeeze(m, axis) + np.log(
        np.sum(np.exp(v - m), axis=axis))


def test_linear_chain_crf_matches_numpy(rng_np):
    import jax

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.fluid.ops import get_kernel

    B, T, C = 3, 5, 7
    lengths = np.array([5, 3, 2], np.int32)
    emission = rng_np.uniform(-1, 1, size=(B, T, C)).astype(np.float32)
    labels = rng_np.integers(0, C, size=(B, T)).astype(np.int32)
    trans = rng_np.uniform(-0.5, 0.5, size=(C + 2, C)).astype(np.float32)

    kernel = get_kernel("linear_chain_crf")
    out = kernel(
        {"Emission": [SequenceBatch(data=emission, length=lengths)],
         "Transition": [trans],
         "Label": [SequenceBatch(data=labels, length=lengths)]},
        {}, jax.random.key(0))
    ll = np.asarray(out["LogLikelihood"][0])[:, 0]
    ref = -_np_crf_nll(emission, labels, trans, lengths)
    np.testing.assert_allclose(ll, ref, rtol=1e-4, atol=1e-4)


def test_linear_chain_crf_gradient(rng_np):
    """Finite-difference check of d(mean NLL)/d(transition) — the check the
    reference runs as check_grad on the fluid op."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.fluid.ops import get_kernel

    B, T, C = 2, 4, 5
    lengths = np.array([4, 2], np.int32)
    emission = rng_np.uniform(-1, 1, size=(B, T, C)).astype(np.float32)
    labels = rng_np.integers(0, C, size=(B, T)).astype(np.int32)
    trans = rng_np.uniform(-0.5, 0.5, size=(C + 2, C)).astype(np.float32)
    kernel = get_kernel("linear_chain_crf")

    def loss(tr, em):
        out = kernel(
            {"Emission": [SequenceBatch(data=em, length=lengths)],
             "Transition": [tr],
             "Label": [SequenceBatch(data=labels, length=lengths)]},
            {}, jax.random.key(0))
        return -jnp.mean(out["LogLikelihood"][0])

    gt, ge = jax.grad(loss, argnums=(0, 1))(jnp.asarray(trans),
                                            jnp.asarray(emission))
    eps = 1e-3
    for arr, g, idx in [(trans, gt, (1, 2)), (trans, gt, (4, 0)),
                        (emission, ge, (0, 1, 3)), (emission, ge, (1, 1, 0))]:
        up = arr.copy(); up[idx] += eps
        dn = arr.copy(); dn[idx] -= eps
        if arr is trans:
            fd = (float(loss(jnp.asarray(up), jnp.asarray(emission)))
                  - float(loss(jnp.asarray(dn), jnp.asarray(emission)))) / (2 * eps)
        else:
            fd = (float(loss(jnp.asarray(trans), jnp.asarray(up)))
                  - float(loss(jnp.asarray(trans), jnp.asarray(dn)))) / (2 * eps)
        an = float(np.asarray(g)[idx])
        assert abs(fd - an) < 5e-3, (idx, fd, an)
    # padded emission steps must carry no gradient
    assert np.all(np.asarray(ge)[1, 2:] == 0)


def test_crf_decoding_modes(rng_np):
    import jax

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.fluid.ops import get_kernel

    B, T, C = 2, 4, 4
    lengths = np.array([4, 3], np.int32)
    emission = rng_np.uniform(-1, 1, size=(B, T, C)).astype(np.float32)
    trans = rng_np.uniform(-0.5, 0.5, size=(C + 2, C)).astype(np.float32)
    kernel = get_kernel("crf_decoding")
    seq = SequenceBatch(data=emission, length=lengths)

    path = kernel({"Emission": [seq], "Transition": [trans]},
                  {}, jax.random.key(0))["ViterbiPath"][0]
    assert path.data.shape == (B, T)
    assert np.asarray(path.data).dtype == np.int32

    # error-indicator mode: the decoded path vs itself mismatches nowhere
    err = kernel({"Emission": [seq], "Transition": [trans],
                  "Label": [path]}, {}, jax.random.key(0))["ViterbiPath"][0]
    assert np.all(np.asarray(err.data) == 0)
