"""Tier-1 wiring of tools/check_kernel_parity.py: every Pallas kernel
module must expose a jnp ``*_reference`` oracle and have an
interpret-mode parity test — one-off kernels without an oracle can't
land (the Compare2Function discipline, FunctionTest.h)."""

from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_every_kernel_has_reference_and_parity_test():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_kernel_parity as ckp
    finally:
        sys.path.pop(0)
    violations = ckp.audit()
    assert not violations, "\n".join(violations)


def test_cli_entrypoint_passes():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "check_kernel_parity.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout
