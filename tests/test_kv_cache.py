"""Paged KV-cache allocator + cache bookkeeping (serving/kv_cache.py):
free-list reuse after retirement, out-of-pages admission rejection, and
no cross-sequence page aliasing under a seeded alloc/free fuzz loop."""

import numpy as np
import pytest

from paddle_tpu.serving.kv_cache import OutOfPages, PageAllocator, PagedKVCache


class TestPageAllocator:
    def test_null_page_never_allocated(self):
        a = PageAllocator(8)
        got = a.alloc(7)  # the whole pool
        assert 0 not in got
        assert sorted(got) == list(range(1, 8))

    def test_out_of_pages_rejection_is_side_effect_free(self):
        a = PageAllocator(4)
        first = a.alloc(2)
        with pytest.raises(OutOfPages):
            a.alloc(2)  # only 1 free
        assert a.free_pages == 1  # nothing leaked by the failed alloc
        a.free(first)
        assert a.free_pages == 3

    def test_reuse_after_retirement(self):
        a = PageAllocator(4)
        s1 = a.alloc(3)
        a.free(s1)
        s2 = a.alloc(3)
        # retired pages are reused (LIFO: the same set comes back)
        assert set(s2) == set(s1)

    def test_double_free_and_null_free_raise(self):
        a = PageAllocator(4)
        pages = a.alloc(1)
        a.free(pages)
        with pytest.raises(Exception):
            a.free(pages)
        with pytest.raises(Exception):
            a.free([0])

    def test_fuzz_no_cross_sequence_aliasing(self):
        """Randomized (seeded) alloc/free churn: live allocations must
        stay disjoint, never contain page 0, and conservation must hold
        (free + live == pool)."""
        rng = np.random.default_rng(7)
        a = PageAllocator(33)  # 32 usable pages
        live: dict[int, list[int]] = {}
        next_id = 0
        for _ in range(500):
            if live and rng.random() < 0.45:
                sid = list(live)[int(rng.integers(len(live)))]
                a.free(live.pop(sid))
            else:
                n = int(rng.integers(1, 6))
                if a.can_alloc(n):
                    live[next_id] = a.alloc(n)
                    next_id += 1
                else:
                    with pytest.raises(OutOfPages):
                        a.alloc(n)
            allocated = [p for pages in live.values() for p in pages]
            assert 0 not in allocated
            assert len(allocated) == len(set(allocated)), "page aliasing!"
            assert a.free_pages + len(allocated) == 32
        assert next_id > 50  # the loop actually exercised allocation


class TestPagedKVCache:
    def _cache(self, num_pages=16, max_slots=4):
        return PagedKVCache(num_layers=2, num_heads=2, head_dim=8,
                            num_pages=num_pages, page_size=4,
                            max_slots=max_slots, max_pages_per_seq=8)

    def test_assign_writes_table_row_and_release_clears_it(self):
        c = self._cache()
        pages = c.assign(1, tokens=10)  # 3 pages of 4
        assert len(pages) == 3
        assert list(c.page_table[1, :3]) == pages
        assert all(c.page_table[1, 3:] == 0)
        free_before = c.allocator.free_pages
        c.release(1)
        assert all(c.page_table[1] == 0)
        assert c.allocator.free_pages == free_before + 3

    def test_assign_rejects_when_pool_exhausted(self):
        c = self._cache(num_pages=5)  # 4 usable
        c.assign(0, tokens=12)  # 3 pages
        with pytest.raises(OutOfPages):
            c.assign(1, tokens=8)  # needs 2, only 1 free
        # the failed assign left no partial state
        assert all(c.page_table[1] == 0)
        assert c.allocator.free_pages == 1

    def test_rows_stay_disjoint_across_slots(self):
        c = self._cache()
        p0 = c.assign(0, tokens=8)
        p1 = c.assign(2, tokens=8)
        assert not set(p0) & set(p1)
        c.release(0)
        p2 = c.assign(3, tokens=8)
        assert not set(p2) & set(p1)

    def test_pages_needed_rounds_up(self):
        c = self._cache()
        assert c.pages_needed(1) == 1
        assert c.pages_needed(4) == 1
        assert c.pages_needed(5) == 2
