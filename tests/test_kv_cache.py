"""Paged KV-cache allocator + cache bookkeeping (serving/kv_cache.py):
free-list reuse after retirement, out-of-pages admission rejection, and
no cross-sequence page aliasing under a seeded alloc/free fuzz loop."""

import numpy as np
import pytest

from paddle_tpu.serving.kv_cache import OutOfPages, PageAllocator, PagedKVCache


class TestPageAllocator:
    def test_null_page_never_allocated(self):
        a = PageAllocator(8)
        got = a.alloc(7)  # the whole pool
        assert 0 not in got
        assert sorted(got) == list(range(1, 8))

    def test_out_of_pages_rejection_is_side_effect_free(self):
        a = PageAllocator(4)
        first = a.alloc(2)
        with pytest.raises(OutOfPages):
            a.alloc(2)  # only 1 free
        assert a.free_pages == 1  # nothing leaked by the failed alloc
        a.free(first)
        assert a.free_pages == 3

    def test_reuse_after_retirement(self):
        a = PageAllocator(4)
        s1 = a.alloc(3)
        a.free(s1)
        s2 = a.alloc(3)
        # retired pages are reused (LIFO: the same set comes back)
        assert set(s2) == set(s1)

    def test_double_free_and_null_free_raise(self):
        a = PageAllocator(4)
        pages = a.alloc(1)
        a.free(pages)
        with pytest.raises(Exception):
            a.free(pages)
        with pytest.raises(Exception):
            a.free([0])

    def test_fuzz_no_cross_sequence_aliasing(self):
        """Randomized (seeded) alloc/free churn: live allocations must
        stay disjoint, never contain page 0, and conservation must hold
        (free + live == pool)."""
        rng = np.random.default_rng(7)
        a = PageAllocator(33)  # 32 usable pages
        live: dict[int, list[int]] = {}
        next_id = 0
        for _ in range(500):
            if live and rng.random() < 0.45:
                sid = list(live)[int(rng.integers(len(live)))]
                a.free(live.pop(sid))
            else:
                n = int(rng.integers(1, 6))
                if a.can_alloc(n):
                    live[next_id] = a.alloc(n)
                    next_id += 1
                else:
                    with pytest.raises(OutOfPages):
                        a.alloc(n)
            allocated = [p for pages in live.values() for p in pages]
            assert 0 not in allocated
            assert len(allocated) == len(set(allocated)), "page aliasing!"
            assert a.free_pages + len(allocated) == 32
        assert next_id > 50  # the loop actually exercised allocation


class TestPagedKVCache:
    def _cache(self, num_pages=16, max_slots=4):
        return PagedKVCache(num_layers=2, num_heads=2, head_dim=8,
                            num_pages=num_pages, page_size=4,
                            max_slots=max_slots, max_pages_per_seq=8)

    def test_assign_writes_table_row_and_release_clears_it(self):
        c = self._cache()
        pages = c.assign(1, tokens=10)  # 3 pages of 4
        assert len(pages) == 3
        assert list(c.page_table[1, :3]) == pages
        assert all(c.page_table[1, 3:] == 0)
        free_before = c.allocator.free_pages
        c.release(1)
        assert all(c.page_table[1] == 0)
        assert c.allocator.free_pages == free_before + 3

    def test_assign_rejects_when_pool_exhausted(self):
        c = self._cache(num_pages=5)  # 4 usable
        c.assign(0, tokens=12)  # 3 pages
        with pytest.raises(OutOfPages):
            c.assign(1, tokens=8)  # needs 2, only 1 free
        # the failed assign left no partial state
        assert all(c.page_table[1] == 0)
        assert c.allocator.free_pages == 1

    def test_rows_stay_disjoint_across_slots(self):
        c = self._cache()
        p0 = c.assign(0, tokens=8)
        p1 = c.assign(2, tokens=8)
        assert not set(p0) & set(p1)
        c.release(0)
        p2 = c.assign(3, tokens=8)
        assert not set(p2) & set(p1)

    def test_pages_needed_rounds_up(self):
        c = self._cache()
        assert c.pages_needed(1) == 1
        assert c.pages_needed(4) == 1
        assert c.pages_needed(5) == 2


class TestRefcounts:
    def test_retain_then_free_keeps_page_allocated(self):
        a = PageAllocator(8)
        (p,) = a.alloc(1)
        a.retain([p])
        assert a.refcount(p) == 2
        a.free([p])
        assert a.refcount(p) == 1  # still allocated, one owner left
        assert p not in [a.alloc(1)[0] for _ in range(a.free_pages)]
        a.free([p] * 1)
        assert a.refcount(p) == 0

    def test_refcount_never_negative(self):
        a = PageAllocator(4)
        (p,) = a.alloc(1)
        a.free([p])
        with pytest.raises(Exception):
            a.free([p])
        with pytest.raises(Exception):
            a.retain([p])  # retain of an unallocated page is an error
        assert a.refcount(p) == 0

    def test_conservation_with_sharing_fuzz(self):
        """free + live (unique) == pool under random alloc/retain/free
        churn — the refcounted conservation law."""
        rng = np.random.default_rng(11)
        a = PageAllocator(33)
        refs: list[int] = []  # one entry per outstanding reference
        for _ in range(800):
            r = rng.random()
            if refs and r < 0.40:
                a.free([refs.pop(int(rng.integers(len(refs))))])
            elif refs and r < 0.55:
                p = refs[int(rng.integers(len(refs)))]
                a.retain([p])
                refs.append(p)
            elif a.can_alloc(1):
                refs.extend(a.alloc(int(rng.integers(1, 4)) if
                                    a.can_alloc(3) else 1))
            assert a.free_pages + a.live_pages == 32
            for p in set(refs):
                assert a.refcount(p) == refs.count(p)


def _prefix_cache(num_pages=16, max_slots=4, max_pages=8):
    return PagedKVCache(num_layers=1, num_heads=1, head_dim=4,
                        num_pages=num_pages, page_size=4,
                        max_slots=max_slots, max_pages_per_seq=max_pages,
                        prefix_cache=True)


class TestPrefixCache:
    def test_match_only_full_pages_and_never_whole_prompt(self):
        c = _prefix_cache()
        prompt = list(range(10))  # 2 full pages + tail of 2
        c.assign_with_prefix(0, tokens=12, prompt=prompt)
        c.prefix.insert(prompt, c.slot_pages(0))
        assert c.prefix.cached_pages == 2
        # exact same prompt: match covers the 2 full pages, tail stays
        assert c.prefix.peek(prompt) == 8
        # a prompt of exactly 8 tokens may only match 1 page: the last
        # token must be prefilled to produce first-token logits
        assert c.prefix.peek(prompt[:8]) == 4
        # divergence after the first page stops the walk
        assert c.prefix.peek(prompt[:4] + [99] * 6) == 4
        assert c.prefix.peek([99] * 10) == 0

    def test_assign_with_prefix_shares_pages_and_counts_tokens(self):
        c = _prefix_cache()
        p1 = list(range(10))
        pages1, cov1 = c.assign_with_prefix(0, 12, p1)
        assert cov1 == 0
        c.prefix.insert(p1, c.slot_pages(0))
        pages2, cov2 = c.assign_with_prefix(1, 12, p1)
        assert cov2 == 8
        assert pages2[:2] == pages1[:2]      # physically shared head
        assert pages2[2] != pages1[2]        # private tail
        assert c.allocator.refcount(pages1[0]) == 3  # slot0+slot1+cache
        rep = c.resident_report()
        assert rep["shared_saved_pages"] == 2
        assert rep["free_pages"] + rep["unique_pages"] == 15

    def test_release_keeps_cached_pages_resident(self):
        c = _prefix_cache()
        prompt = list(range(10))
        pages, _ = c.assign_with_prefix(0, 12, prompt)
        c.prefix.insert(prompt, c.slot_pages(0))
        free_before = c.allocator.free_pages
        c.release(0)
        # slot refs dropped; the 2 cached pages survive, the private
        # tail page is freed
        assert c.allocator.free_pages == free_before + 1
        assert c.allocator.refcount(pages[0]) == 1
        assert c.prefix.peek(prompt) == 8  # still matchable

    def test_lru_eviction_order_and_oop_only_when_unique_exhausted(self):
        c = _prefix_cache(num_pages=9, max_pages=8)  # 8 usable
        old = [1, 2, 3, 4, 9]
        new = [5, 6, 7, 8, 9]
        c.assign_with_prefix(0, 5, old)
        c.prefix.insert(old, c.slot_pages(0))
        c.release(0)
        c.assign_with_prefix(1, 5, new)
        c.prefix.insert(new, c.slot_pages(1))
        c.release(1)
        # 4 pages held: 2 cached prefixes (1 page each) + nothing live.
        assert c.prefix.cached_pages == 2
        assert c.allocator.free_pages == 6
        # an admission needing 7 pages evicts the LRU entry (old) first
        c.assign(2, tokens=28)
        assert c.prefix.evictions == 1
        assert c.prefix.peek(old + [0]) == 0      # old evicted
        assert c.prefix.peek(new + [0]) == 4      # newer survived
        # now the pool is truly full of unique mapped pages + 1 cached:
        # a request the cold pool couldn't take raises even after the
        # last cached page is reclaimed
        with pytest.raises(OutOfPages):
            c.assign(3, tokens=8)  # needs 2, only 1 reclaimable
        assert c.prefix.cached_pages == 0  # eviction drained the cache
        c.assign(3, tokens=4)  # 1 page — fits via the evicted page

    def test_eviction_is_leaf_first(self):
        c = _prefix_cache()
        prompt = list(range(12))  # 3 full pages, chain in the trie
        c.assign_with_prefix(0, 13, prompt)
        c.prefix.insert(prompt, c.slot_pages(0))
        c.release(0)
        assert c.prefix.cached_pages == 3
        leaves = c.prefix.reclaimable()
        assert len(leaves) == 1  # only the chain tail is a candidate
        assert c.prefix.evict_until(c.allocator.free_pages + 1)
        assert c.prefix.cached_pages == 2
        assert c.prefix.peek(prompt) == 8  # interior pages still walk


class TestCopyOnWrite:
    def test_cow_copies_shared_page_and_repoints_row(self):
        c = _prefix_cache()
        import jax.numpy as jnp

        prompt = list(range(10))
        pages1, _ = c.assign_with_prefix(0, 12, prompt)
        c.k = c.k.at[:, :, pages1[0]].set(7.0)  # recognizable contents
        c.prefix.insert(prompt, c.slot_pages(0))
        pages2, _ = c.assign_with_prefix(1, 12, prompt)
        assert pages2[0] == pages1[0]
        got = c.cow_page(1, 0)
        assert got != pages1[0]
        assert c.slot_pages(1)[0] == got
        assert c.page_table[1, 0] == got
        assert bool(jnp.all(c.k[:, :, got] == 7.0))  # contents copied
        # slot 0 and the cache still share the original
        assert c.allocator.refcount(pages1[0]) == 2
        assert c.allocator.refcount(got) == 1

    def test_cow_noop_on_private_page(self):
        c = _prefix_cache()
        prompt = list(range(10))
        pages, _ = c.assign_with_prefix(0, 12, prompt)
        free = c.allocator.free_pages
        c.cow_for_write(0, 9, 3)  # pages 2 covered; private already
        assert c.slot_pages(0) == pages
        assert c.allocator.free_pages == free

    def test_divergence_after_shared_prefix_stays_isolated(self):
        """Two sequences sharing a cached prefix write different
        suffixes; the shared pages' contents stay byte-identical and
        each divergence lands in a private page."""
        import jax.numpy as jnp

        c = _prefix_cache()
        prompt = list(range(10))
        pages1, _ = c.assign_with_prefix(0, 12, prompt)
        c.prefix.insert(prompt, c.slot_pages(0))
        pages2, _ = c.assign_with_prefix(1, 12, prompt)
        shared = pages1[:2]
        before = np.asarray(c.k[:, :, shared])
        # each writer privatises then writes its own tail page region
        c.cow_for_write(0, 10, 2)
        c.cow_for_write(1, 10, 2)
        t1, t2 = c.slot_pages(0)[2], c.slot_pages(1)[2]
        assert t1 != t2
        c.k = c.k.at[:, :, t1].set(1.0)
        c.k = c.k.at[:, :, t2].set(2.0)
        assert np.array_equal(np.asarray(c.k[:, :, shared]), before)
        assert bool(jnp.all(c.k[:, :, t1] == 1.0))
        assert bool(jnp.all(c.k[:, :, t2] == 2.0))
