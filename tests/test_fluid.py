"""Fluid stack tests — op_test-style numerics plus e2e program training.

Mirrors the reference test strategy (SURVEY §4):
- per-op check_output / check_grad (reference
  ``python/paddle/v2/framework/tests/op_test.py:80-338``), with gradients
  checked against finite differences;
- end-to-end model tests (``test_fit_a_line.py``,
  ``test_recognize_digits_conv.py``) asserting the loss actually falls;
- save/load round trips (``save_load_op_test.cc``, ``io.py``).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import framework, layers


@pytest.fixture(autouse=True)
def _fresh_programs():
    framework.reset_default_programs()
    fluid.g_scope.clear()
    yield


def _run_startup(exe):
    exe.run(framework.default_startup_program())


def _numeric_grad(f, x, eps=1e-3):
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        old = flat[i]
        flat[i] = old + eps
        hi = f()
        flat[i] = old - eps
        lo = f()
        flat[i] = old
        gf[i] = (hi - lo) / (2 * eps)
    return g


class TestOps:
    def test_mul_output_and_grad(self):
        rng = np.random.default_rng(0)
        x_np = rng.normal(size=(4, 6)).astype(np.float32)
        y_np = rng.normal(size=(6, 3)).astype(np.float32)

        x = layers.data("x", [6], append_batch_size=True)
        y = layers.data("y", [6, 3], append_batch_size=False)
        block = framework.default_main_program().global_block()
        out = block.create_var(name="out", shape=(4, 3))
        block.append_op("mul", {"X": ["x"], "Y": ["y"]}, {"Out": ["out"]},
                        {"x_num_col_dims": 1, "y_num_col_dims": 1})
        loss = layers.mean(out)
        block.vars["y"].stop_gradient = False
        grads = fluid.append_backward_ops(loss, parameter_list=["y"])

        exe = fluid.Executor()
        res = exe.run(feed={"x": x_np, "y": y_np},
                      fetch_list=[out, loss, grads[0][1]])
        np.testing.assert_allclose(res[0], x_np @ y_np, rtol=1e-5)

        def f():
            return float((x_np @ y_np).mean())

        num = _numeric_grad(f, y_np)
        np.testing.assert_allclose(res[2], num, rtol=1e-2, atol=1e-3)

    def test_elementwise_broadcast_axis(self):
        x_np = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        y_np = np.array([1.0, 2.0, 3.0], np.float32)
        x = layers.data("x", [2, 3, 4], append_batch_size=False)
        y = layers.data("y", [3], append_batch_size=False)
        out = layers.elementwise_add(x, y, axis=1)
        exe = fluid.Executor()
        (res,) = exe.run(feed={"x": x_np, "y": y_np}, fetch_list=[out])
        np.testing.assert_allclose(res, x_np + y_np.reshape(1, 3, 1))

    def test_activations(self):
        x_np = np.linspace(-2, 2, 12).astype(np.float32).reshape(3, 4)
        x = layers.data("x", [4])
        outs = [layers.sigmoid(x), layers.tanh(x), layers.relu(x),
                layers.square(x)]
        exe = fluid.Executor()
        res = exe.run(feed={"x": x_np}, fetch_list=outs)
        np.testing.assert_allclose(res[0], 1 / (1 + np.exp(-x_np)), rtol=1e-5)
        np.testing.assert_allclose(res[1], np.tanh(x_np), rtol=1e-5)
        np.testing.assert_allclose(res[2], np.maximum(x_np, 0))
        np.testing.assert_allclose(res[3], x_np * x_np, rtol=1e-5)

    def test_cross_entropy_and_softmax(self):
        rng = np.random.default_rng(1)
        logits = rng.normal(size=(5, 7)).astype(np.float32)
        labels = rng.integers(0, 7, size=(5, 1))
        x = layers.data("x", [7])
        lbl = layers.data("label", [1], dtype="int64")
        sm = layers.softmax(x)
        ce = layers.cross_entropy(sm, lbl)
        exe = fluid.Executor()
        (res,) = exe.run(feed={"x": logits, "label": labels}, fetch_list=[ce])
        p = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
        expect = -np.log(p[np.arange(5), labels.ravel()])[:, None]
        np.testing.assert_allclose(res, expect, rtol=1e-4)

    def test_accuracy_op(self):
        probs = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32)
        labels = np.array([[1], [0], [0]])
        x = layers.data("x", [2])
        lbl = layers.data("label", [1], dtype="int64")
        acc = layers.accuracy(x, lbl)
        exe = fluid.Executor()
        (res,) = exe.run(feed={"x": probs, "label": labels}, fetch_list=[acc])
        np.testing.assert_allclose(res, 2.0 / 3.0, rtol=1e-6)

    def test_conv_pool_shapes(self):
        rng = np.random.default_rng(2)
        img = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
        x = layers.data("img", [3, 8, 8])
        conv = layers.conv2d(x, num_filters=4, filter_size=3, padding=1)
        pool = layers.pool2d(conv, pool_size=2, pool_stride=2)
        exe = fluid.Executor()
        _run_startup(exe)
        res = exe.run(feed={"img": img}, fetch_list=[conv, pool])
        assert res[0].shape == (2, 4, 8, 8)
        assert res[1].shape == (2, 4, 4, 4)

    def test_batch_norm_train_normalizes(self):
        rng = np.random.default_rng(3)
        xv = (5.0 + 2.0 * rng.normal(size=(16, 4, 3, 3))).astype(np.float32)
        x = layers.data("x", [4, 3, 3])
        y = layers.batch_norm(x)
        exe = fluid.Executor()
        _run_startup(exe)
        (res,) = exe.run(feed={"x": xv}, fetch_list=[y])
        np.testing.assert_allclose(res.mean(axis=(0, 2, 3)), 0.0, atol=1e-4)
        np.testing.assert_allclose(res.std(axis=(0, 2, 3)), 1.0, atol=1e-2)
        # running stats were updated in the scope (MeanOut aliases Mean)
        mean_name = [n for n in fluid.g_scope if "global" in n][0]
        assert np.abs(np.asarray(fluid.g_scope[mean_name])).sum() > 0

    def test_dropout_grad_uses_same_mask(self):
        """Forward and vjp replay must agree on the dropout mask."""
        x_np = np.ones((64, 32), np.float32)
        x = layers.data("x", [32])
        blk = framework.default_main_program().global_block()
        blk.vars["x"].stop_gradient = False
        out = layers.dropout(x, dropout_prob=0.5)
        loss = layers.mean(out)
        fluid.append_backward_ops(loss, parameter_list=["x"])
        exe = fluid.Executor()
        res = exe.run(feed={"x": x_np},
                      fetch_list=[out, framework.grad_var_name("x")])
        fwd, grad = res
        # grad is exactly mask/(1-p)/N: nonzero where fwd nonzero
        np.testing.assert_array_equal(fwd > 0, grad > 0)


class TestBackward:
    def test_fan_out_accumulates(self):
        """x used twice -> dx must be the sum of both paths."""
        x_np = np.array([[2.0, 3.0]], np.float32)
        x = layers.data("x", [2])
        framework.default_main_program().global_block().vars["x"].stop_gradient = False
        a = layers.square(x)          # d/dx = 2x
        b = layers.scale(x, scale=5.0)  # d/dx = 5
        s = layers.elementwise_add(a, b)
        loss = layers.mean(s)         # 1/2 sum
        fluid.append_backward_ops(loss, parameter_list=["x"])
        exe = fluid.Executor()
        (gx,) = exe.run(feed={"x": x_np},
                        fetch_list=[framework.grad_var_name("x")])
        np.testing.assert_allclose(gx, (2 * x_np + 5.0) / 2.0, rtol=1e-5)

    def test_fc_param_grad_matches_numeric(self):
        rng = np.random.default_rng(4)
        x_np = rng.normal(size=(3, 5)).astype(np.float32)
        x = layers.data("x", [5])
        y = layers.fc(x, size=2, bias_attr=None)
        loss = layers.mean(y)
        params = framework.default_main_program().global_block().all_parameters()
        pg = fluid.append_backward_ops(loss)
        exe = fluid.Executor()
        _run_startup(exe)
        w = [p for p in params if p.shape == (5, 2)][0]
        w_np = np.asarray(fluid.g_scope[w.name]).copy()
        b = [p for p in params if p.shape == (2,)][0]
        b_np = np.asarray(fluid.g_scope[b.name]).copy()
        grads = {p.name: g for p, g in pg}
        res = exe.run(feed={"x": x_np}, fetch_list=[grads[w.name]])

        def f():
            return float((x_np @ w_np + b_np).mean())

        num = _numeric_grad(f, w_np)
        np.testing.assert_allclose(res[0], num, rtol=1e-2, atol=1e-3)


class TestOptimizers:
    def _train_quadratic(self, make_opt, steps=150):
        """min ||W x - t||^2 via each optimizer; returns final loss."""
        rng = np.random.default_rng(5)
        x_np = rng.normal(size=(8, 4)).astype(np.float32)
        w_true = rng.normal(size=(4, 1)).astype(np.float32)
        t_np = x_np @ w_true + 0.3  # realizable -> optimum is zero loss
        x = layers.data("x", [4])
        t = layers.data("t", [1])
        y = layers.fc(x, size=1)
        cost = layers.square_error_cost(y, t)
        loss = layers.mean(cost)
        opt = make_opt()
        opt.minimize(loss)
        exe = fluid.Executor()
        _run_startup(exe)
        first = None
        for _ in range(steps):
            (lv,) = exe.run(feed={"x": x_np, "t": t_np}, fetch_list=[loss])
            first = lv if first is None else first
        return float(first), float(lv)

    @pytest.mark.parametrize("maker", [
        lambda: fluid.SGDOptimizer(learning_rate=0.05),
        lambda: fluid.MomentumOptimizer(learning_rate=0.02, momentum=0.9),
        lambda: fluid.AdagradOptimizer(learning_rate=0.3),
        lambda: fluid.AdamOptimizer(learning_rate=0.1),
        lambda: fluid.AdamaxOptimizer(learning_rate=0.1),
        lambda: fluid.DecayedAdagradOptimizer(learning_rate=0.05),
    ])
    def test_optimizer_reduces_loss(self, maker):
        first, last = self._train_quadratic(maker)
        assert last < first * 0.2, (first, last)


class TestEndToEnd:
    def test_fit_a_line(self):
        """Reference ``tests/book/test_fit_a_line.py`` on synthetic data."""
        rng = np.random.default_rng(6)
        true_w = rng.normal(size=(13, 1)).astype(np.float32)
        xs = rng.normal(size=(128, 13)).astype(np.float32)
        ys = xs @ true_w + 0.7

        x = layers.data("x", [13])
        y = layers.data("y", [1])
        predict = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(predict, y))
        fluid.SGDOptimizer(learning_rate=0.05).minimize(loss)

        exe = fluid.Executor()
        _run_startup(exe)
        losses = []
        for epoch in range(50):
            for i in range(0, 128, 32):
                (lv,) = exe.run(feed={"x": xs[i:i + 32], "y": ys[i:i + 32]},
                                fetch_list=[loss])
            losses.append(float(lv))
        assert losses[-1] < 0.05, losses[-1]

    def test_recognize_digits_conv(self):
        """Reference ``test_recognize_digits_conv.py`` shape, synthetic data."""
        from paddle_tpu.fluid import nets
        rng = np.random.default_rng(7)
        n = 64
        imgs = rng.normal(size=(n, 1, 28, 28)).astype(np.float32) * 0.1
        lbls = rng.integers(0, 10, size=(n, 1))
        # make the task learnable: class k has a bright k-th column block
        for i, k in enumerate(lbls.ravel()):
            imgs[i, 0, :, k] += 2.0

        img = layers.data("img", [1, 28, 28])
        label = layers.data("label", [1], dtype="int64")
        c1 = nets.simple_img_conv_pool(img, num_filters=8, filter_size=5,
                                       pool_size=2, pool_stride=2, act="relu")
        predict = layers.fc(c1, size=10, act="softmax")
        cost = layers.cross_entropy(predict, label)
        loss = layers.mean(cost)
        acc = layers.accuracy(predict, label)
        fluid.AdamOptimizer(learning_rate=0.01).minimize(loss)

        exe = fluid.Executor()
        _run_startup(exe)
        accs = []
        for _ in range(20):
            lv, av = exe.run(feed={"img": imgs, "label": lbls},
                             fetch_list=[loss, acc])
            accs.append(float(av))
        assert accs[-1] > 0.9, accs

    def test_save_load_inference_model(self, tmp_path):
        rng = np.random.default_rng(8)
        x_np = rng.normal(size=(4, 6)).astype(np.float32)
        x = layers.data("x", [6])
        y = layers.fc(x, size=3, act="softmax")
        loss = layers.mean(y)
        fluid.SGDOptimizer(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor()
        _run_startup(exe)
        (before,) = exe.run(feed={"x": x_np}, fetch_list=[y])

        d = str(tmp_path / "model")
        fluid.io.save_inference_model(d, ["x"], [y], exe)

        # wipe scope, reload into a fresh program, same predictions
        fluid.g_scope.clear()
        prog, feeds, fetches = fluid.io.load_inference_model(d, exe)
        assert feeds == ["x"]
        (after,) = exe.run(prog, feed={"x": x_np}, fetch_list=fetches)
        np.testing.assert_allclose(before, after, rtol=1e-6)

    def test_save_load_persistables_roundtrip(self, tmp_path):
        x = layers.data("x", [6])
        layers.fc(x, size=3)
        exe = fluid.Executor()
        _run_startup(exe)
        names = [p.name for p in
                 framework.default_main_program().global_block().all_parameters()]
        orig = {n: np.asarray(fluid.g_scope[n]).copy() for n in names}
        fluid.io.save_persistables(exe, str(tmp_path / "ckpt"))
        fluid.g_scope.clear()
        fluid.io.load_persistables(exe, str(tmp_path / "ckpt"))
        for n in names:
            np.testing.assert_array_equal(orig[n], np.asarray(fluid.g_scope[n]))

    def test_program_clone_and_prune(self):
        x = layers.data("x", [6])
        h = layers.fc(x, size=4, act="relu")
        y = layers.fc(h, size=2)
        loss = layers.mean(y)
        fluid.SGDOptimizer(learning_rate=0.1).minimize(loss)
        prog = framework.default_main_program()
        pruned = prog.prune([y])
        # pruned program has no grad/optimizer ops
        types = {op.type for op in pruned.global_block().ops}
        assert "__generic_grad__" not in types and "sgd" not in types
        assert "mul" in types
