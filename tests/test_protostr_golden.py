"""Golden-protostr compatibility: the reference's own config-compiler test
suite, byte-for-byte.

The reference golden-tests its config compiler by diffing
``parse_config(cfg).model_config`` text dumps against checked-in goldens
(``python/paddle/trainer_config_helpers/tests/configs/`` +
``generate_protostr.sh``/``run_tests.sh``).  Here the SAME unmodified config
files run through paddle_tpu's ``parse_config`` and must reproduce the SAME
protostr text — the ModelConfig/TrainerConfig wire-surface compatibility
claim (BASELINE.json north star; proto/ModelConfig.proto:353).

Byte-exact up to one normalization: goldens end with "}\n\n" because py2's
``print proto`` added a newline on top of text_format's trailing one; we
compare with trailing newlines stripped.

Skipped when the reference checkout is absent.
"""

from __future__ import annotations

import os

import pytest

REF = "/root/reference/python/paddle/trainer_config_helpers/tests/configs"

# every config with a golden in the reference suite (file_list.sh + protostr/)
CONFIGS = [
    "img_layers", "img_trans_layers", "last_first_seq", "layer_activations",
    "math_ops", "projections", "shared_fc", "shared_gru", "shared_lstm",
    "simple_rnn_layers", "test_BatchNorm3D", "test_bi_grumemory",
    "test_bilinear_interp", "test_clip_layer", "test_conv3d_layer",
    "test_cost_layers", "test_cost_layers_with_weight",
    "test_cross_entropy_over_beam", "test_deconv3d_layer",
    "test_detection_output_layer", "test_expand_layer", "test_fc",
    "test_gated_unit_layer", "test_grumemory_layer", "test_hsigmoid",
    "test_kmax_seq_socre_layer", "test_lstmemory_layer", "test_maxout",
    "test_multibox_loss_layer", "test_multiplex_layer", "test_ntm_layers",
    "test_pad", "test_pooling3D_layer", "test_prelu_layer",
    "test_print_layer", "test_recursive_topology", "test_repeat_layer",
    "test_resize_layer", "test_rnn_group", "test_row_conv",
    "test_row_l2_norm_layer", "test_scale_shift_layer",
    "test_scale_sub_region_layer", "test_seq_concat_reshape",
    "test_seq_slice_layer", "test_sequence_pooling", "test_smooth_l1",
    "test_split_datasource", "test_spp_layer",
    "test_sub_nested_seq_select_layer", "unused_layers", "util_layers",
]

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REF), reason="reference checkout not available"
)


@pytest.mark.parametrize("name", CONFIGS)
def test_protostr_golden(name):
    from paddle_tpu.config.protostr import to_protostr
    from paddle_tpu.trainer.config_parser import parse_config

    cfg = os.path.join(REF, name + ".py")
    golden = os.path.join(REF, "protostr", name + ".protostr")
    parsed = parse_config(cfg)
    want = open(golden).read()
    if want.startswith("model_config"):
        # whole-TrainerConfig golden (the reference's "whole_configs" set)
        got = to_protostr(parsed.trainer_config,
                          getattr(parsed, "int_style", None))
    else:
        got = parsed.protostr()
    assert got.rstrip("\n") == want.rstrip("\n"), (
        f"protostr mismatch for {name}"
    )


def test_wire_roundtrip():
    """SerializeToString/ParseFromString over the dynamic descriptors."""
    from paddle_tpu import proto
    from paddle_tpu.trainer.config_parser import parse_config

    parsed = parse_config(os.path.join(REF, "test_fc.py"))
    blob = parsed.trainer_config.SerializeToString()
    tc = proto.TrainerConfig()
    tc.ParseFromString(blob)
    assert tc == parsed.trainer_config
    assert tc.model_config.layers[0].name == "data"
