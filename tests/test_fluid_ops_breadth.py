"""Op-registry breadth batch: direct-kernel checks against numpy references
(the reference's op_test.py check_output pattern) plus one generic-grad
check through the fluid executor."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.fluid import ops as O

KEY = jax.random.key(0)


def run(name, ins, attrs=None):
    ins = {k: [jnp.asarray(v) for v in vs] for k, vs in ins.items()}
    return O.get_kernel(name)(ins, attrs or {}, KEY)


def test_tensor_ops(rng_np):
    x = rng_np.normal(size=(4, 5)).astype(np.float32)
    np.testing.assert_allclose(run("sign", {"X": [x]})["Out"][0], np.sign(x))
    y = rng_np.normal(size=(4, 5)).astype(np.float32)
    np.testing.assert_allclose(run("minus", {"X": [x], "Y": [y]})["Out"][0],
                               x - y, rtol=1e-6)
    idx = np.asarray([2, 0])
    np.testing.assert_allclose(
        run("gather", {"X": [x], "Index": [idx]})["Out"][0], x[idx])
    upd = np.ones((2, 5), np.float32)
    got = run("scatter", {"Ref": [x], "Index": [idx], "Updates": [upd]})
    ref = x.copy(); ref[idx] = 1.0
    np.testing.assert_allclose(got["Out"][0], ref)
    parts = run("split", {"X": [x]}, {"axis": 1, "sections": [2, 3]})["Out"]
    assert parts[0].shape == (4, 2) and parts[1].shape == (4, 3)
    padded = run("pad", {"X": [x]}, {"paddings": [0, 1, 2, 0],
                                     "pad_value": 7.0})["Out"][0]
    assert padded.shape == (5, 7) and float(padded[-1, 0]) == 7.0
    cropped = run("crop", {"X": [x]}, {"offsets": [1, 2],
                                       "shape": [2, 3]})["Out"][0]
    np.testing.assert_allclose(cropped, x[1:3, 2:5])
    c = run("clip_by_norm", {"X": [x * 100]}, {"max_norm": 1.0})["Out"][0]
    np.testing.assert_allclose(float(jnp.linalg.norm(c)), 1.0, rtol=1e-4)


def test_loss_ops(rng_np):
    x = rng_np.normal(size=(6, 4)).astype(np.float32)
    y = rng_np.normal(size=(6, 4)).astype(np.float32)
    out = run("squared_l2_distance", {"X": [x], "Y": [y]})["Out"][0]
    np.testing.assert_allclose(
        np.asarray(out)[:, 0], ((x - y) ** 2).sum(-1), rtol=1e-5)
    h = run("huber_loss", {"X": [x], "Y": [y]}, {"delta": 1.0})["Out"][0]
    r = y - x
    np.testing.assert_allclose(
        np.asarray(h),
        np.where(np.abs(r) <= 1, 0.5 * r * r, np.abs(r) - 0.5), rtol=1e-5)
    lbl = (rng_np.random((6, 4)) > 0.5).astype(np.float32)
    s = run("sigmoid_cross_entropy_with_logits",
            {"X": [x], "Label": [lbl]})["Out"][0]
    expect = np.maximum(x, 0) - x * lbl + np.log1p(np.exp(-np.abs(x)))
    np.testing.assert_allclose(np.asarray(s), expect, rtol=1e-5)
    t = (rng_np.random((6, 1)) > 0.5).astype(np.float32)
    rl = run("rank_loss", {"Left": [x[:, :1]], "Right": [y[:, :1]],
                           "Label": [t]})["Out"][0]
    o = x[:, :1] - y[:, :1]
    np.testing.assert_allclose(np.asarray(rl), np.log1p(np.exp(o)) - t * o,
                               rtol=1e-5)


def test_optimizer_ops(rng_np):
    p = rng_np.normal(size=(8,)).astype(np.float32)
    g = rng_np.normal(size=(8,)).astype(np.float32)
    lr = np.asarray([0.1], np.float32)
    z = np.zeros_like(p)
    out = run("rmsprop", {"Param": [p], "Grad": [g], "MeanSquare": [z],
                          "Moment": [z], "LearningRate": [lr]},
              {"decay": 0.9, "epsilon": 1e-6})
    ms = 0.1 * g * g
    mo = 0.1 * g / np.sqrt(ms + 1e-6)
    np.testing.assert_allclose(out["ParamOut"][0], p - mo, rtol=1e-4)
    out = run("adadelta", {"Param": [p], "Grad": [g],
                           "AvgSquaredGrad": [z], "AvgSquaredUpdate": [z]},
              {"rho": 0.95, "epsilon": 1e-6})
    assert out["ParamOut"][0].shape == p.shape
    out = run("proximal_gd", {"Param": [p], "Grad": [g],
                              "LearningRate": [lr]}, {"l1": 0.0, "l2": 0.0})
    np.testing.assert_allclose(out["ParamOut"][0], p - 0.1 * g, rtol=1e-5)


def test_metric_ops(rng_np):
    probs = rng_np.random((32, 2)).astype(np.float32)
    labels = (probs[:, 1] > 0.5).astype(np.int32)  # perfectly separable
    auc = float(run("auc", {"Out": [probs], "Label": [labels]},
                    {"num_thresholds": 200})["AUC"][0][0])
    assert auc > 0.99
    preds = np.asarray([0, 1, 2, 1])
    lbls = np.asarray([0, 1, 2, 2])
    m = run("precision_recall", {"Indices": [preds], "Labels": [lbls]},
            {"class_number": 3})["BatchMetrics"][0]
    assert 0.5 < float(m[0]) <= 1.0  # macro precision sensible


def test_conv2d_transpose_and_pool_index(rng_np):
    x = rng_np.normal(size=(2, 3, 8, 8)).astype(np.float32)
    w = rng_np.normal(size=(3, 4, 3, 3)).astype(np.float32)  # ci,co,kh,kw
    y = run("conv2d_transpose", {"Input": [x], "Filter": [w]},
            {"strides": (2, 2), "paddings": (0, 0)})["Output"][0]
    assert y.shape[0:2] == (2, 4) and y.shape[2] > 8
    out = run("pool2d_with_index", {"X": [x]}, {"ksize": [2, 2],
                                                "strides": [2, 2]})
    assert out["Out"][0].shape == (2, 3, 4, 4)
    assert out["Mask"][0].shape == (2, 3, 4, 4)
    np.testing.assert_allclose(
        np.asarray(out["Out"][0])[0, 0, 0, 0], x[0, 0, :2, :2].max())


def test_generic_grad_covers_new_ops():
    """huber_loss through the executor backward (generic vjp kernel)."""
    from paddle_tpu import fluid
    from paddle_tpu.fluid import framework, layers

    framework.reset_default_programs()
    rng = np.random.default_rng(0)
    x_np = rng.normal(size=(4, 3)).astype(np.float32)
    y_np = rng.normal(size=(4, 3)).astype(np.float32)
    x = layers.data("x", [4, 3], append_batch_size=False)
    y = layers.data("y", [4, 3], append_batch_size=False)
    block = framework.default_main_program().global_block()
    res = block.create_var(name="resid", shape=(4, 3))
    out = block.create_var(name="hub", shape=(4, 3))
    block.append_op("huber_loss", {"X": ["x"], "Y": ["y"]},
                    {"Residual": ["resid"], "Out": ["hub"]}, {"delta": 1.0})
    loss = layers.mean(out)
    block.vars["x"].stop_gradient = False
    grads = fluid.append_backward_ops(loss, parameter_list=["x"])
    exe = fluid.Executor()
    got = exe.run(feed={"x": x_np, "y": y_np}, fetch_list=[grads[0][1]])[0]

    eps = 1e-3
    num = np.zeros_like(x_np)
    def f(xv):
        r = y_np - xv
        a = np.abs(r)
        return float(np.where(a <= 1, 0.5 * r * r, a - 0.5).mean())
    for i in np.ndindex(*x_np.shape):
        xp = x_np.copy(); xp[i] += eps
        xm = x_np.copy(); xm[i] -= eps
        num[i] = (f(xp) - f(xm)) / (2 * eps)
    np.testing.assert_allclose(got, num, rtol=1e-2, atol=1e-4)


def test_round3_straggler_ops(rng_np):
    """positive_negative_pair + compare/reduce/pool3d/conv3d stragglers
    (VERDICT r2 task 7)."""
    # pnpair: q0 ordered pair agrees, q1 tie
    score = np.asarray([[.1, .9], [.2, .8], [.3, .5], [.4, .5]], np.float32)
    label = np.asarray([[1.], [0.], [1.], [0.]], np.float32)
    query = np.asarray([[0], [0], [1], [1]], np.int32)
    out = run("positive_negative_pair",
              {"Score": [score], "Label": [label], "QueryID": [query]},
              {"column": -1})
    assert float(out["PositivePair"][0][0]) == 1.0
    assert float(out["NegativePair"][0][0]) == 0.0
    assert float(out["NeutralPair"][0][0]) == 1.0
    # accumulators seed the counts
    out2 = run("positive_negative_pair",
               {"Score": [score], "Label": [label], "QueryID": [query],
                "AccumulatePositivePair": [np.asarray([2.0], np.float32)],
                "AccumulateNegativePair": [np.asarray([1.0], np.float32)],
                "AccumulateNeutralPair": [np.asarray([0.5], np.float32)]},
               {"column": -1})
    assert float(out2["PositivePair"][0][0]) == 3.0
    assert float(out2["NegativePair"][0][0]) == 1.0
    assert float(out2["NeutralPair"][0][0]) == 1.5

    x = rng_np.normal(size=(3, 4)).astype(np.float32)
    y = rng_np.normal(size=(3, 4)).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(run("greater_than", {"X": [x], "Y": [y]})["Out"][0]), x > y)
    np.testing.assert_array_equal(
        np.asarray(run("less_equal", {"X": [x], "Y": [y]})["Out"][0]), x <= y)
    np.testing.assert_allclose(
        np.asarray(run("reduce_max", {"X": [x]}, {"dim": 1})["Out"][0]),
        x.max(1), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(run("reduce_min", {"X": [x]}, {"dim": 0})["Out"][0]),
        x.min(0), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(run("hard_shrink", {"X": [x]}, {"threshold": 0.5})["Out"][0]),
        np.where(np.abs(x) > 0.5, x, 0.0))
    np.testing.assert_allclose(
        np.asarray(run("thresholded_relu", {"X": [x]},
                       {"threshold": 0.3})["Out"][0]),
        np.where(x > 0.3, x, 0.0))

    # conv3d / pool3d / max_pool2d_with_index shapes + values
    v = np.ones((1, 1, 3, 3, 3), np.float32)
    w = np.ones((2, 1, 2, 2, 2), np.float32)
    c3 = np.asarray(run("conv3d", {"Input": [v], "Filter": [w]})["Output"][0])
    assert c3.shape == (1, 2, 2, 2, 2)
    np.testing.assert_allclose(c3, 8.0)
    p3 = np.asarray(run("pool3d", {"X": [v * 2]},
                        {"ksize": [3, 3, 3], "strides": [1, 1, 1],
                         "pooling_type": "avg"})["Out"][0])
    assert p3.shape == (1, 1, 1, 1, 1)
    np.testing.assert_allclose(p3, 2.0)
    img = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    mp = run("max_pool2d_with_index", {"X": [img]},
             {"ksize": [2, 2], "strides": [2, 2]})
    np.testing.assert_array_equal(
        np.asarray(mp["Mask"][0]).reshape(-1), [5, 7, 13, 15])
