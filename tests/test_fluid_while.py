"""Fluid control flow: the ``while`` op lowered onto lax.while_loop with
tensor-array read/write — a dynamic RNN decoder loop (the reference's
recurrent_op/tensor_array machinery, executor-lowered instead of
interpreted)."""

import numpy as np

from paddle_tpu import fluid
from paddle_tpu.fluid import framework, layers


def test_while_dynamic_rnn_loop(rng_np):
    framework.reset_default_programs()
    T, B, D = 5, 3, 4
    x_np = rng_np.normal(size=(T, B, D)).astype(np.float32)
    w_np = (rng_np.normal(size=(D, D)) * 0.4).astype(np.float32)

    prog = framework.default_main_program()
    main = prog.global_block()
    for name, shape in (("x", (T, B, D)), ("w", (D, D)), ("i", (1,)),
                        ("t_lim", (1,)), ("cond", (1,)), ("h", (B, D)),
                        ("harr", (T, B, D))):
        main.create_var(name=name, shape=shape)

    sub = prog.create_block()
    sub.append_op("read_from_array", {"Array": ["x"], "I": ["i"]},
                  {"Out": ["xt"]}, {})
    sub.append_op("mul", {"X": ["h"], "Y": ["w"]}, {"Out": ["hw"]}, {})
    sub.append_op("elementwise_add", {"X": ["hw"], "Y": ["xt"]},
                  {"Out": ["pre"]}, {})
    sub.append_op("tanh", {"X": ["pre"]}, {"Out": ["h"]}, {})
    sub.append_op("write_to_array", {"X": ["h"], "I": ["i"],
                                     "Array": ["harr"]},
                  {"Out": ["harr"]}, {})
    sub.append_op("increment", {"X": ["i"]}, {"Out": ["i"]}, {"step": 1.0})
    sub.append_op("less_than", {"X": ["i"], "Y": ["t_lim"]},
                  {"Out": ["cond"]}, {})

    main.append_op(
        "while",
        {"Condition": ["cond"], "X": ["x", "w", "i", "t_lim", "h", "harr"]},
        {"Out": ["harr", "h"]},
        {"sub_block": sub.idx},
    )

    exe = fluid.Executor()
    (harr, h_last, i_final) = exe.run(
        feed={"x": x_np, "w": w_np,
              "i": np.zeros((1,), np.float32),
              "t_lim": np.full((1,), float(T), np.float32),
              "cond": np.ones((1,), bool),
              "h": np.zeros((B, D), np.float32),
              "harr": np.zeros((T, B, D), np.float32)},
        fetch_list=["harr", "h", "i"],
    )
    # carried state survives the loop even though "i" is not a declared Out
    assert float(i_final[0]) == T

    # numpy reference loop
    h = np.zeros((B, D), np.float32)
    ref = np.zeros((T, B, D), np.float32)
    for t in range(T):
        h = np.tanh(h @ w_np + x_np[t])
        ref[t] = h
    np.testing.assert_allclose(harr, ref, rtol=2e-2, atol=2e-2)  # bf16 mm
    np.testing.assert_allclose(h_last, ref[-1], rtol=2e-2, atol=2e-2)
