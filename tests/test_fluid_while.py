"""Fluid control flow: the ``while`` op lowered onto lax.while_loop with
tensor-array read/write — a dynamic RNN decoder loop (the reference's
recurrent_op/tensor_array machinery, executor-lowered instead of
interpreted)."""

import numpy as np

from paddle_tpu import fluid
from paddle_tpu.fluid import framework, layers


def test_while_dynamic_rnn_loop(rng_np):
    framework.reset_default_programs()
    T, B, D = 5, 3, 4
    x_np = rng_np.normal(size=(T, B, D)).astype(np.float32)
    w_np = (rng_np.normal(size=(D, D)) * 0.4).astype(np.float32)

    prog = framework.default_main_program()
    main = prog.global_block()
    for name, shape in (("x", (T, B, D)), ("w", (D, D)), ("i", (1,)),
                        ("t_lim", (1,)), ("cond", (1,)), ("h", (B, D)),
                        ("harr", (T, B, D))):
        main.create_var(name=name, shape=shape)

    sub = prog.create_block()
    sub.append_op("read_from_array", {"Array": ["x"], "I": ["i"]},
                  {"Out": ["xt"]}, {})
    sub.append_op("mul", {"X": ["h"], "Y": ["w"]}, {"Out": ["hw"]}, {})
    sub.append_op("elementwise_add", {"X": ["hw"], "Y": ["xt"]},
                  {"Out": ["pre"]}, {})
    sub.append_op("tanh", {"X": ["pre"]}, {"Out": ["h"]}, {})
    sub.append_op("write_to_array", {"X": ["h"], "I": ["i"],
                                     "Array": ["harr"]},
                  {"Out": ["harr"]}, {})
    sub.append_op("increment", {"X": ["i"]}, {"Out": ["i"]}, {"step": 1.0})
    sub.append_op("less_than", {"X": ["i"], "Y": ["t_lim"]},
                  {"Out": ["cond"]}, {})

    main.append_op(
        "while",
        {"Condition": ["cond"], "X": ["x", "w", "i", "t_lim", "h", "harr"]},
        {"Out": ["harr", "h"]},
        {"sub_block": sub.idx},
    )

    exe = fluid.Executor()
    (harr, h_last, i_final) = exe.run(
        feed={"x": x_np, "w": w_np,
              "i": np.zeros((1,), np.float32),
              "t_lim": np.full((1,), float(T), np.float32),
              "cond": np.ones((1,), bool),
              "h": np.zeros((B, D), np.float32),
              "harr": np.zeros((T, B, D), np.float32)},
        fetch_list=["harr", "h", "i"],
    )
    # carried state survives the loop even though "i" is not a declared Out
    assert float(i_final[0]) == T

    # numpy reference loop
    h = np.zeros((B, D), np.float32)
    ref = np.zeros((T, B, D), np.float32)
    for t in range(T):
        h = np.tanh(h @ w_np + x_np[t])
        ref[t] = h
    np.testing.assert_allclose(harr, ref, rtol=2e-2, atol=2e-2)  # bf16 mm
    np.testing.assert_allclose(h_last, ref[-1], rtol=2e-2, atol=2e-2)


def test_cond_op_selects_branch(rng_np):
    """cond lowered onto lax.cond: both branches traced, scalar-pred select."""
    framework.reset_default_programs()
    prog = framework.default_main_program()
    main = prog.global_block()
    for name, shape in (("cx", (4, 3)), ("cpred", (1,)), ("cout", (4, 3))):
        main.create_var(name=name, shape=shape)

    tb = prog.create_block()
    tb.append_op("scale", {"X": ["cx"]}, {"Out": ["cout"]}, {"scale": 2.0})
    fb = prog.create_block()
    fb.append_op("scale", {"X": ["cx"]}, {"Out": ["cout"]},
                 {"scale": -1.0, "bias": 5.0})

    main.append_op("cond", {"Cond": ["cpred"], "X": ["cx"]},
                   {"Out": ["cout"]},
                   {"true_block": tb.idx, "false_block": fb.idx})

    exe = fluid.Executor()
    x = rng_np.normal(size=(4, 3)).astype(np.float32)
    (out_t,) = exe.run(feed={"cx": x, "cpred": np.ones((1,), bool)},
                       fetch_list=["cout"])
    np.testing.assert_allclose(out_t, 2.0 * x, rtol=1e-6)
    (out_f,) = exe.run(feed={"cx": x, "cpred": np.zeros((1,), bool)},
                       fetch_list=["cout"])
    np.testing.assert_allclose(out_f, -x + 5.0, rtol=1e-6)


def test_cond_branch_reads_undeclared_outer_var(rng_np):
    """Branches may read outer vars NOT declared on the cond op; segment
    tracing and prune both follow sub-block reads."""
    framework.reset_default_programs()
    prog = framework.default_main_program()
    main = prog.global_block()
    for name, shape in (("qx", (4, 3)), ("qb", (3,)), ("qpred", (1,)),
                        ("qout", (4, 3))):
        main.create_var(name=name, shape=shape)

    tb = prog.create_block()
    # reads qb, which the cond op does NOT declare in X
    tb.append_op("elementwise_add", {"X": ["qx"], "Y": ["qb"]},
                 {"Out": ["qout"]}, {})
    fb = prog.create_block()
    fb.append_op("scale", {"X": ["qx"]}, {"Out": ["qout"]}, {"scale": 3.0})
    main.append_op("cond", {"Cond": ["qpred"], "X": ["qx"]},
                   {"Out": ["qout"]},
                   {"true_block": tb.idx, "false_block": fb.idx})

    exe = fluid.Executor()
    x = rng_np.normal(size=(4, 3)).astype(np.float32)
    b = rng_np.normal(size=(3,)).astype(np.float32)
    (out,) = exe.run(feed={"qx": x, "qb": b, "qpred": np.ones((1,), bool)},
                     fetch_list=["qout"])
    np.testing.assert_allclose(out, x + b, rtol=1e-6)

    pruned = prog.prune(["qout"])
    assert "qb" in pruned.global_block().vars  # sub-block read kept
