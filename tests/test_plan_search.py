"""tools/plan_search.py — the config-space feasibility pruner: ranking
determinism and the checked-in PLAN.json artifact (the full --enumerate
sweep is minutes of tracing and runs standalone, not in tier-1)."""

import importlib.util
import json
import os

_REPO = os.path.join(os.path.dirname(__file__), "..")


def _mod():
    spec = importlib.util.spec_from_file_location(
        "plan_search", os.path.join(_REPO, "tools", "plan_search.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tie_key_prefers_the_simpler_plan():
    """Statically indistinguishable variants must rank deterministically:
    smaller dp, lower zero, default lowering, fused on, no buckets, no
    remat — and bigger batch last among true ties."""
    ps = _mod()
    base = {"score_chip_ms_per_example": 1.0, "dp": 1, "zero": 0,
            "lowering": "auto", "fused_kernels": True, "seq_buckets": "",
            "remat": False, "batch": 16}
    assert ps._tie_key(base) < ps._tie_key(base | {"dp": 8,
                                                   "lowering": "gspmd"})
    assert ps._tie_key(base) < ps._tie_key(base | {"fused_kernels": False})
    assert ps._tie_key(base) < ps._tie_key(base | {"remat": True})
    assert ps._tie_key(base | {"batch": 32}) < ps._tie_key(base)
    # cost dominates all tie-breaks
    cheap = base | {"score_chip_ms_per_example": 0.5, "dp": 8,
                    "zero": 1, "remat": True}
    assert ps._tie_key(cheap) < ps._tie_key(base)


def test_mesh_shim_quacks_enough_for_the_static_models():
    ps = _mod()
    shim = ps._MeshShim(8)
    assert dict(shim.shape) == {"data": 8}
    assert shim.axis_names == ("data",)


def test_checked_in_plan_meets_the_acceptance_grid():
    """The persisted artifact of the last full sweep: ≥48 grid points,
    GL-P-MEM pruning actually engaged, and at least one family's top
    choice rediscovers the hand-picked bench config."""
    path = os.path.join(_REPO, "PLAN.json")
    assert os.path.exists(path), "run tools/plan_search.py --enumerate"
    plan = json.load(open(path))
    assert plan["schema"] == "paddle_tpu.plan/1"
    assert plan["grid_points"] >= 48
    assert plan["pruned"] >= 1
    fams = plan["families"]
    assert set(fams) == {"transformer", "resnet50", "lstm"}
    assert any(f["top_matches_bench"] for f in fams.values())
    for f in fams.values():
        top = f["top"]
        assert top and top["step_ms"] > 0
        assert top["score_chip_ms_per_example"] > 0
        # the ranked list is sorted by the deterministic key
        scores = [p["score_chip_ms_per_example"] for p in f["ranked"]]
        assert scores == sorted(scores)
    # pruned points carry the GL-P-MEM verdict they were cut by
    pruned = [p for f in fams.values() for p in f["pruned_points"]]
    assert pruned and all(p["pruned"].startswith("GL-P-MEM")
                          for p in pruned)
