"""ProtoDataProvider parity: the binary DataFormat.proto stream
(varint-delimited DataHeader + DataSamples, ProtoReader.h framing) is
read back into trainer feeds, sequences regrouped by ``is_beginning``,
and a TrainData(ProtoData(...)) config trains end-to-end through the
CLI.  MultiData zips two sources into one sample stream."""

from __future__ import annotations

import os
import textwrap

import numpy as np

from paddle_tpu.proto.build import message_class
from paddle_tpu.reader import proto_data as pdata

DataHeader = message_class("DataHeader")
DataSample = message_class("DataSample")


def _mk_header(slots):
    h = DataHeader()
    for t, d in slots:
        sd = h.slot_defs.add()
        sd.type = t
        sd.dim = d
    return h


def _dense_index_file(path, n=32, dim=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    header = _mk_header([(pdata.VECTOR_DENSE, dim), (pdata.INDEX, classes)])
    samples = []
    for _ in range(n):
        y = int(rng.integers(0, classes))
        x = rng.normal(size=(dim,)).astype(np.float32) * 0.1
        x[y * 2:(y + 1) * 2] += 1.0
        s = DataSample()
        vs = s.vector_slots.add()
        vs.values.extend(x.tolist())
        s.id_slots.append(y)
        samples.append(s)
    pdata.write_proto_stream(path, header, samples)


def test_proto_stream_roundtrip(tmp_path):
    p = str(tmp_path / "d.bin")
    _dense_index_file(p, n=5)
    header, samples = pdata.read_proto_stream(p)
    assert len(header.slot_defs) == 2 and len(samples) == 5
    assert header.slot_defs[0].dim == 8
    rows = list(pdata.proto_reader([p])())
    assert len(rows) == 5
    x, y = rows[0]
    assert len(x) == 8 and isinstance(y, int)
    # gzip variant
    pz = str(tmp_path / "d.bin.gz")
    _dense_index_file(pz, n=5)
    assert len(list(pdata.proto_reader([pz])())) == 5


def test_proto_sequences_regroup(tmp_path):
    header = _mk_header([(pdata.INDEX, 10)])
    samples = []
    for begin, val in [(True, 1), (False, 2), (False, 3),
                       (True, 4), (False, 5)]:
        s = DataSample()
        s.is_beginning = begin
        s.id_slots.append(val)
        samples.append(s)
    p = str(tmp_path / "seq.bin")
    pdata.write_proto_stream(p, header, samples)
    rows = list(pdata.proto_reader([p])())
    assert rows == [([1, 2, 3],), ([4, 5],)]
    (t,) = pdata.input_types_from_header(p)
    assert t.seq_type != 0  # sequence detected


def test_cli_trains_from_proto_data(tmp_path):
    _dense_index_file(str(tmp_path / "train.bin"), n=256)
    (tmp_path / "train.list").write_text(str(tmp_path / "train.bin") + "\n")
    cfg = tmp_path / "proto.conf"
    cfg.write_text(textwrap.dedent(f"""
        from paddle.trainer_config_helpers import *

        TrainData(ProtoData(files='{tmp_path}/train.list'))
        settings(batch_size=32, learning_rate=1e-2,
                 learning_method=AdamOptimizer())
        x = data_layer(name='x', size=8)
        pred = fc_layer(input=x, size=4, act=SoftmaxActivation())
        lbl = data_layer(name='label', size=4)
        outputs(classification_cost(input=pred, label=lbl))
    """))
    from paddle_tpu.trainer import cli

    rc = cli.main(["--config", str(cfg), "--job", "train",
                   "--num_passes", "4"])
    assert rc == 0


def test_multi_reader_zips_sources(tmp_path):
    p1, p2 = str(tmp_path / "a.bin"), str(tmp_path / "b.bin")
    _dense_index_file(p1, n=6, seed=1)
    _dense_index_file(p2, n=9, seed=2)
    r1 = pdata.proto_reader([p1])
    r2 = pdata.proto_reader([p2])
    rows = list(pdata.multi_reader([r1, r2])())
    assert len(rows) == 6  # shortest source bounds the zip
    assert len(rows[0]) == 4  # 2 slots from each source


def test_show_pb_and_torch2paddle(tmp_path, capsys):
    """The small-utils family: show_pb prints the stream; torch2paddle
    writes reference-binary params a Parameters object loads back."""
    p = str(tmp_path / "d.bin")
    _dense_index_file(p, n=2)
    from paddle_tpu.utils import show_pb

    assert show_pb.main([p]) == 0
    out = capsys.readouterr().out
    assert "slot_defs" in out and "vector_slots" in out

    import torch

    from paddle_tpu.core.parameters import load_reference_param
    from paddle_tpu.utils.torch2paddle import convert_state_dict

    state = {"fc.weight": torch.arange(6, dtype=torch.float32).reshape(2, 3),
             "fc.bias": torch.ones(2)}
    written = convert_state_dict(state, str(tmp_path / "params"))
    assert sorted(written) == ["fc_bias", "fc_weight"]
    w = load_reference_param(str(tmp_path / "params" / "fc_weight"))
    # [out=2, in=3] transposed to paddle [in, out] layout
    np.testing.assert_array_equal(
        w.reshape(3, 2), np.arange(6, dtype=np.float32).reshape(2, 3).T)


def test_image_multiproc_transformer(tmp_path):
    from PIL import Image

    from paddle_tpu.utils.image_multiproc import MultiProcessImageTransformer

    rows = []
    rng = np.random.default_rng(0)
    for i in range(4):
        p = tmp_path / f"im{i}.png"
        Image.fromarray(
            rng.integers(0, 255, size=(40, 30, 3), dtype=np.uint8)).save(p)
        rows.append((str(p), i))
    tf = MultiProcessImageTransformer(procnum=2, resize_size=32, crop_size=24)
    out = list(tf.run(rows))
    assert [lab for _, lab in out] == [0, 1, 2, 3]  # order preserved
    assert out[0][0].shape == (3, 24, 24)


def test_length_one_sequences_keep_list_shape(tmp_path):
    """A sequence dataset containing a length-1 sequence must still yield
    per-timestep LISTS for every row (review finding r4)."""
    header = _mk_header([(pdata.INDEX, 10)])
    samples = []
    for begin, val in [(True, 1), (False, 2), (True, 7), (True, 3),
                       (False, 4)]:
        s = DataSample()
        s.is_beginning = begin
        s.id_slots.append(val)
        samples.append(s)
    p = str(tmp_path / "seq1.bin")
    pdata.write_proto_stream(p, header, samples)
    rows = list(pdata.proto_reader([p], sequential=True)())
    assert rows == [([1, 2],), ([7],), ([3, 4],)]


def test_proto_config_emits_reference_dataconfig(tmp_path):
    """TrainData(ProtoData(...)) serializes as DataConfig.type='proto'
    with usage_ratio, like the reference's config_parser emission."""
    import textwrap

    from paddle_tpu.trainer.config_parser import parse_config

    cfg = tmp_path / "p.conf"
    cfg.write_text(textwrap.dedent("""
        from paddle.trainer_config_helpers import *
        TrainData(ProtoData(files='train.list', usage_ratio=0.5))
        settings(batch_size=8, learning_rate=1e-2)
        x = data_layer(name='x', size=4)
        pred = fc_layer(input=x, size=2, act=SoftmaxActivation())
        lbl = data_layer(name='label', size=2)
        outputs(classification_cost(input=pred, label=lbl))
    """))
    parsed = parse_config(str(cfg), "")
    dc = parsed.trainer_config.data_config
    assert dc.type == "proto"
    assert dc.files == "train.list"
    assert abs(dc.usage_ratio - 0.5) < 1e-9


def test_cli_trains_from_multi_data(tmp_path):
    """MultiData: two ProtoData sub-providers zip into one sample stream
    through the CLI (MultiDataProvider parity), and the TrainerConfig
    emits nested sub_data_configs."""
    import textwrap

    # source A: dense features; source B: the label
    ha = _mk_header([(pdata.VECTOR_DENSE, 8)])
    hb = _mk_header([(pdata.INDEX, 4)])
    rng = np.random.default_rng(0)
    sa, sb = [], []
    for _ in range(128):
        y = int(rng.integers(0, 4))
        x = rng.normal(size=(8,)).astype(np.float32) * 0.1
        x[y * 2:(y + 1) * 2] += 1.0
        s = DataSample()
        s.vector_slots.add().values.extend(x.tolist())
        sa.append(s)
        s = DataSample()
        s.id_slots.append(y)
        sb.append(s)
    pdata.write_proto_stream(str(tmp_path / "a.bin"), ha, sa)
    pdata.write_proto_stream(str(tmp_path / "b.bin"), hb, sb)
    (tmp_path / "a.list").write_text(str(tmp_path / "a.bin") + "\n")
    (tmp_path / "b.list").write_text(str(tmp_path / "b.bin") + "\n")
    cfg = tmp_path / "multi.conf"
    cfg.write_text(textwrap.dedent(f"""
        from paddle.trainer_config_helpers import *

        TrainData(MultiData([ProtoData(files='{tmp_path}/a.list'),
                             ProtoData(files='{tmp_path}/b.list')]))
        settings(batch_size=32, learning_rate=1e-2,
                 learning_method=AdamOptimizer())
        x = data_layer(name='x', size=8)
        pred = fc_layer(input=x, size=4, act=SoftmaxActivation())
        lbl = data_layer(name='label', size=4)
        outputs(classification_cost(input=pred, label=lbl))
    """))
    from paddle_tpu.trainer import cli
    from paddle_tpu.trainer.config_parser import parse_config

    parsed = parse_config(str(cfg), "")
    dc = parsed.trainer_config.data_config
    assert dc.type == "multi" and len(dc.sub_data_configs) == 2
    assert dc.sub_data_configs[0].type == "proto"

    rc = cli.main(["--config", str(cfg), "--job", "train",
                   "--num_passes", "4"])
    assert rc == 0


def test_preprocess_img_dataset_roundtrip(tmp_path):
    """preprocess_img: label-dir tree -> batched npz + labels/meta, and
    the reader streams (image, label) samples back."""
    from PIL import Image

    from paddle_tpu.utils.preprocess_img import (
        ImageClassificationDatasetCreater,
        batch_reader,
    )

    rng = np.random.default_rng(0)
    for lab in ("cat", "dog"):
        os.makedirs(tmp_path / lab)
        for i in range(6):
            Image.fromarray(rng.integers(
                0, 255, size=(40, 30, 3), dtype=np.uint8)).save(
                tmp_path / lab / f"{i}.png")
    out = ImageClassificationDatasetCreater(
        str(tmp_path), 16, test_ratio=0.25).create_dataset()
    assert (open(os.path.join(out, "labels.txt")).read().split()
            == ["cat", "dog"])
    train = list(batch_reader(os.path.join(out, "train"))())
    test = list(batch_reader(os.path.join(out, "test"))())
    assert len(train) == 9 and len(test) == 3
    im, lab = train[0]
    assert im.shape == (3, 16, 16) and lab in (0, 1)


def test_sparse_value_slot_reader_feeder_roundtrip(tmp_path):
    """VECTOR_SPARSE_VALUE slots yield (index, value) PAIRS — the v2
    sparse_float convention the feeder densifies (ADVICE r4: the old
    (ids_list, values_list) tuple unpacked wrong for 2-id timesteps)."""
    from paddle_tpu.layers import data_type as dt
    from paddle_tpu.reader.feeder import DataFeeder

    p = str(tmp_path / "sv.bin")
    header = _mk_header([(pdata.VECTOR_SPARSE_VALUE, 16),
                         (pdata.INDEX, 4)])
    samples = []
    truth = []
    rng = np.random.default_rng(0)
    for _ in range(6):
        ids = sorted(rng.choice(16, size=2, replace=False).tolist())
        vals = rng.normal(size=(2,)).astype(np.float32).tolist()
        truth.append((ids, vals))
        s = DataSample()
        vs = s.vector_slots.add()
        vs.ids.extend(ids)
        vs.values.extend(vals)
        s.id_slots.append(1)
        samples.append(s)
    pdata.write_proto_stream(p, header, samples)

    rows = list(pdata.proto_reader([p])())
    assert len(rows) == 6
    pairs, label = rows[0]
    # exactly-two-ids timestep: must be [(i0,v0),(i1,v1)], not (ids, vals)
    assert len(pairs) == 2 and len(pairs[0]) == 2
    assert [i for i, _ in pairs] == truth[0][0]
    np.testing.assert_allclose([v for _, v in pairs], truth[0][1], rtol=1e-6)

    types = pdata.input_types_from_header(p)
    assert types[0].kind == dt.DataKind.SPARSE_FLOAT
    feeder = DataFeeder({"sx": types[0], "sy": types[1]})
    feed = feeder(rows)
    dense = np.asarray(feed["sx"])
    assert dense.shape == (6, 16)
    for r, (ids, vals) in enumerate(truth):
        np.testing.assert_allclose(dense[r, ids], vals, rtol=1e-6)
        assert float(np.abs(dense[r]).sum()) == float(
            np.abs(np.asarray(vals)).sum()) or np.isclose(
            np.abs(dense[r]).sum(), np.abs(np.asarray(vals)).sum(),
            rtol=1e-5)


def test_usage_ratio_subsamples_sequences(tmp_path):
    """usage_ratio < 1 consumes only that fraction of each file's
    sequences (ProtoDataProvider.cpp:397-399 truncation semantics)."""
    p = str(tmp_path / "ur.bin")
    _dense_index_file(p, n=40)
    full = list(pdata.proto_reader([p])())
    half = list(pdata.proto_reader([p], usage_ratio=0.5)())
    quarter = list(pdata.proto_reader([p], usage_ratio=0.25)())
    assert len(full) == 40 and len(half) == 20 and len(quarter) == 10
    assert len(list(pdata.proto_reader([p], usage_ratio=1.0)())) == 40
    # the shuffle precedes the cut (reference sequenceLoop order), so
    # repeated passes sample DIFFERENT subsets — no fixed tail is starved
    full_keys = {tuple(row[0]) for row in full}
    seen: set = set()
    r = pdata.proto_reader([p], usage_ratio=0.5)
    for _ in range(12):
        for row in r():
            assert tuple(row[0]) in full_keys
            seen.add(tuple(row[0]))
    assert len(seen) > 20, "usage_ratio subsets never rotate"
