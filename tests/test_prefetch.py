"""Input pipeline & overlapped step loop (ISSUE 3): DevicePrefetcher
ordering/exception/shutdown semantics, deferred-fence (sync_period)
trajectory equality against the synchronous loop, the reader decorator
exception fixes, shard_batch partial-batch policies, and the vectorized
DataFeeder densify paths."""

import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.enforce import EnforceError
from paddle_tpu.parallel.mesh import MeshContext, apply_remainder, make_mesh
from paddle_tpu.reader.decorator import buffered, xmap_readers
from paddle_tpu.reader.feeder import DataFeeder, _densify_ids, _densify_pairs
from paddle_tpu.reader.prefetch import DevicePrefetcher, SynchronousFeeds


# -- trainer helpers ----------------------------------------------------------

def _tiny_trainer(lr=0.05):
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type

    base.reset_name_counters()
    x = layer.data(name="px", type=data_type.dense_vector(6))
    h = layer.fc(input=x, size=4, act=act.SoftmaxActivation())
    lbl = layer.data(name="py", type=data_type.integer_value(4))
    cost = layer.classification_cost(input=h, label=lbl)
    parameters = paddle.parameters.create(paddle.topology.Topology(cost))
    return paddle.trainer.SGD(
        cost=cost, parameters=parameters,
        update_equation=paddle.optimizer.SGD(learning_rate=lr))


def _batches(n_samples=64, batch=8):
    rng = np.random.default_rng(0)
    data = [(rng.normal(size=(6,)).astype(np.float32), int(i % 4))
            for i in range(n_samples)]
    return paddle.reader.batch(lambda: iter(data), batch)


# -- DevicePrefetcher core contract -------------------------------------------

def test_prefetcher_matches_sync_order_and_content():
    def reader():
        for i in range(7):
            yield [(i, j) for j in range(3)]

    sync = list(SynchronousFeeds(reader))
    pre = list(DevicePrefetcher(reader, depth=2))
    assert [fb.feed for fb in pre] == [fb.feed for fb in sync]
    assert [fb.examples for fb in pre] == [3] * 7


def test_prefetcher_propagates_reader_exception():
    def reader():
        yield [1]
        yield [2]
        raise ValueError("disk ate the epoch")

    pf = DevicePrefetcher(reader, depth=2)
    assert next(pf).feed == [1]
    assert next(pf).feed == [2]
    with pytest.raises(ValueError, match="disk ate the epoch"):
        next(pf)
    # terminal: later pulls end the stream instead of hanging
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetcher_propagates_feeder_exception():
    def reader():
        yield [1, 2]

    def bad_feeder(batch):
        raise TypeError("sample shape mismatch")

    pf = DevicePrefetcher(reader, feeder=bad_feeder, depth=2)
    with pytest.raises(TypeError, match="sample shape mismatch"):
        next(pf)


def test_prefetcher_close_unblocks_producer_midstream():
    produced = []

    def reader():
        for i in range(10_000):
            produced.append(i)
            yield [i]

    pf = DevicePrefetcher(reader, depth=2)
    assert next(pf).feed == [0]
    # the bounded queue has the producer blocked in put by now
    pf.close()
    assert not pf._thread.is_alive()
    assert len(produced) < 100  # read-ahead stayed bounded
    with pytest.raises(StopIteration):
        next(pf)


def test_prefetcher_as_context_manager_drains_on_early_exit():
    def reader():
        while True:
            yield [0]

    with DevicePrefetcher(reader, depth=2) as pf:
        next(pf)
    assert not pf._thread.is_alive()


# -- deferred fence + overlap through SGD.train -------------------------------

def _run_train(sync_period, prefetch, n_samples=64, batch=8, passes=2):
    from paddle_tpu import metrics as metrics_mod
    from paddle_tpu.core import rng

    rng.seed(7)
    trainer = _tiny_trainer()
    sink = metrics_mod.MemorySink()
    reg = metrics_mod.MetricsRegistry("test_prefetch")
    reg.add_sink(sink)
    events = []

    def handler(e):
        events.append((type(e).__name__, getattr(e, "batch_id", None)))

    trainer.train(reader=_batches(n_samples, batch), num_passes=passes,
                  event_handler=handler, metrics_registry=reg,
                  sync_period=sync_period, prefetch=prefetch)
    steps = [r for r in sink.records if r.get("kind") == "step"]
    return trainer, steps, events


def test_trajectory_bit_identical_sync_vs_overlapped():
    """Same batches + same RNG key order => the overlapped loop must not
    change training AT ALL: per-step losses and the final parameters are
    bit-identical for (sync_period=1, prefetch=0) vs (4, 2) vs (3, 1)."""
    base_tr, base_steps, base_events = _run_train(1, 0)
    base_losses = [r["loss"] for r in base_steps]
    assert len(base_losses) == 16 and np.all(np.isfinite(base_losses))

    base_ends = [b for n, b in base_events if n == "EndIteration"]
    assert base_ends == list(range(8)) * 2

    for sp, pf in ((4, 2), (3, 1), (100, 2)):
        tr, steps, events = _run_train(sp, pf)
        np.testing.assert_array_equal(
            np.asarray([r["loss"] for r in steps]),
            np.asarray(base_losses),
            err_msg=f"trajectory diverged at sync_period={sp} prefetch={pf}")
        for name in tr.parameters.names():
            np.testing.assert_array_equal(
                np.asarray(tr.parameters[name]),
                np.asarray(base_tr.parameters[name]))
        # EndIteration still fires once per batch, ids in order
        assert [b for n, b in events if n == "EndIteration"] == base_ends


def test_sync_period_1_keeps_v2_event_cadence():
    _, _, events = _run_train(1, 2, n_samples=16, batch=8, passes=1)
    per_batch = [n for n, _ in events
                 if n in ("BeginIteration", "EndForwardBackward",
                          "EndIteration")]
    assert per_batch == ["BeginIteration", "EndForwardBackward",
                        "EndIteration"] * 2


def test_deferred_fence_bursts_and_schema2_fields():
    _, steps, events = _run_train(4, 2, n_samples=32, batch=8, passes=1)
    assert len(steps) == 4
    for r in steps:
        assert r["schema"] == "paddle_tpu.metrics/15"
        assert "input_wait_ms" in r and "host_stall_ms" in r
        assert r["input_wait_ms"] >= 0.0 and r["host_stall_ms"] >= 0.0
    # with sync_period=4 the EndIterations arrive as one burst after the
    # last dispatch: every BeginIteration precedes every EndIteration
    order = [n for n, _ in events if n.endswith("Iteration")]
    assert order == ["BeginIteration"] * 4 + ["EndIteration"] * 4


def test_default_config_keeps_seed_feed_conversion_order(monkeypatch):
    """Unmodified v2 config (prefetch=0, remainder=error): the seed's
    order — reader pull, BeginIteration, THEN feed conversion — so a
    handler may still mutate feeder state for the CURRENT batch.  With
    prefetch, conversion runs ahead of the events (documented)."""
    from paddle_tpu.core import rng
    from paddle_tpu.reader.feeder import DataFeeder

    orig_feed = DataFeeder.feed

    def run(prefetch):
        rng.seed(7)
        trainer = _tiny_trainer()
        trace = []
        monkeypatch.setattr(
            DataFeeder, "feed",
            lambda self, batch: (trace.append("convert"),
                                 orig_feed(self, batch))[1])

        def handler(e):
            if type(e).__name__ == "BeginIteration":
                trace.append("begin")

        trainer.train(reader=_batches(16, 8), num_passes=1,
                      event_handler=handler, prefetch=prefetch)
        return trace

    assert run(0) == ["begin", "convert", "begin", "convert"]
    overlapped = run(2)
    assert sorted(overlapped) == sorted(["begin", "convert"] * 2)
    assert overlapped != ["begin", "convert", "begin", "convert"]


def test_sync_input_wait_includes_reader_time():
    """input_wait_ms in the default synchronous path must cover the
    reader pull (the dominant starvation cost), not just conversion."""
    from paddle_tpu import metrics as metrics_mod
    from paddle_tpu.core import rng

    rng.seed(7)
    trainer = _tiny_trainer()
    sink = metrics_mod.MemorySink()
    reg = metrics_mod.MetricsRegistry("wait_test")
    reg.add_sink(sink)
    rngnp = np.random.default_rng(0)

    def reader():
        for i in range(3):
            time.sleep(0.03)
            yield [(rngnp.normal(size=(6,)).astype(np.float32), int(j % 4))
                   for j in range(8)]

    trainer.train(reader=reader, num_passes=1, metrics_registry=reg,
                  sync_period=1, prefetch=0, event_handler=lambda e: None)
    waits = [r["input_wait_ms"] for r in sink.records
             if r.get("kind") == "step"]
    assert len(waits) == 3
    assert all(w >= 25.0 for w in waits), waits


def test_densify_pairs_rejects_fractional_index():
    with pytest.raises(IndexError, match="fractional"):
        _densify_pairs([[(1.5, 0.3)]], 8)


def test_preemption_drain_with_prefetch(tmp_path):
    """SIGTERM mid-pass with the prefetcher running: the loop flushes its
    fence backlog, checkpoints at a batch boundary and returns; the
    worker thread is drained, not leaked."""
    import os
    import signal

    rng = np.random.default_rng(0)

    def reader():
        for i in range(64):
            if i == 16:
                os.kill(os.getpid(), signal.SIGTERM)
            yield rng.normal(size=(6,)).astype(np.float32), int(i % 4)

    before = threading.active_count()
    trainer = _tiny_trainer()
    trainer.train(reader=paddle.reader.batch(reader, 8), num_passes=50,
                  checkpoint_dir=str(tmp_path / "ck"),
                  sync_period=3, prefetch=2)
    from paddle_tpu.trainer import checkpoint as ckpt

    found = ckpt.latest_checkpoint(str(tmp_path / "ck"))
    assert found is not None
    assert found[1]["pass_id"] < 49
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


# -- reader decorator fixes ---------------------------------------------------

def test_buffered_propagates_reader_exception():
    def failing():
        yield 1
        yield 2
        raise RuntimeError("mid-epoch IO error")

    got = []
    with pytest.raises(RuntimeError, match="mid-epoch IO error"):
        for e in buffered(failing, 2)():
            got.append(e)
    assert got == [1, 2]  # nothing silently truncated before the raise


def test_buffered_early_abandon_unblocks_producer():
    before = threading.active_count()

    def endless():
        i = 0
        while True:
            yield i
            i += 1

    gen = buffered(endless, 2)()
    assert next(gen) == 0
    gen.close()  # consumer walks away mid-stream
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before, \
        "buffered() leaked its producer thread blocked in Queue.put"


def _consume_with_timeout(reader, timeout=15.0):
    """Drive a reader on a worker thread so a regression to the infinite
    consumer loop fails the test instead of hanging the suite."""
    result: dict = {}

    def consume():
        try:
            result["items"] = list(reader())
        except BaseException as e:
            result["exc"] = e

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    t.join(timeout)
    assert not t.is_alive(), "consumer hung (the pre-fix deadlock)"
    return result


def test_xmap_mapper_exception_raises_instead_of_hanging():
    def mapper(x):
        if x == 5:
            raise ValueError("bad sample 5")
        return x * 2

    r = xmap_readers(mapper, lambda: iter(range(32)), process_num=2,
                     buffer_size=4)
    result = _consume_with_timeout(r)
    assert isinstance(result.get("exc"), ValueError)
    assert "bad sample 5" in str(result["exc"])


def test_xmap_source_exception_raises_instead_of_hanging():
    def bad_source():
        yield 1
        raise OSError("source died")

    r = xmap_readers(lambda x: x, bad_source, process_num=3, buffer_size=2)
    result = _consume_with_timeout(r)
    assert isinstance(result.get("exc"), OSError)


def test_xmap_early_abandon_releases_workers():
    before = threading.active_count()

    def endless():
        i = 0
        while True:
            yield i
            i += 1

    gen = xmap_readers(lambda x: x, endless, process_num=3, buffer_size=2)()
    assert next(gen) is not None or True
    gen.close()  # consumer walks away after one item
    deadline = time.monotonic() + 5
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before, \
        "xmap_readers leaked worker threads after early consumer exit"


def test_xmap_ordered_happy_path_unchanged():
    r = xmap_readers(lambda x: x * x, lambda: iter(range(20)),
                     process_num=4, buffer_size=4, order=True)
    result = _consume_with_timeout(r)
    assert result.get("items") == [x * x for x in range(20)]


# -- partial-batch policies ---------------------------------------------------

def _mesh2():
    return MeshContext(mesh=make_mesh({"data": 2}))


def test_apply_remainder_drop_and_pad():
    feed = {"x": np.arange(10, dtype=np.float32).reshape(5, 2),
            "y": np.arange(5)}
    dropped = apply_remainder(feed, 2, "drop")
    assert dropped["x"].shape == (4, 2) and dropped["y"].shape == (4,)
    padded = apply_remainder(feed, 2, "pad")
    assert padded["x"].shape == (6, 2) and padded["y"].shape == (6,)
    np.testing.assert_array_equal(padded["x"][5], feed["x"][4])
    assert padded["y"][5] == feed["y"][4]
    # divisible batches pass through untouched
    ok = {"x": np.zeros((4, 2))}
    assert apply_remainder(ok, 2, "drop") is ok
    # drop smaller-than-mesh -> None (callers skip the batch)
    assert apply_remainder({"x": np.zeros((1, 2))}, 2, "drop") is None
    with pytest.raises(EnforceError):
        apply_remainder(feed, 2, "bogus")


def test_shard_batch_remainder_opt_in():
    ctx = _mesh2()
    feed = {"x": np.zeros((5, 2), np.float32)}
    with pytest.raises(EnforceError):  # default stays strict
        ctx.shard_batch(feed)
    out = ctx.shard_batch(feed, remainder="drop")
    assert out["x"].shape == (4, 2)
    out = ctx.shard_batch(feed, remainder="pad")
    assert out["x"].shape == (6, 2)


def test_prefetcher_remainder_policies_with_mesh():
    ctx = _mesh2()

    def reader():
        yield [(np.zeros(2, np.float32),)] * 4
        yield [(np.ones(2, np.float32),)] * 3  # partial tail batch

    def feeder(batch):
        return {"x": np.stack([s[0] for s in batch])}

    fbs = list(DevicePrefetcher(reader, feeder, ctx, depth=2,
                                remainder="drop"))
    assert [fb.feed["x"].shape[0] for fb in fbs] == [4, 2]
    fbs = list(DevicePrefetcher(reader, feeder, ctx, depth=2,
                                remainder="pad"))
    assert [fb.feed["x"].shape[0] for fb in fbs] == [4, 4]
    # examples still counts REAL samples, not the padded/dropped size
    assert [fb.examples for fb in fbs] == [4, 3]
    # a batch that drops to nothing is skipped, not an error
    def tiny():
        yield [(np.zeros(2, np.float32),)]

    assert list(DevicePrefetcher(tiny, feeder, ctx, remainder="drop")) == []


def test_trainer_test_honors_batch_remainder():
    """trainer.test() on a multi-device mesh must apply the same
    partial-batch policy as training (a 5-sample tail batch on the
    8-device default mesh would otherwise hard-error)."""
    from paddle_tpu.core import flags

    trainer = _tiny_trainer()
    trainer.train(reader=_batches(16, 8), num_passes=1)
    rng = np.random.default_rng(1)
    ragged = [(rng.normal(size=(6,)).astype(np.float32), int(i % 4))
              for i in range(21)]  # 8 + 8 + 5-sample tail

    prev = flags.get("batch_remainder")
    try:
        flags.set("batch_remainder", "drop")
        res = trainer.test(reader=paddle.reader.batch(lambda: iter(ragged), 8))
        assert np.isfinite(res.cost)
        flags.set("batch_remainder", "pad")
        res = trainer.test(reader=paddle.reader.batch(lambda: iter(ragged), 8))
        assert np.isfinite(res.cost)
    finally:
        flags.set("batch_remainder", prev)


# -- vectorized DataFeeder hot path -------------------------------------------

def _densify_ids_ref(rows, dim):
    dense = np.zeros((len(rows), dim), np.float32)
    for i, ids in enumerate(rows):
        dense[i, np.asarray(list(ids), dtype=np.int64)] = 1.0
    return dense


def _densify_pairs_ref(rows, dim):
    dense = np.zeros((len(rows), dim), np.float32)
    for i, pairs in enumerate(rows):
        for j, v in pairs:
            dense[i, j] = v  # the seed's per-pair loop: last write wins
    return dense


def test_densify_ids_vectorized_matches_reference():
    rng = np.random.default_rng(3)
    rows = [list(rng.integers(0, 50, size=rng.integers(0, 8)))
            for _ in range(17)]
    rows[3] = []          # empty row
    rows[5] = [7, 7, 7]   # duplicates collapse to 1
    np.testing.assert_array_equal(
        _densify_ids(rows, 50), _densify_ids_ref(rows, 50))
    assert _densify_ids([[], []], 4).sum() == 0


def test_densify_pairs_vectorized_matches_reference():
    rng = np.random.default_rng(4)
    rows = [[(int(j), float(v)) for j, v in
             zip(rng.integers(0, 30, size=k), rng.normal(size=k))]
            for k in rng.integers(0, 6, size=13)]
    rows[2] = []
    np.testing.assert_allclose(
        _densify_pairs(rows, 30), _densify_pairs_ref(rows, 30), rtol=1e-6)
    # duplicate indices keep the seed's LAST-WRITE-WINS semantic, so
    # v2-era sparse_float datasets produce bit-identical feeds
    out = _densify_pairs([[(3, 1.0), (3, 2.0)]], 8)
    assert out[0, 3] == 2.0
    # malformed pairs still fail fast (the seed's unpack error) instead
    # of silently misaligning every later pair in the flat scan
    with pytest.raises(ValueError):
        _densify_pairs([[(1, 0.5, 9.9)], [(2, 1.0)]], 8)


def test_feeder_uniform_sequence_fast_path_matches_ragged():
    from paddle_tpu.layers.data_type import integer_value_sequence

    feeder = DataFeeder({"w": integer_value_sequence(100)})
    uniform = [([1, 2, 3],), ([4, 5, 6],), ([7, 8, 9],)]
    ragged = [([1, 2, 3],), ([4, 5, 6],), ([7, 8],)]
    fast = feeder.feed(uniform)["w"]
    slow = feeder.feed(ragged)["w"]
    assert fast.data.shape == (3, 16)  # bucket-padded like the slow path
    assert slow.data.shape == (3, 16)
    np.testing.assert_array_equal(np.asarray(fast.data)[:, :3],
                                  [[1, 2, 3], [4, 5, 6], [7, 8, 9]])
    np.testing.assert_array_equal(np.asarray(fast.length), [3, 3, 3])
    assert fast.data.dtype == slow.data.dtype


@pytest.mark.slow
def test_overlap_speeds_up_slow_reader():
    """The acceptance property (lenient CI threshold; bench.py publishes
    the calibrated ≥1.5x row): reader sleep ≈ step time must overlap."""

    def timed(sync_period, prefetch):
        from paddle_tpu.core import rng

        rng.seed(7)
        trainer = _tiny_trainer()
        rngnp = np.random.default_rng(0)
        data = [(rngnp.normal(size=(6,)).astype(np.float32), int(i % 4))
                for i in range(96)]

        def reader():
            for i in range(0, 96, 8):
                time.sleep(0.02)
                yield [data[j] for j in range(i, i + 8)]

        trainer.train(reader=lambda: iter([data[:8]]), num_passes=1,
                      sync_period=1, prefetch=0)  # pay the compile
        t0 = time.perf_counter()
        trainer.train(reader=reader, num_passes=1,
                      sync_period=sync_period, prefetch=prefetch)
        return time.perf_counter() - t0

    # wall-clock on a shared CI box is noisy: best of 2 per side
    t_sync = min(timed(1, 0) for _ in range(2))
    t_pre = min(timed(8, 2) for _ in range(2))
    assert t_pre < t_sync, (t_sync, t_pre)
