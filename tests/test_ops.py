"""Op-level numeric tests, following the reference's compare-two-
implementations pattern (SURVEY §4): each op is checked against a plain
numpy reference, and gradient-carrying ops against finite differences
(the ``op_test.py`` check_grad analog)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.lod import SequenceBatch, from_ragged
from paddle_tpu.ops import loss as L
from paddle_tpu.ops import math as M
from paddle_tpu.ops import nn as N
from paddle_tpu.ops import rnn as R
from paddle_tpu.ops import sequence as S


def numeric_grad(f, x, eps=1e-3):
    """Central finite differences of scalar f at x (LayerGradUtil analog)."""
    x = np.asarray(x, np.float64)
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        idx = it.multi_index
        xp = x.copy()
        xp[idx] += eps
        xm = x.copy()
        xm[idx] -= eps
        g[idx] = (f(xp) - f(xm)) / (2 * eps)
        it.iternext()
    return g


def check_grad(f, x, rtol=2e-2, atol=2e-3):
    ana = np.asarray(jax.grad(lambda a: f(a))(jnp.asarray(x, jnp.float32)))
    num = numeric_grad(lambda a: float(f(jnp.asarray(a, jnp.float32))), x)
    np.testing.assert_allclose(ana, num, rtol=rtol, atol=atol)


def test_matmul_matches_numpy(rng_np):
    a = rng_np.normal(size=(4, 5)).astype(np.float32)
    b = rng_np.normal(size=(5, 3)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(M.matmul(a, b)), a @ b, rtol=1e-5)


def test_conv2d_matches_manual(rng_np):
    x = rng_np.normal(size=(2, 5, 5, 3)).astype(np.float32)
    w = rng_np.normal(size=(3, 3, 3, 4)).astype(np.float32)
    y = np.asarray(N.conv2d(x, w, stride=1, padding=1))
    assert y.shape == (2, 5, 5, 4)
    # check one output element by hand
    xp = np.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    ref = np.sum(xp[0, 0:3, 0:3, :, None] * w, axis=(0, 1, 2))
    np.testing.assert_allclose(y[0, 0, 0], ref, rtol=1e-4)


def test_pooling(rng_np):
    x = rng_np.normal(size=(1, 4, 4, 2)).astype(np.float32)
    mx = np.asarray(N.max_pool2d(x, 2, 2))
    ref = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(2, 4))
    np.testing.assert_allclose(mx, ref, rtol=1e-5, atol=1e-6)
    av = np.asarray(N.avg_pool2d(x, 2, 2))
    ref = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(2, 4))
    np.testing.assert_allclose(av, ref, rtol=1e-5, atol=1e-6)


def test_batch_norm_train_and_infer(rng_np):
    x = rng_np.normal(2.0, 3.0, size=(16, 4)).astype(np.float32)
    scale, bias = np.ones(4, np.float32), np.zeros(4, np.float32)
    rm, rv = np.zeros(4, np.float32), np.ones(4, np.float32)
    y, nm, nv = N.batch_norm(jnp.asarray(x), scale, bias, rm, rv, True, momentum=0.0)
    np.testing.assert_allclose(np.asarray(y).mean(axis=0), 0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(axis=0), 1, atol=1e-2)
    # momentum=0 -> running stats == batch stats
    np.testing.assert_allclose(np.asarray(nm), x.mean(axis=0), rtol=1e-4)
    y2, _, _ = N.batch_norm(jnp.asarray(x), scale, bias, nm, nv, False)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y), atol=1e-4)


def test_softmax_cross_entropy_grad(rng_np):
    logits = rng_np.normal(size=(3, 5))
    labels = np.array([0, 2, 4])

    def f(lg):
        return jnp.mean(L.softmax_cross_entropy_with_logits(lg, jnp.asarray(labels)))

    check_grad(f, logits)


def test_square_error_grad(rng_np):
    pred = rng_np.normal(size=(4, 3))
    label = rng_np.normal(size=(4, 3)).astype(np.float32)

    def f(p):
        return jnp.mean(L.square_error(p, jnp.asarray(label)))

    check_grad(f, pred)


def test_seq_pooling(rng_np):
    seqs = [rng_np.normal(size=(n, 3)).astype(np.float32) for n in (2, 5, 1)]
    sb = from_ragged(seqs)
    np.testing.assert_allclose(
        np.asarray(S.seq_pool_sum(sb)), np.stack([s.sum(0) for s in seqs]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(S.seq_pool_avg(sb)), np.stack([s.mean(0) for s in seqs]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(S.seq_pool_max(sb)), np.stack([s.max(0) for s in seqs]), rtol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(S.seq_pool_sqrt(sb)),
        np.stack([s.sum(0) / np.sqrt(len(s)) for s in seqs]),
        rtol=1e-5,
    )


def test_seq_expand_and_first_last(rng_np):
    seqs = [rng_np.normal(size=(n, 2)).astype(np.float32) for n in (3, 2)]
    sb = from_ragged(seqs)
    vec = rng_np.normal(size=(2, 4)).astype(np.float32)
    ex = S.expand(jnp.asarray(vec), sb)
    assert ex.data.shape == (2, sb.max_len, 4)
    np.testing.assert_allclose(np.asarray(ex.data[0, 2]), vec[0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(S.seq_first(sb)[1]), seqs[1][0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(S.seq_last(sb)[0]), seqs[0][-1], rtol=1e-6)


def test_context_projection(rng_np):
    seqs = [np.arange(12, dtype=np.float32).reshape(4, 3)]
    sb = from_ragged(seqs)
    out = S.context_projection(sb, context_len=3, context_start=-1)
    o = np.asarray(out.data[0])
    # position 0: [pad, x0, x1]
    np.testing.assert_allclose(o[0, :3], 0)
    np.testing.assert_allclose(o[0, 3:6], seqs[0][0], rtol=1e-6)
    np.testing.assert_allclose(o[0, 6:9], seqs[0][1], rtol=1e-6)
    # position 3 (last): [x2, x3, pad]
    np.testing.assert_allclose(o[3, 0:3], seqs[0][2], rtol=1e-6)
    np.testing.assert_allclose(o[3, 6:9], 0)


def test_lstm_masked_equivalence(rng_np):
    """Padded ragged batch must give the same result as per-sequence runs."""
    din, d = 3, 4
    w_x = rng_np.normal(size=(din, 4 * d)).astype(np.float32) * 0.3
    w_h = rng_np.normal(size=(d, 4 * d)).astype(np.float32) * 0.3
    b = np.zeros(4 * d, np.float32)
    seqs = [rng_np.normal(size=(n, din)).astype(np.float32) for n in (3, 6)]
    sb = from_ragged(seqs)
    out, last = R.lstm(sb, w_x, w_h, b)
    for i, s in enumerate(seqs):
        single = SequenceBatch(
            data=jnp.asarray(s[None]), length=jnp.asarray([len(s)])
        )
        o1, l1 = R.lstm(single, w_x, w_h, b)
        np.testing.assert_allclose(
            np.asarray(out.data[i, : len(s)]), np.asarray(o1.data[0, : len(s)]),
            rtol=1e-4, atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(last.h[i]), np.asarray(l1.h[0]), rtol=1e-4, atol=1e-5
        )


def test_gru_shapes(rng_np):
    din, d = 3, 5
    sb = from_ragged([rng_np.normal(size=(4, din)).astype(np.float32)])
    out, last = R.gru(
        sb,
        rng_np.normal(size=(din, 3 * d)).astype(np.float32),
        rng_np.normal(size=(d, 2 * d)).astype(np.float32),
        rng_np.normal(size=(d, d)).astype(np.float32),
        np.zeros(3 * d, np.float32),
    )
    assert out.data.shape == (1, sb.max_len, d)
    assert last.shape == (1, d)


def test_cos_sim(rng_np):
    a = rng_np.normal(size=(4, 8)).astype(np.float32)
    b = rng_np.normal(size=(4, 8)).astype(np.float32)
    got = np.asarray(M.cos_sim(a, b))
    ref = np.sum(a * b, -1) / (np.linalg.norm(a, axis=-1) * np.linalg.norm(b, axis=-1))
    np.testing.assert_allclose(got, ref, rtol=1e-4)


def test_cross_map_normal(rng_np):
    x = rng_np.normal(size=(2, 3, 3, 8)).astype(np.float32)
    y = np.asarray(N.cross_map_normal(x, size=5, scale=1e-4, pow_=0.75))
    # reference formula at channel c
    c = 4
    window = (x[..., 2:7] ** 2).sum(-1)
    ref = x[..., c] / (1 + 1e-4 * window) ** 0.75
    np.testing.assert_allclose(y[..., c], ref, rtol=1e-4)
