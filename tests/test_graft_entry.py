"""The driver's multi-chip dry-run must always work on the virtual CPU mesh
(conftest forces 8 devices)."""

import importlib.util
import pathlib

import pytest


def _load():
    p = pathlib.Path(__file__).parent.parent / "__graft_entry__.py"
    spec = importlib.util.spec_from_file_location("graft_entry", p)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


@pytest.mark.slow  # ~51s compile grid; the 2-device variant below keeps
# every dry-run phase (dp/sp/tp, MoE ep, pipeline, v2, scaling) in tier-1
def test_dryrun_multichip_8():
    _load().dryrun_multichip(8)


def test_dryrun_multichip_2():
    _load().dryrun_multichip(2)
