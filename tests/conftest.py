"""Test config: force an 8-device virtual CPU platform BEFORE jax import so
multi-device mesh tests run anywhere (SURVEY §4: the reference tests
distribution by spawning in-process pservers; we test it with a simulated
mesh — ``XLA_FLAGS=--xla_force_host_platform_device_count``)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# the axon sitecustomize force-registers the TPU platform regardless of env;
# jax.config wins over it
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_naming():
    """Reset auto layer names per test so topologies are reproducible."""
    from paddle_tpu.core import rng
    from paddle_tpu.layers import base

    base.reset_name_counters()
    rng.seed(7)
    yield


@pytest.fixture
def rng_np():
    return np.random.default_rng(0)
