"""graftlint (paddle_tpu/analysis) — the static-analysis suite.

Three layers of coverage:

1. the repo-wide gate: every codebase pass over the actual tree must
   come up clean modulo the checked-in baseline (this is the tier-1
   enforcement of the suite — a regression anywhere in the repo fails
   HERE with the finding id);
2. seeded-defect fixtures: for each pass, a tiny module/program with
   exactly one planted violation asserts the pass fires exactly once
   with its stable ID, plus a clean twin asserting no false positive;
3. the ``trainer --preflight`` CLI: clean configs exit 0; the
   ``preflight_inject`` flag's seeded host-sync and collective-mismatch
   defects exit 1 through the real CLI (including the ZeRO-2 dual-
   lowering comparison on the forced 8-device mesh).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- 1. the repo-wide gate ------------------------------------------------------


def test_repo_wide_suite_clean():
    from paddle_tpu.analysis import (
        apply_baseline,
        load_baseline,
        run_codebase,
    )

    findings = run_codebase()
    unsup, sup, stale = apply_baseline(findings, load_baseline())
    assert not unsup, "unsuppressed findings:\n" + "\n".join(
        f.render() for f in unsup)
    assert not stale, f"stale baseline suppressions: {stale}"
    # the baseline documents the canonical telemetry guards — if it
    # goes empty the suppression machinery itself is untested
    assert sup, "expected the baselined telemetry guards to match"


def test_analysis_cli_exits_zero():
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout


def test_lint_changed_mode_runs():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--changed"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr


def test_lock_registry_covers_threaded_subsystems():
    from paddle_tpu.analysis import lock_registry

    reg = lock_registry()
    assert reg["paddle_tpu/serving/engine.py"]["ServingEngine"] == ["_lock"]
    assert "_mesh_lock" in \
        reg["paddle_tpu/reader/prefetch.py"]["DevicePrefetcher"]
    assert reg["paddle_tpu/resilience/elastic.py"]["ElasticCoordinator"] \
        == ["_lock"]
    assert reg["paddle_tpu/trainer/checkpoint.py"]["AsyncCheckpointer"] \
        == ["_lock"]
    # the serving-fleet threads (PR 11) ride the same audit: the router
    # runs a pump thread, so its books live under declared locks; the
    # replica/health modules are registered (thread-free today — a
    # thread added later is audited the moment it appears)
    assert reg["paddle_tpu/serving/router.py"]["FleetRouter"] \
        == ["_lock", "_pump_lock"]
    from paddle_tpu.analysis.codebase import THREADED_MODULES

    assert "paddle_tpu/serving/fleet.py" in THREADED_MODULES
    assert "paddle_tpu/serving/health.py" in THREADED_MODULES


# -- 2. codebase-pass fixtures --------------------------------------------------


def _corpus(tmp_path, rel, src):
    from paddle_tpu.analysis.codebase import iter_corpus

    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    return iter_corpus(str(tmp_path), files=[rel])


def test_swallow_except_fires_once_with_stable_id(tmp_path):
    from paddle_tpu.analysis.codebase import pass_swallow_except

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        import logging
        log = logging.getLogger(__name__)

        def silent():
            try:
                risky()
            except Exception:
                pass            # the planted defect

        def logged():
            try:
                risky()
            except Exception as e:
                log.warning("failed: %s", e)

        def narrow():
            try:
                risky()
            except (OSError, ValueError):
                pass

        def propagated(q):
            try:
                risky()
            except Exception as e:
                q.put(e)
        """)
    found = pass_swallow_except(corpus, str(tmp_path))
    assert len(found) == 1, [f.fid for f in found]
    assert found[0].fid == "GL-EXCEPT:paddle_tpu/mod.py:silent"


def test_swallow_except_clean_fixture_negative(tmp_path):
    from paddle_tpu.analysis.codebase import pass_swallow_except

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        def f():
            try:
                risky()
            except Exception:
                raise RuntimeError("wrapped")
        """)
    assert pass_swallow_except(corpus, str(tmp_path)) == []


def test_env_pass_fires_on_unregistered_read(tmp_path):
    from paddle_tpu.analysis.codebase import pass_env_registration

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        import os
        A = os.environ.get("PADDLE_TPU_NOT_A_FLAG")     # planted
        B = os.environ.get("PADDLE_TPU_ZERO")           # flag override
        C = os.environ.get("JAX_PLATFORMS")             # declared env
        D = os.environ.get(dynamic_name)                # non-literal: skip
        """)
    found = pass_env_registration(corpus, str(tmp_path))
    assert [f.fid for f in found] == \
        ["GL-ENV:paddle_tpu/mod.py:<module>"]
    assert "PADDLE_TPU_NOT_A_FLAG" in found[0].message


def test_env_pass_clean_fixture_negative(tmp_path):
    from paddle_tpu.analysis.codebase import pass_env_registration

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        import os
        B = os.getenv("PADDLE_TPU_SEED")
        os.environ["PADDLE_TPU_WHATEVER"] = "writes are the launcher's"
        """)
    assert pass_env_registration(corpus, str(tmp_path)) == []


def test_schema_pass_fires_on_unknown_kind(tmp_path):
    from paddle_tpu.analysis.codebase import pass_schema_kinds

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        def a(reg):
            reg.emit({"x": 1}, kind="good")

        def b(reg):
            rec = {"kind": "planted_bad", "x": 1}
            reg.emit(dict(rec))

        LAYER_ATTR = {"kind": "embedding"}   # never emitted: not a record
        """)
    found = pass_schema_kinds(corpus, str(tmp_path),
                              known=frozenset({"good"}))
    assert len(found) == 1, [f.fid for f in found]
    assert found[0].fid == "GL-SCHEMA:paddle_tpu/mod.py:b"
    assert "planted_bad" in found[0].message


def test_schema_pass_reports_stale_registered_kind(tmp_path):
    from paddle_tpu.analysis.codebase import pass_schema_kinds

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        def a(reg):
            reg.emit({"x": 1}, kind="good")
        """)
    found = pass_schema_kinds(corpus, str(tmp_path),
                              known=frozenset({"good", "never_made"}))
    assert len(found) == 1
    assert "never_made" in found[0].message


_THREAD_FIXTURE = """\
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = None
            self._t = threading.Thread(target=self._work)

        def _work(self):
            {worker_body}

        def read(self):
            {consumer_body}
    """


def test_thread_pass_fires_on_unlocked_cross_thread_attr(tmp_path):
    from paddle_tpu.analysis.codebase import pass_thread_safety

    rel = "paddle_tpu/fix_thread.py"
    corpus = _corpus(tmp_path, rel, _THREAD_FIXTURE.format(
        worker_body="self._state = 1    # planted: no lock",
        consumer_body="return self._state"))
    found = pass_thread_safety(corpus, str(tmp_path), modules=(rel,))
    assert [f.fid for f in found] == \
        [f"GL-THREAD:{rel}:Worker._state"]


def test_thread_pass_clean_when_locked(tmp_path):
    from paddle_tpu.analysis.codebase import pass_thread_safety

    rel = "paddle_tpu/fix_thread.py"
    corpus = _corpus(tmp_path, rel, _THREAD_FIXTURE.format(
        worker_body="""
            with self._lock:
                self._state = 1""",
        consumer_body="""
            with self._lock:
                return self._state"""))
    assert pass_thread_safety(corpus, str(tmp_path), modules=(rel,)) == []


def test_lock_order_cycle_detected(tmp_path):
    from paddle_tpu.analysis.codebase import pass_lock_order

    rel = "paddle_tpu/fix_locks.py"
    corpus = _corpus(tmp_path, rel, """\
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._t = threading.Thread(target=self._work)

            def _work(self):
                with self._a:
                    with self._b:       # a -> b
                        pass

            def other(self):
                with self._b:
                    with self._a:       # b -> a: the planted cycle
                        pass
        """)
    found = pass_lock_order(corpus, str(tmp_path), modules=(rel,))
    assert [f.fid for f in found] == [f"GL-LOCKORDER:{rel}:TwoLocks"]
    assert "_a" in found[0].message and "_b" in found[0].message


def test_lock_order_clean_when_consistent(tmp_path):
    from paddle_tpu.analysis.codebase import pass_lock_order

    rel = "paddle_tpu/fix_locks.py"
    corpus = _corpus(tmp_path, rel, """\
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def other(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert pass_lock_order(corpus, str(tmp_path), modules=(rel,)) == []


def test_kernel_parity_pass_fires_without_reference_twin(tmp_path):
    from paddle_tpu.analysis.kernel_parity import kernel_parity_findings

    pallas = tmp_path / "paddle_tpu" / "ops" / "pallas"
    pallas.mkdir(parents=True)
    (tmp_path / "tests").mkdir()
    (pallas / "badkernel.py").write_text(textwrap.dedent("""\
        def fused_op(x):
            return pallas_call(x)   # planted: no jnp reference twin
        """))
    found = kernel_parity_findings(str(tmp_path))
    assert [f.fid for f in found] == \
        ["GL-KERNEL:paddle_tpu/ops/pallas/badkernel.py:<module>"]
    # add the twin + a parity test: the pass goes quiet
    (pallas / "badkernel.py").write_text(textwrap.dedent("""\
        def fused_op(x):
            return pallas_call(x)

        def fused_op_reference(x):
            return x
        """))
    (tmp_path / "tests" / "test_parity.py").write_text(
        "# fused_op vs fused_op_reference interpret-mode parity\n")
    assert kernel_parity_findings(str(tmp_path)) == []


def test_stable_ids_survive_line_drift(tmp_path):
    from paddle_tpu.analysis.codebase import pass_swallow_except

    body = """\
        def silent():
            try:
                risky()
            except Exception:
                pass
        """
    a = pass_swallow_except(_corpus(tmp_path, "paddle_tpu/mod.py", body),
                            str(tmp_path))
    shifted = "# one\n# two\n# three\n" + textwrap.dedent(body)
    b = pass_swallow_except(_corpus(tmp_path, "paddle_tpu/mod.py", shifted),
                            str(tmp_path))
    assert a[0].fid == b[0].fid
    assert a[0].line != b[0].line


# -- 2b. program-pass fixtures --------------------------------------------------


def test_host_sync_pass_fires_on_injected_callback():
    import jax

    from paddle_tpu.analysis import host_sync_pass

    def dirty(x):
        jax.debug.callback(lambda: None)
        return x * 2

    found = host_sync_pass(dirty, 1.0, name="p", sync_period=8)
    assert [f.fid for f in found] == ["GL-P-SYNC:<program:p>:debug_callback"]
    assert "sync_period=8" in found[0].message

    def clean(x):
        return x * 2

    assert host_sync_pass(clean, 1.0, name="p") == []


def test_recompile_pass_shape_and_dtype_churn():
    from paddle_tpu.analysis import recompile_hazard_pass

    base = (("x", (32, 64), "float32"), ("y", (32,), "int32"))

    def with_batch(n):
        return (("x", (n, 64), "float32"), ("y", (n,), "int32"))

    # full batch + one tail = the expected ceiling: clean
    assert recompile_hazard_pass([with_batch(32), with_batch(8)]) == []
    # three dims variants of one structure: shape churn
    churn = recompile_hazard_pass(
        [with_batch(32), with_batch(31), with_batch(30)])
    assert any(f.anchor == "shape-churn" for f in churn)
    # dtype flip
    flipped = (("x", (32, 64), "float64"), ("y", (32,), "int32"))
    dt = recompile_hazard_pass([base, flipped])
    assert any(f.anchor == "dtype-churn" for f in dt)
    # signature-count ceiling
    many = [with_batch(n) for n in range(20)]
    cnt = recompile_hazard_pass(many)
    assert any(f.anchor == "signature-count" for f in cnt)


def test_donation_pass_flags_undonated_update_buffer():
    import jax
    import numpy as np

    from paddle_tpu.analysis import donation_pass

    def update(p, g):
        return p - 0.1 * g, (g * g).sum()

    a = np.zeros((64, 64), np.float32)  # 16 KiB
    undonated = jax.jit(update).lower(a, a).as_text()
    found = donation_pass(undonated, name="p", min_bytes=1 << 10)
    # one update-shaped output: exactly one donation candidate flagged
    assert [f.fid for f in found] == ["GL-P-DONATE:<program:p>:arg0"]

    donated = jax.jit(update, donate_argnums=(0,)).lower(a, a).as_text()
    assert donation_pass(donated, name="p", min_bytes=1 << 10) == []


def test_collective_sequence_extraction_and_mismatch():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import compat
    from paddle_tpu.analysis import (
        collective_sequence_from_hlo_text,
        collective_sequence_from_jaxpr,
        compare_collective_lowerings,
    )

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))

    def body(x):
        s = jax.lax.psum_scatter(x, "data", scatter_dimension=0,
                                 tiled=True)
        return jax.lax.all_gather(s, "data", tiled=True)

    f = compat.shard_map(body, mesh=mesh, in_specs=P("data"),
                         out_specs=P("data"))
    seq = collective_sequence_from_jaxpr(f, jnp.ones((8,)))
    assert seq == ["reduce_scatter", "all_gather"]

    # the seeded defect: one lowering never reduces gradients
    bad = compare_collective_lowerings(
        ["reduce_scatter", "all_gather"], ["all_gather"], name="p")
    assert [f_.fid for f_ in bad] == ["GL-P-COLL:<program:p>:kind-set"]
    # class-equivalent lowerings are clean (combiner/decomposition)
    assert compare_collective_lowerings(
        ["reduce_scatter", "all_gather"],
        ["all_reduce", "all_gather"], name="p") == []
    # same-family order check
    order = compare_collective_lowerings(
        ["reduce_scatter", "all_gather"],
        ["all_gather", "reduce_scatter"], name="p", check_order=True)
    assert [f_.anchor for f_ in order] == ["order"]

    # HLO-text extraction normalizes the all-reduce+slice decomposition
    hlo = textwrap.dedent("""\
        %all-reduce.3 = f32[64]{0} all-reduce(f32[64]{0} %p), to_apply=%sum
        %ds.4 = f32[8]{0} dynamic-slice(f32[64]{0} %all-reduce.3, s32[] %i)
        %ag.5 = f32[64]{0} all-gather(f32[8]{0} %ds.4), dimensions={0}
        %use.6 = f32[64]{0} add(f32[64]{0} %ag.5, f32[64]{0} %all-reduce.3)
        """)
    assert collective_sequence_from_hlo_text(hlo) == \
        ["all_reduce", "reduce_scatter", "all_gather"]


def test_f32_upcast_pass_flags_pre_matmul_upcast():
    import jax.numpy as jnp

    from paddle_tpu.analysis import f32_upcast_pass

    x = jnp.ones((8, 16), jnp.bfloat16)
    w = jnp.ones((16, 4), jnp.bfloat16)

    def dirty(x, w):
        return (x.astype(jnp.float32) @ w.astype(jnp.float32)).sum()

    found = f32_upcast_pass(dirty, x, w, name="p")
    assert found and all(f.rule == "GL-P-UPCAST" for f in found)
    assert found[0].anchor == "dot_general"

    def clean(x, w):
        return (x @ w).astype(jnp.float32).sum()  # sanctioned: post-dot

    assert f32_upcast_pass(clean, x, w, name="p") == []


# -- 3. trainer --preflight through the real CLI --------------------------------


def _write_preflight_config(tmp_path):
    cfg = tmp_path / "digits.conf"
    cfg.write_text(textwrap.dedent("""\
        from paddle.trainer_config_helpers import *

        define_py_data_sources2(
            train_list='{d}/train.list', test_list=None,
            module='digits_provider', obj='process')
        settings(batch_size=16, learning_rate=1e-2)

        img = data_layer(name='pixel', size=64)
        hidden = fc_layer(input=img, size=32, act=ReluActivation())
        predict = fc_layer(input=hidden, size=4, act=SoftmaxActivation())
        lbl = data_layer(name='label', size=4)
        outputs(classification_cost(input=predict, label=lbl))
        """).format(d=tmp_path))
    (tmp_path / "digits_provider.py").write_text(textwrap.dedent("""\
        import numpy as np
        from paddle.trainer.PyDataProvider2 import (
            provider, dense_vector, integer_value)

        @provider(input_types={'pixel': dense_vector(64),
                               'label': integer_value(4)})
        def process(settings, filename):
            rng = np.random.default_rng(0)
            for _ in range(64):
                yield (rng.normal(size=(64,)).astype(np.float32),
                       int(rng.integers(0, 4)))
        """))
    (tmp_path / "train.list").write_text("seed-0\n")
    return str(cfg)


def _run_preflight(cfg, *extra, inject="", devices=0, jsonl=None):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_PREFLIGHT_INJECT", None)
    env["JAX_PLATFORMS"] = "cpu"
    if inject:
        env["PADDLE_TPU_PREFLIGHT_INJECT"] = inject
    if devices:
        flag = f"--xla_force_host_platform_device_count={devices}"
        prev = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in prev:
            env["XLA_FLAGS"] = (prev + " " + flag).strip()
    cmd = [sys.executable, "-m", "paddle_tpu.trainer",
           "--config", cfg, "--preflight", *extra]
    if jsonl:
        cmd += ["--metrics_jsonl", jsonl]
    return subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                          env=env, timeout=600)


def test_preflight_cli_clean_config_exits_zero(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    jsonl = str(tmp_path / "metrics.jsonl")
    out = _run_preflight(cfg, jsonl=jsonl)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "preflight: OK" in out.stdout
    # the schema/7 preflight record reached the sink
    recs = [json.loads(line) for line in open(jsonl)]
    pf = [r for r in recs if r.get("kind") == "preflight"]
    assert pf and pf[0]["clean"] is True
    assert pf[0]["schema"] == "paddle_tpu.metrics/15"
    # the schema/9 GL-P-MEM memory report rode along
    mem = pf[0]["memory"]
    assert mem["params_bytes"] > 0 and mem["opt_state_bytes"] > 0
    assert mem["total_bytes"] >= mem["params_bytes"] + mem["opt_state_bytes"]
    assert mem["activation_source"] in ("jaxpr-liveness",
                                        "xla-memory-analysis")
    # the schema/13 GL-P-COST roofline rode along: predicted step_ms /
    # MFU / named bottleneck, with the matmul class carrying the FLOPs
    cost = pf[0]["cost"]
    assert cost["step_ms"] > 0 and 0 < cost["mfu_pct"] <= 100
    assert cost["bottleneck"]
    assert cost["by_class"]["matmul"]["flops"] > 0
    assert cost["flops_source"] in ("jaxpr-walk", "xla-cost-analysis")
    assert "predicted step" in out.stdout
    # and metrics_to_md renders it, budget + static-cost tables included
    md = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_to_md.py"),
         jsonl], capture_output=True, text=True)
    assert md.returncode == 0
    assert "Preflight (static analysis)" in md.stdout
    assert "Memory budget (GL-P-MEM" in md.stdout
    assert "Static cost (GL-P-COST" in md.stdout


def test_preflight_cli_catches_injected_host_sync(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    out = _run_preflight(cfg, inject="host_sync")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "GL-P-SYNC" in out.stdout


def test_preflight_cli_zero2_dual_lowering_clean(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    out = _run_preflight(cfg, "--zero", "2", devices=8)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "zero=2, data=8" in out.stdout


def test_preflight_cli_catches_injected_collective_mismatch(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    out = _run_preflight(cfg, "--zero", "2", devices=8,
                         inject="collective_mismatch")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "GL-P-COLL" in out.stdout


def test_preflight_record_emission_in_process():
    from paddle_tpu.analysis.core import Finding
    from paddle_tpu.analysis.preflight import emit_preflight_record
    from paddle_tpu.telemetry import MemorySink, MetricsRegistry

    reg = MetricsRegistry("t")
    sink = MemorySink()
    reg.add_sink(sink)
    f = Finding("GL-P-SYNC", "<program:p>", 0, "debug_callback", "m")
    rec = emit_preflight_record([f], [], registry=reg, config="c.conf")
    assert rec["kind"] == "preflight" and rec["clean"] is False
    assert rec["by_rule"] == {"GL-P-SYNC": 1}
    assert sink.records[-1]["ids"] == [f.fid]
    assert reg.get("preflight_findings").value(rule="GL-P-SYNC") == 1.0


# -- 4. graftlint v2: memory / sharding / divergence / rng ----------------------


def test_activation_liveness_walk_counts_intermediates():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis import activation_peak_bytes

    def f(x, w):
        h = x @ w              # 32x128 f32 intermediate
        h2 = jnp.tanh(h)       # second one, while h is still live
        return (h2 * h).sum()

    x, w = jnp.ones((32, 64)), jnp.ones((64, 128))
    peak = activation_peak_bytes(jax.jit(f), x, w)
    # h and h2 (16 KiB each) overlap; the product makes a third
    assert peak >= 2 * 32 * 128 * 4
    assert peak < 1 << 20


def test_memory_budget_hbm_fires_once_with_stable_id():
    from paddle_tpu.analysis import memory_budget_pass

    report = {"zero": 0, "dp": 1, "params_bytes": 3 << 20,
              "opt_state_bytes": 6 << 20, "states_bytes": 0,
              "feed_bytes": 1 << 20, "activation_bytes": 2 << 20,
              "total_bytes": 12 << 20, "pallas_vmem": []}
    found = memory_budget_pass(report, name="p", hbm_gb=0.001)
    assert [f.fid for f in found] == ["GL-P-MEM:<program:p>:hbm-budget"]
    assert "0.013 GB" in found[0].message  # 12 MiB total named
    # generous budget and report-only mode are both clean
    assert memory_budget_pass(report, name="p", hbm_gb=16.0) == []
    assert memory_budget_pass(report, name="p", hbm_gb=0.0) == []


def test_pallas_vmem_fixture_fires_once_with_stable_id():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from paddle_tpu.analysis import (
        memory_budget_pass,
        pallas_vmem_estimates,
    )

    def kern(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2

    def big(x):  # 64 MiB in + 64 MiB out of VMEM-resident blocks
        return pl.pallas_call(kern, out_shape=jax.ShapeDtypeStruct(
            (4096, 4096), jnp.float32), interpret=True)(x)

    est = pallas_vmem_estimates(
        jax.make_jaxpr(big)(jnp.ones((4096, 4096), jnp.float32)))
    assert len(est) == 1 and est[0][1] == 2 * 4096 * 4096 * 4
    report = {"total_bytes": 0, "zero": 0, "dp": 1,
              "pallas_vmem": [{"kernel": k, "bytes": b} for k, b in est]}
    found = memory_budget_pass(report, name="p", vmem_mb=64.0)
    assert len(found) == 1 and found[0].rule == "GL-P-MEM"
    assert found[0].anchor.startswith("vmem:")
    # the same kernel on small blocks is clean
    assert memory_budget_pass(report, name="p", vmem_mb=256.0) == []


def test_fused_input_lstm_fits_default_vmem_budget():
    """GL-P-MEM follow-through for the persistent-recurrence kernels:
    the fused-input LSTM at the bench shapes (embed 128 -> h512, bs 64,
    T 100, bf16) must fit the default --vmem_mb 128 budget, and an
    oversized config (h4096 f32: the resident W_h alone is 256 MB) must
    fail the PREFLIGHT budget pass — not Mosaic compilation."""
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu.analysis import memory_budget_pass, pallas_vmem_estimates
    from paddle_tpu.ops.pallas.lstm import lstm_seq_fi

    def estimates(b, t, e, d, dt):
        args = (np.zeros((b, t, e), dt), np.zeros((b, t), np.float32),
                np.zeros((e, 4 * d), dt), np.zeros((4 * d,), np.float32),
                np.zeros((d, 4 * d), dt), np.zeros((3, d), dt),
                np.zeros((b, d), dt), np.zeros((b, d), np.float32))
        est = pallas_vmem_estimates(
            lambda *a: lstm_seq_fi(*a, False, True, True), *args)
        assert est, "no pallas_call found in the fused-input LSTM trace"
        return {"total_bytes": 0, "zero": 0, "dp": 1,
                "pallas_vmem": [{"kernel": k, "bytes": v} for k, v in est]}

    bench = estimates(64, 100, 128, 512, jnp.bfloat16)
    assert memory_budget_pass(bench, name="lstm_fi", vmem_mb=128.0) == []

    big = estimates(64, 100, 128, 4096, jnp.float32)
    found = memory_budget_pass(big, name="lstm_fi", vmem_mb=128.0)
    assert len(found) == 1 and found[0].rule == "GL-P-MEM"
    assert found[0].anchor == "vmem:_fwd_fi_kernel"


def test_opt_state_bytes_agree_with_zero_census():
    """Static GL-P-MEM param+opt accounting vs the runtime census on a
    forced-8-device mesh: at every zero mode the static slot bytes must
    equal the placed addressable shard bytes (the scalar `step` slot is
    the only delta — the census counts slots only)."""
    script = textwrap.dedent("""\
        import jax, numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.layers import api as layer, base, data_type
        from paddle_tpu.layers import activation as act
        from paddle_tpu.config.topology import Topology
        from paddle_tpu.optimizer import Adam
        from paddle_tpu.parallel import zero as Z
        from paddle_tpu.parallel.mesh import get_mesh
        from paddle_tpu.analysis import opt_state_bytes_per_device
        from paddle_tpu.analysis.memory import tree_bytes

        base.reset_name_counters()
        x = layer.data(name='x', type=data_type.dense_vector(64))
        h = layer.fc(input=x, size=128, act=act.ReluActivation())
        p = layer.fc(input=h, size=8, act=act.SoftmaxActivation())
        y = layer.data(name='y', type=data_type.integer_value(8))
        topo = Topology(layer.classification_cost(input=p, label=y))
        specs = {s.name: s for s in topo.param_specs()}
        params = paddle.parameters.create(topo).as_dict()
        opt = Adam(learning_rate=1e-2)
        opt_state = opt.init(params, specs)
        mesh = get_mesh().mesh
        step_bytes = tree_bytes({"step": opt_state["step"]})
        for zero in (0, 1, 2):
            static = opt_state_bytes_per_device(opt_state, params, mesh,
                                                zero)
            if zero == 0:
                measured = sum(
                    leaf.size * leaf.dtype.itemsize for leaf in
                    jax.tree.leaves(opt_state["slots"]))
            else:
                placed = Z.shard_opt_state(opt_state, params, mesh)
                measured = Z.state_bytes_per_device(placed)
            assert static - step_bytes == measured, (
                zero, static, measured, step_bytes)
        print("CENSUS_AGREE")
        """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(
        kept + ["--xla_force_host_platform_device_count=8"])
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, cwd=REPO,
                         timeout=300)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CENSUS_AGREE" in out.stdout


def test_sharding_flow_replicated_intermediate_fixture():
    from paddle_tpu.analysis import sharding_flow_pass

    big = "1024x4096xf32"  # 16 MiB
    stablehlo = textwrap.dedent("""\
        func.func public @main(%arg0: tensor<{big}> {{tf.aliasing_output = 0 : i32}}, %arg1: tensor<8x{big}>) -> (tensor<{big}>) {{
          %0 = stablehlo.custom_call @Sharding(%arg1) {{backend_config = "", mhlo.sharding = "{{replicated}}"}} : (tensor<8x{big}>) -> tensor<8x{big}>
          %1 = stablehlo.custom_call @Sharding(%arg0) {{backend_config = "", mhlo.sharding = "{{replicated}}"}} : (tensor<{big}>) -> tensor<{big}>
          return %1 : tensor<{big}>
        }}
        """).format(big=big)
    found = sharding_flow_pass(stablehlo, None, name="p")
    # the donated param pin (%arg0's type) is sanctioned pre-ZeRO-3;
    # the 8x-sized activation pin is the planted defect, firing once
    assert [f.fid for f in found] == \
        ["GL-P-SHARD:<program:p>:replicated:f32[8,1024,4096]"]
    # allowlisting the reviewed type silences it
    assert sharding_flow_pass(stablehlo, None, name="p",
                              allowlist=("f32[8,1024,4096]",)) == []
    # small intermediates never fire (byte-gated like GL-P-DONATE)
    assert sharding_flow_pass(stablehlo, None, name="p",
                              min_bytes=1 << 30) == []


def test_sharding_flow_implicit_reshard_fixture():
    from paddle_tpu.analysis import sharding_flow_pass

    stablehlo = textwrap.dedent("""\
        func.func public @main(%arg0: tensor<1024x4096xf32> {tf.aliasing_output = 0 : i32}, %arg1: tensor<32x4096xf32>) -> (tensor<1024x4096xf32>) {
          return %arg0 : tensor<1024x4096xf32>
        }
        """)
    compiled = textwrap.dedent("""\
        %ag.1 = f32[1024,4096]{1,0} all-gather(f32[128,4096]{1,0} %p0), dimensions={0}
        %ag.2 = f32[4096,4096]{1,0} all-gather(f32[4096,512]{1,0} %act), dimensions={1}
        %ag.3 = f32[8,8]{1,0} all-gather(f32[1,8]{1,0} %tiny), dimensions={0}
        """)
    found = sharding_flow_pass(stablehlo, compiled, name="p")
    # ag.1 rebuilds the donated param type (the ZeRO all-gather) and
    # ag.3 is below the byte gate; ag.2 is the planted implicit reshard
    assert [f.fid for f in found] == \
        ["GL-P-SHARD:<program:p>:reshard:f32[4096,4096]"]
    assert "67.1 MB" in found[0].message  # the payload is named
    # TPU HLO emits collectives as async start/done pairs with a TUPLE
    # result type — the start op must fire identically, the done op
    # (referencing the same result) must not double-count
    async_compiled = textwrap.dedent("""\
        %ags = (f32[4096,512]{1,0}, f32[4096,4096]{1,0}) all-gather-start(f32[4096,512]{1,0} %act), dimensions={1}
        %agd = f32[4096,4096]{1,0} all-gather-done(%ags)
        """)
    found = sharding_flow_pass(stablehlo, async_compiled, name="p")
    assert [f.fid for f in found] == \
        ["GL-P-SHARD:<program:p>:reshard:f32[4096,4096]"]


def test_rng_key_reuse_fixture_fires_once_with_stable_id(tmp_path):
    from paddle_tpu.analysis.rng import pass_rng_discipline

    rel = "paddle_tpu/fix_rng.py"
    corpus = _corpus(tmp_path, rel, """\
        import jax

        def reused(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))    # planted: same key
            return a + b

        def split_ok(key):
            k1, k2 = jax.random.split(key)
            return jax.random.normal(k1, (2,)) + \\
                jax.random.uniform(k2, (2,))

        def branch_ok(key, flag):
            if flag:
                return jax.random.normal(key, (2,))
            else:
                return jax.random.uniform(key, (2,))

        def refold_ok(key):
            a = jax.random.normal(key, (2,))
            key = jax.random.fold_in(key, 1)
            return a + jax.random.normal(key, (2,))
        """)
    found = pass_rng_discipline(corpus, str(tmp_path), modules=(rel,))
    assert [f.fid for f in found] == [f"GL-RNG:{rel}:reused"]
    assert "without an intervening split/fold_in" in found[0].message


def test_rng_literal_key_fixture(tmp_path):
    from paddle_tpu.analysis.rng import pass_rng_discipline

    rel = "paddle_tpu/fix_rng.py"
    corpus = _corpus(tmp_path, rel, """\
        import jax

        def literal_draw():
            return jax.random.normal(jax.random.PRNGKey(0), (2,))

        def literal_bound():
            k = jax.random.key(0)
            return jax.random.uniform(k, (2,))

        def seed_only():
            return jax.random.key(0)   # a seed, never drawn from: fine

        def threaded(key):
            return jax.random.normal(key, (2,))
        """)
    found = pass_rng_discipline(corpus, str(tmp_path), modules=(rel,))
    assert sorted(f.fid for f in found) == [
        f"GL-RNG:{rel}:literal_bound",
        f"GL-RNG:{rel}:literal_draw",
    ]


def test_rng_fold_pass_flags_unfolded_shard_map_draw():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import compat
    from paddle_tpu.analysis import rng_fold_pass

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))

    def nofold(x, key):
        return x * jax.random.uniform(key, x.shape)

    def folded(x, key):
        key = jax.random.fold_in(key, jax.lax.axis_index("data"))
        return x * jax.random.uniform(key, x.shape)

    x, key = jnp.ones((8, 4)), jax.random.key(0)
    bad = compat.shard_map(nofold, mesh=mesh, in_specs=(P("data"), P()),
                           out_specs=P("data"))
    good = compat.shard_map(folded, mesh=mesh, in_specs=(P("data"), P()),
                            out_specs=P("data"))
    found = rng_fold_pass(bad, x, key, name="p")
    assert [f.fid for f in found] == ["GL-RNG:<program:p>:shard-fold"]
    assert rng_fold_pass(good, x, key, name="p") == []


def test_rng_pass_clean_on_repo():
    from paddle_tpu.analysis.codebase import iter_corpus
    from paddle_tpu.analysis.rng import pass_rng_discipline

    found = pass_rng_discipline(iter_corpus(REPO), REPO)
    assert found == [], [f.fid for f in found]


def test_program_fingerprint_canonicalization():
    from paddle_tpu.analysis import program_fingerprint

    a = ("%1 = f32[8]{0} add(f32[8]{0} %p0, f32[8]{0} %p1), "
         "metadata={op_name=\"x\" source_line=3}\n"
         "%2 = f32[8]{0} all-gather(f32[8]{0} %1)")
    # SSA renumbering + metadata churn canonicalize away
    b = ("%41 = f32[8]{0} add(f32[8]{0} %arg0, f32[8]{0} %arg1), "
         "metadata={op_name=\"y\" source_line=99}\n"
         "%55 = f32[8]{0} all-gather(f32[8]{0} %41)")
    fa, fb = program_fingerprint(a), program_fingerprint(b)
    assert fa["hash"] == fb["hash"]
    assert fa["ops"] == ["add", "all-gather"]
    # a real op change does not
    c = a.replace("all-gather", "reduce-scatter")
    assert program_fingerprint(c)["hash"] != fa["hash"]


def test_divergence_pass_names_the_diff():
    from paddle_tpu.analysis import divergence_pass, program_fingerprint

    same = "%1 = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b)\n" \
           "%2 = f32[8]{0} all-gather(f32[8]{0} %1)"
    diff = same.replace("all-gather", "reduce-scatter")
    fps = {0: program_fingerprint(same, rank=0),
           1: program_fingerprint(same, rank=1),
           2: program_fingerprint(diff, rank=2)}
    found = divergence_pass(fps, name="p")
    assert [f.fid for f in found] == ["GL-P-DIVERGE:<program:p>:rank-2"]
    assert "op[1]: reduce-scatter vs all-gather" in found[0].message
    # agreement is clean
    assert divergence_pass({0: fps[0], 1: fps[1]}, name="p") == []


def test_exchange_fingerprints_roundtrip_and_timeout(tmp_path):
    from paddle_tpu.analysis import (
        exchange_fingerprints,
        program_fingerprint,
    )
    from paddle_tpu.analysis.diverge import publish_fingerprint

    d = str(tmp_path / "rdv")
    fp1 = program_fingerprint("%1 = f32[8]{0} add(f32[8]{0} %a)", rank=1)
    publish_fingerprint(fp1, d, 1)
    fp0 = program_fingerprint("%1 = f32[8]{0} add(f32[8]{0} %a)", rank=0)
    fps = exchange_fingerprints(fp0, d, 0, 2, timeout_s=10)
    assert set(fps) == {0, 1} and fps[1]["hash"] == fp0["hash"]
    # a missing rank times out naming who never published
    with pytest.raises(TimeoutError, match=r"rank\(s\) \[2\]"):
        exchange_fingerprints(fp0, d, 0, 3, timeout_s=0.3)


# -- 4b. graftlint v2 through the real CLI --------------------------------------


def test_preflight_cli_hbm_budget(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    # a deliberately over-budget device (10 KB of HBM) fails with the
    # GL-P-MEM finding; a real budget passes and is echoed
    out = _run_preflight(cfg, "--hbm_gb", "0.00001")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "GL-P-MEM" in out.stdout and "hbm-budget" in out.stdout
    out = _run_preflight(cfg, "--hbm_gb", "16")
    assert out.returncode == 0, out.stdout + out.stderr
    assert "16.0 GB budget" in out.stdout


def test_preflight_cli_zero2_with_budget_clean(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    out = _run_preflight(cfg, "--zero", "2", "--hbm_gb", "16", devices=8)
    assert out.returncode == 0, out.stdout + out.stderr


def test_preflight_cli_catches_injected_eval_host_sync(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    out = _run_preflight(cfg, inject="host_sync_eval")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "GL-P-SYNC:<program:eval_step>" in out.stdout


def _run_preflight_rank(cfg, rank, nproc, rdv, inject=""):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_PREFLIGHT_INJECT", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PADDLE_TPU_TRAINER_ID"] = str(rank)
    env["PADDLE_TPU_NPROC"] = str(nproc)
    env["PADDLE_TPU_PREFLIGHT_RENDEZVOUS"] = rdv
    if inject:
        env["PADDLE_TPU_PREFLIGHT_INJECT"] = inject
    return subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.trainer", "--config", cfg,
         "--preflight"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        cwd=REPO, env=env)


def test_preflight_cli_rank_divergence_aborts_with_named_diff(tmp_path):
    """The GL-P-DIVERGE acceptance: two ranks preflight the same config
    through the real CLI with the chaos hook perturbing rank 1's
    program — BOTH abort with the named diff instead of a fleet that
    would deadlock in its first collective; without the injection the
    exchange agrees and both pass."""
    cfg = _write_preflight_config(tmp_path)
    rdv = str(tmp_path / "rdv")
    procs = [_run_preflight_rank(cfg, r, 2, rdv, inject="rank_divergence")
             for r in range(2)]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 1, out
        assert "GL-P-DIVERGE" in out
        assert "chaos.divergence" in out  # the diff names the alien op
    # the clean twin: same fleet, no injection, agreement
    rdv2 = str(tmp_path / "rdv2")
    procs = [_run_preflight_rank(cfg, r, 2, rdv2) for r in range(2)]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out


# -- 4c. baseline staleness + machine-readable counts ---------------------------


def _baseline_with_bogus_entry(tmp_path):
    from paddle_tpu.analysis import load_baseline

    sup = load_baseline()
    sup["GL-EXCEPT:paddle_tpu/does_not_exist.py:gone"] = "stale on purpose"
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"suppressions": sup}))
    return str(path)


def test_analysis_json_reports_suppressed_and_stale_counts(tmp_path):
    bl = _baseline_with_bogus_entry(tmp_path)
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis", "--json",
         "--baseline", bl],
        capture_output=True, text=True, cwd=REPO)
    data = json.loads(out.stdout)
    assert out.returncode == 1          # stale entry fails the full run
    assert data["clean"] is False
    assert data["findings"] == []       # no real findings — only stale
    assert data["suppressed_count"] == len(data["suppressed"]) >= 3
    assert data["suppressed"][0]["fid"]  # full finding objects, not fids
    assert data["stale_count"] == 1
    assert data["stale_suppressions"] == \
        ["GL-EXCEPT:paddle_tpu/does_not_exist.py:gone"]


def test_lint_full_run_fails_on_stale_baseline_entry(tmp_path):
    bl = _baseline_with_bogus_entry(tmp_path)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--baseline", bl],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 1, out.stdout + out.stderr
    # the dead entry is named in the failure
    assert "GL-EXCEPT:paddle_tpu/does_not_exist.py:gone" in out.stdout
    assert "stale baseline" in out.stdout
    # --changed subset runs can't evaluate staleness: still green
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--changed", "--baseline", bl],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr


def test_divergence_pass_shape_only_drift_names_the_line():
    """Same op kinds, different dims (the classic batch-size config
    drift) must still name the divergent instruction — the op-kind diff
    comes up empty, so the canonical-line diff takes over."""
    from paddle_tpu.analysis import divergence_pass, program_fingerprint

    a = "%1 = f32[32,64]{1,0} add(f32[32,64]{1,0} %p0, f32[32,64]{1,0} %p1)"
    b = "%1 = f32[64,64]{1,0} add(f32[64,64]{1,0} %p0, f32[64,64]{1,0} %p1)"
    found = divergence_pass({0: program_fingerprint(a, rank=0),
                             1: program_fingerprint(b, rank=1)}, name="p")
    assert len(found) == 1
    assert "line[0]" in found[0].message
    assert "f32[64,64]" in found[0].message


# ---------------------------------------------------------------------------
# GL-P-COST: the static roofline cost model (analysis/cost.py)
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_hw_profile_table_and_auto(self):
        from paddle_tpu.analysis import HW_PROFILES, hw_profile

        assert {"v5p", "cpu-testbed"} <= set(HW_PROFILES)
        v5p = hw_profile("v5p")
        assert v5p.peak_flops > 1e14 and v5p.hbm_gb == 95.0
        # auto on the CPU testbed resolves to the calibrated profile
        assert hw_profile("auto").name == "cpu-testbed"

    def test_unknown_profile_is_clean_error_not_keyerror(self):
        from paddle_tpu.analysis import hw_profile

        with pytest.raises(ValueError) as ei:
            hw_profile("v9000")
        # names the table so the fix is obvious; never a raw KeyError
        assert "v9000" in str(ei.value)
        assert "v5p" in str(ei.value) and "cpu-testbed" in str(ei.value)

    def test_cost_report_charges_matmul_exactly(self):
        import jax.numpy as jnp

        from paddle_tpu.analysis import cost_report

        def f(x, w):
            return jnp.sum(x @ w)

        x = np.zeros((8, 32), np.float32)
        w = np.zeros((32, 16), np.float32)
        rep = cost_report(f, x, w, profile="v5p")
        # 2·M·N·K for the single dot
        assert rep["by_class"]["matmul"]["flops"] == 2 * 8 * 16 * 32
        assert rep["flops_source"] == "jaxpr-walk"
        assert rep["step_ms"] > 0 and 0 < rep["mfu_pct"] <= 100
        assert set(rep["by_class"]) == {"matmul", "conv", "elementwise",
                                        "reduce", "gather", "layout"}
        assert rep["bottleneck"]

    def test_collective_wire_model_and_zero_schedule(self):
        from paddle_tpu.analysis import zero_collective_bytes
        from paddle_tpu.analysis.cost import collective_wire_bytes

        # ring all-reduce: 2(n-1)/n of the payload crosses each link
        assert collective_wire_bytes("all_reduce", 8 * 10 ** 9, 8) == (
            pytest.approx(2 * 7 / 8 * 8e9))
        assert collective_wire_bytes("all_gather", 1e9, 4) == (
            pytest.approx(3 / 4 * 1e9))
        assert collective_wire_bytes("all_reduce", 1e9, 1) == 0.0
        # analytic ZeRO schedule when the trace has no collectives
        assert zero_collective_bytes(100, 1, 0) == []
        assert [c["kind"] for c in zero_collective_bytes(100, 8, 0)] == [
            "all_reduce"]
        assert [c["kind"] for c in zero_collective_bytes(100, 8, 1)] == [
            "reduce_scatter", "all_gather"]

    def test_dp_mesh_scales_work_and_can_bind_on_collectives(self):
        import jax.numpy as jnp

        from paddle_tpu.analysis import cost_report

        def f(x, w):
            return jnp.sum(x @ w)

        x = np.zeros((8, 32), np.float32)
        w = np.zeros((32, 16), np.float32)

        class Shim:  # plan_search's _MeshShim shape
            shape = {"data": 8}
            axis_names = ("data",)

        solo = cost_report(f, x, w, profile="v5p")
        # tiny compute + a fat analytic all-reduce: collective-bound
        dp = cost_report(f, x, w, profile="v5p", mesh=Shim(), zero=0,
                         params_bytes=10 ** 9)
        assert dp["dp"] == 8
        # GSPMD global-shape trace: per-device flops are 1/dp
        assert dp["flops"] == solo["flops"] // 8
        assert dp["comm_ms"] > 0 and dp["bottleneck"] == "collective-bound"
        assert dp["overlap_headroom_ms"] < 0

    def test_mfu_floor_finding_round_trips_analysis_json(self):
        """GL-P-COST findings survive the exact ``--json`` wire format
        (vars + fid) the analysis CLI emits — fid stable, fields intact."""
        import jax.numpy as jnp

        from paddle_tpu.analysis import Finding, cost_report
        from paddle_tpu.analysis.cost import cost_budget_pass

        def f(x):
            return jnp.sum(x * 2.0)  # elementwise-only: terrible MFU

        rep = cost_report(f, np.zeros((64,), np.float32), profile="v5p")
        found = cost_budget_pass(rep, name="train_step", mfu_floor=99.0)
        assert len(found) == 1
        f0 = found[0]
        assert f0.rule == "GL-P-COST" and f0.anchor == "mfu-floor"
        assert "bottleneck" in f0.message
        wire = json.loads(json.dumps(vars(f0) | {"fid": f0.fid}))
        back = Finding(**{k: v for k, v in wire.items() if k != "fid"})
        assert back.fid == wire["fid"] == f0.fid
        assert back == f0
        # floor 0 = report-only: no finding
        assert cost_budget_pass(rep, mfu_floor=0.0) == []
