"""graftlint (paddle_tpu/analysis) — the static-analysis suite.

Three layers of coverage:

1. the repo-wide gate: every codebase pass over the actual tree must
   come up clean modulo the checked-in baseline (this is the tier-1
   enforcement of the suite — a regression anywhere in the repo fails
   HERE with the finding id);
2. seeded-defect fixtures: for each pass, a tiny module/program with
   exactly one planted violation asserts the pass fires exactly once
   with its stable ID, plus a clean twin asserting no false positive;
3. the ``trainer --preflight`` CLI: clean configs exit 0; the
   ``preflight_inject`` flag's seeded host-sync and collective-mismatch
   defects exit 1 through the real CLI (including the ZeRO-2 dual-
   lowering comparison on the forced 8-device mesh).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- 1. the repo-wide gate ------------------------------------------------------


def test_repo_wide_suite_clean():
    from paddle_tpu.analysis import (
        apply_baseline,
        load_baseline,
        run_codebase,
    )

    findings = run_codebase()
    unsup, sup, stale = apply_baseline(findings, load_baseline())
    assert not unsup, "unsuppressed findings:\n" + "\n".join(
        f.render() for f in unsup)
    assert not stale, f"stale baseline suppressions: {stale}"
    # the baseline documents the canonical telemetry guards — if it
    # goes empty the suppression machinery itself is untested
    assert sup, "expected the baselined telemetry guards to match"


def test_analysis_cli_exits_zero():
    out = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.analysis"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "OK" in out.stdout


def test_lint_changed_mode_runs():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint.py"),
         "--changed"],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stdout + out.stderr


def test_lock_registry_covers_threaded_subsystems():
    from paddle_tpu.analysis import lock_registry

    reg = lock_registry()
    assert reg["paddle_tpu/serving/engine.py"]["ServingEngine"] == ["_lock"]
    assert "_mesh_lock" in \
        reg["paddle_tpu/reader/prefetch.py"]["DevicePrefetcher"]
    assert reg["paddle_tpu/resilience/elastic.py"]["ElasticCoordinator"] \
        == ["_lock"]
    assert reg["paddle_tpu/trainer/checkpoint.py"]["AsyncCheckpointer"] \
        == ["_lock"]
    # the serving-fleet threads (PR 11) ride the same audit: the router
    # runs a pump thread, so its books live under declared locks; the
    # replica/health modules are registered (thread-free today — a
    # thread added later is audited the moment it appears)
    assert reg["paddle_tpu/serving/router.py"]["FleetRouter"] \
        == ["_lock", "_pump_lock"]
    from paddle_tpu.analysis.codebase import THREADED_MODULES

    assert "paddle_tpu/serving/fleet.py" in THREADED_MODULES
    assert "paddle_tpu/serving/health.py" in THREADED_MODULES


# -- 2. codebase-pass fixtures --------------------------------------------------


def _corpus(tmp_path, rel, src):
    from paddle_tpu.analysis.codebase import iter_corpus

    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    return iter_corpus(str(tmp_path), files=[rel])


def test_swallow_except_fires_once_with_stable_id(tmp_path):
    from paddle_tpu.analysis.codebase import pass_swallow_except

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        import logging
        log = logging.getLogger(__name__)

        def silent():
            try:
                risky()
            except Exception:
                pass            # the planted defect

        def logged():
            try:
                risky()
            except Exception as e:
                log.warning("failed: %s", e)

        def narrow():
            try:
                risky()
            except (OSError, ValueError):
                pass

        def propagated(q):
            try:
                risky()
            except Exception as e:
                q.put(e)
        """)
    found = pass_swallow_except(corpus, str(tmp_path))
    assert len(found) == 1, [f.fid for f in found]
    assert found[0].fid == "GL-EXCEPT:paddle_tpu/mod.py:silent"


def test_swallow_except_clean_fixture_negative(tmp_path):
    from paddle_tpu.analysis.codebase import pass_swallow_except

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        def f():
            try:
                risky()
            except Exception:
                raise RuntimeError("wrapped")
        """)
    assert pass_swallow_except(corpus, str(tmp_path)) == []


def test_env_pass_fires_on_unregistered_read(tmp_path):
    from paddle_tpu.analysis.codebase import pass_env_registration

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        import os
        A = os.environ.get("PADDLE_TPU_NOT_A_FLAG")     # planted
        B = os.environ.get("PADDLE_TPU_ZERO")           # flag override
        C = os.environ.get("JAX_PLATFORMS")             # declared env
        D = os.environ.get(dynamic_name)                # non-literal: skip
        """)
    found = pass_env_registration(corpus, str(tmp_path))
    assert [f.fid for f in found] == \
        ["GL-ENV:paddle_tpu/mod.py:<module>"]
    assert "PADDLE_TPU_NOT_A_FLAG" in found[0].message


def test_env_pass_clean_fixture_negative(tmp_path):
    from paddle_tpu.analysis.codebase import pass_env_registration

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        import os
        B = os.getenv("PADDLE_TPU_SEED")
        os.environ["PADDLE_TPU_WHATEVER"] = "writes are the launcher's"
        """)
    assert pass_env_registration(corpus, str(tmp_path)) == []


def test_schema_pass_fires_on_unknown_kind(tmp_path):
    from paddle_tpu.analysis.codebase import pass_schema_kinds

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        def a(reg):
            reg.emit({"x": 1}, kind="good")

        def b(reg):
            rec = {"kind": "planted_bad", "x": 1}
            reg.emit(dict(rec))

        LAYER_ATTR = {"kind": "embedding"}   # never emitted: not a record
        """)
    found = pass_schema_kinds(corpus, str(tmp_path),
                              known=frozenset({"good"}))
    assert len(found) == 1, [f.fid for f in found]
    assert found[0].fid == "GL-SCHEMA:paddle_tpu/mod.py:b"
    assert "planted_bad" in found[0].message


def test_schema_pass_reports_stale_registered_kind(tmp_path):
    from paddle_tpu.analysis.codebase import pass_schema_kinds

    corpus = _corpus(tmp_path, "paddle_tpu/mod.py", """\
        def a(reg):
            reg.emit({"x": 1}, kind="good")
        """)
    found = pass_schema_kinds(corpus, str(tmp_path),
                              known=frozenset({"good", "never_made"}))
    assert len(found) == 1
    assert "never_made" in found[0].message


_THREAD_FIXTURE = """\
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._state = None
            self._t = threading.Thread(target=self._work)

        def _work(self):
            {worker_body}

        def read(self):
            {consumer_body}
    """


def test_thread_pass_fires_on_unlocked_cross_thread_attr(tmp_path):
    from paddle_tpu.analysis.codebase import pass_thread_safety

    rel = "paddle_tpu/fix_thread.py"
    corpus = _corpus(tmp_path, rel, _THREAD_FIXTURE.format(
        worker_body="self._state = 1    # planted: no lock",
        consumer_body="return self._state"))
    found = pass_thread_safety(corpus, str(tmp_path), modules=(rel,))
    assert [f.fid for f in found] == \
        [f"GL-THREAD:{rel}:Worker._state"]


def test_thread_pass_clean_when_locked(tmp_path):
    from paddle_tpu.analysis.codebase import pass_thread_safety

    rel = "paddle_tpu/fix_thread.py"
    corpus = _corpus(tmp_path, rel, _THREAD_FIXTURE.format(
        worker_body="""
            with self._lock:
                self._state = 1""",
        consumer_body="""
            with self._lock:
                return self._state"""))
    assert pass_thread_safety(corpus, str(tmp_path), modules=(rel,)) == []


def test_lock_order_cycle_detected(tmp_path):
    from paddle_tpu.analysis.codebase import pass_lock_order

    rel = "paddle_tpu/fix_locks.py"
    corpus = _corpus(tmp_path, rel, """\
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._t = threading.Thread(target=self._work)

            def _work(self):
                with self._a:
                    with self._b:       # a -> b
                        pass

            def other(self):
                with self._b:
                    with self._a:       # b -> a: the planted cycle
                        pass
        """)
    found = pass_lock_order(corpus, str(tmp_path), modules=(rel,))
    assert [f.fid for f in found] == [f"GL-LOCKORDER:{rel}:TwoLocks"]
    assert "_a" in found[0].message and "_b" in found[0].message


def test_lock_order_clean_when_consistent(tmp_path):
    from paddle_tpu.analysis.codebase import pass_lock_order

    rel = "paddle_tpu/fix_locks.py"
    corpus = _corpus(tmp_path, rel, """\
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def other(self):
                with self._a:
                    with self._b:
                        pass
        """)
    assert pass_lock_order(corpus, str(tmp_path), modules=(rel,)) == []


def test_kernel_parity_pass_fires_without_reference_twin(tmp_path):
    from paddle_tpu.analysis.kernel_parity import kernel_parity_findings

    pallas = tmp_path / "paddle_tpu" / "ops" / "pallas"
    pallas.mkdir(parents=True)
    (tmp_path / "tests").mkdir()
    (pallas / "badkernel.py").write_text(textwrap.dedent("""\
        def fused_op(x):
            return pallas_call(x)   # planted: no jnp reference twin
        """))
    found = kernel_parity_findings(str(tmp_path))
    assert [f.fid for f in found] == \
        ["GL-KERNEL:paddle_tpu/ops/pallas/badkernel.py:<module>"]
    # add the twin + a parity test: the pass goes quiet
    (pallas / "badkernel.py").write_text(textwrap.dedent("""\
        def fused_op(x):
            return pallas_call(x)

        def fused_op_reference(x):
            return x
        """))
    (tmp_path / "tests" / "test_parity.py").write_text(
        "# fused_op vs fused_op_reference interpret-mode parity\n")
    assert kernel_parity_findings(str(tmp_path)) == []


def test_stable_ids_survive_line_drift(tmp_path):
    from paddle_tpu.analysis.codebase import pass_swallow_except

    body = """\
        def silent():
            try:
                risky()
            except Exception:
                pass
        """
    a = pass_swallow_except(_corpus(tmp_path, "paddle_tpu/mod.py", body),
                            str(tmp_path))
    shifted = "# one\n# two\n# three\n" + textwrap.dedent(body)
    b = pass_swallow_except(_corpus(tmp_path, "paddle_tpu/mod.py", shifted),
                            str(tmp_path))
    assert a[0].fid == b[0].fid
    assert a[0].line != b[0].line


# -- 2b. program-pass fixtures --------------------------------------------------


def test_host_sync_pass_fires_on_injected_callback():
    import jax

    from paddle_tpu.analysis import host_sync_pass

    def dirty(x):
        jax.debug.callback(lambda: None)
        return x * 2

    found = host_sync_pass(dirty, 1.0, name="p", sync_period=8)
    assert [f.fid for f in found] == ["GL-P-SYNC:<program:p>:debug_callback"]
    assert "sync_period=8" in found[0].message

    def clean(x):
        return x * 2

    assert host_sync_pass(clean, 1.0, name="p") == []


def test_recompile_pass_shape_and_dtype_churn():
    from paddle_tpu.analysis import recompile_hazard_pass

    base = (("x", (32, 64), "float32"), ("y", (32,), "int32"))

    def with_batch(n):
        return (("x", (n, 64), "float32"), ("y", (n,), "int32"))

    # full batch + one tail = the expected ceiling: clean
    assert recompile_hazard_pass([with_batch(32), with_batch(8)]) == []
    # three dims variants of one structure: shape churn
    churn = recompile_hazard_pass(
        [with_batch(32), with_batch(31), with_batch(30)])
    assert any(f.anchor == "shape-churn" for f in churn)
    # dtype flip
    flipped = (("x", (32, 64), "float64"), ("y", (32,), "int32"))
    dt = recompile_hazard_pass([base, flipped])
    assert any(f.anchor == "dtype-churn" for f in dt)
    # signature-count ceiling
    many = [with_batch(n) for n in range(20)]
    cnt = recompile_hazard_pass(many)
    assert any(f.anchor == "signature-count" for f in cnt)


def test_donation_pass_flags_undonated_update_buffer():
    import jax
    import numpy as np

    from paddle_tpu.analysis import donation_pass

    def update(p, g):
        return p - 0.1 * g, (g * g).sum()

    a = np.zeros((64, 64), np.float32)  # 16 KiB
    undonated = jax.jit(update).lower(a, a).as_text()
    found = donation_pass(undonated, name="p", min_bytes=1 << 10)
    # one update-shaped output: exactly one donation candidate flagged
    assert [f.fid for f in found] == ["GL-P-DONATE:<program:p>:arg0"]

    donated = jax.jit(update, donate_argnums=(0,)).lower(a, a).as_text()
    assert donation_pass(donated, name="p", min_bytes=1 << 10) == []


def test_collective_sequence_extraction_and_mismatch():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from paddle_tpu import compat
    from paddle_tpu.analysis import (
        collective_sequence_from_hlo_text,
        collective_sequence_from_jaxpr,
        compare_collective_lowerings,
    )

    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))

    def body(x):
        s = jax.lax.psum_scatter(x, "data", scatter_dimension=0,
                                 tiled=True)
        return jax.lax.all_gather(s, "data", tiled=True)

    f = compat.shard_map(body, mesh=mesh, in_specs=P("data"),
                         out_specs=P("data"))
    seq = collective_sequence_from_jaxpr(f, jnp.ones((8,)))
    assert seq == ["reduce_scatter", "all_gather"]

    # the seeded defect: one lowering never reduces gradients
    bad = compare_collective_lowerings(
        ["reduce_scatter", "all_gather"], ["all_gather"], name="p")
    assert [f_.fid for f_ in bad] == ["GL-P-COLL:<program:p>:kind-set"]
    # class-equivalent lowerings are clean (combiner/decomposition)
    assert compare_collective_lowerings(
        ["reduce_scatter", "all_gather"],
        ["all_reduce", "all_gather"], name="p") == []
    # same-family order check
    order = compare_collective_lowerings(
        ["reduce_scatter", "all_gather"],
        ["all_gather", "reduce_scatter"], name="p", check_order=True)
    assert [f_.anchor for f_ in order] == ["order"]

    # HLO-text extraction normalizes the all-reduce+slice decomposition
    hlo = textwrap.dedent("""\
        %all-reduce.3 = f32[64]{0} all-reduce(f32[64]{0} %p), to_apply=%sum
        %ds.4 = f32[8]{0} dynamic-slice(f32[64]{0} %all-reduce.3, s32[] %i)
        %ag.5 = f32[64]{0} all-gather(f32[8]{0} %ds.4), dimensions={0}
        %use.6 = f32[64]{0} add(f32[64]{0} %ag.5, f32[64]{0} %all-reduce.3)
        """)
    assert collective_sequence_from_hlo_text(hlo) == \
        ["all_reduce", "reduce_scatter", "all_gather"]


def test_f32_upcast_pass_flags_pre_matmul_upcast():
    import jax.numpy as jnp

    from paddle_tpu.analysis import f32_upcast_pass

    x = jnp.ones((8, 16), jnp.bfloat16)
    w = jnp.ones((16, 4), jnp.bfloat16)

    def dirty(x, w):
        return (x.astype(jnp.float32) @ w.astype(jnp.float32)).sum()

    found = f32_upcast_pass(dirty, x, w, name="p")
    assert found and all(f.rule == "GL-P-UPCAST" for f in found)
    assert found[0].anchor == "dot_general"

    def clean(x, w):
        return (x @ w).astype(jnp.float32).sum()  # sanctioned: post-dot

    assert f32_upcast_pass(clean, x, w, name="p") == []


# -- 3. trainer --preflight through the real CLI --------------------------------


def _write_preflight_config(tmp_path):
    cfg = tmp_path / "digits.conf"
    cfg.write_text(textwrap.dedent("""\
        from paddle.trainer_config_helpers import *

        define_py_data_sources2(
            train_list='{d}/train.list', test_list=None,
            module='digits_provider', obj='process')
        settings(batch_size=16, learning_rate=1e-2)

        img = data_layer(name='pixel', size=64)
        hidden = fc_layer(input=img, size=32, act=ReluActivation())
        predict = fc_layer(input=hidden, size=4, act=SoftmaxActivation())
        lbl = data_layer(name='label', size=4)
        outputs(classification_cost(input=predict, label=lbl))
        """).format(d=tmp_path))
    (tmp_path / "digits_provider.py").write_text(textwrap.dedent("""\
        import numpy as np
        from paddle.trainer.PyDataProvider2 import (
            provider, dense_vector, integer_value)

        @provider(input_types={'pixel': dense_vector(64),
                               'label': integer_value(4)})
        def process(settings, filename):
            rng = np.random.default_rng(0)
            for _ in range(64):
                yield (rng.normal(size=(64,)).astype(np.float32),
                       int(rng.integers(0, 4)))
        """))
    (tmp_path / "train.list").write_text("seed-0\n")
    return str(cfg)


def _run_preflight(cfg, *extra, inject="", devices=0, jsonl=None):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_PREFLIGHT_INJECT", None)
    env["JAX_PLATFORMS"] = "cpu"
    if inject:
        env["PADDLE_TPU_PREFLIGHT_INJECT"] = inject
    if devices:
        flag = f"--xla_force_host_platform_device_count={devices}"
        prev = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in prev:
            env["XLA_FLAGS"] = (prev + " " + flag).strip()
    cmd = [sys.executable, "-m", "paddle_tpu.trainer",
           "--config", cfg, "--preflight", *extra]
    if jsonl:
        cmd += ["--metrics_jsonl", jsonl]
    return subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                          env=env, timeout=600)


def test_preflight_cli_clean_config_exits_zero(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    jsonl = str(tmp_path / "metrics.jsonl")
    out = _run_preflight(cfg, jsonl=jsonl)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "preflight: OK" in out.stdout
    # the schema/7 preflight record reached the sink
    recs = [json.loads(line) for line in open(jsonl)]
    pf = [r for r in recs if r.get("kind") == "preflight"]
    assert pf and pf[0]["clean"] is True
    assert pf[0]["schema"] == "paddle_tpu.metrics/8"
    # and metrics_to_md renders it
    md = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "metrics_to_md.py"),
         jsonl], capture_output=True, text=True)
    assert md.returncode == 0
    assert "Preflight (static analysis)" in md.stdout


def test_preflight_cli_catches_injected_host_sync(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    out = _run_preflight(cfg, inject="host_sync")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "GL-P-SYNC" in out.stdout


def test_preflight_cli_zero2_dual_lowering_clean(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    out = _run_preflight(cfg, "--zero", "2", devices=8)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "zero=2, data=8" in out.stdout


def test_preflight_cli_catches_injected_collective_mismatch(tmp_path):
    cfg = _write_preflight_config(tmp_path)
    out = _run_preflight(cfg, "--zero", "2", devices=8,
                         inject="collective_mismatch")
    assert out.returncode == 1, out.stdout + out.stderr
    assert "GL-P-COLL" in out.stdout


def test_preflight_record_emission_in_process():
    from paddle_tpu.analysis.core import Finding
    from paddle_tpu.analysis.preflight import emit_preflight_record
    from paddle_tpu.telemetry import MemorySink, MetricsRegistry

    reg = MetricsRegistry("t")
    sink = MemorySink()
    reg.add_sink(sink)
    f = Finding("GL-P-SYNC", "<program:p>", 0, "debug_callback", "m")
    rec = emit_preflight_record([f], [], registry=reg, config="c.conf")
    assert rec["kind"] == "preflight" and rec["clean"] is False
    assert rec["by_rule"] == {"GL-P-SYNC": 1}
    assert sink.records[-1]["ids"] == [f.fid]
    assert reg.get("preflight_findings").value(rule="GL-P-SYNC") == 1.0
