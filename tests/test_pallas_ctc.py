"""Fused CTC forward-backward + greedy decode kernels (ops/pallas/ctc.py)
vs the ``ops/ctc.py`` scan oracles — interpret mode, ragged lengths,
gradients, both input conventions (log-probs and in-kernel log-softmax)
— plus the NEG_INF-hardening regression tests for the scan itself
(degenerate inputs must yield the pinned sentinel loss and exactly-zero
gradients, not drifting junk)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import ctc as ctc_ops
from paddle_tpu.ops.ctc import NEG_INF
from paddle_tpu.ops.pallas.ctc import (
    ctc_greedy_decode_fused,
    ctc_greedy_decode_fused_reference,
    ctc_loss_fused,
    ctc_loss_fused_reference,
)


@pytest.fixture
def ragged_ctc(rng_np):
    B, T, V, L = 4, 9, 7, 3
    logits = jnp.asarray(rng_np.normal(size=(B, T, V)).astype(np.float32))
    ilen = jnp.asarray([9, 7, 5, 3], jnp.int32)
    labels = jnp.asarray(rng_np.integers(1, V, size=(B, L)), jnp.int32)
    llen = jnp.asarray([3, 2, 1, 0], jnp.int32)  # incl. zero-length row
    return logits, ilen, labels, llen


@pytest.mark.parametrize("normalize", [False, True])
def test_ctc_loss_fused_matches_reference_fwd_and_grad(ragged_ctc,
                                                       normalize):
    logits, ilen, labels, llen = ragged_ctc
    inp = logits if normalize else jax.nn.log_softmax(logits)
    weights = jnp.asarray([1.0, 2.0, 3.0, 4.0])

    def k_loss(x):
        return jnp.sum(weights * ctc_loss_fused(
            x, ilen, labels, llen, 0, normalize, impl="kernel",
            interpret=True))

    def r_loss(x):
        return jnp.sum(weights * ctc_loss_fused_reference(
            x, ilen, labels, llen, 0, normalize))

    lk = ctc_loss_fused(inp, ilen, labels, llen, 0, normalize,
                        impl="kernel", interpret=True)
    lr = ctc_loss_fused_reference(inp, ilen, labels, llen, 0, normalize)
    np.testing.assert_allclose(np.asarray(lk), np.asarray(lr),
                               rtol=1e-5, atol=1e-5)
    gk = jax.grad(k_loss)(inp)
    gr = jax.grad(r_loss)(inp)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)


def test_ctc_loss_fused_reference_is_the_scan(ragged_ctc):
    """The reference twin (the CPU production path under impl='auto')
    must be bit-identical to the unfused ops/ctc scan — the ablation's
    bit-identity anchor."""
    logits, ilen, labels, llen = ragged_ctc
    lp = jax.nn.log_softmax(logits)
    via_auto = ctc_loss_fused(lp, ilen, labels, llen, 0)  # CPU -> reference
    direct = ctc_ops.ctc_loss(lp, ilen, labels, llen, 0)
    assert np.array_equal(np.asarray(via_auto), np.asarray(direct))


def test_ctc_fused_kernel_infeasible_pins_loss_and_zeroes_grad(rng_np):
    """Truly infeasible alignment (3 repeated labels need >= 5 frames,
    only 4 given): the kernel's loss pins at the sentinel and its
    hand-derived gradient is exactly zero — matching the hardened scan."""
    V = 5
    labels = jnp.asarray([[2, 2, 2]], jnp.int32)
    llen = jnp.asarray([3], jnp.int32)
    lp = jax.nn.log_softmax(
        jnp.asarray(rng_np.normal(size=(1, 4, V)).astype(np.float32)))
    ilen = jnp.asarray([4], jnp.int32)

    lk = ctc_loss_fused(lp, ilen, labels, llen, 0, impl="kernel",
                        interpret=True)
    assert float(lk[0]) == float(np.float32(-NEG_INF))
    gk = jax.grad(lambda x: jnp.sum(ctc_loss_fused(
        x, ilen, labels, llen, 0, impl="kernel", interpret=True)))(lp)
    assert np.array_equal(np.asarray(gk), np.zeros_like(np.asarray(gk)))


def test_ctc_scan_degenerate_inputs_regression(rng_np):
    """ops/ctc.py hardening: (a) a zero-length label row's loss is the
    pure-blank path probability; (b) an infeasible row (T too short for
    the repeat structure) reports the finite sentinel with EXACTLY zero
    gradient (jnp.maximum ties used to leak junk cotangents); (c) all
    values and grads stay finite."""
    V = 5
    # (a) zero-length labels: loss = -sum of blank log-probs over length
    lp = jax.nn.log_softmax(
        jnp.asarray(rng_np.normal(size=(1, 6, V)).astype(np.float32)))
    ilen = jnp.asarray([4], jnp.int32)
    loss0 = ctc_ops.ctc_loss(lp, ilen, jnp.zeros((1, 3), jnp.int32),
                             jnp.asarray([0], jnp.int32), 0)
    want = -float(jnp.sum(lp[0, :4, 0]))
    assert abs(float(loss0[0]) - want) < 1e-5

    # (b) infeasible: 3 repeated labels in 4 frames
    labels = jnp.asarray([[2, 2, 2]], jnp.int32)
    llen = jnp.asarray([3], jnp.int32)
    lp4 = jax.nn.log_softmax(
        jnp.asarray(rng_np.normal(size=(1, 4, V)).astype(np.float32)))
    loss = ctc_ops.ctc_loss(lp4, ilen, labels, llen, 0)
    assert float(loss[0]) == float(np.float32(-NEG_INF))  # pinned, finite
    g = jax.grad(lambda x: jnp.sum(ctc_ops.ctc_loss(
        x, ilen, labels, llen, 0)))(lp4)
    assert np.array_equal(np.asarray(g), np.zeros_like(np.asarray(g)))

    # (c) T < 2L+1 but feasible (distinct labels skip blanks): finite
    # loss, finite grads, kernel agrees
    labels2 = jnp.asarray([[1, 2, 3]], jnp.int32)
    lp5 = jax.nn.log_softmax(
        jnp.asarray(rng_np.normal(size=(1, 4, V)).astype(np.float32)))
    l_scan = ctc_ops.ctc_loss(lp5, ilen, labels2, llen, 0)
    l_kern = ctc_loss_fused(lp5, ilen, labels2, llen, 0, impl="kernel",
                            interpret=True)
    assert np.isfinite(float(l_scan[0])) and float(l_scan[0]) < 1e29
    np.testing.assert_allclose(np.asarray(l_kern), np.asarray(l_scan),
                               rtol=1e-5, atol=1e-5)
    g2 = jax.grad(lambda x: jnp.sum(ctc_ops.ctc_loss(
        x, ilen, labels2, llen, 0)))(lp5)
    assert np.all(np.isfinite(np.asarray(g2)))


def test_ctc_greedy_decode_fused_matches_reference(rng_np):
    B, T, V = 5, 11, 6
    lp = jax.nn.log_softmax(
        jnp.asarray(rng_np.normal(size=(B, T, V)).astype(np.float32) * 2))
    ilen = jnp.asarray([11, 9, 6, 3, 1], jnp.int32)
    for blank in (0, V - 1):
        idk, lnk = ctc_greedy_decode_fused(lp, ilen, blank, impl="kernel",
                                           interpret=True)
        idr, lnr = ctc_greedy_decode_fused_reference(lp, ilen, blank)
        assert np.array_equal(np.asarray(idk), np.asarray(idr))
        assert np.array_equal(np.asarray(lnk), np.asarray(lnr))
    # and the reference twin IS the production scan decode
    ids_a, len_a = ctc_greedy_decode_fused(lp, ilen, 0)  # CPU -> reference
    ids_s, len_s = ctc_ops.ctc_greedy_decode(lp, ilen, 0)
    assert np.array_equal(np.asarray(ids_a), np.asarray(ids_s))
    assert np.array_equal(np.asarray(len_a), np.asarray(len_s))


def test_ctc_fused_batch_blocking_covers_non_multiple_batches(rng_np):
    """The kernel grids over batch blocks (largest divisor <= 8): odd
    batch sizes must still produce per-row losses equal to the scan."""
    for B in (1, 3, 6, 16):
        T, V, L = 7, 5, 2
        lp = jax.nn.log_softmax(jnp.asarray(
            rng_np.normal(size=(B, T, V)).astype(np.float32)))
        ilen = jnp.asarray(rng_np.integers(3, T + 1, size=(B,)), jnp.int32)
        labels = jnp.asarray(rng_np.integers(1, V, size=(B, L)), jnp.int32)
        llen = jnp.asarray(rng_np.integers(0, L + 1, size=(B,)), jnp.int32)
        lk = ctc_loss_fused(lp, ilen, labels, llen, 0, impl="kernel",
                            interpret=True)
        lr = ctc_ops.ctc_loss(lp, ilen, labels, llen, 0)
        np.testing.assert_allclose(np.asarray(lk), np.asarray(lr),
                                   rtol=1e-5, atol=1e-5)
