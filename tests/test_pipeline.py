"""Pipeline parallel == sequential stage application (forward and grads)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from paddle_tpu.parallel.pipeline import pipeline_apply


def _setup(n_stages=4, dim=8):
    r = np.random.default_rng(0)
    w = jnp.asarray(r.normal(size=(n_stages, dim, dim)).astype(np.float32) * 0.3)
    b = jnp.asarray(r.normal(size=(n_stages, dim)).astype(np.float32) * 0.1)
    x = jnp.asarray(r.normal(size=(8, dim)).astype(np.float32))
    mesh = Mesh(np.asarray(jax.devices()[:n_stages]).reshape(n_stages), ("pipe",))

    def stage_fn(params, h):
        w, b = params
        return jnp.tanh(h @ w + b)

    def sequential(params, x):
        w, b = params
        h = x
        for i in range(n_stages):
            h = stage_fn((w[i], b[i]), h)
        return h

    return (w, b), x, mesh, stage_fn, sequential


def test_pipeline_forward_matches_sequential():
    params, x, mesh, stage_fn, sequential = _setup()
    ref = sequential(params, x)
    out = pipeline_apply(stage_fn, params, x, n_microbatches=4, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_grads_match_sequential():
    params, x, mesh, stage_fn, sequential = _setup()

    def loss_pipe(params):
        return jnp.sum(
            pipeline_apply(stage_fn, params, x, n_microbatches=4, mesh=mesh) ** 2
        )

    def loss_seq(params):
        return jnp.sum(sequential(params, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(params)
    g_seq = jax.grad(loss_seq)(params)
    for a, b in zip(g_pipe, g_seq):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_pipeline_jits():
    params, x, mesh, stage_fn, sequential = _setup()
    f = jax.jit(
        lambda p, x: pipeline_apply(stage_fn, p, x, n_microbatches=2, mesh=mesh)
    )
    out = f(params, x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(sequential(params, x)), atol=1e-5
    )
