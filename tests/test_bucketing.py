"""Sequence bucketing (reader/decorator.bucket_by_length + DataFeeder
seq_buckets + the padding_ratio telemetry): determinism, remainder
policy, the recompile cap, prefetch interaction, and the end-to-end
trainer wiring (bounded jit signatures + the schema/10 padding signal).
"""

from __future__ import annotations

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.reader.decorator import MAX_SEQ_BUCKETS, bucket_by_length
from paddle_tpu.reader.feeder import (DataFeeder, padding_stats,
                                      parse_seq_buckets)


def _skewed_samples(n=100, seed=0):
    g = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        t = (int(g.integers(3, 9)) if g.random() < 0.8
             else int(g.integers(40, 60)))
        out.append((g.integers(0, 50, size=t).tolist(),
                    int(g.integers(0, 2))))
    return out


def _stream(reader):
    return [[tuple((tuple(s[0]), s[1])) for s in b] for b in reader()]


def test_bucket_by_length_deterministic_given_seed():
    samples = _skewed_samples()
    mk = lambda seed: bucket_by_length(  # noqa: E731
        lambda: iter(samples), 8, buckets=(8, 64), seed=seed)
    a, b = _stream(mk(3)), _stream(mk(3))
    assert a == b  # identical batch stream, including leftover order
    # full-batch (in-stream) flushes are seed-independent; only the
    # leftover flush order may move
    c = _stream(mk(4))
    assert sorted(map(str, a)) == sorted(map(str, c))


def test_bucket_by_length_one_shape_per_bucket():
    samples = _skewed_samples(96)
    reader = bucket_by_length(lambda: iter(samples), 8, buckets=(8, 64))
    batches = list(reader())
    sizes = [len(b) for b in batches]
    assert all(s <= 8 for s in sizes)
    # at most one (leftover) tail batch per bucket; the rest are full
    assert sum(1 for s in sizes if s < 8) <= 2
    # every sample's bucket is respected: no short batch mixes with long
    for b in batches:
        lens = [len(s[0]) for s in b]
        assert max(lens) <= 8 or min(lens) > 8


def test_bucket_by_length_remainder_policies():
    # 10 samples of one length, batch 8: leftover pool of 2
    samples = [([1, 2, 3], 0)] * 10
    drop = bucket_by_length(lambda: iter(samples), 8, buckets=(8,),
                            remainder="drop", size_multiple=4)
    batches = list(drop())
    assert [len(b) for b in batches] == [8]  # 2-sample tail < multiple 4
    pad = bucket_by_length(lambda: iter(samples), 8, buckets=(8,),
                           remainder="pad")
    batches = list(pad())
    # pad repeats the last sample up to the FULL batch (one shape/bucket)
    assert [len(b) for b in batches] == [8, 8]
    assert batches[1][-1] == batches[1][1]


def test_bucket_by_length_caps_the_bucket_table():
    from paddle_tpu.core.enforce import EnforceError

    with pytest.raises(EnforceError):
        bucket_by_length(lambda: iter([]), 8,
                         buckets=tuple(range(1, MAX_SEQ_BUCKETS + 2)))


def test_parse_seq_buckets_forms():
    assert parse_seq_buckets(None) is None
    assert parse_seq_buckets("") is None
    assert parse_seq_buckets("32, 8,16") == (8, 16, 32)
    assert parse_seq_buckets([64, 16]) == (16, 64)


def test_feeder_pads_to_the_bucket_table():
    from paddle_tpu.layers.data_type import integer_value_sequence

    feeder = DataFeeder({"w": integer_value_sequence(100)},
                        seq_buckets=(8, 64))
    short = feeder.feed([([1, 2, 3],), ([4, 5, 6, 7],)])
    assert short["w"].data.shape == (2, 8)  # bucket 8, not default 16
    long = feeder.feed([(list(range(40)),), (list(range(9)),)])
    assert long["w"].data.shape == (2, 64)
    padded, total = padding_stats(long)
    assert total == 2 * 64 and padded == (64 - 40) + (64 - 9)


def test_prefetcher_carries_padding_stats():
    from paddle_tpu.layers.data_type import integer_value_sequence
    from paddle_tpu.reader.prefetch import DevicePrefetcher

    feeder = DataFeeder({"w": integer_value_sequence(100)},
                        seq_buckets=(8, 64))
    samples = _skewed_samples(32)
    reader = bucket_by_length(
        lambda: iter([(s[0],) for s in samples]), 8, buckets=(8, 64))
    with DevicePrefetcher(reader, feeder) as feeds:
        got = list(feeds)
    assert got, "prefetcher yielded nothing"
    for fb in got:
        assert fb.total_timesteps > 0
        assert 0 <= fb.padded_timesteps < fb.total_timesteps
        assert fb.feed["w"].data.shape[1] in (8, 64)


def _lstm_text_trainer(vocab=50, hidden=8):
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type

    base.reset_name_counters()
    data = layer.data(name="data",
                      type=data_type.integer_value_sequence(vocab))
    net = layer.embedding(input=data, size=8)
    net = layer.fc(input=net, size=hidden * 4, act=act.LinearActivation())
    net = layer.lstmemory(input=net)
    net = layer.last_seq(input=net)
    net = layer.fc(input=net, size=2, act=act.SoftmaxActivation())
    label = layer.data(name="label", type=data_type.integer_value(2))
    cost = layer.classification_cost(input=net, label=label)
    params = paddle.parameters.create(paddle.topology.Topology(cost))
    return paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.SGD(learning_rate=0.1))


def test_train_with_buckets_bounds_signatures_and_reports_padding():
    """End-to-end: a bucketed reader + matching feeder table keeps the
    compiled-signature set at (near) the bucket count — the
    GL-P-RECOMPILE bound bucketing promises — and every step record
    carries the schema/10 padding_ratio field."""
    from paddle_tpu import metrics as metrics_mod

    samples = _skewed_samples(64, seed=1)
    buckets = (8, 64)
    reader = bucket_by_length(lambda: iter(samples), 8, buckets=buckets,
                              remainder="pad")
    trainer = _lstm_text_trainer()
    sink = metrics_mod.MemorySink()
    reg = metrics_mod.MetricsRegistry("test_bucketing")
    reg.add_sink(sink)
    trainer.train(reader=reader, num_passes=2, metrics_registry=reg,
                  seq_buckets=buckets)
    # remainder="pad" keeps ONE static shape per bucket: the jit saw at
    # most len(buckets) train-step signatures over both passes
    assert len(trainer._compiled_sigs) <= len(buckets)
    steps = [r for r in sink.records if r.get("kind") == "step"]
    assert steps and all("padding_ratio" in r for r in steps)
    assert all(0.0 <= r["padding_ratio"] < 1.0 for r in steps)
    assert any(r["padding_ratio"] > 0 for r in steps)


def test_real_dataset_reader_buckets_by_default():
    """The dataset ``bucketed_batches`` helpers (wmt14/conll05/imdb)
    are the DEFAULT length-bucketed path: the reader carries its table
    (``reader.seq_buckets``), SGD.train's feeder picks it up without
    ``seq_buckets=...`` being repeated, and every step record still
    carries the schema/10 padding_ratio field."""
    import itertools

    from paddle_tpu import metrics as metrics_mod
    from paddle_tpu.dataset import imdb

    from paddle_tpu.parallel.mesh import get_mesh

    reader = imdb.bucketed_batches(
        lambda: itertools.islice(imdb.train()(), 32), 8,
        size_multiple=get_mesh().num_replicas)
    assert reader.seq_buckets == imdb.SEQ_BUCKETS
    trainer = _lstm_text_trainer(vocab=imdb.VOCAB_SIZE)
    sink = metrics_mod.MemorySink()
    reg = metrics_mod.MetricsRegistry("test_bucketing_imdb")
    reg.add_sink(sink)
    trainer.train(reader=reader, num_passes=1, metrics_registry=reg)
    steps = [r for r in sink.records if r.get("kind") == "step"]
    assert steps and all("padding_ratio" in r for r in steps)
    assert all(0.0 <= r["padding_ratio"] < 1.0 for r in steps)
    # the feeder padded to bucket ceilings, not one stream-max shape:
    # at most one signature per table entry
    assert len(trainer._compiled_sigs) <= len(imdb.SEQ_BUCKETS)


def test_metrics_to_md_flags_padding_bound_steps(tmp_path, capsys):
    import json
    import sys

    sys.path.insert(0, "tools")
    try:
        import metrics_to_md
    finally:
        sys.path.pop(0)
    recs = [
        {"kind": "step", "run": "train", "step": 0, "loss": 1.0,
         "step_ms": 5.0, "examples_per_sec": 10.0, "mfu_pct": 1.0,
         "padding_ratio": 0.62},
        {"kind": "step", "run": "train", "step": 1, "loss": 0.9,
         "step_ms": 5.0, "examples_per_sec": 10.0, "mfu_pct": 1.0,
         "padding_ratio": 0.05},
    ]
    path = tmp_path / "m.jsonl"
    path.write_text("\n".join(json.dumps(r) for r in recs) + "\n")
    metrics_to_md.main([str(path)])
    out = capsys.readouterr().out
    assert "pad %" in out
    assert "padding-bound" in out and "--seq_buckets" in out
    # only the 62% step is flagged
    assert out.count("⚠") >= 1
