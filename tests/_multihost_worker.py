"""Worker for the multi-process jax.distributed integration test.

Run as: python _multihost_worker.py <process_id> <num_processes> <port> <out>

Each process owns 4 virtual CPU devices; together they form one 8-device
dp mesh.  The model/data/step are identical to what the single-process
reference path in tests/test_multihost_process.py builds via
``build_model`` / ``run_steps`` below — the test asserts final-parameter
equality.  (≅ the reference's in-process cluster tests,
``paddle/trainer/tests/test_CompareSparse.cpp:65-73``, redone for the
multi-controller SPMD runtime.)
"""

from __future__ import annotations

import os
import pickle
import sys


def _setup_env(local_devices: int) -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "").split(
            " --xla_force_host_platform_device_count", 1)[0]
        + f" --xla_force_host_platform_device_count={local_devices}")
    os.environ.setdefault("JAX_ENABLE_X64", "0")


def build_model():
    """Tiny classifier (deterministic init) + its jitted dp train step."""
    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core import rng
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import base, data_type
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    rng.seed(7)
    x = layer.data(name="x", type=data_type.dense_vector(8))
    hidden = layer.fc(input=x, size=16, act=act.ReluActivation())
    predict = layer.fc(input=hidden, size=4, act=act.SoftmaxActivation())
    lbl = layer.data(name="label", type=data_type.integer_value(4))
    cost = layer.classification_cost(input=predict, label=lbl)
    topo = Topology(cost)
    params = paddle.parameters.create(topo).as_dict()
    opt = Momentum(momentum=0.9, learning_rate=0.05)
    specs = {s.name: s for s in topo.param_specs()}
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, opt)
    return params, opt_state, states, step


def global_feed(step_idx: int, batch: int = 16):
    """Deterministic global batch for step ``step_idx`` (same on all hosts)."""
    import numpy as np

    g = np.random.default_rng(1000 + step_idx)
    xs = g.normal(size=(batch, 8)).astype(np.float32)
    ys = g.integers(0, 4, size=(batch,)).astype(np.int32)
    return {"x": xs, "label": ys}


def run_steps(params, opt_state, states, step, place, n_steps: int = 4):
    """place(feed_np) -> on-device feed; returns final params as numpy."""
    import jax
    import numpy as np

    key = jax.random.key(0)
    for i in range(n_steps):
        feed = place(global_feed(i))
        params, opt_state, states, cost, _ = step(
            params, opt_state, states, feed, key)
    return {k: np.asarray(jax.device_get(v.addressable_data(0)))
            if hasattr(v, "addressable_data") else np.asarray(v)
            for k, v in params.items()}


def main() -> None:
    pid, nproc, port, out = (int(sys.argv[1]), int(sys.argv[2]),
                             sys.argv[3], sys.argv[4])
    _setup_env(local_devices=8 // nproc)
    import jax

    # the axon sitecustomize force-registers its TPU platform regardless of
    # env; jax.config wins over it (same trick as tests/conftest.py)
    jax.config.update("jax_platforms", "cpu")

    from paddle_tpu.distributed import multihost as mh

    mh.initialize(coordinator_address=f"127.0.0.1:{port}",
                  num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    mesh = mh.pod_mesh(data=None)
    params, opt_state, states, step = build_model()

    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())

    def as_global(tree, sharding):
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, __import__("numpy").asarray(x)), tree)

    params = as_global(params, repl)
    opt_state = as_global(opt_state, repl)
    states = as_global(states, repl)

    def place(feed_np):
        # every host slices ITS rows of the deterministic global batch,
        # then assembles the globally-sharded array (the real multi-host
        # input path: mh.global_batch / make_array_from_process_local_data)
        n = feed_np["x"].shape[0]
        lo = pid * (n // nproc)
        hi = lo + n // nproc
        local = {k: v[lo:hi] for k, v in feed_np.items()}
        return mh.global_batch(local, mesh)

    final = run_steps(params, opt_state, states, step, place)
    if pid == 0:
        with open(out, "wb") as f:
            pickle.dump(final, f)
    # all processes must stay alive until the collective program finishes
    jax.effects_barrier()


if __name__ == "__main__":
    main()
