"""Attention numerics: blockwise == exact, ring == exact (on the 8-device
virtual mesh), plus gradient agreement — the compare-two-implementations
pattern of the reference's test_matrixCompare/Compare2Function harnesses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.ops import attention as A


def _qkv(b=2, t=32, h=4, d=8, seed=0):
    r = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(r.normal(size=(b, t, h, d)).astype(np.float32))
    return mk(), mk(), mk()


def test_blockwise_matches_exact():
    q, k, v = _qkv()
    ref = A.dot_product_attention(q, k, v)
    out = A.blockwise_attention(q, k, v, block_size=8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_causal_matches_exact():
    q, k, v = _qkv(t=33)  # non-divisible by block
    mask = A.causal_mask(33, 33)
    ref = A.dot_product_attention(q, k, v, mask=mask)
    out = A.blockwise_attention(q, k, v, block_size=8, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_grads_match():
    q, k, v = _qkv(t=16)

    def loss_exact(q, k, v):
        return jnp.sum(A.dot_product_attention(q, k, v) ** 2)

    def loss_block(q, k, v):
        return jnp.sum(A.blockwise_attention(q, k, v, block_size=4) ** 2)

    g_ref = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
    g_out = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_out, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_exact(causal):
    q, k, v = _qkv(b=2, t=32, h=2, d=4)
    mask = A.causal_mask(32, 32) if causal else None
    ref = A.dot_product_attention(q, k, v, mask=mask)

    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("seq",))
    out = A.attention_with_sequence_parallel(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_grads_match():
    q, k, v = _qkv(b=1, t=16, h=2, d=4)
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("seq",))

    def loss_ring(q, k, v):
        return jnp.sum(
            A.attention_with_sequence_parallel(q, k, v, mesh, causal=True) ** 2
        )

    def loss_exact(q, k, v):
        m = A.causal_mask(16, 16)
        return jnp.sum(A.dot_product_attention(q, k, v, mask=m) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_mha_shapes_and_causal():
    b, t, e, hds = 2, 10, 16, 16
    r = np.random.default_rng(0)
    x = jnp.asarray(r.normal(size=(b, t, e)).astype(np.float32))
    w = lambda m, n: jnp.asarray(r.normal(size=(m, n)).astype(np.float32) * 0.1)
    out = A.multi_head_attention(
        x, x, w(e, hds), w(e, hds), w(e, hds), w(hds, e), num_heads=4, causal=True
    )
    assert out.shape == (b, t, e)
    # causal: early positions unaffected by corrupting later positions
    wq, wk, wv, wo = w(e, hds), w(e, hds), w(e, hds), w(hds, e)
    o1 = A.multi_head_attention(x, x, wq, wk, wv, wo, num_heads=4, causal=True)
    o2 = A.multi_head_attention(
        x.at[:, 5:, :].set(123.0), x.at[:, 5:, :].set(123.0),
        wq, wk, wv, wo, num_heads=4, causal=True,
    )
    np.testing.assert_allclose(
        np.asarray(o1[:, :5]), np.asarray(o2[:, :5]), atol=1e-5
    )


def test_collectives_surface():
    from paddle_tpu.parallel import collective as C

    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("data",))
    x = jnp.arange(8.0).reshape(4, 2)

    def body(x):
        s = C.all_reduce(x, "data")
        g = C.all_gather(x, "data")
        b = C.broadcast(x, "data", root=2)
        r = C.ring_shift(x, "data")
        return s, g, b, r

    fn = C.on_mesh(mesh, body, in_specs=(P("data"),),
                   out_specs=(P("data"), P("data"), P("data"), P("data")))
    s, g, b, r = fn(x)
    np.testing.assert_allclose(np.asarray(s)[0], x.sum(0))  # every shard = total
    assert np.asarray(g).shape == (16, 2)
    np.testing.assert_allclose(np.asarray(b)[0], np.asarray(x)[2])
    np.testing.assert_allclose(np.asarray(r)[1], np.asarray(x)[0])


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_exact(causal):
    """Ulysses (all_to_all seq<->head re-sharding) is exact: equals plain
    full-sequence attention, both maskings."""
    q, k, v = _qkv(b=2, t=32, h=4, d=4)
    mask = A.causal_mask(32, 32) if causal else None
    ref = A.dot_product_attention(q, k, v, mask=mask)

    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("seq",))
    out = A.attention_with_ulysses(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ulysses_attention_grads_match():
    q, k, v = _qkv(b=1, t=16, h=4, d=4)
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("seq",))

    def loss_u(q, k, v):
        return jnp.sum(
            A.attention_with_ulysses(q, k, v, mesh, causal=True) ** 2)

    def loss_exact(q, k, v):
        m = A.causal_mask(16, 16)
        return jnp.sum(A.dot_product_attention(q, k, v, mask=m) ** 2)

    g_u = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_exact, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_u, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    import pytest as _pytest

    q, k, v = _qkv(b=1, t=16, h=2, d=4)  # 2 heads on a 4-way seq axis
    devs = jax.devices()[:4]
    mesh = Mesh(np.asarray(devs).reshape(4), ("seq",))
    with _pytest.raises(ValueError, match="not divisible"):
        A.attention_with_ulysses(q, k, v, mesh, causal=True)


def test_ulysses_transformer_trains_on_dp_sp_mesh():
    """attn_impl='ulysses' through the LM train step on {data, seq}."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    devs = jax.devices()[:8]
    mesh = Mesh(np.asarray(devs).reshape(2, 4), ("data", "seq"))
    cfg = T.TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=4, embed_dim=16, mlp_dim=32,
        max_seq_len=32, remat=False, attn_impl="ulysses")
    params = T.place_params(T.init_params(cfg, jax.random.key(0)), mesh, cfg)
    opt = Adam(learning_rate=1e-2)
    state = opt.init_tree(params)
    step = T.build_train_step(cfg, opt, mesh=mesh)
    ids = jax.device_put(
        jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 17))),
        NamedSharding(mesh, P("data", None)))
    txt = step.lower(params, state, ids).compile().as_text()
    assert "all-to-all" in txt
    losses = []
    for _ in range(6):
        params, state, loss = step(params, state, ids)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
