"""Bench regression sentinel (tools/bench_sentinel.py) — the CI gate
that diffs two BENCH_r*.json artifacts and fails on a >threshold
regression in any shared metric, direction-aware (throughput down OR
latency/cost up)."""

import importlib.util
import os

import pytest

pytestmark = pytest.mark.sentinel

_REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture(scope="module")
def sentinel():
    spec = importlib.util.spec_from_file_location(
        "bench_sentinel", os.path.join(_REPO, "tools", "bench_sentinel.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _newest_rounds() -> list[str]:
    """The two newest checked-in BENCH_r*.json by round number — the
    gate tracks new rounds automatically instead of pinning r07→r08."""
    import glob

    rounds = sorted(glob.glob(os.path.join(_REPO, "BENCH_r*.json")))
    return rounds[-2:]


def test_newest_rounds_pass_at_release_threshold(sentinel):
    """The two newest shipped round-over-round artifacts are the
    no-regression baseline: they must exit 0 at the release threshold."""
    rounds = _newest_rounds()
    assert len(rounds) == 2, "need two checked-in BENCH_r*.json rounds"
    assert sentinel.main([*rounds, "--threshold", "0.30"]) == 0


def test_seeded_regression_fixture_trips_nonzero(sentinel, tmp_path, capsys):
    base, cand = sentinel.write_regression_fixture(str(tmp_path))
    assert sentinel.main([base, cand, "--threshold", "0.10"]) == 1
    out = capsys.readouterr().out
    assert "toy_train_samples_per_sec" in out
    assert "toy_p99_ttft_ms" in out
    # the clean direction stays clean
    assert sentinel.main([base, base]) == 0


def test_self_test_flag_exits_zero(sentinel):
    assert sentinel.main(["--self-test"]) == 0


def test_direction_awareness(sentinel):
    assert sentinel.lower_is_better("serve_p99_ttft_ms", "ms")
    assert sentinel.lower_is_better("cost_per_token_s", "s/token")
    assert not sentinel.lower_is_better("train_samples_per_sec", "samples/s")
    assert not sentinel.lower_is_better("mfu_pct", "%")


def test_compare_flags_only_crossing_metrics(sentinel, tmp_path):
    base, cand = sentinel.write_regression_fixture(str(tmp_path))
    result = sentinel.compare(sentinel.load_metrics(base),
                              sentinel.load_metrics(cand), threshold=0.10)
    assert set(result["regressions"]) == {"toy_train_samples_per_sec",
                                          "toy_p99_ttft_ms"}
    # the small mfu improvement is not a regression
    assert "toy_mfu_pct" not in result["regressions"]


def test_bad_usage_exits_two(sentinel, tmp_path):
    assert sentinel.main([]) == 2
    assert sentinel.main([str(tmp_path / "missing_a.json"),
                         str(tmp_path / "missing_b.json")]) == 2
