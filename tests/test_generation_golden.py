"""Config-driven beam-search generation against the reference's own golden
outputs — the ``test_recurrent_machine_generation.cpp:110-141`` analog.

Runs the literal ``sample_trainer_rnn_gen.conf`` with the reference's
checked-in trained parameters (``rnn_gen_test_model_dir/t1``), writes the
generated sequences through the seqtext_printer evaluator, and compares the
result with the reference's expected files (``r1.test.nobeam`` /
``r1.test.beam``) the same way the reference test does: as a stream of
floats (whitespace-insensitive)."""

import os
import re
import struct

import jax
import numpy as np
import pytest

from paddle_tpu.config.topology import Topology
from paddle_tpu.evaluator import runtime as ev_runtime

REF_TESTS = "/root/reference/paddle/trainer/tests"
MODEL_DIR = os.path.join(REF_TESTS, "rnn_gen_test_model_dir")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(MODEL_DIR), reason="reference checkout absent")


def load_reference_param(path: str) -> np.ndarray:
    """Reference Parameter::save format: int32 version, uint32 valueSize,
    uint64 count, then count f32 values (paddle/parameter/Parameter.cpp)."""
    with open(path, "rb") as f:
        raw = f.read()
    version, value_size, count = struct.unpack("<iIQ", raw[:16])
    assert version == 0 and value_size == 4
    return np.frombuffer(raw[16:], np.float32, count=count).copy()


def float_stream(text: str) -> list[float]:
    return [float(t) for t in re.findall(r"-?\d+(?:\.\d+)?(?:e-?\d+)?", text)]


@pytest.mark.parametrize("beam", [False, True])
def test_rnn_generation_matches_reference_golden(tmp_path, beam):
    from paddle_tpu.trainer.config_parser import parse_config

    parsed = parse_config(
        os.path.join(REF_TESTS, "sample_trainer_rnn_gen.conf"),
        f"beam_search={1 if beam else 0}")

    out = parsed.output_layers()
    topo = Topology(out)

    # the reference's trained parameters, loaded from its binary format
    params = {}
    for spec in topo.param_specs():
        arr = load_reference_param(os.path.join(MODEL_DIR, "t1", spec.name))
        params[spec.name] = arr.reshape(spec.shape)

    batch = 15
    rng = np.random.default_rng(0)
    feed = {
        "sent_id": np.arange(batch, dtype=np.float32).reshape(batch, 1),
        "dummy_data_input": rng.uniform(size=(batch, 2)).astype(np.float32),
    }
    # the reference computes in f32, which is now the default policy (the
    # bf16 MXU cast would round the -0.2 transition score to -0.200195)
    values, _ = topo.forward(params, topo.init_states(), feed, False,
                             jax.random.key(0))

    # the declared seqtext printer, redirected to tmp and the absolute
    # dict path (the conf assumes cwd == reference/paddle)
    specs = parsed.evaluators
    assert len(specs) == 1 and specs[0].type == "seq_text_printer"
    result_file = tmp_path / "dump_text.test"
    specs[0].fields["result_file"] = str(result_file)
    specs[0].fields["dict_file"] = os.path.join(REF_TESTS,
                                                "test_gen_dict.txt")
    evs = ev_runtime.build(specs)
    evs.start()
    evs.eval_batch(values, feed=feed)
    evs.finish()

    golden = os.path.join(
        MODEL_DIR, "r1.test." + ("beam" if beam else "nobeam"))
    got = float_stream(result_file.read_text())
    want = float_stream(open(golden).read())
    assert got == want, (
        f"generation output diverged from the reference golden {golden}:\n"
        f"got  {got[:30]}...\nwant {want[:30]}...")


@pytest.mark.parametrize("beam", [False, True])
def test_nested_rnn_generation_matches_reference_golden(tmp_path, beam):
    """The hierarchical variant (test_recurrent_machine_generation.cpp:
    NEST_CONFIG_FILE): beam_search inside an outer recurrent_group over
    subsequences; both beam settings produce the same r1.test.nest output
    (the conf sets num_results_per_sample=1)."""
    from paddle_tpu.core import flags
    from paddle_tpu.core.lod import NestedSequenceBatch
    from paddle_tpu.trainer.config_parser import parse_config

    parsed = parse_config(
        os.path.join(REF_TESTS, "sample_trainer_nest_rnn_gen.conf"),
        f"beam_search={1 if beam else 0}")
    topo = Topology(parsed.output_layers())
    params = {}
    for spec in topo.param_specs():
        arr = load_reference_param(os.path.join(MODEL_DIR, "t1", spec.name))
        params[spec.name] = arr.reshape(spec.shape)

    # one outer sequence with 15 single-word subsequences (the reference
    # test's prepareInArgs hasSubseq branch); one sample id
    n_sub = 15
    rng = np.random.default_rng(0)
    feed = {
        "sent_id": np.zeros((1, 1), np.float32),
        "dummy_data_input": NestedSequenceBatch(
            data=np.asarray(
                rng.uniform(size=(1, n_sub, 1, 2)).astype(np.float32)),
            seq_length=np.asarray([n_sub], np.int32),
            sub_length=np.ones((1, n_sub), np.int32)),
    }
    # the reference computes in f32, which is now the default policy (the
    # bf16 MXU cast would round the -0.2 transition score to -0.200195)
    values, _ = topo.forward(params, topo.init_states(), feed, False,
                             jax.random.key(0))

    specs = parsed.evaluators
    assert len(specs) == 1 and specs[0].type == "seq_text_printer"
    result_file = tmp_path / "dump_text.nest"
    specs[0].fields["result_file"] = str(result_file)
    specs[0].fields["dict_file"] = os.path.join(REF_TESTS,
                                                "test_gen_dict.txt")
    evs = ev_runtime.build(specs)
    evs.start()
    evs.eval_batch(values, feed=feed)
    evs.finish()

    got = float_stream(result_file.read_text())
    want = float_stream(
        open(os.path.join(MODEL_DIR, "r1.test.nest")).read())
    assert got == want, (
        f"nested generation diverged:\ngot  {got[:30]}\nwant {want[:30]}")
