"""The reference's benchmark harness configs
(``benchmark/paddle/image/*.py``, ``benchmark/paddle/rnn/rnn.py``) run
byte-identical through ``paddle_tpu.demo.benchmark.run`` — the
``--job=time`` invocation mirrors ``image/run.sh``; a ``--job=train``
pass exercises the py3 provider ports end to end."""

from __future__ import annotations

import os

import numpy as np
import pytest

REF = os.environ.get("PADDLE_REFERENCE_ROOT", "/root/reference")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(os.path.join(REF, "benchmark/paddle")),
    reason="reference checkout absent")


def _copied_verbatim(tmp_path, family, cfg):
    with open(os.path.join(REF, "benchmark/paddle", family, cfg)) as f:
        ref = f.read()
    with open(tmp_path / family / cfg) as f:
        ours = f.read()
    assert ours == ref


def test_smallnet_time_job(tmp_path, capsys):
    from paddle_tpu.demo.benchmark import run

    rc = run.main(["--net", "smallnet", "--batch_size", "8",
                   "--workdir", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "ms/batch" in out
    _copied_verbatim(tmp_path, "image", "smallnet_mnist_cifar.py")


def test_rnn_time_job(tmp_path, capsys):
    from paddle_tpu.demo.benchmark import run

    rc = run.main(["--net", "rnn", "--batch_size", "8",
                   "--config_args", "hidden_size=32",
                   "--seq_dim", "16", "--workdir", str(tmp_path)])
    assert rc == 0
    assert "ms/batch" in capsys.readouterr().out
    _copied_verbatim(tmp_path, "rnn", "rnn.py")


def test_smallnet_train_pass(tmp_path, capsys, monkeypatch):
    from paddle_tpu.demo.benchmark import run

    rc = run.main(["--net", "smallnet", "--batch_size", "256",
                   "--job", "train", "--workdir", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Pass 0" in out


def test_rnn_train_pass(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_IMDB_SYNTH_N", "64")
    from paddle_tpu.demo.benchmark import run

    rc = run.main(["--net", "rnn", "--batch_size", "16", "--job", "train",
                   "--config_args", "hidden_size=32",
                   "--workdir", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Pass 0" in out


CONCAT2_CFG = """
from paddle.trainer_config_helpers import *

settings(batch_size=4, learning_rate=0.1)
img = data_layer(name='img', size=192, height=8, width=8)
p1 = conv_projection(input=img, filter_size=1, num_filters=4, num_channels=3)
p2 = conv_projection(input=img, filter_size=3, num_filters=2, num_channels=3,
                     padding=1)
cat = concat_layer(name='cat', input=[p1, p2], bias_attr=True,
                   act=LinearActivation())
outputs(cat)
"""


def test_concat2_conv_projection_bias(tmp_path):
    """concat_layer(bias_attr=True) over conv projections (the googlenet
    inception block, benchmark/paddle/image/googlenet.py:138-142): shared
    per-channel bias of size sum(num_filters)
    (config_parser.py:3544-3553); forward adds it channel-wise."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.trainer.config_parser import parse_config

    cfg = tmp_path / "concat2_bias.py"
    cfg.write_text(CONCAT2_CFG)
    parsed = parse_config(str(cfg))

    lc = next(l for l in parsed.model_config.layers if l.name == "cat")
    assert lc.bias_size == 6
    assert lc.shared_biases
    assert lc.bias_parameter_name == "_cat.wbias"
    pconf = next(p for p in parsed.model_config.parameters
                 if p.name == "_cat.wbias")
    assert pconf.size == 6

    topo = Topology(parsed.output_layers())
    specs = {s.name: s for s in topo.param_specs()}
    assert tuple(specs["_cat.wbias"].shape) == (6,)

    params = paddle.parameters.create(topo).as_dict()
    feed = {"img": np.random.default_rng(0).normal(
        size=(2, 192)).astype(np.float32)}
    base0, _ = topo.forward(params, {}, feed, False, jax.random.key(0))
    y0 = np.asarray(base0["cat"])
    # bump channel 0 of projection 2's bias; exactly its 64 spatial
    # outputs (after the first projection's 4*64 block) shift by +1
    params["_cat.wbias"] = params["_cat.wbias"].at[4].add(1.0)
    base1, _ = topo.forward(params, {}, feed, False, jax.random.key(0))
    y1 = np.asarray(base1["cat"])
    delta = y1 - y0
    assert np.allclose(delta[:, 4 * 64:5 * 64], 1.0, atol=1e-5)
    mask = np.ones(y0.shape[1], bool)
    mask[4 * 64:5 * 64] = False
    assert np.allclose(delta[:, mask], 0.0, atol=1e-6)
