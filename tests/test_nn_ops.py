"""Direct unit coverage for the ops/nn.py conv/pool family that until now
only had end-to-end model coverage: depthwise_conv2d, conv2d_transpose
and avg_pool2d(exclude_pad=...) against independent
``lax.conv_general_dilated`` / ``lax.reduce_window`` oracles across
stride/padding/dtype combinations."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from paddle_tpu.ops import nn


def _tol(dtype):
    return dict(rtol=2e-2, atol=2e-2) if dtype == jnp.bfloat16 else \
        dict(rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("stride", [1, 2])
@pytest.mark.parametrize("padding", [0, 1])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_depthwise_conv2d_matches_per_channel_oracle(rng_np, stride,
                                                     padding, dtype):
    """Each channel must be an INDEPENDENT single-channel convolution —
    the oracle runs C separate lax convs and stacks them."""
    c = 3
    x = jnp.asarray(rng_np.normal(size=(2, 9, 10, c)).astype(np.float32)
                    ).astype(dtype)
    w = jnp.asarray(rng_np.normal(size=(3, 3, 1, c)).astype(np.float32)
                    ).astype(dtype)
    got = nn.depthwise_conv2d(x, w, stride=stride, padding=padding)
    per = [
        lax.conv_general_dilated(
            x[..., ci:ci + 1], w[:, :, :, ci:ci + 1],
            window_strides=(stride, stride),
            padding=[(padding, padding)] * 2,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            precision=lax.Precision.HIGHEST)
        for ci in range(c)
    ]
    oracle = jnp.concatenate(per, axis=-1)
    assert got.shape == oracle.shape
    assert got.dtype == dtype
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(oracle, np.float32), **_tol(dtype))


@pytest.mark.parametrize("stride", [1, 2, 3])
@pytest.mark.parametrize("padding", [0, 1])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_conv2d_transpose_matches_dilated_conv_oracle(rng_np, stride,
                                                      padding, dtype):
    """Fractionally-strided oracle: zero-dilate the input by ``stride``,
    convolve with the spatially flipped kernel (in/out swapped) at
    padding k-1-p — out = (in-1)*s + k - 2p."""
    k, cin, cout = 3, 4, 5
    x = jnp.asarray(rng_np.normal(size=(2, 6, 7, cin)).astype(np.float32)
                    ).astype(dtype)
    w = jnp.asarray(rng_np.normal(size=(k, k, cout, cin)).astype(np.float32)
                    ).astype(dtype)
    got = nn.conv2d_transpose(x, w, stride=stride, padding=padding)
    # rhs HWIO with I = x's channels: flip taps, swap (cout, cin)
    rhs = jnp.flip(w, (0, 1)).transpose(0, 1, 3, 2)
    oracle = lax.conv_general_dilated(
        x, rhs, window_strides=(1, 1),
        padding=[(k - 1 - padding, k - 1 - padding)] * 2,
        lhs_dilation=(stride, stride),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=lax.Precision.HIGHEST)
    expect_h = (x.shape[1] - 1) * stride + k - 2 * padding
    assert got.shape == (2, expect_h, (x.shape[2] - 1) * stride + k
                         - 2 * padding, cout)
    assert got.shape == oracle.shape
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(oracle, np.float32), **_tol(dtype))


@pytest.mark.parametrize("ksize,stride,padding", [
    (2, 2, 0), (3, 2, 1), (3, 1, 1), ((2, 3), (1, 2), (1, 0)),
])
@pytest.mark.parametrize("exclude_pad", [True, False])
def test_avg_pool2d_matches_reduce_window_oracle(rng_np, ksize, stride,
                                                 padding, exclude_pad):
    x = jnp.asarray(rng_np.normal(size=(2, 8, 9, 3)).astype(np.float32))
    got = nn.avg_pool2d(x, ksize, stride, padding, exclude_pad=exclude_pad)
    kh, kw = (ksize, ksize) if isinstance(ksize, int) else ksize
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    win = dict(window_dimensions=(1, kh, kw, 1),
               window_strides=(1, sh, sw, 1),
               padding=((0, 0), (ph, ph), (pw, pw), (0, 0)))
    summed = lax.reduce_window(x, 0.0, lax.add, **win)
    if exclude_pad and (ph or pw):
        # EXCLUDE_PADDING: divide by the number of REAL elements under
        # each window (border windows see fewer)
        counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, **win)
        oracle = summed / counts
    else:
        oracle = summed / (kh * kw)
    assert got.shape == oracle.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(oracle),
                               rtol=2e-6, atol=2e-6)


def test_avg_pool2d_exclude_pad_border_value():
    """Hand-computed border check: a constant input avg-pooled with
    exclude_pad=True must stay constant (counts divide exactly), while
    include-pad shrinks border values by the zero ring."""
    x = jnp.ones((1, 4, 4, 1))
    ex = nn.avg_pool2d(x, 3, 1, 1, exclude_pad=True)
    np.testing.assert_allclose(np.asarray(ex), 1.0, atol=1e-6)
    inc = nn.avg_pool2d(x, 3, 1, 1, exclude_pad=False)
    np.testing.assert_allclose(float(inc[0, 0, 0, 0]), 4.0 / 9.0, atol=1e-6)
    np.testing.assert_allclose(float(inc[0, 1, 1, 0]), 1.0, atol=1e-6)
