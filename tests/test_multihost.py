"""Multi-host surface on the virtual 8-device CPU mesh: mesh construction
(pod + multi-slice dcn/ici split), reader sharding, global batch assembly,
and a dp-over-dcn train step whose gradients cross the dcn axis."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.distributed import multihost as mh


def test_pod_mesh_axis_resolution():
    mesh = mh.pod_mesh(data=None, model=2)
    assert mesh.shape == {"data": 4, "model": 2}
    mesh = mh.pod_mesh(data=None)
    assert mesh.shape == {"data": 8}


def test_multislice_mesh_groups_slices():
    mesh = mh.multislice_mesh(num_slices=2, data=None, model=2)
    assert mesh.axis_names == ("dcn", "data", "model")
    assert mesh.shape == {"dcn": 2, "data": 2, "model": 2}
    # contiguous split: first slice's devices all in dcn row 0
    devs = np.asarray(mesh.devices)
    first = {d.id for d in devs[0].flatten()}
    assert first == set(range(4))


def test_shard_reader_disjoint_cover():
    data = list(range(20))
    shards = [list(mh.shard_reader(lambda: iter(data), i, 4)())
              for i in range(4)]
    assert sorted(sum(shards, [])) == data
    assert all(len(s) == 5 for s in shards)
    assert not set(shards[0]) & set(shards[1])


def test_global_batch_and_dcn_train_step():
    """Pure-DP over the dcn axis: loss/grads all-reduce across slices."""
    mesh = mh.multislice_mesh(num_slices=2, data=2, model=2)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))
    w = jax.device_put(w, NamedSharding(mesh, P(None, "model")))
    x = rng.normal(size=(8, 8)).astype(np.float32)
    y = rng.normal(size=(8, 4)).astype(np.float32)
    # single-process: global_batch is the identity placement
    gx = mh.global_batch(jnp.asarray(x), mesh, P(("dcn", "data"), None))
    gy = mh.global_batch(jnp.asarray(y), mesh, P(("dcn", "data"), None))
    assert gx.sharding.spec == P(("dcn", "data"), None)

    @jax.jit
    def step(w, x, y):
        def loss_fn(w):
            return jnp.mean((x @ w - y) ** 2)

        l, g = jax.value_and_grad(loss_fn)(w)
        return l, w - 0.1 * g

    l0, w1 = step(w, gx, gy)
    l1, _ = step(w1, gx, gy)
    assert np.isfinite(float(l0)) and float(l1) < float(l0)
