"""Telemetry layer (ISSUE 1): registry semantics, sink round-trips, the
per-step records ``SGD.train`` emits, comm-bytes accounting from the
collective wrappers, and the flight recorder's dump-on-exception."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import metrics
from paddle_tpu.core import flags
from paddle_tpu.distributed import multihost as mh


@pytest.fixture
def registry():
    """A fresh, isolated registry (never the process-global one)."""
    return metrics.MetricsRegistry("test")


@pytest.fixture
def global_sink():
    """MemorySink attached to the process-global registry (what SGD.train
    uses), detached afterwards."""
    sink = metrics.MemorySink()
    reg = metrics.get_registry()
    reg.add_sink(sink)
    yield sink
    reg.remove_sink(sink)


# -- registry semantics -------------------------------------------------------

def test_counter_gauge_histogram_with_labels(registry):
    c = registry.counter("requests")
    c.inc(op="a")
    c.inc(2.5, op="a")
    c.inc(op="b")
    assert c.value(op="a") == 3.5 and c.value(op="b") == 1.0
    assert c.value(op="missing") == 0.0
    with pytest.raises(ValueError):
        c.inc(-1.0, op="a")

    g = registry.gauge("loss")
    g.set(2.0, run="train")
    g.set(1.5, run="train")  # last write wins
    assert g.value(run="train") == 1.5
    assert g.value(run="test") is None

    h = registry.histogram("ms", buckets=(1.0, 10.0))
    for v in (0.5, 5.0, 500.0):
        h.observe(v, run="train")
    s = h.summary(run="train")
    assert s["count"] == 3 and s["min"] == 0.5 and s["max"] == 500.0
    assert s["buckets"] == {"1.0": 1, "10.0": 1, "+Inf": 1}

    # same name, same type -> same object; different type -> error
    assert registry.counter("requests") is c
    with pytest.raises(TypeError):
        registry.gauge("requests")

    snap = registry.snapshot()
    assert {"requests", "loss", "ms"} <= set(snap)
    assert {s["op"]: s["value"] for s in snap["requests"]} == \
        {"a": 3.5, "b": 1.0}


def test_emit_stamps_schema_and_fans_out(registry):
    m1, m2 = metrics.MemorySink(), metrics.MemorySink()
    registry.add_sink(m1)
    registry.add_sink(m2)
    rec = registry.emit({"value": 1}, kind="bench")
    for sink in (m1, m2):
        assert sink.records == [rec]
    assert rec["schema"] == metrics.SCHEMA
    assert rec["kind"] == "bench" and "ts" in rec and "host" in rec
    registry.remove_sink(m2)
    registry.emit({"value": 2})
    assert len(m1.records) == 2 and len(m2.records) == 1


def test_jsonl_sink_roundtrip(tmp_path, registry):
    path = str(tmp_path / "sub" / "metrics.jsonl")
    registry.add_sink(metrics.JsonlSink(path))
    registry.emit({"kind": "step", "loss": np.float32(1.5),
                   "n": np.int64(3), "arr": np.arange(2)})
    registry.emit({"kind": "step", "loss": 2.0})
    registry.clear_sinks()  # closes the file
    lines = [json.loads(line) for line in open(path)]
    assert len(lines) == 2
    assert lines[0]["loss"] == 1.5 and lines[0]["n"] == 3
    assert lines[0]["arr"] == [0, 1]  # numpy -> JSON-native
    assert all(r["schema"] == metrics.SCHEMA for r in lines)


# -- per-step records from SGD.train ------------------------------------------

def _tiny_trainer():
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import data_type

    x = layer.data(name="x", type=data_type.dense_vector(4))
    y = layer.data(name="y", type=data_type.dense_vector(1))
    fc = layer.fc(input=x, size=1,
                  act=paddle.activation.LinearActivation(), name="out")
    cost = layer.mse_cost(input=fc, label=y)
    params = paddle.parameters.create(paddle.topology.Topology(cost))
    return paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.05))


def _reader(n_batches=2, poison_batch=None):
    rs = np.random.RandomState(0)
    w = np.array([1.0, -2.0, 0.5, 3.0])

    def r():
        for i in range(8 * n_batches):
            x = rs.randn(4).astype(np.float32)
            if poison_batch is not None and i // 8 == poison_batch:
                x[0] = np.nan
            yield x, np.array([x @ w], np.float32)
    return paddle.reader.batch(r, batch_size=8)


def test_sgd_train_emits_one_record_per_step(global_sink, tmp_path):
    """Acceptance: a 2-step run with the JSONL sink produces one parseable
    record per step with {step, loss, step_ms, examples_per_sec, mfu_pct}."""
    path = str(tmp_path / "train.jsonl")
    jsonl = metrics.JsonlSink(path)
    reg = metrics.get_registry()
    reg.add_sink(jsonl)
    try:
        _tiny_trainer().train(reader=_reader(n_batches=2), num_passes=1)
    finally:
        reg.remove_sink(jsonl)
        jsonl.close()

    for records in ([json.loads(line) for line in open(path)],
                    global_sink.by_kind("step")):
        steps = [r for r in records if r.get("kind") == "step"]
        assert len(steps) == 2
        for i, r in enumerate(steps):
            assert r["step"] == i
            assert np.isfinite(r["loss"])
            assert r["step_ms"] > 0
            assert r["examples_per_sec"] > 0
            assert "mfu_pct" in r  # ~0 on CPU, but always present
            assert r["pass_id"] == 0 and r["batch_id"] == i
        # XLA cost analysis rode along (cached per compile signature)
        assert steps[0]["flops"] > 0


def test_sgd_train_uses_explicit_registry():
    reg = metrics.MetricsRegistry("isolated")
    sink = metrics.MemorySink()
    reg.add_sink(sink)
    _tiny_trainer().train(reader=_reader(n_batches=2), num_passes=1,
                          metrics_registry=reg)
    assert len(sink.by_kind("step")) == 2
    # pull-side aggregates accumulated on the same registry
    assert reg.counter("steps").value(run="train") == 2.0
    assert reg.counter("examples").value(run="train") == 16.0
    assert reg.histogram("step_ms").summary(run="train")["count"] == 2


def test_tokens_in_feed():
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.telemetry import tokens_in_feed

    assert tokens_in_feed({"x": np.zeros((4, 2))}) is None
    feed = {"s": SequenceBatch(data=np.zeros((2, 5)),
                               length=np.array([5, 3], np.int32)),
            "x": np.zeros((2, 2))}
    assert tokens_in_feed(feed) == 8


# -- comm accounting from the collective wrappers -----------------------------

def test_collective_wrappers_count_bytes():
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel import collective
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.telemetry import comm_snapshot, get_default_registry

    mesh = make_mesh({"data": 2})
    before = comm_snapshot().get("all_reduce/data", 0.0)
    fn = collective.on_mesh(
        mesh, lambda x: collective.all_reduce(x, "data"),
        in_specs=P("data"), out_specs=P())
    out = fn(jnp.ones((4, 8), jnp.float32))
    np.testing.assert_allclose(np.asarray(out)[0], 2.0)
    # per-shard payload of the traced program: [2, 8] f32 = 64 bytes
    # (>= : jax may trace the fresh shard_map more than once)
    after = comm_snapshot()["all_reduce/data"]
    delta = after - before
    assert delta >= 64.0 and delta % 64.0 == 0.0
    calls = get_default_registry().counter("comm_calls")
    assert calls.value(op="all_reduce", axis="data") >= 1


def test_capture_comm_scopes_trace_accounting():
    """record_comm feeds an active capture AND the global counters —
    jax's trace cache runs a program's Python body exactly once, so a
    single firing serves both consumers without double counting."""
    from paddle_tpu.telemetry import (capture_comm, comm_snapshot,
                                      record_comm)

    before = comm_snapshot().get("psum/data", 0.0)
    with capture_comm() as comm:
        record_comm("psum", "data", 256)
        record_comm("psum", "data", 256)
    assert comm == {"psum/data": 512.0}
    assert comm_snapshot()["psum/data"] == before + 512.0
    record_comm("psum", "data", 128)  # outside capture: counters only
    assert comm == {"psum/data": 512.0}
    assert comm_snapshot()["psum/data"] == before + 640.0


def test_cost_for_captures_program_comm():
    """cost_for returns (flops, bytes, comm) with the lowered program's
    collective payload — independent of which registry is in use."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel import collective
    from paddle_tpu.parallel.mesh import make_mesh
    from paddle_tpu.telemetry import StepTelemetry

    mesh = make_mesh({"data": 2})
    fn = collective.on_mesh(
        mesh, lambda x: collective.all_reduce(x, "data"),
        in_specs=P("data"), out_specs=P())
    jitted = __import__("jax").jit(fn)
    x = jnp.ones((4, 8), jnp.float32)
    reg = metrics.MetricsRegistry("isolated-comm")
    st = StepTelemetry(registry=reg)
    flops, nbytes, comm = st.cost_for("sig0", lambda: jitted.lower(x))
    assert comm.get("all_reduce/data") == 64.0  # [2, 8] f32 per shard
    # cached: second call returns the same triple without re-lowering
    assert st.cost_for("sig0", lambda: 1 / 0) == (flops, nbytes, comm)
    rec = st.record_step(loss=1.0, step_ms=1.0, examples=4, comm=comm)
    assert rec["comm_bytes"] == {"all_reduce/data": 64.0}


def test_step_records_carry_comm_snapshot(registry):
    from paddle_tpu.telemetry import StepTelemetry, record_comm

    sink = metrics.MemorySink()
    registry.add_sink(sink)
    record_comm("all_gather", "model", 1024, registry=registry)
    st = StepTelemetry(registry=registry)
    rec = st.record_step(loss=1.0, step_ms=2.0, examples=4)
    assert rec["comm_bytes"] == {"all_gather/model": 1024.0}
    assert sink.records[-1]["comm_bytes"] == {"all_gather/model": 1024.0}


# -- flight recorder ----------------------------------------------------------

def test_flight_recorder_ring_and_dump(tmp_path):
    rec = mh.FlightRecorder(capacity=3)
    for i in range(5):
        rec.record({"step": i})
    rec.heartbeat("begin_batch", step=5)
    assert [r["step"] for r in rec.records] == [2, 3, 4]  # ring evicted 0,1
    path = rec.dump(reason="unit", dump_dir=str(tmp_path))
    payload = json.load(open(path))
    assert payload["reason"] == "unit"
    assert [r["step"] for r in payload["records"]] == [2, 3, 4]
    assert payload["heartbeats"][-1]["tag"] == "begin_batch"
    assert payload["schema"] == "paddle_tpu.flight/1"


def test_flight_recorder_dumps_when_train_step_raises(tmp_path):
    """Acceptance: the ring-buffer dump is written when the train step
    raises (here: debug_nans trapping a poisoned batch)."""
    import jax

    mh.flight_recorder().clear()
    prev_dir = flags.get("flight_recorder_dir")
    prev_nans = flags.get("debug_nans")
    flags.set("flight_recorder_dir", str(tmp_path))
    flags.set("debug_nans", True)
    prev_cfg = jax.config.jax_debug_nans
    try:
        with pytest.raises(FloatingPointError):
            _tiny_trainer().train(
                reader=_reader(n_batches=3, poison_batch=2), num_passes=1)
    finally:
        flags.set("flight_recorder_dir", prev_dir)
        flags.set("debug_nans", prev_nans)
        jax.config.update("jax_debug_nans", prev_cfg)
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("flight-host")]
    assert len(dumps) == 1
    payload = json.load(open(os.path.join(str(tmp_path), dumps[0])))
    assert "FloatingPointError" in payload["reason"]
    # the two good steps preceding the poisoned one are in the ring, and
    # the pre-step heartbeat pins where the failing batch began
    assert len(payload["records"]) >= 2
    assert all(r["kind"] == "step" for r in payload["records"])
    assert any(h["tag"] == "begin_batch" for h in payload["heartbeats"])
