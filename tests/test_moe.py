"""Mixture-of-Experts / expert parallelism (parallel/moe.py).

The repo's compare-two-implementations pattern (SURVEY §4): the
expert-parallel shard_map path must equal the dense-dispatch reference
run group-by-group, values AND gradients; the post-SPMD HLO must carry
real all-to-alls; routing must respect capacity; and the layer must
train."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.parallel.moe import (
    MoEConfig,
    capacity,
    init_moe_params,
    moe_ffn,
    moe_ffn_sharded,
    place_moe_params,
)

D, H, E = 16, 32, 8


def _mesh(n=4):
    devs = jax.devices()[:n]
    return Mesh(np.asarray(devs).reshape(n), ("expert",))


def _setup(top_k=2, T=64, seed=0):
    cfg = MoEConfig(num_experts=E, mlp_dim=H, top_k=top_k,
                    capacity_factor=1.5)
    params = init_moe_params(jax.random.key(seed), D, cfg)
    x = jax.random.normal(jax.random.key(seed + 1), (T, D), jnp.float32)
    return cfg, params, x


def _reference_groups(params, x, cfg, n, cap):
    """The sharded semantics, computed shard-by-shard with the dense path."""
    ys, auxes = [], []
    for xs in jnp.split(x, n, axis=0):
        y, aux = moe_ffn(params, xs, cfg, cap=cap)
        ys.append(y)
        auxes.append(aux)
    return jnp.concatenate(ys, axis=0), jnp.mean(jnp.asarray(auxes))


@pytest.mark.parametrize("top_k", [1, 2])
def test_sharded_equals_dense_groups(top_k):
    cfg, params, x = _setup(top_k)
    mesh = _mesh(4)
    cap = capacity(x.shape[0] // 4, cfg)
    want, want_aux = _reference_groups(params, x, cfg, 4, cap)

    placed = place_moe_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert")))
    got, aux = jax.jit(
        lambda p, v: moe_ffn_sharded(p, v, cfg, mesh))(placed, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux), float(want_aux), rtol=1e-5)


def test_sharded_gradients_equal_dense():
    cfg, params, x = _setup(top_k=2)
    mesh = _mesh(4)
    cap = capacity(x.shape[0] // 4, cfg)

    def loss_sharded(p, v):
        y, aux = moe_ffn_sharded(p, v, cfg, mesh)
        return jnp.sum(y ** 2) + cfg.aux_loss_weight * aux

    def loss_ref(p, v):
        y, aux = _reference_groups(p, v, cfg, 4, cap)
        return jnp.sum(y ** 2) + cfg.aux_loss_weight * aux

    placed = place_moe_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert")))
    g_sh = jax.jit(jax.grad(loss_sharded))(placed, xs)
    g_ref = jax.grad(loss_ref)(params, x)
    for k in params:
        np.testing.assert_allclose(
            np.asarray(g_sh[k]), np.asarray(g_ref[k]), rtol=2e-4, atol=2e-5,
            err_msg=k)


def test_all_to_all_in_hlo():
    cfg, params, x = _setup(top_k=2)
    mesh = _mesh(4)
    placed = place_moe_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert")))
    txt = (jax.jit(lambda p, v: moe_ffn_sharded(p, v, cfg, mesh))
           .lower(placed, xs).compile().as_text())
    assert txt.count("all-to-all") >= 2, "expected dispatch+return all2all"


def test_capacity_drops_overflow_tokens():
    # one expert, capacity 2 of 8 tokens: exactly the first 2 tokens in
    # group order survive, the rest emit zeros (dropped-token semantics)
    cfg = MoEConfig(num_experts=1, mlp_dim=H, top_k=1, capacity_factor=1.0)
    params = init_moe_params(jax.random.key(0), D, cfg)
    x = jax.random.normal(jax.random.key(1), (8, D), jnp.float32)
    y, _ = moe_ffn(params, x, cfg, cap=2)
    y = np.asarray(y)
    assert np.abs(y[:2]).sum() > 0
    np.testing.assert_allclose(y[2:], 0.0, atol=1e-7)


def test_top2_combine_weights_renormalize():
    cfg, params, x = _setup(top_k=2, T=32)
    from paddle_tpu.parallel.moe import route

    dispatch, combine, aux = route(x, params["wg"], cfg,
                                   capacity(32, cfg))
    s = np.asarray(combine.sum(axis=(1, 2)))
    # tokens with both choices kept sum to 1; dropped-one tokens < 1
    assert np.all(s <= 1.0 + 1e-5)
    assert (s > 0.99).mean() > 0.5
    assert float(aux) >= 1.0 - 1e-5  # Switch aux floor at uniform load


def test_moe_transformer_dp_ep_trains():
    """Flagship integration: MoE-LM train step on a {data, expert} mesh —
    loss finite and decreasing, all_to_alls present in the compiled HLO."""
    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    devs = jax.devices()[:8]
    mesh = Mesh(np.asarray(devs).reshape(2, 4), ("data", "expert"))
    cfg = T.TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=2, embed_dim=16, mlp_dim=32,
        max_seq_len=32, remat=False, moe_experts=8, moe_top_k=2)
    params = T.place_params(T.init_params(cfg, jax.random.key(0)), mesh, cfg)
    opt = Adam(learning_rate=1e-2)
    state = opt.init_tree(params)
    step = T.build_train_step(cfg, opt, mesh=mesh)
    ids = jax.device_put(
        jnp.asarray(np.random.default_rng(0).integers(0, 64, (8, 17))),
        NamedSharding(mesh, P("data", None)))

    txt = step.lower(params, state, ids).compile().as_text()
    assert "all-to-all" in txt

    losses = []
    for _ in range(8):
        params, state, loss = step(params, state, ids)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_transformer_dp_ep_tp_trains():
    """Three parallelism axes in ONE mesh: batch over data, experts over
    expert (all_to_all), attention/embedding weights Megatron-sharded
    over model — the composition story, not just pairwise."""
    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    devs = jax.devices()[:8]
    mesh = Mesh(np.asarray(devs).reshape(2, 2, 2),
                ("data", "expert", "model"))
    cfg = T.TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=2, embed_dim=16, mlp_dim=32,
        max_seq_len=32, remat=False, moe_experts=4, moe_top_k=2)
    params = T.place_params(T.init_params(cfg, jax.random.key(0)), mesh, cfg)
    opt = Adam(learning_rate=1e-2)
    state = opt.init_tree(params)
    step = T.build_train_step(cfg, opt, mesh=mesh, zero1=True)
    ids = jax.device_put(
        jnp.asarray(np.random.default_rng(0).integers(0, 64, (8, 17))),
        NamedSharding(mesh, P("data", None)))
    txt = step.lower(params, state, ids).compile().as_text()
    assert "all-to-all" in txt
    losses = []
    for _ in range(6):
        params, state, loss = step(params, state, ids)
        losses.append(float(loss))
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_moe_transformer_dense_path_trains():
    """moe_experts without a mesh: dense dispatch single-device path."""
    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    cfg = T.TransformerConfig(
        vocab_size=64, num_layers=2, num_heads=2, embed_dim=16, mlp_dim=32,
        max_seq_len=32, remat=False, moe_experts=4, moe_top_k=1)
    params = T.init_params(cfg, jax.random.key(0))
    opt = Adam(learning_rate=1e-2)
    state = opt.init_tree(params)
    step = T.build_train_step(cfg, opt)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 64, (4, 17)))
    losses = []
    for _ in range(8):
        params, state, loss = step(params, state, ids)
        losses.append(float(loss))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_moe_layer_trains():
    cfg, params, x = _setup(top_k=2, T=64)
    tgt = jax.random.normal(jax.random.key(9), x.shape, jnp.float32)

    @jax.jit
    def step(p):
        def loss_fn(p):
            y, aux = moe_ffn(p, x, cfg)
            return jnp.mean((y - tgt) ** 2) + cfg.aux_loss_weight * aux

        l, g = jax.value_and_grad(loss_fn)(p)
        return jax.tree.map(lambda w, gw: w - 0.1 * gw, p, g), l

    losses = []
    # 60 steps: the init draw differs across jax PRNG streams, and at 30
    # steps the slowest observed stream sits right on the 0.7 threshold
    # (0.72 on jax 0.4.37 cpu); convergence, not speed, is the claim
    for _ in range(60):
        params, l = step(params)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.7, losses[:3] + losses[-3:]


@pytest.mark.parametrize("top_k", [1, 2])
def test_sort_dispatch_equals_einsum(top_k):
    """dispatch='sort' (scatter/gather by slot id) reproduces the dense
    one-hot einsum path exactly: same routing, same outputs, same
    gradients."""
    import dataclasses

    cfg_e, params, x = _setup(top_k)
    cfg_s = dataclasses.replace(cfg_e, dispatch="sort")

    def loss(cfg):
        def f(p, v):
            y, aux = moe_ffn(p, v, cfg)
            return jnp.sum(y ** 2) + cfg.aux_loss_weight * aux
        return f

    y_e, aux_e = jax.jit(lambda p, v: moe_ffn(p, v, cfg_e))(params, x)
    y_s, aux_s = jax.jit(lambda p, v: moe_ffn(p, v, cfg_s))(params, x)
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_s), float(aux_e), rtol=1e-6)

    g_e = jax.grad(loss(cfg_e))(params, x)
    g_s = jax.grad(loss(cfg_s))(params, x)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_s[k]), np.asarray(g_e[k]),
                                   rtol=2e-4, atol=2e-6, err_msg=k)


def test_sort_dispatch_sharded_equals_einsum_sharded():
    import dataclasses

    cfg_e, params, x = _setup(top_k=2)
    cfg_s = dataclasses.replace(cfg_e, dispatch="sort")
    mesh = _mesh(4)
    placed = place_moe_params(params, mesh)
    xs = jax.device_put(x, NamedSharding(mesh, P("expert")))
    y_e, aux_e = jax.jit(
        lambda p, v: moe_ffn_sharded(p, v, cfg_e, mesh))(placed, xs)
    y_s, aux_s = jax.jit(
        lambda p, v: moe_ffn_sharded(p, v, cfg_s, mesh))(placed, xs)
    np.testing.assert_allclose(np.asarray(y_s), np.asarray(y_e),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(float(aux_s), float(aux_e), rtol=1e-6)
