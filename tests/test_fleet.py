"""The serving fleet end to end (serving/fleet.py + router.py +
health.py), driven through ChaosSchedule and deterministic given seed +
arrival order: replica loss/hang mid-decode loses zero requests and the
survivors' results are token-identical to a fault-free run; overload
sheds with RetryAfter instead of queueing unboundedly; deadlines fail
fast; a rolling weight swap serves continuously and rolls back on a
corrupt servable; fleet telemetry renders through metrics_to_md."""

import threading
import time

import numpy as np
import pytest

import jax

from paddle_tpu.models import transformer as T
from paddle_tpu.resilience.chaos import ChaosSchedule
from paddle_tpu.serving import ServingConfig
from paddle_tpu.serving.engine import ServingEngine
from paddle_tpu.serving.export import export_servable
from paddle_tpu.serving.fleet import (
    FleetConfig,
    LocalReplica,
    build_local_fleet,
    fleet_launch_argv,
)
from paddle_tpu.serving.router import RetryAfter, SwapFailed
from paddle_tpu.telemetry import MemorySink, MetricsRegistry

pytestmark = pytest.mark.fleet


def small_cfg(**kw):
    base = dict(vocab_size=64, num_layers=2, num_heads=2, embed_dim=32,
                mlp_dim=64, max_seq_len=64, remat=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def small_scfg(**kw):
    base = dict(max_slots=2, page_size=4, num_pages=32, max_prompt_len=8,
                max_new_tokens=6, prefill_batch=2, seed=0)
    base.update(kw)
    return ServingConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = small_cfg()
    return cfg, T.init_params(cfg, jax.random.key(1))


def _mixed_requests(rng, n=8):
    """Ragged prompts, alternating greedy/temperature sampling — the
    identity assertions cover both."""
    return [(list(rng.integers(1, 64, size=3 + (i % 4))),
             3 + (i % 3), 0.0 if i % 2 == 0 else 0.8)
            for i in range(n)]


def _serve(model, chaos_spec=None, n_replicas=3, fleet=None,
           registry=None, requests=None):
    cfg, params = model
    reg = registry or MetricsRegistry("fleet_test")
    chaos = (ChaosSchedule(chaos_spec, registry=reg)
             if chaos_spec else None)
    router = build_local_fleet(cfg, params, small_scfg(), n=n_replicas,
                               registry=reg, chaos=chaos, fleet=fleet)
    rids = [router.submit(p, max_new_tokens=n, temperature=t)
            for p, n, t in requests]
    router.run_until_idle()
    res = {r.id: r for r in router.results()}
    return rids, res, router


class TestFailover:
    def test_replica_loss_mid_decode_zero_lost_token_identical(
            self, model, rng_np):
        """The acceptance property: killing a replica with requests in
        flight loses nothing, and every surviving result is token-for-
        token what the fault-free fleet produced — failover is
        invisible in the output stream."""
        reqs = _mixed_requests(rng_np)
        rids0, res0, r0 = _serve(model, None, requests=reqs)
        rids1, res1, r1 = _serve(model, "replica_loss@3:replica=1",
                                 requests=reqs)
        s1 = r1.stats()
        assert s1["requests_lost"] == 0
        assert s1["failovers"] == 1 and s1["requeued"] >= 1
        assert set(res1) == set(rids1)
        assert all(res1[r].finish_reason == "length" for r in rids1)
        assert {r: res0[r].tokens for r in rids0} \
            == {r: res1[r].tokens for r in rids1}
        assert r1.registry.counter("fleet_failovers").value() == 1.0
        assert r1.health.dead() == {1: "crash: chaos replica_loss"}

    def test_replica_hang_detected_and_failed_over(self, model, rng_np):
        """A wedged-but-alive replica (no crash to observe) is caught
        by no-progress detection and failed over, token-identically."""
        reqs = _mixed_requests(rng_np)
        _, res0, _ = _serve(model, None, requests=reqs)
        _, res1, r1 = _serve(model, "replica_hang@3:replica=0",
                             fleet=FleetConfig(hang_rounds=4),
                             requests=reqs)
        s1 = r1.stats()
        assert s1["requests_lost"] == 0 and s1["failovers"] == 1
        assert {i: r.tokens for i, r in res0.items()} \
            == {i: r.tokens for i, r in res1.items()}
        assert list(r1.health.dead()) == [0]
        assert r1.health.dead()[0].startswith("hang:")

    def test_chaos_run_is_deterministic(self, model, rng_np):
        """Same seed + arrival order + chaos spec -> the same trace,
        twice — the replay property every assertion above rests on."""
        reqs = _mixed_requests(rng_np)
        _, a, ra = _serve(model, "replica_loss@3:replica=1",
                          requests=reqs)
        _, b, rb = _serve(model, "replica_loss@3:replica=1",
                          requests=reqs)
        assert {i: r.tokens for i, r in a.items()} \
            == {i: r.tokens for i, r in b.items()}
        assert ra.stats() == rb.stats()

    def test_redial_budget_exhaustion_fails_request(self, model, rng_np):
        """With every replica dead and the RetryPolicy budget spent,
        requests FAIL (finish_reason="error") instead of looping — and
        still count as delivered, never lost."""
        reqs = _mixed_requests(rng_np, n=3)
        _, res, router = _serve(
            model, "replica_loss@1:replica=0",
            n_replicas=1, fleet=FleetConfig(redial_attempts=2),
            requests=reqs)
        s = router.stats()
        assert s["requests_lost"] == 0 and s["alive_replicas"] == 0
        assert len(res) == 3
        assert all(r.finish_reason == "error" for r in res.values())


class TestShedding:
    def test_queue_depth_sheds_with_retry_after(self, model):
        router = build_local_fleet(
            *model, small_scfg(), n=1,
            registry=MetricsRegistry("shed"),
            fleet=FleetConfig(shed_queue_depth=3, retry_after_s=0.75))
        accepted = []
        with pytest.raises(RetryAfter) as ei:
            for _ in range(10):
                accepted.append(router.submit([1, 2, 3],
                                              max_new_tokens=2))
        assert len(accepted) == 3  # bounded, not unbounded queueing
        assert ei.value.retry_after_s == 0.75
        assert "queue_depth" in ei.value.reason
        router.run_until_idle()
        # everything ACCEPTED still completes; sheds were never admitted
        assert {r.id for r in router.results()} == set(accepted)
        s = router.stats()
        assert s["shed"] == 1 and s["requests_lost"] == 0
        assert router.registry.counter("fleet_shed").value(
            reason="queue_depth") == 1.0

    def test_slo_ttft_breach_sheds(self, model):
        reg = MetricsRegistry("shed_slo")
        # a previously observed TTFT distribution far above the SLO
        reg.histogram("serve_ttft_ms", "ttft").observe(500.0)
        router = build_local_fleet(
            *model, small_scfg(), n=1, registry=reg,
            fleet=FleetConfig(slo_p99_ttft_ms=50.0))
        with pytest.raises(RetryAfter, match="slo_ttft"):
            router.submit([1, 2, 3], max_new_tokens=2)

    def test_free_page_watermark_sheds(self, model):
        router = build_local_fleet(
            *model, small_scfg(num_pages=8), n=1,
            registry=MetricsRegistry("shed_pages"),
            fleet=FleetConfig(shed_free_page_frac=0.6))
        # 4+6 tokens -> 3 of 7 usable pages reserved; 4/7 < 0.6 left
        router.submit([1, 2, 3, 4], max_new_tokens=6)
        router.pump()  # route + admit (allocates the pages)
        router.pump()  # probes now see the post-admission free list
        with pytest.raises(RetryAfter, match="pages"):
            router.submit([1, 2, 3, 4], max_new_tokens=6)

    def test_deadline_fails_fast_and_does_not_wedge_queue(self, model):
        clk = {"t": 0.0}
        router = build_local_fleet(
            *model, small_scfg(), n=1,
            registry=MetricsRegistry("ttl"), clock=lambda: clk["t"])
        ra = router.submit([1, 2, 3], max_new_tokens=2, ttl_s=5.0)
        clk["t"] = 10.0  # the head's deadline passes while queued
        rb = router.submit([1, 2, 3], max_new_tokens=2)
        router.run_until_idle()
        res = {r.id: r for r in router.results()}
        assert res[ra].finish_reason == "deadline"
        assert "deadline" in res[ra].metrics["error"]
        # the request BEHIND the expired head was served normally
        assert res[rb].finish_reason == "length"
        s = router.stats()
        assert s["deadline_expired"] == 1 and s["requests_lost"] == 0


class TestPrefixCacheFleet:
    def _requests(self, rng, n=6):
        head = list(rng.integers(1, 64, size=4))
        return [(head + list(rng.integers(1, 64, size=1 + (i % 3))),
                 3 + (i % 3), 0.0) for i in range(n)]

    def test_prefix_cache_identity_across_failover(self, model, rng_np):
        """The fleet acceptance property composed with the tentpole:
        --prefix_cache on/off and a mid-run replica loss all produce
        byte-identical greedy tokens."""
        reqs = self._requests(rng_np)
        runs = {}
        for name, scfg_kw, chaos in (
                ("off", {}, None),
                ("on", {"prefix_cache": True}, None),
                ("on_failover", {"prefix_cache": True},
                 "replica_loss@3:replica=1")):
            reg = MetricsRegistry(f"fleet_prefix_{name}")
            chaos_s = (ChaosSchedule(chaos, registry=reg)
                       if chaos else None)
            router = build_local_fleet(
                *model, small_scfg(**scfg_kw), n=3, registry=reg,
                chaos=chaos_s)
            rids = [router.submit(p, max_new_tokens=n, temperature=t)
                    for p, n, t in reqs]
            router.run_until_idle()
            res = {r.id: r for r in router.results()}
            assert set(res) == set(rids)
            runs[name] = [res[r].tokens for r in rids]
        assert runs["off"] == runs["on"] == runs["on_failover"]

    def test_router_affinity_prefers_warm_replica(self, model, rng_np):
        """Cache-aware routing: a repeat prompt lands on the replica
        whose prefix cache is warm (prefix_peek), instead of pure
        least-loaded round-robin spreading it cold."""
        prompt = list(rng_np.integers(1, 64, size=9))
        router = build_local_fleet(
            *model, small_scfg(prefix_cache=True, max_prompt_len=12),
            n=3, registry=MetricsRegistry("fleet_affinity"))
        router.submit(prompt, max_new_tokens=3, temperature=0.0)
        router.run_until_idle()
        router.results()
        warm = [i for i, rep in enumerate(router.replicas)
                if rep.engine.cache.prefix.cached_pages > 0]
        assert len(warm) == 1  # exactly one replica computed the prompt
        rep = router.replicas[warm[0]]
        assert rep.prefix_peek(prompt) == 8  # 2 full pages of 4
        before_hits = rep.engine.cache.prefix.hits
        for _ in range(3):  # repeats must all ride the warm cache
            router.submit(prompt, max_new_tokens=3, temperature=0.0)
            router.run_until_idle()
        router.results()
        assert rep.engine.cache.prefix.hits == before_hits + 3
        others = [r for i, r in enumerate(router.replicas)
                  if i != warm[0]]
        assert all(r.engine.cache.prefix.cached_pages == 0
                   for r in others)

    def test_probe_counts_reclaimable_pages_as_free(self, model, rng_np):
        """A warm (idle) cache must not read as memory pressure: the
        health probe's free_pages includes reclaimable cached pages, so
        shed_free_page_frac only fires on pages active sequences pin."""
        prompt = list(rng_np.integers(1, 64, size=9))
        router = build_local_fleet(
            *model, small_scfg(prefix_cache=True, max_prompt_len=12),
            n=1, registry=MetricsRegistry("fleet_probe"))
        router.submit(prompt, max_new_tokens=3, temperature=0.0)
        router.run_until_idle()
        router.results()
        rep = router.replicas[0]
        probe = rep.probe()
        assert rep.engine.cache.prefix.cached_pages == 2
        assert rep.engine.cache.allocator.free_pages == \
            probe.total_pages - 2
        assert probe.free_pages == probe.total_pages  # fully idle


class TestWeightSwap:
    def test_rolling_swap_serves_continuously(self, model, tmp_path):
        """Requests stream in while the swap rolls replica by replica:
        no submit fails, every request completes, and post-swap tokens
        come from the NEW weights."""
        cfg, params = model
        params2 = T.init_params(cfg, jax.random.key(2))
        sv = export_servable(str(tmp_path / "sv"), cfg, params2)
        scfg = small_scfg()
        # hang detection stays ON during the swap: a held (mid-swap)
        # replica's frozen progress must NOT read as a hang — the
        # health monitor skips held replicas (regression)
        router = build_local_fleet(cfg, params, scfg, n=2,
                                   registry=MetricsRegistry("swap"),
                                   fleet=FleetConfig(hang_rounds=4))
        router.start()
        try:
            rids = []

            def feeder():
                for i in range(16):
                    rids.append(router.submit(
                        [5, 6, (i % 50) + 1], max_new_tokens=3))
                    time.sleep(0.005)

            t = threading.Thread(target=feeder)
            t.start()
            report = router.swap_servable(sv)
            t.join()
            got = router.results(n=16, timeout=60.0)
        finally:
            router.stop()
        assert report == {0: "swapped", 1: "swapped"}
        assert len(got) == 16
        assert all(r.finish_reason == "length" for r in got)
        s = router.stats()
        assert s["requests_lost"] == 0 and s["swaps"] == 1
        assert router.health.dead() == {}  # no false hang verdicts
        assert s["alive_replicas"] == 2
        # a post-swap request serves the new weights
        ref = ServingEngine(cfg, params2, scfg).generate(
            [[5, 6, 7]], max_new_tokens=3)[0].tokens
        router2 = build_local_fleet(cfg, params, scfg, n=2,
                                    registry=MetricsRegistry("swap2"))
        router2.swap_servable(sv)
        rid = router2.submit([5, 6, 7], max_new_tokens=3)
        router2.run_until_idle()
        assert {r.id: r.tokens for r in router2.results()}[rid] == ref

    def test_corrupt_servable_rolls_back(self, model, tmp_path):
        """servable_corrupt chaos poisons the artifact before the 2nd
        per-replica load: sha256 verification refuses it, the already-
        swapped replica 0 rolls back, and the whole fleet keeps serving
        the OLD weights — never a mix."""
        cfg, params = model
        params2 = T.init_params(cfg, jax.random.key(2))
        sv = export_servable(str(tmp_path / "sv"), cfg, params2)
        scfg = small_scfg()
        reg = MetricsRegistry("swap_corrupt")
        sink = MemorySink()
        reg.add_sink(sink)
        router = build_local_fleet(
            cfg, params, scfg, n=2, registry=reg,
            chaos=ChaosSchedule("servable_corrupt@1", registry=reg))
        with pytest.raises(SwapFailed, match="hash mismatch"):
            router.swap_servable(sv)
        s = router.stats()
        assert s["swap_rollbacks"] == 1 and s["swaps"] == 0
        # BOTH replicas serve the old weights (replica 0 was reverted):
        # two concurrent submits load-balance one onto each
        old = ServingEngine(cfg, params, scfg).generate(
            [[5, 6, 7]], max_new_tokens=3)[0].tokens
        rids = [router.submit([5, 6, 7], max_new_tokens=3)
                for _ in range(2)]
        router.run_until_idle()
        got = {r.id: r.tokens for r in router.results()}
        assert [got[r] for r in rids] == [old, old]
        events = [r for r in sink.records if r.get("kind") == "fleet"]
        rb = [r for r in events if r.get("event") == "swap_rollback"]
        assert len(rb) == 1 and rb[0]["rolled_back"] == [0]

    def test_smoke_mismatch_rolls_back(self, model, tmp_path,
                                       monkeypatch):
        """A servable that loads clean but fails its smoke decode (the
        engine does not reproduce the model's own greedy continuation)
        is rolled back everywhere."""
        cfg, params = model
        params2 = T.init_params(cfg, jax.random.key(2))
        sv = export_servable(str(tmp_path / "sv"), cfg, params2)
        scfg = small_scfg()
        router = build_local_fleet(cfg, params, scfg, n=2,
                                   registry=MetricsRegistry("swap_smoke"))
        real = LocalReplica.smoke_decode

        def lying_smoke(self, prompt, n):
            toks = real(self, prompt, n)
            return [(t + 1) % 64 for t in toks] if self.index == 1 \
                else toks

        monkeypatch.setattr(LocalReplica, "smoke_decode", lying_smoke)
        with pytest.raises(SwapFailed, match="smoke decode"):
            router.swap_servable(sv)
        monkeypatch.undo()
        old = ServingEngine(cfg, params, scfg).generate(
            [[5, 6, 7]], max_new_tokens=3)[0].tokens
        rids = [router.submit([5, 6, 7], max_new_tokens=3)
                for _ in range(2)]
        router.run_until_idle()
        got = {r.id: r.tokens for r in router.results()}
        assert [got[r] for r in rids] == [old, old]


class TestRouterLifecycle:
    def test_loop_crash_fails_pending_and_refuses_submit(self, model):
        router = build_local_fleet(*model, small_scfg(), n=1,
                                   registry=MetricsRegistry("crash"))
        boom = RuntimeError("injected router fault")

        def bad_pump():
            raise boom

        router.pump = bad_pump
        router.start()
        try:
            with pytest.raises(RuntimeError,
                               match="router loop crashed") as ei:
                router.results(n=1, timeout=30.0)
            assert ei.value.__cause__ is boom
            with pytest.raises(RuntimeError, match="submit refused"):
                router.submit([1, 2, 3], max_new_tokens=2)
        finally:
            router.stop()

    def test_submit_after_stop_raises(self, model):
        """A stopped background router refuses submits (nothing will
        ever pump them) — the engine's dead-engine contract."""
        router = build_local_fleet(*model, small_scfg(), n=1,
                                   registry=MetricsRegistry("stopped"))
        router.start()
        router.stop()
        with pytest.raises(RuntimeError, match="stopped"):
            router.submit([1, 2, 3], max_new_tokens=2)
        # sync drive still works after a restart
        router.start()
        try:
            rid = router.submit([1, 2, 3], max_new_tokens=2)
            assert router.results(n=1, timeout=60.0)[0].id == rid
        finally:
            router.stop()

    def test_fleet_records_render_in_metrics_to_md(self, model,
                                                   tmp_path, capsys):
        import json
        import sys

        reqs = [([1, 2, 3], 2, 0.0) for _ in range(4)]
        reg = MetricsRegistry("md")
        sink = MemorySink()
        reg.add_sink(sink)
        _, _, router = _serve(model, "replica_loss@2:replica=0",
                              n_replicas=2, registry=reg, requests=reqs)
        router.emit_summary()
        events = [r for r in sink.records if r.get("kind") == "fleet"]
        assert {r["event"] for r in events} == {"replica_down",
                                                "summary"}
        path = tmp_path / "m.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in sink.records)
                        + "\n")
        sys.path.insert(0, "tools")
        try:
            import metrics_to_md
        finally:
            sys.path.pop(0)
        metrics_to_md.main([str(path)])
        out = capsys.readouterr().out
        assert "## Serving fleet" in out
        assert "replica_down" in out and "re-queued" in out
        assert "requests lost: 0" in out

    def test_launch_argv_shape(self):
        argv = fleet_launch_argv(3, "/tmp/sv", "--max_new_tokens", 8)
        assert "--serving" in argv and "--nproc" in argv
        assert argv[argv.index("--nproc") + 1] == "3"
        assert argv[argv.index("--servable") + 1] == "/tmp/sv"


class TestCliFleetMode:
    def test_main_with_replicas_matches_single_engine(self, monkeypatch,
                                                      capsys):
        """`python -m paddle_tpu.serving --replicas 2` serves the same
        greedy tokens the single-engine CLI serves (placement never
        changes output)."""
        import io

        from paddle_tpu.serving.__main__ import main

        lines = "5 17 3\n9 9 9 9\n"
        outs = []
        for replicas in ("1", "2"):
            monkeypatch.setattr("sys.stdin", io.StringIO(lines))
            rc = main(["--random", "--vocab", "64", "--embed", "32",
                       "--max_new_tokens", "4", "--seed", "7",
                       "--replicas", replicas])
            assert rc == 0
            outs.append(capsys.readouterr().out)
        assert outs[0] == outs[1]
        got = [l for l in outs[1].splitlines() if l.strip()]
        assert len(got) == 2
        assert got[0].startswith("0:") and got[1].startswith("1:")
