"""Fluid LoD sequence + RNN ops: SequenceBatch scope values through the
segment-jitted executor, kernels checked against ragged numpy references
and the generic vjp backward."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu import fluid
from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.fluid import framework, layers, ops as O

KEY = jax.random.key(0)


def _seq(rng, b=3, t=5, d=4, lengths=(5, 3, 1)):
    data = rng.normal(size=(b, t, d)).astype(np.float32)
    sb = SequenceBatch(data=jnp.asarray(data),
                       length=jnp.asarray(lengths, jnp.int32))
    return sb, data, np.asarray(lengths)


def run(name, ins, attrs=None):
    return O.get_kernel(name)(ins, attrs or {}, KEY)


def test_sequence_pool_modes(rng_np):
    sb, data, lens = _seq(rng_np)
    out = np.asarray(run("sequence_pool", {"X": [sb]},
                         {"pooltype": "AVERAGE"})["Out"][0])
    for i, l in enumerate(lens):
        np.testing.assert_allclose(out[i], data[i, :l].mean(0), rtol=1e-5)
    last = np.asarray(run("sequence_pool", {"X": [sb]},
                          {"pooltype": "LAST"})["Out"][0])
    for i, l in enumerate(lens):
        np.testing.assert_allclose(last[i], data[i, l - 1], rtol=1e-6)
    mx = np.asarray(run("sequence_pool", {"X": [sb]},
                        {"pooltype": "MAX"})["Out"][0])
    for i, l in enumerate(lens):
        np.testing.assert_allclose(mx[i], data[i, :l].max(0), rtol=1e-6)


def test_sequence_softmax_masks_padding(rng_np):
    sb, data, lens = _seq(rng_np, d=1)
    out = run("sequence_softmax", {"X": [sb]})["Out"][0]
    probs = np.asarray(out.data)[..., 0]
    for i, l in enumerate(lens):
        np.testing.assert_allclose(probs[i, :l].sum(), 1.0, rtol=1e-5)
        assert np.all(probs[i, l:] == 0)


def test_seq_expand_and_concat(rng_np):
    sb, data, lens = _seq(rng_np)
    x = rng_np.normal(size=(3, 4)).astype(np.float32)
    out = run("seq_expand", {"X": [jnp.asarray(x)], "Y": [sb]})["Out"][0]
    assert isinstance(out, SequenceBatch)
    for i, l in enumerate(lens):
        for t in range(l):
            np.testing.assert_allclose(np.asarray(out.data)[i, t], x[i],
                                       rtol=1e-6)
    cat = run("sequence_concat", {"X": [sb, sb]})["Out"][0]
    assert int(cat.length[0]) == 2 * lens[0]


def test_lstm_gru_ops_match_cells(rng_np):
    _lstm_gru_case(rng_np)  # exact f32 comparisons (f32 is the default)


def _lstm_gru_case(rng_np):
    sb, data, lens = _seq(rng_np, d=4)
    d_in, d_h = 4, 6
    wx = rng_np.normal(size=(d_in, 4 * d_h)).astype(np.float32) * 0.3
    wh = rng_np.normal(size=(d_h, 4 * d_h)).astype(np.float32) * 0.3
    out = run("lstm", {"Input": [sb], "WeightX": [jnp.asarray(wx)],
                       "WeightH": [jnp.asarray(wh)]})
    hidden = out["Hidden"][0]
    assert hidden.data.shape == (3, 5, d_h)
    # LastHidden equals the hidden at each row's final valid step
    for i, l in enumerate(lens):
        np.testing.assert_allclose(np.asarray(out["LastHidden"][0])[i],
                                   np.asarray(hidden.data)[i, l - 1],
                                   rtol=1e-5)
    # single-step unit agrees with step 0 of the full op
    xw0 = data[:, 0] @ wx
    h0 = np.zeros((3, d_h), np.float32)
    unit = run("lstm_unit", {"X": [jnp.asarray(xw0)],
                             "HPrev": [jnp.asarray(h0)],
                             "CPrev": [jnp.asarray(h0)],
                             "WeightH": [jnp.asarray(wh)]})
    np.testing.assert_allclose(np.asarray(unit["H"][0]),
                               np.asarray(hidden.data)[:, 0], rtol=1e-5)


def test_sequence_ops_through_executor(rng_np):
    """lod feed -> sequence_conv -> sequence_pool -> mean, with backward."""
    framework.reset_default_programs()
    sb, data, lens = _seq(rng_np)
    w = rng_np.normal(size=(3 * 4, 8)).astype(np.float32)

    x = layers.data("xseq", [5, 4], append_batch_size=False, lod_level=1)
    block = framework.default_main_program().global_block()
    wv = block.create_var(name="w", shape=(12, 8), persistable=True)
    conv = block.create_var(name="conv", shape=(3, 5, 8), lod_level=1)
    block.append_op("sequence_conv", {"X": ["xseq"], "Filter": ["w"]},
                    {"Out": ["conv"]}, {"contextLength": 3})
    pooled = block.create_var(name="pooled", shape=(3, 8))
    block.append_op("sequence_pool", {"X": ["conv"]}, {"Out": ["pooled"]},
                    {"pooltype": "SUM"})
    loss = layers.mean(pooled)
    block.vars["w"].stop_gradient = False
    grads = fluid.append_backward_ops(loss, parameter_list=["w"])
    exe = fluid.Executor()
    res = exe.run(feed={"xseq": sb, "w": w},
                  fetch_list=[pooled, loss, grads[0][1]])
    assert res[0].shape == (3, 8)
    assert np.all(np.isfinite(res[2])) and res[2].shape == w.shape
    assert np.abs(res[2]).sum() > 0
