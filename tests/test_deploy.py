"""The train→serve control plane (paddle_tpu/deploy): the SLO
autoscaler's hysteresis band edges, cooldown suppression, min/max
clamps and fake-clock determinism; scale-down draining a victim with
requests in flight through the failover path with zero loss and
token-identical output; the deployment controller's export→verify→
swap→ledger loop, including a chaos-corrupted rollout that rolls back
cleanly and redeploys; checkpoint retention GC that never eats the
newest valid checkpoint or one pinned mid-export; the pool arbiter's
trainer floor; the client back-off loop; and the crash contract on
every background loop."""

import os
import sys
import time

import numpy as np
import pytest

import jax

from paddle_tpu.deploy import (
    AutoscalePolicy,
    DeploymentController,
    PoolArbiter,
    SloAutoscaler,
)
from paddle_tpu.models import transformer as T
from paddle_tpu.resilience.chaos import ChaosSchedule
from paddle_tpu.resilience.policy import RetryPolicy
from paddle_tpu.serving import ServingConfig
from paddle_tpu.serving.client import backoff_submit
from paddle_tpu.serving.fleet import build_local_fleet
from paddle_tpu.serving.router import RetryAfter
from paddle_tpu.telemetry import MemorySink, MetricsRegistry
from paddle_tpu.trainer import checkpoint as ckpt

pytestmark = pytest.mark.deploy


def small_cfg(**kw):
    base = dict(vocab_size=64, num_layers=2, num_heads=2, embed_dim=32,
                mlp_dim=64, max_seq_len=64, remat=False)
    base.update(kw)
    return T.TransformerConfig(**base)


def small_scfg(**kw):
    base = dict(max_slots=2, page_size=4, num_pages=32, max_prompt_len=8,
                max_new_tokens=6, prefill_batch=2, seed=0)
    base.update(kw)
    return ServingConfig(**base)


@pytest.fixture(scope="module")
def model():
    cfg = small_cfg()
    return cfg, T.init_params(cfg, jax.random.key(1))


def save_model_checkpoint(ckpt_dir, params, pass_id=0, **kw):
    flat = {}

    def flatten(d, prefix=""):
        for k, v in d.items():
            if isinstance(v, dict):
                flatten(v, f"{prefix}{k}/")
            else:
                flat[f"{prefix}{k}"] = np.asarray(v)

    flatten(params)
    return ckpt.save_checkpoint(ckpt_dir, pass_id, flat, **kw)


class FakeRouter:
    """The autoscaler's router surface without a fleet: counts
    membership, records actions — the policy tests drive it with a
    scripted signal stream."""

    def __init__(self):
        self.registry = MetricsRegistry("deploy_test")
        self.alive = 1
        self.added = []
        self.removed = []

    def add_replica(self, factory):
        idx = self.alive
        self.alive += 1
        self.added.append(idx)
        return idx

    def pick_victim(self):
        return self.alive - 1

    def remove_replica(self, idx, reason=""):
        self.alive -= 1
        self.removed.append((idx, reason))
        return {"replica": idx, "requeued": 0}


def make_autoscaler(policy, sigs, clk):
    """An autoscaler over a FakeRouter fed from the mutable ``sigs``
    dict under the fake clock ``clk`` — alive tracks the fake fleet."""
    router = FakeRouter()

    def rollup():
        return {**sigs, "alive": router.alive}

    return router, SloAutoscaler(router, policy, clock=lambda: clk["t"],
                                 rollup=rollup)


BAND = AutoscalePolicy(min_replicas=1, max_replicas=4,
                       up_queue_per_replica=4.0,
                       down_queue_per_replica=0.5, idle_hold_s=5.0,
                       cooldown_up_s=1.0, cooldown_down_s=2.0)


class TestAutoscalePolicy:
    def test_band_inversion_refused(self):
        with pytest.raises(Exception, match="band inverted"):
            AutoscalePolicy(up_queue_per_replica=2.0,
                            down_queue_per_replica=2.0)
        with pytest.raises(Exception, match="band inverted"):
            AutoscalePolicy(up_p99_ttft_ms=100.0, down_p99_ttft_ms=100.0)
        with pytest.raises(Exception, match="clamp inverted"):
            AutoscalePolicy(min_replicas=3, max_replicas=2)

    def test_band_boundaries(self):
        """The band edges exactly: queue/replica AT the high edge
        scales up (inclusive — the SLO is already breached there),
        inside the gap holds in both directions, AT the low edge counts
        as idle (inclusive) and scales down once sustained."""
        clk = {"t": 0.0}
        sigs = {"queue_depth": 4, "shed": 0}
        router, asc = make_autoscaler(BAND, sigs, clk)
        # 4 queued / 1 alive = 4.0 == up edge -> breach
        assert asc.step()["event"] == "scale_up"
        assert router.alive == 2
        # 7.9/2 = 3.95 just under the edge, above 0.5*2=1 low edge:
        # inside the band — no action EVER, regardless of time
        sigs["queue_depth"] = 7
        for _ in range(10):
            clk["t"] += 10.0
            assert asc.step()["event"] == "hold"
        # 1 queued / 2 alive = 0.5 == low edge -> idle (inclusive);
        # sustained past idle_hold_s + cooldown -> scale_down
        sigs["queue_depth"] = 1
        assert asc.step()["event"] == "hold"  # idle clock starts
        clk["t"] += BAND.idle_hold_s + 0.1
        assert asc.step()["event"] == "scale_down"
        assert router.removed[0][0] == 1

    def test_idle_blip_resets_the_hold_clock(self):
        clk = {"t": 0.0}
        sigs = {"queue_depth": 0, "shed": 0}
        router, asc = make_autoscaler(BAND, sigs, clk)
        router.alive = 2
        assert asc.step()["event"] == "hold"  # idle since t=0
        clk["t"] += 4.9  # almost held long enough...
        sigs["queue_depth"] = 4  # ...but load returns (2/replica: in-band)
        assert asc.step()["event"] == "hold"
        sigs["queue_depth"] = 0
        clk["t"] += 4.9  # idle again, but the clock restarted
        assert asc.step()["event"] == "hold"
        clk["t"] += BAND.idle_hold_s
        assert asc.step()["event"] == "scale_down"

    def test_shed_is_always_a_breach(self):
        """A shed IS the SLO saying no — the cumulative counter rising
        between rounds scales up even with a quiet queue."""
        clk = {"t": 0.0}
        sigs = {"queue_depth": 0, "shed": 3}
        router, asc = make_autoscaler(BAND, sigs, clk)
        assert asc.step()["event"] == "scale_up"  # 3 sheds since start
        clk["t"] += 10.0
        assert asc.step()["event"] == "hold"  # counter flat now
        sigs["shed"] = 5
        clk["t"] += 10.0
        assert asc.step()["event"] == "scale_up"

    def test_cooldown_suppresses_consecutive_actions(self):
        clk = {"t": 0.0}
        sigs = {"queue_depth": 50, "shed": 0}
        router, asc = make_autoscaler(BAND, sigs, clk)
        assert asc.step()["event"] == "scale_up"
        rec = asc.step()  # still breached, but inside cooldown_up_s
        assert rec["event"] == "hold" and "cooldown" in rec["reason"]
        clk["t"] += BAND.cooldown_up_s + 0.01
        assert asc.step()["event"] == "scale_up"
        # the down side: a policy whose down cooldown OUTLASTS the idle
        # hold — sustained idle alone is not enough until the cooldown
        # from the last action expires
        slow = AutoscalePolicy(min_replicas=1, max_replicas=4,
                               up_queue_per_replica=4.0,
                               down_queue_per_replica=0.5,
                               idle_hold_s=1.0, cooldown_up_s=0.5,
                               cooldown_down_s=10.0)
        clk = {"t": 0.0}
        sigs = {"queue_depth": 50, "shed": 0}
        router, asc = make_autoscaler(slow, sigs, clk)
        assert asc.step()["event"] == "scale_up"  # action at t=0
        sigs["queue_depth"] = 0
        clk["t"] = 2.0
        assert asc.step()["event"] == "hold"  # idle clock starts (t=2)
        clk["t"] = 3.5  # idle 1.5s >= hold 1.0s, but t < cooldown 10s
        rec = asc.step()
        assert rec["event"] == "hold" and "cooldown" in rec["reason"]
        clk["t"] = 10.5  # cooldown expired, idle still sustained
        assert asc.step()["event"] == "scale_down"

    def test_min_max_clamps(self):
        clk = {"t": 0.0}
        sigs = {"queue_depth": 100, "shed": 0}
        router, asc = make_autoscaler(BAND, sigs, clk)
        for _ in range(10):
            clk["t"] += BAND.cooldown_up_s + 0.1
            asc.step()
        assert router.alive == BAND.max_replicas
        rec = asc.step()
        assert rec["event"] == "hold" and "max_replicas" in rec["reason"]
        sigs["queue_depth"] = 0
        for _ in range(10):
            clk["t"] += BAND.idle_hold_s + BAND.cooldown_down_s + 0.1
            asc.step()
        assert router.alive == BAND.min_replicas
        clk["t"] += BAND.idle_hold_s + BAND.cooldown_down_s + 0.1
        rec = asc.step()
        assert rec["event"] == "hold" and "min_replicas" in rec["reason"]

    def test_fake_clock_determinism(self):
        """The acceptance property: the same (probe, clock) stream
        replays the SAME action sequence — decisions are a pure
        function of the stream, not of wall clock or iteration
        timing."""
        stream = []
        rng = np.random.default_rng(7)
        t = 0.0
        for _ in range(60):
            t += float(rng.uniform(0.1, 2.0))
            stream.append((t, {"queue_depth": int(rng.integers(0, 20)),
                               "shed": int(rng.integers(0, 3))}))
        # cumulative shed counter, like the real rollup
        acc = 0
        for _, sig in stream:
            acc += sig["shed"]
            sig["shed"] = acc

        def replay():
            clk = {"t": 0.0}
            sigs = {}
            router, asc = make_autoscaler(BAND, sigs, clk)
            history = []
            for t, sig in stream:
                clk["t"] = t
                sigs.update(sig)
                rec = asc.step()
                history.append((rec["event"], rec.get("replica"),
                                rec["reason"]))
            return history, asc.history()

        h1, a1 = replay()
        h2, a2 = replay()
        assert h1 == h2
        assert [(a["event"], a["replica"]) for a in a1] \
            == [(a["event"], a["replica"]) for a in a2]
        assert any(e == "scale_up" for e, _, _ in h1)  # stream not trivial

    def test_arbiter_floor_turns_scale_up_into_hold(self):
        clk = {"t": 0.0}
        sigs = {"queue_depth": 100, "shed": 0}
        router = FakeRouter()
        arb = PoolArbiter(total_hosts=2, serving_hosts=1,
                          min_trainer_hosts=1)
        asc = SloAutoscaler(router, BAND, arbiter=arb,
                            clock=lambda: clk["t"],
                            rollup=lambda: {**sigs,
                                            "alive": router.alive})
        rec = asc.step()  # breach, but the trainer is at its floor
        assert rec["event"] == "hold" and "pool exhausted" in rec["reason"]
        assert router.alive == 1 and arb.snapshot()["serving_hosts"] == 1

    def test_loop_crash_contract(self):
        router = FakeRouter()

        def boom():
            raise RuntimeError("rollup died")

        asc = SloAutoscaler(router, BAND, rollup=boom)
        asc.start(poll_s=0.01)
        deadline = time.monotonic() + 5.0
        while asc._loop_error_now() is None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        asc.stop()
        with pytest.raises(RuntimeError, match="loop crashed"):
            asc.step()
        assert router.registry.counter(
            "serve_loop_crashes").value() >= 1.0


class TestPoolArbiter:
    def test_borrow_return_and_floor(self):
        posts = []

        class Elastic:
            def post_host_loss(self, **kw):
                posts.append(("host_loss", kw))

            def post_scale_up(self, **kw):
                posts.append(("scale_up", kw))

        arb = PoolArbiter(total_hosts=4, serving_hosts=1,
                          min_trainer_hosts=1, elastic=Elastic(),
                          devices_per_host=2)
        assert arb.acquire_serving_host("ramp")  # trainer 3 -> 2
        assert arb.acquire_serving_host("ramp")  # trainer 2 -> 1
        assert not arb.acquire_serving_host("ramp")  # at the floor
        assert arb.snapshot() == {"total_hosts": 4, "serving_hosts": 3,
                                  "trainer_hosts": 1,
                                  "min_trainer_hosts": 1}
        assert arb.release_serving_host("trough")  # trainer 1 -> 2
        # the trainer mesh saw a planned shrink per borrow (dp counts
        # DEVICES: hosts * devices_per_host) and a reshard-up on return
        assert [p[0] for p in posts] == ["host_loss", "host_loss",
                                        "scale_up"]
        assert posts[0][1]["new_data_parallel"] == 4  # 2 hosts * 2
        assert posts[2][1]["new_data_parallel"] == 4
        events = [s["event"] for s in arb.shifts()]
        assert events == ["pool_borrow", "pool_borrow", "pool_return"]


class TestScaleDrain:
    def test_drain_victim_with_inflight_zero_loss(self, model, rng_np):
        """The scale-down acceptance property: retiring a replica with
        requests IN FLIGHT re-queues them through the failover path —
        nothing lost, every result token-identical to an undisturbed
        fleet."""
        cfg, params = model
        reqs = [(list(rng_np.integers(1, 64, size=3 + (i % 4))),
                 3 + (i % 3), 0.0 if i % 2 == 0 else 0.8)
                for i in range(8)]

        def run(scale_down_after):
            reg = MetricsRegistry("deploy_drain")
            router = build_local_fleet(cfg, params, small_scfg(), n=2,
                                       registry=reg)
            for p, n, t in reqs:
                router.submit(p, max_new_tokens=n, temperature=t)
            removed = None
            pumps = 0
            while router.pump() or router.stats()["pending"]:
                pumps += 1
                if pumps == scale_down_after and removed is None:
                    victim = router.pick_victim()
                    removed = router.remove_replica(victim,
                                                    reason="test drain")
            router.run_until_idle()
            return ({r.id: r.tokens for r in router.results()},
                    router.stats(), removed)

        base, base_stats, _ = run(scale_down_after=None)
        got, stats, removed = run(scale_down_after=3)
        assert removed is not None and removed["requeued"] >= 1
        assert stats["requests_lost"] == 0
        assert stats["requeued"] >= removed["requeued"]
        assert got == base  # drain invisible in the output stream
        assert stats["alive_replicas"] == 1

    def test_remove_last_replica_refused(self, model):
        cfg, params = model
        router = build_local_fleet(cfg, params, small_scfg(), n=1,
                                   registry=MetricsRegistry("one"))
        with pytest.raises(Exception, match="last alive replica"):
            router.remove_replica(0)

    def test_added_replica_serves_and_is_counted(self, model, rng_np):
        from paddle_tpu.serving.fleet import clone_replica

        cfg, params = model
        reg = MetricsRegistry("deploy_add")
        router = build_local_fleet(cfg, params, small_scfg(), n=1,
                                   registry=reg)
        idx = router.add_replica(
            lambda i, src: clone_replica(i, src, registry=reg))
        assert idx == 1 and router.stats()["alive_replicas"] == 2
        for i in range(6):
            router.submit(list(rng_np.integers(1, 64, size=4)),
                          max_new_tokens=3)
        router.run_until_idle()
        assert len(router.results()) == 6
        assert router.stats()["requests_lost"] == 0
        # both replicas took work (the new one is really in rotation)
        assert reg.counter("fleet_replicas_added").value() == 1.0


class TestDeploymentController:
    def test_rollout_then_noop_then_new_checkpoint(self, model, tmp_path):
        cfg, params = model
        reg = MetricsRegistry("deploy_ctl")
        router = build_local_fleet(cfg, params, small_scfg(), n=2,
                                   registry=reg)
        ctl = DeploymentController(
            str(tmp_path / "ckpts"), str(tmp_path / "servable"),
            router, cfg, registry=reg)
        assert ctl.poll() is None  # nothing to deploy yet
        save_model_checkpoint(str(tmp_path / "ckpts"), params)
        rec = ctl.poll()
        assert rec["outcome"] == "deployed" and rec["attempt"] == 1
        assert rec["export_ms"] > 0 and rec["swap_ms"] > 0
        assert ctl.deployed_uuid() is not None
        assert ctl.poll() is None  # same checkpoint: nothing to do
        assert router.stats()["swaps"] == 1
        # a NEW checkpoint deploys over the old one
        save_model_checkpoint(str(tmp_path / "ckpts"), params, pass_id=1)
        rec2 = ctl.poll()
        assert rec2["outcome"] == "deployed"
        assert rec2["uuid"] != rec["uuid"]
        assert [r["outcome"] for r in ctl.ledger()] \
            == ["deployed", "deployed"]

    def test_corrupt_rollout_rolls_back_then_redeploys(
            self, model, tmp_path, rng_np):
        """The chaos property: a servable corrupted in flight is
        refused at swap, every replica rolls back to the old weights
        (still serving, token-identical), and the next poll re-exports
        and succeeds."""
        cfg, params = model
        reg = MetricsRegistry("deploy_chaos")
        chaos = ChaosSchedule("servable_corrupt@0", registry=reg)
        router = build_local_fleet(cfg, params, small_scfg(), n=2,
                                   registry=reg, chaos=chaos)
        prompt = list(rng_np.integers(1, 64, size=5))
        router.submit(prompt, max_new_tokens=4)
        router.run_until_idle()
        want = router.results()[0].tokens
        ctl = DeploymentController(
            str(tmp_path / "ckpts"), str(tmp_path / "servable"),
            router, cfg, registry=reg)
        save_model_checkpoint(str(tmp_path / "ckpts"), params)
        rec = ctl.poll()
        assert rec["outcome"] == "rolled_back" and rec["attempt"] == 1
        assert "hash mismatch" in rec["error"]
        assert ctl.deployed_uuid() is None
        # the fleet kept serving the old weights, token-identically
        router.submit(prompt, max_new_tokens=4)
        router.run_until_idle()
        assert router.results()[0].tokens == want
        rec2 = ctl.poll()  # fresh export, chaos spent -> deploys
        assert rec2["outcome"] == "deployed" and rec2["attempt"] == 2
        assert reg.counter("deploys_rolled_back").value() == 1.0
        assert reg.counter("deploys_succeeded").value() == 1.0
        # same weights: the rollout itself must be token-invisible
        router.submit(prompt, max_new_tokens=4)
        router.run_until_idle()
        assert router.results()[0].tokens == want

    def test_poisoned_checkpoint_skipped_after_max_attempts(
            self, model, tmp_path):
        cfg, params = model
        reg = MetricsRegistry("deploy_poison")
        chaos = ChaosSchedule(
            "servable_corrupt@0,servable_corrupt@1", registry=reg)
        router = build_local_fleet(cfg, params, small_scfg(), n=1,
                                   registry=reg, chaos=chaos)
        ctl = DeploymentController(
            str(tmp_path / "ckpts"), str(tmp_path / "servable"),
            router, cfg, registry=reg, max_attempts=2)
        save_model_checkpoint(str(tmp_path / "ckpts"), params)
        assert ctl.poll()["outcome"] == "rolled_back"
        assert ctl.poll()["outcome"] == "rolled_back"
        assert ctl.poll() is None  # marked bad: no third attempt
        # ...but a NEW checkpoint is not blocked by the poisoned one
        save_model_checkpoint(str(tmp_path / "ckpts"), params, pass_id=1)
        assert ctl.poll()["outcome"] == "deployed"

    def test_loop_crash_contract(self, model, tmp_path):
        cfg, params = model
        router = build_local_fleet(cfg, params, small_scfg(), n=1,
                                   registry=MetricsRegistry("ctl_crash"))
        ctl = DeploymentController(
            "/nonexistent", str(tmp_path / "s"), router, cfg)
        assert ctl.poll() is None  # no checkpoint dir: benign, no crash

        def boom(*a, **kw):
            raise RuntimeError("watch died")

        ctl2 = DeploymentController(
            str(tmp_path / "ckpts"), str(tmp_path / "s2"), router, cfg)
        ctl2.poll = boom  # crash the loop body
        ctl2.start(poll_s=0.01)
        deadline = time.monotonic() + 5.0
        while ctl2._loop_error_now() is None \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        ctl2.stop()
        assert ctl2._loop_error_now() is not None


class TestRetentionGC:
    def test_prune_never_deletes_newest_valid(self, tmp_path):
        """Retention by count must not outrank recoverability: when
        every younger checkpoint is corrupt, the newest VALID one
        survives the prune regardless of age."""
        d = str(tmp_path)
        for i in range(4):
            ckpt.save_checkpoint(d, i, {"w": np.full(2, i, np.float32)},
                                 keep_last=0)  # no GC while arranging
        for i in (1, 2, 3):  # corrupt everything younger than pass-0
            with open(os.path.join(d, f"pass-{i:05d}", "params.npz"),
                      "ab") as f:
                f.write(b"garbage")
        removed = ckpt.prune_old(d, keep_last=1)
        left = sorted(x for x in os.listdir(d) if x.startswith("pass-"))
        # pass-3 kept by count, pass-0 kept as the newest VALID
        assert left == ["pass-00000", "pass-00003"]
        assert [os.path.basename(p) for p in removed] \
            == ["pass-00001", "pass-00002"]
        path, manifest = ckpt.latest_checkpoint(d)
        assert manifest["pass_id"] == 0

    def test_prune_never_deletes_mid_export(self, tmp_path):
        d = str(tmp_path)
        for i in range(3):
            ckpt.save_checkpoint(d, i, {"w": np.zeros(1, np.float32)},
                                 keep_last=0)
        oldest = os.path.join(d, "pass-00000")
        with ckpt.export_pin(oldest):
            ckpt.prune_old(d, keep_last=1)
            left = sorted(x for x in os.listdir(d)
                          if x.startswith("pass-"))
            # the pinned dir survives mid-export; pass-1 is pruned
            assert left == ["pass-00000", "pass-00002"]
            # the pin marker does not break validation
            assert ckpt._validate(oldest) is not None
        # pin released: the next prune may take it
        ckpt.prune_old(d, keep_last=1)
        left = sorted(x for x in os.listdir(d) if x.startswith("pass-"))
        assert left == ["pass-00002"]

    def test_save_checkpoint_keep_last_still_prunes(self, tmp_path):
        d = str(tmp_path)
        for i in range(5):
            ckpt.save_checkpoint(d, i, {"w": np.zeros(1, np.float32)},
                                 keep_last=2)
        left = sorted(x for x in os.listdir(d) if x.startswith("pass-"))
        assert left == ["pass-00003", "pass-00004"]

    def test_keep_last_zero_disables(self, tmp_path):
        d = str(tmp_path)
        for i in range(4):
            ckpt.save_checkpoint(d, i, {"w": np.zeros(1, np.float32)},
                                 keep_last=0)
        assert len(ckpt.checkpoint_entries(d)) == 4
        assert ckpt.prune_old(d, keep_last=0) == []


class TestClientBackoff:
    def test_honors_retry_after_with_capped_jitter(self):
        class SheddingRouter:
            registry = MetricsRegistry("client_test")

            def __init__(self, sheds):
                self.sheds = sheds
                self.calls = 0

            def submit(self, prompt, **kw):
                self.calls += 1
                if self.calls <= self.sheds:
                    raise RetryAfter("test shed", 0.2)
                return 41 + self.calls

        waits = []
        r = SheddingRouter(sheds=3)
        rid = backoff_submit(r, [1, 2], seed=5, wait=waits.append)
        assert rid == 45 and r.calls == 4
        assert len(waits) == 3
        # jitter ±25% around the 0.2s hint, capped
        assert all(0.15 <= w <= 0.25 for w in waits)
        # deterministic: the same seed replays the same wait sequence
        waits2 = []
        backoff_submit(SheddingRouter(sheds=3), [1, 2], seed=5,
                       wait=waits2.append)
        assert waits == waits2
        assert r.registry.counter("client_backoffs").value() >= 3.0

    def test_gives_up_after_attempts(self):
        class AlwaysShed:
            registry = MetricsRegistry("client_test2")

            def submit(self, prompt, **kw):
                raise RetryAfter("always shed", 0.01)

        with pytest.raises(RetryAfter):
            backoff_submit(AlwaysShed(), [1], attempts=3,
                           wait=lambda s: None)


class TestScrapeRetry:
    def test_transient_scrape_error_retried_once(self, model,
                                                 monkeypatch):
        """One flaky fetch (GC pause, connection reset) must not read
        as a dead replica: the retry absorbs it and the rollup is
        complete, with the retry counted."""
        from paddle_tpu.resilience.chaos import flaky
        from paddle_tpu.telemetry import introspect

        cfg, params = model
        reg = MetricsRegistry("scrape_retry")
        router = build_local_fleet(cfg, params, small_scfg(), n=1,
                                   registry=reg)
        real = ("serve_tokens 5.0\nserve_requests 1.0\n"
                "serve_active_slots 0.0\nserve_free_pages 32.0\n")
        monkeypatch.setattr(
            introspect, "scrape",
            flaky(lambda url, timeout=5.0: real, fail_times=1,
                  exc=OSError))
        rollup = router.scrape_replicas(
            ["http://fake/metrics"],
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                              retry_on=(OSError,), scope="fleet_scrape",
                              registry=reg, sleep=lambda s: None))
        assert rollup["replicas_scraped"] == 1
        assert rollup["scrape_errors"] == {}
        assert rollup["serve_tokens"] == 5.0
        assert reg.counter("fleet_scrape_errors").value() == 0.0

    def test_dead_endpoint_counted_not_silent(self, model, monkeypatch):
        from paddle_tpu.telemetry import introspect

        cfg, params = model
        reg = MetricsRegistry("scrape_dead")
        router = build_local_fleet(cfg, params, small_scfg(), n=1,
                                   registry=reg)

        def dead(url, timeout=5.0):
            raise OSError("connection refused")

        monkeypatch.setattr(introspect, "scrape", dead)
        rollup = router.scrape_replicas(
            ["http://fake/metrics"],
            retry=RetryPolicy(max_attempts=2, base_delay_s=0.0,
                              retry_on=(OSError,), scope="fleet_scrape",
                              registry=reg, sleep=lambda s: None))
        assert rollup["replicas_scraped"] == 0
        assert list(rollup["scrape_errors"]) == ["http://fake/metrics"]
        assert reg.counter("fleet_scrape_errors").value() == 1.0


class TestTelemetryRendering:
    def test_deploy_and_autoscale_records_render(self, model, tmp_path):
        """The /15 stream end to end: a real rollout + autoscale
        actions land in a JSONL capture that metrics_to_md renders
        without error (the bench's reporting path)."""
        from paddle_tpu.serving.fleet import clone_replica
        from paddle_tpu.telemetry import JsonlSink

        cfg, params = model
        reg = MetricsRegistry("deploy_md")
        mem = MemorySink()
        reg.add_sink(mem)
        path = tmp_path / "metrics.jsonl"
        with open(path, "w") as f:
            reg.add_sink(JsonlSink(f))
            router = build_local_fleet(cfg, params, small_scfg(), n=1,
                                       registry=reg)
            router.add_replica(
                lambda i, src: clone_replica(i, src, registry=reg))
            ctl = DeploymentController(
                str(tmp_path / "ckpts"), str(tmp_path / "servable"),
                router, cfg, registry=reg)
            save_model_checkpoint(str(tmp_path / "ckpts"), params)
            assert ctl.poll()["outcome"] == "deployed"
            router.remove_replica(1, reason="test idle")
            arb = PoolArbiter(total_hosts=2, serving_hosts=0,
                              min_trainer_hosts=1, registry=reg)
            assert arb.acquire_serving_host("render test")
        kinds = {r.get("kind") for r in mem.records}
        assert {"deploy", "autoscale", "fleet"} <= kinds
        sys.path.insert(0, "tools")
        try:
            import metrics_to_md
            assert metrics_to_md.main([str(path)]) == 0
        finally:
            sys.path.remove("tools")
