"""Fluid dynamic RNN: the block-as-stepnet ``recurrent`` op + LoD-array
machinery, DIFFERENTIABLE end to end.

≅ the reference's fluid RNN surface: recurrent_op.cc:49-62 (step-net RNN
with a backward pass), test_recurrent_op.py (StaticRNN + PySimpleRNN
numeric parity), lod_rank_table_op.cc:19, lod_tensor_to_array_op /
array_to_lod_tensor_op / shrink_rnn_memory_op, and the requirement that a
fluid dynamic-RNN language model TRAINS (loss decreases with gradient flow
through the scan-lowered recurrent op).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu import fluid
from paddle_tpu.fluid import framework, layers


def _reset():
    framework.reset_default_programs()


def test_static_rnn_matches_numpy_simple_rnn1(rng_np):
    """PySimpleRNN1 (test_recurrent_op.py:28): h_t = (x_t + h_{t-1})/2."""
    _reset()
    T, B, D = 4, 3, 5
    x_np = rng_np.normal(size=(T, B, D)).astype(np.float32)
    h_boot_np = rng_np.normal(size=(B, D)).astype(np.float32)

    x = layers.data("x", shape=[T, B, D], append_batch_size=False)
    h_boot = layers.data("h_boot", shape=[B, D], append_batch_size=False)
    rnn = layers.StaticRNN()
    with rnn.step():
        h_pre = rnn.memory(init=h_boot)
        x_t = rnn.step_input(x)
        h = layers.scale(x=layers.elementwise_add(x=h_pre, y=x_t), scale=0.5)
        rnn.update_memory(h_pre, h)
        rnn.output(h)
    out = rnn()

    exe = fluid.Executor()
    (y,) = exe.run(feed={"x": x_np, "h_boot": h_boot_np},
                   fetch_list=[out])

    ref = np.zeros((T, B, D), np.float32)
    h = h_boot_np
    for t in range(T):
        h = (h + x_np[t]) * 0.5
        ref[t] = h
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-5, atol=1e-5)


def test_static_rnn_gradient_flows(rng_np):
    """jax.grad crosses the recurrent op: finite-diff check on the boot
    state through a 2-layer step net (the reference's recurrent_op grad)."""
    import jax
    import jax.numpy as jnp

    _reset()
    T, B, D = 3, 2, 4
    x_np = rng_np.normal(size=(T, B, D)).astype(np.float32)
    w_np = (rng_np.normal(size=(D, D)) * 0.4).astype(np.float32)
    boot_np = rng_np.normal(size=(B, D)).astype(np.float32)

    x = layers.data("x", shape=[T, B, D], append_batch_size=False)
    w = layers.data("w", shape=[D, D], append_batch_size=False)
    h_boot = layers.data("h_boot", shape=[B, D], append_batch_size=False)
    rnn = layers.StaticRNN()
    with rnn.step():
        h_pre = rnn.memory(init=h_boot)
        x_t = rnn.step_input(x)
        hw = layers.mul(x=h_pre, y=w)
        h = layers.tanh(x=layers.elementwise_add(x=hw, y=x_t))
        rnn.update_memory(h_pre, h)
        rnn.output(h)
    out = rnn()
    loss = layers.mean(x=out)

    prog = framework.default_main_program()
    from paddle_tpu.fluid.executor import _run_op

    def loss_fn(boot):
        env = {"x": jnp.asarray(x_np), "w": jnp.asarray(w_np),
               "h_boot": boot}
        rng = jax.random.key(0)
        for op in prog.global_block().ops:
            _run_op(op, env, rng, prog)
        return env[loss.name].reshape(())

    g = jax.grad(loss_fn)(jnp.asarray(boot_np))
    assert np.isfinite(np.asarray(g)).all()
    # finite differences
    eps = 1e-3
    base_p = np.asarray(loss_fn(jnp.asarray(boot_np + eps * 0)))
    for idx in [(0, 0), (1, 2)]:
        bumped = boot_np.copy()
        bumped[idx] += eps
        fd = (float(loss_fn(jnp.asarray(bumped))) - float(base_p)) / eps
        an = float(np.asarray(g)[idx])
        assert abs(fd - an) < 5e-3, (idx, fd, an)


def test_lod_array_ops_roundtrip(rng_np):
    """lod_rank_table sorts desc; to_array/array_to restore the original
    order; shrink masks rows whose sequence already ended."""
    import jax

    _reset()
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.fluid.ops import get_kernel

    B, T, D = 4, 5, 3
    lengths = np.array([2, 5, 3, 1], np.int32)
    data = rng_np.normal(size=(B, T, D)).astype(np.float32)
    for b in range(B):
        data[b, lengths[b]:] = 0.0
    x = SequenceBatch(data=data, length=lengths)
    rng = jax.random.key(0)

    table = get_kernel("lod_rank_table")({"X": [x]}, {}, rng)["Out"][0]
    np.testing.assert_array_equal(np.asarray(table["index"]), [1, 2, 0, 3])
    np.testing.assert_array_equal(np.asarray(table["length"]), [5, 3, 2, 1])

    arr = get_kernel("lod_tensor_to_array")(
        {"X": [x], "RankTable": [table]}, {}, rng)["Out"][0]
    assert arr.shape == (T, B, D)
    # step 3: only the longest sequence still lives
    live3 = np.asarray(arr[3])
    assert np.any(live3[0] != 0)
    assert np.all(live3[1:] == 0)

    back = get_kernel("array_to_lod_tensor")(
        {"X": [arr], "RankTable": [table]}, {}, rng)["Out"][0]
    np.testing.assert_allclose(np.asarray(back.data), data, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(back.length), lengths)

    mem = rng_np.normal(size=(B, D)).astype(np.float32)
    shrunk = get_kernel("shrink_rnn_memory")(
        {"X": [mem], "I": [np.asarray([2.0])], "RankTable": [table]},
        {}, rng)["Out"][0]
    # at step 2, table rows with length > 2 live: rows 0 (len5) and 1 (len3)
    np.testing.assert_allclose(np.asarray(shrunk[:2]), mem[:2], rtol=1e-6)
    assert np.all(np.asarray(shrunk[2:]) == 0)

    ml = get_kernel("max_sequence_len")({"RankTable": [table]}, {}, rng)
    assert int(np.asarray(ml["Out"][0])[0]) == 5


def test_dynamic_rnn_lm_trains(rng_np):
    """A fluid dynamic-RNN language model over VARIABLE-length sequences
    (lod_rank_table -> lod_tensor_to_array -> recurrent -> array_to_lod)
    trains: loss decreases, gradients flow through embedding, recurrent
    weights, and the softmax projection."""
    import jax
    import jax.numpy as jnp

    _reset()
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.fluid.executor import _run_op

    V, E, H, B, T = 17, 8, 12, 8, 6

    words = layers.data("words", shape=[B, T], append_batch_size=False,
                        dtype="int32", lod_level=1)
    table = layers.lod_rank_table(words)
    # embed then convert to a time-major array in rank order
    emb_w = layers.data("emb_w", shape=[V, E], append_batch_size=False)

    prog = framework.default_main_program()
    main = prog.global_block()
    emb = main.create_var(name="emb", shape=[B, T, E], lod_level=1)
    main.append_op("lookup_table", {"Ids": ["words"], "W": ["emb_w"]},
                   {"Out": ["emb"]}, {})
    arr = layers.lod_tensor_to_array(main.vars["emb"], table)

    w_ih = layers.data("w_ih", shape=[E, H], append_batch_size=False)
    w_hh = layers.data("w_hh", shape=[H, H], append_batch_size=False)
    w_out = layers.data("w_out", shape=[H, V], append_batch_size=False)
    boot = layers.data("boot", shape=[B, H], append_batch_size=False)
    lens = layers.data("lens_sorted", shape=[B], append_batch_size=False,
                       dtype="int32")

    rnn = layers.StaticRNN(sequence_lengths=lens)
    with rnn.step():
        h_pre = rnn.memory(init=boot)
        x_t = rnn.step_input(arr)
        a = layers.elementwise_add(
            x=layers.mul(x=x_t, y=w_ih), y=layers.mul(x=h_pre, y=w_hh))
        h = layers.tanh(x=a)
        logits = layers.mul(x=h, y=w_out)
        rnn.update_memory(h_pre, h)
        rnn.output(logits)
    logits_arr = rnn()
    seq_logits = layers.array_to_lod_tensor(logits_arr, table)

    # data: next-token = (token + 1) % V, variable lengths
    lengths = rng_np.integers(2, T + 1, size=(B,)).astype(np.int32)
    toks = (rng_np.integers(0, V, size=(B, T))).astype(np.int32)

    params = {
        "emb_w": jnp.asarray(rng_np.normal(size=(V, E)) * 0.1, jnp.float32),
        "w_ih": jnp.asarray(rng_np.normal(size=(E, H)) * 0.3, jnp.float32),
        "w_hh": jnp.asarray(rng_np.normal(size=(H, H)) * 0.3, jnp.float32),
        "w_out": jnp.asarray(rng_np.normal(size=(H, V)) * 0.3, jnp.float32),
    }

    x_seq = SequenceBatch(data=jnp.asarray(toks), length=jnp.asarray(lengths))
    targets = jnp.asarray((toks + 1) % V)

    def loss_fn(params):
        env = dict(params)
        env["words"] = x_seq
        env["boot"] = jnp.zeros((B, H), jnp.float32)
        # rank-order lengths for the recurrent mask
        order = jnp.argsort(-x_seq.length, stable=True)
        env["lens_sorted"] = x_seq.length[order]
        rng = jax.random.key(0)
        for op in prog.global_block().ops:
            _run_op(op, env, rng, prog)
        out = env[seq_logits.name]  # SequenceBatch [B, T, V]
        logp = jax.nn.log_softmax(out.data, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        mask = out.mask()
        return jnp.sum(nll * mask) / jnp.sum(mask)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    first = last = None
    for i in range(60):
        l, g = grad_fn(params)
        params = jax.tree.map(lambda p, gg: p - 0.5 * gg, params, g)
        l = float(l)
        first = first if first is not None else l
        last = l
    assert np.isfinite(last)
    assert last < first * 0.5, (first, last)
    # every parameter received gradient
    for k, gv in g.items():
        assert float(jnp.max(jnp.abs(gv))) > 0, k
