"""Multi-PROCESS ``jax.distributed`` integration test — the mesh-era
version of the reference's in-process cluster tests
(``paddle/trainer/tests/test_CompareSparse.cpp:65-73``, which spawn real
pservers inside the test binary and compare sparse vs dense training).

Two local processes with 4 virtual CPU devices each — spawned through
``paddle_tpu.distributed.launch`` (the trainer-fleet launcher, VERDICT
item 4) — rendezvous through ``multihost.initialize`` (real
coordinator, real ``jax.distributed`` handshake), build the 8-device dp
mesh, feed per-process slices of a deterministic global batch through
``multihost.global_batch``, run 4 dp train steps, and must end
bit-comparable to the same model trained in THIS process on its own
8-device mesh."""

from __future__ import annotations

import os
import pickle
import sys

import numpy as np
import pytest

import jax

from paddle_tpu.distributed.launch import launch_local

_WORKER = os.path.join(os.path.dirname(__file__), "_multihost_worker.py")


def test_two_process_dp_matches_single_process(tmp_path):
    nproc = 2
    out = tmp_path / "params_mp.pkl"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    log_dir = tmp_path / "logs"
    # the launcher substitutes {rank}/{nproc}/{port}, sets the rank env
    # (PADDLE_TPU_TRAINER_ID/NPROC/COORDINATOR), tees per-rank logs and
    # propagates the first failing rank's exit code
    rc = launch_local(
        [sys.executable, _WORKER, "{rank}", "{nproc}", "{port}",
         str(out)],
        nproc=nproc, env=env, log_dir=str(log_dir), echo_rank0=False,
        timeout=240)
    logs = [(log_dir / f"rank{i}.log").read_text(errors="replace")
            if (log_dir / f"rank{i}.log").exists() else ""
            for i in range(nproc)]
    if rc != 0 and any(
            "Multiprocess computations aren't implemented" in l
            for l in logs):
        pytest.skip("installed jaxlib's CPU backend cannot run "
                    "cross-process collectives")
    assert rc == 0, f"launch rc={rc}:\n{logs[0][-2000:]}\n{logs[1][-2000:]}"
    assert out.exists(), logs[0][-2000:]
    with open(out, "rb") as f:
        mp_params = pickle.load(f)

    # single-process reference on this process's own 8-device mesh
    sys.path.insert(0, os.path.dirname(__file__))
    try:
        import _multihost_worker as W
    finally:
        sys.path.pop(0)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("data"))
    params, opt_state, states, step = W.build_model()

    def place(feed_np):
        return {k: jax.device_put(v, shard) for k, v in feed_np.items()}

    params = jax.tree.map(lambda x: jax.device_put(x, repl), params)
    sp_params = W.run_steps(params, opt_state, states, step, place)

    assert set(sp_params) == set(mp_params)
    for k in sp_params:
        np.testing.assert_allclose(
            sp_params[k], mp_params[k], rtol=1e-5, atol=1e-6,
            err_msg=f"parameter {k} diverged between 1-process and "
                    f"2-process dp training")
