"""Utils tooling (image pipeline, plot, topology dump) + profiler/MFU
harness."""

import os

import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import profiler
from paddle_tpu.utils import image as I
from paddle_tpu.utils import format_topology, parse_log, plotcurve
from paddle_tpu.utils.plotcurve import Ploter


def test_image_pipeline(rng_np):
    im = (rng_np.random((48, 64, 3)) * 255).astype(np.uint8)
    r = I.resize_short(im, 32)
    assert min(r.shape[:2]) == 32 and r.shape[1] > r.shape[0]
    c = I.center_crop(r, 28)
    assert c.shape[:2] == (28, 28)
    rc = I.random_crop(r, 28, rng=rng_np)
    assert rc.shape[:2] == (28, 28)
    assert np.array_equal(I.left_right_flip(c), c[:, ::-1])
    chw = I.to_chw(c)
    assert chw.shape == (3, 28, 28)
    out = I.simple_transform(im, 36, 32, is_train=True, rng=rng_np,
                             mean=np.array([120.0, 120.0, 120.0]))
    assert out.shape == (3, 32, 32) and out.dtype == np.float32
    gray = I.simple_transform(im[:, :, 0], 36, 32, is_train=False)
    assert gray.shape == (1, 32, 32)


def test_plotcurve_and_ploter(tmp_path):
    log = tmp_path / "train.log"
    log.write_text("\n".join(
        f"I 0101 paddle_tpu] Pass 0, Batch {i}, Cost {3.0 / (i + 1):.4f}, {{}}"
        for i in range(10)))
    points = parse_log(log.read_text().splitlines())
    assert len(points) == 10 and points[0][2] == 3.0
    out = str(tmp_path / "curve.png")
    plotcurve(str(log), out)
    assert os.path.getsize(out) > 0

    p = Ploter("train", "test")
    p.append("train", 0, 1.0)
    p.append("train", 1, 0.5)
    p.plot(str(tmp_path / "ploter.png"))
    assert os.path.getsize(tmp_path / "ploter.png") > 0


def test_show_topology_dump():
    from paddle_tpu.models.lenet import lenet_cost

    cost, predict, img, label = lenet_cost()
    text = paddle.topology.Topology(cost).serialize()
    dump = format_topology(text)
    assert "total parameters:" in dump
    assert "conv" in dump and "fc" in dump


def test_profiler_benchmark_and_flops():
    dim = 256
    a = jnp.ones((dim, dim), jnp.float32)

    def fn(x):
        return x @ x

    flops = profiler.flops_of(fn, a)
    assert flops >= 2 * dim ** 3 * 0.9  # matmul flops dominate

    res = profiler.benchmark(fn, (a,), iters=5, warmup=2)
    assert res.seconds_per_step > 0
    assert 0 <= res.mfu < 1.5  # sane on any backend
    assert "ms/step" in repr(res)


def test_profile_trace_writes(tmp_path):
    with profiler.profile(str(tmp_path)):
        with profiler.trace_annotation("matmul"):
            x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
            x.block_until_ready()
    # a plugins/profile dir with at least one trace file appears
    found = [f for _, _, fs in os.walk(tmp_path) for f in fs]
    assert found, "no profiler output written"
