"""Native master service: the reference tests its cluster services by
spawning them in-process on localhost ports (SURVEY §4 —
test_TrainerOnePass.cpp, go/master/client_test.go); same pattern here
against the real C++ binary."""

import os
import time

import pytest

from paddle_tpu.distributed import MasterClient, MasterServer, master_reader
from paddle_tpu.reader import recordio


@pytest.fixture(scope="module")
def server():
    with MasterServer(timeout_ms=60000) as s:
        yield s


def test_ping_and_set_get_fin(server):
    c = server.client()
    assert c.ping()
    n = c.set_dataset([f"task-{i}" for i in range(5)])
    assert n == 5  # reply counts the tasks just enqueued
    seen = []
    while True:
        got = c.get_task()
        if got is None:
            break
        tid, epoch, payload = got
        seen.append(payload)
        assert c.task_finished(tid, epoch)
    assert sorted(seen) == [f"task-{i}" for i in range(5)]
    # next pass: RESET re-queues everything
    c.reset_pass()
    assert server.client().stat()["todo"] == 5
    # drain for the following tests in this module-scoped server
    while (got := c.get_task()) is not None:
        c.task_finished(got[0], got[1])
    c.close()


def test_two_clients_disjoint_tasks(server):
    c1, c2 = server.client(), server.client()
    c1.reset_pass()
    ids = set()
    for c in (c1, c2, c1, c2, c1):
        got = c.get_task()
        if got in (None, "WAIT"):
            continue
        ids.add(got[0])
        c.task_finished(got[0], got[1])
    assert len(ids) >= 4  # no task handed to two clients concurrently
    while (got := c1.get_task()) not in (None, "WAIT"):
        c1.task_finished(got[0], got[1])
    c1.close(), c2.close()


def test_timeout_redispatch_and_stale_fin():
    with MasterServer(timeout_ms=300, failure_max=10) as s:
        c = s.client()
        c.set_dataset(["only-task"])
        tid, epoch, _ = c.get_task()
        assert c.get_task() == "WAIT"  # pending elsewhere, not re-given
        time.sleep(0.5)  # let it time out
        got = c.get_task()  # re-dispatched with a new epoch
        assert got not in (None, "WAIT")
        tid2, epoch2, _ = got
        assert tid2 == tid and epoch2 > epoch
        # the original holder's FIN is stale and must be rejected
        assert not c.task_finished(tid, epoch)
        assert c.task_finished(tid2, epoch2)
        assert c.get_task() is None


def test_failure_cap_discards_task():
    with MasterServer(timeout_ms=60000, failure_max=2) as s:
        c = s.client()
        c.set_dataset(["poison", "good"])
        finished, discarded = [], 0
        while True:
            got = c.get_task()
            if got is None:
                break
            if got == "WAIT":
                time.sleep(0.01)
                continue
            tid, epoch, payload = got
            if payload == "poison":
                c.task_failed(tid, epoch)
            else:
                c.task_finished(tid, epoch)
                finished.append(payload)
        st = c.stat()
        assert finished == ["good"]
        assert st["failed"] == 1  # poison discarded after failure_max+1 tries
        assert st["done"] == 1


def test_snapshot_recover_after_crash(tmp_path):
    snap = str(tmp_path / "master.snapshot")
    s = MasterServer(timeout_ms=60000, snapshot_path=snap)
    c = s.client()
    c.set_dataset([f"t{i}" for i in range(6)])
    tid, epoch, _ = c.get_task()  # one task in flight
    c.task_finished(tid, epoch)
    tid2, _, _ = c.get_task()  # a second in flight, never finished
    time.sleep(0.4)  # snapshots flush on a 100ms throttle
    s.kill()  # crash, not clean shutdown
    assert os.path.exists(snap)

    s2 = MasterServer(timeout_ms=60000, snapshot_path=snap)
    try:
        c2 = s2.client()
        st = c2.stat()
        # done survived; the in-flight task was re-queued as todo
        assert st["done"] == 1
        assert st["todo"] == 5
        remaining = []
        while (got := c2.get_task()) is not None:
            c2.task_finished(got[0], got[1])
            remaining.append(got[2])
        assert len(remaining) == 5
    finally:
        s2.shutdown()


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "data.recordio")
    with recordio.Writer(path, max_records_per_chunk=10) as w:
        for i in range(35):
            w.write(f"rec-{i}".encode())
    assert len(recordio.chunk_offsets(path)) == 4  # 10+10+10+5
    got = [r.decode() for r in recordio.reader(path)()]
    assert got == [f"rec-{i}" for i in range(35)]


def test_master_reader_end_to_end(tmp_path):
    """recordio chunks -> master tasks -> reader generator, two passes,
    with one simulated worker crash mid-pass."""
    paths = []
    for f in range(2):
        p = str(tmp_path / f"part-{f}.recordio")
        with recordio.Writer(p, max_records_per_chunk=8) as w:
            for i in range(20):
                w.write(f"{f}:{i}".encode())
        paths.append(p)
    expected = sorted(f"{f}:{i}" for f in range(2) for i in range(20))

    with MasterServer(timeout_ms=400) as s:
        c = s.client()
        c.set_dataset(recordio.task_payloads(paths))

        # a "crashed" worker pulls one task and never reports back
        dead = s.client()
        assert dead.get_task() not in (None, "WAIT")
        dead.close()

        reader = master_reader(c, recordio.read_task)
        pass1 = sorted(r.decode() for r in reader())
        assert pass1 == expected  # timeout re-dispatched the dead task
        c.reset_pass()
        pass2 = sorted(r.decode() for r in reader())
        assert pass2 == expected
