"""Checkpoint/resume — mirrors the Go pserver checkpoint tests
(``go/pserver/service.go:342-391`` behavior: manifest+hash, newest-valid
recovery) and ParamUtil pass-snapshot semantics."""

import os

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.trainer import checkpoint as ckpt


def _tiny_trainer():
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import data_type

    x = layer.data(name="x", type=data_type.dense_vector(4))
    y = layer.data(name="y", type=data_type.dense_vector(1))
    fc = layer.fc(input=x, size=1, act=paddle.activation.LinearActivation(),
                  name="out")
    cost = layer.mse_cost(input=fc, label=y)
    params = paddle.parameters.create(paddle.topology.Topology(cost))
    tr = paddle.trainer.SGD(cost=cost, parameters=params,
                            update_equation=paddle.optimizer.Momentum(
                                momentum=0.9, learning_rate=0.05))
    return tr


def _reader():
    rs = np.random.RandomState(0)
    w = np.array([1.0, -2.0, 0.5, 3.0])

    def r():
        for _ in range(16):
            x = rs.randn(4).astype(np.float32)
            yield x, np.array([x @ w], np.float32)
    return paddle.reader.batch(r, batch_size=8)


def test_save_load_roundtrip(tmp_path):
    d = str(tmp_path)
    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    opt = {"m": {"w": jnp.ones((2, 3))}, "step": jnp.zeros(())}
    states = {"bn.mean": np.full((3,), 0.5, np.float32)}
    path = ckpt.save_checkpoint(d, 3, params, opt_state=opt, states=states,
                                meta={"note": "hi"})
    assert os.path.basename(path) == "pass-00003"
    found = ckpt.latest_checkpoint(d)
    assert found is not None and found[1]["pass_id"] == 3
    template = {"m": {"w": jnp.zeros((2, 3))}, "step": jnp.ones(())}
    p2, o2, s2, manifest = ckpt.load_checkpoint(path, template)
    np.testing.assert_array_equal(p2["w"], params["w"])
    np.testing.assert_array_equal(np.asarray(o2["m"]["w"]), 1.0)
    np.testing.assert_array_equal(s2["bn.mean"], 0.5)
    assert manifest["meta"]["note"] == "hi"


def test_corrupt_checkpoint_falls_back_to_previous(tmp_path):
    d = str(tmp_path)
    ckpt.save_checkpoint(d, 0, {"w": np.zeros(2, np.float32)})
    ckpt.save_checkpoint(d, 1, {"w": np.ones(2, np.float32)})
    # corrupt the newest payload
    with open(os.path.join(d, "pass-00001", "params.npz"), "ab") as f:
        f.write(b"garbage")
    path, manifest = ckpt.latest_checkpoint(d)
    assert manifest["pass_id"] == 0
    p, _, _, _ = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(p["w"], 0.0)


def test_latest_checkpoint_skips_concurrent_writer_debris(tmp_path):
    """latest_checkpoint racing a concurrent writer: visible pass-*/
    batch-* dirs whose manifest is missing, torn (half-written JSON),
    empty, or pointing at not-yet-written payload files must be SKIPPED
    — selection falls back to the newest complete checkpoint instead of
    crashing (the Go pserver newest-VALID recovery rule, extended to
    mid-write states a non-atomic writer or lagging NFS can expose)."""
    import json
    d = str(tmp_path)
    good = ckpt.save_checkpoint(d, 0, {"w": np.zeros(2, np.float32)},
                                batch_id=3)

    # 1) dir exists, manifest not yet written
    os.makedirs(os.path.join(d, "pass-00000-batch-000005"))
    # 2) manifest torn mid-write (truncated JSON)
    torn = os.path.join(d, "pass-00000-batch-000007")
    os.makedirs(torn)
    with open(os.path.join(torn, ckpt.MANIFEST), "w") as f:
        f.write('{"uuid": "abc", "pass_id": 0, "files": {"par')
    # 3) manifest empty (open()'d but nothing flushed)
    empty = os.path.join(d, "pass-00000-batch-000008")
    os.makedirs(empty)
    open(os.path.join(empty, ckpt.MANIFEST), "w").close()
    # 4) manifest complete but a payload file it names is missing
    missing = os.path.join(d, "pass-00000-batch-000009")
    os.makedirs(missing)
    with open(os.path.join(missing, ckpt.MANIFEST), "w") as f:
        json.dump({"uuid": "x", "pass_id": 0,
                   "cursor": {"pass_id": 0, "batch_id": 9},
                   "files": {"params.npz": "0" * 64}, "meta": {}}, f)
    # 5) a stray FILE named like a checkpoint dir
    with open(os.path.join(d, "pass-00000-batch-000011"), "w") as f:
        f.write("not a directory")
    # 6) the writer's own tmp staging dir (never selectable)
    os.makedirs(os.path.join(d, "pass-00000-batch-000012.tmp-deadbeef"))

    found = ckpt.latest_checkpoint(d)
    assert found is not None
    path, manifest = found
    assert path == good
    assert manifest["cursor"] == {"pass_id": 0, "batch_id": 3}


def test_latest_checkpoint_empty_and_debris_only_dir(tmp_path):
    """No valid checkpoint at all -> None, not an exception."""
    d = str(tmp_path)
    assert ckpt.latest_checkpoint(d) is None  # dir doesn't even exist yet
    os.makedirs(os.path.join(d, "pass-00000-batch-000001"))
    torn = os.path.join(d, "pass-00002")
    os.makedirs(torn)
    with open(os.path.join(torn, ckpt.MANIFEST), "w") as f:
        f.write("{")
    assert ckpt.latest_checkpoint(d) is None


def test_gc_keeps_last_n(tmp_path):
    d = str(tmp_path)
    for i in range(5):
        ckpt.save_checkpoint(d, i, {"w": np.zeros(1, np.float32)},
                             keep_last=2)
    left = sorted(x for x in os.listdir(d) if x.startswith("pass-"))
    assert left == ["pass-00003", "pass-00004"]


def test_trainer_checkpoint_and_resume(tmp_path):
    d = str(tmp_path / "ckpt")
    tr = _tiny_trainer()
    tr.train(reader=_reader(), num_passes=2, checkpoint_dir=d)
    assert ckpt.latest_checkpoint(d)[1]["pass_id"] == 1
    w_after = tr.parameters["_out.w0"].copy()

    # fresh trainer resumes: starts at pass 2, parameters restored
    tr2 = _tiny_trainer()
    seen_passes = []

    def handler(e):
        if isinstance(e, paddle.event.BeginPass):
            seen_passes.append(e.pass_id)

    tr2.train(reader=_reader(), num_passes=4, checkpoint_dir=d,
              event_handler=handler)
    assert seen_passes == [2, 3]
    # resumed from the saved weights, then kept training
    assert ckpt.latest_checkpoint(d)[1]["pass_id"] == 3

    # resume with num_passes already done -> trains nothing
    tr3 = _tiny_trainer()
    seen = []
    tr3.train(reader=_reader(), num_passes=4, checkpoint_dir=d,
              event_handler=lambda e: seen.append(e))
    assert not any(isinstance(e, paddle.event.EndIteration) for e in seen)
    np.testing.assert_allclose(
        tr3.parameters["_out.w0"],
        ckpt.load_checkpoint(ckpt.latest_checkpoint(d)[0])[0]["_out.w0"])
    del w_after


def test_sigterm_preemption_checkpoints_and_resumes(tmp_path):
    """SIGTERM mid-training -> cursor checkpoint at the batch boundary ->
    a fresh trainer resumes the SAME pass from the next batch (SURVEY §5
    preemption handling + the resilience mid-pass replay cursor)."""
    import os
    import signal
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.layers import api as layer, base, data_type

    def build():
        base.reset_name_counters()
        x = layer.data(name="sx", type=data_type.dense_vector(4))
        h = layer.fc(input=x, size=4)
        lbl = layer.data(name="sy", type=data_type.integer_value(4))
        cost = layer.classification_cost(input=h, label=lbl)
        parameters = paddle.parameters.create(paddle.topology.Topology(cost))
        return paddle.trainer.SGD(
            cost=cost, parameters=parameters,
            update_equation=paddle.optimizer.SGD(learning_rate=0.1))

    rng = np.random.default_rng(0)

    def reader():
        for i in range(16):
            if i == 4:  # simulate the pod eviction signal mid-pass
                os.kill(os.getpid(), signal.SIGTERM)
            yield rng.normal(size=(4,)).astype(np.float32), int(i % 4)

    ckdir = str(tmp_path / "ck")
    trainer = build()
    trainer.train(reader=paddle.reader.batch(reader, 8), num_passes=50,
                  checkpoint_dir=ckdir)
    from paddle_tpu.trainer import checkpoint as ckpt

    found = ckpt.latest_checkpoint(ckdir)
    assert found is not None
    saved_pass = found[1]["pass_id"]
    assert saved_pass < 49  # preempted long before the end
    # mid-pass preemption records a replay cursor into the SAME pass
    cursor = found[1]["cursor"]
    assert cursor["pass_id"] == saved_pass and cursor["batch_id"] >= 1
    assert found[1]["meta"]["preempted"] is True

    # resume re-enters the preempted pass at the cursor batch
    passes = []
    trainer2 = build()
    trainer2.train(
        reader=paddle.reader.batch(
            lambda: ((rng.normal(size=(4,)).astype(np.float32), 0)
                     for _ in range(8)), 8),
        num_passes=saved_pass + 3, checkpoint_dir=ckdir,
        event_handler=lambda e: passes.append(e.pass_id)
        if isinstance(e, paddle.event.BeginPass) else None)
    assert passes and passes[0] == saved_pass


def test_async_checkpointer_writes_and_raises(tmp_path):
    """AsyncCheckpointer: identical artifacts to the sync path, one write
    in flight, deferred errors re-raise on wait()."""
    import pytest

    d = str(tmp_path / "a")
    w = ckpt.AsyncCheckpointer()
    params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    w.save(d, 0, params, states={"s": np.ones(2, np.float32)},
           meta={"tag": 1})
    w.wait()
    path, manifest = ckpt.latest_checkpoint(d)
    assert manifest["pass_id"] == 0 and manifest["meta"] == {"tag": 1}
    loaded, _, states, _ = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(loaded["w"], params["w"])
    np.testing.assert_array_equal(states["s"], np.ones(2, np.float32))

    # a failing write surfaces at the next wait(), not silently
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("x")
    w.save(str(blocker / "denied"), 1, params)
    with pytest.raises(OSError):
        w.wait()
    w.wait()  # error consumed; idempotent afterwards


def test_trainer_async_checkpoint_and_resume(tmp_path):
    """checkpoint_async=True produces the same resumable checkpoints."""
    d = str(tmp_path / "ckpt")
    tr = _tiny_trainer()
    tr.train(reader=_reader(), num_passes=2, checkpoint_dir=d,
             checkpoint_async=True)
    # train() returned only after the writer flushed
    assert ckpt.latest_checkpoint(d)[1]["pass_id"] == 1

    tr2 = _tiny_trainer()
    seen = []
    tr2.train(reader=_reader(), num_passes=3, checkpoint_dir=d,
              checkpoint_async=True,
              event_handler=lambda e: seen.append(
                  e.pass_id) if isinstance(e, paddle.event.BeginPass)
              else None)
    assert seen == [2]
    np.testing.assert_allclose(
        tr2.parameters["_out.w0"],
        ckpt.load_checkpoint(ckpt.latest_checkpoint(d)[0])[0]["_out.w0"])


def test_bf16_params_dtype_roundtrip(tmp_path):
    """ADVICE round 5 (checkpoint.py:182): params saved bf16/fp8 must come
    back bf16/fp8 — the npz layer stores them f32, and without the
    manifest dtype record a resume would silently recompile the train
    step under an f32 signature."""
    d = str(tmp_path / "c")
    params = {
        "w_bf16": jnp.arange(6, dtype=jnp.bfloat16).reshape(2, 3),
        "w_f32": np.arange(4, dtype=np.float32),
        "w_f16": np.arange(4, dtype=np.float16),  # native: untouched
    }
    states = {"bn.mean": jnp.full((3,), 0.5, jnp.bfloat16)}
    ckpt.save_checkpoint(d, 0, params, states=states)
    path, manifest = ckpt.latest_checkpoint(d)
    assert manifest["dtypes"]["params"] == {"w_bf16": "bfloat16"}
    assert manifest["dtypes"]["states"] == {"bn.mean": "bfloat16"}
    p2, _, s2, _ = ckpt.load_checkpoint(path)
    assert str(p2["w_bf16"].dtype) == "bfloat16"
    assert p2["w_f32"].dtype == np.float32
    assert p2["w_f16"].dtype == np.float16
    assert str(s2["bn.mean"].dtype) == "bfloat16"
    np.testing.assert_array_equal(
        np.asarray(p2["w_bf16"], np.float32),
        np.asarray(params["w_bf16"], np.float32))

    # pre-dtype-manifest checkpoints (no "dtypes" key) still load
    import json as _json
    mpath = os.path.join(path, ckpt.MANIFEST)
    m = _json.load(open(mpath))
    del m["dtypes"]
    with open(mpath, "w") as f:
        _json.dump(m, f)
    # manifest hash doesn't cover itself, so the edit is legal
    p3, _, _, _ = ckpt.load_checkpoint(path)
    assert p3["w_bf16"].dtype == np.float32  # legacy behavior preserved


def test_bf16_moment_opt_state_roundtrip(tmp_path):
    """npz loses extension dtypes (bfloat16 -> |V2); the checkpoint layer
    stores them f32 and restores the template dtype, so
    Adam(moment_dtype=bf16) states resume exactly."""
    from paddle_tpu.optimizer import Adam

    opt = Adam(learning_rate=1e-3, moment_dtype=jnp.bfloat16)
    params = {"w": jnp.arange(8, dtype=jnp.float32).reshape(2, 4)}
    state = opt.init_tree(params)
    grads = {"w": jnp.full((2, 4), 0.5, jnp.float32)}
    params, state = opt.apply_tree(grads, params, state)
    assert state["slots"][0]["m"].dtype == jnp.bfloat16

    d = str(tmp_path / "c")
    ckpt.save_checkpoint(d, 0, {"w": np.asarray(params["w"])},
                         opt_state=state)
    template = Adam(learning_rate=1e-3,
                    moment_dtype=jnp.bfloat16).init_tree(params)
    _, restored, _, _ = ckpt.load_checkpoint(
        ckpt.latest_checkpoint(d)[0], opt_state_template=template)
    assert restored["slots"][0]["m"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored["slots"][0]["m"].astype(jnp.float32)),
        np.asarray(state["slots"][0]["m"].astype(jnp.float32)))
    assert int(restored["step"]) == int(state["step"])
