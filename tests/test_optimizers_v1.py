"""v1 optimizer-parity stragglers (VERDICT r4 #1): sparse_momentum wiring
and equivalence, loud unknown-learning_method errors, per-parameter
momentum application, and model-average apply at eval.

Reference anchors: ``paddle/parameter/FirstOrderOptimizer.{h,cpp}``
(SparseMomentumParameterOptimizer, sgdUpdate's paraConfig.momentum()),
``paddle/parameter/AverageOptimizer.h:63-64`` (apply/restore), and
``paddle/trainer/tests/test_CompareTwoOpts.cpp`` (convergence-equality
test style)."""

import numpy as np
import pytest

import paddle_tpu.optimizer as opt
from paddle_tpu.core.parameters import ParamSpec


def _spec(name, shape, **kw):
    from paddle_tpu.core import initializer as I

    return ParamSpec(name=name, shape=shape, initializer=I.constant(0.0), **kw)


def _run(optimizer, params, grads_seq, specs=None):
    state = optimizer.init(params, specs)
    for g in grads_seq:
        params, state = optimizer.apply(g, params, state, specs)
    return params, state


def _toy_problem(steps=25, seed=0):
    rng = np.random.default_rng(seed)
    import jax.numpy as jnp

    params = {"w": jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))}
    grads = [{"w": jnp.asarray(rng.normal(size=(8, 4)).astype(np.float32))}
             for _ in range(steps)]
    return params, grads


class TestSparseMomentum:
    def test_equals_dense_momentum_all_rows(self):
        """All rows touched + constant lr => float-equal to heavy-ball
        momentum (test_CompareTwoOpts-style equality)."""
        params, grads = _toy_problem()
        dense, _ = _run(opt.Momentum(momentum=0.9, learning_rate=0.05),
                        dict(params), grads)
        sparse, _ = _run(opt.SparseMomentum(momentum=0.9, learning_rate=0.05),
                         dict(params), grads)
        np.testing.assert_allclose(np.asarray(dense["w"]),
                                   np.asarray(sparse["w"]),
                                   rtol=2e-4, atol=2e-5)

    def test_threshold_restart_preserves_trajectory(self):
        """The alpha>threshold restart (FirstOrderOptimizer.cpp:86-113
        needSpecialTraversal + finishBatch) rescales u and reassigns v
        without changing the represented parameter."""
        params, grads = _toy_problem(steps=40)
        ref, _ = _run(opt.SparseMomentum(momentum=0.9, learning_rate=0.05),
                      dict(params), grads)
        restarting = opt.SparseMomentum(momentum=0.9, learning_rate=0.05)
        restarting.threshold = 5.0  # alpha=1/0.9^t crosses 5 every ~15 steps
        got, state = _run(restarting, dict(params), grads)
        assert float(state["slots"]["w"]["alpha"]) < 5.0 / 0.9 + 1e-3
        np.testing.assert_allclose(np.asarray(ref["w"]), np.asarray(got["w"]),
                                   rtol=2e-4, atol=2e-5)

    def test_decay_is_decoupled_weight_decay(self):
        """beta carries the decay term as true decoupled weight decay:

            mom_t   = k * mom_{t-1} - lr * g_t
            theta_t = (1 - lambda*lr) * theta_{t-1} + mom_t

        NOTE this deliberately fixes the reference's sign
        (FirstOrderOptimizer.cpp:54 divides beta by (1 + lambda*gamma),
        under which decay GROWS theta by (1+lambda*lr) per step — verified
        against a direct transcription; see the SparseMomentum docstring)."""
        params, grads = _toy_problem()
        lam, lr, k = 0.01, 0.05, 0.9
        specs = {"w": _spec("w", (8, 4), decay_rate=lam)}
        sparse, _ = _run(opt.SparseMomentum(momentum=k, learning_rate=lr),
                         dict(params), grads, specs)
        theta = np.asarray(params["w"], np.float64)
        mom = np.zeros_like(theta)
        for g in grads:
            mom = k * mom - lr * np.asarray(g["w"], np.float64)
            theta = (1.0 - lam * lr) * theta + mom
        np.testing.assert_allclose(theta, np.asarray(sparse["w"]),
                                   rtol=2e-4, atol=2e-5)

    def test_decay_shrinks_with_zero_gradient(self):
        """With g=0, decay must shrink the parameter, never amplify it."""
        import jax.numpy as jnp

        o = opt.SparseMomentum(momentum=0.9, learning_rate=0.05,
                               regularization=opt.L2Regularization(0.1))
        params = {"w": jnp.ones((4,), jnp.float32)}
        state = o.init(params)
        for _ in range(50):
            params, state = o.apply({"w": jnp.zeros((4,))}, params, state)
        assert float(np.abs(np.asarray(params["w"])).max()) < 1.0

    def test_spec_zero_momentum_rejected_per_param(self):
        import jax.numpy as jnp

        o = opt.SparseMomentum(momentum=0.9, learning_rate=0.05)
        specs = {"w": _spec("w", (4,), momentum=0.0)}
        params = {"w": jnp.ones((4,))}
        state = o.init(params, specs)
        with pytest.raises(ValueError, match="momentum > 0"):
            o.apply({"w": jnp.ones((4,))}, params, state, specs)

    def test_zero_momentum_rejected(self):
        with pytest.raises(ValueError, match="momentum > 0"):
            opt.SparseMomentum(momentum=0.0)


class TestFactorySurfaces:
    def test_from_config_sparse_momentum(self):
        class Cfg:
            learning_method = "sparse_momentum"
            learning_rate = 0.1
            gradient_clipping_threshold = 0.0
            learning_rate_schedule = "constant"
            learning_rate_decay_a = 0.0
            learning_rate_decay_b = 0.0
            learning_rate_warmup_steps = 0
            l1_rate = 0.0
            l2_rate = 0.0
            average_window = 0.0
            max_average_window = 0
            momentum = 0.9

        o = opt.from_config(Cfg())
        assert isinstance(o, opt.SparseMomentum)

    def test_from_config_unknown_method_is_loud(self):
        class Cfg:
            learning_method = "adamw_totally_unknown"
            learning_rate = 0.1
            gradient_clipping_threshold = 0.0
            learning_rate_schedule = "constant"
            learning_rate_decay_a = 0.0
            learning_rate_decay_b = 0.0
            learning_rate_warmup_steps = 0
            l1_rate = 0.0
            l2_rate = 0.0
            average_window = 0.0
            max_average_window = 0

        with pytest.raises(ValueError, match="unknown learning_method"):
            opt.from_config(Cfg())

    def test_settings_every_reference_method_builds(self):
        """No reference settings() learning_method form may KeyError."""
        import paddle_tpu.trainer_config_helpers as tch

        for method in ("momentum", "torch_momentum", "sparse_momentum",
                       "adagrad", "decayed_adagrad", "adadelta", "rmsprop",
                       "adam", "adamax", "sgd", "ftrl", None):
            tch.settings(batch_size=16, learning_rate=0.1,
                         learning_method=method)
            o = tch.optimizers.get_settings_optimizer()
            assert isinstance(o, opt.Optimizer), method
        tch.settings(batch_size=16, learning_rate=0.1,
                     learning_method="sparse_momentum")
        assert isinstance(tch.optimizers.get_settings_optimizer(),
                          opt.SparseMomentum)

    def test_settings_unknown_method_is_loud(self):
        import paddle_tpu.trainer_config_helpers as tch

        tch.settings(batch_size=16, learning_rate=0.1,
                     learning_method="lbfgs_not_a_method")
        with pytest.raises(ValueError, match="not a supported"):
            tch.optimizers.get_settings_optimizer()

    def test_momentum_object_sparse_selects_sparse_momentum(self):
        """MomentumOptimizer(momentum, sparse=True) is the reference's
        spelling for sparse_momentum (optimizers.py:100)."""
        import paddle_tpu.trainer_config_helpers as tch

        tch.settings(batch_size=16, learning_rate=0.1,
                     learning_method=tch.MomentumOptimizer(0.9, sparse=True))
        o = tch.optimizers.get_settings_optimizer()
        assert isinstance(o, opt.SparseMomentum)
        assert o.momentum == 0.9
        tch.settings(batch_size=16, learning_rate=0.1,
                     learning_method=tch.MomentumOptimizer(0.8))
        o = tch.optimizers.get_settings_optimizer()
        assert isinstance(o, opt.Momentum) and not isinstance(
            o, opt.SparseMomentum)
        assert o.momentum == 0.8


class TestFactoryEdgeCases:
    def test_sgd_spec_momentum_survives_apply_without_specs(self):
        """The coefficient rides in the velocity slot: init with specs then
        apply without them (checkpoint-restored generic step) must not
        crash and must keep the momentum trajectory."""
        import jax.numpy as jnp

        o = opt.SGD(learning_rate=0.1)
        specs = {"w": _spec("w", (4,), momentum=0.9)}
        params = {"w": jnp.ones((4,))}
        state = o.init(params, specs)
        g = {"w": jnp.ones((4,))}
        p_spec, s_spec = o.apply(g, dict(params), o.init(params, specs), specs)
        p_none, _ = o.apply(g, dict(params), state)  # no specs passed
        np.testing.assert_allclose(np.asarray(p_spec["w"]),
                                   np.asarray(p_none["w"]))

    def test_settings_string_path_forwards_momentum(self):
        import paddle_tpu.trainer_config_helpers as tch

        tch.settings(batch_size=16, learning_rate=0.1,
                     learning_method="sparse_momentum", momentum=0.5)
        o = tch.optimizers.get_settings_optimizer()
        assert isinstance(o, opt.SparseMomentum) and o.momentum == 0.5
        tch.settings(batch_size=16, learning_rate=0.1,
                     learning_method="momentum", momentum=0.4)
        o = tch.optimizers.get_settings_optimizer()
        assert isinstance(o, opt.Momentum) and o.momentum == 0.4

    def test_settings_forwards_model_average(self):
        """settings(model_average=ModelAverage(...)) must reach the built
        optimizer (else the apply-at-eval feature is silently inert)."""
        import paddle_tpu.trainer_config_helpers as tch

        tch.settings(batch_size=16, learning_rate=0.1,
                     learning_method="momentum",
                     model_average=tch.optimizers.ModelAverage(
                         average_window=0.5, max_average_window=300))
        o = tch.optimizers.get_settings_optimizer()
        assert o.model_average is not None
        assert o.model_average.average_window == 0.5
        assert o.model_average.max_average_window == 300
        import jax.numpy as jnp

        state = o.init({"w": jnp.zeros((2,))})
        assert "avg" in state

    def test_from_config_momentum_from_extra_kwargs(self):
        """settings()-built configs keep momentum in extra kwargs (the
        OptimizationConfig proto has no global momentum field)."""

        class Cfg:
            learning_method = "sparse_momentum"
            learning_rate = 0.1
            gradient_clipping_threshold = 0.0
            learning_rate_schedule = "constant"
            learning_rate_decay_a = 0.0
            learning_rate_decay_b = 0.0
            learning_rate_warmup_steps = 0
            l1_rate = 0.0
            l2_rate = 0.0
            average_window = 0.0
            max_average_window = 0
            extra = {"momentum": 0.7}

        assert opt.from_config(Cfg()).momentum == 0.7


class TestPerParamMomentum:
    def test_spec_momentum_under_sgd_equals_momentum_optimizer(self):
        """ParameterConfig.momentum drives the update even under plain sgd
        (reference SgdOptimizer::update uses paraConfig.momentum())."""
        params, grads = _toy_problem()
        specs = {"w": _spec("w", (8, 4), momentum=0.9)}
        via_spec, _ = _run(opt.SGD(learning_rate=0.05), dict(params), grads,
                           specs)
        via_opt, _ = _run(opt.Momentum(momentum=0.9, learning_rate=0.05),
                          dict(params), grads)
        np.testing.assert_allclose(np.asarray(via_spec["w"]),
                                   np.asarray(via_opt["w"]), rtol=1e-6)

    def test_spec_momentum_overrides_optimizer_momentum(self):
        params, grads = _toy_problem()
        specs = {"w": _spec("w", (8, 4), momentum=0.5)}
        overridden, _ = _run(opt.Momentum(momentum=0.9, learning_rate=0.05),
                             dict(params), grads, specs)
        direct, _ = _run(opt.Momentum(momentum=0.5, learning_rate=0.05),
                         dict(params), grads)
        np.testing.assert_allclose(np.asarray(overridden["w"]),
                                   np.asarray(direct["w"]), rtol=1e-6)

    def test_default_momentum_flows_into_param_specs(self):
        """config-level default_momentum() lands in ParamSpec.momentum
        (the reference's g_default_momentum -> ParameterConfig path)."""
        from paddle_tpu.config import parse_state
        from paddle_tpu.layers import api as layer, base, data_type

        base.reset_name_counters()
        parse_state.reset_defaults()
        parse_state.default_momentum(0.75)
        try:
            x = layer.data(name="dmx", type=data_type.dense_vector(4))
            h = layer.fc(input=x, size=2, bias_attr=False)
            spec = [s for s in h.param_specs if "w" in s.name.lower()
                    or s.shape == (4, 2)][0]
            assert spec.momentum == 0.75
        finally:
            parse_state.reset_defaults()


class TestModelAverage:
    def test_averaged_eval_beats_raw_on_noisy_toy(self):
        """Noisy-gradient quadratic: the averaged iterate is closer to the
        optimum than the oscillating raw iterate (the reason
        AverageOptimizer::apply() exists)."""
        import jax.numpy as jnp

        rng = np.random.default_rng(3)
        target = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
        o = opt.SGD(learning_rate=0.35,
                    model_average=opt.ModelAverage(average_window=0.5,
                                                   max_average_window=200))
        params = {"w": jnp.zeros((16,), jnp.float32)}
        state = o.init(params)
        for _ in range(120):
            noise = jnp.asarray(rng.normal(
                scale=2.0, size=(16,)).astype(np.float32))
            grads = {"w": (params["w"] - target) + noise}
            params, state = o.apply(grads, params, state)
        avg = o.averaged(state)
        assert avg is not None
        err_raw = float(jnp.linalg.norm(params["w"] - target))
        err_avg = float(jnp.linalg.norm(avg["w"] - target))
        assert err_avg < err_raw, (err_avg, err_raw)

    def test_trainer_test_applies_average(self):
        """SGD.test() swaps averaged parameters in when an average is kept;
        on the noisy toy that must beat evaluating the raw weights."""
        import paddle_tpu as paddle

        rng = np.random.default_rng(0)
        from paddle_tpu.layers import activation, api as layer, base, data_type

        base.reset_name_counters()
        x = layer.data(name="avx", type=data_type.dense_vector(8))
        y = layer.data(name="avy", type=data_type.dense_vector(1))
        pred = layer.fc(input=x, size=1, act=activation.LinearActivation(),
                        bias_attr=False)
        cost = layer.square_error_cost(input=pred, label=y)
        parameters = paddle.parameters.create(paddle.topology.Topology(cost))
        optimizer = paddle.optimizer.SGD(
            learning_rate=0.6,
            model_average=opt.ModelAverage(average_window=0.5,
                                           max_average_window=400))
        trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                     update_equation=optimizer)
        w_true = rng.normal(size=(8, 1)).astype(np.float32)

        def train_reader():
            r = np.random.default_rng(1)
            for _ in range(80):
                v = r.normal(size=(8,)).astype(np.float32)
                noise = r.normal(scale=1.5)
                yield v, (v @ w_true + noise).astype(np.float32)

        def test_reader():
            r = np.random.default_rng(2)
            for _ in range(16):
                v = r.normal(size=(8,)).astype(np.float32)
                yield v, (v @ w_true).astype(np.float32)

        trainer.train(reader=paddle.reader.batch(train_reader, 8),
                      num_passes=1)
        assert trainer.optimizer.averaged(trainer._opt_state) is not None
        cost_avg = trainer.test(
            reader=paddle.reader.batch(test_reader, 16)).cost
        # drop the average and re-test: raw weights must do worse
        trainer._opt_state = {k: v for k, v in trainer._opt_state.items()
                              if k not in ("avg", "avg_count")}
        cost_raw = trainer.test(
            reader=paddle.reader.batch(test_reader, 16)).cost
        assert cost_avg < cost_raw, (cost_avg, cost_raw)

    def test_averaged_parameters_for_inference(self):
        """averaged_parameters() hands the averaged weights to infer()."""
        import paddle_tpu as paddle
        from paddle_tpu.layers import activation, api as layer, base, data_type

        base.reset_name_counters()
        x = layer.data(name="aix", type=data_type.dense_vector(4))
        y = layer.data(name="aiy", type=data_type.dense_vector(1))
        pred = layer.fc(input=x, size=1, act=activation.LinearActivation(),
                        bias_attr=False, param_attr=paddle.attr.Param(
                            name="ai_w"))
        cost = layer.square_error_cost(input=pred, label=y)
        parameters = paddle.parameters.create(paddle.topology.Topology(cost))
        optimizer = paddle.optimizer.SGD(
            learning_rate=0.5,
            model_average=opt.ModelAverage(average_window=0.5,
                                           max_average_window=100))
        trainer = paddle.trainer.SGD(cost=cost, parameters=parameters,
                                     update_equation=optimizer)
        rng = np.random.default_rng(0)

        def reader():
            for _ in range(24):
                v = rng.normal(size=(4,)).astype(np.float32)
                yield v, np.asarray([v.sum()], dtype=np.float32)

        trainer.train(reader=paddle.reader.batch(reader, 8), num_passes=1)
        avg_params = trainer.averaged_parameters()
        raw = np.asarray(trainer.parameters["ai_w"])
        avg = np.asarray(avg_params["ai_w"])
        assert avg.shape == raw.shape
        assert not np.allclose(raw, avg)  # oscillating weights => differ


class TestAdamMomentDtype:
    """Opt-in low-precision Adam moments (the LM accounting's HBM lever):
    storage dtype changes, update math stays f32, trajectory stays close
    to the f32-moment baseline."""

    def test_default_unchanged_f32(self):
        import jax.numpy as jnp

        params, grads = _toy_problem()
        a = opt.Adam(learning_rate=1e-2)
        p_ref, st = _run(a, dict(params), grads)
        assert st["slots"]["w"]["m"].dtype == jnp.float32

    def test_bf16_moments_dtype_and_close_trajectory(self):
        import jax.numpy as jnp

        params, grads = _toy_problem(steps=50)
        ref, _ = _run(opt.Adam(learning_rate=1e-2), dict(params), grads)
        a16 = opt.Adam(learning_rate=1e-2, moment_dtype=jnp.bfloat16)
        got, st = _run(a16, dict(params), grads)
        assert st["slots"]["w"]["m"].dtype == jnp.bfloat16
        assert st["slots"]["w"]["v"].dtype == jnp.bfloat16
        # parameters remain f32 and track the f32-moment run closely
        assert got["w"].dtype == jnp.float32
        diff = float(jnp.max(jnp.abs(got["w"] - ref["w"])))
        scale = float(jnp.max(jnp.abs(ref["w"] - params["w"])))
        assert diff < 0.05 * scale, (diff, scale)

    def test_bf16_moments_tree_api_converges(self):
        """apply_tree path (the transformer family): a least-squares
        problem reaches the same loss region as f32 moments."""
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        w_true = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
        x = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32))
        y = x @ w_true

        def losses_for(optimizer, steps=120):
            params = {"w": jnp.zeros((16, 4), jnp.float32)}
            state = optimizer.init_tree(params)

            @jax.jit
            def step(params, state):
                def loss_fn(p):
                    return jnp.mean((x @ p["w"] - y) ** 2)

                l, g = jax.value_and_grad(loss_fn)(params)
                params, state = optimizer.apply_tree(g, params, state)
                return params, state, l

            out = []
            for _ in range(steps):
                params, state, l = step(params, state)
                out.append(float(l))
            return out

        ref = losses_for(opt.Adam(learning_rate=5e-2))
        got = losses_for(opt.Adam(learning_rate=5e-2,
                                  moment_dtype=jnp.bfloat16))
        assert got[-1] < ref[0] * 0.05       # actually converges
        assert got[-1] < max(ref[-1] * 3.0, 1e-3), (got[-1], ref[-1])
