"""Model-zoo tests — shape/cost sanity for the benchmark nets
(reference: `benchmark/paddle/image/*.py`, run by `run.sh`).  Full-size
forwards for the big nets are exercised by bench.py; here we keep CI fast:
smallnet trains a step, the big nets just build + serialize."""

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.config.topology import Topology
from paddle_tpu.models import image as M
from paddle_tpu.optimizer import Momentum
from paddle_tpu.trainer.step import build_train_step


def test_smallnet_trains_a_step():
    cost, predict, img, label = M.smallnet_cost()
    topo = Topology(cost)
    params = paddle.parameters.create(topo)
    opt = Momentum(momentum=0.9, learning_rate=0.01 / 16)
    step = build_train_step(topo, opt)
    feed = {
        "image": np.random.default_rng(0).normal(size=(16, 32 * 32 * 3)).astype(np.float32),
        "label": np.arange(16) % 10,
    }
    p = params.as_dict()
    before = {k: np.asarray(v).copy() for k, v in p.items()}  # step donates p
    opt_state = opt.init(p, {s.name: s for s in topo.param_specs()})
    p2, _, _, cost_val, metrics = step(p, opt_state, topo.init_states(), feed, jax.random.key(0))
    assert np.isfinite(float(cost_val))
    assert "classification_error_evaluator" in metrics
    moved = any(
        not np.allclose(np.asarray(p2[k]), v) for k, v in before.items()
    )
    assert moved


@pytest.mark.parametrize(
    "builder,n_params",
    [(M.alexnet_cost, 16), (M.resnet_cost, 161), (M.googlenet_cost, 116), (M.vgg_cost, 38)],
)
def test_big_nets_build(builder, n_params):
    cost, predict, img, label = builder()
    topo = Topology(cost)
    assert len(topo.param_specs()) == n_params
    # abstract evaluation (no FLOPs) validates every layer's shape math
    specs = {s.name: s for s in topo.param_specs()}
    feed = {
        "image": jax.ShapeDtypeStruct((2, 224 * 224 * 3), np.float32)
        if "alexnet" not in builder.__name__
        else jax.ShapeDtypeStruct((2, 227 * 227 * 3), np.float32),
        "label": jax.ShapeDtypeStruct((2,), np.int32),
    }
    params = {n: jax.ShapeDtypeStruct(s.shape, s.dtype) for n, s in specs.items()}
    states = {
        s.name: jax.ShapeDtypeStruct(s.shape, np.float32) for s in topo.state_specs()
    }
    out = jax.eval_shape(
        lambda p, st, f: topo.forward(p, st, f, False, jax.random.key(0))[0][
            predict.name
        ],
        params, states, feed,
    )
    assert out.shape == (2, 1000)
    assert topo.serialize()  # config record is stable/serializable
