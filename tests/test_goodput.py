"""Goodput ledger — wall-clock badput attribution (telemetry/goodput.py).

The acceptance properties of the ledger:

- a fake-clock chaos timeline (slow reader, NaN rescue with nested
  restore, elastic drain+reshard, recompile, supervisor restart) lands
  every injected second in its named bucket and the buckets sum to the
  wall-clock exactly;
- ``fold()`` is incremental over ring snapshots (each span classified
  once, new spans picked up on the next fold);
- the closing record is a schema/14 ``kind="ledger"`` emission, sets
  the ``goodput_fraction`` gauge, and appends to ledger.jsonl;
- a REAL 50-step CPU chaos run (nan-skip + one elastic 8→4 reshard +
  prefetch-starved reader) through the trainer produces a ledger whose
  buckets sum to wall-clock within 1% with each fault visible;
- arming the ledger never changes the training trajectory — final
  parameters are bit-identical to a ledger-off run.
"""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags, rng as prng
from paddle_tpu.layers import api as layer, base, data_type
from paddle_tpu.parallel import mesh as mesh_mod
from paddle_tpu.resilience.chaos import ChaosSchedule
from paddle_tpu.resilience.elastic import ElasticCoordinator
from paddle_tpu.telemetry import MemorySink, MetricsRegistry
from paddle_tpu.telemetry.goodput import (
    BADPUT_BUCKETS,
    BUCKETS,
    GoodputLedger,
    serving_costs,
)
from paddle_tpu.telemetry.tracing import Tracer, configure_tracing


@pytest.fixture(autouse=True)
def _restore_tracing_and_flags():
    """The trainer arms the global tracer when --goodput_ledger is on
    and never disarms it; undo that (and any flag edits) per test."""
    prev = flags.snapshot_raw()
    yield
    flags.restore_raw(prev)
    configure_tracing(enabled=bool(flags.get("trace_spans")))


class _Clock:
    """Manually-advanced fake clock shared by tracer and ledger."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _ledger(reg=None):
    clk = _Clock()
    tracer = Tracer(enabled=True, rank=0, clock=clk)
    reg = reg or MetricsRegistry("goodput_test")
    return GoodputLedger(registry=reg, tracer=tracer).start(), tracer, clk, reg


# -- the fake-clock chaos timeline --------------------------------------------


def test_chaos_windows_land_in_their_buckets_and_sum_to_wall():
    """Every injected chaos window books its named bucket with exactly
    the injected seconds, idle absorbs the rest, and the closing
    account sums to the wall-clock."""
    led, tracer, clk, reg = _ledger()

    # slow reader: the consumer blocked 2.0s on the feed
    tracer.add_span("feed", 0.0, 2.0, cat="trainer")
    # first dispatch built a new executable: 3.0s of recompile
    tracer.add_span("compute", 2.0, 5.0, cat="trainer", compile=True)
    # steady-state step: 1.0s productive compute
    tracer.add_span("compute", 5.0, 6.0, cat="trainer", compile=False)
    tracer.add_span("fence", 6.0, 6.5, cat="trainer")
    # nan@k rescue (2.0s) that restored from checkpoint (nested 1.0s):
    # the restore second lands in checkpoint_restore, NOT twice
    tracer.add_span("restore", 7.5, 8.5, cat="trainer")
    tracer.add_span("guard_rescue", 7.0, 9.0, cat="trainer", policy="rollback")
    # host_loss@k:dp=4: drain checkpoint then the live mesh rebuild
    tracer.add_span("drain", 9.0, 10.0, cat="elastic")
    tracer.add_span("gather", 10.0, 10.5, cat="elastic")
    tracer.add_span("reshard", 10.5, 11.0, cat="elastic")
    tracer.add_span("rebuild", 11.0, 11.5, cat="elastic")
    tracer.add_span("checkpoint", 11.5, 12.0, cat="trainer")
    # parent/overlapping spans must NOT double-count
    tracer.add_span("step", 0.0, 12.0, cat="trainer")
    tracer.add_span("prefetch", 0.0, 12.0, cat="prefetch")
    # supervisor restart: the counter delta prices the recovery gauge in
    reg.counter("restarts", "").inc(run="train")
    reg.gauge("recovery_ms", "").set(500.0, run="train")
    reg.gauge("recovery_ms", "").set(9999.0, run="elastic")  # excluded

    clk.t = 20.0
    rec = led.finish()
    b = rec["buckets_s"]
    assert b["input_wait"] == pytest.approx(2.0)
    assert b["recompile"] == pytest.approx(3.0)
    assert b["compute"] == pytest.approx(1.0)
    assert b["fence"] == pytest.approx(0.5)
    assert b["guard_rescue"] == pytest.approx(1.0)      # 2.0 - nested 1.0
    assert b["checkpoint_restore"] == pytest.approx(1.0)
    assert b["elastic_drain"] == pytest.approx(1.0)
    assert b["elastic_reshard"] == pytest.approx(1.5)
    assert b["checkpoint_save"] == pytest.approx(0.5)
    assert b["restart"] == pytest.approx(0.5)           # 1 restart x 500ms
    assert b["idle"] == pytest.approx(20.0 - 12.0)
    assert rec["wall_s"] == pytest.approx(20.0)
    assert sum(b.values()) == pytest.approx(rec["wall_s"], rel=0.01)
    assert rec["goodput_fraction"] == pytest.approx(1.0 / 20.0)
    assert rec["badput_fraction"] == pytest.approx(19.0 / 20.0)
    assert set(b) == set(BUCKETS)
    assert set(BADPUT_BUCKETS) == set(BUCKETS) - {"compute"}


def test_fold_is_incremental_over_ring_snapshots():
    led, tracer, clk, _ = _ledger()
    tracer.add_span("feed", 0.0, 1.0, cat="trainer")
    tracer.add_span("compute", 1.0, 2.0, cat="trainer")
    assert led.fold() == 2
    assert led.fold() == 0          # nothing new -> nothing reclassified
    tracer.add_span("fence", 2.0, 2.5, cat="trainer")
    assert led.fold() == 1
    snap = led.snapshot()
    assert snap["input_wait"] == pytest.approx(1.0)
    assert snap["compute"] == pytest.approx(1.0)
    assert snap["fence"] == pytest.approx(0.5)
    clk.t = 3.0
    rec = led.finish()
    assert rec["spans_folded"] == 3
    assert rec["spans_dropped"] == 0


def test_finish_emits_ledger_record_gauge_and_jsonl(tmp_path):
    reg = MetricsRegistry("goodput_emit")
    sink = MemorySink()
    reg.add_sink(sink)
    led, tracer, clk, _ = _ledger(reg)
    tracer.add_span("compute", 0.0, 3.0, cat="trainer")
    clk.t = 4.0
    path = str(tmp_path / "ledger.jsonl")
    rec = led.finish(path=path)
    assert rec["kind"] == "ledger"
    assert rec["schema"].endswith("/15")
    assert reg.get("goodput_fraction").value() == pytest.approx(0.75)
    recs = [r for r in sink.records if r.get("kind") == "ledger"]
    assert len(recs) == 1
    with open(path) as f:
        lines = [json.loads(ln) for ln in f]
    assert len(lines) == 1 and lines[0]["buckets_s"] == rec["buckets_s"]


def test_serving_costs_split_and_absence():
    reg = MetricsRegistry("goodput_serving")
    assert serving_costs(reg) == {}     # nothing served -> no section
    reg.counter("serve_prefill_compute_s", "").inc(3.0)
    reg.counter("serve_decode_compute_s", "").inc(7.0)
    reg.counter("serve_queue_s", "").inc(2.0)
    reg.counter("serve_kv_page_s", "").inc(40.0)
    reg.counter("serve_tokens", "").inc(1000)
    c = serving_costs(reg)
    assert c["cost_per_token_s"] == pytest.approx(0.01)
    assert c["cost_per_token_prefill_s"] == pytest.approx(0.003)
    assert c["cost_per_token_decode_s"] == pytest.approx(0.007)
    assert c["cost_per_token_queue_s"] == pytest.approx(0.002)
    assert c["kv_page_s"] == pytest.approx(40.0)
    assert c["tokens"] == 1000


# -- the real 50-step CPU chaos run -------------------------------------------

IN_DIM, HIDDEN, CLASSES = 8, 16, 4


def _trainer(mesh_ctx=None, zero=0):
    from paddle_tpu.layers import activation as act

    base.reset_name_counters()
    prng.seed(7)
    x = layer.data(name="x", type=data_type.dense_vector(IN_DIM))
    h = layer.fc(input=x, size=HIDDEN, act=act.ReluActivation())
    predict = layer.fc(input=h, size=CLASSES, act=act.SoftmaxActivation())
    lbl = layer.data(name="y", type=data_type.integer_value(CLASSES))
    cost = layer.classification_cost(input=predict, label=lbl)
    params = paddle.parameters.create(paddle.topology.Topology(cost))
    kw = {}
    if mesh_ctx is not None:
        kw = {"mesh": mesh_ctx, "zero": zero}
    return paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(momentum=0.9,
                                                  learning_rate=0.05), **kw)


def _reader(batches=50, bs=8, delay_s=0.0):
    def r():
        rs = np.random.RandomState(0)
        for i in range(batches * bs):
            if delay_s and i % bs == 0:
                time.sleep(delay_s)  # prefetch-starved reader
            yield rs.randn(IN_DIM).astype(np.float32), int(i % CLASSES)

    return paddle.reader.batch(r, bs)


def _mesh(dp):
    import jax

    return mesh_mod.MeshContext(
        mesh=mesh_mod.make_mesh({"data": dp}, devices=jax.devices()[:dp]))


@pytest.mark.elastic
def test_fifty_step_chaos_run_ledger_sums_to_wall(tmp_path):
    """The ISSUE's acceptance run: 50 steps on CPU with a nan-skip at
    step 7, one elastic 8→4 reshard at step 25, and a prefetch-starved
    reader — the closing ledger must sum to wall-clock within 1% and
    show every injected fault in its bucket."""
    prev_mesh = mesh_mod._current
    flags.set("goodput_ledger", True)
    flags.set("ledger_dir", str(tmp_path))
    reg = MetricsRegistry("chaos_ledger")
    reg.add_sink(MemorySink())
    try:
        tr = _trainer(_mesh(8), zero=2)
        coord = ElasticCoordinator(registry=reg)
        sched = ChaosSchedule("nan@7,host_loss@25:dp=4",
                              registry=reg).bind_elastic(coord)
        tr.train(reader=sched.wrap_reader(_reader(delay_s=0.002)),
                 num_passes=1, nan_policy="skip",
                 checkpoint_dir=str(tmp_path / "ck"),
                 event_handler=sched.wrap_event_handler(None),
                 elastic=coord, metrics_registry=reg)
    finally:
        mesh_mod._current = prev_mesh

    with open(os.path.join(str(tmp_path), "ledger.jsonl")) as f:
        (rec,) = [json.loads(ln) for ln in f]
    b = rec["buckets_s"]
    assert sum(b.values()) == pytest.approx(rec["wall_s"], rel=0.01)
    assert rec["spans_dropped"] == 0
    assert b["compute"] > 0                      # steady-state steps
    assert b["recompile"] > 0                    # first-signature builds
    assert b["input_wait"] > 0                   # the starved reader
    assert b["guard_rescue"] > 0                 # nan@7 skip handling
    assert b["elastic_drain"] > 0                # drain ckpt before rebuild
    assert b["elastic_reshard"] > 0              # the 8→4 rebuild
    assert 0.0 < rec["goodput_fraction"] < 1.0
    assert reg.get("goodput_fraction").value() == pytest.approx(
        rec["goodput_fraction"], abs=1e-6)
    assert dict(tr.mesh.mesh.shape) == {"data": 4}


def test_trajectory_bit_identical_with_ledger_enabled():
    """Arming the ledger adds zero perturbation: the final parameters
    of a ledger-on run equal a ledger-off run bit-for-bit."""
    def run(enabled):
        flags.set("goodput_ledger", enabled)
        configure_tracing(enabled=False)
        tr = _trainer()
        tr.train(reader=_reader(batches=6), num_passes=1,
                 metrics_registry=MetricsRegistry("traj"))
        return {n: np.asarray(tr.parameters[n]) for n in
                tr.parameters.names()}

    off = run(False)
    on = run(True)
    assert off.keys() == on.keys()
    for n in off:
        np.testing.assert_array_equal(off[n], on[n], err_msg=n)
