"""recurrent_group / memory / beam_search / seq2seq tests.

Mirrors the reference's RecurrentGradientMachine tests
(``paddle/gserver/tests/test_RecurrentGradientMachine.cpp``,
``test_recurrent_machine_generation.cpp``) with numeric golden checks instead
of golden model dirs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.config.topology import Topology
from paddle_tpu.core.lod import SequenceBatch
from paddle_tpu.core.parameters import Parameters
from paddle_tpu.layers import api as layer
from paddle_tpu.layers import data_type
from paddle_tpu.layers.base import reset_name_counters
from paddle_tpu.layers.mixed import identity_projection, mixed
from paddle_tpu.layers.recurrent_group import (
    GeneratedSequence,
    StaticInput,
    memory,
    recurrent_group,
)


@pytest.fixture(autouse=True)
def _fresh_names():
    reset_name_counters()
    yield


def _run(topology, feed, params=None):
    p = params or Parameters.from_specs(topology.param_specs(),
                                        key=jax.random.PRNGKey(0))
    vals, _ = topology.forward(p.as_dict(), topology.init_states(), feed,
                               is_train=False)
    return vals, p


def test_recurrent_group_cumsum_semantics():
    """step out = x_t + out_{t-1} -> masked cumulative sum (golden check of
    scan + memory wiring, no parameters involved)."""
    d = 4
    x = layer.data(name="x", type=data_type.dense_vector_sequence(d))

    def step(xt):
        mem = memory(name="acc", size=d)
        return mixed(size=d, name="acc",
                     input=[identity_projection(xt), identity_projection(mem)])

    out = recurrent_group(step=step, input=x)
    topo = Topology(out)

    data = np.random.RandomState(0).randn(2, 5, d).astype(np.float32)
    length = np.array([5, 3], np.int32)
    feed = {"x": SequenceBatch(jnp.asarray(data), jnp.asarray(length))}
    vals, _ = _run(topo, feed)
    got = np.asarray(vals[out.name].data)
    want = np.cumsum(data, axis=1)
    # valid region matches cumsum
    np.testing.assert_allclose(got[0], want[0], rtol=1e-5)
    np.testing.assert_allclose(got[1, :3], want[1, :3], rtol=1e-5)


def test_memory_boot_layer():
    d = 3
    x = layer.data(name="x", type=data_type.dense_vector_sequence(d))
    boot = layer.data(name="boot", type=data_type.dense_vector(d))

    def step(xt):
        mem = memory(name="acc", size=d, boot_layer=boot)
        return mixed(size=d, name="acc",
                     input=[identity_projection(xt), identity_projection(mem)])

    out = recurrent_group(step=step, input=x)
    topo = Topology(out)
    data = np.ones((1, 2, d), np.float32)
    feed = {
        "x": SequenceBatch(jnp.asarray(data), jnp.asarray([2])),
        "boot": jnp.full((1, d), 10.0),
    }
    vals, _ = _run(topo, feed)
    got = np.asarray(vals[out.name].data)
    np.testing.assert_allclose(got[0, 0], 11.0)  # 1 + boot
    np.testing.assert_allclose(got[0, 1], 12.0)


def test_recurrent_group_reverse():
    d = 2
    x = layer.data(name="x", type=data_type.dense_vector_sequence(d))

    def step(xt):
        mem = memory(name="acc", size=d)
        return mixed(size=d, name="acc",
                     input=[identity_projection(xt), identity_projection(mem)])

    out = recurrent_group(step=step, input=x, reverse=True)
    topo = Topology(out)
    data = np.random.RandomState(1).randn(1, 4, d).astype(np.float32)
    feed = {"x": SequenceBatch(jnp.asarray(data), jnp.asarray([4]))}
    vals, _ = _run(topo, feed)
    got = np.asarray(vals[out.name].data)
    want = np.cumsum(data[0][::-1], axis=0)[::-1]
    np.testing.assert_allclose(got[0], want, rtol=1e-5)


def test_seqtoseq_training_cost_and_grads():
    from paddle_tpu.models.seqtoseq import seqtoseq_net

    cost = seqtoseq_net(source_dict_dim=20, target_dict_dim=17,
                        word_vector_dim=8, encoder_size=8, decoder_size=8)
    topo = Topology(cost)
    params = Parameters.from_specs(topo.param_specs(),
                                   key=jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    feed = {
        "source_language_word": SequenceBatch(
            jnp.asarray(rs.randint(0, 20, (2, 6))), jnp.asarray([6, 4])),
        "target_language_word": SequenceBatch(
            jnp.asarray(rs.randint(0, 17, (2, 5))), jnp.asarray([5, 3])),
        "target_language_next_word": SequenceBatch(
            jnp.asarray(rs.randint(0, 17, (2, 5))), jnp.asarray([5, 3])),
    }

    def loss_fn(pvals):
        vals, _ = topo.forward(pvals, topo.init_states(), feed, is_train=False)
        return vals[cost.name]

    loss, grads = jax.value_and_grad(loss_fn)(params.as_dict())
    assert np.isfinite(float(loss))
    # every trainable parameter gets a gradient signal somewhere
    flat = jax.tree.leaves(grads)
    assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)
    nonzero = sum(float(jnp.sum(jnp.abs(g))) > 0 for g in flat)
    assert nonzero >= len(flat) - 2  # allow e.g. unused padding rows


def test_seqtoseq_beam_search_generation():
    from paddle_tpu.models.seqtoseq import seqtoseq_net

    gen = seqtoseq_net(source_dict_dim=20, target_dict_dim=17,
                       word_vector_dim=8, encoder_size=8, decoder_size=8,
                       is_generating=True, beam_size=3, max_length=7)
    topo = Topology(gen)
    params = Parameters.from_specs(topo.param_specs(),
                                   key=jax.random.PRNGKey(1))
    rs = np.random.RandomState(3)
    feed = {
        "source_language_word": SequenceBatch(
            jnp.asarray(rs.randint(0, 20, (2, 6))), jnp.asarray([6, 4])),
    }
    vals, _ = topo.forward(params.as_dict(), topo.init_states(), feed,
                           is_train=False)
    res = vals[gen.name]
    assert isinstance(res, GeneratedSequence)
    assert res.ids.shape == (2, 3, 7)
    scores = np.asarray(res.score)
    # beams sorted by score, best first
    assert np.all(np.diff(scores, axis=1) <= 1e-5)
    lens = np.asarray(res.length)
    assert np.all(lens >= 1) and np.all(lens <= 7)
    ids = np.asarray(res.ids)
    assert ids.min() >= 0 and ids.max() < 17
    # deterministic
    vals2, _ = topo.forward(params.as_dict(), topo.init_states(), feed,
                            is_train=False)
    np.testing.assert_array_equal(ids, np.asarray(vals2[gen.name].ids))
    # ragged python conversion works
    rows = res.to_list()
    assert len(rows) == 2 and len(rows[0]) == 3


def test_seqtoseq_train_generate_share_all_params_same_process():
    """Building the generation topology AFTER the training one (no counter
    reset, as a real user script does) must reference the same parameter
    names, or generation would silently run on fresh random weights."""
    from paddle_tpu.models.seqtoseq import seqtoseq_net

    cost = seqtoseq_net(20, 17, word_vector_dim=8, encoder_size=8,
                        decoder_size=8)
    train_names = {s.name for s in Topology(cost).param_specs()}
    gen = seqtoseq_net(20, 17, word_vector_dim=8, encoder_size=8,
                       decoder_size=8, is_generating=True, beam_size=2,
                       max_length=5)
    gen_names = {s.name for s in Topology(gen).param_specs()}
    # every generation parameter except the source-side-only data path must
    # exist in the trained set
    missing = gen_names - train_names
    assert not missing, f"generation params not trained: {missing}"


def test_scan_tail_sink_equivalence():
    """The sunk feed-forward tail (vocab fc outside the scan) is float-
    equal to the per-step application, for cost AND gradients, on the
    canonical NMT decoder step (simple_attention + gru_step -> fc)."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core import flags
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.layers import base, recurrent_group as rg
    from paddle_tpu.models import seqtoseq as S

    rng = np.random.default_rng(0)
    bs, tlen, vocab = 4, 6, 50

    def build():
        base.reset_name_counters()
        cost = S.seqtoseq_net(vocab, vocab, word_vector_dim=8,
                              encoder_size=8, decoder_size=8)
        topo = Topology(cost)
        return cost, topo

    def run(topo, cost, params):
        from paddle_tpu.layers.base import Context, evaluate

        def f(params):
            ctx = Context(is_train=True, key=jax.random.key(0))
            ids = rng_feed
            vals, _ = evaluate([cost], ctx, params, topo.init_states(), ids)
            v = vals[cost.name]
            return v if v.ndim == 0 else v.mean()

        loss, grads = jax.value_and_grad(f)(params)
        return loss, grads

    def seq(r):
        return SequenceBatch(data=r.integers(0, vocab, size=(bs, tlen)),
                             length=np.full((bs,), tlen, np.int32))

    r1 = np.random.default_rng(1)
    rng_feed = {"source_language_word": seq(r1),
                "target_language_word": seq(r1),
                "target_language_next_word": seq(r1)}

    prev_bf16 = flags.get("bf16")
    flags.set("bf16", False)
    try:
        from paddle_tpu.core import rng as prng

        assert rg.SINK_SCAN_TAIL
        cost, topo = build()
        prng.seed(11)
        params = paddle.parameters.create(topo).as_dict()
        loss_sink, grads_sink = run(topo, cost, params)

        rg.SINK_SCAN_TAIL = False
        cost2, topo2 = build()
        # identical init: same names + same seed path
        prng.seed(11)
        params2 = paddle.parameters.create(topo2).as_dict()
        for k in params:
            np.testing.assert_array_equal(np.asarray(params[k]),
                                          np.asarray(params2[k]))
        loss_ref, grads_ref = run(topo2, cost2, params2)
    finally:
        rg.SINK_SCAN_TAIL = True
        flags.set("bf16", prev_bf16)

    np.testing.assert_allclose(float(loss_sink), float(loss_ref),
                               rtol=1e-6)
    for k in grads_ref:
        np.testing.assert_allclose(
            np.asarray(grads_sink[k]), np.asarray(grads_ref[k]),
            rtol=1e-5, atol=1e-7, err_msg=k)


def test_fused_logits_ce_equivalence():
    """classification_cost's fused lse-based CE (via the #logits
    companion) equals the probs-path CE, for a DIRECT softmax fc and
    the NMT-style group with a sunk softmax tail — cost and grads."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core import flags, rng as prng
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer, base, data_type
    from paddle_tpu.layers.base import Context, evaluate

    flags.set("bf16", False)
    try:
        base.reset_name_counters()
        x = layer.data(name="fx", type=data_type.dense_vector(16))
        h = layer.fc(input=x, size=32, act=act.TanhActivation())
        out = layer.fc(input=h, size=7, act=act.SoftmaxActivation())
        assert "__fc_logits__" in out.attrs
        lbl = layer.data(name="fy", type=data_type.integer_value(7))
        cost = layer.classification_cost(input=out, label=lbl)
        # the fused path attached a hidden logits companion
        assert any(p.name.endswith("#logits") for p in cost.parents)
        topo = Topology(cost)
        prng.seed(3)
        params = paddle.parameters.create(topo).as_dict()
        r = np.random.default_rng(0)
        feed = {"fx": r.normal(size=(8, 16)).astype(np.float32),
                "fy": r.integers(0, 7, size=(8,))}

        def f(params):
            vals, _ = evaluate([cost], Context(is_train=True,
                                               key=jax.random.key(0)),
                               params, topo.init_states(), feed)
            return vals[cost.name].mean()

        loss, grads = jax.value_and_grad(f)(params)
        # reference: -log(softmax[y]) computed by hand
        w1 = params[[k for k in params if "fc_layer_0" in k and "w" in k
                     and "bias" not in k][0]]
        logits_h = np.tanh(feed["fx"] @ np.asarray(w1))
        wk = [k for k in params if "fc_layer_1" in k]
        w2 = np.asarray(params[[k for k in wk if k.endswith(".w0")][0]])
        b2 = np.asarray(params[[k for k in wk if "bias" in k][0]])
        lg = logits_h @ w2 + b2
        lse = np.log(np.exp(lg - lg.max(1, keepdims=True)).sum(1)) \
            + lg.max(1)
        ref = float(np.mean(lse - lg[np.arange(8), feed["fy"]]))
        np.testing.assert_allclose(float(loss), ref, rtol=1e-5)
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree.leaves(grads))
    finally:
        flags.set("bf16", False)


def test_sink_rejects_static_input_tail():
    """A tail that reads a StaticInput must NOT sink, even when that
    static also feeds the recurrence (its per-step value is the whole
    sequence — stacking it would be wrong); the group falls back to the
    per-step path and still computes correctly."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer, base, data_type
    from paddle_tpu.layers.base import Context, evaluate
    from paddle_tpu.layers.recurrent_group import (
        StaticInput, memory, recurrent_group,
    )
    import jax

    base.reset_name_counters()
    seq = layer.data(name="stx", type=data_type.dense_vector_sequence(4))
    outer = layer.fc(input=layer.first_seq(input=seq), size=4,
                     act=act.TanhActivation(), name="outer_ctx")

    def step(s_t, ctx_static):
        mem = memory(name="st_step", size=4)
        h = layer.fc(input=[s_t, mem], size=4, act=act.TanhActivation(),
                     name="st_step")
        # tail reads BOTH the recurrence value and the static input
        out = layer.fc(input=[h, ctx_static], size=3,
                       act=act.SoftmaxActivation())
        return out

    g = recurrent_group(step=step,
                        input=[seq, StaticInput(input=outer)],
                        name="static_tail_group")
    topo = Topology(g)
    params = paddle.parameters.create(topo).as_dict()
    r = np.random.default_rng(0)
    sb = SequenceBatch(data=r.normal(size=(2, 5, 4)).astype(np.float32),
                       length=np.array([5, 3], np.int32))
    vals, _ = evaluate([g], Context(is_train=False, key=jax.random.key(0)),
                       params, topo.init_states(), {"stx": sb})
    out = vals[g.name]
    assert out.data.shape == (2, 5, 3)
    np.testing.assert_allclose(np.asarray(out.data).sum(-1)[0, 0], 1.0,
                               rtol=1e-5)  # softmax rows


def test_two_costs_share_one_logits_companion():
    """Two classification_cost calls on the same softmax fc reuse ONE
    #logits companion; both runtime metrics point at the node that
    actually exists."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer, base, data_type

    base.reset_name_counters()
    x = layer.data(name="tcx", type=data_type.dense_vector(8))
    out = layer.fc(input=x, size=4, act=act.SoftmaxActivation())
    y1 = layer.data(name="tcy1", type=data_type.integer_value(4))
    y2 = layer.data(name="tcy2", type=data_type.integer_value(4))
    c1 = layer.classification_cost(input=out, label=y1, name="costA")
    c2 = layer.classification_cost(input=out, label=y2, name="costB")
    companions = {p.name for c in (c1, c2) for p in c.parents
                  if p.name.endswith("#logits")}
    assert companions == {"costA#logits"}  # ONE shared companion
    topo = Topology([c1, c2])
    node_names = {n.name for n in topo.nodes}
    for kind, pred, lbl, tag in topo.metrics():
        assert pred in node_names, (pred, tag)
    # and the whole thing trains
    params = paddle.parameters.create(topo).as_dict()
    from paddle_tpu.trainer.step import build_train_step
    from paddle_tpu.optimizer import SGD
    from paddle_tpu.parallel.mesh import get_mesh
    import jax
    import numpy as np

    step = build_train_step(topo, SGD(learning_rate=0.1))
    specs = {s.name: s for s in topo.param_specs()}
    opt_state = SGD(learning_rate=0.1).init(params, specs)
    r = np.random.default_rng(0)
    feed = {"tcx": r.normal(size=(8, 8)).astype(np.float32),
            "tcy1": r.integers(0, 4, size=(8,)),
            "tcy2": r.integers(0, 4, size=(8,))}
    params2, _, _, cost, metrics = step(params, opt_state, topo.init_states(),
                                        feed, jax.random.key(0))
    assert np.isfinite(float(cost))
