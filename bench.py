"""Headline benchmark — AlexNet training, ms/batch at batch size 64.

The reference's own headline number (benchmark/README.md:31-38): 195 ms/batch
on 1x Tesla K40m (cuDNN 5.1).  Here: the full jitted train step (forward,
backward, momentum update — the same work TrainerInternal::trainOneBatch
does per batch) on one TPU chip.  Prints ONE JSON line;
``vs_baseline`` = reference_ms / our_ms (>1 means faster than the reference).
"""

from __future__ import annotations

import json
import time

import numpy as np

REFERENCE_MS = 195.0  # AlexNet bs64, 1x K40m — benchmark/README.md:31-38
BATCH = 64


def main() -> None:
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import base
    from paddle_tpu.models import image as M
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.trainer.step import build_train_step

    import jax.numpy as jnp

    base.reset_name_counters()
    cost, predict, img, label = M.alexnet_cost()
    topo = Topology(cost)
    opt = Momentum(momentum=0.9, learning_rate=0.01 / BATCH)
    specs = {s.name: s for s in topo.param_specs()}

    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    # mixed precision: bf16 activations/compute on the MXU, f32 master params
    step = build_train_step(topo, opt, compute_dtype=jnp.bfloat16)

    rng = np.random.default_rng(0)
    feed = {
        "image": jax.device_put(
            rng.normal(size=(BATCH, 227 * 227 * 3)).astype(np.float32)
        ),
        "label": jax.device_put(rng.integers(0, 1000, size=(BATCH,))),
    }
    key = jax.random.key(0)

    def run(n):
        """n chained steps + one scalar readback.  The readback (not
        block_until_ready, which the tunneled backend does not honor) forces
        execution; its ~constant RTT is cancelled by the two-point method."""
        nonlocal params, opt_state, states
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt_state, states, c, _ = step(
                params, opt_state, states, feed, key
            )
        float(c)
        return time.perf_counter() - t0

    run(3)  # compile + warmup
    n1, n2 = 5, 55
    t_small = min(run(n1) for _ in range(2))
    t_large = min(run(n2) for _ in range(2))
    ms = max(t_large - t_small, 1e-9) / (n2 - n1) * 1000.0

    print(
        json.dumps(
            {
                "metric": "alexnet_train_ms_per_batch_bs64",
                "value": round(ms, 3),
                "unit": "ms",
                "vs_baseline": round(REFERENCE_MS / ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
