"""Benchmark grid — JSON lines, one per config; the LAST line is the
north-star metric (ResNet-50 throughput/MFU).

The grid covers every row BENCHMARKS.md publishes, so the doc tables can be
regenerated from this script's output (``python bench.py | tee /tmp/bench.jsonl``
then ``python tools/bench_to_md.py /tmp/bench.jsonl``): AlexNet 4 batch
sizes, GoogleNet, SmallNet, LSTM h256/512/1280, seq2seq NMT, wide&deep CTR,
OCR CRNN, ResNet-50 bs64/128/256, and the 124M transformer LM.  Reference
configs mirror the reference's published tables (benchmark/README.md:31-58,
113-119, benchmark/paddle/rnn/rnn.py) plus BASELINE.md's targets;
``vs_baseline`` is reference_time / our_time where the reference published a
number (>1 ⇒ faster than the reference hardware), else 0.

MFU counting: FLOPs = 2×MACs (ResNet-50 fwd ≈ 4.09 GFLOP/img at 224²),
train ≈ 3× fwd, against the v5e bf16 peak 197 TFLOP/s.  The ResNet step is
*measured* HBM-bandwidth-bound (see BENCHMARKS.md: per-segment achieved
GB/s from profiler byte counts vs a STREAM-triad calibration), so its MFU
ceiling on one v5e is ≈20%; the transformer row uses 6ND + attention FLOPs.

Timing: device-side via jax.profiler traces (paddle_tpu.profiler.
device_step_ms — the tunnel's dispatch noise makes wall-clock two-point
timing unstable below ~10 ms/step); falls back to the two-point
chained-dispatch method with a scalar readback fence if tracing fails.
"""

from __future__ import annotations

import sys
import time

import numpy as np

PEAK_FLOPS = 197e12  # v5e bf16
RESNET_FWD_GFLOP_PER_IMG = 4.09  # 2*MACs at 224x224


def _wall_two_point(step_fn, warmup=3, n1=5, n2=25):
    """ms per step via chained dispatch; step_fn() must keep its own state
    and return a scalar-readback-able array."""
    def run(n):
        t0 = time.perf_counter()
        c = None
        for _ in range(n):
            c = step_fn()
        float(np.asarray(c).reshape(-1)[0])
        return time.perf_counter() - t0

    run(warmup)
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(n2) for _ in range(2))
    return max(t2 - t1, 1e-9) / (n2 - n1) * 1000.0


TIMING_FALLBACKS: list[str] = []


def _two_point(step_fn, warmup=3, n1=5, n2=25):
    from paddle_tpu.profiler import device_step_ms

    try:
        ms = device_step_ms(step_fn, steps=max(n2 // 2, 8), warmup=warmup)
        if ms <= 0.0:
            # a trace with no device events (CPU-only box) reads as 0 —
            # that is a failed measurement, not an infinitely fast step
            raise RuntimeError("device trace yielded 0 ms (no device "
                               "events on this backend)")
        return ms
    except Exception as e:
        # record it: wall-clock numbers must not masquerade as device-side
        TIMING_FALLBACKS.append(f"{type(e).__name__}: {e}"[:120])
        return _wall_two_point(step_fn, warmup=warmup, n1=n1, n2=n2)


def _utilization(step_fn):
    """Ceiling-relative utilization for a bench row: MFU vs bf16 peak and
    op-level byte throughput vs the STREAM-calibrated HBM ceiling of this
    chip (661-673 GB/s, BENCHMARKS.md).  hbm_pct > 100 means the op-level
    byte count exceeds physical HBM bandwidth — operands are being re-read
    from VMEM/fused buffers, i.e. the workload is latency-bound, not
    HBM-bound."""
    try:
        from tools.xprof import measure_utilization

        u = measure_utilization(step_fn)
        return {"mfu_pct": u["mfu_pct"], "achieved_gbps": u["gbps"],
                "hbm_pct": u["hbm_pct"]}
    except Exception as e:  # keep the row alive without utilization
        return {"util_error": f"{type(e).__name__}: {e}"[:100]}


def _topology_step(cost_fn, feed_fn, optimizer=None, compute_dtype=None,
                   lr=0.01):
    """Generic jitted-train-step closure for a v2-layer-API model: builds
    the Topology, params, optimizer state and a self-chaining step fn."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import base
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    cost = cost_fn()
    topo = Topology(cost)
    opt = optimizer or Momentum(momentum=0.9, learning_rate=lr)
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(
        topo, opt,
        compute_dtype=jnp.bfloat16 if compute_dtype is None else compute_dtype)
    feed = feed_fn()
    key = jax.random.key(0)
    state = {"p": params, "o": opt_state, "s": states}

    def one():
        state["p"], state["o"], state["s"], c, _ = step(
            state["p"], state["o"], state["s"], feed, key
        )
        return c

    return one


def _image_feed(batch, img_dim, classes=1000):
    def feed_fn():
        import jax

        rng = np.random.default_rng(0)
        return {
            "image": jax.device_put(
                rng.normal(size=(batch, img_dim)).astype(np.float32)),
            "label": jax.device_put(rng.integers(0, classes, size=(batch,))),
        }
    return feed_fn


def _image_step(model_fn, batch, img_dim, lr=0.01, classes=1000):
    from paddle_tpu.optimizer import Momentum

    return _topology_step(
        model_fn, _image_feed(batch, img_dim, classes),
        optimizer=Momentum(momentum=0.9, learning_rate=lr / batch))


def bench_alexnet(records):
    from paddle_tpu.models import image as M

    # reference: 1x K40m ms/batch (benchmark/README.md:31-38)
    k40 = {64: 195.0, 128: 334.0, 256: 602.0, 512: 1629.0}
    for bs in (64, 128, 256, 512):
        step = _image_step(lambda: M.alexnet_cost()[0], bs, 227 * 227 * 3)
        ms = _two_point(step, n2=15 if bs >= 256 else 25)
        records.append({
            "metric": f"alexnet_train_ms_per_batch_bs{bs}",
            "value": round(ms, 3), "unit": "ms",
            "vs_baseline": round(k40[bs] / ms, 2),
        })


def bench_googlenet(records):
    from paddle_tpu.models import image as M

    k40 = {64: 613.0, 128: 1149.0}
    for bs in (64, 128):
        step = _image_step(lambda: M.googlenet_cost()[0], bs, 224 * 224 * 3)
        ms = _two_point(step, n2=15)
        records.append({
            "metric": f"googlenet_train_ms_per_batch_bs{bs}",
            "value": round(ms, 3), "unit": "ms",
            "vs_baseline": round(k40[bs] / ms, 2),
        })


def bench_smallnet(records):
    from paddle_tpu.models import image as M

    step = _image_step(lambda: M.smallnet_cost()[0], 64, 32 * 32 * 3,
                       classes=10)
    ms = _two_point(step)
    records.append({
        "metric": "smallnet_cifar_train_ms_per_batch_bs64",
        "value": round(ms, 3), "unit": "ms",
        "vs_baseline": round(10.46 / ms, 2),
    })


def _lstm_classify_cost(hidden, vocab=30000, embed=128):
    """≅ benchmark/paddle/rnn/rnn.py: embedding 128 -> simple_lstm(h) ->
    last_seq -> fc2 softmax -> classification_cost."""
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer
    from paddle_tpu.layers import data_type

    data = layer.data(name="data",
                      type=data_type.integer_value_sequence(vocab))
    net = layer.embedding(input=data, size=embed)
    net = layer.fc(input=net, size=hidden * 4, act=act.LinearActivation())
    net = layer.lstmemory(input=net)
    net = layer.last_seq(input=net)
    net = layer.fc(input=net, size=2, act=act.SoftmaxActivation())
    label = layer.data(name="label", type=data_type.integer_value(2))
    return layer.classification_cost(input=net, label=label)


def bench_lstm(records, bs=64, hiddens=(256, 512, 1280),
               saturated=False):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.optimizer import Adam

    k40 = {256: 83.0, 512: 184.0, 1280: 641.0}
    seqlen, vocab = 100, 30000
    rng = np.random.default_rng(0)

    def feed_fn():
        return {
            "data": SequenceBatch(
                data=rng.integers(0, vocab, size=(bs, seqlen)),
                length=np.full((bs,), seqlen, np.int32)),
            "label": jax.device_put(rng.integers(0, 2, size=(bs,))),
        }

    for h in hiddens:
        step = _topology_step(lambda h=h: _lstm_classify_cost(h), feed_fn,
                              optimizer=Adam(learning_rate=2e-3,
                                             moment_dtype=jnp.bfloat16))
        ms = _two_point(step, n2=10 if saturated else 15)
        row = {
            "metric": f"lstm_text_train_ms_per_batch_h{h}_bs{bs}"
                      + ("_saturated" if saturated else ""),
            "value": round(ms, 3), "unit": "ms",
            "vs_baseline": 0 if saturated else round(k40[h] / ms, 2),
            **_utilization(step),
        }
        if saturated:
            row["seq_per_sec"] = round(bs / ms * 1000.0, 0)
        records.append(row)


def bench_lstm_ablation(records, bs=32, seqlen=64, hidden=256,
                        vocab=30000):
    """Persistent-recurrence ablation for the LSTM text model: flag on
    routes the lstmemory sweep through remat mode (no [T, B, 4D] gates
    residual round-tripped through HBM) and, on TPU, the fused-input
    kernels — trajectory asserted, bit-identical on CPU where both
    modes resolve to the same unfused program.  Separate from
    ``bench_lstm`` so the CPU testbed snapshot can run it without the
    h256-1280 reference grid."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.optimizer import Adam

    rng = np.random.default_rng(0)

    def feed_fn():
        return {
            "data": SequenceBatch(
                data=rng.integers(0, vocab, size=(bs, seqlen)),
                length=np.full((bs,), seqlen, np.int32)),
            "label": jax.device_put(rng.integers(0, 2, size=(bs,))),
        }

    _fused_ablation_row(
        records, "lstm_fused_ablation_speedup",
        lambda: _lstm_classify_cost(hidden), feed_fn,
        lambda: Adam(learning_rate=2e-3, moment_dtype=jnp.bfloat16),
        per_unit="steps_per_sec", n2=8, steps=3)


def bench_nmt_ablation(records, bs=16, tlen=16, vocab=2000, dim=64):
    """Fused-recurrence ablation for the NMT encoder/decoder GRUs (same
    contract as the other _fused_ablation_row rows; scaled-down config so
    the row is measurable on the CPU testbed)."""
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.models import seqtoseq as S
    from paddle_tpu.optimizer import Adam

    rng = np.random.default_rng(0)

    def feed_fn():
        def seq():
            return SequenceBatch(
                data=rng.integers(0, vocab, size=(bs, tlen)),
                length=np.full((bs,), tlen, np.int32))
        return {
            "source_language_word": seq(),
            "target_language_word": seq(),
            "target_language_next_word": seq(),
        }

    _fused_ablation_row(
        records, "nmt_fused_ablation_speedup",
        lambda: S.seqtoseq_net(vocab, vocab, word_vector_dim=dim,
                               encoder_size=dim, decoder_size=dim),
        feed_fn,
        lambda: Adam(learning_rate=5e-4, moment_dtype=jnp.bfloat16),
        per_unit="steps_per_sec", n2=8, steps=3)


def bench_nmt(records, bs=64, saturated=False):
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.models import seqtoseq as S
    from paddle_tpu.optimizer import Adam

    tlen, vocab = 32, 30000
    rng = np.random.default_rng(0)

    def feed_fn():
        def seq():
            return SequenceBatch(
                data=rng.integers(0, vocab, size=(bs, tlen)),
                length=np.full((bs,), tlen, np.int32))
        return {
            "source_language_word": seq(),
            "target_language_word": seq(),
            "target_language_next_word": seq(),
        }

    step = _topology_step(
        lambda: S.seqtoseq_net(vocab, vocab, word_vector_dim=512,
                               encoder_size=512, decoder_size=512),
        feed_fn, optimizer=Adam(learning_rate=5e-4,
                                moment_dtype=jnp.bfloat16))
    ms = _two_point(step, n2=10 if saturated else 15)
    records.append({
        "metric": "nmt_attention_train_seq_per_sec"
                  + (f"_bs{bs}_saturated" if saturated else ""),
        "value": round(bs / ms * 1000.0, 1), "unit": "seq/s",
        "config": f"vocab {vocab}, dim 512, len {tlen}, bs {bs}, bf16 mixed precision, bf16 Adam moments",
        "vs_baseline": 0,
        **_utilization(step),
    })


def bench_ctr(records, bs=1024, saturated=False):
    from paddle_tpu.models.ctr import wide_and_deep_ctr
    from paddle_tpu.optimizer import AdaGrad
    from paddle_tpu.reader.feeder import DataFeeder
    from paddle_tpu.layers.data_type import integer_value, sparse_binary_vector

    wide_dim, vocabs = 10000, [1000] * 8
    rng = np.random.default_rng(0)

    def feed_fn():
        dtypes = {"wide_input": sparse_binary_vector(wide_dim),
                  "label": integer_value(2)}
        for i in range(len(vocabs)):
            dtypes[f"cat_{i}"] = integer_value(vocabs[i])
        feeder = DataFeeder(dtypes)
        batch = []
        for _ in range(bs):
            row = [rng.integers(0, wide_dim, size=3).tolist()]
            row += [int(rng.integers(0, v)) for v in vocabs]
            row.append(int(rng.integers(0, 2)))
            batch.append(tuple(row))
        return feeder.feed(batch)

    step = _topology_step(
        lambda: wide_and_deep_ctr(
            wide_dim=wide_dim, categorical_vocab_sizes=vocabs,
            embedding_size=64, hidden_sizes=(256, 128))[0],
        feed_fn, optimizer=AdaGrad(learning_rate=1e-2))
    ms = _two_point(step, n2=10 if saturated else 25)
    records.append({
        "metric": "ctr_wide_deep_train_examples_per_sec"
                  + (f"_bs{bs}_saturated" if saturated else ""),
        "value": round(bs / ms * 1000.0, 0), "unit": "ex/s",
        "config": f"wide {wide_dim}, 8x1k vocab emb64, bs {bs}, bf16 mixed precision",
        "vs_baseline": 0,
        **_utilization(step),
    })


def _fused_ablation_row(records, metric, cost_fn, feed_fn, optimizer_fn,
                        per_unit, unit_scale=1.0, n2=10, steps=4):
    """Fused-vs-unfused TPP-kernel ablation: the SAME model + feed through
    the trainer step with ``fused_kernels`` off vs on, reporting ms/step
    both ways, the speedup, and the trajectory check.  Contract: on CPU
    the fused routing resolves to the jnp reference (identical op
    sequence) so the trajectories are bit-identical; on TPU the Pallas
    kernels run and the match is tolerance-bounded (kernel accumulation
    order; bound documented in BENCHMARKS.md).  A divergence beyond the
    bound raises — a broken fused path must not report a speedup."""
    from paddle_tpu.core import flags
    from paddle_tpu.core import rng as prng

    # ONE feed for both modes: a feed_fn over an advancing shared rng
    # (bench_crnn's) would hand each mode different batches and trip the
    # divergence guard on data, not numerics
    feed = feed_fn()
    snap = flags.snapshot_raw()
    res = {}
    try:
        for mode in ("off", "on"):
            flags.set("fused_kernels", mode)
            prng.seed(7)
            step = _topology_step(cost_fn, lambda: feed,
                                  optimizer=optimizer_fn())
            losses = [float(np.asarray(step()).reshape(-1)[0])
                      for _ in range(steps)]
            ms = _two_point(step, n2=n2)
            if ms <= 0:  # empty profiler trace (some CPU testbeds)
                ms = _wall_two_point(step, n1=3, n2=max(n2, 6))
            res[mode] = (ms, losses)
    finally:
        flags.restore_raw(snap)
    (ms_off, l_off), (ms_on, l_on) = res["off"], res["on"]
    l_off, l_on = np.asarray(l_off), np.asarray(l_on)
    identical = bool(np.array_equal(l_off, l_on))
    max_rel = float(np.max(np.abs(l_off - l_on)
                           / np.maximum(np.abs(l_off), 1e-9)))
    if not identical and max_rel > 5e-3:
        raise RuntimeError(
            f"{metric}: fused trajectory diverged from unfused "
            f"(max rel diff {max_rel:.2e} over {steps} steps)")
    records.append({
        "metric": metric,
        "value": round(ms_off / max(ms_on, 1e-9), 2), "unit": "x",
        "unfused_ms": round(ms_off, 3), "fused_ms": round(ms_on, 3),
        "unfused_" + per_unit: round(unit_scale * 1000.0
                                     / max(ms_off, 1e-9), 1),
        "fused_" + per_unit: round(unit_scale * 1000.0
                                   / max(ms_on, 1e-9), 1),
        "trajectory_identical": identical,
        "trajectory_max_rel_diff": max_rel,
        "vs_baseline": 0,
    })
    return ms_on


def bench_crnn(records, bs=64, saturated=False):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.models.ocr_crnn import crnn_ctc_cost
    from paddle_tpu.optimizer import Adam

    h, w, classes = 32, 96, 26
    rng = np.random.default_rng(0)

    def feed_fn():
        lab_len = 5
        return {
            "image": jax.device_put(
                rng.normal(size=(bs, h * w)).astype(np.float32)),
            "label": SequenceBatch(
                data=rng.integers(0, classes, size=(bs, lab_len)),
                length=np.full((bs,), lab_len, np.int32)),
        }

    step = _topology_step(
        lambda: crnn_ctc_cost(image_height=h, image_width=w,
                              num_classes=classes)[0],
        feed_fn, optimizer=Adam(learning_rate=1e-3,
                                moment_dtype=jnp.bfloat16))
    ms = _two_point(step, n2=10 if saturated else 15)
    records.append({
        "metric": "ocr_crnn_ctc_train_samples_per_sec"
                  + (f"_bs{bs}_saturated" if saturated else ""),
        "value": round(bs / ms * 1000.0, 0), "unit": "samples/s",
        "config": f"32x96 conv+BN+ReLU(+BiLSTM+CTC), bs {bs}, bf16 mixed precision, bf16 Adam moments",
        "vs_baseline": 0,
        **_utilization(step),
    })
    if not saturated:
        # OCR step-time row of the TPP fused-kernel ablation (the CRNN
        # conv stack rides layer.img_conv_bn -> ops/nn.conv2d_bn_relu)
        _fused_ablation_row(
            records, "ocr_crnn_fused_ablation_speedup",
            lambda: crnn_ctc_cost(image_height=h, image_width=w,
                                  num_classes=classes)[0],
            feed_fn,
            lambda: Adam(learning_rate=1e-3, moment_dtype=jnp.bfloat16),
            per_unit="steps_per_sec", n2=10)


def bench_saturation(records):
    """Saturated-batch rows for the latency-bound-diagnosed benches
    (VERDICT r4 #3): each reference-batch row gets a companion at the
    batch size that maximizes throughput, with the same MFU/GB/s
    accounting — the SAME builders as the reference-batch rows, only the
    batch differs.  Measured finding (round 5): the reference-batch rows
    were already at or near the chip's sustained per-sample cost —
    batch scaling buys +12% (CTR @16k), +25% (OCR @512), +35% (NMT
    @512), and ~0% (LSTM), NOT the >10x a pure-latency-bound model
    would predict; the sub-ms steps were small, not idle."""
    bench_lstm(records, bs=256, hiddens=(256, 512), saturated=True)
    bench_nmt(records, bs=512, saturated=True)
    bench_ctr(records, bs=16384, saturated=True)
    bench_crnn(records, bs=512, saturated=True)


PREFETCH_ABLATION_DEPTH = 2  # bench.py --prefetch=0|N (0 = sync row only)


def bench_input_pipeline(records):
    """Input-pipeline overlap ablation (the host-fed-workload fix): the
    SAME model + a synthetic slow reader (sleep calibrated ≈ step time,
    the worst case for a synchronous loop) through the real ``SGD.train``
    path — once synchronous (prefetch=0, sync_period=1, the seed loop)
    and once overlapped (prefetch=N, sync_period=8).  Rows carry the
    per-step ``input_wait_ms`` mean so host starvation is visible in the
    JSONL stream; ``input_pipeline_overlap_speedup`` is the steps/sec
    ratio (ideal = 2.0 when reader time == step time)."""
    import paddle_tpu as paddle
    from paddle_tpu import metrics as metrics_mod
    from paddle_tpu.core import rng as prng
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer_api
    from paddle_tpu.layers import base as layer_base
    from paddle_tpu.layers import data_type

    dim, classes, bs, nb = 1024, 10, 512, 16
    rngnp = np.random.default_rng(0)
    batch_data = [(rngnp.normal(size=(dim,)).astype(np.float32),
                   int(rngnp.integers(classes))) for _ in range(bs)]

    def build():
        layer_base.reset_name_counters()
        prng.seed(7)
        x = layer_api.data(name="px", type=data_type.dense_vector(dim))
        h = layer_api.fc(input=x, size=512)
        h = layer_api.fc(input=h, size=classes,
                         act=act.SoftmaxActivation())
        lbl = layer_api.data(name="py", type=data_type.integer_value(classes))
        cost = layer_api.classification_cost(input=h, label=lbl)
        params = paddle.parameters.create(paddle.topology.Topology(cost))
        return paddle.trainer.SGD(
            cost=cost, parameters=params,
            update_equation=paddle.optimizer.SGD(learning_rate=0.01))

    def run(prefetch, sync_period, sleep_s):
        """2 passes (pass 1 pays the compile); returns (steps/sec of
        pass 2, mean input_wait_ms of pass 2, pass-2 losses,
        mean step_ms of pass 2)."""
        trainer = build()
        sink = metrics_mod.MemorySink()
        reg = metrics_mod.MetricsRegistry("bench_input_pipeline")
        reg.add_sink(sink)

        def reader():
            for _ in range(nb):
                if sleep_s:
                    time.sleep(sleep_s)
                yield batch_data

        marks = {}

        def on_event(e):
            if isinstance(e, paddle.event.BeginPass) and e.pass_id == 1:
                marks["t0"] = time.perf_counter()
            elif isinstance(e, paddle.event.EndPass) and e.pass_id == 1:
                marks["t1"] = time.perf_counter()

        trainer.train(reader=reader, num_passes=2, event_handler=on_event,
                      metrics_registry=reg, sync_period=sync_period,
                      prefetch=prefetch)
        steps = [r for r in sink.records
                 if r.get("kind") == "step" and r.get("pass_id") == 1]
        waits = [r["input_wait_ms"] for r in steps if "input_wait_ms" in r]
        losses = [r["loss"] for r in steps]
        step_ms = [r["step_ms"] for r in steps]
        sps = nb / max(marks["t1"] - marks["t0"], 1e-9)
        return (sps, (sum(waits) / len(waits) if waits else 0.0), losses,
                min(step_ms) if step_ms else 0.0)

    # calibrate the reader sleep to ~the measured per-step device+host
    # time (the worst case for a synchronous loop is reader ≈ step; the
    # 1.5 factor keeps the overlapped run firmly producer-bound — the
    # producer's time is then mostly pure sleep, GIL-free and immune to
    # compute jitter — at ideal = 2.5/1.5 ≈ 1.67x).  MIN step time, not
    # mean: a loaded host inflates the mean, which would oversize the
    # sleep and understate the overlap headroom
    _, _, _, calib_step_ms = run(0, 1, 0.0)
    sleep_s = max(1.5 * calib_step_ms / 1e3, 1e-4)
    row_cfg = (f"fc {dim}->512->{classes}, bs {bs}, reader sleep "
               f"{sleep_s * 1e3:.1f} ms/batch")

    n = PREFETCH_ABLATION_DEPTH
    if n <= 0:
        sync_sps, sync_wait, _, _ = run(0, 1, sleep_s)
        records.append({
            "metric": "input_pipeline_steps_per_sec_sync",
            "value": round(sync_sps, 2), "unit": "steps/s",
            "input_wait_ms": round(sync_wait, 3),
            "config": row_cfg + ", prefetch 0, sync_period 1",
            "vs_baseline": 0,
        })
        return
    # interleaved sync/overlapped PAIRS, publishing the MEDIAN pair by
    # ratio: both runs of a pair see the same background load (drift
    # cancels out of the ratio), and the median is robust to one
    # corrupted pair without the upward bias a max-ratio pick would have
    pairs = [(run(0, 1, sleep_s), run(n, 8, sleep_s)) for _ in range(5)]
    pairs.sort(key=lambda sp: sp[1][0] / max(sp[0][0], 1e-9))
    (sync_sps, sync_wait, sync_losses, _), (pf_sps, pf_wait, pf_losses, _) \
        = pairs[len(pairs) // 2]
    records.append({
        "metric": "input_pipeline_steps_per_sec_sync",
        "value": round(sync_sps, 2), "unit": "steps/s",
        "input_wait_ms": round(sync_wait, 3),
        "config": row_cfg + ", prefetch 0, sync_period 1",
        "vs_baseline": 0,
    })
    records.append({
        "metric": f"input_pipeline_steps_per_sec_prefetch{n}",
        "value": round(pf_sps, 2), "unit": "steps/s",
        "input_wait_ms": round(pf_wait, 3),
        "config": row_cfg + f", prefetch {n}, sync_period 8",
        "vs_baseline": 0,
    })
    records.append({
        "metric": "input_pipeline_overlap_speedup",
        "value": round(pf_sps / max(sync_sps, 1e-9), 2), "unit": "x",
        "trajectory_identical": bool(
            np.array_equal(np.asarray(sync_losses), np.asarray(pf_losses))),
        "config": row_cfg,
        "vs_baseline": 0,
    })


def bench_input_bucketing(records):
    """Sequence-bucketing ablation on a skewed-length text workload (85%
    short sequences, 15% ~12x longer — the realistic tagging/OCR/NMT
    length mix): the SAME model + sample stream through ``SGD.train``,
    once batched in arrival order (every batch pads to the long tail's
    ceiling) and once through ``reader.bucket_by_length`` + the matching
    feeder ``seq_buckets`` table.  Rows carry the measured per-step
    ``padding_ratio`` (from the schema/10 telemetry field) and seq/s;
    the speedup row is the seq/s ratio.  Unlike the fused-kernel
    ablations there is no trajectory assert — bucketing reorders batch
    composition by design."""
    import paddle_tpu as paddle
    from paddle_tpu import metrics as metrics_mod
    from paddle_tpu.core import rng as prng
    from paddle_tpu.layers import activation as act
    from paddle_tpu.layers import api as layer_api
    from paddle_tpu.layers import base as layer_base
    from paddle_tpu.layers import data_type
    from paddle_tpu.reader.decorator import bucket_by_length

    vocab, hidden, bs, n_samples = 1000, 64, 32, 384
    buckets = (16, 192)
    rngnp = np.random.default_rng(0)
    samples = []
    for _ in range(n_samples):
        t = (int(rngnp.integers(6, 15)) if rngnp.random() < 0.85
             else int(rngnp.integers(150, 190)))
        samples.append((rngnp.integers(0, vocab, size=t).tolist(),
                        int(rngnp.integers(0, 2))))

    def raw_reader():
        yield from samples

    def build():
        layer_base.reset_name_counters()
        prng.seed(7)
        data = layer_api.data(
            name="data", type=data_type.integer_value_sequence(vocab))
        net = layer_api.embedding(input=data, size=32)
        net = layer_api.fc(input=net, size=hidden * 4,
                           act=act.LinearActivation())
        net = layer_api.lstmemory(input=net)
        net = layer_api.last_seq(input=net)
        net = layer_api.fc(input=net, size=2, act=act.SoftmaxActivation())
        label = layer_api.data(name="label",
                               type=data_type.integer_value(2))
        cost = layer_api.classification_cost(input=net, label=label)
        params = paddle.parameters.create(paddle.topology.Topology(cost))
        return paddle.trainer.SGD(
            cost=cost, parameters=params,
            update_equation=paddle.optimizer.Adam(learning_rate=1e-3))

    def run(bucketed):
        trainer = build()
        sink = metrics_mod.MemorySink()
        reg = metrics_mod.MetricsRegistry("bench_input_bucketing")
        reg.add_sink(sink)
        if bucketed:
            reader = bucket_by_length(raw_reader, bs, buckets=buckets)
            table = buckets
        else:
            reader = paddle.reader.batch(raw_reader, bs, drop_last=True)
            table = None
        marks = {}

        def on_event(e):
            if isinstance(e, paddle.event.BeginPass) and e.pass_id == 1:
                marks["t0"] = time.perf_counter()
            elif isinstance(e, paddle.event.EndPass) and e.pass_id == 1:
                marks["t1"] = time.perf_counter()

        # pass 0 pays the per-bucket compiles; pass 1 is the measurement
        trainer.train(reader=reader, num_passes=2, event_handler=on_event,
                      metrics_registry=reg, seq_buckets=table)
        steps = [r for r in sink.records
                 if r.get("kind") == "step" and r.get("pass_id") == 1]
        pads = [r["padding_ratio"] for r in steps if "padding_ratio" in r]
        examples = sum(
            r["examples_per_sec"] * r["step_ms"] / 1e3 for r in steps)
        sps = examples / max(marks["t1"] - marks["t0"], 1e-9)
        return sps, (sum(pads) / len(pads) if pads else 0.0)

    sps_off, pad_off = run(False)
    sps_on, pad_on = run(True)
    cfg = (f"emb32-lstm{hidden}, bs {bs}, {n_samples} samples, 85% len "
           f"6-15 / 15% len 150-190, buckets {list(buckets)}")
    records.append({
        "metric": "input_bucketing_padded_timestep_ratio_off",
        "value": round(pad_off, 4), "unit": "ratio", "config": cfg,
        "vs_baseline": 0})
    records.append({
        "metric": "input_bucketing_padded_timestep_ratio_on",
        "value": round(pad_on, 4), "unit": "ratio", "config": cfg,
        "vs_baseline": 0})
    records.append({
        "metric": "input_bucketing_speedup",
        "value": round(sps_on / max(sps_off, 1e-9), 2), "unit": "x",
        "seq_per_sec_off": round(sps_off, 1),
        "seq_per_sec_on": round(sps_on, 1),
        "padded_ratio_off": round(pad_off, 4),
        "padded_ratio_on": round(pad_on, 4),
        "config": cfg, "vs_baseline": 0})


def bench_zero(records):
    """ZeRO weight-update-sharding ablation (tools/bench_zero.py):
    replicated vs zero1 vs zero2 on a forced-8-device host mesh, in a
    SUBPROCESS so the virtual mesh never touches this process's backend.
    Rows carry opt-state bytes/device and grad-reduce bytes/device
    alongside steps/s — the sharded-aggregation memory and traffic
    story (1/n under zero>=1 / zero=2)."""
    import json
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_zero.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(
        kept + ["--xla_force_host_platform_device_count=8"])
    out = subprocess.run([sys.executable, script], env=env,
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"bench_zero subprocess failed: "
                           f"{out.stderr[-400:]}")
    for line in out.stdout.splitlines():
        if not line.startswith("{"):
            continue
        r = json.loads(line)
        r.pop("schema", None), r.pop("ts", None), r.pop("host", None)
        r.pop("kind", None)
        records.append(r)


def bench_embedding(records):
    """Sharded-embedding CTR ablation (tools/bench_embedding.py):
    replicated-dense vs row-sharded tables + fused TPP lookup on a
    forced-8-device host mesh, in a SUBPROCESS so the virtual mesh never
    touches this process's backend.  The row carries the per-device
    table byte census (runtime == static GL-P-MEM model, checked in the
    script) alongside ms/step and the trajectory-identity contract."""
    import json
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_embedding.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    kept = [f for f in env.get("XLA_FLAGS", "").split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    env["XLA_FLAGS"] = " ".join(
        kept + ["--xla_force_host_platform_device_count=8"])
    out = subprocess.run([sys.executable, script], env=env,
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"bench_embedding subprocess failed: "
                           f"{out.stderr[-400:]}")
    for line in out.stdout.splitlines():
        if not line.startswith("{"):
            continue
        r = json.loads(line)
        r.pop("schema", None), r.pop("ts", None), r.pop("host", None)
        r.pop("kind", None)
        records.append(r)


def bench_serving(records):
    """Serving ablation (tools/bench_serving.py in a subprocess, CPU-safe):
    continuous batching vs naive static batching on the same synthetic
    Poisson arrival trace — tokens/sec + p99 TTFT per mode and the
    speedup row (the continuous engine refills retired slots every step
    instead of draining whole batches)."""
    import json
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_serving.py")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run([sys.executable, script], env=env,
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"bench_serving subprocess failed: "
                           f"{out.stderr[-400:]}")
    for line in out.stdout.splitlines():
        if not line.startswith("{"):
            continue
        r = json.loads(line)
        for k in ("schema", "ts", "host", "kind"):
            r.pop(k, None)
        records.append(r)


def bench_serving_fleet(records):
    """Fleet availability row (tools/bench_serving_fleet.py in a
    subprocess): 3 replicas on seeded Poisson arrivals, one injected
    replica_loss — p99 TTFT with/without the failover and
    requests_lost (the script RAISES unless it is 0)."""
    import json
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_serving_fleet.py")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run([sys.executable, script], env=env,
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"bench_serving_fleet subprocess failed: "
                           f"{out.stderr[-400:]}")
    for line in out.stdout.splitlines():
        if not line.startswith("{"):
            continue
        r = json.loads(line)
        for k in ("schema", "ts", "host", "kind"):
            r.pop(k, None)
        records.append(r)


def bench_serving_prefix(records):
    """Per-token serving cost ablation (tools/bench_serving_prefix.py in
    a subprocess): a 2-replica fleet on a shared-system-prompt trace,
    prefix cache on vs off at the same offered QPS (recompute-FLOPs
    saved + p99 TTFT), plus the long-prompt chunked-prefill row.  Greedy
    tokens must be byte-identical across every arm."""
    import json
    import os
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "tools", "bench_serving_prefix.py")
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run([sys.executable, script], env=env,
                         capture_output=True, text=True, timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"bench_serving_prefix subprocess failed: "
                           f"{out.stderr[-400:]}")
    for line in out.stdout.splitlines():
        if not line.startswith("{"):
            continue
        r = json.loads(line)
        for k in ("schema", "ts", "host", "kind"):
            r.pop(k, None)
        records.append(r)


def bench_transformer(records):
    """124M GPT-2-shape LM, bs 8x1024, mixed precision, flash attention,
    dots-remat — the modern-workload flagship row."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    cfg = T.TransformerConfig(
        vocab_size=50257, num_layers=12, num_heads=12, embed_dim=768,
        # remat=False: all activations fit this chip's 16 GB at bs16, and
        # skipping the dots-policy recompute + taking the larger batch is
        # worth +8% tok/s (round-4 sweep: bs8/dots 130.0k, bs8/False
        # 134.0k, bs16/False 140.9k, bs24/False 140.0k tok/s)
        mlp_dim=3072, max_seq_len=2048, dtype=jnp.float32, remat=False,
        attn_impl="flash", attn_block_size=1024)
    params = T.init_params(cfg, jax.random.key(0))
    n = sum(x.size for x in jax.tree.leaves(params))
    # bf16 Adam moments (opt-in moment_dtype): halves the m/v HBM traffic
    # on the ~5 ms optimizer line for -1.5 ms/step (114.6 -> 113.1,
    # 58.6% -> 59.4% MFU); update math stays f32, trajectory-parity
    # asserted in tests/test_optimizers_v1.py::TestAdamMomentDtype
    opt = Adam(learning_rate=1e-4, moment_dtype=jnp.bfloat16)
    opt_state = opt.init_tree(params)
    bs, seqlen = 16, 1024
    ids = jax.device_put(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(bs, seqlen + 1)))
    step = T.build_train_step(cfg, opt, compute_dtype=jnp.bfloat16)
    state = {"p": params, "o": opt_state}

    def one():
        state["p"], state["o"], loss = step(state["p"], state["o"], ids)
        return loss

    ms = _two_point(one, n2=15)
    tokens = bs * seqlen
    attn_fl = 12 * cfg.num_layers * bs * seqlen * seqlen * cfg.embed_dim / 2
    mfu = (6.0 * n * tokens + attn_fl) / (ms / 1e3) / PEAK_FLOPS
    records.append({
        "metric": "transformer_lm_124m_tokens_per_sec",
        "value": round(tokens / ms * 1000.0, 0), "unit": "tok/s",
        "mfu_pct": round(mfu * 100, 1),
        "config": "GPT-2-small shape, bs 16x1024, flash attn, mixed "
                  "precision, bf16 Adam moments",
        "vs_baseline": 0,
    })


def bench_resnet(records):
    from paddle_tpu.models import image as M
    from paddle_tpu.optimizer import Momentum

    # fused-vs-unfused TPP ablation sub-row (bs 64): conv+BN+ReLU blocks
    # + the ZeRO-less momentum update, trajectory asserted against the
    # unfused XLA path (bit-identical on CPU, tolerance-bounded on TPU)
    try:
        _fused_ablation_row(
            records, "resnet50_fused_ablation_speedup",
            lambda: M.resnet_cost(depth=50)[0],
            _image_feed(64, 224 * 224 * 3),
            lambda: Momentum(momentum=0.9, learning_rate=0.1 / 64),
            per_unit="img_per_sec", unit_scale=64, n2=8, steps=3)
    except Exception as e:
        records.append({
            "metric": "resnet50_fused_ablation_speedup", "value": 0,
            "unit": "x", "error": f"{type(e).__name__}: {e}"[:200],
            "vs_baseline": 0})

    best = None
    for bs in (64, 128, 256):
        try:
            step = _image_step(lambda: M.resnet_cost(depth=50)[0], bs,
                               224 * 224 * 3, lr=0.1)
            ms = _two_point(step, n2=15 if bs < 256 else 10)
        except Exception as e:
            records.append({
                "metric": f"resnet50_train_img_per_sec_bs{bs}",
                "value": 0, "unit": "img/s",
                "error": f"{type(e).__name__}: {e}"[:200],
                "vs_baseline": 0})
            continue
        img_s = bs / ms * 1000.0
        tf = 3 * RESNET_FWD_GFLOP_PER_IMG * bs / ms  # GFLOP/ms == TF/s
        mfu = tf * 1e12 / PEAK_FLOPS
        records.append({
            "metric": f"resnet50_train_img_per_sec_bs{bs}",
            "value": round(img_s, 1), "unit": "img/s",
            "mfu_pct": round(mfu * 100, 1),
            "vs_baseline": 0,
        })
        if best is None or img_s > best["value"]:
            best = {
                "metric": "resnet50_train_img_per_sec",
                "value": round(img_s, 1), "unit": "img/s",
                "mfu_pct": round(mfu * 100, 1),
                "batch_size": bs,
                "vs_baseline": 0,
            }
    return best


def main() -> None:
    records: list[dict] = []
    failures = []
    rows = (bench_alexnet, bench_googlenet, bench_smallnet, bench_lstm,
            bench_lstm_ablation, bench_nmt, bench_nmt_ablation, bench_ctr,
            bench_crnn, bench_saturation, bench_input_pipeline,
            bench_input_bucketing, bench_transformer, bench_zero,
            bench_embedding, bench_serving, bench_serving_fleet,
            bench_serving_prefix)
    # debugging aid: `python bench.py transformer resnet` runs a subset;
    # the driver's no-arg invocation runs everything.  --prefetch=0|N
    # sets the input-pipeline ablation depth (0 = sync row only).
    global PREFETCH_ABLATION_DEPTH
    for a in sys.argv[1:]:
        if a.startswith("--prefetch="):
            PREFETCH_ABLATION_DEPTH = int(a.split("=", 1)[1])
    selected = [a for a in sys.argv[1:] if not a.startswith("-")]
    wants_resnet = not selected or any(s in "bench_resnet" for s in selected)
    if selected:
        rows = tuple(f for f in rows
                     if any(s in f.__name__ for s in selected))
        if not rows and not wants_resnet:
            sys.stderr.write(
                f"bench.py: no bench rows match {selected}\n")
            sys.exit(2)
    for fn in rows:
        try:
            fn(records)
        except Exception as e:  # keep the headline alive
            failures.append(f"{fn.__name__}: {type(e).__name__}: {e}")
    headline = None
    if wants_resnet:
        try:
            headline = bench_resnet(records)
        except Exception as e:
            failures.append(f"bench_resnet: {type(e).__name__}: {e}")
    # rows flow through the telemetry sink API (paddle_tpu/metrics.py) so
    # bench and trainer step records share one schema/toolchain — a JSONL
    # capture of this stdout feeds bench_to_md.py AND metrics_to_md.py
    from paddle_tpu.telemetry import JsonlSink, MetricsRegistry

    reg = MetricsRegistry("bench")
    reg.add_sink(JsonlSink(sys.stdout))
    for r in records:
        reg.emit(r, kind="bench")
    if failures:
        reg.emit({"metric": "bench_failures", "value": len(failures),
                  "unit": "count", "detail": failures,
                  "vs_baseline": 0}, kind="bench")
    if TIMING_FALLBACKS:
        reg.emit({
            "metric": "timing_wall_clock_fallbacks",
            "value": len(TIMING_FALLBACKS), "unit": "count",
            "detail": TIMING_FALLBACKS[:5],
            "note": "these rows used wall-clock two-point timing, NOT "
                    "device-side traces", "vs_baseline": 0}, kind="bench")
    # the driver-recorded headline: north-star ResNet-50 throughput
    if headline is not None:
        reg.emit(headline, kind="bench")


if __name__ == "__main__":
    main()
