"""Benchmark grid — JSON lines, one per config; the LAST line is the
north-star metric (ResNet-50 throughput/MFU).

Configs mirror the reference's published tables (benchmark/README.md:31-58,
113-119) plus BASELINE.md's targets: AlexNet ms/batch grid vs the K40m
numbers, ResNet-50 img/s + MFU, seq2seq NMT seq/s, CTR examples/s.
``vs_baseline`` is reference_time / our_time where the reference published a
number (>1 ⇒ faster than the reference hardware), else 0.

MFU counting: FLOPs = 2×MACs (ResNet-50 fwd ≈ 4.09 GFLOP/img at 224²),
train ≈ 3× fwd, against the v5e bf16 peak 197 TFLOP/s.  The same step's
bandwidth roofline is discussed in BENCHMARKS.md — ResNet training on one
v5e chip is HBM-bound in BN/elementwise, not MXU-bound.

Timing: two-point chained-dispatch method with a scalar readback fence (the
tunneled backend acks block_until_ready without completion; see
paddle_tpu/profiler.py).
"""

from __future__ import annotations

import json
import time

import numpy as np

PEAK_FLOPS = 197e12  # v5e bf16
RESNET_FWD_GFLOP_PER_IMG = 4.09  # 2*MACs at 224x224


def _two_point(step_fn, warmup=3, n1=5, n2=25):
    """ms per step via chained dispatch; step_fn() must keep its own state
    and return a scalar-readback-able array."""
    def run(n):
        t0 = time.perf_counter()
        c = None
        for _ in range(n):
            c = step_fn()
        float(np.asarray(c).reshape(-1)[0])
        return time.perf_counter() - t0

    run(warmup)
    t1 = min(run(n1) for _ in range(2))
    t2 = min(run(n2) for _ in range(2))
    return max(t2 - t1, 1e-9) / (n2 - n1) * 1000.0


def _image_step(model_fn, batch, img_dim, lr=0.01):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import base
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    cost = model_fn()
    topo = Topology(cost)
    opt = Momentum(momentum=0.9, learning_rate=lr / batch)
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, opt, compute_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    feed = {
        "image": jax.device_put(
            rng.normal(size=(batch, img_dim)).astype(np.float32)
        ),
        "label": jax.device_put(rng.integers(0, 1000, size=(batch,))),
    }
    key = jax.random.key(0)
    state = {"p": params, "o": opt_state, "s": states}

    def one():
        state["p"], state["o"], state["s"], c, _ = step(
            state["p"], state["o"], state["s"], feed, key
        )
        return c

    return one


def bench_alexnet(records):
    from paddle_tpu.models import image as M

    # reference: 195/334/602 ms on 1x K40m (benchmark/README.md:31-38)
    k40 = {64: 195.0, 128: 334.0, 256: 602.0}
    for bs in (64, 128):
        step = _image_step(lambda: M.alexnet_cost()[0], bs, 227 * 227 * 3)
        ms = _two_point(step)
        records.append({
            "metric": f"alexnet_train_ms_per_batch_bs{bs}",
            "value": round(ms, 3), "unit": "ms",
            "vs_baseline": round(k40[bs] / ms, 2),
        })


def bench_resnet(records):
    from paddle_tpu.models import image as M

    best = None
    for bs in (64, 128):
        step = _image_step(lambda: M.resnet_cost(depth=50)[0], bs,
                           224 * 224 * 3, lr=0.1)
        ms = _two_point(step, n2=15)
        img_s = bs / ms * 1000.0
        tf = 3 * RESNET_FWD_GFLOP_PER_IMG * bs / ms  # GFLOP/ms == TF/s
        mfu = tf * 1e12 / PEAK_FLOPS
        records.append({
            "metric": f"resnet50_train_img_per_sec_bs{bs}",
            "value": round(img_s, 1), "unit": "img/s",
            "mfu_pct": round(mfu * 100, 1),
            "vs_baseline": 0,
        })
        if best is None or img_s > best["value"]:
            best = {
                "metric": "resnet50_train_img_per_sec",
                "value": round(img_s, 1), "unit": "img/s",
                "mfu_pct": round(mfu * 100, 1),
                "batch_size": bs,
                "vs_baseline": 0,
            }
    return best


def bench_nmt(records):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.layers import base
    from paddle_tpu.models import seqtoseq as S
    from paddle_tpu.optimizer import Adam
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    cost = S.seqtoseq_net(30000, 30000, word_vector_dim=512,
                          encoder_size=512, decoder_size=512)
    topo = Topology(cost)
    opt = Adam(learning_rate=5e-4)
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, opt)
    rng = np.random.default_rng(0)
    bs, tlen = 64, 32
    feed = {
        "source_language_word": SequenceBatch(
            data=rng.integers(0, 30000, size=(bs, tlen)),
            length=np.full((bs,), tlen, np.int32)),
        "target_language_word": SequenceBatch(
            data=rng.integers(0, 30000, size=(bs, tlen)),
            length=np.full((bs,), tlen, np.int32)),
        "target_language_next_word": SequenceBatch(
            data=rng.integers(0, 30000, size=(bs, tlen)),
            length=np.full((bs,), tlen, np.int32)),
    }
    key = jax.random.key(0)
    state = {"p": params, "o": opt_state, "s": states}

    def one():
        state["p"], state["o"], state["s"], c, _ = step(
            state["p"], state["o"], state["s"], feed, key)
        return c

    ms = _two_point(one, n2=15)
    records.append({
        "metric": "nmt_attention_train_seq_per_sec",
        "value": round(bs / ms * 1000.0, 1), "unit": "seq/s",
        "vs_baseline": 0,
    })


def bench_ctr(records):
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import base
    from paddle_tpu.models.ctr import wide_and_deep_ctr
    from paddle_tpu.optimizer import AdaGrad
    from paddle_tpu.reader.feeder import DataFeeder
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    wide_dim, vocabs = 10000, [1000] * 8
    cost, predict, _ = wide_and_deep_ctr(
        wide_dim=wide_dim, categorical_vocab_sizes=vocabs,
        embedding_size=64, hidden_sizes=(256, 128))
    topo = Topology(cost)
    opt = AdaGrad(learning_rate=1e-2)
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, opt)
    rng = np.random.default_rng(0)
    bs = 1024
    from paddle_tpu.layers.data_type import (
        integer_value,
        sparse_binary_vector,
    )

    dtypes = {"wide_input": sparse_binary_vector(wide_dim),
              "label": integer_value(2)}
    for i in range(len(vocabs)):
        dtypes[f"cat_{i}"] = integer_value(vocabs[i])
    feeder = DataFeeder(dtypes)
    batch = []
    for _ in range(bs):
        row = [rng.integers(0, wide_dim, size=3).tolist()]
        row += [int(rng.integers(0, v)) for v in vocabs]
        row.append(int(rng.integers(0, 2)))
        batch.append(tuple(row))
    feed = feeder.feed(batch)
    key = jax.random.key(0)
    state = {"p": params, "o": opt_state, "s": states}

    def one():
        state["p"], state["o"], state["s"], c, _ = step(
            state["p"], state["o"], state["s"], feed, key)
        return c

    ms = _two_point(one)
    records.append({
        "metric": "ctr_wide_deep_train_examples_per_sec",
        "value": round(bs / ms * 1000.0, 0), "unit": "ex/s",
        "vs_baseline": 0,
    })


def main() -> None:
    records: list[dict] = []
    failures = []
    for fn in (bench_alexnet, bench_nmt, bench_ctr):
        try:
            fn(records)
        except Exception as e:  # keep the headline alive
            failures.append(f"{fn.__name__}: {type(e).__name__}: {e}")
    try:
        headline = bench_resnet(records)
    except Exception as e:
        failures.append(f"bench_resnet: {type(e).__name__}: {e}")
        headline = None
    for r in records:
        print(json.dumps(r))
    if failures:
        print(json.dumps({"metric": "bench_failures", "value": len(failures),
                          "unit": "count", "detail": failures,
                          "vs_baseline": 0}))
    # the driver-recorded headline: north-star ResNet-50 throughput
    if headline is not None:
        print(json.dumps(headline))


if __name__ == "__main__":
    main()
