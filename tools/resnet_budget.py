"""ResNet-50 bs128 train-step HBM byte budget — bottom-up minimum traffic
vs the profiler's measured byte counts (VERDICT r3 item 3: "is 43.2
GB/step necessary?").

The budget assumes PERFECT fusion and residency:

- forward: every conv reads its input activation once (bf16, the
  compute_dtype policy), reads its weights (bf16 copy of the f32
  master), writes its output once; BN scale/shift and ReLU are epilogue
  math (no extra traffic beyond the tiny stats vectors); each residual
  add re-reads the skip tensor once.
- batch-norm statistics: one extra READ of each conv output (the mean/var
  reduction cannot fuse into the conv that produces the tensor on TPU —
  XLA's conv epilogue cannot hold the cross-batch reduction) — this is
  the one "optional" line the audit flags; a fully fused single-pass
  Welford epilogue would remove it.
- backward: for each conv, dY is read twice (once by the dX contraction,
  once by dW), the saved bf16 input activation read once (dW), weights
  read once (dX), dX written once, dW written once (f32).
- optimizer (momentum): read grad f32 + master f32 + momentum f32, write
  master + momentum  -> 5 x 4 bytes per parameter.
- loss/head: logits [128, 1000] negligible.

Run: PYTHONPATH=. python tools/resnet_budget.py
"""

from __future__ import annotations

BS = 128
BF16 = 2
F32 = 4


def resnet50_convs():
    """(name, in_hw, cin, k, stride, out_hw, cout) for every conv,
    including projection shortcuts (standard ResNet-50 v1.5 shapes)."""
    convs = [("stem", 224, 3, 7, 2, 112, 64)]
    stages = [  # (blocks, cin_first, mid, out, hw_in, stride_first)
        (3, 64, 64, 256, 56, 1),
        (4, 256, 128, 512, 56, 2),
        (6, 512, 256, 1024, 28, 2),
        (3, 1024, 512, 2048, 14, 2),
    ]
    for si, (blocks, cin0, mid, cout, hw_in, stride0) in enumerate(stages):
        cin = cin0
        hw = hw_in
        for b in range(blocks):
            stride = stride0 if b == 0 else 1
            hw_out = hw // stride
            tag = f"s{si+1}b{b+1}"
            convs.append((f"{tag}.c1", hw, cin, 1, 1, hw, mid))
            convs.append((f"{tag}.c2", hw, mid, 3, stride, hw_out, mid))
            convs.append((f"{tag}.c3", hw_out, mid, 1, 1, hw_out, cout))
            if b == 0:
                convs.append((f"{tag}.proj", hw, cin, 1, stride, hw_out,
                              cout))
            cin = cout
            hw = hw_out
    return convs


def budget(bs: int = BS):
    convs = resnet50_convs()
    act_in = act_out = weights = 0
    n_params = 0
    for name, hw, cin, k, stride, hwo, cout in convs:
        a_in = bs * hw * hw * cin * BF16
        a_out = bs * hwo * hwo * cout * BF16
        w = k * k * cin * cout * BF16
        n_params += k * k * cin * cout
        act_in += a_in
        act_out += a_out
        weights += w

    # residual skip adds: one extra read of each block output (16 blocks)
    skip = 0
    hw_map = [(3, 56, 256), (4, 28, 512), (6, 14, 1024), (3, 7, 2048)]
    for blocks, hw, cout in hw_map:
        skip += blocks * bs * hw * hw * cout * BF16

    fwd = act_in + act_out + weights + skip
    bn_stats = act_out  # one extra read of each conv output for mean/var
    # BN backward reduction pass: dgamma/dbeta and the recentering terms
    # need one read of dY and one of x_hat (= the saved conv output) that
    # cannot fuse into the conv-bwd contractions' own operand reads
    bn_bwd = 2 * act_out
    # backward: dY read twice + act read once + W read + dX write + dW write
    bwd = (2 * act_out        # dY read by dX and dW contractions
           + act_in           # saved activations (dW)
           + weights          # W read (dX)
           + act_in           # dX written (same sizes as inputs)
           + n_params * F32)  # dW written f32
    opt = 5 * n_params * F32
    total = fwd + bn_stats + bn_bwd + bwd + opt
    rows = [
        ("fwd: conv input reads (bf16)", act_in),
        ("fwd: conv output writes (bf16)", act_out),
        ("fwd: weight reads (bf16)", weights),
        ("fwd: residual skip re-reads", skip),
        ("BN statistics pass (re-read of conv outputs)", bn_stats),
        ("BN backward reduce pass (dY + x_hat reads)", bn_bwd),
        ("bwd: dY reads (x2: dX + dW contractions)", 2 * act_out),
        ("bwd: saved activation reads", act_in),
        ("bwd: weight reads", weights),
        ("bwd: dX writes", act_in),
        ("bwd: dW writes (f32)", n_params * F32),
        ("optimizer (momentum, 5x f32/param)", opt),
    ]
    return rows, total, n_params


def v5p_projection(total_bytes: float, serviceable_gb: float):
    """Price the measured byte budget against v5p's bytes/flops ratio
    (VERDICT r4 #5): the ≥50%-MFU north star was written for v5p-32,
    while the 'physically unreachable' conclusion was measured on v5e.

    Public chip specs: v5e 197 TF/s bf16, 819 GB/s HBM; v5p 459 TF/s
    bf16, 2765 GB/s HBM — v5p has 1.44x the bytes-per-flop.  The v5e
    STREAM triad achieves 670/819 = 81.8% of spec; the projection
    assumes the same achievable fraction on v5p."""
    tflop_step = 1.58e12  # measured model_flops per bs128 train step
    v5p_peak = 459e12
    v5p_bw = 2765.0 * 0.818  # GB/s, STREAM-scaled
    for label, gb in (("bottom-up minimum", total_bytes / 1e9),
                      ("measured serviceable", serviceable_gb)):
        t_bw = gb / v5p_bw * 1e3           # ms, bandwidth floor
        t_fl = tflop_step / v5p_peak * 1e3  # ms, compute floor
        t = max(t_bw, t_fl)
        mfu = tflop_step / (t * 1e-3) / v5p_peak * 100
        bound = "bandwidth" if t_bw > t_fl else "compute"
        print(f"  v5p @ {label} ({gb:.1f} GB): step >= {t:.1f} ms "
              f"({bound}-bound) -> MFU <= {mfu:.1f}%")
    # the chip-independent statement: model arithmetic intensity
    ai = tflop_step / total_bytes
    need = v5p_bw * 1e9 / (0.5 * v5p_peak)
    print(f"  model arithmetic intensity: {ai:.0f} FLOP/byte; 50% MFU on "
          f"v5p needs >= {1/need:.0f} FLOP/byte "
          f"({1/need/ai:.2f}x traffic reduction)")
    fused = total_bytes - 8.5e9 - 1.46e9  # perfect BN fusion + skip fusion
    print(f"  even with perfect BN-stats/BN-bwd/skip fusion "
          f"({fused/1e9:.1f} GB): MFU <= "
          f"{tflop_step / (fused / (v5p_bw*1e9)) / v5p_peak * 100:.1f}%")


def main():
    rows, total, n_params = budget()
    print(f"ResNet-50 bs{BS} minimum-traffic budget "
          f"({n_params/1e6:.1f}M conv params):")
    for name, b in rows:
        print(f"  {name:48s} {b/1e9:7.2f} GB")
    print(f"  {'TOTAL minimum':48s} {total/1e9:7.2f} GB")
    print()
    ms, stream = 45.25, 670.0
    serviceable = ms * 1e-3 * stream
    print("measured (tools/profile_resnet.py): 43.2 GB COUNTED per step —")
    print("  per-op raw_bytes_accessed double-counts VMEM-served fusion")
    print("  operands (the 'other' segment runs at 4000+ GB/s counted);")
    print(f"  the step's {ms} ms at the {stream:.0f} GB/s STREAM ceiling can")
    print(f"  physically service {serviceable:.1f} GB")
    slack = serviceable - total / 1e9
    print(f"slack: {serviceable:.1f} - {total/1e9:.1f} = {slack:.1f} GB "
          f"({slack / serviceable * 100:.0f}% of serviceable)")
    print()
    print("v5p-32 projection (north-star hardware):")
    v5p_projection(total, serviceable)


if __name__ == "__main__":
    main()
