"""MoE transformer step-time on one chip (dense-dispatch path).

Measures a GPT-2-small-width MoE LM (top-2, capacity 1.25) against the
dense-FFN 124M baseline at matched active FLOPs — the capability row for
parallel/moe.py.  Device-side timing.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python tools/bench_moe.py
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import transformer as T
from paddle_tpu.optimizer import Adam
from paddle_tpu.profiler import device_step_ms

VOCAB = 50257


def run(name: str, cfg: T.TransformerConfig, bs=8, seqlen=1024):
    params = T.init_params(cfg, jax.random.key(0))
    n = sum(x.size for x in jax.tree.leaves(params))
    opt = Adam(learning_rate=1e-4, moment_dtype=jnp.bfloat16)
    st = {"p": params, "o": opt.init_tree(params)}
    ids = jax.device_put(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(bs, seqlen + 1)))
    step = T.build_train_step(cfg, opt, compute_dtype=jnp.bfloat16)

    def one():
        st["p"], st["o"], loss = step(st["p"], st["o"], ids)
        return loss

    ms = device_step_ms(one, steps=10, warmup=3)
    tokens = bs * seqlen
    # active params per token: dense share + top_k/E of expert weights
    print(f"{name:22s} {ms:8.2f} ms/step  {tokens / ms * 1000:9.0f} tok/s  "
          f"(params {n / 1e6:.0f}M)")
    return ms


def main():
    base = dict(vocab_size=VOCAB, num_layers=12, num_heads=12,
                embed_dim=768, mlp_dim=3072, max_seq_len=2048,
                dtype=jnp.float32, remat=False, attn_impl="flash",
                attn_block_size=1024)
    import sys

    known = ["dense", "top2", "top1", "top2sort", "top1sort"]
    sel = sys.argv[1:] or known
    bad = [s for s in sel if s not in known]
    if bad:
        raise SystemExit(f"unknown variants {bad}; choose from {known}")
    if "dense" in sel:
        run("dense-124M", T.TransformerConfig(**base), bs=8)
    if "top2" in sel:
        run("moe-8e-top2", T.TransformerConfig(
            **base, moe_experts=8, moe_top_k=2,
            moe_capacity_factor=1.25), bs=8)
    if "top1" in sel:
        run("moe-8e-top1", T.TransformerConfig(
            **base, moe_experts=8, moe_top_k=1,
            moe_capacity_factor=1.25), bs=8)
    if "top2sort" in sel:
        run("moe-8e-top2-sort", T.TransformerConfig(
            **base, moe_experts=8, moe_top_k=2, moe_capacity_factor=1.25,
            moe_dispatch="sort"), bs=8)
    if "top1sort" in sel:
        run("moe-8e-top1-sort", T.TransformerConfig(
            **base, moe_experts=8, moe_top_k=1, moe_capacity_factor=1.25,
            moe_dispatch="sort"), bs=8)


if __name__ == "__main__":
    main()
