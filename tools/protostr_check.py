#!/usr/bin/env python
"""Golden-protostr compatibility harness.

Runs reference config files (trainer_config_helpers/tests/configs/*.py)
through paddle_tpu's parse_config and diffs the emitted ModelConfig protostr
against the reference goldens (protostr/*.protostr).  Development tool; the
pytest version of the passing set lives in tests/test_protostr_golden.py.

Usage:
  python tools/protostr_check.py              # summary over all configs
  python tools/protostr_check.py test_fc      # full diff for one config
"""

from __future__ import annotations

import difflib
import os
import sys
import traceback

REF = "/root/reference/python/paddle/trainer_config_helpers/tests/configs"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(name: str, show: bool = False) -> str:
    from paddle_tpu.trainer.config_parser import parse_config

    golden_path = os.path.join(REF, "protostr", name + ".protostr")
    cfg_path = os.path.join(REF, name + ".py")
    if not os.path.exists(golden_path):
        return "NO-GOLDEN"
    if not os.path.exists(cfg_path):
        return "NO-CONFIG"
    try:
        parsed = parse_config(cfg_path)
        want_head = open(golden_path).readline()
        if want_head.startswith("model_config"):
            from paddle_tpu.config.protostr import to_protostr

            got = to_protostr(parsed.trainer_config,
                              getattr(parsed, "int_style", None))
        else:
            got = parsed.protostr()
    except Exception as e:
        if show:
            traceback.print_exc()
        return f"ERROR: {type(e).__name__}: {str(e)[:120]}"
    want = open(golden_path).read()
    # goldens end "}\n\n" (py2 `print proto` adds a newline on top of the
    # text-format trailing one); normalize only that artifact
    if got.rstrip("\n") == want.rstrip("\n"):
        return "PASS"
    if show:
        sys.stdout.writelines(
            difflib.unified_diff(
                want.splitlines(True), got.splitlines(True),
                "golden", "emitted", n=2,
            )
        )
    ndiff = sum(
        1 for l in difflib.unified_diff(want.splitlines(), got.splitlines())
        if l[:1] in "+-"
    )
    return f"DIFF({ndiff})"


def main():
    if len(sys.argv) > 1:
        for name in sys.argv[1:]:
            print(f"== {name}: {run_one(name, show=True)}")
        return
    names = sorted(
        f[:-3] for f in os.listdir(REF)
        if f.endswith(".py") and not f.startswith("__")
    )
    results = {}
    for name in names:
        results[name] = run_one(name)
    npass = sum(1 for v in results.values() if v == "PASS")
    for name, res in sorted(results.items()):
        print(f"{res:40s} {name}")
    print(f"\n{npass}/{len([v for v in results.values() if v != 'NO-GOLDEN'])} byte-exact")


if __name__ == "__main__":
    main()
