#!/usr/bin/env python
"""Kernel/oracle pairing audit — THIN SHIM.

The audit moved into the static-analysis suite as the ``GL-KERNEL``
pass (``paddle_tpu/analysis/kernel_parity.py``); this script keeps the
historical entry points (``audit()`` returning violation strings, a CLI
printing ``OK``/violations) so ``tests/test_kernel_parity.py`` and any
operator muscle memory keep working unchanged.  The rule itself is
unchanged: every ``pallas_call`` module under ``paddle_tpu/ops/pallas/``
must expose a public ``<entry>/<entry>_reference`` pair, both mentioned
by a parity test under ``tests/`` (the Compare2Function discipline,
``paddle/function/FunctionTest.h``).
"""

from __future__ import annotations

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from paddle_tpu.analysis.kernel_parity import audit, main  # noqa: E402,F401

if __name__ == "__main__":
    sys.exit(main(REPO))
