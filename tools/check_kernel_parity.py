#!/usr/bin/env python
"""Kernel/oracle pairing audit for ``paddle_tpu/ops/pallas/``.

Every Pallas kernel module must ship a pure-jnp reference twin
(``<entry>_reference``) and an interpret-mode parity test, so a future
one-off kernel can't land without an oracle (the Compare2Function
discipline the reference applied to its CUDA kernels,
``paddle/function/FunctionTest.h``).  Concretely, for every module under
``paddle_tpu/ops/pallas/`` (recursively, ``__init__`` excluded) that
calls ``pallas_call``:

1. the module defines at least one public ``<entry>_reference`` function
   whose base name ``<entry>`` is also defined in the module;
2. for each such pair, some file under ``tests/`` mentions BOTH the
   entry name and its reference name (the parity test — kernel vs
   oracle in interpret mode).

Run directly (exit 1 + a violation listing on failure) or through
``tests/test_kernel_parity.py``, which wires it into tier-1.
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PALLAS_DIR = os.path.join(REPO, "paddle_tpu", "ops", "pallas")
TESTS_DIR = os.path.join(REPO, "tests")


def kernel_modules() -> list[str]:
    out = []
    for root, _dirs, files in os.walk(PALLAS_DIR):
        for f in sorted(files):
            if f.endswith(".py") and f != "__init__.py":
                out.append(os.path.join(root, f))
    return out


def module_defs(path: str) -> list[str]:
    with open(path) as fh:
        tree = ast.parse(fh.read(), filename=path)
    return [n.name for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def uses_pallas(path: str) -> bool:
    with open(path) as fh:
        return "pallas_call" in fh.read()


def tests_corpus() -> str:
    chunks = []
    for f in sorted(os.listdir(TESTS_DIR)):
        if f.endswith(".py"):
            with open(os.path.join(TESTS_DIR, f)) as fh:
                chunks.append(fh.read())
    return "\n".join(chunks)


def audit() -> list[str]:
    """Returns a list of violation strings (empty = pass)."""
    violations = []
    corpus = tests_corpus()
    for path in kernel_modules():
        rel = os.path.relpath(path, REPO)
        if not uses_pallas(path):
            continue
        defs = module_defs(path)
        pairs = [(n[:-len("_reference")], n) for n in defs
                 if n.endswith("_reference") and not n.startswith("_")]
        pairs = [(base, ref) for base, ref in pairs if base in defs]
        if not pairs:
            violations.append(
                f"{rel}: no public <entry>/<entry>_reference pair — every "
                f"kernel module needs a jnp oracle")
            continue
        for base, ref in pairs:
            if base not in corpus or ref not in corpus:
                missing = [n for n in (base, ref) if n not in corpus]
                violations.append(
                    f"{rel}: {base!r} has no interpret-mode parity test "
                    f"under tests/ ({', '.join(missing)} never referenced)")
    return violations


def main() -> int:
    violations = audit()
    mods = [m for m in kernel_modules() if uses_pallas(m)]
    if violations:
        print(f"check_kernel_parity: {len(violations)} violation(s) over "
              f"{len(mods)} kernel modules:")
        for v in violations:
            print(f"  - {v}")
        return 1
    print(f"check_kernel_parity: OK — {len(mods)} kernel modules, every "
          f"entry has a jnp reference and a tests/ parity mention")
    return 0


if __name__ == "__main__":
    sys.exit(main())
