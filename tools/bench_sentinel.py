#!/usr/bin/env python
"""Bench regression sentinel — the pre-merge bench gate.

Diffs two bench artifacts and exits nonzero when any tracked metric
regressed beyond the threshold, so a perf claim is a *checked* claim:

    python tools/bench_sentinel.py BENCH_r07.json BENCH_r08.json
    python tools/bench_sentinel.py old/ledger.jsonl new/ledger.jsonl \
        --threshold 0.05
    python tools/bench_sentinel.py --self-test

Accepted artifacts (either side, mixable):

- ``BENCH_*.json`` — the round snapshots ``bench.py`` tails into
  ``{"n", "cmd", "rc", "tail"}``; every ``kind="bench"`` record in the
  tail contributes its ``metric``/``value``/``unit``;
- a ``ledger.jsonl`` / telemetry ``metrics.jsonl`` — JSONL of schema
  records; ``kind="bench"`` rows contribute as above, the LAST
  ``kind="ledger"`` row contributes ``goodput_fraction`` and the
  serving ``cost_per_token_*`` split (telemetry/goodput.py).

Regression direction is inferred per metric: time-like units (ms/s)
and latency/cost/padding/badput names regress UPWARD, throughput-like
metrics (tok/s, samples/s, speedups, MFU, goodput fraction) regress
DOWNWARD.  Metrics present on only one side are reported but never
fatal (rounds add benches; the gate judges the intersection).

``--threshold`` is the tolerated relative change (default 0.10).
``--metrics a,b`` restricts the tracked set; default = every shared
metric.  Exit codes: 0 clean, 1 regression(s), 2 usage/parse error.

``--self-test`` seeds a synthetic pair (one halved throughput metric)
in a temp dir and verifies the sentinel flags it — the fixture
``tests/test_bench_sentinel.py`` wires into tier-1.
"""

from __future__ import annotations

import json
import os
import sys

# tolerated relative change before a tracked metric fails the gate
DEFAULT_THRESHOLD = 0.10

_LOWER_IS_BETTER_UNITS = {"ms", "s", "seconds", "s/token"}
_LOWER_IS_BETTER_TOKENS = ("ttft", "tpot", "latency", "cost_per_token",
                           "padded", "badput", "_ms", "ms_per",
                           "queue_wait", "recovery")


def lower_is_better(name: str, unit: str | None) -> bool:
    """Direction of regression for one metric: True when an INCREASE is
    the regression (latencies, costs, padding waste)."""
    if unit and unit.lower() in _LOWER_IS_BETTER_UNITS:
        return True
    n = name.lower()
    return any(tok in n for tok in _LOWER_IS_BETTER_TOKENS)


def _ledger_metrics(rec: dict) -> dict[str, dict]:
    out = {"goodput_fraction": {"value": rec.get("goodput_fraction"),
                                "unit": "frac"}}
    serving = rec.get("serving") or {}
    for k in ("cost_per_token_s", "cost_per_token_prefill_s",
              "cost_per_token_decode_s", "cost_per_token_queue_s"):
        if serving.get(k) is not None:
            out[k] = {"value": serving[k], "unit": "s/token"}
    return {k: v for k, v in out.items()
            if isinstance(v["value"], (int, float))}


def load_metrics(path: str) -> dict[str, dict]:
    """{metric name: {"value", "unit"}} from one artifact (see module
    docstring for the accepted shapes).  Raises ValueError when the
    file yields no metrics at all — a gate diffing nothing against
    nothing must not pass silently."""
    with open(path) as f:
        text = f.read()
    lines: list[str] = []
    stripped = text.lstrip()
    if stripped.startswith("{") and '"tail"' in stripped.split("\n", 1)[0] \
            or _is_bench_snapshot(stripped):
        snap = json.loads(text)
        lines = str(snap.get("tail", "")).splitlines()
    else:
        lines = text.splitlines()
    out: dict[str, dict] = {}
    ledger_last: dict | None = None
    for line in lines:
        line = line.strip()
        if not line or not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        kind = rec.get("kind")
        if kind == "bench" and isinstance(rec.get("metric"), str) \
                and isinstance(rec.get("value"), (int, float)):
            out[rec["metric"]] = {"value": float(rec["value"]),
                                  "unit": rec.get("unit")}
        elif kind == "ledger":
            ledger_last = rec
    if ledger_last is not None:
        out.update(_ledger_metrics(ledger_last))
    if not out:
        raise ValueError(
            f"{path}: no bench or ledger metrics found (expected a "
            f"BENCH_*.json snapshot or a JSONL of kind=bench/ledger "
            f"records)")
    return out


def _is_bench_snapshot(stripped: str) -> bool:
    if not stripped.startswith("{"):
        return False
    try:
        head = json.loads(stripped.split("\n", 1)[0].rstrip().rstrip(","))
    except ValueError:
        try:
            head = json.loads(stripped)
        except ValueError:
            return False
    return isinstance(head, dict) and "tail" in head


def compare(base: dict[str, dict], cand: dict[str, dict],
            threshold: float = DEFAULT_THRESHOLD,
            metrics: list[str] | None = None) -> dict:
    """Judge candidate vs. base.  Returns {"rows": [...], "regressions":
    [names], "only_base": [...], "only_cand": [...]}."""
    shared = sorted(set(base) & set(cand))
    if metrics:
        missing = [m for m in metrics if m not in shared]
        if missing:
            raise ValueError(
                f"tracked metric(s) not present on both sides: {missing}")
        shared = [m for m in shared if m in metrics]
    rows, regressions = [], []
    for name in shared:
        b, c = base[name]["value"], cand[name]["value"]
        unit = cand[name].get("unit") or base[name].get("unit")
        lower = lower_is_better(name, unit)
        rel = (c - b) / abs(b) if b else (0.0 if c == b else float("inf"))
        regressed = (rel > threshold) if lower else (rel < -threshold)
        if regressed:
            regressions.append(name)
        rows.append({"metric": name, "base": b, "cand": c,
                     "unit": unit, "rel_change": rel,
                     "direction": "lower_better" if lower
                                  else "higher_better",
                     "regressed": regressed})
    return {"rows": rows, "regressions": regressions,
            "only_base": sorted(set(base) - set(cand)),
            "only_cand": sorted(set(cand) - set(base)),
            "threshold": threshold}


def render(result: dict, base_path: str, cand_path: str) -> str:
    lines = [f"bench_sentinel: {base_path} -> {cand_path} "
             f"(threshold {result['threshold']:.0%})",
             f"{'metric':44s} {'base':>12s} {'cand':>12s} "
             f"{'change':>8s}  verdict"]
    for r in result["rows"]:
        verdict = "REGRESSED" if r["regressed"] else "ok"
        arrow = "↓ better" if r["direction"] == "lower_better" \
            else "↑ better"
        lines.append(
            f"{r['metric'][:44]:44s} {r['base']:12.4g} {r['cand']:12.4g} "
            f"{r['rel_change']:+7.1%}  {verdict} ({arrow})")
    for name in result["only_base"]:
        lines.append(f"{name[:44]:44s} {'—':>12s} {'—':>12s} "
                     f"{'':8s}  base-only (not judged)")
    for name in result["only_cand"]:
        lines.append(f"{name[:44]:44s} {'—':>12s} {'—':>12s} "
                     f"{'':8s}  new (not judged)")
    n = len(result["regressions"])
    lines.append(f"bench_sentinel: {len(result['rows'])} tracked, "
                 f"{n} regression(s)"
                 + (f": {', '.join(result['regressions'])}" if n else ""))
    return "\n".join(lines)


def write_regression_fixture(dirpath: str) -> tuple[str, str]:
    """Seed a (base, candidate) BENCH pair where the candidate halves
    one throughput metric and doubles one latency metric — the
    self-test / tier-1 fixture.  Returns the two paths."""
    os.makedirs(dirpath, exist_ok=True)

    def snap(path, rows):
        tail = "\n".join(json.dumps({"kind": "bench", **r}) for r in rows)
        with open(path, "w") as f:
            json.dump({"n": len(rows), "cmd": "self-test", "rc": 0,
                       "tail": tail}, f)
        return path

    base = snap(os.path.join(dirpath, "BENCH_base.json"), [
        {"metric": "toy_train_samples_per_sec", "value": 100.0,
         "unit": "samples/s"},
        {"metric": "toy_p99_ttft_ms", "value": 50.0, "unit": "ms"},
        {"metric": "toy_mfu_pct", "value": 40.0, "unit": "%"},
    ])
    cand = snap(os.path.join(dirpath, "BENCH_regressed.json"), [
        {"metric": "toy_train_samples_per_sec", "value": 50.0,
         "unit": "samples/s"},
        {"metric": "toy_p99_ttft_ms", "value": 100.0, "unit": "ms"},
        {"metric": "toy_mfu_pct", "value": 41.0, "unit": "%"},
    ])
    return base, cand


def self_test() -> int:
    import tempfile

    with tempfile.TemporaryDirectory(prefix="bench_sentinel_") as d:
        base, cand = write_regression_fixture(d)
        rc = main([base, cand, "--threshold", "0.10"])
        if rc == 0:
            print("bench_sentinel --self-test: FAILED — seeded "
                  "regression not flagged", file=sys.stderr)
            return 1
        # and the clean direction must stay clean
        rc_clean = main([base, base])
        if rc_clean != 0:
            print("bench_sentinel --self-test: FAILED — identical "
                  "artifacts flagged", file=sys.stderr)
            return 1
    print("bench_sentinel --self-test: ok (seeded regression flagged, "
          "identical pair clean)")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--self-test" in argv:
        return self_test()
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__.strip())
        return 2
    threshold = DEFAULT_THRESHOLD
    metrics = None
    as_json = False
    if "--threshold" in argv:
        i = argv.index("--threshold")
        threshold = float(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    if "--metrics" in argv:
        i = argv.index("--metrics")
        metrics = [m for m in argv[i + 1].split(",") if m]
        argv = argv[:i] + argv[i + 2:]
    if "--json" in argv:
        as_json = True
        argv.remove("--json")
    if len(argv) != 2:
        print("bench_sentinel: need exactly BASE and CANDIDATE artifacts "
              f"(got {argv})", file=sys.stderr)
        return 2
    base_path, cand_path = argv
    try:
        base = load_metrics(base_path)
        cand = load_metrics(cand_path)
        result = compare(base, cand, threshold=threshold, metrics=metrics)
    except (OSError, ValueError) as e:
        print(f"bench_sentinel: {e}", file=sys.stderr)
        return 2
    if as_json:
        print(json.dumps(result, indent=2))
    else:
        print(render(result, base_path, cand_path))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
