"""ResNet-50 train-step segment analysis — the measured (not modeled)
bandwidth roofline VERDICT r2 asked for.

Buckets every executed HLO op of the bs-128 train step into segments
(conv MXU work vs BN/elementwise chains vs pooling vs loss/optimizer),
summing device time, model FLOPs and raw bytes accessed from the profiler
trace, and reports achieved GB/s and TF/s per segment against the v5e
peaks (197 TFLOP/s bf16, 819 GB/s HBM).
"""

from __future__ import annotations

import re
import sys

from bench import _image_step
from paddle_tpu.models import image as M
from tools.xprof import profile_step, device_module_ms

PEAK_GBPS = 819.0
PEAK_TFLOPS = 197.0


def segment(row) -> str:
    tf_op = row["tf_op"]
    name = row["name"]
    if "conv_general_dilated" in tf_op:
        # MXU conv vs bandwidth-bound fused bwd chains: split by achieved
        # compute intensity instead — keep one conv segment, let the
        # aggregate speak
        return "conv (fwd+bwd, incl fused BN math)"
    if re.search(r"reduce_window|select_and_scatter|_pool", tf_op):
        return "pooling"
    if re.search(r"transpose|copy|pad|reshape|bitcast|convert", tf_op) and row["flops"] == 0:
        return "layout/copy"
    if re.search(r"log_softmax|softmax|reduce_sum|div|sub:|exp|gather|scatter|one_hot|max:|add:|mul|rsqrt|sqrt|select", tf_op):
        return "elementwise/BN-apply/loss"
    return "other"


def main():
    bs = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    step = _image_step(lambda: M.resnet_cost(depth=50)[0], bs, 224 * 224 * 3,
                       lr=0.1)
    ms = device_module_ms(step, steps=5)
    print(f"bs{bs}: {ms:.2f} ms/step device -> {bs / ms * 1000:.0f} img/s, "
          f"MFU {3 * 4.09 * bs / ms / 197 * 100:.1f}%")
    rows, totals = profile_step(step, steps=3, top=0)
    seg = {}
    for r in rows:
        s = segment(r)
        d = seg.setdefault(s, {"ms": 0.0, "flops": 0.0, "bytes": 0.0, "n": 0})
        d["ms"] += r["ms"]
        d["flops"] += r["flops"] / 3
        d["bytes"] += r["bytes"] / 3
        d["n"] += r["count"] // 3
    print(f"\n{'segment':40s} {'ms':>7} {'%':>5} {'GB':>6} {'GB/s':>6} "
          f"{'%peakBW':>7} {'TF/s':>6} {'ops':>4}")
    for s, d in sorted(seg.items(), key=lambda kv: -kv[1]["ms"]):
        gbps = d["bytes"] / max(d["ms"] * 1e-3, 1e-12) / 1e9
        tf = d["flops"] / max(d["ms"] * 1e-3, 1e-12) / 1e12
        print(f"{s:40s} {d['ms']:7.2f} {d['ms'] / totals['ms'] * 100:5.1f} "
              f"{d['bytes'] / 1e9:6.2f} {gbps:6.0f} {gbps / PEAK_GBPS * 100:7.1f} "
              f"{tf:6.1f} {d['n']:4d}")
    print(f"\ntotal: {totals['ms']:.2f} ms, {totals['bytes'] / 1e9:.1f} GB "
          f"counted, avg {totals['gbps']:.0f} GB/s "
          f"({totals['gbps'] / PEAK_GBPS * 100:.0f}% of HBM peak), "
          f"{totals['tflops']:.1f} TF/s")


if __name__ == "__main__":
    main()
