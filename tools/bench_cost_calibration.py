#!/usr/bin/env python
"""tools/bench_cost_calibration.py — ties the GL-P-COST roofline to a
tracewire-measured wall clock, so the static model stays honest.

For each checked-in bench family (transformer LM, resnet50, lstm) it
builds a **CPU-calibration shape** — the same architecture as the bench
config with reduced dims, because the full bench shapes take minutes
per step on the 1-core CI box — then:

- predicts the compute-phase time with ``cost_report(...)`` under the
  ``cpu-testbed`` profile (XLA's own ``cost_analysis()`` refinement
  engages, same as ``trainer --preflight``);
- measures it with a tracewire ``Tracer``: one warmup step (compile +
  first-touch excluded), then ``--steps`` executed steps each inside a
  ``span("compute")`` with ``block_until_ready``, taking the phase p50;
- fails (rc 1) when any family's prediction/measurement ratio leaves
  the documented band ``[1/BAND, BAND]`` with ``BAND = 2.0``.

The band is the contract BENCHMARKS.md documents: the ``cpu-testbed``
``HwProfile`` constants in ``paddle_tpu/analysis/cost.py`` are
*calibrated against this harness*, not datasheet numbers.  A run
outside the band means either those constants or the charging rules
drifted — fix the model, don't widen the band.

    python tools/bench_cost_calibration.py
    python tools/bench_cost_calibration.py --families lstm --steps 5
    python tools/bench_cost_calibration.py --json -
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# documented prediction band: predicted/measured must stay in
# [1/BAND, BAND].  2× is loose for a reason — XLA:CPU's achieved
# FLOP/s swings with shape, and the roofline carries no fusion model.
BAND = 2.0


def _measure(step, args_fn, steps: int) -> float:
    """Phase p50 over ``steps`` executed calls of ``step`` (donation-safe:
    ``args_fn`` threads the returned state back in), warmup excluded."""
    import jax

    from paddle_tpu.telemetry.tracing import Tracer

    tracer = Tracer(enabled=True)
    state = args_fn(None)
    state = jax.block_until_ready(step(*state))  # warmup: compile+run
    for _ in range(steps):
        state = args_fn(state)
        with tracer.span("compute"):
            state = jax.block_until_ready(step(*state))
    return tracer.phase_summary()["compute"]["p50_ms"]


# -- CPU-calibration shapes (documented; same architectures as bench.py) --------


def _calibrate_transformer(steps: int) -> dict:
    """GPT-2 architecture at calibration scale: 2 layers, embed 128,
    4 heads, seq 128, bs 4 (bench: 12×768×12, seq 1024, bs 16)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    cfg = T.TransformerConfig(
        vocab_size=2048, num_layers=2, num_heads=4, embed_dim=128,
        mlp_dim=512, max_seq_len=256, dtype=jnp.float32, remat=False)
    params = T.init_params(cfg, jax.random.key(0))
    opt = Adam(learning_rate=1e-4, moment_dtype=jnp.bfloat16)
    opt_state = opt.init_tree(params)
    ids = jax.device_put(np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(4, 129)))
    step = T.build_train_step(cfg, opt, compute_dtype=jnp.bfloat16)

    def args_fn(prev):
        if prev is None:
            return (params, opt_state, ids)
        p, o, _loss = prev
        return (p, o, ids)

    return {"step": step, "args_fn": args_fn,
            "args": (params, opt_state, ids), "steps": steps}


def _calibrate_topology(cost_fn, feed, optimizer, steps: int) -> dict:
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import base
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    topo = Topology(cost_fn())
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = optimizer.init(params, specs)
    states = topo.init_states()
    step = build_train_step(topo, optimizer, compute_dtype=jnp.bfloat16)
    key = jax.random.key(0)

    def args_fn(prev):
        if prev is None:
            return (params, opt_state, states, feed, key)
        p, o, s, _cost, _metrics = prev
        return (p, o, s, feed, key)

    return {"step": step, "args_fn": args_fn,
            "args": (params, opt_state, states, feed, key),
            "steps": steps}


def _calibrate_resnet50(steps: int) -> dict:
    """The full resnet50 bottleneck stack at bs 1 (bench: bs 128).  The
    224×224 input cannot shrink — the trunk's stride-32 downsample ends
    in a hard-coded 7×7 global pool — so this family calibrates at full
    spatial resolution and caps its step count instead."""
    from paddle_tpu.models import image as M
    from paddle_tpu.optimizer import Momentum

    rng = np.random.default_rng(0)
    feed = {"image": rng.normal(size=(1, 224 * 224 * 3)).astype(
                np.float32),
            "label": rng.integers(0, 1000, size=(1,))}
    return _calibrate_topology(
        lambda: M.resnet_cost(depth=50)[0], feed,
        Momentum(momentum=0.9, learning_rate=0.01), min(steps, 3))


def _calibrate_lstm(steps: int) -> dict:
    """The bench lstm classifier at hidden 256, bs 16, T 50
    (bench: hidden 512, bs 256, T 100)."""
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.optimizer import Adam

    rng = np.random.default_rng(0)
    feed = {"data": SequenceBatch(
                data=rng.integers(0, 30000, size=(16, 50)),
                length=np.full((16,), 50, np.int32)),
            "label": rng.integers(0, 2, size=(16,))}
    return _calibrate_topology(
        lambda: __import__("bench")._lstm_classify_cost(256), feed,
        Adam(learning_rate=2e-3, moment_dtype=jnp.bfloat16), steps)


FAMILIES = {
    "transformer": _calibrate_transformer,
    "resnet50": _calibrate_resnet50,
    "lstm": _calibrate_lstm,
}


def calibrate_family(name: str, steps: int) -> dict:
    from paddle_tpu.analysis.cost import cost_report
    from paddle_tpu.analysis.program import jaxpr_of

    t0 = time.time()
    cal = FAMILIES[name](steps)
    jx = jaxpr_of(cal["step"], *cal["args"])
    lowered = None
    try:
        import jax

        lowered = jax.jit(cal["step"]).lower(*cal["args"])
    except Exception as e:
        # prediction falls back to the pure jaxpr walk
        print(f"bench_cost_calibration: {name}: lowering unavailable "
              f"({e}); using jaxpr-walk totals", file=sys.stderr)
    rep = cost_report(jx, profile="cpu-testbed", lowered=lowered)
    measured = _measure(cal["step"], cal["args_fn"], cal["steps"])
    ratio = rep["compute_ms"] / measured if measured > 0 else float("inf")
    return {
        "family": name,
        "predicted_compute_ms": round(rep["compute_ms"], 3),
        "measured_p50_ms": round(measured, 3),
        "ratio": round(ratio, 3),
        "in_band": (1.0 / BAND) <= ratio <= BAND,
        "flops_source": rep["flops_source"],
        "bottleneck": rep["bottleneck"],
        "wall_s": round(time.time() - t0, 1),
    }


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "-h" in argv or "--help" in argv:
        print(__doc__.strip())
        return 2

    def _opt(flag, default):
        if flag in argv:
            i = argv.index(flag)
            val = argv[i + 1]
            del argv[i:i + 2]
            return val
        return default

    steps = int(_opt("--steps", "5"))
    fams = _opt("--families", "")
    json_out = _opt("--json", "")
    families = [f for f in fams.split(",") if f] or list(FAMILIES)
    if argv:
        print(f"bench_cost_calibration: unknown arguments {argv}",
              file=sys.stderr)
        return 2
    unknown = [f for f in families if f not in FAMILIES]
    if unknown:
        print(f"bench_cost_calibration: unknown families {unknown} "
              f"(known: {', '.join(FAMILIES)})", file=sys.stderr)
        return 2

    rows = [calibrate_family(f, steps) for f in families]
    hdr = (f"{'family':<12} {'pred ms':>9} {'meas p50':>9} "
           f"{'ratio':>6}  band  source")
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        print(f"{r['family']:<12} {r['predicted_compute_ms']:>9.2f} "
              f"{r['measured_p50_ms']:>9.2f} {r['ratio']:>6.2f}  "
              f"{'ok  ' if r['in_band'] else 'FAIL'}  "
              f"{r['flops_source']}")
    ok = all(r["in_band"] for r in rows)
    verdict = (f"bench_cost_calibration: {'PASS' if ok else 'FAIL'} — "
               f"band [{1 / BAND:g}x, {BAND:g}x], {steps} steps/family")
    print(verdict)
    if json_out:
        payload = json.dumps({"band": BAND, "steps": steps,
                              "pass": ok, "rows": rows}, indent=1)
        if json_out == "-":
            print(payload)
        else:
            with open(json_out, "w") as f:
                f.write(payload + "\n")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
