"""Per-op TPU profiling via jax.profiler traces (no tensorboard needed).

``jax.profiler.start_trace`` emits a Chrome-trace ``*.trace.json.gz`` whose
``XLA Ops`` thread carries one complete event per executed HLO op with
``dur`` (device µs), ``model_flops`` and ``raw_bytes_accessed`` — enough to
attribute a step's wall time op-by-op and compute achieved FLOP/s and HBM
bandwidth per op class (the tensorboard_plugin_profile converter is
proto-incompatible with the installed protobuf; parsing the chrome trace
directly sidesteps it).

Usage:
    from tools.xprof import profile_step
    rows, totals = profile_step(lambda: step_fn(), steps=3)
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import tempfile

import numpy as np

import jax


def _read_trace(logdir: str):
    """(per-op events, module_ms) — thin wrapper over the library parser
    (paddle_tpu.profiler.read_device_trace, the single implementation)."""
    from paddle_tpu.profiler import read_device_trace

    events, module_ms = read_device_trace(logdir)
    return events, module_ms * 1000.0


def device_module_ms(run_once, steps: int = 10, logdir: str | None = None):
    """Device-side ms per call — delegates to
    paddle_tpu.profiler.device_step_ms (single implementation)."""
    from paddle_tpu.profiler import device_step_ms

    def scalarable():
        out = run_once()
        return jax.tree.leaves(out)[0]

    return device_step_ms(scalarable, steps=steps, warmup=1)


def profile_step(run_once, steps: int = 3, logdir: str | None = None,
                 top: int = 25, group: str = "op"):
    """Run ``run_once`` ``steps`` times under a device trace and print a
    per-op table (durations divided by the number of module executions).

    group: "op" (per HLO op) | "source" (per python source line).
    Returns (rows, totals) where rows are aggregated dicts.
    """
    logdir = logdir or tempfile.mkdtemp(prefix="xprof_")
    run_once()  # warm / compile outside the trace
    jax.profiler.start_trace(logdir)
    out = None
    for _ in range(steps):
        out = run_once()
    float(np.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0])
    jax.profiler.stop_trace()
    events, _ = _read_trace(logdir)

    key = (lambda e: e["name"]) if group == "op" else (
        lambda e: e["source"] or e["name"])
    agg = collections.defaultdict(
        lambda: {"dur_us": 0.0, "flops": 0.0, "bytes": 0.0, "count": 0,
                 "tf_op": "", "source": ""})
    for e in events:
        r = agg[key(e)]
        r["dur_us"] += e["dur_us"]
        r["flops"] += e["flops"]
        r["bytes"] += e["bytes"]
        r["count"] += 1
        r["tf_op"] = e["tf_op"]
        r["source"] = e["source"]
    # one event per executed op: divide by executions of the module to get
    # per-step cost.  Module count is unreliable when several jits run, so
    # normalize by `steps` (callers run the same fn each time).
    rows = []
    for name, r in agg.items():
        d = dict(r)
        d["name"] = name
        d["ms"] = r["dur_us"] / 1000.0 / steps
        d["gbps"] = (r["bytes"] / steps) / max(d["ms"] * 1e-3, 1e-12) / 1e9
        d["tflops"] = (r["flops"] / steps) / max(d["ms"] * 1e-3, 1e-12) / 1e12
        rows.append(d)
    rows.sort(key=lambda d: -d["ms"])
    tot_ms = sum(d["ms"] for d in rows)
    tot_fl = sum(d["flops"] for d in rows) / steps
    tot_by = sum(d["bytes"] for d in rows) / steps
    totals = {"ms": tot_ms, "flops": tot_fl, "bytes": tot_by,
              "tflops": tot_fl / max(tot_ms * 1e-3, 1e-12) / 1e12,
              "gbps": tot_by / max(tot_ms * 1e-3, 1e-12) / 1e9}
    print(f"device total {tot_ms:8.2f} ms/step   "
          f"{totals['tflops']:6.1f} TF/s   {totals['gbps']:7.1f} GB/s   "
          f"({tot_by / 1e9:.2f} GB accessed)")
    print(f"{'ms':>8} {'%':>5} {'TF/s':>6} {'GB/s':>7} {'x':>4}  op  [origin]")
    for d in rows[:top]:
        frac = d["ms"] / tot_ms * 100
        label = d["name"]
        origin = d["tf_op"] or d["source"]
        print(f"{d['ms']:8.3f} {frac:5.1f} {d['tflops']:6.1f} {d['gbps']:7.1f} "
              f"{d['count'] // steps:4d}  {label[:48]:48s} {origin[:60]}")
    return rows, totals


def measure_utilization(run_once, steps: int = 8,
                        peak_flops: float = 197e12,
                        stream_gbps: float = 670.0):
    """Quiet per-step utilization: device ms, achieved TF/s and GB/s from
    the trace's per-op ``model_flops``/``raw_bytes_accessed`` sums, and the
    two ceiling ratios (MFU vs bf16 peak, HBM vs the STREAM-triad
    calibration of THIS chip, 661-673 GB/s measured round 3).

    Returns a dict: {ms, tflops, gbps, mfu_pct, hbm_pct}.  The larger of
    mfu_pct/hbm_pct says which roof the workload is near; when both are
    low the step is latency/serialization-bound (small ops, scan chains).
    """
    import shutil

    logdir = tempfile.mkdtemp(prefix="xprof_util_")
    run_once()  # warm / compile outside the trace
    jax.profiler.start_trace(logdir)
    try:
        out = None
        for _ in range(steps):
            out = run_once()
        leaves = jax.tree.leaves(out)
        if leaves:
            float(np.asarray(leaves[0]).reshape(-1)[0])
    finally:
        # a dangling trace would poison every later measurement in the run
        jax.profiler.stop_trace()
    try:
        events, module_us = _read_trace(logdir)
    finally:
        shutil.rmtree(logdir, ignore_errors=True)
    ms = module_us / 1000.0 / steps
    flops = sum(e["flops"] for e in events) / steps
    by = sum(e["bytes"] for e in events) / steps
    sec = max(ms * 1e-3, 1e-12)
    tflops = flops / sec / 1e12
    gbps = by / sec / 1e9
    return {
        "ms": ms,
        "tflops": round(tflops, 2),
        "gbps": round(gbps, 1),
        "mfu_pct": round(tflops * 1e12 / peak_flops * 100, 1),
        "hbm_pct": round(gbps / stream_gbps * 100, 1),
    }
