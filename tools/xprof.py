"""Per-op TPU profiling via jax.profiler traces (no tensorboard needed).

``jax.profiler.start_trace`` emits a Chrome-trace ``*.trace.json.gz`` whose
``XLA Ops`` thread carries one complete event per executed HLO op with
``dur`` (device µs), ``model_flops`` and ``raw_bytes_accessed`` — enough to
attribute a step's wall time op-by-op and compute achieved FLOP/s and HBM
bandwidth per op class (the tensorboard_plugin_profile converter is
proto-incompatible with the installed protobuf; parsing the chrome trace
directly sidesteps it).

Usage:
    from tools.xprof import profile_step
    rows, totals = profile_step(lambda: step_fn(), steps=3)
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import tempfile

import numpy as np

import jax


def _read_trace(logdir: str):
    """Returns (per-op events on the 'XLA Ops' device thread,
    total device-module ms summed over the trace)."""
    files = sorted(glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                             recursive=True))
    if not files:
        raise RuntimeError(
            f"no *.trace.json.gz under {logdir} — the profiler produced no "
            "device trace (unsupported backend?)")
    tr = json.load(gzip.open(files[-1]))
    events = tr["traceEvents"]
    pids, tids = {}, {}
    for e in events:
        if e.get("ph") == "M":
            if e.get("name") == "process_name":
                pids[e["pid"]] = e["args"].get("name")
            elif e.get("name") == "thread_name":
                tids[(e["pid"], e["tid"])] = e["args"].get("name")
    dev_pids = {p for p, n in pids.items() if n and "TPU" in n}
    out = []
    module_us = 0.0
    for e in events:
        if e.get("ph") != "X" or e["pid"] not in dev_pids:
            continue
        tname = tids.get((e["pid"], e["tid"]))
        if tname == "XLA Modules":
            module_us += e.get("dur", 0.0)
        elif tname == "XLA Ops":
            a = e.get("args", {})
            out.append({
                "name": e["name"],
                "dur_us": e.get("dur", 0.0),
                "flops": float(a.get("model_flops", 0) or 0),
                "bytes": float(a.get("raw_bytes_accessed", 0) or 0),
                "tf_op": a.get("tf_op", ""),
                "source": a.get("source", ""),
            })
    return out, module_us


def device_module_ms(run_once, steps: int = 10, logdir: str | None = None):
    """Device-side ms per call of ``run_once`` from XLA-module events —
    immune to host/tunnel dispatch noise (wall-clock two-point timing is
    only trustworthy above ~10 ms through the axon tunnel)."""
    logdir = logdir or tempfile.mkdtemp(prefix="xprof_")
    run_once()  # compile outside the trace
    jax.profiler.start_trace(logdir)
    out = None
    for _ in range(steps):
        out = run_once()
    float(np.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0])
    jax.profiler.stop_trace()
    _, module_us = _read_trace(logdir)
    return module_us / 1000.0 / steps


def profile_step(run_once, steps: int = 3, logdir: str | None = None,
                 top: int = 25, group: str = "op"):
    """Run ``run_once`` ``steps`` times under a device trace and print a
    per-op table (durations divided by the number of module executions).

    group: "op" (per HLO op) | "source" (per python source line).
    Returns (rows, totals) where rows are aggregated dicts.
    """
    logdir = logdir or tempfile.mkdtemp(prefix="xprof_")
    run_once()  # warm / compile outside the trace
    jax.profiler.start_trace(logdir)
    out = None
    for _ in range(steps):
        out = run_once()
    float(np.asarray(jax.tree.leaves(out)[0]).reshape(-1)[0])
    jax.profiler.stop_trace()
    events, _ = _read_trace(logdir)

    key = (lambda e: e["name"]) if group == "op" else (
        lambda e: e["source"] or e["name"])
    agg = collections.defaultdict(
        lambda: {"dur_us": 0.0, "flops": 0.0, "bytes": 0.0, "count": 0,
                 "tf_op": "", "source": ""})
    for e in events:
        r = agg[key(e)]
        r["dur_us"] += e["dur_us"]
        r["flops"] += e["flops"]
        r["bytes"] += e["bytes"]
        r["count"] += 1
        r["tf_op"] = e["tf_op"]
        r["source"] = e["source"]
    # one event per executed op: divide by executions of the module to get
    # per-step cost.  Module count is unreliable when several jits run, so
    # normalize by `steps` (callers run the same fn each time).
    rows = []
    for name, r in agg.items():
        d = dict(r)
        d["name"] = name
        d["ms"] = r["dur_us"] / 1000.0 / steps
        d["gbps"] = (r["bytes"] / steps) / max(d["ms"] * 1e-3, 1e-12) / 1e9
        d["tflops"] = (r["flops"] / steps) / max(d["ms"] * 1e-3, 1e-12) / 1e12
        rows.append(d)
    rows.sort(key=lambda d: -d["ms"])
    tot_ms = sum(d["ms"] for d in rows)
    tot_fl = sum(d["flops"] for d in rows) / steps
    tot_by = sum(d["bytes"] for d in rows) / steps
    totals = {"ms": tot_ms, "flops": tot_fl, "bytes": tot_by,
              "tflops": tot_fl / max(tot_ms * 1e-3, 1e-12) / 1e12,
              "gbps": tot_by / max(tot_ms * 1e-3, 1e-12) / 1e9}
    print(f"device total {tot_ms:8.2f} ms/step   "
          f"{totals['tflops']:6.1f} TF/s   {totals['gbps']:7.1f} GB/s   "
          f"({tot_by / 1e9:.2f} GB accessed)")
    print(f"{'ms':>8} {'%':>5} {'TF/s':>6} {'GB/s':>7} {'x':>4}  op  [origin]")
    for d in rows[:top]:
        frac = d["ms"] / tot_ms * 100
        label = d["name"]
        origin = d["tf_op"] or d["source"]
        print(f"{d['ms']:8.3f} {frac:5.1f} {d['tflops']:6.1f} {d['gbps']:7.1f} "
              f"{d['count'] // steps:4d}  {label[:48]:48s} {origin[:60]}")
    return rows, totals
