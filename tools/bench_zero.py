"""ZeRO weight-update-sharding ablation on a forced-8-device host mesh.

Runs the SAME small transformer LM three ways — replicated update
(zero=0), state-sharded (zero=1), reduce-scatter/sharded-update/
all-gather (zero=2) — and emits one JSONL row per mode with

- ``steps_per_sec`` (wall, post-compile),
- ``opt_state_bytes_per_device`` (addressable slot residency — the
  ZeRO-1 headline: 1/n under zero>=1),
- ``grad_reduce_bytes_per_device`` (the traced gradient-sync payload a
  device materializes: the full all-reduce copy when replicated, the 1/n
  reduce-scatter shard under zero=2 — the ZeRO-2 headline),
- the per-kind collective census of the step program.

Standalone: ``python tools/bench_zero.py`` (forces JAX_PLATFORMS=cpu +
8 host devices when run on a 1-device box, so the ablation is about the
lowering, not the hardware).  ``bench.py`` shells out to this script so
the rows ride the normal bench stream on any machine.  On a real pod the
same rows measure actual ICI traffic shifts.
"""

from __future__ import annotations

import os
import sys

if __name__ == "__main__":  # force the virtual mesh BEFORE jax imports
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8")
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _repo not in sys.path:
        sys.path.insert(0, _repo)

import numpy as np


def run_ablation(steps: int = 8, layers: int = 2, embed: int = 64,
                 seq_len: int = 64, batch_per_replica: int = 2) -> list[dict]:
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam
    from paddle_tpu.parallel import zero as Z
    from paddle_tpu.telemetry import capture_comm, census_by_kind

    n = len(jax.devices())
    mesh = Mesh(np.asarray(jax.devices()).reshape(n), ("data",))
    cfg = T.TransformerConfig(
        vocab_size=256, num_layers=layers, num_heads=4, embed_dim=embed,
        mlp_dim=embed * 4, max_seq_len=seq_len, remat=False)
    b = batch_per_replica * n
    ids_np = np.random.default_rng(0).integers(0, 256, (b, seq_len + 1))
    grad_bytes = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(
        T.init_params(cfg, jax.random.key(0))))

    rows = []
    for zero in (0, 1, 2):
        opt = Adam(learning_rate=1e-4)
        params = T.place_params(T.init_params(cfg, jax.random.key(0)),
                                mesh, cfg)
        state = opt.init_tree(params)
        if zero >= 1:
            state = Z.shard_opt_state(state, params, mesh,
                                      param_specs=T.param_shardings(cfg))
        else:
            state = jax.device_put(state, NamedSharding(mesh, P()))
        step = T.build_train_step(cfg, opt, mesh=mesh, zero=zero)
        ids = jax.device_put(jnp.asarray(ids_np),
                             NamedSharding(mesh, P("data", None)))
        with capture_comm() as comm:
            step.lower(params, state, ids)
        params, state, loss = step(params, state, ids)  # compile
        float(loss)
        t0 = time.monotonic()
        for _ in range(steps):
            params, state, loss = step(params, state, ids)
        float(loss)
        wall = time.monotonic() - t0
        # grad-reduce bytes a device materializes per step: zero=2 has
        # the traced reduce_scatter shards (+ all_reduce of indivisible
        # leaves); replicated/zero1 all-reduce a full gradient copy
        # (implicit GSPMD — statically the whole param payload)
        rs = comm.get("reduce_scatter/data", 0.0)
        ar = comm.get("all_reduce/data", 0.0)
        grad_reduce = (rs + ar) if zero >= 2 else float(grad_bytes)
        rows.append({
            "metric": f"zero{zero}_train",
            "value": round(steps / wall, 2), "unit": "steps/s",
            "steps_per_sec": round(steps / wall, 2),
            "opt_state_bytes_per_device": int(
                Z.state_bytes_per_device(state)),
            "grad_reduce_bytes_per_device": int(grad_reduce),
            "param_bytes_total": int(grad_bytes),
            "collective_census": census_by_kind(comm),
            "config": f"{layers}L/{embed}d transformer LM, dp{n}, "
                      f"bs {b}x{seq_len}, zero={zero}",
            "vs_baseline": 0,
        })
    return rows


def main() -> int:
    rows = run_ablation()
    from paddle_tpu.telemetry import JsonlSink, MetricsRegistry

    reg = MetricsRegistry("bench_zero")
    reg.add_sink(JsonlSink(sys.stdout))
    for r in rows:
        reg.emit(r, kind="bench")
    return 0


if __name__ == "__main__":
    sys.exit(main())
