#!/usr/bin/env python
"""tools/bench_mem.py — GL-P-MEM static memory estimates for the bench
grid's representative configs.

Runs the same static per-device accounting ``trainer --preflight
--hbm_gb`` gates on (``paddle_tpu/analysis/memory.py``) over the bench
models WITHOUT executing a step: params + optimizer slots under the
requested zero mode + jaxpr activation liveness, plus any
``pallas_call`` VMEM footprints.  Output is the BENCHMARKS.md budget
table (markdown; ``--json`` for JSON lines), so published bench rows
carry a citable static byte count next to the measured HBM traffic.

Trace-only: safe on a CPU dev box, no accelerator required.
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _topology_row(name, cost_fn, feed, optimizer=None, compute_dtype=None):
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.analysis.memory import memory_report
    from paddle_tpu.config.topology import Topology
    from paddle_tpu.layers import base
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.trainer.step import build_train_step

    base.reset_name_counters()
    topo = Topology(cost_fn())
    opt = optimizer or Momentum(momentum=0.9, learning_rate=0.01)
    specs = {s.name: s for s in topo.param_specs()}
    params = paddle.parameters.create(topo).as_dict()
    opt_state = opt.init(params, specs)
    states = topo.init_states()
    step = build_train_step(
        topo, opt,
        compute_dtype=jnp.bfloat16 if compute_dtype is None
        else compute_dtype)
    import jax

    args = (params, opt_state, states, feed, jax.random.key(0))
    rep = memory_report(params, opt_state, states, feed, None, zero=0,
                        step=step, args=args)
    rep["config"] = name
    return rep


def _transformer_row():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.memory import memory_report
    from paddle_tpu.models import transformer as T
    from paddle_tpu.optimizer import Adam

    cfg = T.TransformerConfig(
        vocab_size=50257, num_layers=12, num_heads=12, embed_dim=768,
        mlp_dim=3072, max_seq_len=2048, dtype=jnp.float32, remat=False,
        attn_impl="flash", attn_block_size=1024)
    params = T.init_params(cfg, jax.random.key(0))
    opt = Adam(learning_rate=1e-4, moment_dtype=jnp.bfloat16)
    opt_state = opt.init_tree(params)
    bs, seqlen = 16, 1024
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(bs, seqlen + 1))
    step = T.build_train_step(cfg, opt, compute_dtype=jnp.bfloat16)
    rep = memory_report(params, opt_state, {}, {"ids": ids}, None, zero=0,
                        step=step, args=(params, opt_state, ids))
    rep["config"] = "transformer_lm_124m bs16x1024 bf16"
    return rep


def rows() -> list[dict]:
    import jax.numpy as jnp

    from paddle_tpu.core.lod import SequenceBatch
    from paddle_tpu.models import image as M
    from paddle_tpu.models.ocr_crnn import crnn_ctc_cost
    from paddle_tpu.optimizer import Adam

    rng = np.random.default_rng(0)
    out = []
    specs = [
        ("transformer", _transformer_row),
        ("resnet50 bs128 bf16", lambda: _topology_row(
            "resnet50 bs128 bf16", lambda: M.resnet_cost(depth=50)[0],
            {"image": rng.normal(size=(128, 224 * 224 * 3)).astype(
                np.float32),
             "label": rng.integers(0, 1000, size=(128,))})),
        ("lstm h512 bs256 bf16", lambda: _topology_row(
            "lstm h512 bs256 bf16",
            lambda: __import__("bench")._lstm_classify_cost(512),
            {"data": SequenceBatch(
                data=rng.integers(0, 30000, size=(256, 100)),
                length=np.full((256,), 100, np.int32)),
             "label": rng.integers(0, 2, size=(256,))},
            optimizer=Adam(learning_rate=2e-3,
                           moment_dtype=jnp.bfloat16))),
        ("ocr_crnn bs64 bf16", lambda: _topology_row(
            "ocr_crnn bs64 bf16", lambda: crnn_ctc_cost()[0],
            {"image": rng.normal(size=(64, 32 * 96)).astype(np.float32),
             "label": SequenceBatch(
                 data=rng.integers(1, 95, size=(64, 8)),
                 length=np.full((64,), 8, np.int32))},
            optimizer=Adam(learning_rate=1e-3,
                           moment_dtype=jnp.bfloat16))),
    ]
    for label, fn in specs:
        try:
            out.append(fn())
        except Exception as e:  # keep the table alive per-row
            out.append({"config": label,
                        "error": f"{type(e).__name__}: {e}"[:200]})
    return out


def kernel_vmem_rows() -> list[dict]:
    """Static VMEM block footprints of the persistent-recurrence + CTC
    kernels at the bench shapes, traced DIRECTLY: on a CPU box the model
    routing resolves to the jnp references, so these kernels never
    appear in a model-level trace — this keeps their GL-P-MEM story in
    the table anyway (the same ``pallas_vmem_estimates`` accounting the
    ``--vmem_mb`` preflight gate runs)."""
    import jax.numpy as jnp

    from paddle_tpu.analysis.memory import pallas_vmem_estimates
    from paddle_tpu.ops.pallas.ctc import ctc_loss_fused
    from paddle_tpu.ops.pallas.gru import gru_seq_fi
    from paddle_tpu.ops.pallas.lstm import bilstm_seq, lstm_seq_fi

    def z(*shape, dt=jnp.bfloat16):
        return np.zeros(shape, dt)

    def lstm_fi_bench():  # lstm bench row: embed 128 -> h512, bs64 T100
        b, t, e, d = 64, 100, 128, 512
        args = (z(b, t, e), z(b, t, dt=np.float32), z(e, 4 * d),
                z(4 * d, dt=np.float32), z(d, 4 * d),
                z(3, d), z(b, d), z(b, d, dt=np.float32))
        return pallas_vmem_estimates(
            lambda *a: lstm_seq_fi(*a, False, True, True), *args)

    def bilstm_crnn():    # crnn BiLSTM: cols 256 -> h64 both dirs, T24
        b, t, e, d = 64, 24, 256, 64
        w = (z(e, 4 * d), z(4 * d, dt=np.float32), z(d, 4 * d), z(3, d))
        s = (z(b, d), z(b, d, dt=np.float32))
        args = (z(b, t, e), z(b, t, dt=np.float32)) + w + w + s + s
        return pallas_vmem_estimates(
            lambda *a: bilstm_seq(*a, True, True), *args)

    def gru_fi_nmt():     # nmt encoder GRU: emb 512 -> h512, bs64 T32
        b, t, e, d = 64, 32, 512, 512
        args = (z(b, t, e), z(b, t, dt=np.float32), z(e, 3 * d),
                z(3 * d, dt=np.float32), z(d, 2 * d), z(d, d), z(b, d))
        return pallas_vmem_estimates(
            lambda *a: gru_seq_fi(*a, False, True, True), *args)

    def ctc_crnn():       # crnn CTC head: bs64, W'=24, 27 classes, L=6
        b, t, v, l = 64, 24, 27, 6
        args = (z(b, t, v, dt=np.float32), np.zeros((b,), np.int32),
                np.zeros((b, l), np.int32), np.zeros((b,), np.int32))
        return pallas_vmem_estimates(
            lambda lp, il, lb, ll: ctc_loss_fused(
                lp, il, lb, ll, impl="kernel", interpret=True), *args)

    out = []
    for label, fn in (
            ("lstm_seq_fi h512 bs64 T100 bf16", lstm_fi_bench),
            ("bilstm_seq crnn h64 bs64 T24 bf16", bilstm_crnn),
            ("gru_seq_fi h512 bs64 T32 bf16", gru_fi_nmt),
            ("ctc_fused crnn bs64 T24 V27", ctc_crnn)):
        try:
            ests = fn()
            out.append({"config": label,
                        "pallas_vmem": [{"kernel": k, "bytes": b}
                                        for k, b in ests]})
        except Exception as e:
            out.append({"config": label,
                        "error": f"{type(e).__name__}: {e}"[:200]})
    return out


def main(argv: list[str]) -> int:
    as_json = "--json" in argv
    reports = rows()
    kernels = kernel_vmem_rows()
    if as_json:
        for r in reports + kernels:
            print(json.dumps(r))
        return 0
    print("| config | params MB | opt MB | acts MB (est) | feed MB "
          "| total MB | pallas VMEM MB |")
    print("|---|---|---|---|---|---|---|")
    for r in reports:
        if "error" in r:
            print(f"| {r['config']} | (skipped: {r['error']}) ||||||")
            continue
        vmem = max((k["bytes"] for k in r.get("pallas_vmem", ())),
                   default=0)
        print(f"| {r['config']} | {r['params_bytes'] / 1e6:.1f} "
              f"| {r['opt_state_bytes'] / 1e6:.1f} "
              f"| {r['activation_bytes'] / 1e6:.1f} "
              f"| {r['feed_bytes'] / 1e6:.1f} "
              f"| **{r['total_bytes'] / 1e6:.1f}** "
              f"| {vmem / 1e6:.1f} |")
    print("\n| kernel (direct trace) | pallas VMEM MB |")
    print("|---|---|")
    for r in kernels:
        if "error" in r:
            print(f"| {r['config']} | (skipped: {r['error']}) |")
            continue
        vmem = max((k["bytes"] for k in r.get("pallas_vmem", ())),
                   default=0)
        print(f"| {r['config']} | {vmem / 1e6:.1f} |")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
