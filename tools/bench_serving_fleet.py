"""Serving-fleet availability bench: p99 TTFT with and without a
replica loss, on the same seeded Poisson arrival trace.

Three in-process replicas behind the FleetRouter serve the trace twice:
a fault-free baseline, then the same trace with ONE injected
``replica_loss`` (ChaosSchedule, deterministic pump-round index) whose
in-flight requests fail over to the survivors.  The row reports both
p99 TTFTs — the availability/latency trade under replica churn the
Gemma-on-TPU serving study (PAPERS arxiv 2605.25645) benchmarks — and
``requests_lost``, which MUST be 0: losing a request to a replica death
is a correctness failure, not a latency number, so this script raises
rather than report it.

Standalone: ``python tools/bench_serving_fleet.py`` (CPU-safe; the jnp
reference paged-attention path serves).  ``bench.py`` shells out to
this script so the row rides the normal bench stream.
"""

from __future__ import annotations

import os
import sys
import time

if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if _repo not in sys.path:
        sys.path.insert(0, _repo)
    _tools = os.path.dirname(os.path.abspath(__file__))
    if _tools not in sys.path:
        sys.path.insert(0, _tools)

from bench_serving import make_trace  # noqa: E402  (tools/ sibling)

REPLICAS = 3
LOSS_ROUND = 40  # pump round of the injected loss (mid-trace in flight)


def run_fleet(cfg, params, trace, chaos_spec: str | None, seed: int = 0):
    """Feed the trace (real sleeps between arrivals) through a local
    fleet; returns (p99_ttft_ms, tokens_per_sec, results, stats)."""
    from paddle_tpu.resilience.chaos import ChaosSchedule
    from paddle_tpu.serving.fleet import FleetConfig, build_local_fleet
    from paddle_tpu.serving.scheduler import ServingConfig
    from paddle_tpu.telemetry import MetricsRegistry

    reg = MetricsRegistry("bench_serving_fleet")
    chaos = (ChaosSchedule(chaos_spec, registry=reg)
             if chaos_spec else None)
    scfg = ServingConfig(
        max_slots=8, page_size=16, num_pages=128, max_prompt_len=16,
        max_new_tokens=48, prefill_batch=4, seed=seed)
    router = build_local_fleet(cfg, params, scfg, n=REPLICAS,
                               registry=reg, chaos=chaos,
                               fleet=FleetConfig())
    # pay every compile signature before timing (prefill, decode) — the
    # replicas share shapes but not jitted closures, so warm each
    for rep in router.replicas:
        rep.engine.generate([[1, 2, 3]] * 2, max_new_tokens=2)

    t0 = time.perf_counter()
    for prompt, max_new, arrival in trace:
        while time.perf_counter() - t0 < arrival:
            if not router.pump():
                time.sleep(2e-4)
        router.submit(prompt, max_new_tokens=max_new)
    router.run_until_idle()
    elapsed = time.perf_counter() - t0
    results = router.results()
    stats = router.stats()
    total = sum(len(r.tokens) for r in results)
    ttfts = sorted(float(r.metrics["ttft_ms"]) for r in results
                   if "ttft_ms" in r.metrics)
    p99 = ttfts[min(int(round(0.99 * (len(ttfts) - 1))),
                    len(ttfts) - 1)] if ttfts else 0.0
    return p99, total / elapsed, results, stats


def run_bench(n_requests: int = 32, seed: int = 0) -> list[dict]:
    import jax

    from paddle_tpu.models import transformer as T

    cfg = T.TransformerConfig(
        vocab_size=256, num_layers=2, num_heads=2, embed_dim=64,
        mlp_dim=128, max_seq_len=128, remat=False)
    params = T.init_params(cfg, jax.random.key(seed))
    trace = make_trace(n_requests, seed=seed, rate_per_s=150.0)

    base_p99, base_tps, base_res, base_stats = run_fleet(
        cfg, params, trace, chaos_spec=None, seed=seed)
    loss_p99, loss_tps, loss_res, loss_stats = run_fleet(
        cfg, params, trace,
        chaos_spec=f"replica_loss@{LOSS_ROUND}:replica=1", seed=seed)

    # the acceptance property, not a latency number: a replica death
    # may cost TTFT, never requests
    if loss_stats["requests_lost"] != 0 or len(loss_res) != n_requests:
        raise RuntimeError(
            f"fleet lost requests under replica_loss: "
            f"{loss_stats['requests_lost']} lost, "
            f"{len(loss_res)}/{n_requests} delivered — {loss_stats}")
    if loss_stats["failovers"] < 1:
        raise RuntimeError(
            f"injected replica_loss did not fail over: {loss_stats}")
    # greedy trace → failover must be token-invisible (enforced, like
    # requests_lost: a drifted redial is a correctness bug, not noise)
    same = all(a.tokens == b.tokens for a, b in
               zip(sorted(base_res, key=lambda r: r.id),
                   sorted(loss_res, key=lambda r: r.id)))
    if not same:
        raise RuntimeError(
            "failover changed generated tokens vs the fault-free run — "
            "the fleet-global request-id sampling contract is broken")
    config = (f"2L/64d transformer, {n_requests} Poisson arrivals, "
              f"{REPLICAS} replicas, one replica_loss@" f"{LOSS_ROUND}")
    return [{
        "metric": "serving_fleet_p99_ttft_ms",
        "value": round(loss_p99, 1), "unit": "ms",
        "baseline_p99_ttft_ms": round(base_p99, 1),
        "tokens_per_sec": round(loss_tps, 1),
        "baseline_tokens_per_sec": round(base_tps, 1),
        "requests_lost": loss_stats["requests_lost"],
        "failovers": loss_stats["failovers"],
        "requeued": loss_stats["requeued"],
        "tokens_identical": bool(same),
        "config": config, "vs_baseline": 0,
    }]


def main() -> None:
    rows = run_bench()
    from paddle_tpu.telemetry import JsonlSink, MetricsRegistry

    reg = MetricsRegistry("bench_serving_fleet")
    reg.add_sink(JsonlSink(sys.stdout))
    for r in rows:
        reg.emit(r, kind="bench")


if __name__ == "__main__":
    main()
