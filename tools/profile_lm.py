"""Profile the current-best 124M LM train step per-op (tools/xprof)."""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.models import transformer as T
from paddle_tpu.optimizer import Adam
from tools.bench_lm import gpt2_cfg
from tools.xprof import profile_step

import sys

variant = sys.argv[1] if len(sys.argv) > 1 else "mp"
kw = {}
if variant == "mp":
    cfg = gpt2_cfg(remat="dots", dtype=jnp.float32)
    kw["compute_dtype"] = jnp.bfloat16
elif variant == "mp_full":
    cfg = gpt2_cfg(remat=True, dtype=jnp.float32)
    kw["compute_dtype"] = jnp.bfloat16
elif variant == "baseline":
    cfg = gpt2_cfg()
else:
    raise SystemExit(f"unknown variant {variant!r} (mp, mp_full, baseline)")

params = T.init_params(cfg, jax.random.key(0))
opt = Adam(learning_rate=1e-4)
opt_state = opt.init_tree(params)
ids = jax.device_put(
    np.random.default_rng(0).integers(0, cfg.vocab_size, size=(8, 1025)))
step = T.build_train_step(cfg, opt, **kw)
state = {"p": params, "o": opt_state}


def one():
    state["p"], state["o"], loss = step(state["p"], state["o"], ids)
    return loss


profile_step(one, steps=3, top=30)
