"""Attention-kernel shootout at the LM bench shape and long-context shapes.

Compares paddle_tpu's own Pallas flash kernel against the JAX-shipped TPU
reference kernels (pallas flash / splash) and XLA exact einsum, forward and
forward+backward, to locate where the LM step's attention time goes.
"""

from __future__ import annotations

import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.pallas.flash_attention import flash_attention as ours
from paddle_tpu.ops import attention as attn_ops


from tools.xprof import device_module_ms as device_ms


def mk(b, t, h, d, dtype=jnp.bfloat16):
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, (b, t, h, d), dtype) for k in ks)


def bench_impl(name, fn, q, k, v, fwd_only=False):
    # fwd
    f = jax.jit(lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)))
    try:
        ms_f = device_ms(lambda: f(q, k, v))
    except Exception as e:
        print(f"{name:24s} fwd FAILED: {type(e).__name__}")
        return
    if fwd_only:
        print(f"{name:24s} fwd {ms_f:8.3f} ms")
        return
    g = jax.jit(jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32)),
                         argnums=(0, 1, 2)))
    try:
        ms_fb = device_ms(lambda: g(q, k, v)[0])
    except Exception as e:
        print(f"{name:24s} fwd {ms_f:8.3f} ms   f+b FAILED: {type(e).__name__}")
        return
    print(f"{name:24s} fwd {ms_f:8.3f} ms   f+b {ms_fb:8.3f} ms")


def jax_flash(q, k, v, block=512):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as jf, BlockSizes)
    # theirs wants [B, H, T, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    t = q.shape[1]
    bs = BlockSizes(
        block_q=min(block, t), block_k_major=min(block, t), block_k=min(block, t),
        block_b=1,
        block_q_major_dkv=min(block, t), block_k_major_dkv=min(block, t),
        block_k_dkv=min(block, t), block_q_dkv=min(block, t),
        block_k_major_dq=min(block, t), block_k_dq=min(block, t),
        block_q_dq=min(block, t),
    )
    o = jf(qt, kt, vt, causal=True, sm_scale=q.shape[-1] ** -0.5,
           block_sizes=bs)
    return o.transpose(0, 2, 1, 3)


def exact(q, k, v):
    t = q.shape[1]
    return attn_ops.dot_product_attention(
        q, k, v, mask=attn_ops.causal_mask(t, t))


def main():
    shapes = [(8, 1024, 12, 64), (1, 8192, 8, 64)]
    if len(sys.argv) > 1:
        shapes = [tuple(int(x) for x in s.split("x")) for s in sys.argv[1:]]
    for (b, t, h, d) in shapes:
        print(f"== B={b} T={t} H={h} D={d} bf16 causal ==")
        q, k, v = mk(b, t, h, d)
        for bq, bk in ((512, 512), (512, min(1024, t)),
                       (min(1024, t), min(1024, t))):
            bench_impl(f"ours q{bq}k{bk}",
                       functools.partial(ours, causal=True, block_q=bq,
                                         block_k=bk),
                       *(q, k, v))
        bench_impl("jax pallas flash", jax_flash, q, k, v)
        bench_impl("jax.nn.dpa", functools.partial(
            jax.nn.dot_product_attention, is_causal=True), q, k, v)
        bench_impl("exact einsum", exact, q, k, v)
        from paddle_tpu.ops.attention import blockwise_attention
        bench_impl("blockwise scan", functools.partial(
            blockwise_attention, block_size=min(1024, t), causal=True),
            q, k, v)


if __name__ == "__main__":
    main()
